"""Serving plane v2 tests — AOT executable cache, continuous batching,
multi-tenant registry (ISSUE 13).

Acceptance pins:
 * AOT store round-trips serialized executables content-addressed on
   (model digest, bucket, backend, jax version); corrupted and
   version-mismatched entries fall back to JIT (and are replaced);
 * AOT-loaded programs score BYTE-IDENTICAL to their JIT-compiled
   twins (same compiled artifact, loaded vs built);
 * warmup runs largest-first and skips buckets the AOT store satisfies;
 * continuous batching keeps results identical to windowed batching,
   and the windowed flag preserves the PR 1 coalescing semantics;
 * ``close(drain=True)`` never drops a pending enqueued during the
   drain window (the PR 1 race, regression);
 * two tenants under injected faults (breaker open on A, rollback on B)
   show ZERO cross-tenant metric/generation contamination; per-tenant
   quotas shed only the offender; weighted-fair dequeue tracks weights
   under saturation; per-tenant Prometheus labels parse.
"""
import json
import os
import threading
import time

import numpy as np
import pandas as pd
import pytest

from transmogrifai_tpu.local import load_model_local
from transmogrifai_tpu.local.scorer import score_function_batch
from transmogrifai_tpu.models.classification import LogisticRegressionModel
from transmogrifai_tpu.serving import (AOTStore, BucketedExecutor,
                                       MicroBatcher, ModelServer,
                                       MultiTenantServer, ShedResult,
                                       TenantConfig, scoring_digest)
from transmogrifai_tpu.serving.aot import ScoringProgramSet, program_set_for
from transmogrifai_tpu.tuning.costmodel import ServingCostLookup
from transmogrifai_tpu.utils import compile_cache

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")
MODEL_V1 = os.path.join(FIXTURES, "model_v1")


@pytest.fixture(scope="module")
def rows():
    df = pd.read_csv(os.path.join(FIXTURES, "model_v1_input.csv"))
    return df.to_dict("records")


@pytest.fixture()
def store(tmp_path):
    return AOTStore(str(tmp_path / "aot"))


def _model():
    return LogisticRegressionModel(coef=[0.2, -0.1, 0.4], intercept=0.05)


# ---------------------------------------------------------------------------
# AOT store
# ---------------------------------------------------------------------------

class TestAOTStore:
    def test_put_get_roundtrip(self, store):
        store.put("k1", b"payload-bytes", {"backend": "cpu"})
        got = store.get("k1", expect={"backend": "cpu"})
        assert got is not None
        payload, meta = got
        assert payload == b"payload-bytes"
        assert meta["backend"] == "cpu"
        assert meta["bytes"] == len(b"payload-bytes")

    def test_corrupted_payload_reads_as_miss_and_is_deleted(self, store):
        store.put("k1", b"payload-bytes", {})
        bin_path, _ = store._paths("k1")
        with open(bin_path, "wb") as f:
            f.write(b"garbage")
        assert store.get("k1") is None
        assert "k1" not in store.keys()  # invalid entry dropped

    def test_meta_field_mismatch_reads_as_miss(self, store):
        store.put("k1", b"x", {"backend": "cpu", "jaxVersion": "9.9.9"})
        assert store.get("k1", expect={"jaxVersion": "0.4.37"}) is None

    def test_truncated_meta_reads_as_miss(self, store):
        store.put("k1", b"x", {})
        _, meta_path = store._paths("k1")
        with open(meta_path, "w") as f:
            f.write('{"incomplete":')
        assert store.get("k1") is None

    def test_contains_probe(self, store):
        assert not store.contains("nope")
        store.put("k1", b"x", {"backend": "cpu"})
        assert store.contains("k1", expect={"backend": "cpu"})
        assert not store.contains("k1", expect={"backend": "tpu"})

    def test_atomic_write_leaves_no_tmp(self, store):
        store.put("k1", b"x" * 1024, {})
        leftovers = [n for n in os.listdir(store.root)
                     if n.endswith(".tmp")]
        assert leftovers == []


class TestScoringDigest:
    def test_same_params_same_key_different_params_different(self):
        a = _model().aot_scoring_spec()
        b = _model().aot_scoring_spec()
        c = LogisticRegressionModel(
            coef=[0.2, -0.1, 0.5], intercept=0.05).aot_scoring_spec()
        assert scoring_digest(a, 8, "cpu") == scoring_digest(b, 8, "cpu")
        assert scoring_digest(a, 8, "cpu") != scoring_digest(c, 8, "cpu")
        assert scoring_digest(a, 8, "cpu") != scoring_digest(a, 16, "cpu")
        assert scoring_digest(a, 8, "cpu") != scoring_digest(a, 8, "tpu")


# ---------------------------------------------------------------------------
# program set: AOT load vs JIT compile parity
# ---------------------------------------------------------------------------

class TestScoringProgramSet:
    def test_jit_then_aot_load_byte_identical(self, store):
        X = np.random.default_rng(0).normal(size=(8, 3)).astype(np.float32)
        ps1 = program_set_for(_model(), store=store, cache_key_prefix="p1")
        assert ps1.ensure_bucket(8) == "jit"       # cold store: compiles
        out1 = ps1.predict(X)
        ps2 = program_set_for(_model(), store=store, cache_key_prefix="p2")
        assert ps2.ensure_bucket(8) == "aot"       # write-through hit
        out2 = ps2.predict(X)
        assert (out1.prediction == out2.prediction).all()
        assert (out1.raw_prediction == out2.raw_prediction).all()
        assert (out1.probability == out2.probability).all()

    def test_host_predict_close(self, store):
        X = np.random.default_rng(1).normal(size=(4, 3)).astype(np.float32)
        m = _model()
        ps = program_set_for(m, store=store)
        ps.ensure_bucket(4)
        dev = ps.predict(X)
        host = m.predict_batch(X)
        np.testing.assert_allclose(dev.probability, host.probability,
                                   rtol=3e-6, atol=1e-7)
        assert (dev.prediction == host.prediction).all()

    def test_corrupted_entry_falls_back_to_jit_and_heals(self, store):
        ps1 = program_set_for(_model(), store=store)
        ps1.ensure_bucket(4)
        key = scoring_digest(ps1.spec, 4, ps1.backend)
        bin_path, _ = store._paths(key)
        with open(bin_path, "ab") as f:
            f.write(b"trailing-corruption")
        ps2 = program_set_for(_model(), store=store)
        assert ps2.ensure_bucket(4) == "jit"       # corrupt -> recompile
        ps3 = program_set_for(_model(), store=store)
        assert ps3.ensure_bucket(4) == "aot"       # write-through healed

    def test_unknown_shape_returns_none(self, store):
        ps = program_set_for(_model(), store=store)
        ps.ensure_bucket(4)
        assert ps.predict(np.zeros((3, 3), np.float32)) is None   # no bucket
        assert ps.predict(np.zeros((4, 7), np.float32)) is None   # wrong D

    def test_naive_bayes_parity_classes_neq_features(self, store):
        # K=3 classes over D=5 features: NB's params[0] is the (K,) log
        # prior, so inferring D from params[0] used to lower (bucket, 3)
        # programs whose matmul against the (3, 5) likelihoods blew up at
        # compile time — D must come from the spec's explicit n_features
        from transmogrifai_tpu.models.classification import NaiveBayesModel

        rng = np.random.default_rng(2)
        probs = rng.dirichlet(np.ones(5), size=3)

        def _nb():
            return NaiveBayesModel(
                log_prior=np.log([0.2, 0.3, 0.5]).tolist(),
                log_lik=np.log(probs).tolist())

        X = np.abs(rng.normal(size=(4, 5))).astype(np.float32)
        m = _nb()
        ps1 = program_set_for(m, store=store, cache_key_prefix="nb1")
        assert ps1.n_features == 5
        assert ps1.ensure_bucket(4) == "jit"
        dev = ps1.predict(X)
        host = m.predict_batch(X)
        assert (dev.prediction == host.prediction).all()
        np.testing.assert_allclose(dev.probability, host.probability,
                                   rtol=3e-6, atol=1e-7)
        # a fresh replica loads the same executable: byte-identical
        ps2 = program_set_for(_nb(), store=store, cache_key_prefix="nb2")
        assert ps2.ensure_bucket(4) == "aot"
        out2 = ps2.predict(X)
        assert (dev.prediction == out2.prediction).all()
        assert (dev.probability == out2.probability).all()

    def test_tree_family_has_no_spec(self):
        from transmogrifai_tpu.serving.aot import program_set_for as psf
        from transmogrifai_tpu.models.regression import (
            IsotonicRegressionModel)

        m = IsotonicRegressionModel(boundaries=[0.0, 1.0],
                                    predictions=[0.0, 1.0])
        assert psf(m) is None


# ---------------------------------------------------------------------------
# executor: warmup order + AOT skip
# ---------------------------------------------------------------------------

class TestWarmupOrder:
    def test_warmup_is_largest_first(self, rows):
        srv = ModelServer.from_path(MODEL_V1, name="wo", max_batch=8,
                                    warmup_row=dict(rows[0]))
        ex = srv._executor_for(srv.registry.get("wo"))
        seen = []
        orig = ex._run_bucket

        def spy(padded, bucket):
            seen.append(bucket)
            return orig(padded, bucket)

        ex._run_bucket = spy
        ex.warmup(dict(rows[0]))
        assert seen == [8, 4, 2, 1]

    def test_aot_satisfied_buckets_skip_warm_run(self, store):
        m = _model()
        pre = program_set_for(m, store=store, cache_key_prefix="pre")
        for b in (1, 2, 4):
            pre.ensure_bucket(b)                    # populate the store

        calls = []

        def score_fn(batch_rows):
            calls.append(len(batch_rows))
            return [{"s": 0.0} for _ in batch_rows]

        m2 = _model()
        ex = BucketedExecutor(score_fn, max_batch=4, model=m2,
                              aot_store=store, device_programs=True,
                              cache_key_prefix="skip")
        timings = ex.warmup({"x": 1.0})
        assert calls == []                          # nothing warm-ran
        assert sorted(timings) == [1, 2, 4]
        assert ex.programs.modes == {1: "aot", 2: "aot", 4: "aot"}
        assert ex.warm_buckets == [1, 2, 4]

    def test_failed_jit_warm_run_leaves_bucket_cold(self):
        # warm is only recorded AFTER a successful first execution — a
        # transient warmup failure must not mark the bucket warm (which
        # would also skew the compile/hit accounting)
        boom = {"on": True}

        def score_fn(batch_rows):
            if boom["on"]:
                raise RuntimeError("transient warm-run failure")
            return [{"s": 0.0} for _ in batch_rows]

        ex = BucketedExecutor(score_fn, max_batch=2, model=_model(),
                              device_programs=True,
                              cache_key_prefix="coldfail")
        with pytest.raises(RuntimeError):
            ex.warmup({"x": 1.0})
        assert ex.warm_buckets == []
        boom["on"] = False
        ex.warmup({"x": 1.0})
        assert ex.warm_buckets == [1, 2]


# ---------------------------------------------------------------------------
# continuous batching
# ---------------------------------------------------------------------------

class TestContinuousBatching:
    def test_results_identical_across_modes(self, rows):
        expected = score_function_batch(load_model_local(MODEL_V1))(rows[:6])
        for mode in ("windowed", "continuous"):
            srv = ModelServer.from_path(
                MODEL_V1, name=f"mode-{mode}", max_batch=8,
                max_latency_ms=2.0, warmup_row=dict(rows[0]),
                batch_mode=mode)
            with srv:
                assert srv.score(rows[:6]) == expected
                assert srv.snapshot()["batchMode"] == mode

    def test_continuous_dispatches_without_window_wait(self):
        """A lone request must NOT wait out a coalescing window: the
        continuous dispatcher forms the batch the moment the executor is
        free."""
        batcher = MicroBatcher(lambda rs: list(rs), max_batch=64,
                               max_latency_ms=200.0, mode="continuous")
        batcher.start()
        try:
            t0 = time.perf_counter()
            batcher.submit([{"i": 1}]).result(timeout=2)
            elapsed = time.perf_counter() - t0
            assert elapsed < 0.1   # windowed would have waited ~200ms
        finally:
            batcher.close()

    def test_windowed_flag_keeps_pr1_coalescing(self):
        """The PR 1 pin, now behind mode="windowed": requests queued
        before start coalesce into ONE batch after the window closes."""
        executed = []
        batcher = MicroBatcher(
            lambda rs: executed.append(len(rs)) or list(rs),
            max_batch=16, max_latency_ms=1.0, mode="windowed")
        futures = [batcher.submit([{"i": i}]) for i in range(6)]
        batcher.start()
        try:
            results = [f.result(timeout=2) for f in futures]
            assert [r[0]["i"] for r in results] == list(range(6))
            assert executed == [6]
        finally:
            batcher.close()

    def test_greedy_bucket_choice_prefers_measured_cheap_bucket(self):
        lookup = ServingCostLookup()
        # bucket 8 measured pathological, bucket 4 cheap
        for _ in range(4):
            lookup.observe(8, 1.0)
            lookup.observe(4, 0.001)
        batcher = MicroBatcher(lambda rs: list(rs), max_batch=8,
                               mode="continuous", cost_lookup=lookup)
        assert batcher._choose_bucket(8) == 4
        # and with no signal: largest fillable wins (linear assumption)
        fresh = MicroBatcher(lambda rs: list(rs), max_batch=8,
                             mode="continuous",
                             cost_lookup=ServingCostLookup())
        assert fresh._choose_bucket(8) == 8
        assert fresh._choose_bucket(3) == 4

    def test_cost_lookup_tiers(self):
        lookup = ServingCostLookup()
        assert lookup.source(8) == "analytic"
        lookup.observe(8, 0.01)
        assert lookup.source(8) == "measured"
        assert lookup.predict_s(8) == pytest.approx(0.01)
        lookup.observe(8, 0.02)   # EWMA moves toward the new value
        assert 0.01 < lookup.predict_s(8) < 0.02

    def test_late_arrivals_admitted_into_forming_batch(self):
        """While the dispatcher holds an under-filled batch open
        (throughput mode: a burst projects max_batch fillable), a late
        submit must ride the SAME batch.  The arrival-rate probe is
        pinned so the regime choice is deterministic."""
        executed = []

        def execute(rs):
            executed.append(len(rs))
            return list(rs)

        batcher = MicroBatcher(execute, max_batch=8, max_latency_ms=80.0,
                               mode="continuous")
        # pinned burst: deficit/rate = 7/100 = 70ms <= 2x max_latency ->
        # throughput mode targets bucket 8 and holds the batch open
        batcher._arrival_rate_locked = lambda: 100.0
        f1 = batcher.submit([{"i": 0}])
        batcher.start()
        time.sleep(0.02)           # dispatcher is inside the fill hold
        f2 = batcher.submit([{"i": 1}])
        try:
            assert len(f1.result(timeout=2)) == 1
            assert len(f2.result(timeout=2)) == 1
            assert executed[0] == 2   # late row rode the forming batch
        finally:
            batcher.close()

    def test_no_burst_dispatches_immediately(self):
        """Latency mode: with no burst in progress a lone request leaves
        at once (no hold), regardless of max_latency."""
        executed = []
        batcher = MicroBatcher(
            lambda rs: executed.append(len(rs)) or list(rs),
            max_batch=8, max_latency_ms=500.0, mode="continuous")
        batcher._arrival_rate_locked = lambda: 0.0
        batcher.start()
        try:
            t0 = time.perf_counter()
            batcher.submit([{"i": 0}]).result(timeout=2)
            assert time.perf_counter() - t0 < 0.1
            assert executed == [1]
        finally:
            batcher.close()


class TestCloseDrainRace:
    def test_drain_never_drops_racing_submits(self):
        """Regression (ISSUE 13 satellite): submits racing close(drain=True)
        must ALL resolve — scored or shed, never hung."""
        def execute(rs):
            time.sleep(0.002)
            return list(rs)

        for _ in range(5):
            batcher = MicroBatcher(execute, max_batch=4,
                                   mode="continuous")
            batcher.start()
            futures = []
            stop = threading.Event()

            def submitter():
                while not stop.is_set():
                    futures.append(batcher.submit([{"i": 1}]))
                    time.sleep(0.0005)

            t = threading.Thread(target=submitter, daemon=True)
            t.start()
            time.sleep(0.01)
            batcher.close(drain=True)
            stop.set()
            t.join(timeout=2)
            for f in futures:
                res = f.result(timeout=5)   # hangs = dropped pending
                assert len(res) == 1
            # drained: everything in the queue at close time was scored
            assert not batcher._queue

    def test_submits_after_close_shed_as_shutting_down(self):
        batcher = MicroBatcher(lambda rs: list(rs), max_batch=4,
                               mode="continuous")
        batcher.start()
        batcher.close(drain=True)
        res = batcher.submit([{"i": 1}]).result(timeout=1)
        assert isinstance(res[0], ShedResult)
        assert res[0].reason == "shutting_down"


# ---------------------------------------------------------------------------
# multi-tenancy
# ---------------------------------------------------------------------------

def _slow_executor(server, name, delay_s=0.003):
    ex = server._executor_for(server.registry.get(name))
    orig = ex.score_fn

    def slow(rs, _orig=orig):
        time.sleep(delay_s)
        return _orig(rs)

    ex.score_fn = slow
    return ex


class TestMultiTenant:
    def test_parity_and_routing(self, rows):
        expected = score_function_batch(load_model_local(MODEL_V1))(rows[:4])
        mts = MultiTenantServer()
        mts.add_tenant(TenantConfig("a", max_batch=8,
                                    warmup_row=dict(rows[0])),
                       path=MODEL_V1)
        mts.add_tenant(TenantConfig("b", max_batch=8), path=MODEL_V1)
        with mts:
            assert mts.score(rows[:4], tenant="a") == expected
            assert mts.score(rows[:4], tenant="b") == expected
            with pytest.raises(KeyError):
                mts.score(rows[:1], tenant="nope")
            with pytest.raises(KeyError):
                mts.score(rows[:1])   # ambiguous with two tenants

    def test_single_tenant_default_routing(self, rows):
        mts = MultiTenantServer()
        mts.add_tenant(TenantConfig("only", max_batch=8), path=MODEL_V1)
        with mts:
            out = mts.score(rows[:2])   # no tenant needed with one lane
            assert len(out) == 2

    def test_per_tenant_quota_sheds_only_offender(self, rows):
        mts = MultiTenantServer()
        mts.add_tenant(TenantConfig("small", max_batch=4,
                                    max_queue_rows=4), path=MODEL_V1)
        mts.add_tenant(TenantConfig("big", max_batch=4,
                                    max_queue_rows=1024), path=MODEL_V1)
        # NOT started: queues cannot drain, quotas bind immediately
        mts.submit(rows[:4], tenant="small")
        shed = mts.submit(rows[:2], tenant="small").result(timeout=1)
        assert isinstance(shed[0], ShedResult)
        assert shed[0].reason == "queue_full"
        ok = mts.submit(rows[:2], tenant="big")
        assert not ok.done()            # big admitted, just queued
        assert mts.tenant("small").metrics.shed == 2
        assert mts.tenant("big").metrics.shed == 0
        mts.stop(drain=False)

    def test_weighted_fair_dequeue_under_saturation(self, rows):
        mts = MultiTenantServer()
        mts.add_tenant(TenantConfig("gold", weight=3.0, max_batch=4,
                                    max_queue_rows=64), path=MODEL_V1)
        mts.add_tenant(TenantConfig("bronze", weight=1.0, max_batch=4,
                                    max_queue_rows=64), path=MODEL_V1)
        for name in ("gold", "bronze"):
            _slow_executor(mts.tenant(name), name)
        mts.start()
        stop = threading.Event()

        def flood(tenant):
            while not stop.is_set():
                mts.submit(rows[:2], tenant=tenant)
                time.sleep(0.0005)

        threads = [threading.Thread(target=flood, args=(t,), daemon=True)
                   for t in ("gold", "bronze")]
        for t in threads:
            t.start()
        time.sleep(1.2)
        stop.set()
        for t in threads:
            t.join(timeout=2)
        snap = mts.snapshot()
        mts.stop(drain=False)
        gold = snap["tenants"]["gold"]["wfq"]["dispatchedRows"]
        bronze = snap["tenants"]["bronze"]["wfq"]["dispatchedRows"]
        assert bronze > 0
        assert 2.0 <= gold / bronze <= 4.5   # tracks the 3:1 weights

    def test_breaker_isolation_under_injected_fault(self, rows):
        """Breaker open on A: A host-fallbacks, B's ledgers stay clean."""
        mts = MultiTenantServer()
        mts.add_tenant(TenantConfig("a", max_batch=4, failure_threshold=1,
                                    breaker_reset_s=60.0), path=MODEL_V1)
        mts.add_tenant(TenantConfig("b", max_batch=4), path=MODEL_V1)
        sa = mts.tenant("a")
        ex = sa._executor_for(sa.registry.get("a"))

        def boom(_rows):
            raise RuntimeError("injected device worker crash")

        ex.score_fn = boom
        expected = score_function_batch(load_model_local(MODEL_V1))(rows[:2])
        with mts:
            out_a = mts.score(rows[:2], tenant="a")
            assert out_a == expected          # host fallback answered
            out_b = mts.score(rows[:2], tenant="b")
            assert out_b == expected
            snap_a = mts.tenant("a").snapshot()
            snap_b = mts.tenant("b").snapshot()
        assert snap_a["breakerState"] == "open"
        assert snap_a["deviceErrors"] >= 1
        assert snap_a["hostFallbacks"] >= 1
        # ZERO contamination of B
        assert snap_b["breakerState"] == "closed"
        assert snap_b["deviceErrors"] == 0
        assert snap_b["hostFallbacks"] == 0
        assert snap_b["shed"] == 0

    def test_rollback_isolation(self, rows):
        """Rollback on B's registry name never touches A's generations,
        entry, or metrics."""
        mts = MultiTenantServer()
        mts.add_tenant(TenantConfig("a", max_batch=4), path=MODEL_V1)
        mts.add_tenant(TenantConfig("b", max_batch=4), path=MODEL_V1)
        reg = mts.registry
        reg.pin("b")                       # v1 is last-known-good
        reg.load("b", MODEL_V1)            # v2 swap
        a_before = reg.get("a")
        a_gens_before = reg.generations("a")
        assert reg.get("b").version == 2
        rolled = reg.rollback("b")
        assert rolled.version == 1
        assert reg.get("b").version == 1
        # A untouched: same entry object, same generation list
        assert reg.get("a") is a_before
        assert reg.generations("a") == a_gens_before
        assert mts.tenant("a").metrics.rollbacks == 0
        mts.stop(drain=False)

    def test_drift_monitor_per_tenant(self, rows):
        """Each tenant's DriftMonitor sees only its own traffic (the
        fixture model predates exported baselines, so observation routing
        is pinned with counting stubs — the DriftMonitor protocol)."""

        class CountingMonitor:
            def __init__(self):
                self.rows_observed = 0

            def observe_rows(self, batch_rows):
                self.rows_observed += len(batch_rows)

            def snapshot(self):
                return {"rowsObserved": self.rows_observed}

        mts = MultiTenantServer()
        mts.add_tenant(TenantConfig("a", max_batch=4), path=MODEL_V1)
        mts.add_tenant(TenantConfig("b", max_batch=4), path=MODEL_V1)
        mon_a, mon_b = CountingMonitor(), CountingMonitor()
        mts.tenant("a").with_drift_monitor(mon_a)
        mts.tenant("b").with_drift_monitor(mon_b)
        with mts:
            mts.score(rows[:4], tenant="a")
            mts.score(rows[:2], tenant="a")
            snap = mts.snapshot()
        assert mon_a.rows_observed == 6
        assert mon_b.rows_observed == 0
        assert snap["tenants"]["a"]["drift"]["rowsObserved"] == 6
        assert snap["tenants"]["b"]["drift"]["rowsObserved"] == 0

    def test_remove_tenant_sheds_and_evicts(self, rows):
        mts = MultiTenantServer()
        srv_x = mts.add_tenant(TenantConfig("x", max_batch=4), path=MODEL_V1)
        mts.add_tenant(TenantConfig("y", max_batch=4), path=MODEL_V1)
        fut = mts.submit(rows[:2], tenant="x")   # not started: stays queued
        assert mts.remove_tenant("x")
        res = fut.result(timeout=1)
        assert isinstance(res[0], ShedResult)
        # the removal sheds are visible in metrics, like every shed path
        assert srv_x.metrics.snapshot()["shed"] == 2
        assert mts.tenants() == ["y"]
        assert mts.registry.maybe_get("x") is None
        mts.stop(drain=False)

    def test_prometheus_per_tenant_labels_parse(self, rows):
        from transmogrifai_tpu.obs.prometheus import (parse_exposition,
                                                      prometheus_text)

        mts = MultiTenantServer()
        mts.add_tenant(TenantConfig("a", max_batch=8), path=MODEL_V1)
        mts.add_tenant(TenantConfig("b", max_batch=8), path=MODEL_V1)
        with mts:
            mts.score(rows[:4], tenant="a")
            text = prometheus_text(tenants=mts.tenant_snapshots())
        parsed = parse_exposition(text)   # raises on any malformed line
        a_rows = parsed['tmog_serving_rows_total{tenant="a"}']
        b_rows = parsed['tmog_serving_rows_total{tenant="b"}']
        assert a_rows == 4 and b_rows == 0
        assert 'tmog_serving_queue_depth{tenant="a"}' in parsed
        # the batch histogram carries both labels, sorted
        assert any(k.startswith("tmog_serving_batches_by_bucket_total{")
                   and 'tenant="a"' in k for k in parsed)


# ---------------------------------------------------------------------------
# device-programs server e2e (AOT cache through ModelServer)
# ---------------------------------------------------------------------------

class TestDeviceProgramServer:
    def test_aot_server_scores_and_reports(self, rows, tmp_path):
        aot_dir = str(tmp_path / "aot")
        srv1 = ModelServer.from_path(
            MODEL_V1, name="dev1", max_batch=4, warmup_row=dict(rows[0]),
            device_programs=True, aot_store=aot_dir)
        with srv1:
            out1 = srv1.score(rows[:3])
            snap1 = srv1.snapshot()
        assert set(snap1["aotPrograms"].values()) == {"jit"}
        # a second "replica" over the same store cold-starts via AOT loads
        srv2 = ModelServer.from_path(
            MODEL_V1, name="dev2", max_batch=4, warmup_row=dict(rows[0]),
            device_programs=True, aot_store=aot_dir)
        with srv2:
            out2 = srv2.score(rows[:3])
            snap2 = srv2.snapshot()
        assert set(snap2["aotPrograms"].values()) == {"aot"}
        # byte-identical scoring between the JIT and AOT replicas
        assert json.dumps(out1, sort_keys=True, default=str) == \
            json.dumps(out2, sort_keys=True, default=str)

    def test_breaker_fallback_bypasses_device_programs(self, rows,
                                                       tmp_path):
        """An open breaker serves from the HOST scorer even when device
        programs are installed — the programs live behind the device
        scoring context only."""
        srv = ModelServer.from_path(
            MODEL_V1, name="devbrk", max_batch=4, failure_threshold=1,
            breaker_reset_s=60.0, warmup_row=dict(rows[0]),
            device_programs=True, aot_store=str(tmp_path / "aot"))
        expected = score_function_batch(load_model_local(MODEL_V1))(rows[:2])
        with srv:
            ex = srv._executor_for(srv.registry.get("devbrk"))

            def boom(_rows):
                raise RuntimeError("injected")

            ex.score_fn = boom
            got = srv.score(rows[:2])
            assert got == expected          # exact host-path parity
            assert srv.snapshot()["breakerState"] == "open"
