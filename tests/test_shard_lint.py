"""Shard-safety lint tests (analysis/shard_lint.py, TM040-TM045).

One seeded-violation fixture per rule id that fires EXACTLY that rule,
negative fixtures distilled from the real shard_map bodies in
parallel/sharded.py (the regression corpus for the collective-aware
taint), and the repo self-lint contract.
"""
import os

from transmogrifai_tpu.analysis import shard_lint

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_PRELUDE = (
    "import functools\n"
    "import jax\n"
    "import jax.numpy as jnp\n"
    "import numpy as np\n"
    "from jax import lax\n"
    "from jax.sharding import Mesh, NamedSharding, PartitionSpec as P\n"
    "from transmogrifai_tpu.parallel.mesh import (make_mesh, "
    "make_sweep_mesh, shard_map_compat)\n")


def _lint(body: str):
    return shard_lint.lint_source(_PRELUDE + body, "fixture.py")


# ---------------------------------------------------------------------------
# TM040 — cross-shard reduction with no collective
# ---------------------------------------------------------------------------

def test_tm040_reduction_without_psum():
    f = _lint(
        "def total(X, w, mesh):\n"
        "    def shard_fn(X_s, w_s):\n"
        "        return (w_s * X_s[:, 0]).sum()\n"
        "    fn = shard_map_compat(shard_fn, mesh,\n"
        "                          (P('data', None), P('data')), P())\n"
        "    return fn(X, w)\n")
    assert f.rules_fired() == ["TM040"]


def test_tm040_matmul_without_psum():
    f = _lint(
        "def gram(X, mesh):\n"
        "    def shard_fn(X_s):\n"
        "        return X_s.T @ X_s\n"
        "    fn = shard_map_compat(shard_fn, mesh,\n"
        "                          (P('data', None),), P(None, None))\n"
        "    return fn(X)\n")
    assert f.rules_fired() == ["TM040"]


def test_tm040_clean_with_psum():
    """The colstats_psum shape: per-shard partials + one psum."""
    f = _lint(
        "def colstats(X, w, mesh):\n"
        "    data_axis = mesh.axis_names[0]\n"
        "    def shard_fn(X_s, w_s):\n"
        "        part = jnp.concatenate([w_s.sum()[None], w_s @ X_s])\n"
        "        tot = lax.psum(part, axis_name=data_axis)\n"
        "        return tot[1:] / jnp.maximum(tot[0], 1.0)\n"
        "    fn = shard_map_compat(shard_fn, mesh,\n"
        "                          (P('data', None), P('data')), P(None))\n"
        "    return fn(X, w)\n")
    assert len(f) == 0


def test_tm040_partial_bound_collective_is_clean():
    """grow_forest_sharded shape: the collective rides in via a
    functools.partial plumbed to a helper — still counts as present."""
    f = _lint(
        "def grow(B, W, mesh, helper):\n"
        "    data_axis = mesh.axis_names[0]\n"
        "    psum = functools.partial(lax.psum, axis_name=data_axis)\n"
        "    def shard_fn(B_s, W_s):\n"
        "        fn = functools.partial(helper, all_reduce=psum)\n"
        "        return jax.vmap(fn)(B_s, W_s)\n"
        "    f2 = shard_map_compat(shard_fn, mesh,\n"
        "                          (P('data', None), P(None, 'data')),\n"
        "                          P(None, None))\n"
        "    return f2(B, W)\n")
    assert len(f) == 0


def test_tm040_axis_restricted_reduction_is_clean():
    """A reduction over an UNSHARDED axis stays per-row local."""
    f = _lint(
        "def rowsum(X, mesh):\n"
        "    def shard_fn(X_s):\n"
        "        return X_s.sum(axis=1)\n"
        "    fn = shard_map_compat(shard_fn, mesh,\n"
        "                          (P('data', None),), P('data'))\n"
        "    return fn(X)\n")
    assert len(f) == 0


# ---------------------------------------------------------------------------
# TM041 — axis names the mesh does not define
# ---------------------------------------------------------------------------

def test_tm041_unknown_axis_in_spec():
    f = _lint(
        "def run(X):\n"
        "    mesh = make_sweep_mesh(4)\n"
        "    def shard_fn(X_s):\n"
        "        return lax.psum(X_s, axis_name='data')\n"
        "    fn = shard_map_compat(shard_fn, mesh,\n"
        "                          (P('model', None),), P(None, None))\n"
        "    return fn(X)\n")
    assert f.rules_fired() == ["TM041"]
    assert "'model'" in f.by_rule("TM041")[0].message


def test_tm041_unknown_axis_in_collective():
    f = _lint(
        "def run(X):\n"
        "    mesh = make_sweep_mesh(4)\n"
        "    def shard_fn(X_s):\n"
        "        return lax.psum(X_s.sum(), axis_name='batch')\n"
        "    fn = shard_map_compat(shard_fn, mesh,\n"
        "                          (P('data', None),), P())\n"
        "    return fn(X)\n")
    assert f.rules_fired() == ["TM041"]


def test_tm041_symbolic_axis_is_clean():
    """``ax = mesh.axis_names[0]`` resolves to a real axis."""
    f = _lint(
        "def run(X):\n"
        "    mesh = make_mesh(8)\n"
        "    ax = mesh.axis_names[0]\n"
        "    def shard_fn(X_s):\n"
        "        return lax.psum(X_s.sum(), axis_name=ax)\n"
        "    fn = shard_map_compat(shard_fn, mesh, (P(ax, None),), P())\n"
        "    return fn(X)\n")
    assert len(f) == 0


def test_tm041_unknown_mesh_skips():
    """A mesh of unknown provenance (parameter) is never flagged."""
    f = _lint(
        "def run(X, mesh):\n"
        "    def shard_fn(X_s):\n"
        "        return lax.psum(X_s, axis_name='whatever')\n"
        "    fn = shard_map_compat(shard_fn, mesh,\n"
        "                          (P('data', None),), P(None, None))\n"
        "    return fn(X)\n")
    assert len(f) == 0


# ---------------------------------------------------------------------------
# TM042 — host round-trips inside sweep inner loops
# ---------------------------------------------------------------------------

def test_tm042_device_put_in_sweep_loop():
    f = _lint(
        "def sweep(chunks, n):\n"
        "    mesh = make_sweep_mesh(n)\n"
        "    out = []\n"
        "    for c in chunks:\n"
        "        out.append(jax.device_put(c))\n"
        "    return out\n")
    assert f.rules_fired() == ["TM042"]


def test_tm042_block_until_ready_in_sweep_loop():
    f = _lint(
        "def sweep(xs, n):\n"
        "    mesh = make_sweep_mesh(n)\n"
        "    for x in xs:\n"
        "        x.block_until_ready()\n")
    assert f.rules_fired() == ["TM042"]


def test_tm042_hoisted_placement_is_clean():
    f = _lint(
        "def sweep(X, chunks, n):\n"
        "    mesh = make_sweep_mesh(n)\n"
        "    X_dev = jax.device_put(X)\n"
        "    for c in chunks:\n"
        "        consume(X_dev, c)\n")
    assert len(f) == 0


def test_tm042_non_sweep_function_is_clean():
    """Loops with device_put outside a sweep context are fine (the
    ShardedMatrixWriter's per-shard upload loop is the idiom)."""
    f = _lint(
        "def writer(chunks):\n"
        "    out = []\n"
        "    for c in chunks:\n"
        "        out.append(jax.device_put(c))\n"
        "    return out\n")
    assert len(f) == 0


# -- the async-dispatch extension: blocking metric fetches in the loop
# that drives run_group_block/run_unit ---------------------------------------

def test_tm042_bare_materialize_in_dispatch_loop_fires():
    f = _lint(
        "def drive(queue, groups, all_vals):\n"
        "    for g in groups:\n"
        "        queue.run_group_block(g)\n"
        "        rows = _materialize(all_vals)\n")
    assert f.rules_fired() == ["TM042"]
    assert "overlapped=" in f.format()


def test_tm042_bare_fetch_timed_in_dispatch_loop_fires():
    f = _lint(
        "def drive(queue, units):\n"
        "    out = []\n"
        "    for u in units:\n"
        "        queue.run_unit(u)\n"
        "        out.append(fetch_timed(u.metrics))\n"
        "    return out\n")
    assert f.rules_fired() == ["TM042"]


def test_tm042_overlapped_kwarg_is_the_sanctioned_lagged_fetch():
    """Any statically visible overlapped= keyword exempts the call —
    including overlapped=<variable> (the flush_pending idiom)."""
    f = _lint(
        "def drive(queue, groups, all_vals, overlapped):\n"
        "    for g in groups:\n"
        "        queue.run_group_block(g)\n"
        "        _materialize(all_vals, overlapped=True)\n"
        "        fetch_timed(g.matrix, overlapped=overlapped)\n")
    assert len(f) == 0


def test_tm042_block_until_ready_in_dispatch_loop_names_pipeline():
    f = _lint(
        "def drive(queue, groups):\n"
        "    for g in groups:\n"
        "        queue.run_group_block(g)\n"
        "        g.matrix.block_until_ready()\n")
    assert f.rules_fired() == ["TM042"]
    assert "double-buffered launch pipeline" in f.format()


def test_tm042_materialize_outside_dispatch_context_is_clean():
    """halving_validate's end-of-ladder combined materialize: the
    function calls validator.validate, not run_group_block/run_unit, so
    it is no dispatch context and the one-shot fetch is sanctioned."""
    f = _lint(
        "def ladder(validator, rungs):\n"
        "    deferred = []\n"
        "    for r in rungs:\n"
        "        deferred.append(validator.validate(r))\n"
        "    return _materialize(deferred)\n")
    assert len(f) == 0


def test_tm042_fetch_after_dispatch_loop_is_clean():
    """The end-of-sweep collect: fetches AFTER the dispatch loop (not
    inside it) are the design, not a violation."""
    f = _lint(
        "def drive(queue, groups, all_vals):\n"
        "    for g in groups:\n"
        "        queue.run_group_block(g)\n"
        "    return _materialize(all_vals)\n")
    assert len(f) == 0


# ---------------------------------------------------------------------------
# TM043 — donated-buffer reuse
# ---------------------------------------------------------------------------

def test_tm043_donated_reuse():
    f = _lint(
        "def step(x):\n"
        "    f = jax.jit(lambda a: a + 1, donate_argnums=(0,))\n"
        "    y = f(x)\n"
        "    return x + y\n")
    assert f.rules_fired() == ["TM043"]


def test_tm043_rebinding_is_clean():
    f = _lint(
        "def step(x):\n"
        "    f = jax.jit(lambda a: a + 1, donate_argnums=(0,))\n"
        "    x = f(x)\n"
        "    return x + 1\n")
    assert len(f) == 0


def test_tm043_no_donation_is_clean():
    f = _lint(
        "def step(x):\n"
        "    f = jax.jit(lambda a: a + 1)\n"
        "    y = f(x)\n"
        "    return x + y\n")
    assert len(f) == 0


# ---------------------------------------------------------------------------
# TM044 — NamedSharding rank mismatch
# ---------------------------------------------------------------------------

def test_tm044_rank_mismatch():
    f = _lint(
        "def place(mesh):\n"
        "    s = NamedSharding(mesh, P('data', None))\n"
        "    v = np.zeros(8)\n"
        "    return jax.device_put(v, s)\n")
    assert f.rules_fired() == ["TM044"]


def test_tm044_matching_rank_is_clean():
    f = _lint(
        "def place(mesh):\n"
        "    s = NamedSharding(mesh, P('data', None))\n"
        "    m = np.zeros((8, 4))\n"
        "    return jax.device_put(m, s)\n")
    assert len(f) == 0


def test_tm044_spec_prefix_is_clean():
    """A spec SHORTER than the operand rank is a legal prefix."""
    f = _lint(
        "def place(mesh):\n"
        "    s = NamedSharding(mesh, P('data'))\n"
        "    m = np.zeros((8, 4))\n"
        "    return jax.device_put(m, s)\n")
    assert len(f) == 0


# ---------------------------------------------------------------------------
# TM045 — spec arity mismatch
# ---------------------------------------------------------------------------

def test_tm045_in_specs_arity():
    f = _lint(
        "def run(X, w, mesh):\n"
        "    def shard_fn(X_s, w_s):\n"
        "        return lax.psum(w_s @ X_s, axis_name='data')\n"
        "    fn = shard_map_compat(shard_fn, mesh,\n"
        "                          (P('data', None),), P(None))\n"
        "    return fn(X, w)\n")
    assert f.rules_fired() == ["TM045"]


def test_tm045_out_specs_arity():
    f = _lint(
        "def run(X, mesh):\n"
        "    def shard_fn(X_s):\n"
        "        t = lax.psum(X_s.sum(axis=0), axis_name='data')\n"
        "        return t, t * t\n"
        "    fn = shard_map_compat(shard_fn, mesh,\n"
        "                          (P('data', None),),\n"
        "                          (P(None), P(None), P(None)))\n"
        "    return fn(X)\n")
    assert f.rules_fired() == ["TM045"]


def test_tm045_matching_arity_is_clean():
    f = _lint(
        "def run(X, w, mesh):\n"
        "    def shard_fn(X_s, w_s):\n"
        "        m = lax.psum(w_s @ X_s, axis_name='data')\n"
        "        v = lax.psum(w_s @ (X_s * X_s), axis_name='data')\n"
        "        return m, v\n"
        "    fn = shard_map_compat(shard_fn, mesh,\n"
        "                          (P('data', None), P('data')),\n"
        "                          (P(None), P(None)))\n"
        "    return fn(X, w)\n")
    assert len(f) == 0


# ---------------------------------------------------------------------------
# TM030 inside shard bodies — collective results are device values
# (regression corpus: parallel/sharded.py; satellite of PR 8)
# ---------------------------------------------------------------------------

def test_tm030_host_cast_of_collective_result():
    f = _lint(
        "def run(X, w, mesh):\n"
        "    def shard_fn(X_s, w_s):\n"
        "        tot = lax.psum(w_s.sum(), axis_name='data')\n"
        "        return X_s / float(tot)\n"
        "    fn = shard_map_compat(shard_fn, mesh,\n"
        "                          (P('data', None), P('data')),\n"
        "                          P('data', None))\n"
        "    return fn(X, w)\n")
    assert f.rules_fired() == ["TM030"]


def test_tm030_axis_index_is_traced():
    """axis_index has no tainted operand but its result is a device
    value — casting it is a host sync."""
    f = _lint(
        "def run(X, mesh):\n"
        "    def shard_fn(X_s):\n"
        "        i = lax.axis_index('data')\n"
        "        return X_s * int(i)\n"
        "    fn = shard_map_compat(shard_fn, mesh,\n"
        "                          (P('data', None),), P('data', None))\n"
        "    return fn(X)\n")
    assert f.rules_fired() == ["TM030"]


def test_collective_body_with_host_driver_is_clean():
    """The host driver around the shard_map call (np.asarray of the
    jitted result, float() of host metadata) must NOT be misread as
    traced — the historical false-positive mode on psum/shard_map code."""
    f = _lint(
        "def driver(X, w, mesh):\n"
        "    data_axis = mesh.axis_names[0]\n"
        "    def shard_fn(X_s, w_s):\n"
        "        part = jnp.stack([w_s.sum(), (w_s * w_s).sum()])\n"
        "        return lax.psum(part, axis_name=data_axis)\n"
        "    fn = shard_map_compat(shard_fn, mesh,\n"
        "                          (P('data', None), P('data')), P(None))\n"
        "    out = jax.jit(fn)(X, w)\n"
        "    beta = np.asarray(out)\n"
        "    return beta[0], float(beta[1])\n")
    assert len(f) == 0


def test_shard_body_static_metadata_is_clean():
    f = _lint(
        "def run(X, mesh):\n"
        "    def shard_fn(X_s):\n"
        "        k = max(1, X_s.shape[0] // 4)\n"
        "        idx = (jnp.arange(k) * 2) % X_s.shape[0]\n"
        "        pooled = lax.all_gather(X_s[idx], 'data')\n"
        "        return pooled.reshape(-1, X_s.shape[1])\n"
        "    fn = shard_map_compat(shard_fn, mesh,\n"
        "                          (P('data', None),), P(None, None))\n"
        "    return fn(X)\n")
    assert len(f) == 0


# ---------------------------------------------------------------------------
# suppression + self-lint
# ---------------------------------------------------------------------------

def test_disable_comment_suppresses():
    f = _lint(
        "def total(X, w, mesh):\n"
        "    def shard_fn(X_s, w_s):\n"
        "        return (w_s * X_s[:, 0]).sum()  # tmog: disable=TM040\n"
        "    fn = shard_map_compat(shard_fn, mesh,\n"
        "                          (P('data', None), P('data')), P())\n"
        "    return fn(X, w)\n")
    assert len(f) == 0


def test_parallel_sharded_is_the_clean_corpus():
    """Every real shard_map body (colstats/Newton/histogram/quantile/
    forest) lints clean — the satellite regression for collective code."""
    f = shard_lint.lint_paths(
        [os.path.join(_ROOT, "transmogrifai_tpu", "parallel")])
    assert len(f) == 0, f.format()


def test_repo_self_lint_is_clean():
    f = shard_lint.lint_paths(
        [os.path.join(_ROOT, "transmogrifai_tpu"),
         os.path.join(_ROOT, "examples")])
    assert len(f) == 0, f.format()
