"""Runtime SPMD contract tests (analysis/contracts.py TM024-TM026).

The TMOG_CHECK=1 sharding contracts the tier-1 multichip smoke runs:
pad-invariance of the sharded sweep programs, mesh-vs-single-device
parity, and checkpoint fingerprint byte round-trip — plus seeded
violations proving each check actually bites.
"""
import json
import os

import numpy as np
import pytest

from transmogrifai_tpu.analysis.contracts import (
    check_checkpoint_roundtrip, check_mesh_parity, check_pad_invariance,
    check_sharding_contracts,
)


def _data(n=600, d=6, seed=3):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)).astype(np.float32)
    beta = rng.normal(size=d).astype(np.float32)
    y = (1 / (1 + np.exp(-(X @ beta))) > rng.random(n)).astype(np.float32)
    in_tr = rng.random(n) < 0.75
    ctxs = [(in_tr.astype(np.float32), (~in_tr).astype(np.float32))]
    return X, y, ctxs


def _lr_group_factory(grid=None):
    from transmogrifai_tpu.models import OpLogisticRegression
    from transmogrifai_tpu.selector.grid_groups import make_grid_group

    grid = grid or [{"reg_param": r, "elastic_net_param": 0.0}
                    for r in (0.01, 0.1)]
    proto = OpLogisticRegression()
    return lambda: make_grid_group(proto, grid, "binary", "AuPR")


def _mesh(queue_width=2, n_devices=4):
    import jax

    from transmogrifai_tpu.parallel.mesh import make_sweep_mesh

    return make_sweep_mesh(queue_width,
                           n_devices=min(n_devices, len(jax.devices())))


# ---------------------------------------------------------------------------
# the real LR grid group satisfies all three contracts
# ---------------------------------------------------------------------------

def test_lr_grid_group_is_pad_invariant():
    X, y, ctxs = _data()
    f = check_pad_invariance(_lr_group_factory(), X, y, ctxs, _mesh())
    assert len(f) == 0, f.format()


def test_lr_grid_group_mesh_parity():
    X, y, ctxs = _data()
    f = check_mesh_parity(_lr_group_factory(), X, y, ctxs, _mesh())
    assert len(f) == 0, f.format()


def test_pad_invariance_single_device_group():
    """mesh=None: zero-weight garbage rows must be inert on the
    single-chip batched program too."""
    X, y, ctxs = _data()
    f = check_pad_invariance(_lr_group_factory(), X, y, ctxs, None)
    assert len(f) == 0, f.format()


def test_combined_audit_with_checkpoint(tmp_path):
    from transmogrifai_tpu.workflow.checkpoint import (
        SweepCheckpointManager, sweep_fingerprint)

    X, y, ctxs = _data()
    mesh = _mesh()
    fp = sweep_fingerprint([("lr", {"reg_param": 0.1}, None)], "AuPR",
                           "tvs", mesh=mesh, n_rows=len(y))
    m = SweepCheckpointManager(str(tmp_path), fp)
    m.record_unit(0, [0.625, 0.5], None)
    m.save_rung_state({"alive": [0, 1], "rung": 0})
    f = check_sharding_contracts(
        _lr_group_factory(), X, y, ctxs, mesh,
        checkpoint_dir=str(tmp_path), checkpoint_fingerprint=fp)
    assert len(f) == 0, f.format()


# ---------------------------------------------------------------------------
# seeded violations — each check fires exactly its rule
# ---------------------------------------------------------------------------

class _PadLeakyGroup:
    """A 'batched program' whose metric depends on the PADDED row count:
    the exact bug pad-invariance exists to catch."""

    def __init__(self):
        self.mesh = None

    def with_mesh(self, mesh):
        self.mesh = mesh
        return self

    def run(self, X, y, weight_ctxs):
        # unmasked reduction over ALL rows — pad rows leak in
        return np.array([[float(np.abs(X).sum())] for _ in range(2)])


class _MeshDivergentGroup(_PadLeakyGroup):
    def run(self, X, y, weight_ctxs):
        base = float((X[:, 0] * weight_ctxs[0][1]).sum())
        bump = 1.0 if self.mesh is not None else 0.0  # sharded math drifted
        return np.array([[base + bump], [base + bump]])


def test_tm024_fires_on_pad_leak():
    X, y, ctxs = _data(200, 4)
    f = check_pad_invariance(lambda: _PadLeakyGroup(), X, y, ctxs, _mesh())
    assert f.rules_fired() == ["TM024"]


def test_tm025_fires_on_mesh_divergence():
    X, y, ctxs = _data(200, 4)
    f = check_mesh_parity(lambda: _MeshDivergentGroup(), X, y, ctxs,
                          _mesh())
    assert f.rules_fired() == ["TM025"]


def test_tm026_fires_on_reencoded_checkpoint(tmp_path):
    from transmogrifai_tpu.workflow.checkpoint import (
        SWEEP_CHECKPOINT_JSON, SweepCheckpointManager, sweep_fingerprint)

    fp = sweep_fingerprint([("lr", {"reg_param": 0.1}, None)], "AuPR",
                           "tvs")
    m = SweepCheckpointManager(str(tmp_path), fp)
    m.record_unit(0, [0.5], None)
    assert len(check_checkpoint_roundtrip(str(tmp_path), fp)) == 0
    # a foreign writer re-encodes the manifest (different separators):
    # the round-trip is no longer the identity
    path = tmp_path / SWEEP_CHECKPOINT_JSON
    doc = json.loads(path.read_text())
    path.write_text(json.dumps(doc, sort_keys=True))
    f = check_checkpoint_roundtrip(str(tmp_path), fp)
    assert f.rules_fired() == ["TM026"]


def test_declining_group_raises():
    X, y, ctxs = _data(100, 4)

    class _Declines(_PadLeakyGroup):
        def run(self, X, y, weight_ctxs):
            return None

    with pytest.raises(ValueError, match="declined"):
        check_pad_invariance(lambda: _Declines(), X, y, ctxs, _mesh())


# ---------------------------------------------------------------------------
# ShardedMatrixWriter pad-tail guard (TMOG_CHECK=1)
# ---------------------------------------------------------------------------

def test_writer_pad_tail_contract(monkeypatch):
    from transmogrifai_tpu.parallel.ingest import ShardedMatrixWriter

    monkeypatch.setenv("TMOG_CHECK", "1")
    mesh = _mesh(queue_width=2, n_devices=4)
    w = ShardedMatrixWriter(mesh, 10, 3)  # 10 rows over 2+ data shards
    rng = np.random.default_rng(0)
    w.append(rng.normal(size=(10, 3)).astype(np.float32))
    out = w.finish()  # clean: pad tail zero-filled by the writer
    assert out.shape[0] % mesh.shape[mesh.axis_names[0]] == 0
    host = np.asarray(out)
    assert (host[10:] == 0).all()
