"""The "day in production" soak — seed-determinism and recovery counters.

The capstone e2e (examples/bench_soak.py): stream ingest with injected
faults -> chunked workflow-CV train with RawFeatureFilter -> serve ->
drift -> warm-start refresh -> guarded swap with a poisoned candidate
rejected and a forced bake rollback.  Two runs at one seed must produce
byte-identical deterministic records.

The in-process tests here run the scenario WITHOUT the SIGKILL
subprocess legs and without a device mesh (single-device pytest
environment); the full matrix — forced 4-device mesh, device.loss mesh
shrink, CV-sweep SIGKILL + cross-mesh resume, refresh SIGKILL — is gated
by scripts/tier1.sh SOAK_SMOKE, and the slow-marked test below runs the
whole harness end to end.
"""
import json
import os
import subprocess
import sys

import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_ROOT, "examples"))

import bench_soak  # noqa: E402


@pytest.fixture(scope="module")
def two_runs():
    records = []
    for _ in range(2):
        record, _walls = bench_soak.run_soak(
            seed=11, rows=300, chunk_rows=32, parallel=None,
            kill_legs=False, log=lambda m: None)
        records.append(record)
    return records


class TestSoakDeterminism:
    def test_two_runs_byte_identical(self, two_runs):
        a, b = two_runs
        assert json.dumps(a, sort_keys=True) == json.dumps(b,
                                                           sort_keys=True)

    def test_final_scores_byte_identical(self, two_runs):
        a, b = two_runs
        assert a["final_scores"] == b["final_scores"]
        assert len(a["final_scores"]) >= 100

    def test_recovery_counters_moved(self, two_runs):
        rec = two_runs[0]
        # every recovery path exercised (mesh shrinks need the forced
        # multi-device environment — SOAK_SMOKE gates that leg)
        assert rec["train"]["retries"] >= 1
        assert rec["train"]["quarantined_records"] >= 1
        assert rec["swap"]["rollbacks"] >= 1
        assert rec["drift"]["fired_on_drifted"]
        assert rec["drift"]["quiet_on_clean"]
        assert rec["faults_fired"]["reader.chunk:io_error"] == 1
        assert rec["faults_fired"]["swap.bake:raise"] == 1

    def test_scenario_shape(self, two_runs):
        rec = two_runs[0]
        assert rec["phases"] == ["ingest", "train", "serve", "drift",
                                 "refresh", "swap", "score"]
        assert rec["train"]["dropped_features"] == ["junk", "leaky"]
        assert rec["swap"]["swaps_rejected"] >= 1
        assert rec["swap"]["baked_in"]
        assert rec["swap"]["rollback_reason"] == "probe_error:FaultError"


@pytest.mark.slow
@pytest.mark.faults
def test_full_soak_smoke_harness():
    """The whole bench — two subprocess runs on a forced 4-device mesh
    with both SIGKILL legs — exits zero and reports nonzero counters."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "examples", "bench_soak.py"),
         "--smoke"],
        capture_output=True, text=True, env=env, timeout=900)
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads(proc.stdout.splitlines()[-1])
    assert out["ok"]
    assert all(v >= 1 for v in out["counters"].values()), out["counters"]
