"""Sparse histogram path (round 4) — XGBoost-core sparsity parity.

The reference's only native component (xgboost4j's C++ hist core,
OpXGBoostClassifier.scala:47) is sparsity-aware twice over: the quantile
sketch runs on present values, and histogram accumulation touches only
stored entries.  This suite pins both TPU-native equivalents:

 * ``quantile_bins_sparse_aware`` — mostly-zero features spend their bins
   on the nonzeros (an all-values sketch collapses to ~2 usable bins);
 * ``build_feature_csr`` + ``_sparse_level_hists`` — per-feature CSR
   histogram build over the ~density·N·D nonzero entries with the zero
   bin reconstructed analytically (zero-bin = node totals − nonzero sums),
   verified against the dense kernel on identical edges.
"""
import numpy as np
import pytest

from transmogrifai_tpu.models.gbdt_kernels import (
    build_feature_csr, grow_tree, quantile_bins, quantile_bins_sparse_aware,
)


def _sparse_data(n=4000, d=40, density=0.05, seed=5):
    rng = np.random.default_rng(seed)
    X = np.zeros((n, d), np.float32)
    nnz = max(1, int(d * density))
    cols = rng.integers(0, d, size=(n, nnz))
    vals = rng.exponential(1.0, size=(n, nnz)).astype(np.float32)
    X[np.repeat(np.arange(n), nnz), cols.ravel()] = vals.ravel()
    z = X[:, :8] @ rng.normal(size=8).astype(np.float32)
    y = (z > np.median(z)).astype(np.float32)
    return X, y


class TestSparseSketch:
    def test_sparse_aware_sketch_keeps_resolution(self):
        X, _ = _sparse_data(6000, 10, density=0.05)
        e_plain = quantile_bins(X, 32)
        e_sparse = quantile_bins_sparse_aware(X, 32)
        # all-values sketch of a 95%-zero feature: nearly every edge
        # collapses; nonzero-aware sketch keeps most of the 31 edges
        assert np.isfinite(e_plain[0]).sum() <= 5
        assert np.isfinite(e_sparse[0]).sum() >= 20
        # an edge at 0 separates the zeros from positive values
        assert 0.0 in e_sparse[0]

    def test_dense_features_unchanged(self):
        rng = np.random.default_rng(0)
        X = rng.normal(size=(5000, 4)).astype(np.float32)
        np.testing.assert_allclose(quantile_bins_sparse_aware(X, 16),
                                   quantile_bins(X, 16), atol=1e-6)


class TestCsrBuild:
    def test_entries_and_zero_bin(self):
        X, _ = _sparse_data(2000, 12)
        edges = quantile_bins_sparse_aware(X, 16)
        rows, bins, zero_bin = build_feature_csr(X, edges)
        n, d = X.shape
        for j in range(d):
            idx = np.nonzero(X[:, j])[0]
            assert (rows[j, :len(idx)] == idx).all()
            assert (rows[j, len(idx):] == n).all()        # sentinel padding
            # bins match the dense quantizer on those entries
            e = np.sort(edges[j])
            expect = np.searchsorted(e, X[idx, j], side="left")
            np.testing.assert_array_equal(bins[j, :len(idx)], expect)
            assert zero_bin[j] == np.searchsorted(e, 0.0, side="left")

    def test_declines_dense_and_outlier_matrices(self):
        rng = np.random.default_rng(1)
        dense = rng.normal(size=(500, 8)).astype(np.float32)
        assert build_feature_csr(dense, quantile_bins(dense, 8)) is None
        X, _ = _sparse_data(2000, 12)
        X[:, 0] = 1.0                                     # one dense column
        assert build_feature_csr(
            X, quantile_bins_sparse_aware(X, 8)) is None


class TestSparseKernelParity:
    @pytest.mark.parametrize("depth", [3, 6])
    def test_sparse_tree_equals_dense_kernel(self, depth):
        """Identical edges + identical channels: the CSR build with
        analytic zero-bin must reproduce the dense kernel's tree."""
        import jax.numpy as jnp

        from transmogrifai_tpu.models.gbdt_kernels import apply_bins

        X, y = _sparse_data(3000, 24)
        edges = quantile_bins_sparse_aware(X, 16)
        binned = apply_bins(jnp.asarray(X), jnp.asarray(edges))
        rows, bins, zero_bin = build_feature_csr(X, edges)
        csr = (jnp.asarray(rows), jnp.asarray(bins),
               jnp.asarray(np.eye(16, dtype=np.float32)[zero_bin]))
        G = jnp.asarray((0.5 - y)[:, None])
        H = jnp.asarray(np.full((len(y), 1), 0.25, np.float32))
        C = jnp.asarray(np.ones(len(y), np.float32))
        kw = dict(max_depth=depth, n_bins=16, lam=1.0,
                  min_instances=5.0, newton_leaf=True)
        f_d, t_d, l_d = grow_tree(binned, G, H, C, **kw)
        f_s, t_s, l_s = grow_tree(binned, G, H, C, csr=csr, **kw)
        np.testing.assert_array_equal(np.asarray(f_s), np.asarray(f_d))
        np.testing.assert_array_equal(np.asarray(t_s), np.asarray(t_d))
        np.testing.assert_allclose(np.asarray(l_s), np.asarray(l_d),
                                   atol=1e-5)


class TestSparseEndToEnd:
    def test_xgb_sparse_fit_engages_and_learns(self, monkeypatch):
        """A wide mostly-zero fit takes the CSR path end to end (prep
        detection -> scan-chunk rounds) and still learns the signal."""
        import transmogrifai_tpu.models.trees as trees_mod
        from transmogrifai_tpu.evaluators.metrics import aupr
        from transmogrifai_tpu.models.trees import OpXGBoostClassifier

        # drop the size floor so the small test matrix qualifies; opt into
        # the CSR histogram path (default off — see _prep_tree_inputs_sparse)
        monkeypatch.setattr(trees_mod, "_SPARSE_MIN_ELEMS", 1)
        monkeypatch.setenv("TMOG_SPARSE_HIST", "1")
        X, y = _sparse_data(6000, 50, density=0.08, seed=9)
        edges, binned, csr = trees_mod._prep_tree_inputs_sparse(X, 32)
        assert csr is not None, "sparse path should engage on 92%-zero data"
        est = OpXGBoostClassifier(num_round=15, eta=0.3, max_depth=4,
                                  gamma=0.0, early_stopping_rounds=0)
        model = est.fit_raw(X, y)
        score = model.predict_batch(X).probability[:, 1]
        assert float(aupr(y, score)) > 0.80
