"""Contract specs: serialize -> deserialize -> re-apply round trips across
the stage library.

Reference test strategy (SURVEY §4): ``OpTransformerSpec`` asserts every
transformer reproduces its output after a JSON round trip
(features/.../test/OpTransformerSpec.scala:47-182); ``OpEstimatorSpec`` the
same for fitted models.  Here one parameterized sweep covers the breadth the
reference spreads over ~100 per-stage suites.
"""
import json

import numpy as np
import pytest

from transmogrifai_tpu.ops.date_geo import (
    DateListVectorizer, DateToUnitCircleVectorizer, GeolocationVectorizer,
    TimePeriodTransformer,
)
from transmogrifai_tpu.ops.detectors import (
    EmailToPickListMapTransformer, FilterMap, IsValidPhoneDefaultCountry,
    LangDetector, MimeTypeDetector, ValidEmailTransformer,
    UrlMapToPickListMapTransformer,
)
from transmogrifai_tpu.ops.dsl_transformers import (
    AliasTransformer, ExistsTransformer, JaccardSimilarity,
    MathBinaryTransformer, MathScalarTransformer, NGramSimilarity,
    ReplaceTransformer, SubstringTransformer, ToOccurTransformer,
)
from transmogrifai_tpu.ops.map_vectorizers import (
    GeolocationMapVectorizer, MultiPickListMapVectorizer, NumericMapVectorizer,
    SmartTextMapVectorizer, TextMapPivotVectorizer,
)
from transmogrifai_tpu.ops.numeric import (
    FillMissingWithMean, NumericBucketizer, OpScalarStandardScaler,
    PercentileCalibrator,
)
from transmogrifai_tpu.ops.text import (
    OpCountVectorizer, OpHashingTF, OpNGram, OpStopWordsRemover,
    OpStringIndexer, TextLenTransformer, TextTokenizer,
)
from transmogrifai_tpu.ops.vectorizers import (
    BinaryVectorizer, IntegralVectorizer, MultiPickListVectorizer,
    OneHotVectorizer, RealVectorizer, SmartTextVectorizer,
    TextHashingVectorizer,
)
from transmogrifai_tpu.stages.base import Estimator
from transmogrifai_tpu.testkit import TestFeatureBuilder
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.workflow.persistence import (
    _ArrayStore, _load_stage, _stage_record,
)

REALS = ("x", ft.Real, [1.0, 2.5, None, 4.0, -1.0, 0.0])
REALS2 = ("x2", ft.Real, [2.0, None, 1.0, 0.5, 3.0, 1.5])
INTS = ("i", ft.Integral, [1, None, 3, 0, 7, 2])
BINS = ("b", ft.Binary, [True, False, None, True, False, True])
PICK = ("p", ft.PickList, ["a", "b", "a", None, "c", "a"])
MPL = ("mp", ft.MultiPickList, [{"a", "b"}, {"a"}, None, {"c"}, set(), {"b"}])
TEXT = ("t", ft.Text, ["hello world", "foo bar baz", None,
                       "hello again world", "the quick brown fox", "foo"])
TXTL = ("tl", ft.TextList, [["a", "b"], ["b"], None, ["c", "a"], [], ["a"]])
DATES = ("d", ft.Date, [1577836800000, 1585699200000, None,
                        1593561600000, 1601510400000, 1609459200000])
DLIST = ("dl", ft.DateList, [[1577836800000, 1585699200000],
                             [1593561600000], None, [], [1601510400000],
                             [1609459200000]])
GEO = ("g", ft.Geolocation, [[37.7, -122.4, 1.0], None, [40.7, -74.0, 2.0],
                             [51.5, -0.1, 1.0], [48.9, 2.35, 3.0], None])
NMAP = ("nm", ft.RealMap, [{"a": 1.0, "b": 2.0}, {"a": 3.0}, None,
                           {"b": 4.0}, {}, {"a": 5.0, "b": 6.0}])
TMAP = ("tm", ft.TextMap, [{"k1": "x", "k2": "y"}, {"k1": "x"}, None,
                           {"k2": "z"}, {}, {"k1": "y"}])
MPMAP = ("mm", ft.MultiPickListMap, [{"k": {"a", "b"}}, {"k": {"a"}}, None,
                                     {"k": {"c"}}, {}, {"k": {"b"}}])
GMAP = ("gm", ft.GeolocationMap, [{"home": [37.7, -122.4, 1.0]}, None,
                                  {"home": [40.7, -74.0, 2.0]}, {},
                                  {"home": [51.5, -0.1, 1.0]}, None])
EMAIL = ("e", ft.Email, ["a@b.com", "bad", None, "x@y.org", "no-at", "q@r.io"])
PHONE = ("ph", ft.Phone, ["+14155552671", "555-2671", None, "12025550123",
                          "bad", "+442071838750"])
EMAP = ("em", ft.EmailMap, [{"w": "a@b.com"}, {"w": "bad"}, None,
                            {"w": "x@y.org"}, {}, {"w": "q@r.io"}])
UMAP = ("um", ft.URLMap, [{"w": "https://a.com/x"}, {"w": "bad"}, None,
                          {"w": "http://b.org/y"}, {}, {"w": "https://c.io"}])
B64 = ("b64", ft.Base64, ["aGVsbG8=", None, "UEsDBA==", "JVBERi0=",
                          "aGVsbG8=", None])

CASES = [
    ("RealVectorizer", lambda: RealVectorizer(), [REALS, REALS2]),
    ("IntegralVectorizer", lambda: IntegralVectorizer(), [INTS]),
    ("BinaryVectorizer", lambda: BinaryVectorizer(), [BINS]),
    ("OneHotVectorizer", lambda: OneHotVectorizer(top_k=3, min_support=1),
     [PICK]),
    ("MultiPickListVectorizer",
     lambda: MultiPickListVectorizer(top_k=3, min_support=1), [MPL]),
    ("TextHashingVectorizer",
     lambda: TextHashingVectorizer(num_features=16), [TEXT]),
    ("SmartTextVectorizer",
     lambda: SmartTextVectorizer(max_cardinality=2, num_hash_features=16,
                                 min_support=1), [TEXT]),
    ("NumericBucketizer",
     lambda: NumericBucketizer(split_points=[-10.0, 0.0, 2.0, 10.0]), [REALS]),
    ("FillMissingWithMean", lambda: FillMissingWithMean(), [REALS]),
    ("OpScalarStandardScaler", lambda: OpScalarStandardScaler(), [REALS]),
    ("PercentileCalibrator", lambda: PercentileCalibrator(buckets=4),
     [REALS]),
    ("TextTokenizer", lambda: TextTokenizer(), [TEXT]),
    ("OpNGram", lambda: OpNGram(n=2), [TXTL]),
    ("OpStopWordsRemover", lambda: OpStopWordsRemover(), [TXTL]),
    ("OpCountVectorizer",
     lambda: OpCountVectorizer(vocab_size=8, min_df=1), [TXTL]),
    ("OpHashingTF", lambda: OpHashingTF(num_features=16), [TXTL]),
    ("OpStringIndexer", lambda: OpStringIndexer(), [("s", ft.Text,
     ["a", "b", "a", "c", "b", "a"])]),
    ("TextLenTransformer", lambda: TextLenTransformer(), [TEXT]),
    ("MathScalarTransformer",
     lambda: MathScalarTransformer(op="multiply", scalar=2.0), [REALS]),
    ("MathBinaryTransformer", lambda: MathBinaryTransformer(op="plus"),
     [REALS, REALS2]),
    ("AliasTransformer", lambda: AliasTransformer(name="renamed"), [REALS]),
    ("SubstringTransformer", lambda: SubstringTransformer(),
     [("hay", ft.Text, ["hello world", "foo", None, "bar", "baz", "ok"]),
      ("needle", ft.Text, ["world", "oo", "x", None, "zz", "k"])]),
    ("JaccardSimilarity", lambda: JaccardSimilarity(), [MPL,
     ("mp2", ft.MultiPickList, [{"a"}, {"a", "c"}, {"b"}, None, {"c"},
                                set()])]),
    ("NGramSimilarity", lambda: NGramSimilarity(n=3),
     [("t1", ft.Text, ["hello", "abcdef", None, "xyz", "same", "q"]),
      ("t2", ft.Text, ["hallo", "abcxef", "y", None, "same", "q"])]),
    ("ToOccurTransformer", lambda: ToOccurTransformer(), [REALS]),
    ("ExistsTransformer", lambda: ExistsTransformer(), [REALS]),
    ("ReplaceTransformer",
     lambda: ReplaceTransformer(replace="a", with_value="z"), [PICK]),
    ("TimePeriodTransformer",
     lambda: TimePeriodTransformer(period="DayOfWeek"), [DATES]),
    ("DateToUnitCircleVectorizer",
     lambda: DateToUnitCircleVectorizer(time_periods=("HourOfDay",)), [DATES]),
    ("DateListVectorizer",
     lambda: DateListVectorizer(pivot="SinceLast",
                                reference_ms=1612137600000), [DLIST]),
    ("GeolocationVectorizer", lambda: GeolocationVectorizer(), [GEO]),
    ("NumericMapVectorizer", lambda: NumericMapVectorizer(), [NMAP]),
    ("TextMapPivotVectorizer",
     lambda: TextMapPivotVectorizer(top_k=3, min_support=1), [TMAP]),
    ("MultiPickListMapVectorizer",
     lambda: MultiPickListMapVectorizer(top_k=3, min_support=1), [MPMAP]),
    ("SmartTextMapVectorizer",
     lambda: SmartTextMapVectorizer(max_cardinality=2, num_hash_features=8,
                                    min_support=1), [TMAP]),
    ("GeolocationMapVectorizer", lambda: GeolocationMapVectorizer(), [GMAP]),
    ("ValidEmailTransformer", lambda: ValidEmailTransformer(), [EMAIL]),
    ("IsValidPhoneDefaultCountry",
     lambda: IsValidPhoneDefaultCountry(default_region="1"), [PHONE]),
    ("EmailToPickListMapTransformer",
     lambda: EmailToPickListMapTransformer(), [EMAP]),
    ("UrlMapToPickListMapTransformer",
     lambda: UrlMapToPickListMapTransformer(), [UMAP]),
    ("FilterMap", lambda: FilterMap(allow_keys=["w"]), [UMAP]),
    ("MimeTypeDetector", lambda: MimeTypeDetector(), [B64]),
    ("LangDetector", lambda: LangDetector(), [TEXT]),
]


def _round_trip(stage, feats):
    store = _ArrayStore()
    rec = _stage_record(stage, store)
    rec = json.loads(json.dumps(rec, default=str))   # same as model writer
    stage2 = _load_stage(rec, store.arrays)
    stage2.set_input(*feats)
    return stage2


def _assert_columns_equal(c1, c2, label):
    v1, v2 = c1.values, c2.values
    a1, a2 = np.asarray(v1), np.asarray(v2)
    assert a1.shape == a2.shape, label
    if a1.dtype == object or a2.dtype == object:
        for r1, r2 in zip(a1, a2):
            assert r1 == r2 or (r1 is None and r2 is None), (label, r1, r2)
    else:
        np.testing.assert_allclose(a1, a2, rtol=1e-6, atol=1e-6,
                                   err_msg=label, equal_nan=True)


@pytest.mark.parametrize("name,make,inputs", CASES,
                         ids=[c[0] for c in CASES])
def test_serialize_deserialize_reapply(name, make, inputs):
    data, feats = TestFeatureBuilder.build(*inputs)
    stage = make()
    stage.set_input(*feats)
    cols = [data[f.name] for f in feats]
    if isinstance(stage, Estimator):
        model = stage.fit(data)
    else:
        model = stage
    out1 = model.transform_columns(*cols)
    model2 = _round_trip(model, feats)
    out2 = model2.transform_columns(*cols)
    assert out1.ftype is out2.ftype or \
        out1.ftype.type_name() == out2.ftype.type_name()
    _assert_columns_equal(out1, out2, name)
