"""Streaming workflow-level CV — fold-tagged mergeable fit states.

The ISSUE 14 tentpole's CV half: ``train(chunk_rows=k)`` with
``with_workflow_cv()`` must match the in-core fold-refit path — identical
winner, per-fold metrics within each stage's declared
``streaming_fit_tol`` — at chunk_rows in {7, 64, N}; checkpointed CV
trains resume bit-exactly from a mid-fold kill AND from a mid-CV-sweep
kill; fold-geometry changes refuse with a key-level fingerprint diff.
"""
import os

import numpy as np
import pandas as pd
import pytest

from transmogrifai_tpu import FeatureBuilder, OpWorkflow, transmogrify
from transmogrifai_tpu.models import OpLogisticRegression
from transmogrifai_tpu.preparators import SanityChecker
from transmogrifai_tpu.selector import BinaryClassificationModelSelector, grid
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.utils import faults
from transmogrifai_tpu.utils.faults import FaultSpec
from transmogrifai_tpu.utils.uid import reset_uids
from transmogrifai_tpu.workflow.checkpoint import CheckpointMismatchError

N_ROWS = 400


def synthetic_binary(n=N_ROWS, seed=0):
    rng = np.random.default_rng(seed)
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)
    cat = rng.choice(["a", "b", "c"], size=n)
    logits = 1.5 * x1 - 1.0 * x2 + (cat == "a") * 0.8
    y = (rng.random(n) < 1 / (1 + np.exp(-logits))).astype(float)
    return pd.DataFrame({"label": y, "x1": x1, "x2": x2, "cat": cat})


def build_dag(num_folds=3, validator="cv", spearman=False):
    reset_uids()
    label = FeatureBuilder.RealNN("label").as_response()
    feats = transmogrify([FeatureBuilder.Real("x1").as_predictor(),
                          FeatureBuilder.Real("x2").as_predictor(),
                          FeatureBuilder.PickList("cat").as_predictor()])
    checker = SanityChecker(
        max_correlation=0.99,
        correlation_type="spearman" if spearman else "pearson")
    checked = checker.set_input(label, feats).get_output()
    factory = (BinaryClassificationModelSelector.with_cross_validation
               if validator == "cv" else
               BinaryClassificationModelSelector.with_train_validation_split)
    kwargs = ({"num_folds": num_folds} if validator == "cv"
              else {"train_ratio": 0.75})
    selector = factory(models_and_parameters=[
        (OpLogisticRegression(), grid(reg_param=[0.01, 0.1]))], **kwargs)
    prediction = selector.set_input(label, checked).get_output()
    return prediction, selector, checker


def _probs(model, df):
    scored = model.score(data=df)
    name = next(n for n in scored.names()
                if issubclass(scored[n].ftype, ft.Prediction))
    return [d["probability_1"] for d in scored[name].to_list()]


@pytest.fixture(scope="module")
def df():
    return synthetic_binary()


@pytest.fixture(scope="module")
def incore_cv(df):
    prediction, selector, _ = build_dag()
    model = (OpWorkflow().set_result_features(prediction)
             .set_input_data(df).with_workflow_cv().train())
    return (model, _probs(model, df),
            selector.metadata["workflow_cv_results"],
            selector.metadata["model_selector_summary"])


class TestStreamingCVParity:
    @pytest.mark.parametrize("chunk_rows", [7, 64, N_ROWS])
    def test_matches_incore_fold_refit(self, df, incore_cv, chunk_rows):
        _, p0, results0, summ0 = incore_cv
        prediction, selector, _ = build_dag()
        model = (OpWorkflow().set_result_features(prediction)
                 .set_input_data(df).with_workflow_cv()
                 .train(chunk_rows=chunk_rows))
        results = selector.metadata["workflow_cv_results"]
        summ = selector.metadata["model_selector_summary"]
        # identical winner, per-fold metrics within streaming tolerance
        assert summ["bestModelParams"] == summ0["bestModelParams"]
        assert len(results) == len(results0)
        for a, b in zip(results0, results):
            assert a["params"] == b["params"]
            assert len(b["foldValues"]) == 3
            assert b["metricValue"] == pytest.approx(a["metricValue"],
                                                     abs=1e-4)
        # the winner was CONSUMED by the tail fit (find_best contract)
        assert selector.best_estimator is None
        # end-to-end scores track the in-core CV train
        assert _probs(model, df) == pytest.approx(p0, abs=1e-3)

    def test_train_validation_split_variant(self, df):
        prediction, selector, _ = build_dag(validator="tvs")
        m0 = (OpWorkflow().set_result_features(prediction)
              .set_input_data(df).with_workflow_cv().train())
        r0 = selector.metadata["workflow_cv_results"]
        prediction1, selector1, _ = build_dag(validator="tvs")
        (OpWorkflow().set_result_features(prediction1)
         .set_input_data(df).with_workflow_cv().train(chunk_rows=64))
        r1 = selector1.metadata["workflow_cv_results"]
        assert [len(r["foldValues"]) for r in r1] == [1, 1]
        for a, b in zip(r0, r1):
            assert b["metricValue"] == pytest.approx(a["metricValue"],
                                                     abs=1e-4)

    def test_refresh_composes_with_workflow_cv(self, df):
        prediction, selector, _ = build_dag()
        wf = (OpWorkflow().set_result_features(prediction)
              .set_input_data(df).with_workflow_cv())
        model = wf.train(chunk_rows=64)
        window = synthetic_binary(n=200, seed=9)
        refreshed = wf.refresh(model, data=window, chunk_rows=64)
        # the re-selection ran on the window, warm-started states merged
        assert refreshed.refresh_report["merged"]
        assert selector.metadata["workflow_cv_results"]
        assert len(_probs(refreshed, window)) == 200

    def test_cv_fold_fault_point_fires(self, df):
        prediction, _sel, _ = build_dag()
        wf = (OpWorkflow().set_result_features(prediction)
              .set_input_data(df).with_workflow_cv())
        with faults.inject(FaultSpec(point="cv.fold", action="raise",
                                     at=1)):
            with pytest.raises(faults.FaultError, match=r"cv\.fold\[1\]"):
                wf.train(chunk_rows=64)


class TestStreamingCVCheckpoint:
    def _train(self, df, ckdir, fault=None, num_folds=3):
        prediction, selector, _ = build_dag(num_folds=num_folds)
        wf = (OpWorkflow().set_result_features(prediction)
              .set_input_data(df).with_workflow_cv())
        if fault is None:
            model = wf.train(chunk_rows=32, checkpoint_dir=ckdir,
                             checkpoint_every_chunks=2)
            return model, selector
        with faults.inject(fault):
            with pytest.raises(faults.FaultError):
                wf.train(chunk_rows=32, checkpoint_dir=ckdir,
                         checkpoint_every_chunks=2)
        return None, None

    def test_mid_fold_resume_is_bit_exact(self, df, tmp_path):
        """A kill DURING the fold-tagged SanityChecker pass: the per-fold
        states restore bit-exactly from the mid-pass cursor and the
        resumed train reproduces the uninterrupted scores byte-for-byte."""
        ref, _ = self._train(df, str(tmp_path / "a"))
        p_ref = _probs(ref, df)
        ck = str(tmp_path / "b")
        self._train(df, ck, fault=FaultSpec(
            point="checkpoint.barrier", action="raise", at=3))
        assert os.path.exists(os.path.join(ck, "checkpoint.json"))
        resumed, selector = self._train(df, ck)
        assert resumed.ingest_profile.resumed
        assert sum(p.chunks_skipped
                   for p in resumed.ingest_profile.passes) > 0
        assert _probs(resumed, df) == p_ref

    def test_mid_cv_sweep_resume_is_bit_exact(self, df, tmp_path):
        """A kill at the CV sweep's cursor save (after the prefix passes
        completed): the fold states restore from the pass-boundary
        record, the sweep resumes at its unit cursor, and the final
        scores + per-fold metrics are byte-identical."""
        ref, sel_ref = self._train(df, str(tmp_path / "a"))
        p_ref = _probs(ref, df)
        ck = str(tmp_path / "b")
        self._train(df, ck, fault=FaultSpec(
            point="sweep.checkpoint", action="raise", at=1))
        assert os.path.exists(os.path.join(ck, "sweep", "sweep.json"))
        resumed, selector = self._train(df, ck)
        assert resumed.ingest_profile.resumed
        assert _probs(resumed, df) == p_ref
        assert ([r["metricValue"]
                 for r in selector.metadata["workflow_cv_results"]]
                == [r["metricValue"]
                    for r in sel_ref.metadata["workflow_cv_results"]])

    def test_fold_geometry_mismatch_refuses_with_key_diff(self, df,
                                                          tmp_path):
        ck = str(tmp_path / "ck")
        self._train(df, ck, fault=FaultSpec(
            point="checkpoint.barrier", action="raise", at=1))
        prediction, _, _ = build_dag(num_folds=5)
        wf = (OpWorkflow().set_result_features(prediction)
              .set_input_data(df).with_workflow_cv())
        with pytest.raises(CheckpointMismatchError,
                           match=r"cv\.numFolds: saved=3 current=5"):
            wf.train(chunk_rows=32, checkpoint_dir=ck)

    def test_cv_checkpoint_refuses_plain_train(self, df, tmp_path):
        """The CV geometry key is part of the LOGICAL fingerprint: a
        plain (non-CV) chunked train must refuse a CV checkpoint."""
        ck = str(tmp_path / "ck")
        self._train(df, ck, fault=FaultSpec(
            point="checkpoint.barrier", action="raise", at=1))
        prediction, _, _ = build_dag()
        wf = (OpWorkflow().set_result_features(prediction)
              .set_input_data(df))  # no with_workflow_cv
        with pytest.raises(CheckpointMismatchError, match="cv"):
            wf.train(chunk_rows=32, checkpoint_dir=ck)


class TestFoldTaggedStates:
    def test_fold_states_export_full_only_onto_model(self, df):
        """fit_states carries the FULL-data component (warm-start
        capital), never the per-fold scaffolding."""
        prediction, _, checker = build_dag()
        model = (OpWorkflow().set_result_features(prediction)
                 .set_input_data(df).with_workflow_cv()
                 .train(chunk_rows=64))
        payload = model.fit_states[checker.uid]
        assert not (isinstance(payload, dict)
                    and payload.get("__fold_tagged__"))

    def test_non_streamable_during_est_raises_named(self, df):
        prediction, _, checker = build_dag(spearman=True)
        wf = (OpWorkflow().set_result_features(prediction)
              .set_input_data(df).with_workflow_cv())
        with pytest.raises(ValueError, match=checker.uid):
            wf.train(chunk_rows=64)
        # in-core CV keeps working for the same DAG
        model = wf.train()
        assert _probs(model, df)
