"""Streaming RawFeatureFilter — mergeable-monoid distribution profiles.

The ISSUE 14 tentpole's RFF half: ``train(chunk_rows=k)`` with
``with_raw_feature_filter(...)`` profiles the train (and scoring) reader
chunk by chunk and must make IDENTICAL drop decisions to the in-core
pass at chunk_rows in {7, 64, N}; the distribution pass honors the
reader's resilience config, and bad records hit by all three reader
passes count ONCE in the quarantine sidecar.
"""
import json
import os

import numpy as np
import pandas as pd
import pytest

from transmogrifai_tpu import FeatureBuilder, OpWorkflow, transmogrify
from transmogrifai_tpu.models import OpLogisticRegression
from transmogrifai_tpu.preparators import SanityChecker
from transmogrifai_tpu.readers import CSVReader
from transmogrifai_tpu.readers.resilience import RetryPolicy
from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.utils import faults
from transmogrifai_tpu.utils.faults import FaultSpec

N_ROWS = 300


def make_df(n=N_ROWS, seed=5):
    rng = np.random.default_rng(seed)
    y = (rng.random(n) > 0.5).astype(float)
    return pd.DataFrame({
        "label": y,
        "good": rng.normal(size=n),
        "mostly_null": np.where(rng.random(n) < 0.999, np.nan, 1.0),
        "leaky": np.where(y > 0, np.nan, rng.normal(size=n)),
        "cat": rng.choice(["a", "b"], n),
    })


def build_pred():
    label = FeatureBuilder.RealNN("label").as_response()
    preds = [FeatureBuilder.Real("good").as_predictor(),
             FeatureBuilder.Real("mostly_null").as_predictor(),
             FeatureBuilder.Real("leaky").as_predictor(),
             FeatureBuilder.PickList("cat").as_predictor()]
    features = transmogrify(preds)
    checked = SanityChecker(max_correlation=0.99).set_input(
        label, features).get_output()
    return OpLogisticRegression(reg_param=0.01).set_input(
        label, checked).get_output()


def _wf(df_or_reader, **rff):
    kwargs = dict(min_fill_rate=0.05, max_correlation=0.9)
    kwargs.update(rff)
    wf = (OpWorkflow().set_result_features(build_pred())
          .with_raw_feature_filter(**kwargs))
    return wf.set_reader(df_or_reader)


@pytest.fixture(scope="module")
def df():
    return make_df()


@pytest.fixture(scope="module")
def incore(df):
    model = _wf(df).train()
    return model, model.raw_feature_filter_results


class TestStreamingDropParity:
    @pytest.mark.parametrize("chunk_rows", [7, 64, N_ROWS])
    def test_identical_drop_decisions(self, df, incore, chunk_rows):
        m0, res0 = incore
        mk = _wf(df).train(chunk_rows=chunk_rows)
        res = mk.raw_feature_filter_results
        assert (sorted(res.dropped_features)
                == sorted(res0.dropped_features)
                == ["leaky", "mostly_null"])
        assert res.dropped_map_keys == res0.dropped_map_keys
        # per-distribution parity: exact counts, leakage corr to float tol
        for d0, d1 in zip(res0.train_distributions,
                          res.train_distributions):
            assert (d0.name, d0.key) == (d1.name, d1.key)
            assert (d0.count, d0.nulls) == (d1.count, d1.nulls)
            assert d1.null_label_corr() == pytest.approx(
                d0.null_label_corr(), abs=1e-9)
        # exclusion reasons agree flag-for-flag
        assert ([r.to_json() for r in res.exclusion_reasons]
                == [r.to_json() for r in res0.exclusion_reasons])
        # the model actually trained on the pruned DAG
        scored = mk.score(data=df)
        assert any(issubclass(scored[n].ftype, ft.Prediction)
                   for n in scored.names())

    def test_scoring_reader_divergence_streams(self, df, rng):
        score_df = df.copy()
        score_df["good"] = rng.normal(50.0, 1.0, len(df))
        m0 = _wf(df, max_js_divergence=0.5, min_fill_rate=0.0,
                 max_correlation=1.1, scoring_data=score_df).train()
        mk = _wf(df, max_js_divergence=0.5, min_fill_rate=0.0,
                 max_correlation=1.1,
                 scoring_data=score_df).train(chunk_rows=64)
        assert "good" in mk.raw_feature_filter_results.dropped_features
        assert (sorted(mk.raw_feature_filter_results.dropped_features)
                == sorted(m0.raw_feature_filter_results.dropped_features))
        assert (mk.ingest_profile.rff or {}).get("passes") == 2

    def test_map_key_drops_clean_per_chunk(self):
        """A map column with one leaky key: the key (not the feature)
        drops, and every later chunked pass sees the cleaned maps."""
        rng = np.random.default_rng(3)
        n = 200
        y = (rng.random(n) > 0.5).astype(float)
        rows = [{"ok": float(rng.normal()),
                 **({} if y[i] > 0 else {"bad": float(rng.normal())})}
                for i in range(n)]
        df = pd.DataFrame({"label": y, "m": rows,
                           "good": rng.normal(size=n)})
        def build():
            label = FeatureBuilder.RealNN("label").as_response()
            features = transmogrify([
                FeatureBuilder.RealMap("m").as_predictor(),
                FeatureBuilder.Real("good").as_predictor()])
            # the label must reach the result DAG for the leakage check
            return SanityChecker(max_correlation=0.999).set_input(
                label, features).get_output()

        wf = (OpWorkflow().set_result_features(build())
              .with_raw_feature_filter(min_fill_rate=0.0,
                                       max_correlation=0.9))
        m0 = wf.set_reader(df).train()
        res0 = m0.raw_feature_filter_results
        assert res0.dropped_map_keys == {"m": ["bad"]}
        wf2 = (OpWorkflow().set_result_features(build())
               .with_raw_feature_filter(min_fill_rate=0.0,
                                        max_correlation=0.9))
        mk = wf2.set_reader(df).train(chunk_rows=32)
        assert (mk.raw_feature_filter_results.dropped_map_keys
                == {"m": ["bad"]})
        # the fitted map vectorizer never saw the dropped key
        vec = next(s for s in mk.stages
                   if "Map" in type(s).__name__ and hasattr(s, "keysets"))
        assert all("bad" not in ks for ks in vec.keysets)


class TestQuarantineReconciliation:
    def _csv_with_bad_row(self, df, tmp_path):
        path = str(tmp_path / "rows.csv")
        lines = df.to_csv(index=False).splitlines()
        lines.insert(8, lines[8] + ",EXTRA,EXTRA")
        with open(path, "w") as f:
            f.write("\n".join(lines) + "\n")
        return path

    def test_bad_row_counts_once_across_three_passes(self, df, tmp_path):
        """RFF adds a third reader pass; the same corrupt row is hit by
        the distribution pass AND both fit passes, and must reconcile to
        exactly ONE sidecar entry (dedupe on (source, location))."""
        path = self._csv_with_bad_row(df, tmp_path)
        side = str(tmp_path / "bad.jsonl")
        reader = CSVReader(path).with_resilience(
            bad_records="quarantine", quarantine_path=side)
        mk = _wf(reader).train(chunk_rows=32)
        ip = mk.ingest_profile
        assert ip.quarantined_records == 1
        assert ip.quarantined_rows == 1
        entries = [json.loads(l) for l in open(side)]
        assert len(entries) == 1
        assert "malformed CSV row" in entries[0]["reason"]
        # the RFF pass saw the same row universe as the fit passes
        assert (ip.rff or {}).get("rows") == ip.total_rows
        assert ip.to_json()["quarantinedRecords"] == 1

    def test_rff_pass_retries_transient_io(self, df, tmp_path):
        path = str(tmp_path / "rows.csv")
        df.to_csv(path, index=False)
        reader = CSVReader(path).with_resilience(
            retry=RetryPolicy(max_attempts=4, base_delay_s=0.01, seed=1))
        # at=1 hits the FIRST pass reaching chunk 1 — the RFF profile pass
        with faults.inject(FaultSpec(point="reader.chunk",
                                     action="io_error", at=1, times=1)):
            mk = _wf(reader).train(chunk_rows=64)
        assert (mk.ingest_profile.rff or {}).get("retries") == 1
        m0 = _wf(df).train()
        assert (sorted(mk.raw_feature_filter_results.dropped_features)
                == sorted(m0.raw_feature_filter_results.dropped_features))

    def test_rff_pass_fault_point_fires(self, df):
        wf = _wf(df)
        with faults.inject(FaultSpec(point="rff.pass", action="raise",
                                     tag="train")):
            with pytest.raises(faults.FaultError, match=r"rff\.pass"):
                wf.train(chunk_rows=64)


class TestRefreshWithRFF:
    def test_refresh_reuses_recorded_drops(self, df):
        wf = _wf(df)
        model = wf.train(chunk_rows=64)
        window = make_df(n=150, seed=11)
        refreshed = wf.refresh(model, data=window, chunk_rows=64)
        assert (refreshed.raw_feature_filter_results
                is model.raw_feature_filter_results)
        assert refreshed.refresh_report["merged"]

    def test_refresh_without_recorded_results_raises(self, df):
        plain = (OpWorkflow().set_result_features(build_pred())
                 .set_reader(df).train(chunk_rows=64))
        plain.raw_feature_filter_results = None
        wf = _wf(df)
        with pytest.raises(ValueError, match="recorded filter results"):
            wf.refresh(plain, data=make_df(n=100, seed=2), chunk_rows=64)
