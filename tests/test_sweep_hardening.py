"""Sweep robustness: per-candidate failure isolation, max_wait budget, and
XGBoost early stopping (reference parity: OpValidator.scala:94-214 isolates
candidate fits in Futures bounded by maxWait; XGBoost early stopping per
DefaultSelectorParams NumRound/EarlyStopping)."""
import time

import numpy as np
import pytest

from transmogrifai_tpu.models.classification import OpLogisticRegression
from transmogrifai_tpu.models.trees import (
    OpXGBoostClassifier, OpRandomForestClassifier,
)
from transmogrifai_tpu.selector.model_selector import ModelSelector, grid
from transmogrifai_tpu.selector.validators import (
    OpCrossValidation, OpTrainValidationSplit,
)
from transmogrifai_tpu.selector.splitters import DataSplitter


def _binary_data(n=300, d=6, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)).astype(np.float32)
    logits = X[:, 0] * 2.0 - X[:, 1]
    y = (logits + rng.normal(scale=0.5, size=n) > 0).astype(np.float32)
    return X, y


class _ExplodingLR(OpLogisticRegression):
    """Candidate that diverges: raises during fit (singular-Hessian stand-in)."""

    def fit_device(self, X, y, w, problem_type):
        raise FloatingPointError("synthetic divergence")

    def fit_raw(self, X, y, w=None):
        raise FloatingPointError("synthetic divergence")


class _SlowLR(OpLogisticRegression):
    """Candidate whose fit burns wall-clock (hung-fit stand-in)."""

    sleep_s = 0.15

    def fit_device(self, X, y, w, problem_type):
        time.sleep(self.sleep_s)
        return super().fit_device(X, y, w, problem_type)

    def fit_raw(self, X, y, w=None):
        time.sleep(self.sleep_s)
        return super().fit_raw(X, y, w)


def _selector(models_and_params, validator):
    return ModelSelector(models_and_params=models_and_params,
                         problem_type="binary", validator=validator,
                         splitter=DataSplitter(reserve_test_fraction=0.0),
                         validation_metric="AuPR")


class TestFailureIsolation:
    def test_diverging_candidate_scores_neg_inf_and_records_error(self):
        X, y = _binary_data()
        sel = _selector(
            [(_ExplodingLR(), grid(reg_param=[0.01])),
             (OpLogisticRegression(), grid(reg_param=[0.01, 0.1]))],
            OpCrossValidation(num_folds=3, stratify=True))
        best_i, results = sel.validator.validate(
            sel._candidates(), X, y, np.ones(len(y), np.float32),
            eval_fn=sel._metric, metric_name="AuPR")
        assert results[0].error is not None
        assert "divergence" in results[0].error
        assert results[0].metric_value == float("-inf")
        assert best_i in (1, 2)
        assert results[best_i].error is None
        assert np.isfinite(results[best_i].metric_value)

    def test_error_recorded_in_selector_summary(self):
        from transmogrifai_tpu.types.columns import FeatureColumn
        from transmogrifai_tpu.types.feature_types import OPVector, RealNN

        X, y = _binary_data()
        sel = _selector(
            [(_ExplodingLR(), grid(reg_param=[0.01])),
             (OpLogisticRegression(), grid(reg_param=[0.01]))],
            OpCrossValidation(num_folds=3, stratify=True))
        label_col = FeatureColumn(RealNN, y)
        feat_col = FeatureColumn(OPVector, X)
        sel.fit_columns(None, label_col, feat_col)
        summ = sel.metadata["model_selector_summary"]
        errs = [r.get("error") for r in summ["validationResults"]]
        assert any(e and "divergence" in e for e in errs)
        assert summ["bestModelType"] == "OpLogisticRegression"

    def test_minimize_metric_never_selects_failed_candidate(self):
        # regression sweep (RMSE: smaller better): an errored candidate must
        # sentinel to +inf, not -inf, or argbest would crown the failure
        from transmogrifai_tpu.models.regression import OpLinearRegression

        class _ExplodingLin(OpLinearRegression):
            def fit_device(self, X, y, w, problem_type):
                raise FloatingPointError("boom")

            def fit_raw(self, X, y, w=None):
                raise FloatingPointError("boom")

        rng = np.random.default_rng(3)
        X = rng.normal(size=(200, 4)).astype(np.float32)
        y = (X[:, 0] * 3 + 0.1 * rng.normal(size=200)).astype(np.float32)
        sel = ModelSelector(
            models_and_params=[(_ExplodingLin(), grid(reg_param=[0.0])),
                               (OpLinearRegression(), grid(reg_param=[0.0]))],
            problem_type="regression",
            validator=OpCrossValidation(num_folds=3),
            splitter=DataSplitter(reserve_test_fraction=0.0),
            validation_metric="RootMeanSquaredError")
        best_i, results = sel.validator.validate(
            sel._candidates(), X, y, np.ones(len(y), np.float32),
            eval_fn=sel._metric, metric_name="RootMeanSquaredError",
            larger_better=sel.larger_better)
        assert results[0].error is not None
        assert results[0].metric_value == float("inf")
        assert best_i == 1

    def test_all_candidates_failing_raises_clear_error(self):
        X, y = _binary_data()
        sel = _selector(
            [(_ExplodingLR(), grid(reg_param=[0.01, 0.1]))],
            OpCrossValidation(num_folds=3, stratify=True))
        with pytest.raises(RuntimeError, match="every candidate errored"):
            sel.validator.validate(
                sel._candidates(), X, y, np.ones(len(y), np.float32),
                eval_fn=sel._metric, metric_name="AuPR")


class TestMaxWaitBudget:
    def test_budget_exceeded_skips_remaining_candidates(self):
        X, y = _binary_data(n=200)
        sel = _selector(
            [(_SlowLR(), grid(reg_param=[0.01, 0.1, 0.3]))],
            OpCrossValidation(num_folds=2, stratify=True, max_wait=0.05))
        best_i, results = sel.validator.validate(
            sel._candidates(), X, y, np.ones(len(y), np.float32),
            eval_fn=sel._metric, metric_name="AuPR")
        # first candidate always runs (sweep guarantees one result);
        # the slow fits blow the 50 ms budget so the rest are skipped
        assert results[0].error is None
        skipped = [r for r in results[1:] if r.error
                   and "max_wait" in r.error]
        assert skipped, [r.error for r in results]
        assert all(r.metric_value == float("-inf") for r in skipped)
        assert best_i == 0

    def test_no_budget_runs_every_candidate(self):
        X, y = _binary_data(n=200)
        sel = _selector(
            [(OpLogisticRegression(), grid(reg_param=[0.01, 0.1]))],
            OpTrainValidationSplit(stratify=True))
        _, results = sel.validator.validate(
            sel._candidates(), X, y, np.ones(len(y), np.float32),
            eval_fn=sel._metric, metric_name="AuPR")
        assert all(r.error is None for r in results)
        assert all(np.isfinite(r.metric_value) for r in results)


class TestXGBEarlyStopping:
    def _n_trees(self, model):
        return int(np.asarray(model.feat).shape[0])

    def test_early_stopping_truncates_to_best_iteration(self):
        # trivially separable: validation AuPR saturates after few rounds,
        # stall counter fires and the ensemble truncates at best_len
        rng = np.random.default_rng(1)
        X = rng.normal(size=(400, 4)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float32)
        est = OpXGBoostClassifier(num_round=60, eta=0.3, max_depth=3,
                                  early_stopping_rounds=3,
                                  gamma=0.0, seed=3)
        est.validation_fraction = 0.25
        model = est.fit_raw(X, y)
        n_trees = self._n_trees(model)
        assert n_trees < 60, "early stopping never fired"
        # truncation drops the stalled tail: len == best iteration, which is
        # at most (rounds observed) - early_stopping_rounds
        assert n_trees <= 60 - 3

        ref = OpXGBoostClassifier(num_round=12, eta=0.3, max_depth=3,
                                  early_stopping_rounds=0, seed=3)
        full = ref.fit_raw(X, y)
        assert self._n_trees(full) == 12, "rounds=0 must disable stopping"

    def test_early_stopping_keeps_quality(self):
        rng = np.random.default_rng(2)
        X = rng.normal(size=(500, 5)).astype(np.float32)
        y = ((X[:, 0] + X[:, 1]) > 0).astype(np.float32)
        est = OpXGBoostClassifier(num_round=80, eta=0.2, max_depth=3,
                                  early_stopping_rounds=5, gamma=0.0, seed=4)
        model = est.fit_raw(X, y)
        batch = model.predict_batch(X)
        from transmogrifai_tpu.evaluators.metrics import auroc
        score = np.asarray(batch.probability)[:, 1]
        assert auroc(y, score) > 0.95
        assert self._n_trees(model) < 80

    def test_es_metric_is_validation_aupr_device_scalar(self):
        import jax

        est = OpXGBoostClassifier(num_round=5, early_stopping_rounds=2)
        rng = np.random.default_rng(5)
        n = 64
        F = jax.numpy.asarray(rng.normal(size=(n, 1)).astype(np.float32))
        yj = jax.numpy.asarray((rng.random(n) > 0.5).astype(np.float32))
        val_idx = np.arange(0, n, 2)
        m = est._eval_metric_dev(F, yj, val_idx)
        assert isinstance(m, jax.Array)
        from transmogrifai_tpu.evaluators.metrics import aupr
        expect = aupr(np.asarray(yj)[val_idx],
                      1 / (1 + np.exp(-np.asarray(F)[val_idx, 0])))
        assert abs(float(m) - float(expect)) < 1e-4
