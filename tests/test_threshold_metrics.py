"""Multiclass ThresholdMetrics — golden values vs hand-computed counts
(reference: OpMultiClassificationEvaluator.calculateThresholdMetrics,
core/.../evaluators/OpMultiClassificationEvaluator.scala:153-240)."""
import numpy as np
import pytest

from transmogrifai_tpu.evaluators.metrics import (
    multiclass_threshold_metrics,
)


class TestGoldenCounts:
    # 4 rows, 3 classes; thresholds 0.0/0.5/0.9; hand-derived below
    P = np.array([
        [0.6, 0.3, 0.1],   # y=0: true=0.6 rank0 ; top=0.6
        [0.2, 0.5, 0.3],   # y=0: true=0.2 rank2 ; top=0.5
        [0.1, 0.45, 0.45], # y=2: true=0.45 rank1 (tie, idx1 first); top=0.45
        [0.05, 0.05, 0.9], # y=2: true=0.9 rank0 ; top=0.9
    ])
    Y = np.array([0, 0, 2, 2])
    THR = [0.0, 0.5, 0.9]

    def _run(self, top_ns=(1, 3)):
        return multiclass_threshold_metrics(self.Y, self.P, top_ns=top_ns,
                                            thresholds=self.THR)

    def test_top1_counts(self):
        m = self._run()
        # top1 membership: rows 0 (rank0), 3 (rank0); row2 loses tie to idx1
        # correct@thr: row0 true=.6 (≥0,≥.5), row3 true=.9 (all)
        assert m["correctCounts"][1] == [2, 2, 1]
        # incorrect: rows1,2 top≥thr (both .5/.45): thr0→2, thr.5→1(row1),
        # thr.9→0 ; rows0,3 in-top1 contribute where top≥thr>true: none
        assert m["incorrectCounts"][1] == [2, 1, 0]
        assert m["noPredictionCounts"][1] == [0, 1, 3]

    def test_top3_counts(self):
        m = self._run()
        # top3 contains every class: correct = true≥thr
        assert m["correctCounts"][3] == [4, 2, 1]
        # incorrect = top≥thr but true<thr
        assert m["incorrectCounts"][3] == [0, 1, 0]
        assert m["noPredictionCounts"][3] == [0, 1, 3]

    def test_counts_partition_rows(self):
        m = self._run(top_ns=(1, 2, 3))
        n = len(self.Y)
        for t in (1, 2, 3):
            for j in range(len(self.THR)):
                total = (m["correctCounts"][t][j]
                         + m["incorrectCounts"][t][j]
                         + m["noPredictionCounts"][t][j])
                assert total == n, (t, j)

    def test_tie_goes_to_earlier_index(self):
        # row2: true class 2 ties class 1 at 0.45 — the reference's stable
        # descending sort places index 1 first, so top1 misses class 2
        m = self._run(top_ns=(1,))
        # with top2 the tied true class IS included
        m2 = self._run(top_ns=(2,))
        assert m["correctCounts"][1][0] == 2
        assert m2["correctCounts"][2][0] == 3

    def test_unseen_label_counts_incorrect(self):
        # label index beyond the probability width: score treated as 0
        m = multiclass_threshold_metrics(
            np.array([5]), np.array([[0.7, 0.3]]), top_ns=(1,),
            thresholds=[0.0, 0.5])
        assert m["correctCounts"][1] == [0, 0]
        assert m["incorrectCounts"][1] == [1, 1]

    def test_validation(self):
        with pytest.raises(ValueError, match="thresholds"):
            multiclass_threshold_metrics(self.Y, self.P, thresholds=[1.5])
        with pytest.raises(ValueError, match="top_ns"):
            multiclass_threshold_metrics(self.Y, self.P, top_ns=())

    def test_device_path_matches_host(self):
        import jax.numpy as jnp

        rng = np.random.default_rng(0)
        P = rng.dirichlet(np.ones(4), size=500)
        y = rng.integers(0, 4, size=500)
        host = multiclass_threshold_metrics(y, P, top_ns=(1, 2))
        dev = multiclass_threshold_metrics(jnp.asarray(y),
                                           jnp.asarray(P), top_ns=(1, 2))
        for key in ("correctCounts", "incorrectCounts",
                    "noPredictionCounts"):
            for t in (1, 2):
                assert host[key][t] == dev[key][t], (key, t)


class TestEvaluatorIntegration:
    def test_evaluator_emits_threshold_metrics(self):
        from transmogrifai_tpu.evaluators.evaluators import (
            OpMultiClassificationEvaluator,
        )
        from transmogrifai_tpu.models.prediction import (
            PredictionBatch, prediction_column,
        )
        from transmogrifai_tpu.types.columns import (
            ColumnarDataset, FeatureColumn,
        )
        from transmogrifai_tpu.types.feature_types import RealNN

        rng = np.random.default_rng(1)
        n, k = 200, 3
        proba = rng.dirichlet(np.ones(k), size=n)
        y = rng.integers(0, k, size=n).astype(float)
        ds = ColumnarDataset({
            "y": FeatureColumn(RealNN, y),
            "p": prediction_column(proba.argmax(axis=1).astype(float),
                                   probability=proba),
        })
        ev = OpMultiClassificationEvaluator(label_col="y",
                                            prediction_col="p")
        out = ev.evaluate(ds)
        tm = out["ThresholdMetrics"]
        assert tm["topNs"] == [1, 3]
        assert len(tm["thresholds"]) == 101
        # at threshold 0.0 every row has a prediction; top-3 of 3 classes
        # always contains the true class
        assert tm["correctCounts"][3][0] == n
        assert tm["noPredictionCounts"][1][0] == 0

    def test_n_classes_from_probability_width(self):
        # eval slice missing the top class must not shrink the class space
        from transmogrifai_tpu.evaluators.evaluators import (
            OpMultiClassificationEvaluator,
        )
        from transmogrifai_tpu.models.prediction import prediction_column
        from transmogrifai_tpu.types.columns import (
            ColumnarDataset, FeatureColumn,
        )
        from transmogrifai_tpu.types.feature_types import RealNN

        y = np.array([0.0, 1.0, 0.0, 1.0])  # class 2 absent from the slice
        proba = np.array([[0.8, 0.1, 0.1]] * 4)
        ds = ColumnarDataset({
            "y": FeatureColumn(RealNN, y),
            "p": prediction_column(np.zeros(4), probability=proba),
        })
        out = OpMultiClassificationEvaluator(
            label_col="y", prediction_col="p").evaluate(ds)
        assert len(out["confusionMatrix"]) == 3
