"""End-to-end Titanic workflow — the reference's own headline demo.

Parity target: OpTitanicSimple (helloworld/src/main/scala/com/salesforce/hw/
OpTitanicSimple.scala:75-117) — LR grid AuPR 0.675-0.777, RF grid 0.778-0.810
(reference README.md:63-78).  Uses the reference's test data read-only.
"""
import os

import numpy as np
import pandas as pd
import pytest

from transmogrifai_tpu import FeatureBuilder, OpWorkflow, transmogrify
from transmogrifai_tpu.evaluators import Evaluators
from transmogrifai_tpu.preparators import SanityChecker
from transmogrifai_tpu.selector import (
    BinaryClassificationModelSelector, grid,
)
from transmogrifai_tpu.models import OpLogisticRegression
from transmogrifai_tpu.types import feature_types as ft

TITANIC = "/root/reference/test-data/PassengerDataAll.csv"
COLS = ["PassengerId", "Survived", "Pclass", "Name", "Sex", "Age",
        "SibSp", "Parch", "Ticket", "Fare", "Cabin", "Embarked"]


def load_titanic() -> pd.DataFrame:
    if not os.path.exists(TITANIC):  # pragma: no cover
        pytest.skip("titanic data unavailable")
    return pd.read_csv(TITANIC, header=None, names=COLS)


@pytest.fixture(scope="module")
def titanic_df():
    return load_titanic()


def build_features():
    survived = FeatureBuilder.RealNN("Survived").as_response()
    pclass = FeatureBuilder.PickList("Pclass").as_predictor()
    name = FeatureBuilder.Text("Name").as_predictor()
    sex = FeatureBuilder.PickList("Sex").as_predictor()
    age = FeatureBuilder.Real("Age").as_predictor()
    sibsp = FeatureBuilder.Integral("SibSp").as_predictor()
    parch = FeatureBuilder.Integral("Parch").as_predictor()
    ticket = FeatureBuilder.PickList("Ticket").as_predictor()
    fare = FeatureBuilder.Real("Fare").as_predictor()
    cabin = FeatureBuilder.PickList("Cabin").as_predictor()
    embarked = FeatureBuilder.PickList("Embarked").as_predictor()
    predictors = [pclass, name, sex, age, sibsp, parch, ticket, fare,
                  cabin, embarked]
    return survived, predictors


class TestTitanicEndToEnd:
    def test_lr_workflow_aupr_in_reference_range(self, titanic_df):
        survived, predictors = build_features()
        features = transmogrify(predictors)
        checked = SanityChecker(max_correlation=0.99).set_input(
            survived, features).get_output()
        selector = BinaryClassificationModelSelector.with_cross_validation(
            num_folds=3,
            models_and_parameters=[
                (OpLogisticRegression(), grid(
                    reg_param=[0.001, 0.01, 0.1], elastic_net_param=[0.0])),
            ])
        prediction = selector.set_input(survived, checked).get_output()

        wf = (OpWorkflow()
              .set_result_features(prediction)
              .set_input_data(titanic_df))
        model = wf.train()

        scored, metrics = model.score_and_evaluate(
            Evaluators.BinaryClassification.auPR())
        # reference LR demo: 0.675-0.777 AuPR (on a 90/10 split); full-data
        # scoring should land at or above the bottom of that range
        assert metrics["AuPR"] >= 0.65, metrics
        assert metrics["AuROC"] >= 0.75, metrics

        summary = model.summary()
        sel_summary = next(
            v["model_selector_summary"] for v in summary.values()
            if "model_selector_summary" in v)
        assert sel_summary["bestModelType"] == "OpLogisticRegression"
        assert len(sel_summary["validationResults"]) == 3
        holdout = sel_summary["holdoutMetrics"]
        assert holdout["AuPR"] > 0.5
        assert model.summary_pretty()

    def test_sanity_checker_dropped_and_metadata(self, titanic_df):
        survived, predictors = build_features()
        features = transmogrify(predictors)
        checked = SanityChecker().set_input(survived, features).get_output()
        wf = OpWorkflow().set_result_features(checked).set_input_data(titanic_df)
        model = wf.train()
        scored = model.score(keep_intermediate_features=True,
                             keep_raw_features=True)
        col = scored[checked.name]
        assert col.vmeta is not None
        assert col.values.shape[1] == col.vmeta.size
        # every slot traceable to a raw feature
        parents = set(c.parent_feature for c in col.vmeta.columns)
        assert parents <= {f.name for f in predictors}
