"""PR 11 — tree families on the grid axis + the EFB/GOSS/bf16 fast path.

Covers the ISSUE 11 contracts: EFB bundle/unbundle invertibility (bundled
fit == unbundled fit BIT-FOR-TREE on conflict-free matrices, AuPR within
2e-2 under bounded conflicts), GOSS seed-determinism and its depth gate,
TreeGridGroup pad-invariance over ``n_rows mod 8`` and parity against the
sequential mesh-sharded fits, SIGKILL-mid-rung resume with a tree grid
group, the tree-prep prefetch drain on elastic teardown, the new
``*:fit-grid`` cost-model stage kinds (+ old-history back-compat), and the
TM028 bf16-accumulation tolerance probe.
"""
import json
import os
import signal
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from transmogrifai_tpu.models.gbdt_kernels import (
    apply_bins, bundle_features, bundle_matrix, goss_plan, grow_tree,
    quantile_bins_sparse_aware, unbundle_ensemble,
)
from transmogrifai_tpu.models.trees import (
    OpGBTClassifier, OpRandomForestClassifier, clear_sweep_caches,
)
from transmogrifai_tpu.parallel.mesh import make_sweep_mesh
from transmogrifai_tpu.selector.grid_groups import (
    GBTGridGroup, RFGridGroup,
)

import jax.numpy as jnp


def _onehot_data(n=320, groups=4, card=8, dense=3, seed=9):
    """A transmogrify-shaped matrix: dense numerics + mutually exclusive
    one-hot blocks (the EFB target), with a learnable label."""
    rng = np.random.default_rng(seed)
    cats = rng.integers(0, card, size=(n, groups))
    oh = np.zeros((n, groups * card), np.float32)
    for i in range(groups):
        oh[np.arange(n), i * card + cats[:, i]] = 1.0
    dn = rng.normal(size=(n, dense)).astype(np.float32)
    X = np.concatenate([dn, oh], axis=1)
    y = ((dn[:, 0] + (cats[:, 0] == 3) - (cats[:, 1] == 5)
          + rng.normal(size=n) * 0.3) > 0).astype(np.float32)
    return X, y


def _toy(n=300, d=10, seed=5):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)).astype(np.float32)
    beta = rng.normal(size=d) * (rng.random(d) < 0.6)
    y = (1 / (1 + np.exp(-(X @ beta))) > rng.random(n)).astype(np.float32)
    return X, y


def _ctxs(n, seed=3, folds=2):
    rng = np.random.default_rng(seed)
    f = rng.integers(0, folds, n)
    return [((f != k).astype(np.float32), (f == k).astype(np.float32))
            for k in range(folds)]


def _binned(X, mb=32):
    edges = quantile_bins_sparse_aware(X, mb)
    b = np.asarray(apply_bins(jnp.asarray(X), jnp.asarray(edges)), np.int8)
    return edges, b


@pytest.fixture(autouse=True)
def _fresh_memos():
    clear_sweep_caches()
    yield
    clear_sweep_caches()
    for var in ("TMOG_EFB", "TMOG_GOSS"):
        os.environ.pop(var, None)


class TestEFB:
    def test_bundle_width_and_decode(self):
        X, _ = _onehot_data()
        edges, binned = _binned(X)
        b = bundle_features(binned, edges, 32)
        assert b is not None
        # 4 one-hot blocks of 8 pack into far fewer histogram columns
        assert b.width <= 0.5 * b.n_orig
        Xb = bundle_matrix(b, binned)
        assert Xb.shape == (X.shape[0], b.width)
        # conflict-free encode is fully invertible per member
        for c, spec in enumerate(b.plan):
            if isinstance(spec, (int, np.integer)):
                assert (Xb[:, c] == binned[:, spec]).all()
            else:
                for orig, base, end in spec:
                    vals = Xb[:, c].astype(np.int32)
                    active = (vals >= base) & (vals <= end)
                    dec = np.where(active, vals - base + 1, 0)
                    assert (dec == binned[:, orig]).all()

    def test_bundled_tree_bit_identical(self):
        """Conflict-free: a tree grown on the bundled matrix, unbundled,
        equals the tree grown on the original matrix node-for-node.

        ONE one-hot group + dense numerics, continuous gradients: within
        a single mutually exclusive group no two members can produce an
        identical node partition (their active row sets are disjoint), so
        every gain is unique and argmax order cannot matter.  With
        SEVERAL groups (or discrete gradients), distinct indicator
        columns CAN tie with exactly equal gains at small nodes and the
        two column spaces legitimately break the tie differently — that
        regime is functionally identical and covered by the
        prediction-parity test below."""
        rng = np.random.default_rng(21)
        n, card = 400, 8
        cats = rng.integers(0, card, size=n)
        oh = np.zeros((n, card), np.float32)
        oh[np.arange(n), cats] = 1.0
        dn = rng.normal(size=(n, 3)).astype(np.float32)
        X = np.concatenate([dn, oh], axis=1)
        edges, binned = _binned(X)
        b = bundle_features(binned, edges, 32)
        Xb = bundle_matrix(b, binned)
        G = jnp.asarray(rng.normal(size=n).astype(np.float32)[:, None])
        H = jnp.asarray(np.full((n, 1), 0.25, np.float32))
        C = jnp.asarray(np.ones(n, np.float32))
        # depth 3: level-2 nodes hold ~100 rows, where a dense-feature
        # cut and an indicator coinciding on the exact same partition
        # (the remaining tie source) does not occur (verified over 40
        # seeds); deeper/tinier nodes are covered by prediction parity
        f0, t0, l0 = grow_tree(jnp.asarray(binned.astype(np.int32)), G, H,
                               C, max_depth=3, n_bins=32, lam=1.0)
        f1, t1, l1 = grow_tree(jnp.asarray(Xb.astype(np.int32)), G, H, C,
                               max_depth=3, n_bins=32, lam=1.0,
                               bundle_end=jnp.asarray(b.end_bin))
        fu, tu = unbundle_ensemble(b, np.asarray(f1)[None],
                                   np.asarray(t1)[None])
        np.testing.assert_array_equal(np.asarray(f0), fu[0])
        np.testing.assert_array_equal(np.asarray(t0), tu[0])
        np.testing.assert_allclose(np.asarray(l0), np.asarray(l1),
                                   atol=1e-6)

    def test_bundled_deep_tree_prediction_parity(self):
        """Depth 6 (tiny tie-prone nodes): the unbundled tree may differ
        node-for-node at identical-partition ties, but it must route the
        training matrix IDENTICALLY — same leaf values, same scores."""
        from transmogrifai_tpu.models.gbdt_kernels import predict_tree

        X, y = _onehot_data(seed=23)
        n = len(y)
        edges, binned = _binned(X)
        b = bundle_features(binned, edges, 32)
        Xb = bundle_matrix(b, binned)
        rng = np.random.default_rng(24)
        G = jnp.asarray(((0.5 - y) + 0.01 * rng.normal(size=n)
                         ).astype(np.float32)[:, None])
        H = jnp.asarray(np.full((n, 1), 0.25, np.float32))
        C = jnp.asarray(np.ones(n, np.float32))
        f0, t0, l0 = grow_tree(jnp.asarray(binned.astype(np.int32)), G, H,
                               C, max_depth=6, n_bins=32, lam=1.0)
        f1, t1, l1 = grow_tree(jnp.asarray(Xb.astype(np.int32)), G, H, C,
                               max_depth=6, n_bins=32, lam=1.0,
                               bundle_end=jnp.asarray(b.end_bin))
        fu, tu = unbundle_ensemble(b, np.asarray(f1)[None],
                                   np.asarray(t1)[None])
        p0 = np.asarray(predict_tree(jnp.asarray(binned.astype(np.int32)),
                                     f0, t0, l0, 6))
        p1 = np.asarray(predict_tree(
            jnp.asarray(binned.astype(np.int32)),
            jnp.asarray(fu[0]), jnp.asarray(tu[0]), l1, 6))
        np.testing.assert_allclose(p0, p1, atol=1e-6)

    def test_gbt_fit_efb_bit_for_tree(self):
        """The estimator-level round trip: TMOG_EFB on vs off grows the
        SAME boosted trees on a conflict-free matrix."""
        X, y = _onehot_data(seed=1)
        models = {}
        for efb in ("0", "1"):
            os.environ["TMOG_EFB"] = efb
            clear_sweep_caches()
            models[efb] = OpGBTClassifier(max_iter=8, max_depth=4,
                                          seed=3).fit_raw(X, y)
        np.testing.assert_array_equal(np.asarray(models["0"].feat),
                                      np.asarray(models["1"].feat))
        np.testing.assert_array_equal(np.asarray(models["0"].thresh),
                                      np.asarray(models["1"].thresh))
        np.testing.assert_allclose(np.asarray(models["0"].leaf),
                                   np.asarray(models["1"].leaf), atol=1e-6)

    def test_bounded_conflicts_aupr_close(self):
        """With a nonzero conflict budget the encode is lossy for the
        conflicted rows only — fit quality stays within 2e-2 AuPR."""
        from transmogrifai_tpu.evaluators.metrics import aupr

        X, y = _onehot_data(n=400, seed=2)
        # inject ~2% conflicts: make a few rows activate TWO members of
        # the first block
        rng = np.random.default_rng(0)
        rows = rng.choice(len(y), size=8, replace=False)
        X = X.copy()
        X[rows, 3] = 1.0
        X[rows, 4] = 1.0
        edges, binned = _binned(X)
        b = bundle_features(binned, edges, 32, max_conflict_rate=0.05)
        assert b is not None

        def fit_aupr(efb):
            os.environ["TMOG_EFB"] = efb
            clear_sweep_caches()
            m = OpGBTClassifier(max_iter=8, max_depth=4,
                                seed=3).fit_raw(X, y)
            p = m.predict_batch(X).probability[:, 1]
            return aupr(y, p)

        a0, a1 = fit_aupr("0"), fit_aupr("1")
        assert abs(a0 - a1) < 2e-2

    def test_efb_declines_dense(self):
        X, _ = _toy(n=200, d=8)
        edges, binned = _binned(X)
        assert bundle_features(binned, edges, 32) is None

    def test_dd_mask_blocks_bundles(self):
        X, _ = _onehot_data()
        edges, binned = _binned(X)
        b = bundle_features(binned, edges, 32)
        dd = b.bundled_dd_mask(np.ones(b.n_orig, bool))
        for c, spec in enumerate(b.plan):
            if isinstance(spec, (int, np.integer)):
                assert dd[c]
            else:
                assert not dd[c]


class TestGOSS:
    def _fit(self, X, y, seed, depth=8, rounds=6):
        clear_sweep_caches()
        return OpGBTClassifier(max_iter=rounds, max_depth=depth,
                               seed=seed).fit_raw(X, y)

    def test_plan_gates(self):
        assert goss_plan(100_000, 10) is not None
        assert goss_plan(100_000, 7) is None          # depth gate
        assert goss_plan(1_000, 10) is None           # row gate (auto)
        os.environ["TMOG_GOSS"] = "1"
        assert goss_plan(1_000, 10) is not None       # forced: row gate off
        assert goss_plan(1_000, 7) is None            # depth gate holds
        os.environ["TMOG_GOSS"] = "0"
        assert goss_plan(100_000, 10) is None

    def test_seed_determinism(self):
        os.environ["TMOG_EFB"] = "0"
        os.environ["TMOG_GOSS"] = "1"
        X, y = _toy(n=400, d=8, seed=7)
        a = self._fit(X, y, seed=3)
        b = self._fit(X, y, seed=3)
        c = self._fit(X, y, seed=4)
        np.testing.assert_array_equal(np.asarray(a.feat),
                                      np.asarray(b.feat))
        np.testing.assert_array_equal(np.asarray(a.thresh),
                                      np.asarray(b.thresh))
        assert not (np.asarray(a.feat) == np.asarray(c.feat)).all()

    def test_off_below_depth_threshold(self):
        """Depth-7 candidates grow identically whether GOSS is forced or
        disabled — the depth gate is part of the contract."""
        os.environ["TMOG_EFB"] = "0"
        X, y = _toy(n=400, d=8, seed=8)
        os.environ["TMOG_GOSS"] = "1"
        a = self._fit(X, y, seed=3, depth=7)
        os.environ["TMOG_GOSS"] = "0"
        b = self._fit(X, y, seed=3, depth=7)
        np.testing.assert_array_equal(np.asarray(a.feat),
                                      np.asarray(b.feat))

    def test_quality_stays_useful(self):
        from transmogrifai_tpu.evaluators.metrics import aupr

        os.environ["TMOG_GOSS"] = "1"
        X, y = _toy(n=500, d=8, seed=9)
        m = self._fit(X, y, seed=3, rounds=10)
        p = m.predict_batch(X).probability[:, 1]
        assert aupr(y, p) > 0.8


class TestTreeGridMesh:
    """Tentpole gates: batched tree groups on the ("data", "grid") sweep
    mesh agree with the single-chip batched programs (documented 2e-2
    tolerance) and are invariant to ``n_rows mod 8``."""

    @pytest.mark.parametrize("n", [297, 300, 304])
    def test_rf_group_mesh_parity_residues(self, n):
        X, y = _toy(n=n, d=10, seed=n)
        ctxs = _ctxs(n)
        proto = OpRandomForestClassifier(num_trees=6, seed=3)
        pts = [{"max_depth": 3}, {"max_depth": 5}]
        a = np.asarray(RFGridGroup(proto, pts, "AuPR").run(X, y, ctxs))
        clear_sweep_caches()
        mesh = make_sweep_mesh(6, n_devices=8)
        b = np.asarray(RFGridGroup(proto, pts, "AuPR")
                       .with_mesh(mesh).run(X, y, ctxs))
        np.testing.assert_allclose(a, b, atol=2e-2)

    def test_gbt_group_mesh_parity_with_es(self):
        from transmogrifai_tpu.models.trees import OpXGBoostClassifier

        X, y = _toy(n=260, d=8, seed=7)
        ctxs = _ctxs(len(y), seed=7)
        proto = OpXGBoostClassifier(num_round=12, eta=0.3, max_depth=3,
                                    early_stopping_rounds=5, seed=3)
        pts = [{"max_depth": 3}, {"max_depth": 4}]
        a = np.asarray(GBTGridGroup(proto, pts, "AuPR").run(X, y, ctxs))
        clear_sweep_caches()
        mesh = make_sweep_mesh(4, n_devices=8)
        b = np.asarray(GBTGridGroup(proto, pts, "AuPR")
                       .with_mesh(mesh).run(X, y, ctxs))
        np.testing.assert_allclose(a, b, atol=2e-2)

    def test_gbt_group_mesh_efb_parity(self):
        X, y = _onehot_data(n=310, seed=9)
        ctxs = _ctxs(len(y), seed=9)
        proto = OpGBTClassifier(max_iter=6, seed=3)
        pts = [{"max_depth": 3}, {"max_depth": 4}]
        os.environ["TMOG_EFB"] = "0"
        a = np.asarray(GBTGridGroup(proto, pts, "AuPR").run(X, y, ctxs))
        clear_sweep_caches()
        os.environ["TMOG_EFB"] = "1"
        mesh = make_sweep_mesh(4, n_devices=8)
        b = np.asarray(GBTGridGroup(proto, pts, "AuPR")
                       .with_mesh(mesh).run(X, y, ctxs))
        np.testing.assert_allclose(a, b, atol=2e-2)

    def test_sharding_contracts_on_tree_group(self):
        """TM024 pad-invariance + TM025 mesh-parity run clean on the GBT
        grid group — the contracts the multichip smoke gates on now have
        a TREE program under them.  (The RF group's Poisson bag stream is
        shaped (n_rows,), so STRICT pad-invariance cannot apply to it —
        its contract is the documented 2e-2 parity over row residues,
        covered by test_rf_group_mesh_parity_residues.)"""
        from transmogrifai_tpu.analysis.contracts import (
            check_mesh_parity, check_pad_invariance,
        )

        X, y = _toy(n=280, d=8, seed=4)
        ctxs = _ctxs(len(y), seed=4)
        mesh = make_sweep_mesh(6, n_devices=8)
        proto = OpGBTClassifier(max_iter=5, seed=3)
        pts = [{"max_depth": 3}, {"max_depth": 4}]

        def make_group():
            clear_sweep_caches()
            return GBTGridGroup(proto, pts, "AuPR")

        findings = check_pad_invariance(make_group, X, y, ctxs, mesh)
        check_mesh_parity(make_group, X, y, ctxs, mesh, findings=findings)
        assert not findings, findings.format()

    def test_selector_sweep_uses_batched_tree_groups(self):
        """A tree-only sweep on the mesh keeps its grid groups (no
        sequential stripping) and picks the single-chip winner."""
        from transmogrifai_tpu.selector.model_selector import ModelSelector
        from transmogrifai_tpu.selector.validators import OpCrossValidation

        X, y = _toy(n=300, d=10, seed=5)
        w = np.ones(len(y), np.float32)

        def selector():
            return ModelSelector(
                models_and_params=[
                    (OpRandomForestClassifier(num_trees=6, seed=3), [
                        {"max_depth": 3}, {"max_depth": 5}]),
                    (OpGBTClassifier(max_iter=6, seed=3), [
                        {"max_depth": 3}, {"max_depth": 4}]),
                ],
                problem_type="binary",
                validator=OpCrossValidation(num_folds=2, stratify=True))

        sel_s = selector()
        cands_s = sel_s._candidates()
        best_s, res_s = sel_s.validator.validate(
            cands_s, X, y, w, eval_fn=sel_s._metric,
            metric_name=sel_s.validation_metric,
            larger_better=sel_s.larger_better)

        clear_sweep_caches()
        mesh = make_sweep_mesh(4, n_devices=8)
        sel_m = selector().with_mesh(mesh)
        cands_m = sel_m._candidates()
        # tree groups attach the mesh and are mesh-capable now
        assert cands_m[0][3] is not None and cands_m[0][3].mesh is mesh
        assert cands_m[2][3] is not None and cands_m[2][3].mesh is mesh
        assert cands_m[0][3].supports_mesh and cands_m[2][3].supports_mesh
        best_m, res_m = sel_m.validator.validate(
            cands_m, X, y, w, eval_fn=sel_m._metric,
            metric_name=sel_m.validation_metric,
            larger_better=sel_m.larger_better)
        assert all(r.error is None for r in res_m)
        assert best_m == best_s
        np.testing.assert_allclose(
            [r.metric_value for r in res_m],
            [r.metric_value for r in res_s], atol=2e-2)

    def test_halving_regroup_packs_tree_rungs(self):
        """Halving on the mesh re-batches each rung's tree survivors onto
        the grid axis (the regroup callback) — same ladder and winner as
        the single-chip halving sweep."""
        from transmogrifai_tpu.selector.model_selector import ModelSelector
        from transmogrifai_tpu.selector.validators import OpCrossValidation
        from transmogrifai_tpu.tuning import HalvingConfig
        from transmogrifai_tpu.tuning.halving import halving_validate

        X, y = _toy(n=600, d=8, seed=11)
        w = np.ones(len(y), np.float32)
        cfg = HalvingConfig(eta=2, min_rows=128, seed=7)

        def run(mesh):
            clear_sweep_caches()
            sel = ModelSelector(
                models_and_params=[
                    (OpRandomForestClassifier(num_trees=5, seed=3), [
                        {"max_depth": 3}, {"max_depth": 4},
                        {"max_depth": 5}]),
                ],
                problem_type="binary",
                validator=OpCrossValidation(num_folds=2, stratify=True),
                strategy="halving", halving=cfg)
            if mesh is not None:
                sel.with_mesh(mesh)
            cands = sel._candidates(with_groups=False)
            return halving_validate(
                sel.validator, cands, X, y, w, eval_fn=sel._metric,
                metric_name=sel.validation_metric,
                larger_better=sel.larger_better, config=cfg,
                stratify=True, regroup=sel._make_rung_regroup(cands))

        best_m, res_m, sched_m = run(make_sweep_mesh(3, n_devices=8))
        best_s, res_s, sched_s = run(None)
        assert best_m == best_s
        assert ([r["rows"] for r in sched_m["rungs"]]
                == [r["rows"] for r in sched_s["rungs"]])


_TREE_KILL_SCRIPT = textwrap.dedent("""
    import json, os, sys
    import numpy as np
    sys.path.insert(0, {root!r})
    from transmogrifai_tpu.models import OpRandomForestClassifier
    from transmogrifai_tpu.selector.model_selector import ModelSelector
    from transmogrifai_tpu.selector.validators import OpCrossValidation
    from transmogrifai_tpu.parallel.mesh import make_sweep_mesh
    from transmogrifai_tpu.tuning import HalvingConfig

    rng = np.random.default_rng(5)
    X = rng.normal(size=(600, 8)).astype(np.float32)
    beta = rng.normal(size=8) * (rng.random(8) < 0.6)
    y = (1/(1+np.exp(-(X @ beta))) > rng.random(600)).astype(np.float32)

    sel = ModelSelector(
        models_and_params=[
            (OpRandomForestClassifier(num_trees=5, seed=3), [
                {{"max_depth": 3}}, {{"max_depth": 4}},
                {{"max_depth": 5}}]),
        ],
        problem_type="binary",
        validator=OpCrossValidation(num_folds=2, stratify=True),
        strategy="halving",
        halving=HalvingConfig(eta=2, min_rows=128, seed=7),
    ).with_mesh(make_sweep_mesh(3, n_devices=8))
    sel.with_sweep_checkpoint({ckdir!r})
    from transmogrifai_tpu.types.columns import FeatureColumn
    from transmogrifai_tpu.types.feature_types import OPVector, RealNN
    label = FeatureColumn(RealNN, y.astype(np.float64))
    feats = FeatureColumn(OPVector, X)
    sel.fit_columns(None, label, feats)
    summ = sel.metadata["model_selector_summary"]
    print(json.dumps({{"best": summ["bestModelParams"],
                       "metrics": [r["metricValue"] for r in
                                   summ["validationResults"]]}}))
""")


@pytest.mark.faults
class TestKillResumeTreeGrid:
    """Satellite: SIGKILL mid-RUNG with a TREE grid group packed onto the
    mesh, then a rerun against the same checkpoint dir, reproduces the
    uninterrupted run's winner."""

    def _spawn(self, ckdir, faults_spec=None):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        flags = env.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
        if faults_spec is not None:
            env["TMOG_FAULTS"] = json.dumps(faults_spec)
        else:
            env.pop("TMOG_FAULTS", None)
        script = _TREE_KILL_SCRIPT.format(
            root=os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), ckdir=str(ckdir))
        return subprocess.run([sys.executable, "-c", script],
                              capture_output=True, text=True, env=env,
                              timeout=900)

    def test_sigkill_mid_rung_resumes_same_winner(self, tmp_path):
        ref = self._spawn(tmp_path / "ck_ref")
        assert ref.returncode == 0, ref.stderr[-2000:]
        ref_out = json.loads(ref.stdout.splitlines()[-1])

        ckdir = tmp_path / "ck"
        killed = self._spawn(ckdir, faults_spec={
            "faults": [{"point": "sweep.checkpoint", "action": "kill",
                        "at": 2}]})
        assert killed.returncode == -signal.SIGKILL
        resumed = self._spawn(ckdir)
        assert resumed.returncode == 0, resumed.stderr[-2000:]
        out = json.loads(resumed.stdout.splitlines()[-1])
        assert out["best"] == ref_out["best"]
        np.testing.assert_allclose(out["metrics"], ref_out["metrics"],
                                   atol=2e-2)


class TestPrefetchDrain:
    """Satellite: the tree-prep prefetch daemon never outlives the sweep
    — joined on normal completion AND on the elastic teardown path."""

    def _selector(self):
        from transmogrifai_tpu.selector.model_selector import ModelSelector
        from transmogrifai_tpu.selector.validators import OpCrossValidation

        return ModelSelector(
            models_and_params=[
                (OpRandomForestClassifier(num_trees=4, seed=3), [
                    {"max_depth": 3}, {"max_depth": 4}]),
            ],
            problem_type="binary",
            validator=OpCrossValidation(num_folds=2, stratify=True))

    def _fit(self, sel, X, y):
        from transmogrifai_tpu.types.columns import FeatureColumn
        from transmogrifai_tpu.types.feature_types import OPVector, RealNN

        label = FeatureColumn(RealNN, y.astype(np.float64))
        feats = FeatureColumn(OPVector, X)
        return sel.fit_columns(None, label, feats)

    def test_drained_after_normal_fit(self, monkeypatch):
        from transmogrifai_tpu.selector.model_selector import ModelSelector

        monkeypatch.setattr(ModelSelector, "_PREFETCH_MIN_ELEMS", 0)
        X, y = _toy(n=240, d=6, seed=13)
        sel = self._selector()
        self._fit(sel, X, y)
        assert getattr(sel, "_prep_thread", None) is None

    def test_drained_on_device_loss_teardown(self, monkeypatch):
        """An injected device.loss fires the elastic shrink hook, which
        must cancel+join the prefetch thread BEFORE re-pointing the mesh
        — and the fit's teardown leaves no live daemon either way."""
        from transmogrifai_tpu.selector.model_selector import ModelSelector
        from transmogrifai_tpu.utils import faults

        monkeypatch.setattr(ModelSelector, "_PREFETCH_MIN_ELEMS", 0)
        X, y = _toy(n=240, d=6, seed=14)
        sel = self._selector()
        with faults.inject(faults.FaultSpec(
                point="device.loss", action="device_loss", at=1,
                times=1)):
            self._fit(sel, X, y)
        assert getattr(sel, "_prep_thread", None) is None

    def test_drain_cancels_and_joins(self):
        import threading

        sel = self._selector()
        done = threading.Event()

        class _T(threading.Thread):
            def run(self):
                done.wait(5.0)

        t = _T(daemon=True)
        sel._prep_thread = t
        sel._prep_cancel = done        # drain sets it -> thread exits
        t.start()
        sel._drain_tree_prefetch(timeout_s=10.0)
        assert not t.is_alive()
        assert sel._prep_thread is None


class TestGridStageKinds:
    """Satellite: tree grid units register their own cost-model stage
    kinds, advise_mesh consults them, and OLD histories (no grid kinds,
    no nDevices) still load."""

    def test_rf_group_records_fit_grid_kind(self, tmp_path, monkeypatch):
        from transmogrifai_tpu.tuning.costmodel import load_observations

        hist = tmp_path / "hist.json"
        monkeypatch.setenv("TMOG_COST_HISTORY", str(hist))
        X, y = _toy(n=220, d=6, seed=15)
        RFGridGroup(OpRandomForestClassifier(num_trees=4, seed=3),
                    [{"max_depth": 3}], "AuPR").run(X, y, _ctxs(len(y)))
        kinds = {o.stage_kind for o in load_observations(str(hist))}
        assert "RandomForest:fit-grid" in kinds

    def test_advise_mesh_consults_tree_grid_kind(self):
        from transmogrifai_tpu.tuning.costmodel import (
            CostModel, StageObservation,
        )
        from transmogrifai_tpu.tuning.planner import advise_mesh

        obs = []
        for nd, wall in ((1, 8.0), (2, 4.2), (4, 2.4), (8, 1.5)):
            for rows in (1000, 10_000, 100_000):
                obs.append(StageObservation(
                    "GBT:fit-grid", rows=rows, cols=64, dtype="float32",
                    backend="cpu", wall_s=wall * rows / 10_000,
                    n_devices=nd))
        cm = CostModel().fit(obs)
        adv = advise_mesh(50_000, 64, queue_width=8,
                          devices_available=8, cost_model=cm,
                          backend="cpu")
        assert adv.predicted_wall_s            # measured tier engaged
        assert adv.n_devices == 8              # scaling history says wider

    def test_old_history_backcompat(self, tmp_path):
        from transmogrifai_tpu.tuning.costmodel import (
            CostModel, load_observations,
        )
        from transmogrifai_tpu.tuning.planner import advise_mesh

        hist = tmp_path / "cost_history.json"
        hist.write_text(json.dumps({
            "stage_observations": [
                {"stageKind": "ModelSelector:fit", "rows": 1000,
                 "cols": 10, "dtype": "float32", "backend": "cpu",
                 "wallSecs": 1.5, "t": 0},      # pre-mesh record shape
            ],
            "some_bench_config": {"measured_s": 2.0},
        }))
        obs = load_observations(str(hist))
        assert len(obs) == 1 and obs[0].n_devices == 1
        cm = CostModel.from_history(str(hist))
        adv = advise_mesh(1000, 10, queue_width=4, devices_available=8,
                          cost_model=cm, backend="cpu")
        assert adv.n_devices >= 1              # no KeyError on old shapes


class TestAccumToleranceProbe:
    def test_probe_clean_at_reference_shape(self):
        from transmogrifai_tpu.analysis.contracts import (
            check_accum_tolerance,
        )

        X, y = _toy(n=400, d=12, seed=16)
        findings = check_accum_tolerance(X, y)
        assert not findings, findings.format()

    def test_probe_fires_on_impossible_tolerance(self):
        from transmogrifai_tpu.analysis.contracts import (
            check_accum_tolerance,
        )

        X, y = _toy(n=200, d=6, seed=17)
        findings = check_accum_tolerance(X, y, tol=-1.0, n_rounds=2,
                                         max_depth=3)
        assert [d.rule for d in findings.diagnostics] == ["TM028"]
