"""tuning/ subsystem: cost model, successive halving, budgeter, planner.

ISSUE 6 satellite coverage: seeded grid where ``strategy="halving"``
returns the same winner as the full sweep within documented AuPR
tolerance, deterministic rung schedules across runs, the work-queue
refactor byte-identical to the old sweep under ``strategy="full"``,
atomic history writes, and the plan/bench decision plumbing.
"""
import json
import os

import numpy as np
import pytest

from transmogrifai_tpu.models import (
    OpLogisticRegression, OpRandomForestClassifier,
)
from transmogrifai_tpu.selector.model_selector import ModelSelector, grid
from transmogrifai_tpu.selector.splitters import DataSplitter
from transmogrifai_tpu.selector.validators import (
    OpCrossValidation, SweepUnit, SweepWorkQueue,
)
from transmogrifai_tpu.tuning import (
    BenchBudgeter, CostModel, HalvingConfig, StageObservation, Tuner,
    advise_plan, append_observations, halving_validate, load_observations,
    nested_subsample_order, rung_schedule,
)

#: the documented halving-vs-full winner quality tolerance (docs/tuning.md)
AUPR_TOL = 0.02


def _binary_data(n=3000, d=8, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, d)).astype(np.float32)
    logits = X[:, 0] * 2.0 - X[:, 1] + 0.3 * X[:, 2]
    y = (logits + rng.normal(scale=0.7, size=n) > 0).astype(np.float32)
    return X, y


def _selector(strategy="full", halving=None, models=None):
    models = models or [
        (OpLogisticRegression(), grid(reg_param=[0.001, 0.01, 0.1, 0.3])),
        (OpRandomForestClassifier(num_trees=10),
         grid(max_depth=[3, 6], min_instances_per_node=[10, 100])),
    ]
    return ModelSelector(
        models_and_params=models, problem_type="binary",
        validator=OpCrossValidation(num_folds=3, stratify=True),
        splitter=DataSplitter(reserve_test_fraction=0.1),
        validation_metric="AuPR", strategy=strategy, halving=halving)


def _fit(sel, X, y):
    from transmogrifai_tpu.types.columns import FeatureColumn
    from transmogrifai_tpu.types.feature_types import OPVector, RealNN

    return sel.fit_columns(None, FeatureColumn(RealNN, y),
                           FeatureColumn(OPVector, X))


# ---------------------------------------------------------------------------
# Rung schedule + subsampling
# ---------------------------------------------------------------------------

class TestSchedule:
    def test_schedule_deterministic_across_runs(self):
        a = rung_schedule(100_000, 12, HalvingConfig())
        b = rung_schedule(100_000, 12, HalvingConfig())
        assert [r.to_json() for r in a] == [r.to_json() for r in b]

    def test_schedule_shape(self):
        sched = rung_schedule(100_000, 12, HalvingConfig(eta=3,
                                                         min_rows=2048))
        assert sched[-1].rows == 100_000          # final rung = full data
        rows = [r.rows for r in sched]
        assert rows == sorted(rows)               # monotone resource growth
        survivors = [r.survivors_in for r in sched]
        assert survivors == sorted(survivors, reverse=True)
        assert sched[0].survivors_in == 12

    def test_too_small_shapes_yield_no_ladder(self):
        assert rung_schedule(1000, 12, HalvingConfig(min_rows=2048)) == []
        assert rung_schedule(100_000, 2, HalvingConfig()) == []

    def test_nested_subsample_is_stratified_and_deterministic(self):
        y = np.r_[np.zeros(1800), np.ones(200)].astype(np.float32)
        a = nested_subsample_order(y, seed=7)
        b = nested_subsample_order(y, seed=7)
        np.testing.assert_array_equal(a, b)
        # every reasonable prefix approximates the 10% positive rate
        for k in (200, 500, 1000):
            frac = y[a[:k]].mean()
            assert 0.05 <= frac <= 0.15, (k, frac)
        # prefixes are nested by construction (one fixed order)
        assert set(a[:200]) <= set(a[:500])


# ---------------------------------------------------------------------------
# Halving end-to-end vs the full sweep
# ---------------------------------------------------------------------------

def _holdout_aupr(selector) -> float:
    summ = selector.metadata["model_selector_summary"]
    return float(summ["holdoutMetrics"]["AuPR"])


class TestHalvingSelection:
    def test_halving_matches_full_winner_within_tolerance(self):
        X, y = _binary_data()
        sel_f = _selector("full")
        _fit(sel_f, X, y)
        sel_h = _selector("halving", halving=HalvingConfig(min_rows=256))
        _fit(sel_h, X, y)
        # winner quality within the documented tolerance on the holdout
        fm = _holdout_aupr(sel_f)
        hm = _holdout_aupr(sel_h)
        assert abs(fm - hm) <= AUPR_TOL, (fm, hm)
        sched = sel_h.metadata["halving_schedule"]
        assert sched["rungs"], "expected a real rung ladder"
        assert sched["rungs"][-1]["rows"] >= sched["rungs"][0]["rows"]

    def test_halving_deterministic_across_runs(self):
        X, y = _binary_data(n=2000)
        cfg = HalvingConfig(min_rows=256)
        s1 = _selector("halving", halving=cfg)
        s2 = _selector("halving", halving=cfg)
        m1, m2 = _fit(s1, X, y), _fit(s2, X, y)
        assert m1.best_name == m2.best_name
        assert m1.best_params == m2.best_params
        j1 = s1.metadata["halving_schedule"]
        j2 = s2.metadata["halving_schedule"]
        for a, b in zip(j1["rungs"], j2["rungs"]):
            assert a["rows"] == b["rows"]
            assert a["promoted"] == b["promoted"]

    def test_eliminated_candidates_are_annotated(self):
        X, y = _binary_data(n=2000)
        sel = _selector("halving", halving=HalvingConfig(min_rows=256))
        _fit(sel, X, y)
        summ = sel.metadata["model_selector_summary"]
        errs = [r.get("error") for r in summ["validationResults"]]
        assert any(e and "halving: eliminated" in e for e in errs)
        # the winner's result is full-fidelity (no annotation)
        best = summ["bestModelType"]
        winners = [r for r in summ["validationResults"]
                   if r["modelType"] == best and not r.get("error")]
        assert winners

    def test_halving_validate_runs_fewer_candidate_fits(self):
        """Early rungs run everyone on slivers; only survivors pay full
        fits — total full-data-equivalent candidate fits must be well
        under the full sweep's."""
        X, y = _binary_data(n=4000)
        calls = []

        def fitter_factory(i):
            def fitter(Xf, yf, wf, p):
                calls.append((i, len(yf)))
                mean = Xf[wf > 0].mean(axis=0)

                def predict(Xe):
                    return Xe @ np.ones(Xe.shape[1]) * (1 + 0.01 * i)
                return predict
            return fitter

        cands = [(f"m{i}", {"p": i}, fitter_factory(i)) for i in range(9)]
        validator = OpCrossValidation(num_folds=2, stratify=True)

        def eval_fn(yy, ss, ww):
            from transmogrifai_tpu.evaluators.metrics import aupr
            return float(aupr(yy, np.asarray(ss), ww))

        best, results, sched = halving_validate(
            validator, cands, X, y, np.ones(len(y), np.float32),
            eval_fn, "AuPR", True, HalvingConfig(min_rows=256))
        assert len(results) == 9
        # row-weighted work: full sweep would be 9 * n * folds
        work = sum(rows for _, rows in calls)
        full_work = 9 * len(y) * 2
        assert work < 0.6 * full_work, (work, full_work)
        assert sched["rungs"]

    def test_rounds_scaling_floors(self):
        from transmogrifai_tpu.tuning.halving import _scaled_params

        cfg = HalvingConfig()
        p = _scaled_params({"max_iter": 50, "reg_param": 0.1}, 0.1, cfg)
        assert p["max_iter"] == 5 and p["reg_param"] == 0.1
        p = _scaled_params({"num_round": 200}, 0.01, cfg)
        assert p["num_round"] == 20          # min_round_frac floor
        # full fraction: untouched object semantics
        p0 = {"max_iter": 50}
        assert _scaled_params(p0, 1.0, cfg) is p0


# ---------------------------------------------------------------------------
# Work-queue refactor: byte-identical full sweep
# ---------------------------------------------------------------------------

class TestSweepQueueParity:
    def test_full_strategy_identical_to_default_path(self):
        X, y = _binary_data(n=1500)
        s_default = _selector()           # pre-refactor entry: no strategy
        s_full = _selector("full")
        m1, m2 = _fit(s_default, X, y), _fit(s_full, X, y)
        j1 = s_default.metadata["model_selector_summary"]
        j2 = s_full.metadata["model_selector_summary"]
        assert json.dumps(j1, sort_keys=True, default=str) == \
            json.dumps(j2, sort_keys=True, default=str)
        assert m1.best_name == m2.best_name
        assert m1.best_params == m2.best_params

    def test_queue_units_and_isolation(self):
        def ok_fitter(X, y, w, p):
            return lambda Xe: Xe[:, 0]

        def boom_fitter(X, y, w, p):
            raise FloatingPointError("boom")

        X = np.random.default_rng(0).normal(size=(50, 3)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float32)

        def run_fold(fitter, params, ctx):
            predict = fitter(X, y, None, params)
            return float(np.mean(predict(X) * y))

        q = SweepWorkQueue(
            [("a", {"i": 0}, ok_fitter), ("b", {"i": 1}, boom_fitter)],
            fold_ctxs=[None, None], run_fold=run_fold)
        assert [u.index for u in q.units] == [0, 1]
        vals, err = q.run_unit(q.units[0])
        assert err is None and len(vals) == 2
        vals, err = q.run_unit(q.units[1])
        assert vals == [] and "boom" in err
        best, results = q.run_all("m", True, None)
        assert best == 0
        assert results[1].error and "boom" in results[1].error

    def test_fit_params_override_reported_params(self):
        seen = []

        def fitter(X, y, w, p):
            seen.append(dict(p))
            return lambda Xe: Xe[:, 0]

        unit = SweepUnit(0, "a", {"max_iter": 50}, fitter,
                         fit_params={"max_iter": 5})
        assert unit.run_params == {"max_iter": 5}
        q = SweepWorkQueue([("a", {"max_iter": 50}, fitter, None,
                             {"max_iter": 5})],
                           fold_ctxs=[None],
                           run_fold=lambda f, p, c: (f(None, None, None, p),
                                                     1.0)[1])
        _, results = q.run_all("m", True, None)
        assert seen == [{"max_iter": 5}]
        assert results[0].params == {"max_iter": 50}   # identity preserved


# ---------------------------------------------------------------------------
# Cost model
# ---------------------------------------------------------------------------

def _obs(kind, rows, cols, wall, backend="cpu"):
    return StageObservation(stage_kind=kind, rows=rows, cols=cols,
                            dtype="float32", backend=backend, wall_s=wall)


class TestCostModel:
    def test_fit_and_predict_scaling_law(self):
        # wall ~ 1e-8 * rows * cols: the log-space ridge should recover it
        rng = np.random.default_rng(1)
        obs = []
        for _ in range(40):
            r = int(rng.integers(1000, 1_000_000))
            c = int(rng.integers(4, 512))
            obs.append(_obs("X:fit", r, c, 1e-8 * r * c))
        cm = CostModel().fit(obs)
        for r, c in ((50_000, 100), (500_000, 20), (2_000_000, 300)):
            pred = cm.predict("X:fit", r, c)
            true = 1e-8 * r * c
            assert true / 2 <= pred <= true * 2, (r, c, pred, true)

    def test_cold_model_uses_analytic_fallback(self):
        cm = CostModel()
        assert cm.source("never-seen:fit") == "analytic"
        p = cm.predict("never-seen:fit", 10_000, 50)
        assert p > 0
        assert cm.predict("never-seen:fit", 10_000_000, 500) > p

    def test_backend_bucket_preferred(self):
        obs = ([_obs("X:fit", 10_000, 10, 1.0, backend="cpu")] * 3
               + [_obs("X:fit", 10_000, 10, 10.0, backend="tpu")] * 3)
        cm = CostModel().fit(obs)
        p_cpu = cm.predict("X:fit", 10_000, 10, backend="cpu")
        p_tpu = cm.predict("X:fit", 10_000, 10, backend="tpu")
        assert p_tpu > p_cpu * 3

    def test_within_factor(self):
        obs = [_obs("X:fit", 10_000, 10, 1.0)] * 4
        cm = CostModel().fit(obs)
        frac, n = cm.within_factor(obs)
        assert n == 4 and frac == 1.0
        frac, n = cm.within_factor([_obs("X:fit", 10_000, 10, 100.0)])
        assert frac == 0.0

    def test_history_roundtrip_and_cap(self, tmp_path):
        path = str(tmp_path / "hist.json")
        append_observations(path, [_obs("A:fit", 10, 1, 0.5)] * 5)
        append_observations(path, [_obs("B:fit", 20, 2, 0.7)] * 5, cap=6)
        got = load_observations(path)
        assert len(got) == 6                       # FIFO cap
        assert all(o.stage_kind == "B:fit" for o in got[-5:])
        # atomic write: no tmp residue, file is valid json
        assert not [p for p in os.listdir(tmp_path) if p.endswith(".tmp")]
        with open(path) as f:
            json.load(f)

    def test_history_preserves_bench_config_entries(self, tmp_path):
        path = str(tmp_path / "cost_history.json")
        with open(path, "w") as f:
            json.dump({"titanic": {"measured_s": 12.0, "sig": ""}}, f)
        append_observations(path, [_obs("A:fit", 10, 1, 0.5)])
        with open(path) as f:
            hist = json.load(f)
        assert hist["titanic"]["measured_s"] == 12.0
        assert len(hist["stage_observations"]) == 1

    def test_train_appends_observations(self, tmp_path, monkeypatch):
        import pandas as pd

        from transmogrifai_tpu import (FeatureBuilder, OpWorkflow,
                                       transmogrify)

        path = str(tmp_path / "ch.json")
        monkeypatch.setenv("TMOG_COST_HISTORY", path)
        rng = np.random.default_rng(0)
        df = pd.DataFrame({"label": (rng.random(200) > 0.5).astype(float),
                           "a": rng.normal(size=200),
                           "b": rng.normal(size=200)})
        label = FeatureBuilder.RealNN("label").as_response()
        feats = transmogrify([FeatureBuilder.Real("a").as_predictor(),
                              FeatureBuilder.Real("b").as_predictor()])
        from transmogrifai_tpu.models import OpLogisticRegression as LR
        pred = LR().set_input(label, feats).get_output()
        OpWorkflow().set_result_features(pred).set_input_data(df).train()
        obs = load_observations(path)
        assert obs, "train() must append stage observations"
        assert all(o.rows == 200 for o in obs)
        assert any(":fit" in o.stage_kind for o in obs)
        assert all(o.backend == "cpu" for o in obs)

    def test_disabled_history_records_nothing(self, tmp_path, monkeypatch):
        from transmogrifai_tpu.tuning.costmodel import default_history_path

        monkeypatch.setenv("TMOG_COST_HISTORY", "")
        assert default_history_path() is None
        monkeypatch.setenv("TMOG_COST_HISTORY", "0")
        assert default_history_path() is None


# ---------------------------------------------------------------------------
# Budgeter
# ---------------------------------------------------------------------------

class TestBenchBudgeter:
    def test_measured_history_wins(self, tmp_path):
        path = str(tmp_path / "h.json")
        b = BenchBudgeter(path, budget_s=1000)
        b.record("cfg", 123.0, cold=False, sig="10x2:light")
        assert b.estimate("cfg", 50.0, sig="10x2:light") == (
            123.0, "measured_history")
        assert b.estimate("cfg", 50.0, sig="other") == (50.0, "assumed")

    def test_cost_model_tier_only_raises_estimates(self, tmp_path):
        path = str(tmp_path / "h.json")
        append_observations(path, [_obs("Big:fit", 1_000_000, 500,
                                        5000.0)] * 4)
        b = BenchBudgeter(path, budget_s=10_000)
        est, src = b.estimate("cfg", 10.0, sig="1000000x500:default")
        assert src == "cost_model" and est > 10.0
        # prediction below the stated assumption -> assumption stands
        est, src = b.estimate("cfg", 1e9, sig="1000000x500:default")
        assert src == "assumed" and est == 1e9

    def test_skip_reason_and_reserve(self, tmp_path):
        t = [0.0]
        b = BenchBudgeter(str(tmp_path / "h.json"), budget_s=100,
                          clock=lambda: t[0])
        b.set_reserve(60.0)
        assert b.should_skip("cheap", 10.0) is None
        reason = b.should_skip("big", 50.0)
        assert reason and "exceeds remaining budget" in reason
        assert "reserving 60s" in reason
        t[0] = 95.0
        assert b.should_skip("cheap", 10.0) is not None
        assert "cheap" in b.decisions and "big" in b.decisions


# ---------------------------------------------------------------------------
# Planner + workflow integration
# ---------------------------------------------------------------------------

class TestPlanner:
    def test_small_shape_stays_in_core(self):
        adv = advise_plan(10_000, 50, host_budget_bytes=1 << 30)
        assert adv.mode == "in-core" and adv.chunk_rows is None

    def test_big_shape_streams_with_geometry(self):
        adv = advise_plan(10_000_000, 500, host_budget_bytes=1 << 30)
        assert adv.mode == "stream"
        assert adv.chunk_rows and adv.chunk_rows >= 1024
        # chunk target ~64MB of f32 rows
        assert abs(adv.chunk_rows * 500 * 4 - (64 << 20)) < (8 << 20)
        assert adv.retain_mb >= 64
        assert adv.prefetch_chunks >= 2
        assert "exceeds" in " ".join(adv.reasons)

    def test_deterministic(self):
        a = advise_plan(1_000_000, 500, host_budget_bytes=1 << 30)
        b = advise_plan(1_000_000, 500, host_budget_bytes=1 << 30)
        assert a.to_json() == b.to_json()

    def test_plan_explain_carries_advice(self):
        import pandas as pd

        from transmogrifai_tpu import (FeatureBuilder, OpWorkflow,
                                       transmogrify)
        from transmogrifai_tpu.workflow.dag import compute_dag
        from transmogrifai_tpu.workflow.plan import plan_for

        rng = np.random.default_rng(0)
        df = pd.DataFrame({"label": (rng.random(50) > 0.5).astype(float),
                           "a": rng.normal(size=50)})
        label = FeatureBuilder.RealNN("label").as_response()
        feats = transmogrify([FeatureBuilder.Real("a").as_predictor()])
        from transmogrifai_tpu.models import OpLogisticRegression as LR
        pred = LR().set_input(label, feats).get_output()
        dag = compute_dag([pred])
        plan = plan_for(dag, keep=[pred.name])
        advice = plan.advise(10_000_000, 500,
                             host_budget_bytes=1 << 30)
        text = plan.explain(advice=advice)
        assert "plan advice: stream" in text

    def test_tuner_strategy_applied_and_restored(self):
        import pandas as pd

        from transmogrifai_tpu import (FeatureBuilder, OpWorkflow,
                                       transmogrify)
        from transmogrifai_tpu.selector import (
            BinaryClassificationModelSelector,
        )

        rng = np.random.default_rng(3)
        n = 600
        df = pd.DataFrame({
            "label": (rng.random(n) > 0.5).astype(float),
            "a": rng.normal(size=n), "b": rng.normal(size=n),
            "c": rng.normal(size=n)})
        label = FeatureBuilder.RealNN("label").as_response()
        feats = transmogrify([FeatureBuilder.Real(c).as_predictor()
                              for c in "abc"])
        sel = BinaryClassificationModelSelector.with_cross_validation(
            num_folds=2,
            models_and_parameters=[(OpLogisticRegression(),
                                    grid(reg_param=[0.01, 0.1, 0.3]))])
        pred = sel.set_input(label, feats).get_output()
        wf = OpWorkflow().set_result_features(pred).set_input_data(df)
        assert sel.strategy == "full"
        wf.train(tuner=Tuner(strategy="halving",
                             halving=HalvingConfig(min_rows=64,
                                                   min_candidates=2)))
        # applied for the train, restored afterwards
        assert sel.strategy == "full"
        assert "halving_schedule" in sel.metadata
