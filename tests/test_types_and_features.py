"""Tests for the semantic type system, columns, features and DAG layering.

Parity model: reference FeatureTypeTest / FeatureBuilderTest / FeatureLikeTest
(features/src/test/scala/com/salesforce/op/features/).
"""
import numpy as np
import pandas as pd
import pytest

from transmogrifai_tpu.types import feature_types as ft
from transmogrifai_tpu.types.columns import ColumnarDataset, FeatureColumn
from transmogrifai_tpu.features import Feature, FeatureBuilder, FeatureCycleError
from transmogrifai_tpu.stages.base import LambdaTransformer
from transmogrifai_tpu.workflow.dag import compute_dag


class TestFeatureTypes:
    def test_registry_has_all_35_plus_types(self):
        names = {t.type_name() for t in ft.all_feature_types()}
        expected = {
            "Real", "RealNN", "Binary", "Integral", "Percent", "Currency",
            "Date", "DateTime", "Text", "Email", "Base64", "Phone", "ID",
            "URL", "TextArea", "PickList", "ComboBox", "Country", "State",
            "PostalCode", "City", "Street", "TextList", "DateList",
            "DateTimeList", "MultiPickList", "OPVector", "Geolocation",
            "TextMap", "EmailMap", "PhoneMap", "IDMap", "URLMap",
            "PickListMap", "RealMap", "IntegralMap", "BinaryMap",
            "MultiPickListMap", "GeolocationMap", "Prediction", "NameStats",
        }
        assert expected <= names

    def test_nullability_in_type(self):
        assert ft.Real.is_nullable()
        assert not ft.RealNN.is_nullable()
        assert not ft.Prediction.is_nullable()

    def test_traits(self):
        assert issubclass(ft.RealNN, ft.SingleResponse)
        assert issubclass(ft.PickList, ft.Categorical)
        assert issubclass(ft.Country, ft.Location)
        assert issubclass(ft.MultiPickList, ft.MultiResponse)

    def test_type_by_name_roundtrip(self):
        for t in ft.all_feature_types():
            assert ft.type_by_name(t.type_name()) is t

    def test_prediction_keys(self):
        keys = ft.Prediction.keys_for(2)
        assert keys == ["prediction", "rawPrediction_0", "rawPrediction_1",
                        "probability_0", "probability_1"]


class TestFeatureColumn:
    def test_real_column_mask(self):
        c = FeatureColumn.from_values(ft.Real, [1.0, None, 3.5])
        assert c.mask.tolist() == [True, False, True]
        assert c.to_list() == [1.0, None, 3.5]

    def test_integral_column(self):
        c = FeatureColumn.from_values(ft.Integral, [1, None, 3])
        assert c.to_list() == [1, None, 3]

    def test_binary_column(self):
        c = FeatureColumn.from_values(ft.Binary, [True, None, False])
        assert c.to_list() == [True, None, False]

    def test_text_column(self):
        c = FeatureColumn.from_values(ft.Text, ["a", None, ""])
        assert c.to_list() == ["a", None, None]  # empty string = missing

    def test_picklist_column(self):
        c = FeatureColumn.from_values(ft.PickList, ["x", "y", None])
        assert c.to_list() == ["x", "y", None]

    def test_multipicklist(self):
        c = FeatureColumn.from_values(ft.MultiPickList, [{"a", "b"}, None])
        assert c.to_list()[0] == frozenset({"a", "b"})
        assert c.to_list()[1] == frozenset()

    def test_geolocation(self):
        c = FeatureColumn.from_values(ft.Geolocation, [[1.0, 2.0, 3.0], None])
        assert c.mask.tolist() == [True, False]

    def test_map_column(self):
        c = FeatureColumn.from_values(ft.RealMap, [{"a": 1.0}, None])
        assert c.to_list() == [{"a": 1.0}, {}]

    def test_masked_values_fill(self):
        c = FeatureColumn.from_values(ft.Real, [1.0, None])
        assert c.masked_values(fill=-1.0).tolist() == [1.0, -1.0]

    def test_dataset_ragged_rejected(self):
        a = FeatureColumn.from_values(ft.Real, [1.0, 2.0])
        b = FeatureColumn.from_values(ft.Real, [1.0])
        with pytest.raises(ValueError):
            ColumnarDataset({"a": a, "b": b})

    def test_dataset_pandas_roundtrip(self):
        df = pd.DataFrame({"x": [1.0, None], "s": ["a", None]})
        ds = ColumnarDataset.from_pandas(df, {"x": ft.Real, "s": ft.Text})
        back = ds.to_pandas()
        assert back["x"].tolist()[0] == 1.0
        assert back["s"].tolist()[0] == "a"
        assert back["s"].isna().tolist() == [False, True]


class TestFeatureBuilder:
    def test_typed_builder(self):
        age = FeatureBuilder.Real("age").extract(lambda r: r["age"]).as_predictor()
        assert age.name == "age"
        assert age.ftype is ft.Real
        assert not age.is_response
        assert age.is_raw

    def test_response_type_check(self):
        with pytest.raises(TypeError):
            FeatureBuilder.Text("t").as_response()

    def test_from_dataframe_inference(self):
        df = pd.DataFrame({
            "label": [1.0, 0.0] * 10,
            "age": [20.5, None] * 10,
            "count": list(range(20)),
            "flag": [True, False] * 10,
            "cat": ["a", "b"] * 10,
        })
        resp, preds = FeatureBuilder.from_dataframe(df, response="label")
        assert resp.ftype is ft.RealNN and resp.is_response
        types = {f.name: f.ftype for f in preds}
        assert types["age"] is ft.Real
        assert types["count"] is ft.Integral
        assert types["flag"] is ft.Binary
        assert types["cat"] is ft.PickList


class TestFeatureDAG:
    def test_transform_with_and_raw_features(self):
        x = FeatureBuilder.Real("x").as_predictor()
        doubled = x.transform_with(
            LambdaTransformer(lambda c: c, output_type=ft.Real, operation_name="dbl")
        )
        assert doubled.parents == [x]
        assert [f.name for f in doubled.raw_features()] == ["x"]
        assert len(doubled.parent_stages()) == 2  # generator + lambda

    def test_dag_layering(self):
        x = FeatureBuilder.Real("x").as_predictor()
        y = FeatureBuilder.Real("y").as_predictor()
        s1 = LambdaTransformer(lambda c: c, ft.Real, "a")
        s2 = LambdaTransformer(lambda c: c, ft.Real, "b")
        f1 = x.transform_with(s1)
        f2 = f1.transform_with(s2)
        dag = compute_dag([f2, y])
        sizes = [len(l) for l in dag.layers]
        assert sizes == [2, 1, 1]  # [genX, genY], [s1], [s2]

    def test_cycle_detection(self):
        x = FeatureBuilder.Real("x").as_predictor()
        s = LambdaTransformer(lambda c: c, ft.Real, "a")
        f = x.transform_with(s)
        f.parents.append(f)  # deliberately corrupt
        with pytest.raises(FeatureCycleError):
            f.raw_features()

    def test_history(self):
        x = FeatureBuilder.Real("x").as_predictor()
        f = x.transform_with(LambdaTransformer(lambda c: c, ft.Real, "op"))
        h = f.history()
        assert h.origin_features == ["x"]
        assert len(h.stages) == 2
