"""SensitiveFeatureInformation + VersionInfo (reference
SensitiveFeatureInformationTest, VersionInfo.scala coverage)."""
from transmogrifai_tpu.utils import (
    GenderDetectionResults, SensitiveFeatureInformation,
    SensitiveNameInformation, VersionInfo, sensitive_map_from_json,
    sensitive_map_to_json, version_info,
)


class TestSensitiveFeatureInformation:
    def test_name_info_round_trip(self):
        info = SensitiveNameInformation(
            name="name", key="first", action_taken=True, prob_name=0.92,
            gender_detect_strats=[GenderDetectionResults("ByIndex", 0.1)],
            prob_male=0.4, prob_female=0.5, prob_other=0.1)
        m = {"name": [info]}
        back = sensitive_map_from_json(sensitive_map_to_json(m))
        got = back["name"][0]
        assert isinstance(got, SensitiveNameInformation)
        assert got.prob_name == 0.92 and got.action_taken
        assert got.gender_detect_strats[0].strategy == "ByIndex"

    def test_base_info_round_trip(self):
        m = {"f": [SensitiveFeatureInformation(name="f")]}
        back = sensitive_map_from_json(sensitive_map_to_json(m))
        assert back["f"][0].name == "f" and not back["f"][0].action_taken


class TestVersionInfo:
    def test_version_info_stamped(self):
        vi = version_info()
        assert vi.version and vi.python_version
        assert VersionInfo.from_json(vi.to_json()) == vi
