"""Workflow-level cross-validation (OpWorkflow.withWorkflowCV parity).

Reference: OpWorkflowCVTest — the DAG is cut at the ModelSelector
(FitStagesUtil.cutDAG FitStagesUtil.scala:302-355), label-aware
feature-engineering estimators (SanityChecker) refit inside every fold
(OpValidator.applyDAG OpValidator.scala:250), and the selector skips
validation on the final fit because the best estimator is already chosen
(ModelSelector.findBestEstimator ModelSelector.scala:116).
"""
import numpy as np
import pandas as pd

from transmogrifai_tpu import FeatureBuilder, OpWorkflow, transmogrify
from transmogrifai_tpu.evaluators import Evaluators
from transmogrifai_tpu.models import OpLogisticRegression
from transmogrifai_tpu.preparators import SanityChecker
from transmogrifai_tpu.selector import BinaryClassificationModelSelector, grid
from transmogrifai_tpu.workflow.dag import compute_dag, cut_dag_cv


def synthetic_binary(n=400, seed=0):
    rng = np.random.default_rng(seed)
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)
    cat = rng.choice(["a", "b", "c"], size=n)
    logits = 1.5 * x1 - 1.0 * x2 + (cat == "a") * 0.8
    y = (rng.random(n) < 1 / (1 + np.exp(-logits))).astype(float)
    return pd.DataFrame({"label": y, "x1": x1, "x2": x2, "cat": cat})


def build_dag():
    label = FeatureBuilder.RealNN("label").as_response()
    x1 = FeatureBuilder.Real("x1").as_predictor()
    x2 = FeatureBuilder.Real("x2").as_predictor()
    cat = FeatureBuilder.PickList("cat").as_predictor()
    features = transmogrify([x1, x2, cat])
    checked = SanityChecker(max_correlation=0.99).set_input(
        label, features).get_output()
    selector = BinaryClassificationModelSelector.with_cross_validation(
        num_folds=3,
        models_and_parameters=[
            (OpLogisticRegression(), grid(reg_param=[0.01, 0.1])),
        ])
    prediction = selector.set_input(label, checked).get_output()
    return label, prediction, selector


class TestCutDagCV:
    def test_cut_puts_sanity_checker_in_during(self):
        _, prediction, selector = build_dag()
        dag = compute_dag([prediction])
        cut = cut_dag_cv(dag)
        assert cut.selector is selector
        during_names = [type(s).__name__ for l in cut.during.layers for s in l]
        assert "SanityChecker" in during_names
        # the unsupervised vectorizers stay in the before-DAG
        before_names = [type(s).__name__ for l in cut.before.layers for s in l]
        assert "SanityChecker" not in before_names
        assert any("Vector" in n or "Combiner" in n for n in before_names)
        assert not cut.after.layers

    def test_at_most_one_selector(self):
        label, prediction, _ = build_dag()
        _, prediction2, _ = build_dag()
        dag = compute_dag([prediction, prediction2])
        try:
            cut_dag_cv(dag)
            assert False, "expected ValueError for two selectors"
        except ValueError as e:
            assert "at most 1" in str(e)


class TestWorkflowCV:
    def test_train_with_workflow_cv(self):
        df = synthetic_binary()
        label, prediction, selector = build_dag()
        wf = (OpWorkflow()
              .set_result_features(prediction)
              .set_input_data(df)
              .with_workflow_cv())
        model = wf.train()

        # the selector went through findBestEstimator, not inline validation
        # (the winner is consumed by the final fit; the fold-refit results
        # stay introspectable in metadata)
        assert selector.best_estimator is None
        results = selector.metadata["workflow_cv_results"]
        assert len(results) == 2  # one per grid point
        assert all(len(r["foldValues"]) == 3 for r in results)
        assert all(r["modelType"] == "OpLogisticRegression" for r in results)

        scored, metrics = model.score_and_evaluate(
            Evaluators.BinaryClassification.auPR())
        assert metrics["AuPR"] > 0.7, metrics

        # summary metadata records the fold-validated results
        summ = model.summary()
        sel_meta = next(v for v in summ.values()
                        if "model_selector_summary" in v)
        assert sel_meta["model_selector_summary"]["bestModelType"] \
            == "OpLogisticRegression"

    def test_cv_and_plain_train_agree_on_quality(self):
        df = synthetic_binary(seed=3)
        _, prediction, _ = build_dag()
        plain = (OpWorkflow().set_result_features(prediction)
                 .set_input_data(df).train())
        _, prediction_cv, _ = build_dag()
        cv = (OpWorkflow().set_result_features(prediction_cv)
              .set_input_data(df).with_workflow_cv().train())
        ev = Evaluators.BinaryClassification.auPR()
        _, m_plain = plain.score_and_evaluate(ev)
        ev2 = Evaluators.BinaryClassification.auPR()
        _, m_cv = cv.score_and_evaluate(ev2)
        assert abs(m_plain["AuPR"] - m_cv["AuPR"]) < 0.1
