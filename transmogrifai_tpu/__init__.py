"""transmogrifai_tpu — a TPU-native AutoML framework for structured data.

A ground-up JAX/XLA re-design of TransmogrifAI's capabilities (typed feature
DAG, automated feature engineering/validation/model-selection, model insights,
one-file persistence, lightweight local scoring) where the execution substrate
is compiled XLA programs over device-resident columnar batches instead of
Spark jobs over row RDDs.
"""

__version__ = "0.1.0"

from .features import Feature, FeatureBuilder  # noqa: F401
from .ops.transmogrify import transmogrify  # noqa: F401
from .workflow.workflow import OpWorkflow, OpWorkflowModel  # noqa: F401
from . import dsl  # noqa: F401  installs the fluent Feature methods
