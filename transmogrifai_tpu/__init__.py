"""transmogrifai_tpu — a TPU-native AutoML framework for structured data.

A ground-up JAX/XLA re-design of TransmogrifAI's capabilities (typed feature
DAG, automated feature engineering/validation/model-selection, model insights,
one-file persistence, lightweight local scoring) where the execution substrate
is compiled XLA programs over device-resident columnar batches instead of
Spark jobs over row RDDs.
"""

__version__ = "0.1.0"

import os as _os

if _os.environ.get("TMOG_POD_NUM_PROCESSES"):
    # pod child processes (launched via `tmog pod` / launch_local_pod)
    # must boot jax.distributed BEFORE any jax computation — which the
    # imports below can trigger — so the bootstrap runs first.  A pod
    # of ONE is still a declared pod (it runs the pod train protocol,
    # minus the distributed runtime).  distributed/runtime deliberately
    # imports nothing jax-adjacent at module level, and
    # distributed/__init__ resolves lazily.
    from .distributed.runtime import init_pod_from_env as _init_pod

    _init_pod()

from .features import Feature, FeatureBuilder  # noqa: F401
from .ops.transmogrify import transmogrify  # noqa: F401
from .workflow.workflow import OpWorkflow, OpWorkflowModel  # noqa: F401
from . import dsl  # noqa: F401  installs the fluent Feature methods
