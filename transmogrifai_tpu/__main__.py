"""``python -m transmogrifai_tpu`` — package-level CLI entrypoint
(gen/serve subcommands; same dispatch as ``python -m transmogrifai_tpu.cli``)."""
import sys

from .cli.main import main

sys.exit(main())
