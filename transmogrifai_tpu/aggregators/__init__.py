"""Time-based monoid aggregation of event records into feature values.

Reference: ``features/aggregators/`` (SURVEY §2.4) —
``Event(date, value)`` (aggregators/Event.scala:44), ``FeatureAggregator.
extract`` filtering events by response/predictor cutoff windows then
monoid-reducing (aggregators/FeatureAggregator.scala:48-108), per-type
defaults in ``MonoidAggregatorDefaults.aggregatorOf``
(aggregators/MonoidAggregatorDefaults.scala:52): sums for numerics, concat
for lists/sets, multiset-style union for maps, min/max time for dates;
``CutOffTime`` spec (aggregators/CutOffTime.scala), first/last-K
``TimeBasedAggregator`` (aggregators/TimeBasedAggregator.scala), and the
``CustomMonoidAggregator`` escape hatch.
"""
from .aggregators import (
    AGGREGATOR_REGISTRY, CustomMonoidAggregator, CutOffTime, Event,
    FeatureAggregator, MonoidAggregator, TimeBasedAggregator,
    default_aggregator, register_aggregator,
)

__all__ = [
    "Event", "CutOffTime", "MonoidAggregator", "CustomMonoidAggregator",
    "TimeBasedAggregator", "FeatureAggregator", "default_aggregator",
    "register_aggregator", "AGGREGATOR_REGISTRY",
]
