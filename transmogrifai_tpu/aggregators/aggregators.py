"""Monoid aggregators + event-window extraction (see package docstring)."""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Type

from ..types.feature_types import (
    Binary, Date, DateList, DateTime, FeatureType, Integral, OPList, OPMap,
    OPNumeric, OPSet, Real, Text, TextList,
)

__all__ = [
    "Event", "CutOffTime", "MonoidAggregator", "CustomMonoidAggregator",
    "TimeBasedAggregator", "FeatureAggregator", "default_aggregator",
    "register_aggregator", "AGGREGATOR_REGISTRY",
]


@dataclasses.dataclass(frozen=True)
class Event:
    """One timestamped raw value (Event.scala:44)."""
    time_ms: int
    value: Any


class CutOffTime:
    """Reference-record cutoff spec (CutOffTime.scala).

    ``kind``: 'unix' (absolute ms), 'no_cutoff', or 'function'
    (record -> ms, the DayOfWeek/Age analogues collapse to this).
    """

    def __init__(self, kind: str = "no_cutoff",
                 time_ms: Optional[int] = None,
                 fn: Optional[Callable[[Any], int]] = None):
        self.kind = kind
        self.time_ms = time_ms
        self.fn = fn

    @staticmethod
    def unix(time_ms: int) -> "CutOffTime":
        return CutOffTime("unix", time_ms=time_ms)

    @staticmethod
    def no_cutoff() -> "CutOffTime":
        return CutOffTime("no_cutoff")

    @staticmethod
    def function(fn: Callable[[Any], int]) -> "CutOffTime":
        return CutOffTime("function", fn=fn)

    def cutoff_for(self, record: Any) -> Optional[int]:
        if self.kind == "unix":
            return self.time_ms
        if self.kind == "function":
            return self.fn(record)
        return None


class MonoidAggregator:
    """prepare -> monoid plus -> present (Algebird MonoidAggregator shape)."""

    name = "base"

    def zero(self) -> Any:
        return None

    def prepare(self, value: Any) -> Any:
        return value

    def plus(self, a: Any, b: Any) -> Any:
        raise NotImplementedError

    def present(self, agg: Any) -> Any:
        return agg

    def reduce(self, values: Sequence[Any]) -> Any:
        acc = self.zero()
        for v in values:
            if v is None:
                continue
            acc = self.plus(acc, self.prepare(v)) if acc is not None \
                else self.prepare(v)
        return self.present(acc)


class _SumNumeric(MonoidAggregator):
    name = "sumNumeric"

    def plus(self, a, b):
        return a + b


class _MaxBoolean(MonoidAggregator):
    name = "maxBoolean"

    def plus(self, a, b):
        return bool(a) or bool(b)


class _MinTime(MonoidAggregator):
    name = "minTime"

    def plus(self, a, b):
        return min(a, b)


class _MaxTime(MonoidAggregator):
    name = "maxTime"

    def plus(self, a, b):
        return max(a, b)


class _ConcatText(MonoidAggregator):
    name = "concatText"

    def plus(self, a, b):
        return f"{a} {b}"


class _ConcatList(MonoidAggregator):
    name = "concatList"

    def prepare(self, value):
        return list(value) if isinstance(value, (list, tuple, set, frozenset)) \
            else [value]

    def plus(self, a, b):
        return list(a) + list(b)


class _UnionSet(MonoidAggregator):
    name = "unionSet"

    def prepare(self, value):
        return frozenset(value) if isinstance(
            value, (list, tuple, set, frozenset)) else frozenset([value])

    def plus(self, a, b):
        return a | b


class _UnionMapSum(MonoidAggregator):
    """Map union with numeric value-sum / non-numeric last-wins
    (ExtendedMultiset-style union, MonoidAggregatorDefaults maps)."""

    name = "unionMap"

    def plus(self, a, b):
        out = dict(a)
        for k, v in b.items():
            if k in out and isinstance(v, (int, float)) \
                    and not isinstance(v, bool):
                out[k] = out[k] + v
            else:
                out[k] = v
        return out


class CustomMonoidAggregator(MonoidAggregator):
    """Escape hatch (CustomMonoidAggregator.scala)."""

    name = "custom"

    def __init__(self, zero: Any, plus: Callable[[Any, Any], Any],
                 prepare: Optional[Callable[[Any], Any]] = None,
                 present: Optional[Callable[[Any], Any]] = None):
        self._zero = zero
        self._plus = plus
        self._prepare = prepare
        self._present = present

    def zero(self):
        return self._zero

    def prepare(self, value):
        return self._prepare(value) if self._prepare else value

    def plus(self, a, b):
        return self._plus(a, b)

    def present(self, agg):
        return self._present(agg) if self._present else agg


class TimeBasedAggregator(MonoidAggregator):
    """First/last K values by event time (TimeBasedAggregator.scala)."""

    def __init__(self, k: int = 1, last: bool = True):
        self.k = k
        self.last = last
        self.name = ("last" if last else "first") + f"K{k}"

    def prepare(self, value):
        return [value]  # events arrive time-ordered from FeatureAggregator

    def plus(self, a, b):
        merged = list(a) + list(b)
        return merged[-self.k:] if self.last else merged[: self.k]

    def present(self, agg):
        if agg is None:
            return None
        return agg if self.k > 1 else agg[0]


AGGREGATOR_REGISTRY: Dict[str, MonoidAggregator] = {}


def register_aggregator(agg: MonoidAggregator) -> MonoidAggregator:
    AGGREGATOR_REGISTRY[agg.name] = agg
    return agg


for _a in (_SumNumeric(), _MaxBoolean(), _MinTime(), _MaxTime(),
           _ConcatText(), _ConcatList(), _UnionSet(), _UnionMapSum()):
    register_aggregator(_a)


def default_aggregator(ftype: Type[FeatureType]) -> MonoidAggregator:
    """Per-type default (MonoidAggregatorDefaults.aggregatorOf :52)."""
    if issubclass(ftype, Binary):
        return AGGREGATOR_REGISTRY["maxBoolean"]
    if issubclass(ftype, (Date, DateTime)):
        return AGGREGATOR_REGISTRY["maxTime"]
    if issubclass(ftype, OPNumeric):
        return AGGREGATOR_REGISTRY["sumNumeric"]
    if issubclass(ftype, OPMap):
        return AGGREGATOR_REGISTRY["unionMap"]
    if issubclass(ftype, OPSet):
        return AGGREGATOR_REGISTRY["unionSet"]
    if issubclass(ftype, (OPList, DateList, TextList)):
        return AGGREGATOR_REGISTRY["concatList"]
    if issubclass(ftype, Text):
        return AGGREGATOR_REGISTRY["concatText"]
    return AGGREGATOR_REGISTRY["sumNumeric"]


class FeatureAggregator:
    """Window-filter + reduce one feature's events
    (FeatureAggregator.extract :48-108).

    Predictors aggregate events strictly *before* the cutoff (within
    ``predictor_window_ms`` when given); responses aggregate events *at or
    after* the cutoff (within ``response_window_ms``) — the leakage-safe
    split that lets one event log produce both sides of a training row.
    """

    def __init__(self, ftype: Type[FeatureType], is_response: bool,
                 aggregator: Optional[MonoidAggregator] = None,
                 predictor_window_ms: Optional[int] = None,
                 response_window_ms: Optional[int] = None):
        self.ftype = ftype
        self.is_response = is_response
        self.aggregator = aggregator or default_aggregator(ftype)
        self.predictor_window_ms = predictor_window_ms
        self.response_window_ms = response_window_ms

    def extract(self, events: Sequence[Event],
                cutoff_ms: Optional[int]) -> Any:
        events = sorted(events, key=lambda e: e.time_ms)
        if cutoff_ms is None:
            keep = events
        elif self.is_response:
            hi = (cutoff_ms + self.response_window_ms
                  if self.response_window_ms is not None else None)
            keep = [e for e in events if e.time_ms >= cutoff_ms
                    and (hi is None or e.time_ms < hi)]
        else:
            lo = (cutoff_ms - self.predictor_window_ms
                  if self.predictor_window_ms is not None else None)
            keep = [e for e in events if e.time_ms < cutoff_ms
                    and (lo is None or e.time_ms >= lo)]
        vals = [e.value for e in keep if e.value is not None]
        if not vals:
            return None
        return self.aggregator.reduce(vals)
