"""Static analysis + contract checking for pipelines (``tmog lint``).

The Scala reference rejects mis-wired feature DAGs at *compile* time — the
sealed ``FeatureType`` hierarchy and arity-typed stage signatures make a
dangling column or a label-leaking wire a type error before any data moves
(PAPER.md §1).  The Python port traded that away; this package wins the
safety layer back as five rule families, each with stable ``TM0xx`` ids:

* **DAG lint** (``linter``, TM00x) — pure static validation of an
  ``OpWorkflow``/``StagesDAG``/``ExecutionPlan`` before ``train``/``score``:
  dangling inputs, shadowed/duplicate output columns, feature-type
  mismatches at stage boundaries, dead stages, label leakage.
* **Contract checks** (``contracts``, TM02x) — opt-in ``TMOG_CHECK=1``
  instrumented mode enforcing the runtime contracts PRs 1-3 introduced:
  copy-on-write ``transform``, transform determinism, mergeable
  streaming-fit conformance — plus the mesh-era SPMD contracts (TM024
  pad-invariance, TM025 mesh-vs-single-device parity, TM026 checkpoint
  round-trip byte equality).
* **Trace-safety lint** (``trace_lint``, TM03x) — an AST pass over source
  files flagging host syncs inside jit-decorated functions, Python-scalar
  closures that become fresh trace constants, and unhashable
  static-argument declarations.
* **Shard-safety lint** (``shard_lint``, TM04x) — shard_map bodies that
  reduce sharded values with no collective, undefined mesh axis names,
  host round-trips in sweep inner loops, donated-buffer reuse,
  NamedSharding rank and spec-arity mismatches.
* **Concurrency/durability lint** (``concur_lint``, TM05x) — non-atomic
  JSON/benchmark writes bypassing ``write_json_atomic``, leaked
  tempfiles, unlocked shared mutation from thread-pool closures, and
  lock acquisition order inversions.
* **Collective-safety lint** (``pod_lint`` + ``contracts``, TM07x) —
  host collectives reachable only under process-divergent guards,
  collective-order mismatches between sibling/early-exit paths,
  non-deterministic folds of gathered partials; plus the runtime
  collective LEDGER (``TMOG_CHECK=1``): every pod collective records
  ``(seq, kind, site)``, divergent sequences fail attributed (TM074)
  and a ``TMOG_COLLECTIVE_TIMEOUT`` watchdog dumps the ledger on a
  hang (TM073).

CLI: ``python -m transmogrifai_tpu.lint`` (or ``tmog lint``); library entry
points: ``lint_dag``, ``lint_workflow``, ``lint_paths``,
``lint_paths_all``, ``check_workflow_contracts``,
``check_sharding_contracts``, ``check_collective_consistency``.
"""
from .diagnostics import (  # noqa: F401
    Diagnostic, Findings, PipelineLintError, ContractViolation, RULES,
    JSON_SCHEMA_VERSION,
)
from .linter import lint_dag, lint_workflow  # noqa: F401
from .trace_lint import lint_paths, lint_source  # noqa: F401
from .contracts import (  # noqa: F401
    checks_enabled, check_streaming_fit, check_warm_start,
    check_workflow_contracts,
    check_pad_invariance, check_mesh_parity, check_checkpoint_roundtrip,
    check_sharding_contracts, check_collective_consistency,
)

__all__ = [
    "Diagnostic", "Findings", "PipelineLintError", "ContractViolation",
    "RULES", "JSON_SCHEMA_VERSION", "lint_dag", "lint_workflow",
    "lint_paths", "lint_source", "lint_paths_all", "checks_enabled",
    "check_streaming_fit", "check_warm_start", "check_workflow_contracts",
    "check_pad_invariance", "check_mesh_parity",
    "check_checkpoint_roundtrip", "check_sharding_contracts",
    "check_collective_consistency",
]


def lint_paths_all(paths, cache=None) -> Findings:
    """All four source-lint families (trace TM03x, shard TM04x, concur
    TM05x, pod TM07x) over files / directory trees — what the CLI and
    the tier-1 self-lint run.  ``cache`` (a
    :class:`analysis.cache.LintResultCache`) reuses unchanged files'
    results keyed on ``(path, mtime_ns, size)`` + cross-file digests."""
    if cache is not None:
        from .cache import lint_paths_all_cached

        return lint_paths_all_cached(paths, cache)
    from . import concur_lint, pod_lint, shard_lint, trace_lint

    findings = trace_lint.lint_paths(paths)
    findings.extend(shard_lint.lint_paths(paths))
    findings.extend(concur_lint.lint_paths(paths))
    findings.extend(pod_lint.lint_paths(paths))
    return findings
