"""Static analysis + contract checking for pipelines (``tmog lint``).

The Scala reference rejects mis-wired feature DAGs at *compile* time — the
sealed ``FeatureType`` hierarchy and arity-typed stage signatures make a
dangling column or a label-leaking wire a type error before any data moves
(PAPER.md §1).  The Python port traded that away; this package wins the
safety layer back as three rule families, each with stable ``TM0xx`` ids:

* **DAG lint** (``linter``, TM00x) — pure static validation of an
  ``OpWorkflow``/``StagesDAG``/``ExecutionPlan`` before ``train``/``score``:
  dangling inputs, shadowed/duplicate output columns, feature-type
  mismatches at stage boundaries, dead stages, label leakage.
* **Contract checks** (``contracts``, TM02x) — opt-in ``TMOG_CHECK=1``
  instrumented mode enforcing the runtime contracts PRs 1-3 introduced:
  copy-on-write ``transform`` (inputs are frozen ``writeable=False`` and a
  write is attributed to the offending stage), transform determinism, and
  mergeable streaming-fit conformance (associativity + ``fit_streaming``
  vs ``fit`` equivalence within each fitter's documented tolerance).
* **Trace-safety lint** (``trace_lint``, TM03x) — an AST pass over source
  files flagging host syncs inside jit-decorated functions, Python-scalar
  closures that become fresh trace constants (recompile hazards), and
  unhashable static-argument declarations.

CLI: ``python -m transmogrifai_tpu.lint`` (or ``tmog lint``); library entry
points: ``lint_dag``, ``lint_workflow``, ``lint_paths``,
``check_workflow_contracts``.
"""
from .diagnostics import (  # noqa: F401
    Diagnostic, Findings, PipelineLintError, ContractViolation, RULES,
)
from .linter import lint_dag, lint_workflow  # noqa: F401
from .trace_lint import lint_paths, lint_source  # noqa: F401
from .contracts import (  # noqa: F401
    checks_enabled, check_streaming_fit, check_workflow_contracts,
)

__all__ = [
    "Diagnostic", "Findings", "PipelineLintError", "ContractViolation",
    "RULES", "lint_dag", "lint_workflow", "lint_paths", "lint_source",
    "checks_enabled", "check_streaming_fit", "check_workflow_contracts",
]
