"""Shared AST plumbing for the source-level lint families (TM03x/TM04x/TM05x).

Factored out of ``trace_lint`` when the shard-safety (``shard_lint``) and
concurrency (``concur_lint``) families arrived: all three need dotted-name
resolution, scope-bounded walks, and ``# tmog: disable=`` suppression with
identical semantics.

Suppression semantics: a ``# tmog: disable=TM030`` comment (comma-separate
several ids) disables the rule on that line, on the enclosing ``def`` line,
or — for a statement spanning several lines — on ANY line the flagged
node covers (``lineno..end_lineno``), so trailing comments on multi-line
calls work.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, Optional, Set

__all__ = ["Suppressions", "dotted", "scope_walk", "target_names",
           "load_names", "SCOPE_NODES"]

_DISABLE_RE = re.compile(r"#\s*tmog:\s*disable=([A-Z0-9,\s]+)")

SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
               ast.ClassDef)


class Suppressions:
    """Per-file ``# tmog: disable=`` map: line number -> suppressed ids."""

    def __init__(self, code: str):
        self.by_line: Dict[int, Set[str]] = {}
        for i, line in enumerate(code.splitlines(), 1):
            m = _DISABLE_RE.search(line)
            if m:
                self.by_line[i] = {
                    r.strip() for r in m.group(1).split(",") if r.strip()}

    def suppressed(self, rule: str, node: Optional[ast.AST] = None,
                   extra_lines: Iterable[Optional[int]] = ()) -> bool:
        """True when ``rule`` is disabled on any line ``node`` covers
        (multi-line statements honor a trailing comment on any of their
        lines) or on any of ``extra_lines`` (the enclosing ``def``)."""
        lines = list(extra_lines)
        if node is not None:
            start = getattr(node, "lineno", None)
            if start is not None:
                end = getattr(node, "end_lineno", None) or start
                lines.extend(range(start, end + 1))
        for ln in lines:
            if ln is not None and rule in self.by_line.get(ln, ()):
                return True
        return False


def dotted(node: ast.AST) -> Optional[str]:
    """'jax.jit' for Attribute/Name chains, else None."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


def scope_walk(scope: ast.AST):
    """Yield ``scope``'s nodes WITHOUT descending into nested function /
    lambda / class bodies (separate scopes); the nested scope nodes
    themselves are yielded so callers can recurse."""
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        n = stack.pop()
        yield n
        if not isinstance(n, SCOPE_NODES):
            stack.extend(ast.iter_child_nodes(n))


def target_names(t: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(t)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store)}


def load_names(e: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(e)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)}
