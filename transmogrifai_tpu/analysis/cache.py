"""Per-file lint result cache — the tier-1 self-lint stops re-parsing
unchanged files.

Same idiom as the CSV counting-pre-pass memo (``readers/files.py``): a
file's entry is keyed on ``(path, mtime_ns, size)``, so any rewrite —
even a same-size one, thanks to mtime_ns — invalidates it.  Unlike the
row memo, lint results also depend on CROSS-FILE state, which two
digests pin:

* ``reachingDigest`` — the call graph's collective-reaching name set
  (pod lint TM070/TM071 findings change when ANY file alters
  reachability);
* ``preEdges`` — the lock-order edge set accumulated over the files
  sorted BEFORE this one (concur lint TM053 fires at the LATER file of
  an inversion pair, so a file's findings depend on exactly that
  prefix).

A hit requires all three to match; anything else re-lints the file.
Function summaries (:mod:`analysis.callgraph`) are cached alongside the
findings so a fully warm run rebuilds the whole call graph without
parsing a single file.

The orchestrated entry point is :func:`lint_paths_all_cached` — the
same four families as ``analysis.lint_paths_all`` (trace TM03x, shard
TM04x, concur TM05x, pod TM07x), file-major order.  Persistence is a
single JSON document (``write_json_atomic``); a missing or corrupt
cache file degrades to a cold run.
"""
from __future__ import annotations

import hashlib
import os
from typing import Any, Dict, Iterable, List, Optional, Tuple

from .callgraph import CallGraph, FunctionSummary, summarize_source
from .diagnostics import JSON_SCHEMA_VERSION, Diagnostic, Findings

__all__ = ["LintResultCache", "lint_paths_all_cached"]


def _stat_key(path: str) -> Optional[List[int]]:
    try:
        st = os.stat(path)
    except OSError:
        return None
    return [int(st.st_mtime_ns), int(st.st_size)]


def _edges_digest(edges: Dict[Tuple[str, str], str]) -> str:
    h = hashlib.sha256()
    for (a, b), loc in sorted(edges.items()):
        h.update(f"{a}|{b}|{loc}\n".encode())
    return h.hexdigest()


def _reaching_digest(graph: CallGraph) -> str:
    h = hashlib.sha256()
    for name in sorted(graph.reaching_names()):
        h.update(name.encode() + b"\n")
    return h.hexdigest()


class LintResultCache:
    """Disk-persisted memo of per-file lint results + call summaries."""

    def __init__(self, path: Optional[str] = None):
        self.path = path
        self.files: Dict[str, Dict[str, Any]] = {}
        self.hits = 0
        self.misses = 0
        if path is not None and os.path.exists(path):
            try:
                import json

                with open(path, encoding="utf-8") as f:
                    doc = json.load(f)
                if doc.get("schemaVersion") == JSON_SCHEMA_VERSION:
                    self.files = dict(doc.get("files", {}))
            except (OSError, ValueError):
                self.files = {}

    def save(self) -> None:
        if self.path is None:
            return
        from ..utils.jsonio import write_json_atomic

        write_json_atomic(self.path, {
            "schemaVersion": JSON_SCHEMA_VERSION, "files": self.files})

    # -- entry plumbing -----------------------------------------------

    def lookup(self, path: str, key, reaching_digest: str,
               pre_edges: str) -> Optional[Dict[str, Any]]:
        e = self.files.get(path)
        if (e is not None and e.get("key") == key
                and e.get("reachingDigest") == reaching_digest
                and e.get("preEdges") == pre_edges):
            return e
        return None

    def store(self, path: str, key, reaching_digest: str, pre_edges: str,
              summaries: List[FunctionSummary],
              own_edges: List[List[str]],
              findings: Findings) -> None:
        self.files[path] = {
            "key": key,
            "reachingDigest": reaching_digest,
            "preEdges": pre_edges,
            "summaries": [s.to_json() for s in summaries],
            "ownEdges": own_edges,
            "findings": [d.to_json() for d in findings],
        }


def _decode_findings(raw: Iterable[Dict[str, Any]]) -> Findings:
    return Findings(Diagnostic(
        rule=d["rule"], message=d["message"],
        severity=d.get("severity", "error"),
        stage_uid=d.get("stageUid"), location=d.get("location"))
        for d in raw)


def lint_paths_all_cached(paths: Iterable[str],
                          cache: LintResultCache) -> Findings:
    """All four source-lint families over ``paths`` through ``cache``.

    Phase 1 assembles every file's function summaries (cache or one
    parse) and builds the whole-tree call graph; phase 2 walks the files
    in sorted order, reusing a file's findings when its stat key and
    both cross-file digests match, re-linting otherwise.  Saves the
    cache before returning.
    """
    from . import concur_lint, pod_lint, shard_lint, trace_lint
    from .trace_lint import iter_py_files

    files = list(iter_py_files(paths))
    graph = CallGraph()
    prepared: List[Tuple[str, Any, Optional[str],
                         List[FunctionSummary]]] = []
    for path in files:
        key = _stat_key(path)
        entry = cache.files.get(path)
        if entry is not None and entry.get("key") == key:
            summaries = [FunctionSummary.from_json(s)
                         for s in entry.get("summaries", [])]
            code = None     # lazily read only on a findings miss
        else:
            try:
                with open(path, encoding="utf-8") as f:
                    code = f.read()
            except OSError:
                continue
            try:
                summaries = summarize_source(code, path)
            except SyntaxError:
                summaries = []
        graph.add_summaries(summaries)
        prepared.append((path, key, code, summaries))

    reaching_digest = _reaching_digest(graph)
    edges: Dict[Tuple[str, str], str] = {}
    findings = Findings()
    for path, key, code, summaries in prepared:
        pre_edges = _edges_digest(edges)
        entry = cache.lookup(path, key, reaching_digest, pre_edges)
        if entry is not None:
            cache.hits += 1
            findings.extend(_decode_findings(entry.get("findings", [])))
            for a, b, loc in entry.get("ownEdges", []):
                edges.setdefault((a, b), loc)
            continue
        cache.misses += 1
        if code is None:
            try:
                with open(path, encoding="utf-8") as f:
                    code = f.read()
            except OSError:
                continue
        before = set(edges)
        file_findings = trace_lint.lint_source(code, path)
        file_findings.extend(shard_lint.lint_source(code, path))
        file_findings.extend(
            concur_lint.lint_source(code, path, _edges=edges))
        file_findings.extend(
            pod_lint.lint_source(code, path, graph=graph))
        own = [[a, b, edges[(a, b)]]
               for (a, b) in sorted(set(edges) - before)]
        cache.store(path, key, reaching_digest, pre_edges, summaries,
                    own, file_findings)
        findings.extend(file_findings)
    cache.save()
    return findings
