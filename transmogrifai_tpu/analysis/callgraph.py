"""Package-local call graph for the collective-safety lint (TM07x).

The pod runtime's host collectives (``allgather_obj`` / ``broadcast_obj``
/ ``allsum`` / ``pod.barrier`` and the ``multihost_utils`` primitives
under them) must be issued by EVERY process in the same order, so
``pod_lint`` needs to know not just where a collective literally appears
but which functions *transitively reach* one through plain calls.  This
module builds that reachability set from the AST alone — no imports, no
execution — with deliberately conservative name resolution:

* Functions are indexed by their bare ``def`` name (the last segment of
  any dotted call).  A call site resolves to a graph node ONLY when that
  name maps to exactly one definition across the whole linted file set;
  an ambiguous name (``complete_pass`` is defined on both the stream
  context and the checkpoint manager) resolves to nothing, so ambiguity
  can suppress a finding but never invent one.
* ``barrier`` is treated as a collective only when the receiver chain
  mentions a pod (``pod.barrier`` / ``self.pod.barrier``); the many
  unrelated ``barrier``-named things in test harnesses stay invisible.

Summaries (:class:`FunctionSummary`) are plain data so the per-file lint
cache can persist them and rebuild the graph without re-parsing
unchanged files.
"""
from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set

from .astutil import SCOPE_NODES, dotted

__all__ = ["CallGraph", "FunctionSummary", "HOST_COLLECTIVES",
           "collective_call_kind", "summarize_source"]

#: host-collective call names: the object-level pod collectives plus the
#: ``jax.experimental.multihost_utils`` primitives they are built on
HOST_COLLECTIVES = {"allgather_obj", "broadcast_obj", "allsum",
                    "sync_global_devices", "process_allgather"}


def _last(name: Optional[str]) -> Optional[str]:
    return name.split(".")[-1] if name else None


def collective_call_kind(call: ast.Call) -> Optional[str]:
    """The collective kind a call issues directly, or None.

    ``barrier`` qualifies only through a pod receiver (``pod.barrier``,
    ``self.pod.barrier``, ``ctx.pod_ctx.barrier`` ...), everything in
    :data:`HOST_COLLECTIVES` by bare name.
    """
    name = dotted(call.func)
    if not name:
        return None
    parts = name.split(".")
    leaf = parts[-1]
    if leaf in HOST_COLLECTIVES:
        return leaf
    if leaf == "barrier" and any("pod" in p for p in parts[:-1]):
        return "barrier"
    return None


@dataclasses.dataclass
class FunctionSummary:
    """One ``def``'s collective-relevant facts, JSON-serializable."""

    name: str                  # bare def name (call-site key)
    qualname: str              # Class.name for methods
    filename: str
    lineno: int
    direct: List[str]          # collective kinds issued directly
    calls: List[str]           # bare names of everything it calls

    def to_json(self) -> Dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, d: Dict) -> "FunctionSummary":
        return cls(name=d["name"], qualname=d["qualname"],
                   filename=d["filename"], lineno=int(d["lineno"]),
                   direct=list(d["direct"]), calls=list(d["calls"]))


def _own_calls(fn: ast.AST):
    """Call nodes in ``fn``'s own scope (nested defs are their own
    graph nodes and are summarized separately)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        n = stack.pop()
        if isinstance(n, SCOPE_NODES):
            continue
        if isinstance(n, ast.Call):
            yield n
        stack.extend(ast.iter_child_nodes(n))


def summarize_source(code: str, filename: str) -> List[FunctionSummary]:
    """Summaries for every function/method in one source file.

    Raises ``SyntaxError`` on unparsable input (callers degrade to a
    warning finding the same way the other lint families do).
    """
    tree = ast.parse(code, filename=filename)
    out: List[FunctionSummary] = []

    def visit(scope: ast.AST, prefix: str) -> None:
        for n in ast.iter_child_nodes(scope):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                direct: List[str] = []
                calls: List[str] = []
                for c in _own_calls(n):
                    kind = collective_call_kind(c)
                    if kind is not None:
                        direct.append(kind)
                    leaf = _last(dotted(c.func))
                    if leaf:
                        calls.append(leaf)
                qual = f"{prefix}.{n.name}" if prefix else n.name
                out.append(FunctionSummary(
                    name=n.name, qualname=qual, filename=filename,
                    lineno=n.lineno, direct=direct, calls=calls))
                visit(n, qual)
            elif isinstance(n, ast.ClassDef):
                visit(n, f"{prefix}.{n.name}" if prefix else n.name)
            elif not isinstance(n, SCOPE_NODES):
                visit(n, prefix)

    visit(tree, "")
    return out


class CallGraph:
    """Whole-file-set reachability: which bare names provably lead to a
    host collective."""

    def __init__(self) -> None:
        self._by_name: Dict[str, List[FunctionSummary]] = {}
        self._reaching: Optional[Set[str]] = None

    def add_summaries(self, summaries: List[FunctionSummary]) -> None:
        for s in summaries:
            self._by_name.setdefault(s.name, []).append(s)
        self._reaching = None

    def add_source(self, code: str, filename: str) -> List[FunctionSummary]:
        summaries = summarize_source(code, filename)
        self.add_summaries(summaries)
        return summaries

    def reaching_names(self) -> Set[str]:
        """Bare names that (a) map to exactly ONE definition in the file
        set and (b) transitively reach a host collective.  Ambiguous
        names are excluded — a call through one can never be proven to
        issue a collective, so pod_lint treats it as inert."""
        if self._reaching is not None:
            return self._reaching
        unique = {name: defs[0] for name, defs in self._by_name.items()
                  if len(defs) == 1}
        reach: Set[str] = {name for name, s in unique.items() if s.direct}
        changed = True
        while changed:
            changed = False
            for name, s in unique.items():
                if name in reach:
                    continue
                if any(c in reach for c in s.calls):
                    reach.add(name)
                    changed = True
        self._reaching = reach
        return reach

    def describe(self, name: str) -> Optional[FunctionSummary]:
        defs = self._by_name.get(name)
        return defs[0] if defs and len(defs) == 1 else None
