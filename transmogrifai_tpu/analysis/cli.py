"""``tmog lint`` / ``python -m transmogrifai_tpu.lint`` — the analyzer CLI.

Two kinds of targets, combinable in one invocation:

* **Source paths** (positional) — the three source-lint families over
  ``.py`` files and directory trees: trace safety (TM03x), shard safety
  (TM04x), concurrency/durability (TM05x).
* **Pipelines** (``--dag SPEC``, repeatable) — DAG lint (TM00x) of a
  workflow built by a factory.  ``SPEC`` is ``module.path:callable`` or
  ``path/to/file.py:callable``; the callable (invoked with no arguments)
  may return an ``OpWorkflow``/``OpWorkflowModel``, a ``Feature``, or a
  tuple/list of ``Feature``s (the result features).

Exit status is non-zero when any finding (error or warning) is reported —
the CI contract ``scripts/tier1.sh`` relies on.  ``--json`` emits a
machine-readable report (``schemaVersion`` gates its shape); ``--rules``
prints the rule catalog — with a selector (``--rules TM07x`` or
``--rules TM070,TM041``) it instead restricts the run to the selected
rules, where ``TM0Nx`` expands to the whole family.  ``--cache FILE``
persists per-file results keyed on ``(path, mtime_ns, size)`` plus
cross-file digests so unchanged files are never re-parsed; the JSON
report's top-level ``cacheHits`` counts the reused files.

``--baseline FILE`` arms the ratchet CI uses: findings recorded in the
committed baseline are tolerated (not reported, exit stays 0), NEW
findings still fail, and findings that no longer fire SHRINK the
baseline file in place — the debt can only go down.  Keys are
``rule|file`` with per-key counts, so line drift never invalidates it.
"""
from __future__ import annotations

import argparse
import importlib
import importlib.util
import json
import os
import sys
from typing import Dict, Optional, Sequence

from .diagnostics import RULES, Findings

__all__ = ["main"]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        "tmog lint",
        description="pipeline static analyzer: DAG lint (TM00x) + "
                    "trace (TM03x) / shard (TM04x) / concurrency (TM05x) "
                    "source lint")
    p.add_argument("paths", nargs="*",
                   help=".py files / directories for the source lints")
    p.add_argument("--dag", action="append", default=[], metavar="SPEC",
                   help="lint a pipeline DAG built by SPEC = "
                        "module:callable or file.py:callable (repeatable)")
    p.add_argument("--suppress", default="", metavar="TM001,TM07x",
                   help="comma-separated rule ids (or TM0Nx family "
                        "prefixes) to drop from the report")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit a JSON report instead of text")
    p.add_argument("--rules", nargs="?", const="*", default=None,
                   metavar="TM07x,TM041",
                   help="bare: print the rule catalog and exit; with a "
                        "comma-separated selector (ids or TM0Nx family "
                        "prefixes): restrict the run to those rules, or "
                        "print just that catalog slice when no targets "
                        "are given")
    p.add_argument("--cache", default=None, metavar="FILE",
                   help="per-file lint result cache: unchanged files "
                        "(same mtime_ns/size and cross-file digests) "
                        "reuse their stored findings")
    p.add_argument("--baseline", default=None, metavar="FILE",
                   help="JSON findings baseline: baselined findings pass, "
                        "new ones fail, vanished ones shrink the file "
                        "(the CI ratchet)")
    return p


def expand_rule_selectors(spec: str) -> set:
    """Expand ``TM001,TM07x`` into concrete rule ids.

    An ``x``-suffixed selector (``TM07x``) is a FAMILY prefix matching
    every catalog rule that starts with its first four characters
    (``TM070``–``TM079``); anything else passes through as an exact id.
    """
    out = set()
    for tok in spec.split(","):
        tok = tok.strip()
        if not tok:
            continue
        if len(tok) == 5 and tok.lower().endswith("x"):
            fam = tok[:4]
            members = {r for r in RULES if r.startswith(fam)}
            if not members:
                raise SystemExit(f"unknown rule family {tok!r}")
            out |= members
        else:
            out.add(tok)
    return out


def _baseline_key(d) -> str:
    where = d.location or d.stage_uid or "<pipeline>"
    if d.location and ":" in d.location:
        where = d.location.rsplit(":", 1)[0]
    return f"{d.rule}|{where}"


def _apply_baseline(findings: Findings, path: str) -> None:
    """Drop baselined findings in place; shrink the baseline file when
    entries stopped firing (the ratchet's downward half)."""
    from ..utils.jsonio import read_json_tolerant, write_json_atomic

    doc = read_json_tolerant(path, default={})
    entries: Dict[str, int] = {
        k: int(v) for k, v in (doc.get("entries") or {}).items()}
    matched: Dict[str, int] = {}
    budget = dict(entries)
    kept = []
    for d in findings.diagnostics:
        key = _baseline_key(d)
        if budget.get(key, 0) > 0:
            budget[key] -= 1
            matched[key] = matched.get(key, 0) + 1
        else:
            kept.append(d)
    findings.diagnostics = kept
    shrunk = {k: matched.get(k, 0) for k in entries if matched.get(k, 0)}
    if shrunk != entries and os.path.exists(path):
        write_json_atomic(path, {
            "schemaVersion": doc.get("schemaVersion", 2),
            "entries": shrunk}, sort_keys=True)


def _load_factory(spec: str):
    mod_part, sep, attr = spec.partition(":")
    if not sep:
        raise SystemExit(f"--dag expects module:callable, got {spec!r}")
    if mod_part.endswith(".py"):
        name = os.path.splitext(os.path.basename(mod_part))[0]
        loader_spec = importlib.util.spec_from_file_location(name, mod_part)
        if loader_spec is None or loader_spec.loader is None:
            raise SystemExit(f"cannot load {mod_part!r}")
        module = importlib.util.module_from_spec(loader_spec)
        sys.modules.setdefault(name, module)
        loader_spec.loader.exec_module(module)
    else:
        module = importlib.import_module(mod_part)
    try:
        return getattr(module, attr)
    except AttributeError:
        raise SystemExit(f"{mod_part!r} has no attribute {attr!r}")


def _lint_dag_spec(spec: str, findings: Findings) -> None:
    from ..features.feature import Feature
    from ..workflow.dag import compute_dag
    from .linter import lint_dag, lint_workflow

    obj = _load_factory(spec)
    if callable(obj) and not isinstance(obj, Feature):
        obj = obj()
    if isinstance(obj, Feature):
        obj = [obj]
    if isinstance(obj, (tuple, list)) and obj and all(
            isinstance(f, Feature) for f in obj):
        findings.extend(lint_dag(compute_dag(list(obj)),
                                 result_features=list(obj)))
    elif hasattr(obj, "result_features"):
        findings.extend(lint_workflow(obj))
    else:
        raise SystemExit(
            f"--dag {spec!r} returned {type(obj).__name__}; expected an "
            f"OpWorkflow, a Feature, or a sequence of Features")


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    selected = None
    if args.rules is not None:
        if args.rules != "*":
            selected = expand_rule_selectors(args.rules)
        if args.rules == "*" or (not args.paths and not args.dag):
            for rule, (sev, title) in sorted(RULES.items()):
                if selected is None or rule in selected:
                    print(f"{rule} [{sev}] {title}")
            return 0
    if not args.paths and not args.dag:
        build_parser().print_usage()
        return 2

    cache = None
    findings = Findings()
    if args.paths:
        from . import lint_paths_all

        if args.cache:
            from .cache import LintResultCache

            cache = LintResultCache(args.cache)
        findings.extend(lint_paths_all(args.paths, cache=cache))
    for spec in args.dag:
        _lint_dag_spec(spec, findings)

    if selected is not None:
        findings.diagnostics = [d for d in findings.diagnostics
                                if d.rule in selected]
    suppress = expand_rule_selectors(args.suppress)
    if suppress:
        findings.diagnostics = [d for d in findings.diagnostics
                                if d.rule not in suppress]
    if args.baseline:
        _apply_baseline(findings, args.baseline)

    if args.as_json:
        report = findings.to_json()
        report["cacheHits"] = cache.hits if cache is not None else 0
        print(json.dumps(report, indent=2))
    else:
        print(findings.format())
    return 1 if findings else 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
