"""``tmog lint`` / ``python -m transmogrifai_tpu.lint`` — the analyzer CLI.

Two kinds of targets, combinable in one invocation:

* **Source paths** (positional) — trace-safety lint (TM03x) over ``.py``
  files and directory trees.
* **Pipelines** (``--dag SPEC``, repeatable) — DAG lint (TM00x) of a
  workflow built by a factory.  ``SPEC`` is ``module.path:callable`` or
  ``path/to/file.py:callable``; the callable (invoked with no arguments)
  may return an ``OpWorkflow``/``OpWorkflowModel``, a ``Feature``, or a
  tuple/list of ``Feature``s (the result features).

Exit status is non-zero when any finding (error or warning) is reported —
the CI contract ``scripts/tier1.sh`` relies on.  ``--json`` emits a
machine-readable report; ``--rules`` prints the rule catalog.
"""
from __future__ import annotations

import argparse
import importlib
import importlib.util
import json
import os
import sys
from typing import Optional, Sequence

from .diagnostics import RULES, Findings

__all__ = ["main"]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        "tmog lint",
        description="pipeline static analyzer: DAG lint (TM00x) + "
                    "trace-safety lint (TM03x)")
    p.add_argument("paths", nargs="*",
                   help=".py files / directories for the trace-safety lint")
    p.add_argument("--dag", action="append", default=[], metavar="SPEC",
                   help="lint a pipeline DAG built by SPEC = "
                        "module:callable or file.py:callable (repeatable)")
    p.add_argument("--suppress", default="", metavar="TM001,TM005",
                   help="comma-separated rule ids to drop from the report")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit a JSON report instead of text")
    p.add_argument("--rules", action="store_true",
                   help="print the rule catalog and exit")
    return p


def _load_factory(spec: str):
    mod_part, sep, attr = spec.partition(":")
    if not sep:
        raise SystemExit(f"--dag expects module:callable, got {spec!r}")
    if mod_part.endswith(".py"):
        name = os.path.splitext(os.path.basename(mod_part))[0]
        loader_spec = importlib.util.spec_from_file_location(name, mod_part)
        if loader_spec is None or loader_spec.loader is None:
            raise SystemExit(f"cannot load {mod_part!r}")
        module = importlib.util.module_from_spec(loader_spec)
        sys.modules.setdefault(name, module)
        loader_spec.loader.exec_module(module)
    else:
        module = importlib.import_module(mod_part)
    try:
        return getattr(module, attr)
    except AttributeError:
        raise SystemExit(f"{mod_part!r} has no attribute {attr!r}")


def _lint_dag_spec(spec: str, findings: Findings) -> None:
    from ..features.feature import Feature
    from ..workflow.dag import compute_dag
    from .linter import lint_dag, lint_workflow

    obj = _load_factory(spec)
    if callable(obj) and not isinstance(obj, Feature):
        obj = obj()
    if isinstance(obj, Feature):
        obj = [obj]
    if isinstance(obj, (tuple, list)) and obj and all(
            isinstance(f, Feature) for f in obj):
        findings.extend(lint_dag(compute_dag(list(obj)),
                                 result_features=list(obj)))
    elif hasattr(obj, "result_features"):
        findings.extend(lint_workflow(obj))
    else:
        raise SystemExit(
            f"--dag {spec!r} returned {type(obj).__name__}; expected an "
            f"OpWorkflow, a Feature, or a sequence of Features")


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.rules:
        for rule, (sev, title) in sorted(RULES.items()):
            print(f"{rule} [{sev}] {title}")
        return 0
    if not args.paths and not args.dag:
        build_parser().print_usage()
        return 2

    findings = Findings()
    if args.paths:
        from .trace_lint import lint_paths

        findings.extend(lint_paths(args.paths))
    for spec in args.dag:
        _lint_dag_spec(spec, findings)

    suppress = {r.strip() for r in args.suppress.split(",") if r.strip()}
    if suppress:
        findings.diagnostics = [d for d in findings.diagnostics
                                if d.rule not in suppress]

    if args.as_json:
        print(json.dumps(findings.to_json(), indent=2))
    else:
        print(findings.format())
    return 1 if findings else 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
