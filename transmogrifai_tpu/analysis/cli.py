"""``tmog lint`` / ``python -m transmogrifai_tpu.lint`` — the analyzer CLI.

Two kinds of targets, combinable in one invocation:

* **Source paths** (positional) — the three source-lint families over
  ``.py`` files and directory trees: trace safety (TM03x), shard safety
  (TM04x), concurrency/durability (TM05x).
* **Pipelines** (``--dag SPEC``, repeatable) — DAG lint (TM00x) of a
  workflow built by a factory.  ``SPEC`` is ``module.path:callable`` or
  ``path/to/file.py:callable``; the callable (invoked with no arguments)
  may return an ``OpWorkflow``/``OpWorkflowModel``, a ``Feature``, or a
  tuple/list of ``Feature``s (the result features).

Exit status is non-zero when any finding (error or warning) is reported —
the CI contract ``scripts/tier1.sh`` relies on.  ``--json`` emits a
machine-readable report (``schemaVersion`` gates its shape); ``--rules``
prints the rule catalog.

``--baseline FILE`` arms the ratchet CI uses: findings recorded in the
committed baseline are tolerated (not reported, exit stays 0), NEW
findings still fail, and findings that no longer fire SHRINK the
baseline file in place — the debt can only go down.  Keys are
``rule|file`` with per-key counts, so line drift never invalidates it.
"""
from __future__ import annotations

import argparse
import importlib
import importlib.util
import json
import os
import sys
from typing import Dict, Optional, Sequence

from .diagnostics import RULES, Findings

__all__ = ["main"]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        "tmog lint",
        description="pipeline static analyzer: DAG lint (TM00x) + "
                    "trace (TM03x) / shard (TM04x) / concurrency (TM05x) "
                    "source lint")
    p.add_argument("paths", nargs="*",
                   help=".py files / directories for the source lints")
    p.add_argument("--dag", action="append", default=[], metavar="SPEC",
                   help="lint a pipeline DAG built by SPEC = "
                        "module:callable or file.py:callable (repeatable)")
    p.add_argument("--suppress", default="", metavar="TM001,TM005",
                   help="comma-separated rule ids to drop from the report")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit a JSON report instead of text")
    p.add_argument("--rules", action="store_true",
                   help="print the rule catalog and exit")
    p.add_argument("--baseline", default=None, metavar="FILE",
                   help="JSON findings baseline: baselined findings pass, "
                        "new ones fail, vanished ones shrink the file "
                        "(the CI ratchet)")
    return p


def _baseline_key(d) -> str:
    where = d.location or d.stage_uid or "<pipeline>"
    if d.location and ":" in d.location:
        where = d.location.rsplit(":", 1)[0]
    return f"{d.rule}|{where}"


def _apply_baseline(findings: Findings, path: str) -> None:
    """Drop baselined findings in place; shrink the baseline file when
    entries stopped firing (the ratchet's downward half)."""
    from ..utils.jsonio import read_json_tolerant, write_json_atomic

    doc = read_json_tolerant(path, default={})
    entries: Dict[str, int] = {
        k: int(v) for k, v in (doc.get("entries") or {}).items()}
    matched: Dict[str, int] = {}
    budget = dict(entries)
    kept = []
    for d in findings.diagnostics:
        key = _baseline_key(d)
        if budget.get(key, 0) > 0:
            budget[key] -= 1
            matched[key] = matched.get(key, 0) + 1
        else:
            kept.append(d)
    findings.diagnostics = kept
    shrunk = {k: matched.get(k, 0) for k in entries if matched.get(k, 0)}
    if shrunk != entries and os.path.exists(path):
        write_json_atomic(path, {
            "schemaVersion": doc.get("schemaVersion", 2),
            "entries": shrunk}, sort_keys=True)


def _load_factory(spec: str):
    mod_part, sep, attr = spec.partition(":")
    if not sep:
        raise SystemExit(f"--dag expects module:callable, got {spec!r}")
    if mod_part.endswith(".py"):
        name = os.path.splitext(os.path.basename(mod_part))[0]
        loader_spec = importlib.util.spec_from_file_location(name, mod_part)
        if loader_spec is None or loader_spec.loader is None:
            raise SystemExit(f"cannot load {mod_part!r}")
        module = importlib.util.module_from_spec(loader_spec)
        sys.modules.setdefault(name, module)
        loader_spec.loader.exec_module(module)
    else:
        module = importlib.import_module(mod_part)
    try:
        return getattr(module, attr)
    except AttributeError:
        raise SystemExit(f"{mod_part!r} has no attribute {attr!r}")


def _lint_dag_spec(spec: str, findings: Findings) -> None:
    from ..features.feature import Feature
    from ..workflow.dag import compute_dag
    from .linter import lint_dag, lint_workflow

    obj = _load_factory(spec)
    if callable(obj) and not isinstance(obj, Feature):
        obj = obj()
    if isinstance(obj, Feature):
        obj = [obj]
    if isinstance(obj, (tuple, list)) and obj and all(
            isinstance(f, Feature) for f in obj):
        findings.extend(lint_dag(compute_dag(list(obj)),
                                 result_features=list(obj)))
    elif hasattr(obj, "result_features"):
        findings.extend(lint_workflow(obj))
    else:
        raise SystemExit(
            f"--dag {spec!r} returned {type(obj).__name__}; expected an "
            f"OpWorkflow, a Feature, or a sequence of Features")


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.rules:
        for rule, (sev, title) in sorted(RULES.items()):
            print(f"{rule} [{sev}] {title}")
        return 0
    if not args.paths and not args.dag:
        build_parser().print_usage()
        return 2

    findings = Findings()
    if args.paths:
        from . import lint_paths_all

        findings.extend(lint_paths_all(args.paths))
    for spec in args.dag:
        _lint_dag_spec(spec, findings)

    suppress = {r.strip() for r in args.suppress.split(",") if r.strip()}
    if suppress:
        findings.diagnostics = [d for d in findings.diagnostics
                                if d.rule not in suppress]
    if args.baseline:
        _apply_baseline(findings, args.baseline)

    if args.as_json:
        print(json.dumps(findings.to_json(), indent=2))
    else:
        print(findings.format())
    return 1 if findings else 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
