"""Concurrency & durability lint (TM05x) — an AST pass over the source
trees that now carry threads and crash-safe artifacts.

PRs 5–7 made durability a protocol: every benchmark/checkpoint JSON
artifact lands via ``utils/jsonio.write_json_atomic`` (or the same
tmp + ``os.replace`` pattern inline), so a killed process can never
leave a truncated document.  The serving stack and the plan executor
hold real locks on real threads.  These rules pin those conventions:

* **TM050 — non-atomic JSON/benchmark write.**  A ``json.dump(...)``
  call — or an ``open(path, "w")`` whose path mentions ``benchmarks``
  or ``checkpoint`` — in a function that never calls ``os.replace``:
  a crash mid-write leaves a truncated artifact.  Writing through
  ``write_json_atomic`` (or the inline tmp + ``os.replace`` pattern,
  which the rule recognizes by the ``os.replace`` in the same function)
  is the fix.
* **TM051 — uncleaned tempfile.**  ``tempfile.mkstemp``/``mkdtemp``/
  ``NamedTemporaryFile(delete=False)`` outside a ``with`` statement,
  not stored on ``self`` (object-lifetime management), in a function
  with no ``finally`` block that unlinks/removes/rmtrees/closes — the
  temp artifact leaks on any exception.
* **TM052 — unlocked shared mutation from a pool closure.**  A lambda /
  local ``def`` submitted to an executor (``.submit(fn, ...)`` /
  ``.map(fn, ...)``) that mutates state it closes over (append/extend/
  add/update, subscript or attribute store, augmented assignment on a
  free name) with no ``with <lock>`` around the mutation.
* **TM053 — lock order inversion.**  Nested ``with``-lock acquisitions
  observed in both orders across the linted file set (e.g. registry
  lock inside admission lock in one path, admission inside registry in
  another) — the classic deadlock.  Lock identity is the enclosing
  class + attribute (``ModelRegistry._lock``) so the serving registry /
  admission queue pair is tracked across files.
* **TM047 — unguarded durable write on a pod code path.**  The pod
  runtime's convention (distributed/podstream.py) is that durable
  artifacts — checkpoints, ``benchmarks/*.json``, cost-history appends,
  quarantine sidecars — are written by the COORDINATOR only; N
  processes writing the same file race and corrupt it.  In a POD-AWARE
  function (one that calls ``current_pod()`` or takes a ``pod`` /
  ``pod_ctx`` parameter), a durable-write call
  (``write_json_atomic``, ``json.dump``, checkpoint-manager
  ``save_progress*`` / ``complete_pass`` / ``record_unit`` /
  ``save_rung_state``, ``dump_jsonl``) must be coordinator-guarded:
  inside an ``if ...is_coordinator()`` / ``process_index == 0`` branch,
  or after an early-exit guard (``if ... not ...is_coordinator():
  return`` — or a pod-branch exit, so single-process fallthrough code
  stays clean) earlier in the function.

Suppression: ``# tmog: disable=TM050`` on the flagged line (any line of
a multi-line statement, or the enclosing ``def`` line).  Entry points:
:func:`lint_source`, :func:`lint_paths` (TM053 needs the whole file set
to see both orders; ``lint_source`` reports only same-file inversions).
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .astutil import Suppressions, dotted, scope_walk
from .diagnostics import Findings
from .trace_lint import iter_py_files

__all__ = ["lint_source", "lint_paths"]

_TEMPFILE_FNS = {"mkstemp", "mkdtemp", "mktemp"}
_CLEANUP_HINTS = {"unlink", "remove", "rmtree", "cleanup", "close"}
_MUTATORS = {"append", "extend", "add", "update", "insert", "setdefault",
             "pop", "popitem", "clear", "remove", "discard", "put"}
_DURABLE_PATH_HINTS = ("benchmarks", "checkpoint")
#: TM047: durable-write call names / attribute calls; guard needles the
#: coordinator test must mention
_POD_PARAMS = {"pod", "pod_ctx", "pod_context"}
_POD_DURABLE_NAMES = {"write_json_atomic"}
_POD_DURABLE_ATTRS = {"save_progress", "save_progress_pod",
                      "complete_pass", "record_unit", "save_rung_state",
                      "dump_jsonl"}
_POD_GUARD_NEEDLES = ("is_coordinator", "process_index", "coordinator",
                      "pod")


def _last(name: Optional[str]) -> Optional[str]:
    return name.split(".")[-1] if name else None


def _lock_like(expr: ast.AST) -> bool:
    name = dotted(expr)
    if isinstance(expr, ast.Call):
        name = dotted(expr.func)
    return bool(name) and "lock" in name.lower()


def _string_constants(expr: ast.AST) -> List[str]:
    return [n.value for n in ast.walk(expr)
            if isinstance(n, ast.Constant) and isinstance(n.value, str)]


class _ConcurLinter:
    """One file's pass; ``lock_edges`` is shared across files by
    ``lint_paths`` so TM053 sees both acquisition orders wherever they
    live."""

    def __init__(self, code: str, filename: str,
                 lock_edges: Optional[Dict[Tuple[str, str], str]] = None):
        self.filename = filename
        self.findings = Findings()
        self.suppressions = Suppressions(code)
        self.tree = ast.parse(code, filename=filename)
        self.lock_edges = lock_edges if lock_edges is not None else {}

    def run(self) -> Findings:
        self._visit(self.tree, class_name=None, fn=None)
        return self.findings

    def _emit(self, rule: str, node: ast.AST, message: str,
              def_line: Optional[int] = None) -> None:
        if self.suppressions.suppressed(rule, node,
                                        extra_lines=(def_line,)):
            return
        self.findings.add(rule, message,
                          location=f"{self.filename}:{node.lineno}")

    # -- traversal ---------------------------------------------------------

    def _visit(self, scope: ast.AST, class_name: Optional[str],
               fn) -> None:
        if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._check_atomic_writes(scope)
            self._check_tempfiles(scope)
            self._check_pool_closures(scope)
            self._check_pod_writes(scope)
        self._check_lock_order(scope, class_name)
        for n in scope_walk(scope):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._visit(n, class_name, n)
            elif isinstance(n, ast.ClassDef):
                self._visit(n, n.name, fn)

    # -- TM050 ---------------------------------------------------------------

    def _check_atomic_writes(self, fn) -> None:
        has_replace = any(
            isinstance(n, ast.Call) and _last(dotted(n.func)) == "replace"
            and dotted(n.func) in ("os.replace", "replace")
            for n in ast.walk(fn))
        if has_replace:
            return
        for n in scope_walk(fn):
            if not isinstance(n, ast.Call):
                continue
            name = dotted(n.func)
            if name == "json.dump":
                self._emit(
                    "TM050", n,
                    "json.dump without the tmp + os.replace pattern: a "
                    "crash mid-write leaves a truncated artifact; use "
                    "utils.jsonio.write_json_atomic", fn.lineno)
            elif _last(name) == "open" and len(n.args) >= 2 \
                    and isinstance(n.args[1], ast.Constant) \
                    and isinstance(n.args[1].value, str) \
                    and "w" in n.args[1].value \
                    and "b" not in n.args[1].value:
                hay = " ".join(_string_constants(n.args[0])).lower()
                if any(h in hay for h in _DURABLE_PATH_HINTS):
                    self._emit(
                        "TM050", n,
                        f"non-atomic write to a durable artifact path "
                        f"({hay.strip()!r}): use write_json_atomic or "
                        f"tmp + os.replace", fn.lineno)

    # -- TM047 ---------------------------------------------------------------

    def _pod_aware(self, fn) -> bool:
        """A function on a pod code path: takes a pod/pod_ctx parameter
        or resolves the process-wide context itself."""
        a = fn.args
        params = {p.arg for p in (getattr(a, "posonlyargs", []) + a.args
                                  + getattr(a, "kwonlyargs", []))}
        if params & _POD_PARAMS:
            return True
        for n in ast.walk(fn):
            if isinstance(n, ast.Call) and \
                    _last(dotted(n.func)) == "current_pod":
                return True
        return False

    @staticmethod
    def _pod_guard_test(test: ast.AST) -> bool:
        for sub in ast.walk(test):
            name = None
            if isinstance(sub, ast.Attribute):
                name = sub.attr
            elif isinstance(sub, ast.Name):
                name = sub.id
            if name and any(n in name.lower()
                            for n in _POD_GUARD_NEEDLES):
                return True
        return False

    def _check_pod_writes(self, fn) -> None:
        if not self._pod_aware(fn):
            return
        guarded_ids = set()      # nodes inside a coordinator-tested If
        exit_guard_lines = []    # early-exit guards: later lines are safe
        for n in ast.walk(fn):
            if not isinstance(n, ast.If) or not self._pod_guard_test(
                    n.test):
                continue
            for sub in ast.walk(n):
                guarded_ids.add(id(sub))
            if any(isinstance(s, (ast.Return, ast.Raise, ast.Continue,
                                  ast.Break))
                   for b in n.body for s in ast.walk(b)):
                exit_guard_lines.append(n.lineno)
        for n in ast.walk(fn):
            if not isinstance(n, ast.Call):
                continue
            name = dotted(n.func) or ""
            is_write = (_last(name) in _POD_DURABLE_NAMES
                        or name == "json.dump"
                        or (isinstance(n.func, ast.Attribute)
                            and n.func.attr in _POD_DURABLE_ATTRS))
            if not is_write:
                continue
            if id(n) in guarded_ids:
                continue
            if any(line < n.lineno for line in exit_guard_lines):
                continue
            self._emit(
                "TM047", n,
                f"durable write ({_last(name) or name}) on a pod-aware "
                f"code path without a process_index == 0 / "
                f"is_coordinator() guard: every pod process would race "
                f"the same artifact — write on the coordinator only",
                fn.lineno)

    # -- TM051 ---------------------------------------------------------------

    def _check_tempfiles(self, fn) -> None:
        has_finally_cleanup = False
        for n in ast.walk(fn):
            if isinstance(n, ast.Try) and n.finalbody:
                body_names = {
                    _last(dotted(c.func)) for b in n.finalbody
                    for c in ast.walk(b) if isinstance(c, ast.Call)}
                if body_names & _CLEANUP_HINTS:
                    has_finally_cleanup = True
        in_with: Set[int] = set()
        for n in ast.walk(fn):
            if isinstance(n, ast.With):
                for item in n.items:
                    for c in ast.walk(item.context_expr):
                        in_with.add(id(c))
        for n in scope_walk(fn):
            if not isinstance(n, ast.Call):
                continue
            name = dotted(n.func) or ""
            is_tmp = (name.startswith("tempfile.")
                      and _last(name) in _TEMPFILE_FNS)
            if _last(name) == "NamedTemporaryFile":
                is_tmp = any(k.arg == "delete"
                             and isinstance(k.value, ast.Constant)
                             and k.value.value is False
                             for k in n.keywords)
            if not is_tmp or id(n) in in_with or has_finally_cleanup:
                continue
            # stored on self -> lifetime managed by the object (a close()
            # elsewhere), e.g. the streaming spill store
            stored_on_self = False
            for st in ast.walk(fn):
                if isinstance(st, ast.Assign) and st.value is n:
                    for t in st.targets:
                        for sub in ast.walk(t):
                            if isinstance(sub, ast.Attribute) and \
                                    isinstance(sub.ctx, ast.Store):
                                stored_on_self = True
            if stored_on_self:
                continue
            self._emit(
                "TM051", n,
                f"{_last(name) or 'NamedTemporaryFile'} outside a context "
                f"manager and with no finally-block cleanup: the temp "
                f"artifact leaks on any exception", fn.lineno)

    # -- TM052 ---------------------------------------------------------------

    def _check_pool_closures(self, fn) -> None:
        for n in scope_walk(fn):
            if not (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr in ("submit", "map")
                    and n.args):
                continue
            target = n.args[0]
            closure = None
            if isinstance(target, ast.Lambda):
                closure = target
            elif isinstance(target, ast.Name):
                for d in scope_walk(fn):
                    if isinstance(d, ast.FunctionDef) \
                            and d.name == target.id:
                        closure = d
            if closure is None:
                continue
            self._check_closure_mutations(closure, n, fn)

    def _check_closure_mutations(self, closure, submit_node, fn) -> None:
        bound: Set[str] = set()
        a = closure.args
        for p in (getattr(a, "posonlyargs", []) + a.args
                  + getattr(a, "kwonlyargs", [])):
            bound.add(p.arg)
        if a.vararg:
            bound.add(a.vararg.arg)
        if a.kwarg:
            bound.add(a.kwarg.arg)
        body = closure.body if isinstance(closure.body, list) \
            else [ast.Expr(closure.body)]
        for st in body:
            for sub in ast.walk(st):
                if isinstance(sub, ast.Name) and \
                        isinstance(sub.ctx, ast.Store):
                    bound.add(sub.id)

        locked_ids: Set[int] = set()
        for w in ast.walk(closure):
            if isinstance(w, ast.With) and any(
                    _lock_like(item.context_expr) for item in w.items):
                for sub in ast.walk(w):
                    locked_ids.add(id(sub))

        def free_mut(expr_name: ast.AST) -> Optional[str]:
            """The free-variable root of a mutated expression, or None."""
            root = expr_name
            while isinstance(root, (ast.Attribute, ast.Subscript)):
                root = root.value
            if isinstance(root, ast.Name) and root.id not in bound:
                return root.id
            if isinstance(root, ast.Name) and root.id == "self":
                return "self"
            return None

        for sub in ast.walk(closure):
            if id(sub) in locked_ids:
                continue
            hit = None
            if isinstance(sub, ast.AugAssign):
                hit = free_mut(sub.target)
            elif isinstance(sub, (ast.Assign,)):
                for t in sub.targets:
                    if isinstance(t, (ast.Subscript, ast.Attribute)):
                        hit = hit or free_mut(t)
            elif isinstance(sub, ast.Call) and \
                    isinstance(sub.func, ast.Attribute) and \
                    sub.func.attr in _MUTATORS:
                hit = free_mut(sub.func.value)
            if hit is not None:
                self._emit(
                    "TM052", sub,
                    f"thread-pool closure mutates shared state "
                    f"({hit!r}) without a lock: concurrent submits race",
                    fn.lineno)

    # -- TM053 ---------------------------------------------------------------

    def _lock_key(self, expr: ast.AST,
                  class_name: Optional[str]) -> Optional[str]:
        name = dotted(expr)
        if not name or "lock" not in name.lower():
            return None
        if name.startswith("self.") and class_name:
            return f"{class_name}.{name[5:]}"
        return name

    def _check_lock_order(self, scope: ast.AST,
                          class_name: Optional[str]) -> None:
        for outer in scope_walk(scope):
            if not isinstance(outer, ast.With):
                continue
            outer_keys = [k for k in (
                self._lock_key(i.context_expr, class_name)
                for i in outer.items) if k]
            if not outer_keys:
                continue
            for inner in ast.walk(outer):
                if inner is outer or not isinstance(inner, ast.With):
                    continue
                inner_keys = [k for k in (
                    self._lock_key(i.context_expr, class_name)
                    for i in inner.items) if k]
                for ok in outer_keys:
                    for ik in inner_keys:
                        if ok == ik:
                            continue
                        edge = (ok, ik)
                        rev = (ik, ok)
                        if rev in self.lock_edges:
                            self._emit(
                                "TM053", inner,
                                f"lock order inversion: {ok} -> {ik} "
                                f"here, but {ik} -> {ok} at "
                                f"{self.lock_edges[rev]} — concurrent "
                                f"paths can deadlock")
                        self.lock_edges.setdefault(
                            edge, f"{self.filename}:{inner.lineno}")


def lint_source(code: str, filename: str = "<string>",
                _edges: Optional[Dict] = None) -> Findings:
    """Concurrency/durability lint one source string (TM053 sees only
    this file's lock orders; use :func:`lint_paths` for the cross-file
    pass)."""
    try:
        return _ConcurLinter(code, filename, lock_edges=_edges).run()
    except SyntaxError as e:
        f = Findings()
        f.add("TM050", f"could not parse: {e}", severity="warning",
              location=f"{filename}:{e.lineno or 0}")
        return f


def lint_paths(paths: Iterable[str]) -> Findings:
    """Concurrency/durability lint files and directory trees; lock-order
    edges (TM053) accumulate across the whole file set."""
    findings = Findings()
    edges: Dict[Tuple[str, str], str] = {}
    for full in iter_py_files(paths):
        with open(full, encoding="utf-8") as fh:
            findings.extend(lint_source(fh.read(), full, _edges=edges))
    return findings
