"""Runtime contract checks (TM02x) — the ``TMOG_CHECK=1`` instrumented mode.

PRs 1-3 made the executor correct only under implicit contracts nothing
enforced: ``Transformer.transform`` must be copy-on-write (the layer-parallel
executor hands one dataset to concurrent stages), transforms must be
deterministic (serving parity and the sequential/plan byte-parity tests
assume it), and streaming fits must be mergeable and equivalent to in-core
fits.  With ``TMOG_CHECK=1`` the executor routes every transform through
:func:`guarded_transform_output`:

* **COW detection** — every input ndarray buffer is flipped
  ``writeable=False`` for the duration of the stage's transform; an
  in-place write raises immediately and is attributed to the offending
  stage as TM020 (instead of corrupting a sibling stage's view three
  layers later).
* **Determinism probe** — the transform runs twice on the same frozen
  input; differing bytes are a TM023.

Streaming-fit conformance (TM021/TM022) is a property check over every
``supports_streaming_fit`` estimator: chunk-independent states must merge
associatively, and ``fit_streaming`` at two chunk sizes must match ``fit``
within the fitter's declared ``streaming_fit_tol``.  Warm-start
equivalence (TM027, :func:`check_warm_start`) extends this to the
refresh path: a state exported, re-imported, and updated with new chunks
must finish to the fresh old+new streaming fit.
``check_workflow_contracts`` auto-discovers the estimators by walking a
workflow's DAG the way the sequential executor would.

Checks are enforcing: violations raise :class:`ContractViolation` at the
exact offending stage.  The property-check entry points instead *collect*
into ``Findings`` so a full audit reports every violation at once.
"""
from __future__ import annotations

import collections
import contextlib
import copy
import hashlib
import os
import sys
import threading
from typing import (Any, Dict, Iterable, List, Optional, Sequence,
                    Tuple)

import numpy as np

from .diagnostics import ContractViolation, Diagnostic, Findings

__all__ = ["CHECK_ENV", "checks_enabled", "guarded_transform_output",
           "columns_equal", "columns_close", "check_streaming_fit",
           "check_warm_start", "check_fold_merge",
           "check_workflow_contracts",
           "check_pad_invariance", "check_mesh_parity",
           "check_checkpoint_roundtrip", "check_sharding_contracts",
           "check_accum_tolerance",
           "COLLECTIVE_TIMEOUT_ENV", "collective_timeout",
           "CollectiveLedger", "collective_ledger",
           "reset_collective_ledger", "record_collective",
           "verify_collective_headers", "diff_collective_ledgers",
           "check_collective_consistency", "CollectiveWatchdog"]

#: set to "1" to enable the instrumented mode (used by tests and the tier-1
#: contract gate); any other value disables it with zero overhead beyond one
#: env lookup per transform
CHECK_ENV = "TMOG_CHECK"


def checks_enabled() -> bool:
    return os.environ.get(CHECK_ENV) == "1"


# ---------------------------------------------------------------------------
# COW freeze + determinism probe
# ---------------------------------------------------------------------------

def _column_buffers(col) -> List[np.ndarray]:
    """The mutable ndarray buffers a FeatureColumn exposes to a stage."""
    out = []
    vals = col.values
    if isinstance(vals, np.ndarray):
        out.append(vals)
    else:
        # PredictionBatch-style composite values
        for attr in ("prediction", "raw_prediction", "probability"):
            a = getattr(vals, attr, None)
            if isinstance(a, np.ndarray):
                out.append(a)
    if isinstance(col.mask, np.ndarray):
        out.append(col.mask)
    return out


@contextlib.contextmanager
def _frozen(data):
    """Freeze every input column buffer ``writeable=False``; restore the
    prior flags on exit (only buffers we actually flipped)."""
    flipped: List[np.ndarray] = []
    try:
        for col in data.columns.values():
            for arr in _column_buffers(col):
                if arr.flags.writeable:
                    try:
                        arr.setflags(write=False)
                    except ValueError:  # pragma: no cover - exotic views
                        continue
                    flipped.append(arr)
        yield
    finally:
        for arr in flipped:
            try:
                arr.setflags(write=True)
            except ValueError:  # pragma: no cover - base was re-frozen
                pass


def _run_frozen(stage, data):
    try:
        return stage.transform_output(data)
    except ValueError as e:
        if "read-only" in str(e) or "not writeable" in str(e):
            raise ContractViolation(Diagnostic(
                rule="TM020",
                message=(f"{type(stage).__name__} wrote to an input buffer "
                         f"during transform (caught under TMOG_CHECK=1 "
                         f"write-protection): {e}"),
                stage_uid=stage.uid)) from e
        raise


def guarded_transform_output(stage, data) -> Tuple[str, object]:
    """``stage.transform_output(data)`` under the TM020/TM023 guards."""
    with _frozen(data):
        name, col = _run_frozen(stage, data)
        name2, col2 = _run_frozen(stage, data)
    if name != name2 or not columns_equal(col, col2):
        raise ContractViolation(Diagnostic(
            rule="TM023",
            message=(f"{type(stage).__name__} transform is "
                     f"non-deterministic: two runs over the same input "
                     f"produced different output for {name!r}"),
            stage_uid=stage.uid))
    return name, col


# ---------------------------------------------------------------------------
# Column comparison
# ---------------------------------------------------------------------------

def _parts(col) -> List[Tuple[str, object]]:
    vals = col.values
    if isinstance(vals, np.ndarray):
        parts = [("values", vals)]
    else:
        parts = [(a, getattr(vals, a, None))
                 for a in ("prediction", "raw_prediction", "probability")]
    parts.append(("mask", col.mask))
    return parts


def _arrays_match(a, b, rtol: Optional[float]) -> bool:
    if a is None or b is None:
        return (a is None) == (b is None)
    a, b = np.asarray(a), np.asarray(b)
    if a.shape != b.shape:
        return False
    if a.dtype == object or b.dtype == object:
        return all(_obj_eq(x, y) for x, y in zip(a.ravel(), b.ravel()))
    if rtol is None:
        return a.tobytes() == b.tobytes()
    return bool(np.allclose(a, b, rtol=rtol, atol=rtol, equal_nan=True))


def _obj_eq(x, y) -> bool:
    if isinstance(x, float) and isinstance(y, float):
        return x == y or (np.isnan(x) and np.isnan(y))
    try:
        return bool(x == y)
    except Exception:  # pragma: no cover - incomparable cells
        return x is y


def columns_equal(a, b) -> bool:
    """Byte-exact FeatureColumn equality (determinism contract)."""
    return all(_arrays_match(x, y, None)
               for (_, x), (_, y) in zip(_parts(a), _parts(b)))


def columns_close(a, b, rtol: float) -> bool:
    """FeatureColumn equality within ``rtol`` on float payloads; masks and
    object cells must match exactly (streaming-fit contract)."""
    for (name, x), (_, y) in zip(_parts(a), _parts(b)):
        tol = None if name == "mask" else rtol
        if not _arrays_match(x, y, tol):
            return False
    return True


# ---------------------------------------------------------------------------
# Streaming-fit conformance
# ---------------------------------------------------------------------------

def _chunk(data, size: int):
    n = len(data)
    return [data.slice(i, min(i + size, n)) for i in range(0, n, size)]


def _chunk_state(est, chunk):
    state = est.begin_fit()
    cols = [chunk[n] for n in est.input_names]
    return est.update_chunk(state, chunk, *cols)


def _model_output(est, model, data):
    return est.adopt_model(model).transform_output(data)[1]


def check_streaming_fit(est, data, chunk_sizes: Sequence[int] = (7, 64),
                        findings: Optional[Findings] = None,
                        ref_model=None) -> Findings:
    """Property-check one ``supports_streaming_fit`` estimator against
    ``data`` (a ColumnarDataset holding its input columns).

    TM022: ``fit_streaming`` at each chunk size must reproduce ``fit``'s
    transform output within ``est.streaming_fit_tol``.  TM021: states built
    independently per chunk must merge associatively — and, when the
    estimator declares ``streaming_order_insensitive``, commutatively.
    Merges run on deep copies because implementations may fold in place.
    ``ref_model`` (an already-fitted model for ``est``) skips the reference
    re-fit.
    """
    findings = findings if findings is not None else Findings()
    tol = float(est.streaming_fit_tol)
    name = type(est).__name__
    if ref_model is None:
        ref_model = est.fit(data)
    ref_out = ref_model.transform_output(data)[1]

    for cs in chunk_sizes:
        if cs >= len(data):
            continue
        m = est.fit_streaming(iter(_chunk(data, cs)))
        if not columns_close(ref_out, m.transform_output(data)[1], tol):
            findings.add(
                "TM022",
                f"{name}.fit_streaming(chunk_rows={cs}) diverges from fit "
                f"beyond tol={tol}", stage_uid=est.uid)

    # associativity over three uneven chunks
    n = len(data)
    if n >= 6:
        cuts = [0, n // 4 or 1, n // 2 + 1, n]
        states = [_chunk_state(est, data.slice(cuts[i], cuts[i + 1]))
                  for i in range(3)]

        def merged(order, shape) -> object:
            s = [copy.deepcopy(states[i]) for i in order]
            if shape == "left":
                return est.merge_states(est.merge_states(s[0], s[1]), s[2])
            return est.merge_states(s[0], est.merge_states(s[1], s[2]))

        left = _model_output(est, est.finish_fit(merged((0, 1, 2), "left")),
                             data)
        right = _model_output(est, est.finish_fit(merged((0, 1, 2), "right")),
                              data)
        if not columns_close(left, right, tol):
            findings.add(
                "TM021",
                f"{name}.merge_states is not associative: "
                f"(a+b)+c != a+(b+c) beyond tol={tol}", stage_uid=est.uid)
        if est.streaming_order_insensitive:
            rev = _model_output(
                est, est.finish_fit(merged((2, 1, 0), "left")), data)
            if not columns_close(left, rev, tol):
                findings.add(
                    "TM021",
                    f"{name}.merge_states is order-sensitive but the "
                    f"estimator declares streaming_order_insensitive",
                    stage_uid=est.uid)
    # leave the estimator wired to the reference model for callers that
    # continue executing the DAG
    est.adopt_model(ref_model)
    return findings


def check_warm_start(est, data, chunk_rows: int = 16,
                     split_frac: float = 0.6,
                     findings: Optional[Findings] = None) -> Findings:
    """TM027 — warm-start equivalence for one streamable estimator.

    The contract ``OpWorkflow.refresh`` builds on: a fit state
    accumulated over OLD chunks, round-tripped through the estimator's
    ``export_fit_state``/``import_fit_state`` hooks (the persisted-model
    path), then updated with NEW chunks, must finish to the same model —
    within the declared ``streaming_fit_tol`` — as one fresh streaming
    fit over old+new.  An export hook that drops state (a count, a
    tie-break position, an RNG cursor) passes TM021/TM022 and still
    breaks every refresh; this check pins it.
    """
    findings = findings if findings is not None else Findings()
    n = len(data)
    if n < 8:
        return findings
    tol = float(est.streaming_fit_tol)
    name = type(est).__name__
    cut = max(1, int(n * split_frac))

    def run(chunks):
        state = est.begin_fit()
        for c in chunks:
            state = est.update_chunk(state, c,
                                     *[c[nm] for nm in est.input_names])
        return state

    fresh = est.finish_fit(run(_chunk(data, chunk_rows)))
    fresh_out = _model_output(est, fresh, data)

    state_old = run(_chunk(data.slice(0, cut), chunk_rows))
    # the export/import round trip is part of the contract: a refresh
    # resumes from the PERSISTED state, never the live object
    restored = est.import_fit_state(
        copy.deepcopy(est.export_fit_state(state_old)))
    for c in _chunk(data.slice(cut, n), chunk_rows):
        restored = est.update_chunk(restored, c,
                                    *[c[nm] for nm in est.input_names])
    warm_out = _model_output(est, est.finish_fit(restored), data)
    if not columns_close(fresh_out, warm_out, tol):
        findings.add(
            "TM027",
            f"{name} warm-start diverges: import(export(state(old))) + "
            f"new chunks != fresh streaming fit over old+new beyond "
            f"tol={tol}", stage_uid=est.uid)
    return findings


def check_fold_merge(est, data, num_folds: int = 4, chunk_rows: int = 16,
                     seed: int = 42,
                     findings: Optional[Findings] = None) -> Findings:
    """TM029 — fold-tagged state merge equivalence for one streamable
    estimator (the contract streaming workflow-CV builds on,
    workflow/streaming_cv.py).

    Rows are assigned to ``num_folds`` folds per GLOBAL row id
    (``selector.validators.make_folds``) and per-fold states accumulated
    chunk by chunk — exactly the fold-tagged accumulation the streaming
    CV driver performs.  For every fold k the COMPLEMENT merge must:

    * be merge-tree-shape invariant: ``(a+b)+c == a+(b+c)`` over the
      complement's fold states within ``streaming_fit_tol``;
    * be fold-PERMUTATION invariant when the estimator declares
      ``streaming_order_insensitive`` (tie-break ordering makes counting
      fits legitimately order-sensitive, mirroring TM021);
    * match the in-core fit over the complement's rows in fold-grouped
      order (the row order a merged fold state represents) within
      ``streaming_fit_tol`` — the refit-per-fold equivalence that makes
      CV-from-merged-states honest.
    """
    from ..selector.validators import make_folds

    findings = findings if findings is not None else Findings()
    n = len(data)
    if n < num_folds * 2:
        return findings
    tol = float(est.streaming_fit_tol)
    name = type(est).__name__
    folds = make_folds(n, num_folds, seed=seed)

    states = [est.begin_fit() for _ in range(num_folds)]
    for i in range(0, n, chunk_rows):
        chunk = data.slice(i, min(i + chunk_rows, n))
        g = folds[i:i + len(chunk)]
        for k in range(num_folds):
            idx = np.where(g == k)[0]
            if not len(idx):
                continue
            sub = chunk.take(idx)
            cols = [sub[nm] for nm in est.input_names]
            states[k] = est.update_chunk(states[k], sub, *cols)

    def merged(order, shape="left"):
        parts = [copy.deepcopy(states[j]) for j in order]
        if shape == "right" and len(parts) >= 3:
            out = parts[-1]
            for p in reversed(parts[:-1]):
                out = est.merge_states(p, out)
            return out
        out = parts[0]
        for p in parts[1:]:
            out = est.merge_states(out, p)
        return out

    for k in range(num_folds):
        comp = [j for j in range(num_folds) if j != k]
        left = _model_output(est, est.finish_fit(merged(comp, "left")),
                             data)
        right = _model_output(est, est.finish_fit(merged(comp, "right")),
                              data)
        if not columns_close(left, right, tol):
            findings.add(
                "TM029",
                f"{name} fold-complement merge is not associative: the "
                f"merge-tree shape moves fold {k}'s complement model "
                f"beyond tol={tol}", stage_uid=est.uid)
        if est.streaming_order_insensitive:
            rev = _model_output(
                est, est.finish_fit(merged(list(reversed(comp)), "left")),
                data)
            if not columns_close(left, rev, tol):
                findings.add(
                    "TM029",
                    f"{name} fold-complement merge is fold-order "
                    f"sensitive but the estimator declares "
                    f"streaming_order_insensitive (fold {k})",
                    stage_uid=est.uid)
        # in-core reference over the complement rows in FOLD-GROUPED
        # order — the row order the merged state represents
        ref_rows = np.concatenate(
            [np.where(folds == j)[0] for j in comp])
        sub_ds = data.take(ref_rows)
        ref_cols = [sub_ds[nm] for nm in est.input_names]
        ref_out = _model_output(est, est.fit_columns(sub_ds, *ref_cols),
                                data)
        if not columns_close(left, ref_out, tol):
            findings.add(
                "TM029",
                f"{name} merged fold-complement state diverges from the "
                f"in-core fit over fold {k}'s complement rows beyond "
                f"tol={tol} — CV from merged fold states would not match "
                f"refit-per-fold", stage_uid=est.uid)
    return findings


# ---------------------------------------------------------------------------
# Sharding / SPMD contracts (TM024-TM026) — the mesh-era runtime half of
# the shard-safety lint (analysis/shard_lint.py).  Like the streaming
# checks these are property-check entry points that COLLECT into
# ``Findings``; scripts/tier1.sh runs them on the multichip smoke under
# TMOG_CHECK=1 (examples/bench_multichip.py --smoke).
# ---------------------------------------------------------------------------

def _pad_sweep_inputs(X, y, weight_ctxs, extra_rows: int, seed: int = 7):
    """Append ``extra_rows`` garbage rows carrying ZERO weight in every
    fold context — the exact contract ``shard_sweep_inputs`` documents
    (pad rows must be inert through every weighted reduction).  Garbage
    (not zero) feature values so a pad leak actually moves the metrics."""
    rng = np.random.default_rng(seed)
    pad_X = rng.normal(size=(extra_rows, X.shape[1])).astype(X.dtype)
    Xp = np.concatenate([X, pad_X])
    yp = np.concatenate([np.asarray(y, np.float32),
                         np.zeros(extra_rows, np.float32)])
    zeros = np.zeros(extra_rows, np.float32)
    ctxs = [(np.concatenate([np.asarray(w_tr, np.float32), zeros]),
             np.concatenate([np.asarray(w_ev, np.float32), zeros]))
            for w_tr, w_ev in weight_ctxs]
    return Xp, yp, ctxs


def _run_group(make_group, mesh, X, y, weight_ctxs):
    group = make_group()
    if mesh is not None:
        group.with_mesh(mesh)
    M = group.run(X, y, weight_ctxs)
    if M is None:
        raise ValueError(
            f"{type(group).__name__} declined the batched program "
            f"(mesh={'yes' if mesh is not None else 'no'}); pick a "
            f"mesh-capable group for the sharding contract checks")
    return np.asarray(M, np.float64)


def check_pad_invariance(make_group, X, y, weight_ctxs, mesh, *,
                         extra_rows: Optional[int] = None,
                         tol: float = 5e-3,
                         findings: Optional[Findings] = None) -> Findings:
    """TM024: a sharded sweep's metrics must be invariant to the row
    padding used to tile the mesh's data axis.

    Re-runs ``make_group()``'s batched program with ``n_rows`` padded to
    the next shard multiple (``extra_rows`` garbage rows at zero fold
    weight — defaults to one full data-axis tile so the internal pad
    amount provably changes) and asserts the (C, F) metric matrix matches
    within ``tol`` (bit-level equality is not required: shard boundaries
    move, so f32 reduction ORDER legitimately changes).
    """
    findings = findings if findings is not None else Findings()
    X = np.asarray(X, np.float32)
    if extra_rows is None:
        if mesh is not None:
            from ..parallel.mesh import next_shard_pad

            extra_rows = next_shard_pad(mesh, X.shape[0])
        else:
            extra_rows = 4
    base = _run_group(make_group, mesh, X, y, weight_ctxs)
    Xp, yp, ctxs = _pad_sweep_inputs(X, y, weight_ctxs, extra_rows)
    padded = _run_group(make_group, mesh, Xp, yp, ctxs)
    if base.shape != padded.shape or not np.allclose(
            base, padded, rtol=tol, atol=tol, equal_nan=True):
        delta = (float(np.max(np.abs(base - padded)))
                 if base.shape == padded.shape else float("inf"))
        findings.add(
            "TM024",
            f"pad-invariance violation: +{extra_rows} zero-weight rows "
            f"moved the sweep metrics by {delta:.3e} (> tol={tol}); "
            f"padding rows are reaching a reduction unmasked")
    return findings


def check_mesh_parity(make_group, X, y, weight_ctxs, mesh, *,
                      sample_rows: int = 512, tol: float = 2e-2,
                      findings: Optional[Findings] = None) -> Findings:
    """TM025: the mesh-sharded batched program must agree with the
    single-device program on a subsampled unit (stride subsample keeps
    class balance); disagreement beyond ``tol`` means the sharded
    rewrite changed the math, not just the layout."""
    findings = findings if findings is not None else Findings()
    X = np.asarray(X, np.float32)
    n = X.shape[0]
    stride = max(1, n // max(1, min(sample_rows, n)))
    idx = np.arange(0, n, stride)[:sample_rows]
    Xs = np.ascontiguousarray(X[idx])
    ys = np.asarray(y, np.float32)[idx]
    ctxs = [(np.ascontiguousarray(np.asarray(w_tr, np.float32)[idx]),
             np.ascontiguousarray(np.asarray(w_ev, np.float32)[idx]))
            for w_tr, w_ev in weight_ctxs]
    single = _run_group(make_group, None, Xs, ys, ctxs)
    sharded = _run_group(make_group, mesh, Xs, ys, ctxs)
    if single.shape != sharded.shape or not np.allclose(
            single, sharded, rtol=tol, atol=tol, equal_nan=True):
        delta = (float(np.max(np.abs(single - sharded)))
                 if single.shape == sharded.shape else float("inf"))
        findings.add(
            "TM025",
            f"mesh-vs-single-device divergence: sharded metrics differ "
            f"from the single-device program by {delta:.3e} "
            f"(> tol={tol}) on a {len(idx)}-row subsample")
    return findings


def check_checkpoint_roundtrip(directory: str, fingerprint,
                               findings: Optional[Findings] = None
                               ) -> Findings:
    """TM026: a sweep checkpoint must round-trip byte-exactly — the
    manifest on disk, imported by a FRESH manager and re-exported
    through the same canonical writer, must reproduce the original
    bytes.  Anything less means resume state silently drifts across
    export/import generations."""
    from ..utils.jsonio import dumps_canonical
    from ..workflow.checkpoint import (SWEEP_CHECKPOINT_JSON,
                                       SweepCheckpointManager)

    findings = findings if findings is not None else Findings()
    path = os.path.join(directory, SWEEP_CHECKPOINT_JSON)
    with open(path, encoding="utf-8") as f:
        raw = f.read()
    manager = SweepCheckpointManager(directory, fingerprint)
    if not manager.load():
        raise ValueError(f"no sweep checkpoint in {directory!r}")
    re_exported = dumps_canonical(manager.export_doc())
    if re_exported != raw:
        findings.add(
            "TM026",
            f"checkpoint fingerprint round-trip is not byte-exact: "
            f"re-export differs from {path} "
            f"({len(raw)} vs {len(re_exported)} byte(s)); export -> "
            f"import -> re-export must be the identity")
    return findings


def check_sharding_contracts(make_group, X, y, weight_ctxs, mesh, *,
                             checkpoint_dir: Optional[str] = None,
                             checkpoint_fingerprint=None,
                             findings: Optional[Findings] = None
                             ) -> Findings:
    """All three sharding contracts (TM024-TM026) in one audit — the
    entry point the multichip smoke runs under ``TMOG_CHECK=1``."""
    findings = findings if findings is not None else Findings()
    check_pad_invariance(make_group, X, y, weight_ctxs, mesh,
                         findings=findings)
    check_mesh_parity(make_group, X, y, weight_ctxs, mesh,
                      findings=findings)
    if checkpoint_dir is not None:
        check_checkpoint_roundtrip(checkpoint_dir, checkpoint_fingerprint,
                                   findings=findings)
    return findings


def check_accum_tolerance(X, y, *, tol: float = 1e-3, max_depth: int = 6,
                          n_rounds: int = 8, n_bins: int = 16,
                          seed: int = 7,
                          findings: Optional[Findings] = None) -> Findings:
    """TM028 — the bf16 histogram-ACCUMULATION tolerance probe.

    ``TMOG_MATRIX_PRECISION=bf16`` lets the tree kernels accumulate the
    per-level gradient/hessian histogram partials in bf16 (the operands
    already ride bf16 on accelerators).  That opt-in is only sound where
    the metric drift it introduces stays within ``tol`` — this probe
    grows the SAME small boosted chain twice (explicit ``acc_bf16``
    flags, independent of env/backend gates so the comparison is real on
    any backend) and fires TM028 when the train-AuPR drift exceeds
    ``tol``.  Run next to the TM024 pad-invariance gate under
    TMOG_CHECK=1 (the tier-1 trees smoke does both).
    """
    import jax.numpy as jnp

    from ..evaluators.metrics import aupr
    from ..models.gbdt_kernels import (_gbt_chain_rounds_jit, apply_bins,
                                       quantile_bins)

    findings = findings if findings is not None else Findings()
    X = np.asarray(X, np.float32)
    y = np.nan_to_num(np.asarray(y, np.float32))
    n = len(y)
    edges = quantile_bins(X, n_bins, seed=seed)
    binned = apply_bins(jnp.asarray(X), jnp.asarray(edges))
    W = jnp.ones((1, n), jnp.float32)
    vi = jnp.zeros(1, jnp.int32)
    vecs = dict(depth_lim=jnp.full((1,), max_depth, jnp.int32),
                lams=jnp.ones(1, jnp.float32),
                mcws=jnp.zeros(1, jnp.float32),
                migs=jnp.zeros(1, jnp.float32),
                mins_=jnp.ones(1, jnp.float32),
                lrs=jnp.full((1,), 0.3, jnp.float32),
                mgrs=jnp.zeros(1, jnp.float32))

    def run(acc_bf16: bool) -> float:
        Fm = jnp.zeros((1, n), jnp.float32)
        Fm, *_rest = _gbt_chain_rounds_jit(
            binned, jnp.asarray(y), W, Fm, vi, vecs["depth_lim"],
            vecs["lams"], vecs["mcws"], vecs["migs"], vecs["mins_"],
            vecs["lrs"], vecs["mgrs"], n_rounds, max_depth, n_bins,
            "binary", False, False, acc_bf16=acc_bf16)
        import jax

        p = np.asarray(jax.nn.sigmoid(Fm[0]))
        return float(aupr(y, p))

    m_f32 = run(False)
    m_bf16 = run(True)
    drift = abs(m_f32 - m_bf16)
    if drift > tol:
        findings.add(
            "TM028",
            f"bf16 histogram-accumulation drift {drift:.3e} exceeds "
            f"tol={tol} (f32 AuPR {m_f32:.4f} vs bf16-accumulated "
            f"{m_bf16:.4f}); keep TMOG_MATRIX_PRECISION=f32 for this "
            f"workload")
    return findings


# ---------------------------------------------------------------------------
# Collective-ledger contracts (TM073/TM074) — the runtime half of the
# TM07x collective-safety family (analysis/pod_lint.py is the static
# half).  Under TMOG_CHECK=1 every host collective the pod issues
# (distributed/runtime.py) appends ``(seq, kind, call site)`` to the
# per-process ledger and carries that header inside its payload, so a
# pod whose processes drift onto different collective sequences fails
# with BOTH divergent sites named (TM074) instead of hanging; a
# TMOG_COLLECTIVE_TIMEOUT watchdog turns the residual hang (a peer that
# never arrives at all) into a ledger dump in the flight recorder
# (TM073).
# ---------------------------------------------------------------------------

#: seconds a single host collective may block before the watchdog fires;
#: unset/empty disables the watchdog
COLLECTIVE_TIMEOUT_ENV = "TMOG_COLLECTIVE_TIMEOUT"


def collective_timeout() -> Optional[float]:
    raw = os.environ.get(COLLECTIVE_TIMEOUT_ENV, "").strip()
    if not raw:
        return None
    try:
        t = float(raw)
    except ValueError:
        return None
    return t if t > 0 else None


class CollectiveLedger:
    """Per-process record of every host collective issued.

    Keeps a RUNNING digest over the full ``(seq, kind, site)`` history
    (so two processes with identical digests provably issued identical
    sequences) plus a bounded tail for attribution; memory stays O(tail)
    over arbitrarily long trains.
    """

    def __init__(self, tail: int = 64):
        self.seq = 0
        self.tail: collections.deque = collections.deque(maxlen=tail)
        self._hash = hashlib.blake2s()
        self._suspended = 0
        self._lock = threading.Lock()

    def record(self, kind: str, site: str) -> Optional[Tuple[int, str, str]]:
        with self._lock:
            if self._suspended:
                return None
            self.seq += 1
            entry = (self.seq, kind, site)
            self._hash.update(f"{self.seq}|{kind}|{site}\n".encode())
            self.tail.append(entry)
            return entry

    def digest(self) -> str:
        with self._lock:
            return self._hash.hexdigest()

    @contextlib.contextmanager
    def suspended(self):
        """Recording off for the duration — the consistency check's own
        exchange must not perturb the ledger it is auditing."""
        with self._lock:
            self._suspended += 1
        try:
            yield
        finally:
            with self._lock:
                self._suspended -= 1

    def snapshot(self, process: int = 0) -> Dict[str, Any]:
        with self._lock:
            return {"process": int(process), "seq": self.seq,
                    "digest": self._hash.hexdigest(),
                    "tail": [list(e) for e in self.tail]}


_COLLECTIVE_LEDGER = CollectiveLedger()


def collective_ledger() -> CollectiveLedger:
    return _COLLECTIVE_LEDGER


def reset_collective_ledger(tail: int = 64) -> CollectiveLedger:
    """Fresh process-wide ledger (test seam)."""
    global _COLLECTIVE_LEDGER
    _COLLECTIVE_LEDGER = CollectiveLedger(tail=tail)
    return _COLLECTIVE_LEDGER


_LEDGER_INTERNAL = (os.path.join("analysis", "contracts.py"),
                    os.path.join("distributed", "runtime.py"))


def _call_site() -> str:
    """First stack frame outside the collective plumbing — the line the
    divergence report should point at."""
    f = sys._getframe(1)
    while f is not None:
        fn = f.f_code.co_filename
        if not fn.endswith(_LEDGER_INTERNAL):
            return f"{os.path.basename(fn)}:{f.f_lineno}"
        f = f.f_back
    return "<unknown>"


def record_collective(kind: str, name: str = ""
                      ) -> Optional[Tuple[int, str, str]]:
    """Ledger hook the pod collectives call.  Returns the new ``(seq,
    kind, site)`` entry when the ledger is on (``TMOG_CHECK=1`` and not
    suspended), else None — the runtime only header-wraps payloads when
    an entry comes back."""
    if not checks_enabled():
        return None
    label = f"{kind}({name})" if name else kind
    return _COLLECTIVE_LEDGER.record(label, _call_site())


def verify_collective_headers(headers: Sequence[Sequence]) -> None:
    """TM074 — every process's in-band ``(seq, kind, site)`` header for
    ONE paired exchange must agree on seq and kind; a mismatch means the
    pod's collective sequences split, and both sites are named."""
    base = tuple(headers[0])
    for i, h in enumerate(headers):
        h = tuple(h)
        if (h[0], h[1]) != (base[0], base[1]):
            raise ContractViolation(Diagnostic(
                rule="TM074",
                message=(
                    f"collective-ledger divergence: process 0 is at "
                    f"ledger seq {base[0]} issuing {base[1]} from "
                    f"{base[2]}, but process {i} is at seq {h[0]} "
                    f"issuing {h[1]} from {h[2]} — the pod's collective "
                    f"sequences have split; lint the code between the "
                    f"two sites (TM070/TM071)"),
                location=str(base[2])))


def _first_divergent(tail_a, tail_b):
    da = {int(e[0]): (str(e[1]), str(e[2])) for e in tail_a}
    db = {int(e[0]): (str(e[1]), str(e[2])) for e in tail_b}
    for seq in sorted(set(da) & set(db)):
        if da[seq] != db[seq]:
            return seq, da[seq], db[seq]
    only = sorted(set(da) ^ set(db))
    if only:
        seq = only[0]
        return seq, da.get(seq), db.get(seq)
    return None, None, None


def _entry_str(e) -> str:
    return f"{e[0]} at {e[1]}" if e is not None else "nothing (never issued)"


def diff_collective_ledgers(snapshots: Sequence[Dict[str, Any]]
                            ) -> Findings:
    """Compare per-process ledger snapshots (``CollectiveLedger
    .snapshot``); one TM074 finding per process that diverged from
    process 0, naming the first divergent entry on BOTH sides."""
    findings = Findings()
    base = snapshots[0]
    for s in snapshots[1:]:
        if s["seq"] == base["seq"] and s["digest"] == base["digest"]:
            continue
        seq, a, b = _first_divergent(base["tail"], s["tail"])
        where = (f"first divergence at ledger seq {seq}: process "
                 f"{base['process']} issued {_entry_str(a)}, process "
                 f"{s['process']} issued {_entry_str(b)}"
                 if seq is not None else
                 f"divergence precedes the retained ledger tails "
                 f"(seq {base['seq']} vs {s['seq']})")
        findings.add(
            "TM074",
            f"collective-ledger divergence between process "
            f"{base['process']} (seq {base['seq']}, digest "
            f"{base['digest'][:12]}) and process {s['process']} (seq "
            f"{s['seq']}, digest {s['digest'][:12]}); {where}")
    return findings


def check_collective_consistency(pod, label: str = "") -> None:
    """TM074 pass-boundary audit: exchange ledger digests across the pod
    and raise :class:`ContractViolation` on any divergence, naming the
    first divergent entry of both processes.  No-op unless
    ``TMOG_CHECK=1`` and the pod is active.  The exchange itself runs
    with recording suspended so the audit never perturbs the ledger it
    audits."""
    if not checks_enabled() or pod is None or \
            not getattr(pod, "active", False):
        return
    led = _COLLECTIVE_LEDGER
    with led.suspended():
        snaps = pod.allgather_obj(led.snapshot(process=pod.process_index))
    findings = diff_collective_ledgers(snaps)
    if findings:
        from ..obs.flight import record_event

        record_event("collective.divergence", label=label,
                     messages=[d.message for d in findings])
        raise ContractViolation(findings.diagnostics[0])


class CollectiveWatchdog:
    """TM073 — armed around one blocking host collective.

    If the collective has not returned within ``timeout`` seconds
    (default: the ``TMOG_COLLECTIVE_TIMEOUT`` env; None disarms), the
    per-process ledger tail is dumped into the flight recorder and
    stderr and the process exits non-zero — a hung collective never
    returns, so an exception from the timer thread could not unblock
    it.  ``on_hang`` (called with the TM073 :class:`Diagnostic`)
    replaces the exit for tests.
    """

    def __init__(self, kind: str, site: str,
                 timeout: Optional[float] = None,
                 ledger: Optional[CollectiveLedger] = None,
                 on_hang=None):
        self.kind = kind
        self.site = site
        self.timeout = collective_timeout() if timeout is None else timeout
        self.ledger = ledger if ledger is not None else _COLLECTIVE_LEDGER
        self.on_hang = on_hang
        self._timer: Optional[threading.Timer] = None

    def diagnostic(self) -> Diagnostic:
        return Diagnostic(
            rule="TM073",
            message=(f"host collective {self.kind} did not complete "
                     f"within {self.timeout}s — a peer process never "
                     f"arrived (ledger seq {self.ledger.seq}; tail "
                     f"dumped to the flight recorder)"),
            location=str(self.site))

    def __enter__(self) -> "CollectiveWatchdog":
        if self.timeout is not None and self.timeout > 0:
            self._timer = threading.Timer(self.timeout, self._fire)
            self._timer.daemon = True
            self._timer.start()
        return self

    def __exit__(self, *exc) -> bool:
        if self._timer is not None:
            self._timer.cancel()
        return False

    def _fire(self) -> None:
        from ..obs.flight import record_event, record_events

        diag = self.diagnostic()
        tail = list(self.ledger.tail)
        record_event("collective.hang", collective=self.kind,
                     site=self.site, seq=self.ledger.seq,
                     timeoutS=self.timeout)
        record_events("collective.ledger",
                      [{"seq": s, "kind": k, "site": st}
                       for s, k, st in tail])
        if self.on_hang is not None:
            self.on_hang(diag)
            return
        sys.stderr.write(diag.format() + "\n")
        for s, k, st in tail:
            sys.stderr.write(f"  ledger[{s}] {k} @ {st}\n")
        sys.stderr.flush()
        os._exit(74)


def check_workflow_contracts(wf, data=None,
                             chunk_sizes: Sequence[int] = (7, 64),
                             ) -> Findings:
    """Walk a workflow's DAG sequentially, property-checking every
    streaming-capable estimator (TM021/TM022) and running every transform
    under the COW/determinism guards (TM020/TM023).  Returns the combined
    ``Findings``; guard violations are converted to findings rather than
    raised, so one audit reports everything."""
    from ..stages.base import Estimator, Transformer
    from ..workflow.dag import compute_dag

    findings = Findings()
    dag = compute_dag(wf.result_features)
    if data is None:
        data = wf.generate_raw_data()

    for layer in dag.non_generator_layers():
        for stage in layer:
            if isinstance(stage, Estimator):
                model = stage.fit(data)
                if bool(stage.supports_streaming_fit):
                    try:
                        check_streaming_fit(stage, data,
                                            chunk_sizes=chunk_sizes,
                                            findings=findings,
                                            ref_model=model)
                        check_warm_start(stage, data, findings=findings)
                        check_fold_merge(stage, data, findings=findings)
                    except ContractViolation as e:
                        findings.diagnostics.append(e.diagnostic)
            elif isinstance(stage, Transformer):
                model = stage
            else:  # pragma: no cover - unreachable in valid DAGs
                continue
            try:
                name, col = guarded_transform_output(model, data)
            except ContractViolation as e:
                findings.diagnostics.append(e.diagnostic)
                name, col = model.transform_output(data)
            data = data.with_columns({name: col})
    return findings
