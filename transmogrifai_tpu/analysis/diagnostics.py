"""Shared diagnostic machinery for the three lint rule families.

Every finding is a ``Diagnostic`` with a stable rule id (``TM0xx``), a
severity, and a location — ``file:line`` for source-level (trace) findings,
a stage uid for DAG/contract findings — so CI output is greppable and
suppressions are precise.  ``Findings`` is the ordered container all
analyzers return; the CLI exits non-zero when it is non-empty.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Iterable, List, Optional

__all__ = ["Diagnostic", "Findings", "PipelineLintError",
           "ContractViolation", "RULES", "ERROR", "WARNING",
           "JSON_SCHEMA_VERSION"]

ERROR = "error"
WARNING = "warning"

#: rule catalog: id -> (default severity, one-line title).  The authoritative
#: prose catalog (what each rule means, how to fix, how to suppress) lives in
#: docs/static-analysis.md.
RULES: Dict[str, Any] = {
    # -- DAG lint (analysis/linter.py) ----------------------------------
    "TM001": (ERROR, "dangling input column: no stage in the DAG produces it"),
    "TM002": (ERROR, "shadowed column: a stage output overwrites an earlier "
                     "column of the same name"),
    "TM003": (ERROR, "duplicate output column: two stages emit the same name"),
    "TM004": (ERROR, "feature-type mismatch at a stage boundary"),
    "TM005": (WARNING, "dead stage: computed but never consumed by a result "
                       "feature"),
    "TM006": (ERROR, "label leakage: response-derived feature wired into a "
                     "predictor input"),
    # -- runtime contracts (analysis/contracts.py, TMOG_CHECK=1) --------
    "TM020": (ERROR, "copy-on-write violation: stage wrote to an input "
                     "buffer during transform"),
    "TM021": (ERROR, "merge_states is not associative"),
    "TM022": (ERROR, "fit_streaming diverges from fit beyond the declared "
                     "tolerance"),
    "TM023": (ERROR, "non-deterministic transform: same input produced "
                     "different bytes"),
    # -- sharding runtime contracts (analysis/contracts.py, TMOG_CHECK=1)
    "TM024": (ERROR, "pad-invariance violation: sharded sweep metrics "
                     "change with the row padding used to tile the mesh"),
    "TM025": (ERROR, "mesh-vs-single-device divergence: the sharded sweep "
                     "program disagrees with the single-device program"),
    "TM026": (ERROR, "checkpoint fingerprint round-trip is not byte-exact "
                     "(export -> import -> re-export)"),
    "TM027": (ERROR, "warm-start refresh diverges: merge(restored_state, "
                     "fit_state(new_chunks)) does not finish to the fresh "
                     "streaming fit over old+new within the declared "
                     "tolerance"),
    "TM028": (ERROR, "bf16 histogram-accumulation drift exceeds the "
                     "tolerance: a fit with bf16 gradient/hessian "
                     "accumulation moves the metric beyond the f32 "
                     "reference by more than the declared bound"),
    "TM029": (ERROR, "fold-tagged state merge diverges: the merged "
                     "fold-complement state is not associative / "
                     "fold-permutation invariant, or does not match the "
                     "in-core fold-complement fit within the declared "
                     "tolerance (streaming workflow-CV equivalence)"),
    # -- trace safety (analysis/trace_lint.py) --------------------------
    "TM030": (ERROR, "host sync on a traced value inside a jit function"),
    "TM031": (WARNING, "jit closure over an enclosing Python scalar: fresh "
                       "trace constant per call (recompile hazard)"),
    "TM032": (ERROR, "static argument declared on a parameter with an "
                     "unhashable default"),
    # -- shard safety (analysis/shard_lint.py) --------------------------
    "TM040": (ERROR, "cross-shard reduction inside a shard_map body with "
                     "no psum/pmean collective (pad-invariance hazard)"),
    "TM041": (ERROR, "axis name not defined by the enclosing mesh"),
    "TM042": (ERROR, "device_put / host round-trip inside a sweep inner "
                     "loop (per-iteration transfer)"),
    "TM043": (ERROR, "donated buffer reused after donation"),
    "TM044": (ERROR, "NamedSharding spec rank exceeds the operand rank"),
    "TM045": (ERROR, "shard_map in_specs/out_specs arity disagrees with "
                     "the wrapped function"),
    "TM046": (ERROR, "broad except around sweep-unit execution that does "
                     "not route through the shared device-loss classifier "
                     "(parallel.elastic)"),
    "TM047": (ERROR, "durable write reachable from pod-context code "
                     "without a process_index == 0 / is_coordinator() "
                     "guard (every pod process would race the artifact)"),
    # -- concurrency / durability (analysis/concur_lint.py) -------------
    "TM050": (ERROR, "non-atomic JSON/benchmark write: bypasses "
                     "write_json_atomic / the tmp + os.replace pattern"),
    "TM051": (ERROR, "tempfile created without finally/context-manager "
                     "cleanup"),
    "TM052": (ERROR, "shared mutable state touched from a thread-pool "
                     "closure without a lock"),
    "TM053": (ERROR, "lock acquisition order inversion (deadlock hazard)"),
    # -- event-time ingestion (analysis/linter.py, readers/events.py) ---
    "TM060": (ERROR, "event-time leakage: a predictor reads event data not "
                     "provably before the key's cutoff (no cutoff spec, or "
                     "a response event field consumed as a predictor)"),
    # -- collective safety (analysis/pod_lint.py + contracts.py) --------
    "TM070": (ERROR, "host collective reachable only under a process-"
                     "divergent guard (is_coordinator / process_index / "
                     "per-host counters): some pod processes skip it and "
                     "the rest deadlock"),
    "TM071": (ERROR, "collective-order mismatch: sibling branches or an "
                     "early return/continue path of one pod-aware function "
                     "issue host collectives in different sequences"),
    "TM072": (ERROR, "non-deterministic fold of gathered partials: "
                     "iterating a set / unsorted dict / os.listdir to "
                     "combine allgathered state or build a durable artifact "
                     "in pod-aware code (breaks the byte-identical-on-"
                     "every-host contract)"),
    "TM073": (ERROR, "collective watchdog timeout: a host collective did "
                     "not complete within TMOG_COLLECTIVE_TIMEOUT seconds "
                     "(ledger tail dumped to the flight recorder)"),
    "TM074": (ERROR, "collective-ledger divergence: processes issued "
                     "different collective sequences (kind/site mismatch "
                     "at the same ledger seq)"),
}

#: version of the ``tmog lint --json`` report shape (bumped with any
#: field addition/removal; consumers gate on it instead of sniffing keys)
JSON_SCHEMA_VERSION = 3


@dataclasses.dataclass
class Diagnostic:
    """One finding: stable rule id + where + what."""

    rule: str
    message: str
    severity: str = ERROR
    #: DAG/contract findings: the offending stage's uid
    stage_uid: Optional[str] = None
    #: source findings: "path.py:42"
    location: Optional[str] = None

    def format(self) -> str:
        where = self.location or (f"stage {self.stage_uid}"
                                  if self.stage_uid else "<pipeline>")
        return f"{where}: {self.rule} [{self.severity}] {self.message}"

    def to_json(self) -> Dict[str, Any]:
        return {"rule": self.rule, "severity": self.severity,
                "message": self.message, "stageUid": self.stage_uid,
                "location": self.location}


class Findings:
    """Ordered collection of diagnostics from one analysis run."""

    def __init__(self, diagnostics: Optional[Iterable[Diagnostic]] = None):
        self.diagnostics: List[Diagnostic] = list(diagnostics or ())

    def add(self, rule: str, message: str, *, stage_uid: Optional[str] = None,
            location: Optional[str] = None,
            severity: Optional[str] = None) -> Diagnostic:
        default_sev = RULES.get(rule, (ERROR, ""))[0]
        d = Diagnostic(rule=rule, message=message,
                       severity=severity or default_sev,
                       stage_uid=stage_uid, location=location)
        self.diagnostics.append(d)
        return d

    def extend(self, other: "Findings") -> "Findings":
        self.diagnostics.extend(other.diagnostics)
        return self

    @property
    def errors(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == ERROR]

    @property
    def warnings(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == WARNING]

    def rules_fired(self) -> List[str]:
        return sorted({d.rule for d in self.diagnostics})

    def by_rule(self, rule: str) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.rule == rule]

    def __len__(self) -> int:
        return len(self.diagnostics)

    def __bool__(self) -> bool:
        return bool(self.diagnostics)

    def __iter__(self):
        return iter(self.diagnostics)

    def format(self) -> str:
        if not self.diagnostics:
            return "no findings"
        lines = [d.format() for d in self.diagnostics]
        lines.append(f"{len(self.errors)} error(s), "
                     f"{len(self.warnings)} warning(s)")
        return "\n".join(lines)

    def to_json(self) -> Dict[str, Any]:
        return {"schemaVersion": JSON_SCHEMA_VERSION,
                "findings": [d.to_json() for d in self.diagnostics],
                "errors": len(self.errors), "warnings": len(self.warnings)}


class PipelineLintError(ValueError):
    """Raised by ``OpWorkflow.train(validate=True)`` when the DAG lint finds
    error-severity problems — the fail-fast analogue of the reference's
    compile-time rejection.  Carries the full ``Findings``."""

    def __init__(self, findings: Findings):
        self.findings = findings
        super().__init__(
            "pipeline failed static validation "
            f"({len(findings.errors)} error(s)):\n" + findings.format())


class ContractViolation(AssertionError):
    """A runtime contract (TM02x) was broken under ``TMOG_CHECK=1``.
    Carries the diagnostic so harnesses can aggregate into ``Findings``."""

    def __init__(self, diagnostic: Diagnostic):
        self.diagnostic = diagnostic
        super().__init__(diagnostic.format())
