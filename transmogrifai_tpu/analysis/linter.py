"""DAG lint — pure static validation of a feature/stage DAG (TM00x).

Runs on an ``OpWorkflow``, ``StagesDAG`` or ``ExecutionPlan`` *before* any
data moves, the way the Scala reference's type system rejected mis-wired
DAGs at compile time:

* TM001 — dangling input: a stage reads a column no stage in the DAG
  produces (origin stage lost by deserialization or manual surgery).
* TM002 — shadowed column: a stage's output name collides with a raw
  (generator) column; ``with_columns`` would silently clobber the raw
  input for every later consumer.
* TM003 — duplicate output: two stages emit the same column name, so
  layer merge order decides which survives.
* TM004 — feature-type mismatch: the wired feature's semantic type does
  not conform to the consumer stage's declared ``input_types``
  (``stages/base.py``); the run-time analogue raises ``SchemaError`` at
  ``set_input`` time, this catches DAGs assembled by other means.
* TM005 — dead stage (warning): the execution plan would compute the
  stage, but nothing on the path to the result features consumes it.
* TM006 — label leakage: a response-derived feature reaches a predictor
  input.  Taint starts at raw response features and propagates through
  ordinary stages; ``label_input_positions`` (the declared label slots of
  label-aware stages like SanityChecker and the model selector) both
  absorb taint legitimately and mark where tainted *predictor* wires are
  an error.  Vectorizing a tainted feature is flagged at the vectorizer.

Diagnostics carry the stage uid plus the stage class's ``file:line`` so CI
output is clickable.
"""
from __future__ import annotations

import inspect
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from ..features.feature import Feature
from ..stages.base import PipelineStage
from ..types.feature_types import OPVector
from .diagnostics import Findings

__all__ = ["lint_dag", "lint_workflow", "lint_plan"]

_CLASS_LOC: Dict[type, Optional[str]] = {}


def _stage_location(stage: PipelineStage) -> Optional[str]:
    cls = type(stage)
    if cls not in _CLASS_LOC:
        try:
            f = inspect.getsourcefile(cls)
            _, line = inspect.getsourcelines(cls)
            _CLASS_LOC[cls] = f"{f}:{line}" if f else None
        except (OSError, TypeError):
            _CLASS_LOC[cls] = None
    return _CLASS_LOC[cls]


def _is_generator(stage: PipelineStage) -> bool:
    from ..stages.generator import FeatureGeneratorStage

    return isinstance(stage, FeatureGeneratorStage)


def lint_dag(dag, result_features: Optional[Sequence[Feature]] = None,
             suppress: Iterable[str] = ()) -> Findings:
    """Lint a ``StagesDAG``.  ``result_features`` enables the dead-stage
    rule (TM005); ``suppress`` drops listed rule ids from the report."""
    findings = Findings()
    suppress = set(suppress)

    # -- column production map -------------------------------------------
    produced: Dict[str, PipelineStage] = {}
    for layer in dag.layers:
        for s in layer:
            name = s.get_output().name
            prev = produced.get(name)
            if prev is None:
                produced[name] = s
            elif prev.uid != s.uid:
                if _is_generator(prev) and not _is_generator(s):
                    findings.add(
                        "TM002",
                        f"output {name!r} of {type(s).__name__} shadows the "
                        f"raw column produced by generator {prev.uid}",
                        stage_uid=s.uid, location=_stage_location(s))
                else:
                    findings.add(
                        "TM003",
                        f"output {name!r} emitted by both {prev.uid} "
                        f"({type(prev).__name__}) and {s.uid} "
                        f"({type(s).__name__})",
                        stage_uid=s.uid, location=_stage_location(s))

    # -- per-stage wiring checks -----------------------------------------
    for layer in dag.layers:
        for s in layer:
            if _is_generator(s):
                continue
            for i, f in enumerate(s.input_features):
                if f.name not in produced:
                    findings.add(
                        "TM001",
                        f"input {i} ({f.name!r}) of {type(s).__name__} is "
                        f"produced by no stage in the DAG",
                        stage_uid=s.uid, location=_stage_location(s))
                exp = s.expected_input_type(i)
                if exp is not None and not (
                        isinstance(f.ftype, type)
                        and issubclass(f.ftype, exp)):
                    findings.add(
                        "TM004",
                        f"input {i} ({f.name!r}) of {type(s).__name__}: "
                        f"expected {exp.__name__}, got "
                        f"{getattr(f.ftype, '__name__', f.ftype)!r}",
                        stage_uid=s.uid, location=_stage_location(s))

    # -- dead stages vs the result features (TM005) ----------------------
    if result_features is not None:
        needed: Set[str] = set()
        frontier: List[PipelineStage] = [
            produced[f.name] for f in result_features if f.name in produced]
        while frontier:
            s = frontier.pop()
            if s.uid in needed:
                continue
            needed.add(s.uid)
            for f in s.input_features:
                p = produced.get(f.name)
                if p is not None:
                    frontier.append(p)
        for layer in dag.layers:
            for s in layer:
                if not _is_generator(s) and s.uid not in needed:
                    findings.add(
                        "TM005",
                        f"{type(s).__name__} -> {s.get_output().name!r} is "
                        f"computed but consumed by no result feature",
                        stage_uid=s.uid, location=_stage_location(s))

    # -- label leakage (TM006) -------------------------------------------
    findings.extend(_lint_leakage(dag))

    if suppress:
        findings.diagnostics = [d for d in findings.diagnostics
                                if d.rule not in suppress]
    return findings


def _lint_leakage(dag) -> Findings:
    """Taint walk: raw responses taint; ordinary stages propagate; label
    slots absorb; tainted predictor wires are findings."""
    findings = Findings()
    tainted: Set[str] = set()
    for layer in dag.layers:
        for s in layer:
            out_name = s.get_output().name
            if _is_generator(s):
                if s.get_output().is_response:
                    tainted.add(out_name)
                continue
            label_pos = set(s.label_input_positions)
            offending = [
                (i, f) for i, f in enumerate(s.input_features)
                if f.name in tainted and i not in label_pos]
            is_vectorizer = (isinstance(s.output_type, type)
                             and issubclass(s.output_type, OPVector))
            if offending and (label_pos or is_vectorizer):
                names = ", ".join(f"{f.name!r} (input {i})"
                                  for i, f in offending)
                kind = ("predictor input of label-aware stage" if label_pos
                        else "featurizer input")
                findings.add(
                    "TM006",
                    f"response-derived feature(s) {names} wired into a "
                    f"{kind} of {type(s).__name__}",
                    stage_uid=s.uid, location=_stage_location(s))
                continue  # report the root cause once, don't cascade
            if offending:
                # plain transform of a response (e.g. label rescaling):
                # legitimate on its own; keep the taint flowing so a later
                # predictor-side consumer is still caught
                tainted.add(out_name)
    return findings


def lint_workflow(wf, suppress: Iterable[str] = ()) -> Findings:
    """Lint an ``OpWorkflow`` (or fitted ``OpWorkflowModel``) by
    reconstructing its stage DAG from the result features."""
    from ..workflow.dag import compute_dag

    return lint_dag(compute_dag(wf.result_features),
                    result_features=wf.result_features, suppress=suppress)


def lint_plan(plan, result_features: Optional[Sequence[Feature]] = None,
              suppress: Iterable[str] = ()) -> Findings:
    """Lint an ``ExecutionPlan`` (workflow/plan.py) via its source DAG."""
    return lint_dag(plan.dag, result_features=result_features,
                    suppress=suppress)
