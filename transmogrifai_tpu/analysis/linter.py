"""DAG lint — pure static validation of a feature/stage DAG (TM00x).

Runs on an ``OpWorkflow``, ``StagesDAG`` or ``ExecutionPlan`` *before* any
data moves, the way the Scala reference's type system rejected mis-wired
DAGs at compile time:

* TM001 — dangling input: a stage reads a column no stage in the DAG
  produces (origin stage lost by deserialization or manual surgery).
* TM002 — shadowed column: a stage's output name collides with a raw
  (generator) column; ``with_columns`` would silently clobber the raw
  input for every later consumer.
* TM003 — duplicate output: two stages emit the same column name, so
  layer merge order decides which survives.
* TM004 — feature-type mismatch: the wired feature's semantic type does
  not conform to the consumer stage's declared ``input_types``
  (``stages/base.py``); the run-time analogue raises ``SchemaError`` at
  ``set_input`` time, this catches DAGs assembled by other means.
* TM005 — dead stage (warning): the execution plan would compute the
  stage, but nothing on the path to the result features consumes it.
* TM006 — label leakage: a response-derived feature reaches a predictor
  input.  Taint starts at raw response features and propagates through
  ordinary stages; ``label_input_positions`` (the declared label slots of
  label-aware stages like SanityChecker and the model selector) both
  absorb taint legitimately and mark where tainted *predictor* wires are
  an error.  Vectorizing a tainted feature is flagged at the vectorizer.

Diagnostics carry the stage uid plus the stage class's ``file:line`` so CI
output is clickable.
"""
from __future__ import annotations

import inspect
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from ..features.feature import Feature
from ..stages.base import PipelineStage
from ..types.feature_types import OPVector
from .diagnostics import Findings

__all__ = ["lint_dag", "lint_workflow", "lint_plan"]

_CLASS_LOC: Dict[type, Optional[str]] = {}


def _stage_location(stage: PipelineStage) -> Optional[str]:
    cls = type(stage)
    if cls not in _CLASS_LOC:
        try:
            f = inspect.getsourcefile(cls)
            _, line = inspect.getsourcelines(cls)
            _CLASS_LOC[cls] = f"{f}:{line}" if f else None
        except (OSError, TypeError):
            _CLASS_LOC[cls] = None
    return _CLASS_LOC[cls]


def _is_generator(stage: PipelineStage) -> bool:
    from ..stages.generator import FeatureGeneratorStage

    return isinstance(stage, FeatureGeneratorStage)


def lint_dag(dag, result_features: Optional[Sequence[Feature]] = None,
             suppress: Iterable[str] = (), reader=None) -> Findings:
    """Lint a ``StagesDAG``.  ``result_features`` enables the dead-stage
    rule (TM005); ``suppress`` drops listed rule ids from the report;
    ``reader`` (the workflow's data reader, when known) enables the
    event-time leakage rule (TM060)."""
    findings = Findings()
    suppress = set(suppress)

    # -- column production map -------------------------------------------
    produced: Dict[str, PipelineStage] = {}
    for layer in dag.layers:
        for s in layer:
            name = s.get_output().name
            prev = produced.get(name)
            if prev is None:
                produced[name] = s
            elif prev.uid != s.uid:
                if _is_generator(prev) and not _is_generator(s):
                    findings.add(
                        "TM002",
                        f"output {name!r} of {type(s).__name__} shadows the "
                        f"raw column produced by generator {prev.uid}",
                        stage_uid=s.uid, location=_stage_location(s))
                else:
                    findings.add(
                        "TM003",
                        f"output {name!r} emitted by both {prev.uid} "
                        f"({type(prev).__name__}) and {s.uid} "
                        f"({type(s).__name__})",
                        stage_uid=s.uid, location=_stage_location(s))

    # -- per-stage wiring checks -----------------------------------------
    for layer in dag.layers:
        for s in layer:
            if _is_generator(s):
                continue
            for i, f in enumerate(s.input_features):
                if f.name not in produced:
                    findings.add(
                        "TM001",
                        f"input {i} ({f.name!r}) of {type(s).__name__} is "
                        f"produced by no stage in the DAG",
                        stage_uid=s.uid, location=_stage_location(s))
                exp = s.expected_input_type(i)
                if exp is not None and not (
                        isinstance(f.ftype, type)
                        and issubclass(f.ftype, exp)):
                    findings.add(
                        "TM004",
                        f"input {i} ({f.name!r}) of {type(s).__name__}: "
                        f"expected {exp.__name__}, got "
                        f"{getattr(f.ftype, '__name__', f.ftype)!r}",
                        stage_uid=s.uid, location=_stage_location(s))

    # -- dead stages vs the result features (TM005) ----------------------
    if result_features is not None:
        needed: Set[str] = set()
        frontier: List[PipelineStage] = [
            produced[f.name] for f in result_features if f.name in produced]
        while frontier:
            s = frontier.pop()
            if s.uid in needed:
                continue
            needed.add(s.uid)
            for f in s.input_features:
                p = produced.get(f.name)
                if p is not None:
                    frontier.append(p)
        for layer in dag.layers:
            for s in layer:
                if not _is_generator(s) and s.uid not in needed:
                    findings.add(
                        "TM005",
                        f"{type(s).__name__} -> {s.get_output().name!r} is "
                        f"computed but consumed by no result feature",
                        stage_uid=s.uid, location=_stage_location(s))

    # -- label leakage (TM006) -------------------------------------------
    findings.extend(_lint_leakage(dag))

    # -- event-time leakage (TM060) --------------------------------------
    findings.extend(_lint_event_windows(dag, reader))

    if suppress:
        findings.diagnostics = [d for d in findings.diagnostics
                                if d.rule not in suppress]
    return findings


def _lint_leakage(dag) -> Findings:
    """Taint walk: raw responses taint; ordinary stages propagate; label
    slots absorb; tainted predictor wires are findings."""
    findings = Findings()
    tainted: Set[str] = set()
    for layer in dag.layers:
        for s in layer:
            out_name = s.get_output().name
            if _is_generator(s):
                if s.get_output().is_response:
                    tainted.add(out_name)
                continue
            label_pos = set(s.label_input_positions)
            offending = [
                (i, f) for i, f in enumerate(s.input_features)
                if f.name in tainted and i not in label_pos]
            is_vectorizer = (isinstance(s.output_type, type)
                             and issubclass(s.output_type, OPVector))
            if offending and (label_pos or is_vectorizer):
                names = ", ".join(f"{f.name!r} (input {i})"
                                  for i, f in offending)
                kind = ("predictor input of label-aware stage" if label_pos
                        else "featurizer input")
                findings.add(
                    "TM006",
                    f"response-derived feature(s) {names} wired into a "
                    f"{kind} of {type(s).__name__}",
                    stage_uid=s.uid, location=_stage_location(s))
                continue  # report the root cause once, don't cascade
            if offending:
                # plain transform of a response (e.g. label rescaling):
                # legitimate on its own; keep the taint flowing so a later
                # predictor-side consumer is still caught
                tainted.add(out_name)
    return findings


_SUPPRESS_CACHE: Dict[str, Optional["object"]] = {}
_UNCACHED = object()


def _suppressed_at(rule: str, location: Optional[str]) -> bool:
    """``# tmog: disable=<rule>`` check for a ``file:line`` construction
    site (per-file Suppressions cache; unreadable/synthetic files never
    suppress)."""
    if not location or ":" not in location:
        return False
    path, _, line_s = location.rpartition(":")
    try:
        line = int(line_s)
    except ValueError:
        return False
    sup = _SUPPRESS_CACHE.get(path, _UNCACHED)
    if sup is _UNCACHED:
        from .astutil import Suppressions

        try:
            with open(path, "r", encoding="utf-8", errors="replace") as fh:
                sup = Suppressions(fh.read())
        except OSError:
            sup = None
        _SUPPRESS_CACHE[path] = sup
    if sup is None:
        return False
    return sup.suppressed(rule, extra_lines=(line,))


def _event_reader(reader):
    """The event-time reader behind ``reader`` (unwrapping resilience /
    shard wrappers via ``inner_reader``), or None."""
    from ..readers.aggregates import AggregateDataReader
    from ..readers.events import StreamingAggregateReader

    seen = 0
    while reader is not None and seen < 8:
        if isinstance(reader, (AggregateDataReader,
                               StreamingAggregateReader)):
            return reader
        reader = getattr(reader, "inner_reader", None)
        seen += 1
    return None


def _lint_event_windows(dag, reader) -> Findings:
    """TM060 — event-time leakage over aggregate/conditional readers.

    A raw predictor over an event reader is safe only when its events are
    provably before the key's cutoff.  Two violations:

    * the reader declares NO cutoff (``CutOffTime.no_cutoff`` and no
      target condition): every predictor window is unbounded, so
      response-time events aggregate into predictors;
    * a predictor reads the same event field a response reads (declared
      via ``event_field`` or the implicit ``r.get(name)`` default):
      outcome data consumed as a predictor regardless of windows.

    Findings anchor at the feature's construction site, where
    ``# tmog: disable=TM060`` suppresses (a legitimately lagged outcome
    feature, e.g. "previous purchase" with a bounded predictor window).
    """
    findings = Findings()
    er = _event_reader(reader)
    if er is None:
        return findings
    gens = [s for layer in dag.layers for s in layer if _is_generator(s)]
    predictors = [s for s in gens if not s.get_output().is_response]
    responses = [s for s in gens if s.get_output().is_response]
    if not predictors or not responses:
        return findings

    cutoff = getattr(er, "cutoff", None)
    has_cutoff = (getattr(er, "target_condition", None) is not None
                  or (cutoff is not None and cutoff.kind != "no_cutoff"))

    def field_of(s) -> Optional[str]:
        ef = getattr(s, "event_field", None)
        if ef is not None:
            return ef
        # no extract_fn -> the implicit r.get(name) field read
        return s.name if getattr(s, "extract_fn", None) is None else None

    response_fields = {field_of(s) for s in responses} - {None}
    for s in predictors:
        problems = []
        if not has_cutoff:
            problems.append(
                "the reader declares no cutoff (CutOffTime.no_cutoff, no "
                "target condition), so predictor events are not provably "
                "before the key's cutoff")
        fld = field_of(s)
        if fld is not None and fld in response_fields:
            problems.append(
                f"event field {fld!r} is also read by a response feature "
                "(outcome data consumed as a predictor)")
        if not problems:
            continue
        site = getattr(s, "source_location", None)
        if _suppressed_at("TM060", site):
            continue
        findings.add(
            "TM060",
            f"event-time leakage in raw feature {s.name!r}: "
            + "; ".join(problems),
            stage_uid=s.uid, location=site or _stage_location(s))
    return findings


def lint_workflow(wf, suppress: Iterable[str] = ()) -> Findings:
    """Lint an ``OpWorkflow`` (or fitted ``OpWorkflowModel``) by
    reconstructing its stage DAG from the result features."""
    from ..workflow.dag import compute_dag

    return lint_dag(compute_dag(wf.result_features),
                    result_features=wf.result_features, suppress=suppress,
                    reader=getattr(wf, "reader", None))


def lint_plan(plan, result_features: Optional[Sequence[Feature]] = None,
              suppress: Iterable[str] = ()) -> Findings:
    """Lint an ``ExecutionPlan`` (workflow/plan.py) via its source DAG."""
    return lint_dag(plan.dag, result_features=result_features,
                    suppress=suppress)
