"""Collective-safety lint (TM070–TM072) — the static half of the TM07x
family.

SPMD host collectives (``allgather_obj`` / ``broadcast_obj`` /
``allsum`` / ``pod.barrier``) hang the whole pod when any process skips
one or issues them out of order, with no error and no attribution.
These rules reject the three source shapes that produce that hang (or
the subtler cross-host artifact divergence) before the code ever runs;
the runtime ledger in ``analysis/contracts.py`` (TM073/TM074) catches
whatever slips through.

* **TM070 — collective under a process-divergent guard.**  A collective
  (or a call that provably reaches one through the package-local
  :mod:`analysis.callgraph`) appears on exactly one side of a branch
  whose test depends on per-process state — ``is_coordinator()``,
  ``process_index`` comparisons, per-host counters (local row counts,
  chunk cursors).  Coordinator processes enter the collective, the rest
  never do: deadlock.  Pod-uniform guards (``pod.active``, config
  flags) are NOT flagged — every process branches the same way.
* **TM071 — collective-order mismatch.**  Sibling branches of one
  ``if``/``else`` — or an early ``return``/``continue``/``break`` path
  versus the fall-through rest of its suite — issue NON-EMPTY but
  DIFFERENT collective sequences.  Whichever way the pod splits, the
  transport pairs an allgather on one host with a barrier on another.
* **TM072 — non-deterministic fold of gathered partials.**  A
  pod-aware function iterates a ``set`` (display, comprehension,
  ``set(...)`` call, or a local name bound to one) or ``os.listdir``
  without ``sorted(...)``.  Per-host iteration order differs, so
  combining allgathered state or writing a durable artifact from the
  loop breaks the byte-identical-on-every-host contract (PR 18).

"Pod-aware" here is the TM047 notion (takes a ``pod``/``pod_ctx``
parameter or calls ``current_pod``) widened with "issues or reaches a
collective".  Suppression: ``# tmog: disable=TM07x`` on the flagged
line or the enclosing ``def`` line.  Entry points: :func:`lint_source`
(single file — reachability sees only that file) and
:func:`lint_paths` (whole-tree graph, the CI mode).
"""
from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Tuple

from .astutil import SCOPE_NODES, Suppressions, dotted
from .callgraph import CallGraph, collective_call_kind
from .diagnostics import Findings
from .trace_lint import iter_py_files

__all__ = ["lint_source", "lint_paths"]

_POD_PARAMS = {"pod", "pod_ctx", "pod_context"}
#: substrings that mark a branch test as PROCESS-DIVERGENT (different
#: processes can take different sides).  Deliberately excludes "pod" /
#: "active": ``if pod.active`` is pod-uniform — every process agrees.
_DIVERGENT_NEEDLES = ("is_coordinator", "process_index", "coordinator",
                      "local_rows", "local_chunk", "chunks_done",
                      "cursor", "rows_done")


def _last(name: Optional[str]) -> Optional[str]:
    return name.split(".")[-1] if name else None


def _fmt_seq(seq: List[Tuple[str, int]]) -> str:
    return "[" + ", ".join(k for k, _ in seq) + "]" if seq else "[]"


class _PodLinter:
    def __init__(self, code: str, filename: str, graph: CallGraph):
        self.filename = filename
        self.findings = Findings()
        self.suppressions = Suppressions(code)
        self.tree = ast.parse(code, filename=filename)
        self.reaching = graph.reaching_names()

    def run(self) -> Findings:
        self._visit(self.tree)
        return self.findings

    def _visit(self, scope: ast.AST) -> None:
        for n in ast.iter_child_nodes(scope):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._check_fn(n)
                self._visit(n)
            elif not isinstance(n, SCOPE_NODES):
                self._visit(n)
            elif isinstance(n, ast.ClassDef):
                self._visit(n)

    def _emit(self, rule: str, node: ast.AST, message: str,
              def_line: Optional[int] = None) -> None:
        if self.suppressions.suppressed(rule, node,
                                        extra_lines=(def_line,)):
            return
        self.findings.add(rule, message,
                          location=f"{self.filename}:{node.lineno}")

    # -- collective-event extraction ----------------------------------

    def _event_kind(self, call: ast.Call) -> Optional[str]:
        kind = collective_call_kind(call)
        if kind is not None:
            return kind
        leaf = _last(dotted(call.func))
        if leaf and leaf in self.reaching:
            return f"call:{leaf}"
        return None

    def _events(self, node: ast.AST) -> List[Tuple[str, int]]:
        """Collective events in AST order, not descending into nested
        scopes (a nested def is its own graph node)."""
        out: List[Tuple[str, int]] = []
        if isinstance(node, ast.Call):
            kind = self._event_kind(node)
            if kind is not None:
                out.append((kind, node.lineno))
        for child in ast.iter_child_nodes(node):
            if not isinstance(child, SCOPE_NODES):
                out.extend(self._events(child))
        return out

    def _suite_events(self, stmts: Iterable[ast.stmt]) \
            -> List[Tuple[str, int]]:
        out: List[Tuple[str, int]] = []
        for st in stmts:
            out.extend(self._events(st))
        return out

    # -- divergence classification ------------------------------------

    @staticmethod
    def _divergent_test(test: ast.AST) -> bool:
        for sub in ast.walk(test):
            name = None
            if isinstance(sub, ast.Attribute):
                name = sub.attr
            elif isinstance(sub, ast.Name):
                name = sub.id
            if name and any(n in name.lower()
                            for n in _DIVERGENT_NEEDLES):
                return True
        return False

    # -- per-function checks ------------------------------------------

    def _pod_aware(self, fn) -> bool:
        a = fn.args
        params = {p.arg for p in (getattr(a, "posonlyargs", []) + a.args
                                  + getattr(a, "kwonlyargs", []))}
        if params & _POD_PARAMS:
            return True
        for n in ast.walk(fn):
            if isinstance(n, ast.Call) and \
                    _last(dotted(n.func)) == "current_pod":
                return True
        return False

    def _check_fn(self, fn) -> None:
        events = self._suite_events(fn.body)
        aware = self._pod_aware(fn) or bool(events)
        if not aware:
            return
        if events:
            self._check_suite(fn, fn.body)
        self._check_nondet_folds(fn)

    def _check_suite(self, fn, stmts: List[ast.stmt]) -> None:
        """Branch discipline over one statement suite, recursing into
        every nested suite (if/for/while/with/try bodies)."""
        for i, st in enumerate(stmts):
            if isinstance(st, ast.If):
                self._check_if(fn, st, rest=stmts[i + 1:])
                self._check_suite(fn, st.body)
                self._check_suite(fn, st.orelse)
            elif isinstance(st, (ast.For, ast.AsyncFor, ast.While)):
                self._check_suite(fn, st.body)
                self._check_suite(fn, st.orelse)
            elif isinstance(st, (ast.With, ast.AsyncWith)):
                self._check_suite(fn, st.body)
            elif isinstance(st, ast.Try):
                self._check_suite(fn, st.body)
                for h in st.handlers:
                    self._check_suite(fn, h.body)
                self._check_suite(fn, st.orelse)
                self._check_suite(fn, st.finalbody)

    @staticmethod
    def _exits(stmts: List[ast.stmt]) -> bool:
        return any(isinstance(s, (ast.Return, ast.Continue, ast.Break))
                   for s in stmts)

    def _check_if(self, fn, node: ast.If,
                  rest: List[ast.stmt]) -> None:
        body_seq = self._suite_events(node.body)
        orelse_seq = self._suite_events(node.orelse)
        divergent = self._divergent_test(node.test)

        if node.orelse or not self._exits(node.body):
            # sibling-branch comparison (an explicit else, or a
            # fall-through if whose body rejoins the suite)
            other = orelse_seq
            label = "the else branch"
        else:
            # early-exit path: the body leaves the suite, so its
            # collective sequence must match what the fall-through
            # rest of the suite issues
            other = self._suite_events(rest)
            label = "the fall-through path"

        if body_seq == other:
            return
        if divergent and (not body_seq or not other):
            only = body_seq or other
            self._emit(
                "TM070", node,
                f"collective sequence {_fmt_seq(only)} is reachable "
                f"only under a process-divergent guard "
                f"(line {node.lineno}): processes that skip the branch "
                f"never enter the collective and the rest deadlock — "
                f"hoist the collective out of the guard",
                fn.lineno)
        elif body_seq and other:
            self._emit(
                "TM071", node,
                f"collective-order mismatch: this branch issues "
                f"{_fmt_seq(body_seq)} but {label} issues "
                f"{_fmt_seq(other)} — if any per-process state decides "
                f"the branch, hosts pair mismatched collectives; make "
                f"both paths issue the same sequence",
                fn.lineno)
        elif divergent:
            # both empty can't reach here; guard kept for clarity
            pass

    # -- TM072 --------------------------------------------------------

    def _nondet_iter(self, fn, it: ast.AST,
                     depth: int = 0) -> Optional[str]:
        if isinstance(it, (ast.Set, ast.SetComp)):
            return "a set"
        if isinstance(it, ast.Call):
            leaf = _last(dotted(it.func))
            if leaf == "set":
                return "set(...)"
            if leaf == "listdir":
                return "os.listdir(...)"
            return None
        if isinstance(it, ast.Name) and depth == 0:
            src = None
            for st in ast.walk(fn):
                if isinstance(st, ast.Assign) and any(
                        isinstance(t, ast.Name) and t.id == it.id
                        for t in st.targets):
                    src = st.value
            if src is not None:
                return self._nondet_iter(fn, src, depth=1)
        return None

    def _check_nondet_folds(self, fn) -> None:
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            n = stack.pop()
            if isinstance(n, SCOPE_NODES):
                continue
            iters = []
            if isinstance(n, (ast.For, ast.AsyncFor)):
                iters.append(n.iter)
            elif isinstance(n, (ast.ListComp, ast.SetComp, ast.DictComp,
                                ast.GeneratorExp)):
                iters.extend(g.iter for g in n.generators)
            for it in iters:
                what = self._nondet_iter(fn, it)
                if what is not None:
                    self._emit(
                        "TM072", n,
                        f"pod-aware code iterates {what}: per-host "
                        f"iteration order differs, so folding gathered "
                        f"partials or writing a durable artifact from "
                        f"this loop diverges across hosts — wrap the "
                        f"iterable in sorted(...)",
                        fn.lineno)
            stack.extend(ast.iter_child_nodes(n))


def lint_source(code: str, filename: str = "<string>",
                graph: Optional[CallGraph] = None) -> Findings:
    """Collective-safety lint one source string.  Without ``graph``,
    reachability sees only this file; :func:`lint_paths` supplies the
    whole-tree graph."""
    try:
        if graph is None:
            graph = CallGraph()
            graph.add_source(code, filename)
        return _PodLinter(code, filename, graph).run()
    except SyntaxError as e:
        f = Findings()
        f.add("TM070", f"could not parse: {e}", severity="warning",
              location=f"{filename}:{e.lineno or 0}")
        return f


def lint_paths(paths: Iterable[str]) -> Findings:
    """Collective-safety lint files / directory trees with a shared
    call graph, so cross-file reachability (a helper in one module
    calling ``pod.barrier`` in another) is seen."""
    findings = Findings()
    graph = CallGraph()
    sources = []
    for full in iter_py_files(paths):
        with open(full, encoding="utf-8") as fh:
            code = fh.read()
        try:
            graph.add_source(code, full)
        except SyntaxError:
            pass   # lint_source reports the parse failure below
        sources.append((full, code))
    for full, code in sources:
        findings.extend(lint_source(code, full, graph=graph))
    return findings
