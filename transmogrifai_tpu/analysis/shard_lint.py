"""Shard-safety lint (TM04x) — an AST pass over the mesh-era source trees.

PR 7 moved the selector sweep onto a ("data", "grid") mesh held together
by conventions nothing checked statically: every ``shard_map`` body must
merge its per-shard partials with a collective before asserting a
replicated output (``shard_map_compat`` runs with ``check=False``, so the
runtime never verifies it), axis names must exist on the enclosing mesh,
and the sweep inner loops must not leak per-iteration host round-trips.
These rules pin those conventions:

* **TM040 — cross-shard reduction without a collective.**  Inside a
  ``shard_map``-wrapped body whose inputs are sharded, a full reduction
  (``.sum()``/``.mean()``/``@``/``jnp.dot``…) of a sharded value in a
  body containing NO collective (``psum``/``pmean``/``all_gather``…)
  produces a per-shard partial that the replicated out_spec silently
  mis-labels — the pad-invariance hazard the sharded sweep contract
  (docs/multichip.md) forbids.
* **TM041 — undefined axis name.**  A string axis in a ``PartitionSpec``
  or a collective's ``axis_name=`` that the enclosing mesh does not
  define.  The axis environment is tracked lightweight-statically: meshes
  built by ``make_sweep_mesh`` carry ("data", "grid"), ``make_mesh``
  its ``axis_names`` (default ("data", "model")), ``Mesh(devs, names)``
  its literal names; ``ax = mesh.axis_names[i]`` resolves symbolically.
* **TM042 — host round-trip inside a sweep inner loop.**  ``device_put``
  / ``device_get`` / ``.block_until_ready()`` lexically inside a
  ``for``/``while`` loop of a function that establishes a sweep context
  (calls ``make_sweep_mesh`` or ``_place_sweep``) — per-iteration
  transfers are the classic sweep-scaling leak.  Since the async sweep
  scheduler, a DISPATCH loop (a loop in a function driving
  ``run_group_block`` / ``run_unit``) is also a sweep context, and a
  blocking metric fetch inside it (``_materialize`` / ``fetch_timed``
  without a statically visible ``overlapped=`` opt-in, or
  ``block_until_ready`` between group blocks) is a forbidden sync point:
  it stalls the double-buffered launch pipeline once per iteration.  The
  ``overlapped=`` keyword marks a lagged fetch that drains behind
  already-enqueued work (utils/profiling.py books it as overlap, not
  drain) and is the sanctioned way to wait inside the loop.
* **TM043 — donated-buffer reuse.**  An argument passed in a donated
  position of a ``jax.jit(..., donate_argnums=...)`` function is read
  again after the call (its buffer may alias the output).
* **TM044 — NamedSharding rank mismatch.**  ``device_put(x, s)`` where
  ``s``'s ``PartitionSpec`` has more dimensions than ``x`` (rank known
  statically from ``np.zeros``-style constructors) — an error at run
  time, caught before any device is touched.
* **TM045 — shard_map spec arity mismatch.**  A literal ``in_specs``
  tuple whose length differs from the wrapped function's parameter
  count, or a literal ``out_specs`` tuple whose length differs from the
  body's returned tuple.
* **TM046 — unrouted sweep-unit exception handler.**  A broad ``except
  Exception`` (or bare ``except``) whose try body executes sweep units
  (calls ``run_unit`` / ``run_group_block`` / ``_run_fold`` /
  ``_run_group`` / ``run_fold``) but whose handler neither consults the
  shared device-loss classifier (``parallel.elastic``:
  ``classify`` / ``classify_sweep_error`` / ``is_device_loss`` /
  ``DeviceLossError``) nor re-raises — such a handler swallows a chip
  loss as an ordinary candidate failure and the elastic
  shrink/retry/quarantine ladder never engages.

Host syncs on traced values inside shard_map bodies are reported as
TM030 through the shared :func:`~.trace_lint.check_host_syncs` pass —
with collective results correctly treated as device values, so
``tot = lax.psum(part, ...)`` stays traced (and a body's host driver
code around the ``shard_map`` call site is never misread as traced).

Suppression: ``# tmog: disable=TM040`` on the flagged line (or any line
of a multi-line statement, or the enclosing ``def`` line).  Entry
points: :func:`lint_source`, :func:`lint_paths`.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .astutil import Suppressions, dotted, scope_walk, target_names
from .diagnostics import Findings
from .trace_lint import COLLECTIVES, check_host_syncs, iter_py_files

__all__ = ["lint_source", "lint_paths"]

#: mesh constructors the axis environment is seeded from
_SWEEP_MESH_FNS = {"make_sweep_mesh"}
_MESH_FNS = {"make_mesh"}
_RAW_MESH = {"Mesh"}
#: call sites that establish a sweep context for TM042
_SWEEP_CONTEXT_FNS = {"make_sweep_mesh", "_place_sweep"}
#: call sites that make a function a sweep DISPATCH loop for TM042 —
#: the async scheduler's hot path, where any blocking fetch stalls the
#: double-buffered launch pipeline
_DISPATCH_CONTEXT_FNS = {"run_group_block", "run_unit"}
#: blocking metric fetches forbidden inside a dispatch loop unless they
#: carry an ``overlapped=`` keyword (the lagged-fetch opt-in —
#: utils/profiling.py books those as overlap, not drain)
_DEFERRED_FETCH_FNS = {"_materialize", "fetch_timed"}

#: calls that execute a sweep unit's fit body — a try wrapping one of
#: these is "sweep-unit execution" for TM046
_SWEEP_UNIT_CALLS = {"run_unit", "run_group_block", "_run_fold",
                     "_run_group", "run_fold"}
#: names whose presence in a handler counts as routing through the
#: shared device-loss classifier (parallel/elastic.py)
_CLASSIFIER_NAMES = {"classify", "classify_sweep_error", "is_device_loss",
                     "DeviceLossError"}

_SPEC_NAMES = {"P", "PartitionSpec"}
_SHARD_MAP_NAMES = {"shard_map", "shard_map_compat"}
_REDUCE_METHODS = {"sum", "mean", "dot"}
_REDUCE_FNS = {"sum", "mean", "dot", "vdot", "matmul", "tensordot",
               "inner", "einsum"}
_TRANSFER_FNS = {"device_put", "device_get"}

#: unknown-but-valid axis sentinel (``mesh.axis_names[i]`` with an
#: unresolvable mesh): never reported
_VALID = object()


def _last(name: Optional[str]) -> Optional[str]:
    return name.split(".")[-1] if name else None


def _const_strs(node: ast.AST) -> Optional[Tuple[str, ...]]:
    if isinstance(node, (ast.Tuple, ast.List)) and all(
            isinstance(e, ast.Constant) and isinstance(e.value, str)
            for e in node.elts):
        return tuple(e.value for e in node.elts)
    return None


def _own_returns(fn: ast.AST):
    """``Return`` nodes belonging to ``fn`` itself — nested function
    definitions (scan bodies, helper closures) are skipped, since their
    return arity is theirs, not the shard_map out_specs contract's."""
    out = []
    stack = list(getattr(fn, "body", []))
    while stack:
        nd = stack.pop()
        if isinstance(nd, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.Lambda)):
            continue
        if isinstance(nd, ast.Return):
            out.append(nd)
        stack.extend(ast.iter_child_nodes(nd))
    return out


class _Scope:
    """One lexical scope's name -> value-expression table."""

    def __init__(self, node: ast.AST, parent: Optional["_Scope"]):
        self.node = node
        self.parent = parent
        self.env: Dict[str, ast.AST] = {}
        self.local_defs: Dict[str, ast.FunctionDef] = {}
        for n in scope_walk(node):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                    and isinstance(n.targets[0], ast.Name):
                self.env[n.targets[0].id] = n.value
            elif isinstance(n, ast.FunctionDef):
                self.local_defs[n.name] = n

    def lookup(self, name: str) -> Optional[ast.AST]:
        s: Optional[_Scope] = self
        while s is not None:
            if name in s.env:
                return s.env[name]
            s = s.parent
        return None


class _ShardLinter:
    def __init__(self, code: str, filename: str):
        self.filename = filename
        self.findings = Findings()
        self.suppressions = Suppressions(code)
        self.tree = ast.parse(code, filename=filename)

    def run(self) -> Findings:
        self._visit(self.tree, None)
        self._check_unit_exception_routing(self.tree)
        return self.findings

    # -- reporting ---------------------------------------------------------

    def _emit(self, rule: str, node: ast.AST, message: str,
              def_line: Optional[int] = None) -> None:
        if self.suppressions.suppressed(rule, node,
                                        extra_lines=(def_line,)):
            return
        self.findings.add(rule, message,
                          location=f"{self.filename}:{node.lineno}")

    # -- resolution --------------------------------------------------------

    def _resolve(self, expr: ast.AST, scope: _Scope,
                 depth: int = 0) -> Optional[ast.AST]:
        while isinstance(expr, ast.Name) and depth < 8:
            nxt = scope.lookup(expr.id)
            if nxt is None or nxt is expr:
                return expr
            expr, depth = nxt, depth + 1
        return expr

    def _mesh_axes(self, expr: ast.AST,
                   scope: _Scope) -> Optional[Tuple[str, ...]]:
        """Axis names of the mesh ``expr`` evaluates to, or None when
        statically unknown (a parameter, an attribute)."""
        expr = self._resolve(expr, scope)
        if not isinstance(expr, ast.Call):
            return None
        name = _last(dotted(expr.func))
        if name in _SWEEP_MESH_FNS:
            return ("data", "grid")
        if name in _MESH_FNS:
            for kw in expr.keywords:
                if kw.arg == "axis_names":
                    return _const_strs(kw.value)
            return ("data", "model")
        if name in _RAW_MESH and len(expr.args) >= 2:
            return _const_strs(expr.args[1])
        return None

    def _axis_of(self, expr: ast.AST, scope: _Scope):
        """An axis expression's value: a string, ``_VALID`` (resolves to
        some mesh axis we cannot name), or None (unknown — skipped)."""
        expr = self._resolve(expr, scope)
        if isinstance(expr, ast.Constant):
            if expr.value is None:
                return None
            if isinstance(expr.value, str):
                return expr.value
            return None
        # mesh.axis_names[i]
        if (isinstance(expr, ast.Subscript)
                and isinstance(expr.value, ast.Attribute)
                and expr.value.attr == "axis_names"):
            axes = self._mesh_axes(expr.value.value, scope)
            idx = expr.slice
            if (axes is not None and isinstance(idx, ast.Constant)
                    and isinstance(idx.value, int)
                    and 0 <= idx.value < len(axes)):
                return axes[idx.value]
            return _VALID
        return None

    # -- traversal ---------------------------------------------------------

    def _visit(self, node: ast.AST, parent: Optional[_Scope]) -> None:
        scope = _Scope(node, parent)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._check_sweep_loops(node)
            self._check_donation(node, scope)
        self._check_device_put_ranks(node, scope)
        for n in scope_walk(node):
            if isinstance(n, ast.Call) and \
                    _last(dotted(n.func)) in _SHARD_MAP_NAMES:
                self._check_shard_map(n, scope)
        for n in scope_walk(node):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._visit(n, scope)
            elif isinstance(n, ast.ClassDef):
                self._visit(n, scope)

    # -- TM040/TM041/TM045 + TM030: one shard_map site ----------------------

    def _shard_map_parts(self, call: ast.Call):
        """(fn_expr, mesh_expr, in_specs_expr, out_specs_expr) with
        positional/keyword normalization; Nones where absent."""
        args: List[Optional[ast.AST]] = list(call.args[:4])
        args += [None] * (4 - len(args))
        kw = {k.arg: k.value for k in call.keywords}
        return (args[0],
                kw.get("mesh", args[1]),
                kw.get("in_specs", args[2]),
                kw.get("out_specs", args[3]))

    def _spec_elts(self, spec: ast.AST) -> Optional[List[ast.AST]]:
        """P(...) -> its positional elements; else None (not a literal
        spec)."""
        if isinstance(spec, ast.Call) and \
                _last(dotted(spec.func)) in _SPEC_NAMES:
            return list(spec.args)
        return None

    def _check_shard_map(self, call: ast.Call, scope: _Scope) -> None:
        fn_expr, mesh_expr, in_specs, out_specs = self._shard_map_parts(call)
        if fn_expr is None:
            return
        fn = None
        if isinstance(fn_expr, ast.Lambda):
            fn = fn_expr
        elif isinstance(fn_expr, ast.Name):
            fn = scope.local_defs.get(fn_expr.id)
        axes = (self._mesh_axes(mesh_expr, scope)
                if mesh_expr is not None else None)

        # TM041: literal axis strings in the specs
        spec_list: List[ast.AST] = []
        for specs in (in_specs, out_specs):
            if specs is None:
                continue
            if isinstance(specs, (ast.Tuple, ast.List)):
                spec_list.extend(specs.elts)
            else:
                spec_list.append(specs)
        in_spec_elts = None
        if isinstance(in_specs, (ast.Tuple, ast.List)):
            in_spec_elts = in_specs.elts
        elif in_specs is not None:
            in_spec_elts = [in_specs]  # single spec broadcasts to all args
        for spec in spec_list:
            elts = self._spec_elts(spec)
            if elts is None:
                continue
            for e in elts:
                ax = self._axis_of(e, scope)
                if isinstance(ax, str) and axes is not None \
                        and ax not in axes:
                    self._emit("TM041", e if hasattr(e, "lineno") else spec,
                               f"axis {ax!r} is not defined by the "
                               f"enclosing mesh (axes: {axes})")
        if fn is None:
            return
        def_line = fn.lineno
        params = [p.arg for p in (getattr(fn.args, "posonlyargs", [])
                                  + fn.args.args)]

        # TM045: literal in_specs tuple arity vs wrapped params
        if isinstance(in_specs, (ast.Tuple, ast.List)) \
                and len(in_specs.elts) != len(params) \
                and not fn.args.vararg:
            self._emit("TM045", call,
                       f"shard_map in_specs has {len(in_specs.elts)} "
                       f"spec(s) but the wrapped function takes "
                       f"{len(params)} parameter(s)", def_line)
        if isinstance(out_specs, (ast.Tuple, ast.List)):
            # only the wrapped function's OWN returns: a nested def (a
            # lax.scan body returning (carry, ys), a helper closure) has
            # its own return arity and must not trip the spec check
            for ret in _own_returns(fn):
                if isinstance(ret.value, ast.Tuple) and \
                        len(ret.value.elts) != len(out_specs.elts):
                    self._emit(
                        "TM045", ret,
                        f"shard_map out_specs has {len(out_specs.elts)} "
                        f"spec(s) but the body returns "
                        f"{len(ret.value.elts)} value(s)", def_line)

        if getattr(fn, "_tmog_shard_linted", False):
            return
        fn._tmog_shard_linted = True

        # which params are sharded (any non-None spec element)
        sharded: Set[str] = set()
        if in_spec_elts is not None:
            broadcast = len(in_spec_elts) == 1 and len(params) > 1 \
                and not isinstance(in_specs, (ast.Tuple, ast.List))
            for i, p in enumerate(params):
                spec = in_spec_elts[0] if broadcast else (
                    in_spec_elts[i] if i < len(in_spec_elts) else None)
                elts = self._spec_elts(spec) if spec is not None else None
                if elts and any(not (isinstance(e, ast.Constant)
                                     and e.value is None) for e in elts):
                    sharded.add(p)

        # TM041 on collectives' axis_name inside the body
        body_collective = False
        for n in ast.walk(fn):
            if not isinstance(n, ast.Call):
                continue
            cname = _last(dotted(n.func))
            if cname not in COLLECTIVES:
                continue
            body_collective = True
            ax_expr = None
            for k in n.keywords:
                if k.arg == "axis_name":
                    ax_expr = k.value
            if ax_expr is None and len(n.args) >= 2:
                ax_expr = n.args[1]
            elif ax_expr is None and cname == "axis_index" and n.args:
                ax_expr = n.args[0]
            if ax_expr is not None:
                ax = self._axis_of(ax_expr, scope)
                if isinstance(ax, str) and axes is not None \
                        and ax not in axes:
                    self._emit("TM041", n,
                               f"collective {cname} reduces over axis "
                               f"{ax!r}, not defined by the enclosing "
                               f"mesh (axes: {axes})", def_line)
        # partial-bound collectives (all_reduce=psum plumbing) count too
        if not body_collective:
            for n in ast.walk(fn):
                if isinstance(n, ast.Name) and n.id in COLLECTIVES:
                    body_collective = True
                    break
                if isinstance(n, ast.Attribute) and n.attr in COLLECTIVES:
                    body_collective = True
                    break

        # TM040: sharded full reduction with no collective anywhere
        if sharded and not body_collective:
            self._check_cross_shard_reductions(fn, sharded, def_line)

        # TM030 host syncs on traced values (collective-result aware)
        check_host_syncs(
            fn, set(), lambda rule, node, msg: self._emit(
                rule, node, msg, def_line),
            context="shard_map")

    def _check_cross_shard_reductions(self, fn, sharded: Set[str],
                                      def_line: int) -> None:
        from .trace_lint import _tainted_loads

        tainted = set(sharded)
        for _ in range(4):
            grew = False
            for n in ast.walk(fn):
                if isinstance(n, ast.Assign) and \
                        _tainted_loads(n.value, tainted):
                    new = set().union(*(target_names(t) for t in n.targets))
                    grew |= not new <= tainted
                    tainted |= new
            if not grew:
                break

        def full_reduce(call: ast.Call) -> bool:
            """No axis restriction -> reduces over the sharded dim too."""
            return not any(k.arg == "axis" for k in call.keywords)

        for n in ast.walk(fn):
            if isinstance(n, ast.BinOp) and isinstance(n.op, ast.MatMult) \
                    and (_tainted_loads(n.left, tainted)
                         or _tainted_loads(n.right, tainted)):
                self._emit("TM040", n,
                           f"matmul over a sharded operand "
                           f"({ast.unparse(n)!r}) with no psum/pmean in "
                           f"the shard_map body: the contraction "
                           f"produces per-shard partials", def_line)
            elif isinstance(n, ast.Call):
                f = n.func
                if (isinstance(f, ast.Attribute)
                        and f.attr in _REDUCE_METHODS and not n.args
                        and full_reduce(n)
                        and _tainted_loads(f.value, tainted)):
                    self._emit("TM040", n,
                               f".{f.attr}() over sharded value "
                               f"{ast.unparse(f.value)!r} with no "
                               f"psum/pmean in the shard_map body",
                               def_line)
                elif (isinstance(f, ast.Attribute)
                        and f.attr in _REDUCE_FNS
                        and dotted(f.value) in ("jnp", "jax.numpy", "np",
                                                "numpy")
                        and n.args and full_reduce(n)
                        and any(_tainted_loads(a, tainted)
                                for a in n.args)):
                    self._emit("TM040", n,
                               f"{dotted(f)}() over a sharded value with "
                               f"no psum/pmean in the shard_map body",
                               def_line)

    # -- TM042: host round-trips inside sweep inner loops --------------------

    def _check_sweep_loops(self, fn) -> None:
        ctx = {_last(dotted(n.func)) for n in scope_walk(fn)
               if isinstance(n, ast.Call)}
        is_sweep = bool(ctx & _SWEEP_CONTEXT_FNS)
        is_dispatch = bool(ctx & _DISPATCH_CONTEXT_FNS)
        if not (is_sweep or is_dispatch):
            return
        for loop in scope_walk(fn):
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            for n in ast.walk(loop):
                if not isinstance(n, ast.Call):
                    continue
                name = _last(dotted(n.func))
                if name in _TRANSFER_FNS:
                    self._emit("TM042", n,
                               f"{name} inside a sweep inner loop: one "
                               f"host<->device transfer per iteration — "
                               f"hoist the placement out of the loop",
                               fn.lineno)
                elif (isinstance(n.func, ast.Attribute)
                      and n.func.attr == "block_until_ready"):
                    self._emit("TM042", n,
                               "block_until_ready inside a sweep inner "
                               "loop: a device sync per iteration"
                               + (" — between group blocks it stalls "
                                  "the double-buffered launch pipeline"
                                  if is_dispatch else ""),
                               fn.lineno)
                elif (is_dispatch and name in _DEFERRED_FETCH_FNS
                      and not any(kw.arg == "overlapped"
                                  for kw in n.keywords)):
                    self._emit("TM042", n,
                               f"{name} inside the sweep dispatch loop "
                               f"blocks on per-unit metrics while later "
                               f"launches wait — defer the fetch to the "
                               f"end-of-sweep collect, or mark a lagged "
                               f"fetch with overlapped=",
                               fn.lineno)

    # -- TM043: donated-buffer reuse ----------------------------------------

    def _jit_donations(self, expr: ast.AST) -> Optional[Set[int]]:
        """``jax.jit(f, donate_argnums=...)`` -> donated positions."""
        if not (isinstance(expr, ast.Call)
                and _last(dotted(expr.func)) == "jit"):
            return None
        for kw in expr.keywords:
            if kw.arg == "donate_argnums":
                v = kw.value
                elts = v.elts if isinstance(v, (ast.Tuple, ast.List)) else [v]
                out = {e.value for e in elts
                       if isinstance(e, ast.Constant)
                       and isinstance(e.value, int)}
                return out or None
        return None

    def _check_donation(self, fn, scope: _Scope) -> None:
        jitted: Dict[str, Set[int]] = {}
        events: List[Tuple[int, int, str, str, ast.AST]] = []
        for n in scope_walk(fn):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                    and isinstance(n.targets[0], ast.Name):
                don = self._jit_donations(n.value)
                if don:
                    jitted[n.targets[0].id] = don
                for t in target_names(n.targets[0]):
                    events.append((n.end_lineno or n.lineno,
                                   (n.end_col_offset or 0) + 2,
                                   "store", t, n))
        if not jitted:
            return
        for n in scope_walk(fn):
            # donation takes effect AFTER the call's own argument loads
            # (and before any assignment-target store rebinds the name),
            # so events anchor on the node's END position
            if isinstance(n, ast.Call) and isinstance(n.func, ast.Name) \
                    and n.func.id in jitted:
                for i in jitted[n.func.id]:
                    if i < len(n.args) and isinstance(n.args[i], ast.Name):
                        events.append((n.end_lineno or n.lineno,
                                       (n.end_col_offset or 0) + 1,
                                       "donate", n.args[i].id, n))
            if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call) \
                    and isinstance(n.value.func, ast.Name) \
                    and n.value.func.id in jitted:
                for t in n.targets:
                    for t_name in target_names(t):
                        events.append((n.end_lineno or n.lineno,
                                       (n.end_col_offset or 0) + 2,
                                       "store", t_name, n))
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load):
                events.append((n.lineno, n.col_offset, "load", n.id, n))
        events.sort(key=lambda e: (e[0], e[1]))
        donated: Set[str] = set()
        for lineno, _col, kind, name, node in events:
            if kind == "donate":
                donated.add(name)
            elif kind == "store":
                donated.discard(name)
            elif kind == "load" and name in donated:
                self._emit("TM043", node,
                           f"{name!r} was donated to a jit call "
                           f"(donate_argnums) and read again: its buffer "
                           f"may alias the output", fn.lineno)
                donated.discard(name)  # one report per donation

    # -- TM046: unrouted sweep-unit exception handlers -----------------------

    @staticmethod
    def _is_broad_handler(type_expr) -> bool:
        """bare ``except:`` / ``except Exception`` / ``except
        BaseException`` (incl. inside a tuple)."""
        if type_expr is None:
            return True
        exprs = (type_expr.elts if isinstance(type_expr, ast.Tuple)
                 else [type_expr])
        for e in exprs:
            name = _last(dotted(e))
            if name in ("Exception", "BaseException"):
                return True
        return False

    @staticmethod
    def _handler_routes(handler: ast.ExceptHandler) -> bool:
        """The handler consults the shared classifier, or re-raises (the
        loss is not swallowed — an enclosing handler may still route)."""
        for n in ast.walk(handler):
            if isinstance(n, ast.Raise):
                return True
            if isinstance(n, ast.Name) and n.id in _CLASSIFIER_NAMES:
                return True
            if isinstance(n, ast.Attribute) and n.attr in _CLASSIFIER_NAMES:
                return True
        return False

    def _check_unit_exception_routing(self, tree: ast.AST) -> None:
        for n in ast.walk(tree):
            if not isinstance(n, ast.Try):
                continue
            body_calls = {
                _last(dotted(c.func))
                for stmt in n.body for c in ast.walk(stmt)
                if isinstance(c, ast.Call)}
            if not (body_calls & _SWEEP_UNIT_CALLS):
                continue
            for h in n.handlers:
                if not self._is_broad_handler(h.type):
                    continue
                if self._handler_routes(h):
                    continue
                called = sorted(body_calls & _SWEEP_UNIT_CALLS)
                self._emit(
                    "TM046", h,
                    f"broad except around sweep-unit execution "
                    f"({', '.join(called)}) without routing through the "
                    f"shared device-loss classifier (parallel.elastic."
                    f"classify_sweep_error / is_device_loss): a chip loss "
                    f"is swallowed as a candidate failure and the elastic "
                    f"shrink/retry/quarantine ladder never engages",
                    n.lineno)

    # -- TM044: NamedSharding rank vs operand rank ---------------------------

    def _spec_rank(self, expr: ast.AST, scope: _Scope) -> Optional[int]:
        expr = self._resolve(expr, scope)
        if isinstance(expr, ast.Call) and \
                _last(dotted(expr.func)) == "NamedSharding" \
                and len(expr.args) >= 2:
            elts = self._spec_elts(expr.args[1])
            if elts is not None:
                return len(elts)
        return None

    def _array_rank(self, expr: ast.AST, scope: _Scope) -> Optional[int]:
        expr = self._resolve(expr, scope)
        if not isinstance(expr, ast.Call):
            return None
        name = _last(dotted(expr.func))
        if name in ("zeros", "ones", "empty", "full") and expr.args:
            shp = expr.args[0]
            if isinstance(shp, (ast.Tuple, ast.List)):
                return len(shp.elts)
            if isinstance(shp, ast.Constant) and \
                    isinstance(shp.value, int):
                return 1
        if name in ("arange", "linspace"):
            return 1
        if name == "eye":
            return 2
        return None

    def _check_device_put_ranks(self, node: ast.AST, scope: _Scope) -> None:
        for n in scope_walk(node):
            if not (isinstance(n, ast.Call)
                    and _last(dotted(n.func)) == "device_put"
                    and len(n.args) >= 2):
                continue
            spec_rank = self._spec_rank(n.args[1], scope)
            arr_rank = self._array_rank(n.args[0], scope)
            if spec_rank is not None and arr_rank is not None \
                    and spec_rank > arr_rank:
                self._emit("TM044", n,
                           f"NamedSharding spec has {spec_rank} "
                           f"dimension(s) but the operand has rank "
                           f"{arr_rank}")


def lint_source(code: str, filename: str = "<string>") -> Findings:
    """Shard-safety lint one source string."""
    try:
        return _ShardLinter(code, filename).run()
    except SyntaxError as e:
        f = Findings()
        f.add("TM040", f"could not parse: {e}", severity="warning",
              location=f"{filename}:{e.lineno or 0}")
        return f


def lint_paths(paths: Iterable[str]) -> Findings:
    """Shard-safety lint files and directory trees of ``.py`` sources."""
    findings = Findings()
    for full in iter_py_files(paths):
        with open(full, encoding="utf-8") as fh:
            findings.extend(lint_source(fh.read(), full))
    return findings
