"""Trace-safety lint (TM03x) — an AST pass over jit-heavy source trees.

The zero-recompile counters from PR 1 catch trace-cache churn only *after*
a deploy has already paid for it; these rules catch the three classic
causes statically, before the code runs:

* **TM030 — host sync inside jit.**  ``.item()``, ``.tolist()``,
  ``float()``/``int()``/``bool()``, and ``np.asarray``/``np.array`` applied
  to a *traced* value inside a jit-compiled function force a device
  round-trip per call (or a tracer error at runtime).  Traced values are
  the function's parameters minus declared static arguments, propagated
  through local assignments (a small intra-function taint analysis keeps
  ``float(self.learning_rate)``-style host-constant uses clean).
* **TM031 — Python-scalar closure (warning).**  A jit function defined
  inside another function that closes over an enclosing *Python scalar*
  (a local assigned from a numeric literal, ``len()``, ``int()``/
  ``float()``) bakes that scalar in as a fresh trace constant — a new
  compile every time the enclosing function runs with a different value.
  Closures over modules, arrays, and non-scalar locals are not flagged.
* **TM032 — unhashable static argument.**  ``static_argnums``/
  ``static_argnames`` naming a parameter whose default is a list/dict/set
  display will raise ``TypeError: unhashable`` on the first defaulted
  call; also flags static indices out of the parameter range.

Suppression: a ``# tmog: disable=TM030`` comment (comma-separate several
ids) on the flagged line or on the enclosing ``def`` line disables the
rule there.  Entry points: :func:`lint_source`, :func:`lint_paths`.
"""
from __future__ import annotations

import ast
import builtins
import os
from typing import Iterable, List, Optional, Sequence, Set, Tuple

from .astutil import (Suppressions, dotted as _dotted, load_names as
                      _load_names, scope_walk as _scope_walk,
                      target_names as _target_names)
from .diagnostics import Findings

__all__ = ["lint_source", "lint_paths", "check_host_syncs",
           "COLLECTIVES", "iter_py_files"]

_HOST_CASTS = {"float", "int", "bool", "complex"}
_NP_SYNC_FNS = {"asarray", "array", "ascontiguousarray", "asfortranarray"}
_NP_MODULES = {"np", "numpy", "onp"}
_SYNC_METHODS = {"item", "tolist"}

#: collective primitives whose RESULTS are device values — a collective
#: with no tainted operand (``lax.axis_index``) still yields a traced
#: value, and taint must flow THROUGH collectives (a ``psum`` total is as
#: device-resident as the partial it reduced).  Shared with shard_lint.
COLLECTIVES = {"psum", "pmean", "pmax", "pmin", "all_gather", "ppermute",
               "all_to_all", "axis_index", "psum_scatter"}

#: enclosing-scope assignments considered "Python scalars" for TM031
_SCALARISH_CALLS = {"len", "int", "float", "round"}

_BUILTIN_NAMES = set(dir(builtins))


def _is_jit_ref(node: ast.AST) -> bool:
    return _dotted(node) in ("jit", "jax.jit")


def _jit_call_parts(call: ast.Call) -> Optional[Tuple[List[int], List[str]]]:
    """``functools.partial(jax.jit, ...)`` / ``jax.jit(...)`` -> declared
    (static_argnums, static_argnames); None if the call is not jit."""
    fn = call.func
    is_partial = _dotted(fn) in ("partial", "functools.partial")
    if is_partial:
        if not (call.args and _is_jit_ref(call.args[0])):
            return None
    elif not _is_jit_ref(fn):
        return None
    nums: List[int] = []
    names: List[str] = []
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            nums.extend(_const_ints(kw.value))
        elif kw.arg == "static_argnames":
            names.extend(_const_strs(kw.value))
    return nums, names


def _const_ints(node: ast.AST) -> List[int]:
    elts = node.elts if isinstance(node, (ast.Tuple, ast.List)) else [node]
    return [n.value for n in elts
            if isinstance(n, ast.Constant) and isinstance(n.value, int)
            and not isinstance(n.value, bool)]


def _const_strs(node: ast.AST) -> List[str]:
    elts = node.elts if isinstance(node, (ast.Tuple, ast.List)) else [node]
    return [n.value for n in elts
            if isinstance(n, ast.Constant) and isinstance(n.value, str)]


def _param_names(fn) -> List[str]:
    a = fn.args
    return ([p.arg for p in getattr(a, "posonlyargs", [])]
            + [p.arg for p in a.args])


#: attribute reads that are static trace-time metadata even on traced
#: values — deriving a Python int from them is NOT a host sync
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "aval", "sharding"}


def _tainted_loads(e: ast.AST, tainted: Set[str]) -> Set[str]:
    """Names from ``tainted`` loaded by ``e``, ignoring subtrees that only
    read static metadata (``x.shape[0]``, ``len(x)``, ``x.dtype``)."""
    hits: Set[str] = set()

    def rec(n: ast.AST) -> None:
        if isinstance(n, ast.Attribute) and n.attr in _STATIC_ATTRS:
            return
        if (isinstance(n, ast.Call) and isinstance(n.func, ast.Name)
                and n.func.id == "len"):
            return
        if (isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
                and n.id in tainted):
            hits.add(n.id)
        for c in ast.iter_child_nodes(n):
            rec(c)

    rec(e)
    return hits


def _is_collective_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = _dotted(node.func)
    return bool(name) and name.split(".")[-1] in COLLECTIVES


class _SourceLinter:
    def __init__(self, code: str, filename: str):
        self.filename = filename
        self.findings = Findings()
        self.suppressions = Suppressions(code)
        self.tree = ast.parse(code, filename=filename)
        self.module_names = self._module_scope_names()

    # -- driver ------------------------------------------------------------

    def run(self) -> Findings:
        self._visit_scope(self.tree, enclosing_fn=None)
        return self.findings

    def _visit_scope(self, scope: ast.AST, enclosing_fn) -> None:
        """Lint jit targets belonging to one lexical scope, then recurse.

        ``enclosing_fn`` is the nearest enclosing FunctionDef (None at
        module/class level) — the scope whose Python-scalar locals a nested
        jit closure would bake in as trace constants (TM031).
        """
        nodes = list(_scope_walk(scope))
        local_defs = {n.name: n for n in nodes
                      if isinstance(n, ast.FunctionDef)}
        # decorated jit defs
        for node in nodes:
            if isinstance(node, ast.FunctionDef):
                parts = self._jit_decorator(node)
                if parts is not None and not getattr(node, "_tmog_jit", 0):
                    node._tmog_jit = True
                    self._lint_jit_function(node, parts, enclosing_fn)
        # jax.jit(<lambda>) / jax.jit(<local def>) wrapping calls
        for node in nodes:
            if not isinstance(node, ast.Call):
                continue
            parts = _jit_call_parts(node)
            if parts is None or not node.args:
                continue
            target = node.args[0]
            fnode = None
            if isinstance(target, ast.Lambda):
                fnode = target
            elif isinstance(target, ast.Name):
                fnode = local_defs.get(target.id)
            if fnode is not None and not getattr(fnode, "_tmog_jit", 0):
                fnode._tmog_jit = True
                self._lint_jit_function(fnode, parts, enclosing_fn)
        # recurse into nested scopes
        for node in nodes:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._visit_scope(node, enclosing_fn=node)
            elif isinstance(node, ast.ClassDef):
                self._visit_scope(node, enclosing_fn=enclosing_fn)

    def _jit_decorator(self, fn: ast.FunctionDef):
        for dec in fn.decorator_list:
            if _is_jit_ref(dec):
                return [], []
            if isinstance(dec, ast.Call):
                parts = _jit_call_parts(dec)
                if parts is not None:
                    return parts
        return None

    # -- reporting ---------------------------------------------------------

    def _emit(self, rule: str, node, message: str,
              def_line: Optional[int] = None) -> None:
        line = node if isinstance(node, int) else node.lineno
        if self.suppressions.suppressed(
                rule, None if isinstance(node, int) else node,
                extra_lines=(line, def_line)):
            return
        self.findings.add(rule, message,
                          location=f"{self.filename}:{line}")

    # -- per-function analysis ----------------------------------------------

    def _lint_jit_function(self, fn, parts, enclosing_fn) -> None:
        static_nums, static_names = parts
        params = _param_names(fn)
        def_line = fn.lineno

        # TM032: static args must be hashable / in range
        defaults = getattr(fn.args, "defaults", [])
        default_of = dict(zip(params[len(params) - len(defaults):], defaults))
        static = set(static_names)
        for i in static_nums:
            if 0 <= i < len(params):
                static.add(params[i])
            elif not fn.args.vararg:
                self._emit("TM032", def_line,
                           f"static_argnums index {i} out of range for "
                           f"{len(params)} parameter(s)", def_line)
        kwonly = {p.arg for p in getattr(fn.args, "kwonlyargs", [])}
        for nm in static_names:
            if nm not in params and nm not in kwonly and not fn.args.kwarg:
                self._emit("TM032", def_line,
                           f"static_argnames {nm!r} names no parameter",
                           def_line)
        for nm in sorted(static):
            d = default_of.get(nm)
            if isinstance(d, (ast.List, ast.Dict, ast.Set)) or (
                    isinstance(d, ast.Call)
                    and _dotted(d.func) in ("list", "dict", "set")):
                self._emit("TM032", d,
                           f"static argument {nm!r} has an unhashable "
                           f"default ({type(d).__name__.lower()}); jit will "
                           f"raise on the first defaulted call", def_line)

        # TM030: taint params (minus static) through local assignments
        check_host_syncs(
            fn, static,
            lambda rule, node, msg: self._emit(rule, node, msg, def_line))

        # TM031: closure over enclosing Python scalars
        if enclosing_fn is not None:
            scalars = self._scalarish_locals(enclosing_fn)
            free = self._free_names(fn, params)
            for nm in sorted(free & scalars):
                self._emit("TM031", def_line,
                           f"jit function closes over enclosing Python "
                           f"scalar {nm!r}: becomes a fresh trace constant "
                           f"(recompile per distinct value); pass it as a "
                           f"static argument instead", def_line)

    def _free_names(self, fn, params: Sequence[str]) -> Set[str]:
        bound = set(params)
        if getattr(fn.args, "vararg", None):
            bound.add(fn.args.vararg.arg)
        if getattr(fn.args, "kwarg", None):
            bound.add(fn.args.kwarg.arg)
        bound |= {p.arg for p in getattr(fn.args, "kwonlyargs", [])}
        body = fn.body if isinstance(fn.body, list) else [fn.body]
        loads: Set[str] = set()
        for stmt in body:
            loads |= _load_names(stmt)
            for node in ast.walk(stmt):
                if isinstance(node, ast.Name) and isinstance(node.ctx,
                                                             ast.Store):
                    bound.add(node.id)
        return loads - bound - self.module_names - _BUILTIN_NAMES

    def _scalarish_locals(self, scope) -> Set[str]:
        out: Set[str] = set()
        for node in _scope_walk(scope):
            if not isinstance(node, ast.Assign):
                continue
            v = node.value
            scalar = (isinstance(v, ast.Constant)
                      and isinstance(v.value, (int, float))
                      and not isinstance(v.value, bool)) \
                or (isinstance(v, ast.Call)
                    and _dotted(v.func) in _SCALARISH_CALLS) \
                or (isinstance(v, ast.BinOp)
                    and all(isinstance(s, ast.Constant)
                            for s in (v.left, v.right)))
            if scalar:
                out |= set().union(*(_target_names(t) for t in node.targets))
        return out

    def _module_scope_names(self) -> Set[str]:
        names: Set[str] = set()
        for node in self.tree.body:
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                for a in node.names:
                    names.add((a.asname or a.name).split(".")[0])
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                names.add(node.name)
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    names |= _target_names(t)
            elif isinstance(node, ast.AnnAssign) and isinstance(
                    node.target, ast.Name):
                names.add(node.target.id)
        return names


def check_host_syncs(fn, static: Set[str], emit, *,
                     context: str = "jit") -> None:
    """Report TM030 host syncs on traced values inside one traced function.

    ``fn`` is a FunctionDef/Lambda whose parameters (minus ``static`` and
    ``self``) are traced; the taint propagates through local assignments,
    loop targets, and collective calls — a ``lax.psum``/``axis_index``
    RESULT is a device value even when no operand is tainted (collective
    results are device values; the shard_map bodies in
    ``parallel/sharded.py`` are the regression corpus).  ``emit(rule,
    node, message)`` reports; shared between the jit lint and the
    shard_map-body pass in shard_lint.
    """
    params = _param_names(fn)
    tainted = set(params) - set(static) - {"self"}
    for _ in range(4):  # fixpoint over loop-carried assignments
        grew = False
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                if (_tainted_loads(node.value, tainted)
                        or _is_collective_call(node.value)):
                    new = set().union(*(_target_names(t)
                                        for t in node.targets))
                    grew |= not new <= tainted
                    tainted |= new
            elif isinstance(node, ast.AugAssign):
                if (_tainted_loads(node.value, tainted)
                        and isinstance(node.target, ast.Name)):
                    grew |= node.target.id not in tainted
                    tainted.add(node.target.id)
            elif isinstance(node, ast.For):
                if _tainted_loads(node.iter, tainted):
                    new = _target_names(node.target)
                    grew |= not new <= tainted
                    tainted |= new
        if not grew:
            break

    def _sync_operand(e: ast.AST) -> bool:
        return bool(_tainted_loads(e, tainted)) or _is_collective_call(e)

    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if (isinstance(f, ast.Attribute) and f.attr in _SYNC_METHODS
                and not node.args and _sync_operand(f.value)):
            emit("TM030", node,
                 f".{f.attr}() on traced value "
                 f"{ast.unparse(f.value)!r} inside {context}")
        elif (isinstance(f, ast.Name) and f.id in _HOST_CASTS
                and node.args and _sync_operand(node.args[0])):
            emit("TM030", node,
                 f"{f.id}() on traced value "
                 f"{ast.unparse(node.args[0])!r} inside {context}")
        elif (isinstance(f, ast.Attribute) and f.attr in _NP_SYNC_FNS
                and _dotted(f.value) in _NP_MODULES
                and node.args and _sync_operand(node.args[0])):
            emit("TM030", node,
                 f"{_dotted(f)}() on traced value "
                 f"{ast.unparse(node.args[0])!r} inside {context} "
                 f"(device->host copy per call)")


def iter_py_files(paths: Iterable[str]):
    """Yield every ``.py`` file under ``paths`` (files or directory
    trees), skipping ``__pycache__``/``.git`` — shared walk for all three
    source-lint families."""
    for path in paths:
        if os.path.isdir(path):
            for root, dirs, files in os.walk(path):
                dirs[:] = [d for d in dirs
                           if d not in ("__pycache__", ".git")]
                for fn in sorted(files):
                    if fn.endswith(".py"):
                        yield os.path.join(root, fn)
        elif path.endswith(".py"):
            yield path


def lint_source(code: str, filename: str = "<string>") -> Findings:
    """Trace-safety lint one source string."""
    try:
        return _SourceLinter(code, filename).run()
    except SyntaxError as e:
        f = Findings()
        f.add("TM030", f"could not parse: {e}", severity="warning",
              location=f"{filename}:{e.lineno or 0}")
        return f


def lint_paths(paths: Iterable[str]) -> Findings:
    """Trace-safety lint files and directory trees of ``.py`` sources."""
    findings = Findings()
    for full in iter_py_files(paths):
        with open(full, encoding="utf-8") as fh:
            findings.extend(lint_source(fh.read(), full))
    return findings
