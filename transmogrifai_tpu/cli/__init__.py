"""Project-generator CLI — ``python -m transmogrifai_tpu.cli gen ...``.

Reference parity: the ``op gen`` codegen tool
(cli/src/main/scala/com/salesforce/op/cli/ — CommandParser, CliParameters,
CliExec; gen/ProblemSchema.scala, gen/ProblemKind.scala, gen/Ops.scala,
templates rendered into templates/simple/).  Given a sample dataset, a
response field and an id field, it infers the ML problem kind and every
column's semantic feature type, then generates a runnable Python project:
feature declarations, an ``OpApp`` wiring transmogrify → SanityChecker →
the right ModelSelector, and a smoke test.
"""
from .schema import ProblemKind, ProblemSchema, infer_problem_kind  # noqa: F401
from .generator import generate_project  # noqa: F401
from .main import main  # noqa: F401

__all__ = ["ProblemKind", "ProblemSchema", "infer_problem_kind",
           "generate_project", "main"]
