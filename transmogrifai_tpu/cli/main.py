"""CLI argument parsing and dispatch.

Reference: ``cli/CommandParser.scala:82-124`` (gen command: --input, --id,
--response, --schema/--auto, --overwrite, project name) and ``CliExec.scala``.
"""
from __future__ import annotations

import argparse
import sys
from typing import Dict, Optional, Sequence

from .generator import generate_project
from .schema import ProblemKind, ProblemSchema

__all__ = ["main"]


def _parse_overrides(pairs) -> Dict[str, str]:
    out = {}
    for p in pairs or ():
        if "=" not in p:
            raise SystemExit(f"--feature-type expects col=Type, got {p!r}")
        col, tname = p.split("=", 1)
        out[col] = tname
    return out


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        "transmogrifai_tpu", description="TransmogrifAI-TPU command line")
    sub = p.add_subparsers(dest="command", required=True)
    gen = sub.add_parser("gen", help="generate a new AutoML project")
    gen.add_argument("name", help="project name (e.g. Titanic)")
    gen.add_argument("--input", required=True,
                     help="sample CSV/Parquet/JSONL used to infer the schema")
    gen.add_argument("--id", required=True, dest="id_field",
                     help="id column name")
    gen.add_argument("--response", required=True,
                     help="response column name")
    gen.add_argument("--kind", choices=[k.value for k in ProblemKind],
                     default=None,
                     help="override the inferred problem kind")
    gen.add_argument("--feature-type", action="append", metavar="COL=TYPE",
                     help="override an inferred semantic type "
                          "(e.g. Age=Real); repeatable")
    gen.add_argument("--columns", default=None,
                     help="comma-separated column names for headerless CSVs "
                          "(the reference derives these from --schema)")
    gen.add_argument("--dest", default=".", help="output directory")
    gen.add_argument("--overwrite", action="store_true")
    return p


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.command == "gen":
        schema = ProblemSchema.from_file(
            args.name, args.input, args.response, args.id_field,
            overrides=_parse_overrides(args.feature_type), kind=args.kind,
            columns=args.columns.split(",") if args.columns else None)
        written = generate_project(schema, args.dest,
                                   overwrite=args.overwrite)
        print(f"{schema.kind.value} project {schema.name!r}: "
              f"{len(written)} files")
        for rel in sorted(written):
            print(f"  {written[rel]}")
        return 0
    return 2


if __name__ == "__main__":
    sys.exit(main())
