"""CLI argument parsing and dispatch.

Reference: ``cli/CommandParser.scala:82-124`` (gen command: --input, --id,
--response, --schema/--auto, --overwrite, project name) and ``CliExec.scala``.
"""
from __future__ import annotations

import argparse
import sys
from typing import Dict, Optional, Sequence

from .generator import generate_project
from .schema import ProblemKind, ProblemSchema

__all__ = ["main"]


def _parse_overrides(pairs) -> Dict[str, str]:
    out = {}
    for p in pairs or ():
        if "=" not in p:
            raise SystemExit(f"--feature-type expects col=Type, got {p!r}")
        col, tname = p.split("=", 1)
        out[col] = tname
    return out


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        "transmogrifai_tpu", description="TransmogrifAI-TPU command line")
    sub = p.add_subparsers(dest="command", required=True)
    gen = sub.add_parser("gen", help="generate a new AutoML project")
    gen.add_argument("name", help="project name (e.g. Titanic)")
    gen.add_argument("--input", required=True,
                     help="sample CSV/Parquet/JSONL used to infer the schema")
    gen.add_argument("--id", required=True, dest="id_field",
                     help="id column name")
    gen.add_argument("--response", required=True,
                     help="response column name")
    gen.add_argument("--kind", choices=[k.value for k in ProblemKind],
                     default=None,
                     help="override the inferred problem kind")
    gen.add_argument("--feature-type", action="append", metavar="COL=TYPE",
                     help="override an inferred semantic type "
                          "(e.g. Age=Real); repeatable")
    gen.add_argument("--columns", default=None,
                     help="comma-separated column names for headerless CSVs "
                          "(the reference derives these from --schema)")
    gen.add_argument("--dest", default=".", help="output directory")
    gen.add_argument("--overwrite", action="store_true")

    # dispatched before parsing (the analyzer owns its own parser; see
    # main()) — registered here so `tmog --help` lists it
    sub.add_parser(
        "lint", add_help=False,
        help="pipeline static analyzer: DAG lint + trace-safety lint "
             "(python -m transmogrifai_tpu.lint)")

    trc = sub.add_parser(
        "trace", help="validate + summarize an exported Chrome-trace "
                      "JSON file (obs.to_chrome_trace; the file itself "
                      "loads in chrome://tracing / Perfetto)")
    trc.add_argument("file", help="trace JSON file to summarize")
    trc.add_argument("--top", type=int, default=15,
                     help="how many top-duration spans to list")

    pod = sub.add_parser(
        "pod", help="run a command as an N-process local pod "
                    "(jax.distributed bootstrap via TMOG_POD_* env; "
                    "docs/distributed.md)")
    pod.add_argument("-n", "--num-processes", type=int, default=2,
                     help="pod size (default 2)")
    pod.add_argument("--devices", type=int, default=2,
                     help="forced host-platform devices per process "
                          "(CPU pods; default 2)")
    pod.add_argument("--timeout", type=float, default=600.0,
                     help="seconds before the pod is torn down")
    pod.add_argument("cmd", nargs=argparse.REMAINDER,
                     help="command to run in every pod process "
                          "(prefix with --)")

    srv = sub.add_parser(
        "serve", help="serve a persisted model (micro-batched scoring)")
    srv.add_argument("--model", required=True,
                     help="persisted model directory (OpWorkflowModel.save)")
    srv.add_argument("--name", default="default", help="registry model name")
    srv.add_argument("--host", default="127.0.0.1")
    srv.add_argument("--port", type=int, default=8080)
    srv.add_argument("--max-batch", type=int, default=64,
                     help="micro-batch row cap (largest shape bucket)")
    srv.add_argument("--max-latency-ms", type=float, default=5.0,
                     help="coalescing window before a partial batch runs")
    srv.add_argument("--max-queue-rows", type=int, default=1024,
                     help="bounded queue depth; beyond it requests shed 503")
    srv.add_argument("--deadline-ms", type=float, default=None,
                     help="default per-request deadline while queued")
    srv.add_argument("--warmup-json", default=None, metavar="JSON",
                     help="one raw row as JSON used to pre-compile every "
                          "shape bucket at startup")
    srv.add_argument("--score-jsonl", default=None, metavar="FILE",
                     help="offline mode: score a JSONL file of rows, print "
                          "one JSON result per line, and exit (no HTTP)")
    return p


def _run_serve(args) -> int:
    import json as _json

    from ..serving import ModelServer, ShedResult

    warmup_row = (_json.loads(args.warmup_json)
                  if args.warmup_json else None)
    rows = None
    if args.score_jsonl:
        with open(args.score_jsonl) as f:
            rows = [_json.loads(line) for line in f if line.strip()]
        if rows and warmup_row is None:
            warmup_row = dict(rows[0])
    server = ModelServer.from_path(
        args.model, name=args.name, max_batch=args.max_batch,
        max_latency_ms=args.max_latency_ms,
        max_queue_rows=args.max_queue_rows,
        default_deadline_ms=args.deadline_ms, warmup_row=warmup_row)
    if rows is not None:
        with server:
            for i in range(0, len(rows), args.max_batch):
                for res in server.score(rows[i:i + args.max_batch]):
                    if isinstance(res, ShedResult):
                        res = res.to_json()
                    print(_json.dumps(res, default=str))
            print(_json.dumps(server.snapshot(), default=str),
                  file=sys.stderr)
        return 0
    from ..serving.http import serve_forever

    server.start()
    print(f"serving {args.name!r} ({args.model}) on "
          f"http://{args.host}:{args.port} — POST /score, GET /metrics")
    try:
        serve_forever(server, args.host, args.port)
    finally:
        server.stop()
    return 0


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv[:1] == ["lint"]:
        # the analyzer owns its full argument grammar (paths, --dag,
        # --suppress, --json, --rules) — hand everything after `lint` over
        from ..analysis.cli import main as lint_main

        return lint_main(argv[1:])
    args = build_parser().parse_args(argv)
    if args.command == "pod":
        from ..distributed.runtime import main_pod_cli

        cmd = list(args.cmd)
        if cmd[:1] == ["--"]:
            cmd = cmd[1:]
        if not cmd:
            print("tmog pod: no command given (tmog pod -n 2 -- "
                  "python train.py)", file=sys.stderr)
            return 2
        args.cmd = cmd
        return main_pod_cli(args)
    if args.command == "gen":
        schema = ProblemSchema.from_file(
            args.name, args.input, args.response, args.id_field,
            overrides=_parse_overrides(args.feature_type), kind=args.kind,
            columns=args.columns.split(",") if args.columns else None)
        written = generate_project(schema, args.dest,
                                   overwrite=args.overwrite)
        print(f"{schema.kind.value} project {schema.name!r}: "
              f"{len(written)} files")
        for rel in sorted(written):
            print(f"  {written[rel]}")
        return 0
    if args.command == "serve":
        return _run_serve(args)
    if args.command == "trace":
        from ..obs.export import summarize_file

        summary = summarize_file(args.file, top_k=args.top)
        if summary is None:
            return 1
        try:
            print(summary)
        except BrokenPipeError:  # `tmog trace f.json | head` is fine
            pass
        return 0
    return 2


if __name__ == "__main__":
    sys.exit(main())
