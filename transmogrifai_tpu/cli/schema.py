"""Problem-schema inference from a sample dataset.

Reference: ``gen/ProblemSchema.scala:51-99`` (schema + response/id fields →
ProblemSchema), ``gen/ProblemKind.scala:36-66`` (Binary/Multi/Regression),
``gen/AvroField.scala`` (field → feature type).  The reference reads an Avro
schema or asks interactively (``--auto``); here inference is automatic from a
pandas-readable file, with the same override knobs.
"""
from __future__ import annotations

import dataclasses
import enum
from typing import Dict, List, Optional, Type

from ..features.builder import infer_schema_from_pandas
from ..types import feature_types as ft

__all__ = ["ProblemKind", "ProblemSchema", "infer_problem_kind"]


class ProblemKind(enum.Enum):
    BinaryClassification = "BinaryClassification"
    MultiClassification = "MultiClassification"
    Regression = "Regression"


def infer_problem_kind(series) -> ProblemKind:
    """Classify the response column (ProblemKind.scala semantics, auto mode):
    ≤2 distinct values → binary; integers/strings with few distinct values →
    multiclass; continuous numerics → regression."""
    vals = series.dropna()
    nunique = vals.nunique()
    if nunique <= 2:
        return ProblemKind.BinaryClassification
    kind = vals.dtype.kind
    if kind == "f" and (vals != vals.astype("int64", errors="ignore")).any():
        return ProblemKind.Regression
    if kind in ("i", "u", "f"):
        return (ProblemKind.MultiClassification if nunique <= 30
                else ProblemKind.Regression)
    return ProblemKind.MultiClassification


@dataclasses.dataclass
class ProblemSchema:
    """Everything codegen needs (gen/ProblemSchema.scala:51-60)."""

    name: str
    kind: ProblemKind
    response: str
    id_field: str
    #: column name -> semantic feature type, response/id excluded
    features: Dict[str, Type[ft.FeatureType]]
    input_path: Optional[str] = None

    #: column names for headerless CSVs (the reference names columns from an
    #: Avro schema file instead — SchemaSource.scala)
    columns: Optional[List[str]] = None

    @classmethod
    def from_file(cls, name: str, path: str, response: str, id_field: str,
                  overrides: Optional[Dict[str, str]] = None,
                  kind: Optional[str] = None,
                  columns: Optional[List[str]] = None) -> "ProblemSchema":
        import pandas as pd

        if path.endswith(".parquet"):
            df = pd.read_parquet(path)
        elif path.endswith((".json", ".jsonl")):
            df = pd.read_json(path, lines=path.endswith(".jsonl"))
        elif columns:
            df = pd.read_csv(path, header=None, names=list(columns))
        else:
            df = pd.read_csv(path)
        for col in (response, id_field):
            if col not in df.columns:
                raise ValueError(f"column {col!r} not in {sorted(df.columns)}")
        schema = infer_schema_from_pandas(df)
        if overrides:
            by_lower = {t.__name__.lower(): t for t in ft.all_feature_types()}
            for col, tname in overrides.items():
                try:
                    schema[col] = by_lower[tname.replace("_", "").lower()]
                except KeyError:
                    raise ValueError(
                        f"unknown feature type {tname!r} for column {col!r}")
        features = {c: t for c, t in schema.items()
                    if c not in (response, id_field)}
        problem = (ProblemKind(kind) if kind
                   else infer_problem_kind(df[response]))
        return cls(name=name, kind=problem, response=response,
                   id_field=id_field, features=features, input_path=path,
                   columns=list(columns) if columns else None)

    @property
    def feature_lines(self) -> List[str]:
        return [f'    "{c}": ft.{t.__name__},'
                for c, t in sorted(self.features.items())]
