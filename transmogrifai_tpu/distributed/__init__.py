"""Multi-process pod runtime — ROADMAP item 3's missing layer.

``runtime`` boots ``jax.distributed`` (or stays inert in a single
process), ``hostshard`` assigns each process its contiguous row range of
every reader, and ``podstream`` runs the streaming two-pass fit as a
cooperating pod: per-host partial states, allgather merges at pass
boundaries, coordinator-only durable side effects, and cross-host-count
elastic resume.  See docs/distributed.md.

This package resolves its exports LAZILY: the pod bootstrap in the
top-level ``__init__`` must import ``distributed.runtime`` before any
jax computation, and ``hostshard`` pulls the reader stack — eager
imports here would defeat the ordering.
"""
from typing import Any

__all__ = [
    "PodContext", "PodTimeoutError", "current_pod", "init_pod_from_env",
    "launch_local_pod", "pick_free_port", "pod_env",
    "HostShardedReader", "ShardPlan", "count_rows", "host_ranges",
    "plan_host_shard", "PodStreamContext",
]

_RUNTIME = {"PodContext", "PodTimeoutError", "current_pod",
            "init_pod_from_env", "launch_local_pod", "pick_free_port",
            "pod_env"}
_HOSTSHARD = {"HostShardedReader", "ShardPlan", "count_rows",
              "host_ranges", "plan_host_shard"}


def __getattr__(name: str) -> Any:
    if name in _RUNTIME:
        from . import runtime

        return getattr(runtime, name)
    if name in _HOSTSHARD:
        from . import hostshard

        return getattr(hostshard, name)
    if name == "PodStreamContext":
        from .podstream import PodStreamContext

        return PodStreamContext
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
