"""Host-sharded ingest — each pod process streams ONLY its row range.

The out-of-core driver (workflow/streaming.py) bounded peak host memory
per chunk; a pod bounds it per HOST: the global row space [0, N) is
split into one contiguous range per process, every reader serves a
``host_range`` window of its chunk stream (readers/base.py
``iter_chunks(host_range=...)``), and no process ever parses — let
alone materializes — rows outside its range past the window filter.
Combined with the process-local :class:`~transmogrifai_tpu.parallel.
ingest.ShardedMatrixWriter` path, the packed (N, D) matrix exists only
as per-host device shards: the 10M×500 regime stops being a single-host
RAM problem.

Range assignment is CONTIGUOUS (host h owns one block, longer blocks
first when ``rows % hosts != 0``) so that a host's chunk sequence is
byte-identical to the same rows' chunk sequence in a single-process run
— the property the cross-host-count checkpoint resume leans on
(distributed/podstream.py: per-host partial states merge in host order,
so any process count reproduces any other bit-exactly).

Row-count resolution: splitting needs the EXACT total row count before
any pass.  ``Reader.estimate_rows`` answers instantly for in-memory
readers and Avro (block headers carry record counts); event-time
readers (readers/aggregates.py, readers/events.py) answer EXACTLY too —
their rows are distinct entity KEYS, counted by the cached key scan, so
a ``host_range`` over an aggregate reader is a contiguous slice of the
sorted key universe.  Formats whose estimate is a heuristic (CSV/JSONL
line counts — quoted newlines, quarantined rows) fall back to a
COUNTING PRE-PASS over the chunk stream, with a warning naming the
reader (the satellite contract).
"""
from __future__ import annotations

import warnings
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..readers.base import Reader

__all__ = ["host_ranges", "range_chunks", "count_rows", "plan_host_shard",
           "ShardPlan", "HostShardedReader"]


def host_ranges(total_rows: int, process_count: int
                ) -> List[Tuple[int, int]]:
    """Contiguous [start, stop) row range per process.

    The first ``total_rows % process_count`` hosts take one extra row —
    the uneven tail is spread, never dumped on the last host (unit-tested
    with rows % hosts != 0).
    """
    n, p = int(total_rows), int(process_count)
    if p < 1:
        raise ValueError(f"process_count must be >= 1, got {p}")
    if n < p:
        raise ValueError(
            f"cannot shard {n} row(s) across {p} processes — every "
            f"process needs at least one row (shrink the pod)")
    base, rem = divmod(n, p)
    out = []
    start = 0
    for h in range(p):
        stop = start + base + (1 if h < rem else 0)
        out.append((start, stop))
        start = stop
    return out


def range_chunks(rng: Tuple[int, int], chunk_rows: int) -> int:
    """Nominal chunk count of a [start, stop) window at ``chunk_rows``.

    The window rides the SOURCE's global chunk grid, so a misaligned
    window can yield one more chunk than this (both edge chunks
    partial).  Consumers use this only for checkpoint STEP PACING
    (distributed/podstream.py), where the estimate is deterministic and
    identical on every process, and any steps left unfired when a
    stream ends early are drained at pass end — the exchange can never
    deadlock on the off-by-one.  Durable cursors always record ACTUAL
    delivered chunk counts.
    """
    n = rng[1] - rng[0]
    return (n + chunk_rows - 1) // chunk_rows


def count_rows(reader: Reader, raw_features, chunk_rows: int = 4096) -> int:
    """The counting pre-pass: one full chunk iteration summing lengths.

    Runs with the reader's resilience config live (retry + quarantine),
    so the count matches exactly what later passes will yield — a
    quarantined row is already absent here.

    The result is memoized on the reader when it carries a count cache
    (CSV/JSONL: ``cached_row_count``/``cache_row_count``, keyed by
    (path, mtime, size) so a rewritten file re-counts): a pod that
    trains, checkpoints, and resumes over the same file pays the full
    pre-pass once, not once per plan.
    """
    cached_get = getattr(reader, "cached_row_count", None)
    if cached_get is not None:
        hit = cached_get()
        if hit is not None:
            return hit
    rcfg = getattr(reader, "resilience", None)
    if rcfg is not None and rcfg.retry is not None:
        from ..readers.resilience import RetryingChunkStream

        stream = RetryingChunkStream(
            lambda: reader.iter_chunks(raw_features, chunk_rows),
            rcfg.retry)
    else:
        stream = reader.iter_chunks(raw_features, chunk_rows)
    rows = sum(len(chunk) for chunk in stream)
    cached_put = getattr(reader, "cache_row_count", None)
    if cached_put is not None:
        cached_put(rows)
    return rows


class ShardPlan:
    """The pod's agreed view of one reader: exact total rows + the
    per-process contiguous ranges.  Identical on every process (total
    rows resolve deterministically), so no exchange is needed to agree.
    """

    def __init__(self, total_rows: int, ranges: List[Tuple[int, int]],
                 chunk_rows: int, counted: bool):
        self.total_rows = int(total_rows)
        self.ranges = list(ranges)
        self.chunk_rows = int(chunk_rows)
        #: True when the total came from a counting pre-pass rather than
        #: an exact reader estimate
        self.counted = bool(counted)

    def range_of(self, process_index: int) -> Tuple[int, int]:
        return self.ranges[process_index]

    def chunks_of(self, process_index: int) -> int:
        return range_chunks(self.ranges[process_index], self.chunk_rows)

    def max_chunks(self) -> int:
        return max(range_chunks(r, self.chunk_rows) for r in self.ranges)

    def to_json(self) -> Dict[str, Any]:
        return {"totalRows": self.total_rows, "chunkRows": self.chunk_rows,
                "ranges": [list(r) for r in self.ranges],
                "counted": self.counted}


def plan_host_shard(reader: Reader, raw_features, chunk_rows: int,
                    process_count: int) -> ShardPlan:
    """Resolve the exact row count and split it across the pod.

    ``reader.estimate_rows()`` is trusted only when the reader declares
    it exact (``estimate_rows_exact()``); otherwise the counting
    pre-pass runs with a warning — a mis-sized range map would silently
    drop or duplicate rows, which is never worth one saved pass.
    """
    rows: Optional[int] = None
    counted = False
    if reader.estimate_rows_exact():
        rows = reader.estimate_rows()
    if rows is None:
        est = reader.estimate_rows()
        warnings.warn(
            f"{type(reader).__name__} cannot report an exact row count "
            f"(estimate: {est}); host sharding is running a counting "
            f"pre-pass over the chunk stream", stacklevel=2)
        rows = count_rows(reader, raw_features, chunk_rows)
        counted = True
    return ShardPlan(rows, host_ranges(rows, process_count), chunk_rows,
                     counted)


class HostShardedReader(Reader):
    """A reader restricted to row windows of an inner reader.

    Normally holds ONE range (this process's shard); a cross-host-count
    resume hands a process SEVERAL adopted ranges (the dead pod's
    per-host entries), each streamed as its own self-aligned chunk
    sequence — ``iter_chunks`` chains them in range order.

    ``inner_reader`` is the LOGICAL identity: checkpoint fingerprints
    describe the source reader, never the wrapper, so a checkpoint
    written by a 2-process pod resumes under any other process count
    (the pod record itself is advisory).
    """

    def __init__(self, inner: Reader, ranges: Sequence[Tuple[int, int]]):
        self.inner_reader = inner
        self.ranges = [tuple(map(int, r)) for r in ranges]
        for start, stop in self.ranges:
            if stop < start or start < 0:
                raise ValueError(f"bad host range ({start}, {stop})")

    @property
    def resilience(self):
        """The inner reader's resilience config (retry/quarantine) — the
        streaming driver reads it off whatever reader it is handed."""
        return getattr(self.inner_reader, "resilience", None)

    def estimate_rows(self) -> Optional[int]:
        return sum(stop - start for start, stop in self.ranges)

    def estimate_rows_exact(self) -> bool:
        return True

    def generate_dataset(self, raw_features):
        ds = self.inner_reader.generate_dataset(raw_features)
        if len(self.ranges) == 1:
            start, stop = self.ranges[0]
            return ds.slice(start, min(stop, len(ds)))
        raise NotImplementedError(
            "multi-range HostShardedReader is chunk-stream only")

    def iter_chunks(self, raw_features, chunk_rows: int,
                    host_range: Optional[Tuple[int, int]] = None
                    ) -> "_ChainedChunkStream":
        if host_range is not None:
            raise ValueError("HostShardedReader already carries its ranges")
        return _ChainedChunkStream(self.inner_reader, raw_features,
                                   chunk_rows, self.ranges)


class _ChainedChunkStream:
    """Chains one windowed chunk stream per range, LAZILY (a range's
    stream — and its file handle — opens only when the previous range is
    exhausted).  The inner streams are real ``ChunkStream``s and fire the
    ``reader.chunk`` fault point themselves; this wrapper deliberately
    does not re-fire it."""

    def __init__(self, inner: Reader, raw_features, chunk_rows: int,
                 ranges: Sequence[Tuple[int, int]]):
        self._inner = inner
        self._raw = raw_features
        self._chunk_rows = chunk_rows
        self._ranges = list(ranges)
        self._pos = -1
        self._cur = None
        self._done_bytes = 0
        self.bytes_read = 0

    def __iter__(self):
        return self

    def __next__(self):
        while True:
            if self._cur is None:
                self._pos += 1
                if self._pos >= len(self._ranges):
                    raise StopIteration
                self._cur = self._inner.iter_chunks(
                    self._raw, self._chunk_rows,
                    host_range=self._ranges[self._pos])
            try:
                chunk = next(self._cur)
            except StopIteration:
                self._done_bytes += int(
                    getattr(self._cur, "bytes_read", 0) or 0)
                self._cur = None
                continue
            self.bytes_read = self._done_bytes + int(
                getattr(self._cur, "bytes_read", 0) or 0)
            return chunk
