"""Pod streaming-fit protocol — cooperating processes, one train.

The streaming two-pass driver (workflow/streaming.py) already reduced a
fit pass to MERGEABLE MONOID states plus a chunk cursor; this module is
the observation that the same algebra distributes across processes for
free:

* each process streams ONLY its host range (distributed/hostshard.py)
  and folds its own partial state per estimator; event-time readers
  (readers/events.py) slot straight in — their rows are distinct entity
  keys, the host range is a contiguous slice of the sorted key universe
  (their fold buffers only owned keys' in-window events), and the same
  fold state also merges under crc32 key-hash ownership
  (``EventFoldState.shard`` / ``merge_fold_states``) with bit-identical
  finalized output under any partition;
* at every pass boundary the partial states allgather (host order) and
  merge — every process finishes the pass with the IDENTICAL merged
  state, so the rest of the train (fold validation, selector sweep,
  tail fit) replicates deterministically instead of diverging;
* durable side effects (checkpoints, quarantine sidecars, bench JSON)
  happen on the COORDINATOR only, fenced by a pod barrier so a kill
  after the barrier implies the artifact is on disk (lint rule TM047
  pins the convention statically).

Cross-host-count elastic resume is the payoff: a checkpoint stores one
record PER ORIGINAL HOST — its row range, chunk cursor, and partial
states.  A resume under ANY process count adopts the original entries
(round-robin), keeps each entry's accumulation separate, and merges in
entry order at the pass boundary — producing bit-for-bit the states the
uninterrupted original pod would have produced, with the process-count
change counted as a ``mesh_repacks`` elastic event.  The pod identity
itself (``pod.processCount``) rides in the fingerprint's ADVISORY
section: never compared, exactly like PR 9's mesh record.
"""
from __future__ import annotations

import resource
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .hostshard import (HostShardedReader, ShardPlan, plan_host_shard,
                        range_chunks)
from .runtime import PodContext

__all__ = ["PodEntry", "PodStreamContext", "BlockPlane"]


def _rss_now_mb() -> float:
    """CURRENT resident set size (VmRSS), not the high-water mark —
    import/compile transients push ``ru_maxrss`` far above steady state,
    which would mask what ingest actually retains.  glibc arenas are
    trimmed first (best effort) so freed chunk-parse transients stop
    counting as resident.  Falls back to the high-water on non-/proc
    platforms."""
    try:
        import ctypes

        ctypes.CDLL("libc.so.6").malloc_trim(0)
    except (OSError, AttributeError):  # pragma: no cover - non-glibc
        pass
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return float(line.split()[1]) / 1024.0
    except (OSError, ValueError, IndexError):  # pragma: no cover
        pass
    return resource.getrusage(  # pragma: no cover - /proc-less platform
        resource.RUSAGE_SELF).ru_maxrss / 1024.0


class PodEntry:
    """One ORIGINAL host's share of the train, owned by this process.

    Fresh pods own exactly their own range; a cross-host-count resume
    hands a process several adopted entries (or none of some).  The
    ``entry_id`` is the original host index — the global merge order.
    """

    def __init__(self, entry_id: int, rng: Tuple[int, int],
                 skip_chunks: int = 0,
                 initial: Optional[Dict[str, Any]] = None):
        self.entry_id = int(entry_id)
        self.range = (int(rng[0]), int(rng[1]))
        #: chunks of this entry already consumed by the checkpointed run
        self.skip_chunks = int(skip_chunks)
        #: uid -> decoded exported state payload to resume from
        self.initial = initial or {}

    @property
    def rows(self) -> int:
        return self.range[1] - self.range[0]

    def chunks(self, chunk_rows: int) -> int:
        return range_chunks(self.range, chunk_rows)


def _owner(entry_id: int, process_count: int) -> int:
    """Deterministic adoption rule: original host h belongs to process
    h % P' — every process derives every owner without an exchange."""
    return entry_id % process_count


class PodStreamContext:
    """Everything ``fit_dag_streaming`` needs to run as one pod member."""

    def __init__(self, pod: PodContext, reader, raw_features,
                 chunk_rows: int, plan: Optional[ShardPlan] = None):
        self.pod = pod
        self.inner_reader = reader
        self.raw_features = raw_features
        self.chunk_rows = int(chunk_rows)
        if plan is None:
            plan = plan_host_shard(reader, raw_features, chunk_rows,
                                   pod.process_count)
        self.plan = plan
        self.total_rows = plan.total_rows
        #: ranges of the ORIGINAL pod this train continues (fresh: ours)
        self.all_ranges: List[Tuple[int, int]] = list(plan.ranges)
        self.saved_process_count: Optional[int] = None
        self.repacked = False
        self.entries: List[PodEntry] = [
            PodEntry(h, rng) for h, rng in enumerate(self.all_ranges)
            if _owner(h, pod.process_count) == pod.process_index]
        #: pass index the in-flight resume cursor applies to (None = no
        #: mid-pass resume)
        self.resume_pass: Optional[int] = None
        #: the streaming driver flips this after the global CV label sync
        self.labels_synced = False
        # first-touch the collective machinery (gloo init, the allgather
        # jit programs, device buffers) BEFORE the RSS baseline probe:
        # that cost is the pod RUNTIME's, not ingest's, and it would
        # otherwise pollute the per-host ingest delta the POD_SMOKE
        # memory gate measures
        if pod.active:
            pod.allgather_obj(b"\x00" * (1 << 20))
            pod.barrier("warmup")
        #: resident set size at context construction (train start) — the
        #: baseline the ingest delta subtracts, so the POD_SMOKE memory
        #: gate compares what INGEST retains, not the interpreter's floor
        self._rss0_mb = round(_rss_now_mb(), 2)
        self._rss_after_ingest_mb: Optional[float] = None

    # -- resume adoption -----------------------------------------------------

    def adopt_resume(self, resume, ests_by_uid=None) -> None:
        """Re-own the checkpoint's original-host entries under the
        CURRENT process count.  ``resume`` is the driver's ResumeState;
        its manifest-level pod record carries the original ranges, and
        the in-flight record (when present) carries per-entry cursors +
        state payloads, decoded lazily by ``init_entry_states``."""
        pod_rec = getattr(resume, "pod", None)
        if not pod_rec:
            return
        ranges = [tuple(map(int, r)) for r in pod_rec["ranges"]]
        saved_count = int(pod_rec.get("processCount", len(ranges)))
        self.all_ranges = ranges
        self.saved_process_count = saved_count
        per_entry: Dict[int, Dict[str, Any]] = {}
        cur = resume.current
        if cur is not None and cur.get("pod_entries"):
            self.resume_pass = int(cur["pass"])
            for rec in cur["pod_entries"]:
                per_entry[int(rec["entry"])] = rec
        self.entries = []
        for h, rng in enumerate(ranges):
            if _owner(h, self.pod.process_count) != self.pod.process_index:
                continue
            rec = per_entry.get(h)
            self.entries.append(PodEntry(
                h, rng,
                skip_chunks=int(rec["chunks_done"]) if rec else 0,
                initial=dict(rec.get("states") or {}) if rec else {}))
        if saved_count != self.pod.process_count and not self.repacked:
            # the elastic event: same logical train, different host count
            self.repacked = True
            self.pod.repacks += 1
            from ..utils.profiling import count_elastic

            count_elastic("mesh_repacks")
            from ..obs.flight import record_event

            record_event("pod.repack", saved=saved_count,
                         current=self.pod.process_count)

    # -- reader + geometry ---------------------------------------------------

    def local_reader(self) -> HostShardedReader:
        return HostShardedReader(self.inner_reader,
                                 [e.range for e in self.entries])

    @property
    def local_rows(self) -> int:
        return sum(e.rows for e in self.entries)

    def entry_row_counts(self) -> List[int]:
        return [e.rows for e in self.entries]

    def local_chunks(self) -> int:
        return sum(e.chunks(self.chunk_rows) for e in self.entries)

    def chunks_of_process(self, p: int) -> int:
        return sum(range_chunks(rng, self.chunk_rows)
                   for h, rng in enumerate(self.all_ranges)
                   if _owner(h, self.pod.process_count) == p)

    def fingerprint_advisory(self) -> Dict[str, Any]:
        """The ADVISORY half (never compared on resume): host counts are
        elastic by design, the pod analogue of the PR 9 mesh record."""
        return {"pod": {"processCount": self.pod.process_count}}

    def pod_record(self) -> Dict[str, Any]:
        """Manifest record every checkpoint save carries: the ORIGINAL
        ranges (stable across resumes — they define the chunk folds every
        later pass must reproduce) plus the original host count."""
        return {"ranges": [list(r) for r in self.all_ranges],
                "processCount": (self.saved_process_count
                                 if self.saved_process_count is not None
                                 else self.pod.process_count)}

    # -- per-entry states ----------------------------------------------------

    def init_entry_states(self, ests, decode_payload=None,
                          use_initial: bool = False
                          ) -> List[Dict[str, Any]]:
        """One {uid: state} dict per owned entry — fresh ``begin_fit``s,
        or (on the resumed pass, ``use_initial=True``) states imported
        from the checkpoint's per-entry payloads via
        ``decode_payload(raw) -> payload``."""
        out = []
        for e in self.entries:
            states: Dict[str, Any] = {}
            for est in ests:
                raw = e.initial.get(est.uid) if use_initial else None
                if raw is not None and decode_payload is not None:
                    states[est.uid] = est.import_fit_state(
                        decode_payload(raw))
                else:
                    states[est.uid] = est.begin_fit()
            out.append(states)
        return out

    def merge_pass_states(self, ests, entry_states: List[Dict[str, Any]]
                          ) -> Dict[str, Any]:
        """Allgather every entry's exported states and merge in ENTRY
        ORDER — the deterministic global fold every process reproduces
        identically.  Local states also round-trip export→import so the
        fold is the same computation on every process (and on a resumed
        one)."""
        local = [(e.entry_id,
                  {est.uid: est.export_fit_state(st[est.uid])
                   for est in ests})
                 for e, st in zip(self.entries, entry_states)]
        gathered = self.pod.allgather_obj(local)
        flat = sorted((rec for part in gathered for rec in part),
                      key=lambda r: r[0])
        ids = [rec[0] for rec in flat]
        if ids != sorted(set(ids)) or len(ids) != len(self.all_ranges):
            raise RuntimeError(
                f"pod pass exchange is missing entries: got {ids}, "
                f"expected one of each of 0..{len(self.all_ranges) - 1}")
        merged: Dict[str, Any] = {}
        for est in ests:
            parts = [est.import_fit_state(payload[est.uid])
                     for _h, payload in flat]
            acc = parts[0]
            for p in parts[1:]:
                acc = est.merge_states(acc, p)
            merged[est.uid] = acc
        from ..obs.flight import record_event

        record_event("pod.pass_merge", process=self.pod.process_index,
                     entries=len(flat), estimators=len(list(ests)))
        return merged

    # -- barrier-fenced checkpoint protocol ----------------------------------

    def pass_saver(self, manager, pass_index: int, label: str, ests,
                   entry_states: List[Dict[str, Any]]):
        """Mid-pass checkpoint coordinator for one pod fit pass, or None
        when the pass has no agreed mid-pass steps.  Steps happen at
        multiples of ``manager.every_chunks`` of the BUSIEST process's
        chunk count; every process joins every step (processes that ran
        out of chunks contribute their final cursors), so the exchange
        can never deadlock on uneven ranges."""
        if manager is None:
            return None
        steps = max(self.chunks_of_process(p)
                    for p in range(self.pod.process_count)
                    ) // manager.every_chunks
        return _PodPassSaver(self, manager, pass_index, label, ests,
                             entry_states, steps)

    def complete_pass(self, manager, pass_index: int, label: str,
                      models, state_payloads=None) -> None:
        """Pass-boundary save: the models (identical on every process —
        they came from the merged states) land on disk via the
        coordinator, fenced by a barrier."""
        if manager is None:
            return
        if self.pod.is_coordinator():
            manager.complete_pass(pass_index, label, self.total_rows,
                                  models, state_payloads=state_payloads)
        self.pod.barrier(f"ckpt.pass{pass_index}")
        # pass boundary: audit the whole pod's collective ledgers
        # (TM074) while every process is provably at the same point
        from ..analysis.contracts import check_collective_consistency

        check_collective_consistency(self.pod, label=f"pass{pass_index}")

    # -- CV label sync -------------------------------------------------------

    def sync_cv_labels(self, cv_ctx) -> None:
        """Replace the context's LOCAL label vector with the global one:
        slice local labels by entry, allgather, reorder by range start,
        concatenate.  Runs once, right after labels_ready flips."""
        y_local = cv_ctx.y
        counts = self.entry_row_counts()
        if y_local is None or len(y_local) != sum(counts):
            raise RuntimeError(
                f"pod CV label sync: local labels {0 if y_local is None else len(y_local)} "
                f"rows, entries cover {sum(counts)}")
        parts, off = [], 0
        for e, n in zip(self.entries, counts):
            parts.append((e.range[0], y_local[off:off + n]))
            off += n
        gathered = self.pod.allgather_obj(parts)
        flat = sorted((rec for p in gathered for rec in p),
                      key=lambda r: r[0])
        cv_ctx.y = (np.concatenate([y for _s, y in flat])
                    if flat else np.zeros(0))
        if len(cv_ctx.y) != self.total_rows:
            raise RuntimeError(
                f"pod CV label sync: gathered {len(cv_ctx.y)} rows, "
                f"expected {self.total_rows}")

    # -- materialized-column gather ------------------------------------------

    def note_ingest_rss(self, ingest) -> None:
        """Post-ingest, pre-gather resident set — the number the
        POD_SMOKE memory gate compares per host (the gather that follows
        deliberately does not count as ingest).  The local materialized
        buffers are still live here, so (after - before) is what
        host-sharded ingest RETAINED on this host."""
        self._rss_after_ingest_mb = round(_rss_now_mb(), 2)
        ingest.pod = self.to_json()
        from ..obs.flight import record_event

        record_event("pod.ingest", process=self.pod.process_index,
                     local_rows=self.local_rows,
                     rss_after_mb=self._rss_after_ingest_mb,
                     rss_delta_mb=ingest.pod.get("rssIngestDeltaMb"))

    def gather_columns(self, cols: Dict[str, Any]) -> Dict[str, Any]:
        """Assemble the full materialized dataset on EVERY process from
        the per-host pieces: split each local column by entry, allgather,
        reorder by global range start, concatenate.

        This is the smoke-testable host-level assembly; device-resident
        matrices take the :class:`~transmogrifai_tpu.parallel.ingest.
        ShardedMatrixWriter` process-local path instead and never ride
        through here."""
        from ..types.columns import FeatureColumn

        counts = self.entry_row_counts()
        local = []
        for e, n, off in zip(self.entries, counts,
                             np.cumsum([0] + counts)[:-1]):
            sliced = {name: col.slice(int(off), int(off + n))
                      for name, col in cols.items()}
            local.append((e.range[0], sliced))
        gathered = self.pod.allgather_obj(local)
        flat = sorted((rec for p in gathered for rec in p),
                      key=lambda r: r[0])
        out: Dict[str, Any] = {}
        names = list(cols.keys())
        for name in names:
            pieces = [part[name] for _s, part in flat]
            first = pieces[0]
            vals = [np.asarray(p.values) for p in pieces]
            values = np.concatenate(vals) if vals else np.zeros(0)
            mask = None
            if first.mask is not None:
                mask = np.concatenate([np.asarray(p.mask) for p in pieces])
            out[name] = FeatureColumn(first.ftype, values, mask,
                                      first.vmeta)
        return out

    # -- quarantine + reporting ----------------------------------------------

    def flush_quarantine(self, sink) -> None:
        """Gather every process's buffered quarantine entries; the
        coordinator appends them to the ONE sidecar (dedupe on
        (source, location) as always) — non-coordinators never open it."""
        # sink presence is pod-uniform config (the launcher hands every
        # process the same sidecar setting), so the two sequences below
        # can never split a live pod
        if sink is None:  # tmog: disable=TM071
            self.pod.barrier("quarantine.none")
            return
        pending = sink.drain_pending()
        gathered = self.pod.allgather_obj(pending)
        if self.pod.is_coordinator():
            for part in gathered[1:]:  # coordinator's own already landed
                sink.absorb(part)
        self.pod.barrier("quarantine.flush")
        from ..analysis.contracts import check_collective_consistency

        check_collective_consistency(self.pod, label="quarantine.flush")

    def to_json(self) -> Dict[str, Any]:
        return {
            "processIndex": self.pod.process_index,
            "processCount": self.pod.process_count,
            "totalRows": self.total_rows,
            "localRows": self.local_rows,
            "entries": [{"id": e.entry_id, "range": list(e.range),
                         "skipChunks": e.skip_chunks}
                        for e in self.entries],
            "counted": self.plan.counted,
            "repacked": self.repacked,
            "savedProcessCount": self.saved_process_count,
            "rssBeforeIngestMb": self._rss0_mb,
            "rssAfterIngestMb": self._rss_after_ingest_mb,
            "rssIngestDeltaMb": (
                None if self._rss_after_ingest_mb is None
                else round(max(self._rss_after_ingest_mb - self._rss0_mb,
                               0.0), 2)),
        }


class BlockPlane:
    """Block-streaming reduction passes over one host's shard — the
    10M-row pod data plane (ROADMAP item 3).

    ``source`` is either a :class:`~transmogrifai_tpu.parallel.ingest.
    BlockSpillMatrix` (blocks re-read from the spill file one at a
    time — peak host residency is ONE block) or a resident ``(rows,
    cols)`` array (sliced on the same deterministic ``block_grid``).
    ``run_pass`` folds every block through a device-resident accumulator
    with a jitted kernel: the fold ENQUEUES and returns (PR 17 async
    dispatch), so the host reads/prepares the next block while the
    device folds the current one, and the single blocking fetch at pass
    end books as drain — ``drainFracOfWall`` stays an honest overlap
    measure.  ONE cross-host exchange per pass (``combine``: allgather +
    process-order sum) turns host partials into the identical global
    reduction on every process.

    Determinism contract, which the scale bench's parity and resume
    gates check bit-for-bit: fold order is the block-grid order, the
    cross-host combine is a fixed process-order f32 sum, and a stripe
    resume restores the exact device accumulator bytes — so blocked vs
    resident RESIDENCY, any kill/resume split, and every process member
    produce byte-identical results.

    Stripe checkpoints (``stripes`` = a :class:`~transmogrifai_tpu.
    workflow.checkpoint.BlockStripeStore`) are PROCESS-PRIVATE: each
    host persists only its own block cursor + partial accumulator, so
    resume wall scales with the per-host shard, never the global row
    count.  TM047's coordinator-only rule does not apply — a stripe is
    this host's private scratch, like a per-process flight dump; the
    COORDINATED artifacts (sweep cursor, manifests) still ride the
    barrier-fenced managers.
    """

    def __init__(self, pod: Optional[PodContext], source, *,
                 stripes=None, stripe_every: int = 0,
                 label: str = "blockplane"):
        self.pod = pod
        self._source = source
        rows, cols = source.shape
        self.rows, self.cols = int(rows), int(cols)
        self.stripes = stripes
        self.stripe_every = int(stripe_every)
        self.label = label
        #: True once any pass restored a stripe cursor (the resume gate)
        self.resumed = False
        self.pass_walls: Dict[str, float] = {}

    # -- block geometry ------------------------------------------------------

    def block_bounds(self) -> List[Tuple[int, int]]:
        """The deterministic [start, stop) grid this plane folds in —
        the spill file's own bounds, or ``block_grid`` over the resident
        shard (identical by construction when the writer was sized with
        the same retain budget)."""
        bounds = getattr(self._source, "block_bounds", None)
        if bounds is not None:
            return list(bounds)
        from ..parallel.sharded import block_grid

        X = np.asarray(self._source)
        itemsize = X.dtype.itemsize if X.size else 4
        return block_grid(self.rows, self.cols, dtype_bytes=itemsize)

    def blocks(self, start_block: int = 0):
        """Yield ``(start, stop, block)`` in grid order, skipping the
        first ``start_block`` blocks without materializing them."""
        bounds = self.block_bounds()
        it = getattr(self._source, "iter_blocks", None)
        if it is not None:
            for (start, stop), blk in zip(bounds[start_block:],
                                          it(start_block)):
                yield start, stop, blk
        else:
            X = np.asarray(self._source)
            for start, stop in bounds[start_block:]:
                yield start, stop, X[start:stop]

    # -- cross-host combine --------------------------------------------------

    def combine(self, arr: np.ndarray) -> np.ndarray:
        """ONE exchange: allgather the host partials and sum them in
        PROCESS ORDER — the fixed-order f32 fold every process (and any
        resume) reproduces bit-exactly.  Identity when no pod is live."""
        part = np.asarray(arr)
        if self.pod is None or not self.pod.active:
            return part
        parts = self.pod.allgather_obj(part)
        acc = parts[0]
        for p in parts[1:]:
            acc = acc + p
        return acc

    # -- the pass driver -----------------------------------------------------

    def run_pass(self, name: str, init_acc: np.ndarray, fold, *,
                 combine: bool = True) -> np.ndarray:
        """Fold every local block through ``fold(acc, block, start,
        stop) -> acc`` (a jitted kernel — it must only ENQUEUE), fetch
        the host partial once, and return the cross-host combined
        reduction (or the bare partial with ``combine=False``).

        With stripes enabled, every ``stripe_every`` blocks the current
        accumulator is fetched (overlapped — the fetch drains compute
        that had to finish anyway) and persisted with its block cursor;
        a rerun restores the accumulator bytes and resumes at the
        cursor.  A final stripe marks the pass complete so reruns of
        finished passes skip straight to the saved result.
        """
        import jax.numpy as jnp

        from ..utils.profiling import fetch_timed

        label = f"{self.label}.{name}"
        t0 = time.perf_counter()
        bounds = self.block_bounds()
        n_total = len(bounds)
        skip = 0
        acc = None
        if self.stripes is not None:
            rec = self.stripes.load(label)
            if rec is not None and "acc" in rec.get("accs", {}):
                skip = min(int(rec["blocksDone"]), n_total)
                acc = jnp.asarray(rec["accs"]["acc"])
                if skip > 0:
                    self.resumed = True
        if acc is None:
            acc = jnp.asarray(np.asarray(init_acc))
        done = skip
        prev = None
        for start, stop, blk in self.blocks(skip):
            acc = fold(acc, blk, start, stop)
            # lag-one backpressure (the PR 17 double-buffer idiom): wait
            # for the PREVIOUS fold before enqueuing past it, so at most
            # two blocks are ever in flight — without this the host
            # races ahead and every enqueued block's device buffer stays
            # alive, unbounding the very residency this plane bounds.
            # The current fold still overlaps the next block's read.
            if prev is not None:
                wait = getattr(prev, "block_until_ready", None)
                if wait is not None:
                    wait()
            prev = acc
            done += 1
            if (self.stripes is not None and self.stripe_every > 0
                    and done < n_total and done % self.stripe_every == 0):
                host = np.asarray(fetch_timed(
                    acc, tag="blockplane.checkpoint", overlapped=True))
                self.stripes.save(label, done, {"acc": host})
        part = np.asarray(fetch_timed(acc, tag="blockplane.pass"))
        if self.stripes is not None and done >= n_total:
            self.stripes.save(label, n_total, {"acc": part})
        wall = time.perf_counter() - t0
        self.pass_walls[name] = round(wall, 4)
        self._record_observation(name, wall)
        return self.combine(part) if combine else part

    def newton_blocks(self, y: np.ndarray, w: Optional[np.ndarray] = None):
        """Adapter for ``parallel.sharded.fit_logreg_newton_blocked``:
        a ``blocks_fn`` yielding ``(X_b, y_b, w_b)`` with the label /
        weight vectors sliced on this plane's LOCAL row space (the
        caller passes vectors of ``self.rows`` entries)."""
        def blocks_fn():
            for start, stop, blk in self.blocks():
                yb = np.asarray(y[start:stop], dtype=np.float32)
                wb = (np.ones(stop - start, np.float32) if w is None
                      else np.asarray(w[start:stop], dtype=np.float32))
                yield blk, yb, wb
        return blocks_fn

    def _record_observation(self, name: str, wall_s: float) -> None:
        """Best-effort block-level StageObservation into the shared cost
        history — telemetry must never break a pass."""
        if wall_s <= 0:
            return
        try:
            from ..tuning.costmodel import (StageObservation,
                                            append_observations,
                                            default_history_path)
            from ..utils.profiling import backend_name

            append_observations(default_history_path(), [StageObservation(
                stage_kind=f"BlockPlane:{name}", rows=int(self.rows),
                cols=max(int(self.cols), 1), dtype="float32",
                backend=backend_name(), wall_s=float(wall_s),
                t=int(time.time()),
                n_devices=max(
                    1, self.pod.process_count
                    if self.pod is not None and self.pod.active else 1))])
        except Exception:
            pass

    def to_json(self) -> Dict[str, Any]:
        return {"rows": self.rows, "cols": self.cols,
                "blocks": len(self.block_bounds()),
                "stripeEvery": self.stripe_every,
                "resumed": self.resumed,
                "passWalls": dict(self.pass_walls)}


class _PodPassSaver:
    """Mid-pass checkpoint steps for one pod fit pass.

    ``note_chunk`` is called once per consumed chunk; whenever this
    process crosses a step threshold it joins the pod exchange for that
    step and the coordinator persists ALL hosts' cursors + states in one
    durable record.  ``drain`` joins any remaining steps after the local
    chunks ran out (uneven ranges), keeping the step count identical on
    every process.
    """

    def __init__(self, ctx: PodStreamContext, manager, pass_index: int,
                 label: str, ests, entry_states, steps: int):
        self.ctx = ctx
        self.manager = manager
        self.pass_index = int(pass_index)
        self.label = label
        self.ests = ests
        self.entry_states = entry_states
        self.steps = int(steps)
        self.consumed = 0       # chunks consumed locally (skips included)
        self.steps_done = 0
        self.entry_cursors = [e.skip_chunks for e in ctx.entries]
        self._my_chunks = ctx.local_chunks()

    def note_chunk(self, entry_pos: int, entry_chunks_done: int) -> None:
        """One local chunk consumed (resume fast-skips included) —
        ``entry_chunks_done`` is the absolute cursor of that entry."""
        self.consumed += 1
        self.entry_cursors[entry_pos] = int(entry_chunks_done)
        every = self.manager.every_chunks
        while (self.steps_done < self.steps
               and self.consumed >= min((self.steps_done + 1) * every,
                                        self._my_chunks)):
            self._step()

    def drain(self) -> None:
        while self.steps_done < self.steps:
            self._step()

    def _step(self) -> None:
        self.steps_done += 1
        t0 = time.perf_counter()
        local = []
        for e, cur, states in zip(self.ctx.entries, self.entry_cursors,
                                  self.entry_states):
            local.append({
                "entry": e.entry_id,
                "range": list(e.range),
                "chunks_done": int(cur),
                "states": {est.uid: est.export_fit_state(states[est.uid])
                           for est in self.ests}})
        gathered = self.ctx.pod.allgather_obj(local)
        flat = sorted((rec for p in gathered for rec in p),
                      key=lambda r: r["entry"])
        if self.ctx.pod.is_coordinator():
            self.manager.save_progress_pod(
                self.pass_index, self.label, flat,
                rows_done=sum(min(r["chunks_done"] * self.ctx.chunk_rows,
                                  r["range"][1] - r["range"][0])
                              for r in flat))
        self.ctx.pod.barrier(
            f"ckpt.step{self.pass_index}.{self.steps_done}")
        self.wall = time.perf_counter() - t0
