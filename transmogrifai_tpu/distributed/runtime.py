"""Pod runtime — ``jax.distributed`` bootstrap + host-level collectives.

Reference mapping: the reference's multi-machine story is a Spark
cluster — a driver plus executors, with ``treeAggregate`` merging
partition statistics across the wire.  The TPU-native equivalent is a
JAX POD: N OS processes, each owning a slice of the global device set,
booted through ``jax.distributed.initialize`` so device collectives
(psum/allgather) span processes.  "Large Scale Distributed Linear
Algebra With TPUs" (PAPERS.md) is the kernel-side template; this module
is the process-side substrate.

Two layers live here:

* :class:`PodContext` — who am I (``process_index`` / ``process_count``
  / coordinator address), what do I own (``local_devices`` vs the global
  addressable set), plus the HOST-LEVEL collectives the streaming-fit
  protocol needs: ``allgather_obj`` (pickle over a padded uint8
  ``process_allgather``), ``broadcast_obj``, and ``barrier``.  Mergeable
  fit states are host objects, so cross-process merges ride these
  instead of hand-rolled device programs.
* bootstrap — ``TMOG_POD_*`` env handshake (:func:`init_pod_from_env`),
  and :func:`launch_local_pod`, which forks N local CPU processes with
  ``XLA_FLAGS=--xla_force_host_platform_device_count=K`` so a whole pod
  is testable on ONE CI host (the ``tmog pod`` CLI and
  ``examples/launch_pod.py`` are thin wrappers).

Env handshake (set by the launcher, read by ``init_pod_from_env``)::

  TMOG_POD_COORDINATOR     host:port of process 0's coordinator service
  TMOG_POD_NUM_PROCESSES   pod size
  TMOG_POD_PROCESS_ID      this process's index
  TMOG_POD_LOCAL_DEVICES   forced host-platform device count (CPU pods)

CPU pods additionally need the gloo collectives backend
(``jax_cpu_collectives_implementation``) selected BEFORE
``jax.distributed.initialize`` — the stock CPU client raises
"Multiprocess computations aren't implemented" on the first
cross-process program otherwise.
"""
from __future__ import annotations

import os
import pickle
import signal
import socket
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

__all__ = ["PodContext", "PodTimeoutError", "current_pod",
           "init_pod_from_env", "launch_local_pod", "pick_free_port",
           "pod_env", "ENV_COORDINATOR", "ENV_NUM_PROCESSES",
           "ENV_PROCESS_ID", "ENV_LOCAL_DEVICES"]

ENV_COORDINATOR = "TMOG_POD_COORDINATOR"
ENV_NUM_PROCESSES = "TMOG_POD_NUM_PROCESSES"
ENV_PROCESS_ID = "TMOG_POD_PROCESS_ID"
ENV_LOCAL_DEVICES = "TMOG_POD_LOCAL_DEVICES"


class PodTimeoutError(RuntimeError):
    """A pod child did not come up (or a peer died mid-collective)."""


class PodContext:
    """One process's view of the pod.

    ``active`` is False for the inert single-process context
    (``process_count == 1`` with no distributed runtime) — every
    collective then degenerates to the identity, so pod-aware code paths
    never need a separate single-process branch.
    """

    def __init__(self, process_index: int = 0, process_count: int = 1,
                 coordinator_address: Optional[str] = None,
                 initialized: bool = False, declared: bool = False):
        self.process_index = int(process_index)
        self.process_count = int(process_count)
        self.coordinator_address = coordinator_address
        self.initialized = initialized
        #: True when the TMOG_POD_* env named a pod — including a POD OF
        #: ONE, which runs the full pod train protocol (entry-structured
        #: passes, pod checkpoints) with every collective degenerate;
        #: that is how a 2-process checkpoint resumes on 1 process
        self.declared = declared
        #: cross-host-count resumes observed by this process's trains
        self.repacks = 0
        self._step = 0

    # -- identity ------------------------------------------------------------

    @property
    def active(self) -> bool:
        """True when collectives actually cross processes."""
        return self.process_count > 1

    def is_coordinator(self) -> bool:
        """True for process 0 — the ONLY process that performs durable
        side effects (checkpoints, benchmarks/*.json, cost-history
        appends, quarantine sidecars); lint rule TM047 pins the
        convention."""
        return self.process_index == 0

    def local_devices(self) -> List[Any]:
        import jax

        return list(jax.local_devices())

    def addressable_device_count(self) -> int:
        return len(self.local_devices())

    def global_device_count(self) -> int:
        import jax

        return len(jax.devices())

    def describe(self) -> Dict[str, Any]:
        """The ADVISORY pod record a checkpoint carries (never compared
        on resume — host counts are elastic, the exact analogue of the
        PR 9 mesh record)."""
        return {"processCount": self.process_count,
                "processIndex": self.process_index}

    # -- host-level collectives ---------------------------------------------
    #
    # Under TMOG_CHECK=1 every collective records (seq, kind, site) into
    # the per-process CollectiveLedger (analysis/contracts.py) and
    # carries that header INSIDE its payload, so two processes whose
    # collective sequences split fail with both sites named (TM074)
    # instead of hanging the transport; TMOG_COLLECTIVE_TIMEOUT arms a
    # watchdog around every blocking exchange (TM073).

    def _exchange(self, obj: Any) -> List[Any]:
        """The raw padded-pickle allgather every host collective rides."""
        from jax.experimental import multihost_utils

        raw = np.frombuffer(pickle.dumps(obj), np.uint8)
        lens = multihost_utils.process_allgather(
            np.array([len(raw)], np.int64)).ravel()
        # bucket the padded length to the next power of two: every
        # distinct shape jit-compiles a fresh allgather program, and a
        # long train exchanges dozens of distinct payload sizes —
        # bucketing keeps the executable cache to O(log max_payload)
        need = max(int(lens.max()), 1)
        size = 1024
        while size < need:
            size <<= 1
        buf = np.zeros(size, np.uint8)
        buf[:len(raw)] = raw
        rows = multihost_utils.process_allgather(buf)
        rows = np.atleast_2d(rows)
        return [pickle.loads(rows[i, :int(lens[i])].tobytes())
                for i in range(self.process_count)]

    def _ledger_exchange(self, entry, obj: Any) -> List[Any]:
        """Header-verified exchange: every payload carries its ledger
        entry; a peer at a different seq/kind is named (TM074)."""
        from ..analysis.contracts import (CollectiveWatchdog,
                                          verify_collective_headers)
        from ..analysis.diagnostics import ContractViolation, Diagnostic

        with CollectiveWatchdog(entry[1], entry[2]):
            rows = self._exchange({"h": entry, "o": obj})
        headers = []
        for i, r in enumerate(rows):
            if not (isinstance(r, dict) and "h" in r and "o" in r):
                raise ContractViolation(Diagnostic(
                    rule="TM074",
                    message=(f"collective-ledger divergence: this "
                             f"process paired {entry[1]} (ledger seq "
                             f"{entry[0]}, {entry[2]}) with an unledgered "
                             f"payload from process {i} — the peer is "
                             f"executing a different exchange"),
                    location=str(entry[2])))
            headers.append(tuple(r["h"]))
        verify_collective_headers(headers)
        return [r["o"] for r in rows]

    def barrier(self, name: str) -> None:
        """All processes rendezvous; returns once every peer arrived."""
        if not self.active:
            return
        from ..analysis.contracts import record_collective
        from ..utils.faults import FaultSkip, fire

        try:
            fire("pod.barrier", tag=name)
        except FaultSkip:
            return
        self._step += 1
        entry = record_collective("barrier", name)
        # TMOG_CHECK is pod-uniform (launch_local_pod inherits the env),
        # so every process takes the same transport branch
        if entry is not None:  # tmog: disable=TM071
            # ledger mode: the rendezvous doubles as a header check, so
            # a peer arriving with a DIFFERENT collective is attributed
            self._ledger_exchange(entry, None)
            return
        from jax.experimental import multihost_utils

        from ..analysis.contracts import CollectiveWatchdog

        label = f"tmog.{name}.{self._step}"
        with CollectiveWatchdog(f"barrier({name})", label):
            multihost_utils.sync_global_devices(label)

    def allgather_obj(self, obj: Any,
                      _kind: str = "allgather_obj") -> List[Any]:
        """Every process contributes one picklable object; every process
        receives the full list ORDERED BY PROCESS INDEX — the merge-order
        anchor of the streaming-fit exchange (states merge host 0 first,
        matching a single process's sequential chunk order)."""
        if not self.active:
            return [obj]
        from ..analysis.contracts import (CollectiveWatchdog,
                                          record_collective)

        entry = record_collective(_kind)
        # same pod-uniform TMOG_CHECK dispatch as barrier above
        if entry is not None:  # tmog: disable=TM071
            return self._ledger_exchange(entry, obj)
        with CollectiveWatchdog(_kind, "<ledger off>"):
            return self._exchange(obj)

    def broadcast_obj(self, obj: Any, kind: str = "broadcast_obj") -> Any:
        """Coordinator's object lands on every process (others pass any
        placeholder, conventionally None).  ``kind`` labels the exchange
        in the collective ledger — the serving control channel passes
        ``"fabric.control"`` so a divergent fleet-control message is
        attributed as such rather than as a generic broadcast."""
        if not self.active:
            return obj
        # one exchange both directions keeps the protocol lockstep-simple;
        # pod payloads here are small (decisions, counters, cursors)
        return self.allgather_obj(obj, _kind=kind)[0]

    def allsum(self, arr: np.ndarray) -> np.ndarray:
        """Elementwise sum of a host float array across processes."""
        if not self.active:
            return np.asarray(arr)
        parts = self.allgather_obj(np.asarray(arr), _kind="allsum")
        out = parts[0].astype(np.float64, copy=True)
        for p in parts[1:]:
            out += p
        return out.astype(np.asarray(arr).dtype, copy=False)


#: process-wide pod context; inert singleton until init_pod_from_env runs
_POD = PodContext()


def current_pod() -> PodContext:
    return _POD


def init_pod_from_env(local_devices: Optional[int] = None) -> PodContext:
    """Initialize the distributed runtime from the ``TMOG_POD_*``
    handshake; a no-op (returning the inert context) when the env does
    not describe a pod.  Must run BEFORE the first jax device use.
    Idempotent per process."""
    global _POD
    if _POD.initialized:
        return _POD
    raw_n = os.environ.get(ENV_NUM_PROCESSES)
    n = int(raw_n or 1)
    if raw_n is None:
        return _POD
    if n == 1:
        # a DECLARED pod of one: no distributed runtime to boot, but the
        # pod train protocol engages (cross-host-count resume rides it)
        _POD = PodContext(process_index=0, process_count=1,
                          initialized=True, declared=True)
        return _POD
    coord = os.environ.get(ENV_COORDINATOR)
    idx = int(os.environ.get(ENV_PROCESS_ID, "0") or 0)
    if not coord:
        raise ValueError(
            f"{ENV_NUM_PROCESSES}={n} but {ENV_COORDINATOR} is unset — "
            f"launch pod processes via launch_local_pod / `tmog pod` (or "
            f"export the coordinator address yourself)")
    ndev = local_devices if local_devices is not None else int(
        os.environ.get(ENV_LOCAL_DEVICES, "0") or 0)
    if ndev and "xla_force_host_platform_device_count" not in os.environ.get(
            "XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "") +
            f" --xla_force_host_platform_device_count={ndev}").strip()
    import jax

    # the stock CPU client has no cross-process collectives; gloo does.
    # Selected unconditionally (it only affects the CPU client) and
    # WITHOUT consulting jax.default_backend() — that call would
    # initialize the backend, after which distributed.initialize refuses
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except (AttributeError, ValueError):  # pragma: no cover - old jax
        pass
    jax.distributed.initialize(coordinator_address=coord,
                               num_processes=n, process_id=idx)
    _POD = PodContext(process_index=idx, process_count=n,
                      coordinator_address=coord, initialized=True,
                      declared=True)
    from ..obs.trace import set_global_attrs

    set_global_attrs(process=idx)
    from ..obs.flight import record_event

    record_event("pod.init", process=idx, processes=n, coordinator=coord,
                 local_devices=len(jax.local_devices()))
    return _POD


def _set_pod(pod: PodContext) -> PodContext:
    """Test seam: install a context without booting jax.distributed."""
    global _POD
    _POD = pod
    return pod


# ---------------------------------------------------------------------------
# local pod launcher — N processes on ONE host, testable in CI
# ---------------------------------------------------------------------------

def pick_free_port() -> int:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def pod_env(process_id: int, num_processes: int, coordinator: str,
            local_devices: int = 2,
            base_env: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """The child environment for one pod process: the ``TMOG_POD_*``
    handshake plus the forced host-platform device count.  The parent's
    env (``TMOG_FAULTS`` included — fault schedules are INHERITED, so a
    seeded plan is process-deterministic across the pod) passes through
    unless overridden."""
    env = dict(os.environ if base_env is None else base_env)
    env[ENV_COORDINATOR] = coordinator
    env[ENV_NUM_PROCESSES] = str(int(num_processes))
    env[ENV_PROCESS_ID] = str(int(process_id))
    env[ENV_LOCAL_DEVICES] = str(int(local_devices))
    env.setdefault("JAX_PLATFORMS", "cpu")
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append(f"--xla_force_host_platform_device_count={local_devices}")
    env["XLA_FLAGS"] = " ".join(flags)
    return env


def launch_local_pod(num_processes: int, argv: Sequence[str],
                     local_devices: int = 2,
                     base_env: Optional[Dict[str, str]] = None,
                     timeout: float = 600.0,
                     kill_grace_s: float = 20.0,
                     cwd: Optional[str] = None) -> List[Dict[str, Any]]:
    """Fork ``argv`` as an N-process local pod and wait for all of them.

    Each child gets the :func:`pod_env` handshake with a freshly picked
    coordinator port.  If any child dies (non-zero exit or a SIGKILL
    from an armed fault plan), the survivors — which may be blocked in a
    collective waiting for the corpse — are terminated after
    ``kill_grace_s`` so a crash test can never deadlock the harness.

    Returns one record per process: ``{"returncode", "stdout",
    "stderr"}`` in process order.
    """
    coord = f"127.0.0.1:{pick_free_port()}"
    procs = []
    for i in range(int(num_processes)):
        env = pod_env(i, num_processes, coord, local_devices=local_devices,
                      base_env=base_env)
        procs.append(subprocess.Popen(
            list(argv), env=env, cwd=cwd,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    deadline = time.time() + timeout
    first_death: Optional[float] = None
    while True:
        states = [p.poll() for p in procs]
        if all(s is not None for s in states):
            break
        dead_bad = any(s is not None and s != 0 for s in states)
        now = time.time()
        if dead_bad and first_death is None:
            first_death = now
        if ((first_death is not None and now - first_death > kill_grace_s)
                or now > deadline):
            for p in procs:
                if p.poll() is None:
                    p.send_signal(signal.SIGTERM)
            time.sleep(1.0)
            for p in procs:
                if p.poll() is None:
                    p.kill()
            if now > deadline and first_death is None:
                for p in procs:
                    p.wait()
                raise PodTimeoutError(
                    f"pod of {num_processes} did not finish within "
                    f"{timeout:.0f}s")
            break
        time.sleep(0.05)
    out = []
    for p in procs:
        stdout, stderr = p.communicate()
        out.append({"returncode": p.returncode, "stdout": stdout,
                    "stderr": stderr})
    return out


def main_pod_cli(args) -> int:
    """`tmog pod -n N [--devices K] -- cmd ...` — run a command as an
    N-process local pod (each child sees the TMOG_POD_* handshake and
    calls ``init_pod_from_env`` itself)."""
    results = launch_local_pod(args.num_processes, args.cmd,
                               local_devices=args.devices,
                               timeout=args.timeout)
    rc = 0
    for i, r in enumerate(results):
        sys.stdout.write(f"--- pod process {i} (rc={r['returncode']}) ---\n")
        sys.stdout.write(r["stdout"])
        if r["returncode"] != 0:
            sys.stderr.write(r["stderr"])
            rc = r["returncode"] or 1
    return rc
