"""Feature DSL — the fluent per-type methods of the reference's Rich* classes.

Reference: ``core/.../dsl/`` (~3.9k LoC of implicit extension classes):
``RichNumericFeature`` (incl. ``sanityCheck`` :469), ``RichTextFeature``,
``RichMapFeature``, ``RichListFeature``, ``RichSetFeature``,
``RichVectorFeature``, ``RichFeaturesCollection`` (``transmogrify``
dsl/RichFeaturesCollection.scala:69).

Python redesign: instead of Scala implicits, the methods are installed
directly on ``Feature`` when this module is imported (it is, by the package
``__init__``), with operator overloads for feature arithmetic.  Every method
returns a new Feature wired through the corresponding stage.
"""
from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

from .features.feature import Feature
from .ops.dsl_transformers import (
    AliasTransformer, DropIndicesByTransformer, ExistsTransformer,
    FilterTransformer, JaccardSimilarity, MathBinaryTransformer,
    MathScalarTransformer, NGramSimilarity, ReplaceTransformer,
    SubstringTransformer, ToOccurTransformer,
)
from .ops.date_geo import (
    DateListVectorizer, DateToUnitCircleVectorizer, TimePeriodMapTransformer,
    TimePeriodTransformer,
)
from .ops.embeddings import OpLDA, OpWord2Vec
from .ops.numeric import (
    DecisionTreeNumericBucketizer, FillMissingWithMean, NumericBucketizer,
    OpScalarStandardScaler, PercentileCalibrator,
)
from .ops.text import (
    OpHashingTF, OpNGram, OpStopWordsRemover, OpStringIndexer,
    TextLenTransformer, TextTokenizer,
)

__all__ = ["install_dsl"]


def _binary_math(op: str):
    def method(self: Feature, other) -> Feature:
        if isinstance(other, Feature):
            return MathBinaryTransformer(op).set_input(self, other).get_output()
        return MathScalarTransformer(op, float(other)).set_input(
            self).get_output()

    return method


def _unary(stage_factory: Callable[..., Any]):
    def method(self: Feature, *args, **kwargs) -> Feature:
        return stage_factory(*args, **kwargs).set_input(self).get_output()

    return method


def _binary(stage_factory: Callable[..., Any]):
    def method(self: Feature, other: Feature, *args, **kwargs) -> Feature:
        return stage_factory(*args, **kwargs).set_input(
            self, other).get_output()

    return method


def _sanity_check(self: Feature, label: Feature, **kwargs) -> Feature:
    """RichNumericFeature.sanityCheck (dsl/RichNumericFeature.scala:469)."""
    from .preparators.sanity_checker import SanityChecker

    return SanityChecker(**kwargs).set_input(label, self).get_output()


def _vectorize(self: Feature, **kwargs) -> Feature:
    """Single-feature transmogrify (RichFeature vectorize)."""
    from .ops.transmogrify import transmogrify

    return transmogrify([self], **kwargs)


def install_dsl() -> None:
    F = Feature
    F.__add__ = _binary_math("plus")
    F.__sub__ = _binary_math("minus")
    F.__mul__ = _binary_math("multiply")
    F.__truediv__ = _binary_math("divide")
    F.alias = lambda self, name: AliasTransformer(name).set_input(
        self).get_output()
    F.filter_values = _unary(FilterTransformer)
    F.replace_value = lambda self, a, b: ReplaceTransformer(a, b).set_input(
        self).get_output()
    F.to_occur = _unary(ToOccurTransformer)
    F.exists = _unary(ExistsTransformer)
    F.contains = _binary(SubstringTransformer)
    F.jaccard_similarity = _binary(JaccardSimilarity)
    F.ngram_similarity = _binary(NGramSimilarity)
    F.drop_indices_by = _unary(DropIndicesByTransformer)
    # text
    F.tokenize = _unary(TextTokenizer)
    F.ngrams = _unary(OpNGram)
    F.remove_stop_words = _unary(OpStopWordsRemover)
    F.hashing_tf = _unary(OpHashingTF)
    F.index_string = _unary(OpStringIndexer)
    F.text_len = _unary(TextLenTransformer)
    F.word2vec = _unary(OpWord2Vec)
    F.lda = _unary(OpLDA)
    # dates (RichDateFeature: toUnitCircle, toTimePeriod; RichListFeature
    # vectorize for DateList)
    F.to_unit_circle = _unary(DateToUnitCircleVectorizer)
    F.to_time_period = _unary(TimePeriodTransformer)
    F.map_to_time_period = _unary(TimePeriodMapTransformer)
    F.vectorize_date_list = _unary(DateListVectorizer)
    # numeric
    F.bucketize = _unary(NumericBucketizer)
    F.auto_bucketize = (
        lambda self, label, **kw:
        DecisionTreeNumericBucketizer(**kw).set_input(
            label, self).get_output())
    F.fill_missing_with_mean = _unary(FillMissingWithMean)
    F.zscore = _unary(OpScalarStandardScaler)
    F.calibrate_percentile = _unary(PercentileCalibrator)
    F.sanity_check = _sanity_check
    F.vectorize = _vectorize


install_dsl()
