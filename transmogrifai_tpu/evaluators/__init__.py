from .evaluators import (  # noqa: F401
    Evaluators, OpBinaryClassificationEvaluator,
    OpMultiClassificationEvaluator, OpRegressionEvaluator,
    OpForecastEvaluator, OpBinScoreEvaluator,
)
from . import metrics  # noqa: F401
