"""Evaluator stages.

Reference: ``OpEvaluatorBase`` hierarchy (core/.../evaluators/OpEvaluatorBase.scala:113),
``OpBinaryClassificationEvaluator`` (:56), ``OpMultiClassificationEvaluator``,
``OpRegressionEvaluator``, ``OpForecastEvaluator``, ``OpBinScoreEvaluator``
(OpBinScoreEvaluator.scala:53), and the ``Evaluators`` factory
(Evaluators.scala:40-240).
"""
from __future__ import annotations

from typing import Callable, Dict, Optional

import numpy as np

from ..types.columns import ColumnarDataset, FeatureColumn
from .metrics import (
    binary_classification_metrics, forecast_metrics, multiclass_metrics,
    multiclass_threshold_metrics, regression_metrics, threshold_curves,
)

__all__ = [
    "OpEvaluatorBase", "OpBinaryClassificationEvaluator",
    "OpMultiClassificationEvaluator", "OpRegressionEvaluator",
    "OpForecastEvaluator", "OpBinScoreEvaluator", "Evaluators",
]


def _label_scores(data: ColumnarDataset, label_name: str, pred_name: str):
    y = np.nan_to_num(np.asarray(data[label_name].values, np.float64))
    batch = data[pred_name].values
    return y, batch


class OpEvaluatorBase:
    """Computes {metric name -> value} from (label, prediction) columns."""

    #: the single metric used for model selection (lower-is-better if
    #: ``larger_better`` False)
    default_metric: str = ""
    larger_better: bool = True

    def __init__(self, label_col: Optional[str] = None,
                 prediction_col: Optional[str] = None):
        self.label_col = label_col
        self.prediction_col = prediction_col

    def evaluate(self, data: ColumnarDataset,
                 sample_weight=None) -> Dict[str, float]:
        raise NotImplementedError

    def evaluate_default(self, data: ColumnarDataset,
                         sample_weight=None) -> float:
        return self.evaluate(data, sample_weight)[self.default_metric]

    @property
    def name(self) -> str:
        return type(self).__name__


class OpBinaryClassificationEvaluator(OpEvaluatorBase):
    default_metric = "AuPR"  # reference default for binary selection

    def __init__(self, label_col=None, prediction_col=None,
                 threshold: float = 0.5, n_thresholds: int = 0):
        super().__init__(label_col, prediction_col)
        self.threshold = threshold
        self.n_thresholds = n_thresholds

    def evaluate(self, data, sample_weight=None):
        y, batch = _label_scores(data, self.label_col, self.prediction_col)
        if getattr(batch, "probability", None) is not None:
            score = np.asarray(batch.probability)[:, 1]
        elif getattr(batch, "raw_prediction", None) is not None:
            score = np.asarray(batch.raw_prediction)[:, 1]
        else:
            score = np.asarray(batch.prediction, np.float64)
        out = binary_classification_metrics(y, score, sample_weight,
                                            self.threshold)
        if self.n_thresholds:
            curves = threshold_curves(y, score, self.n_thresholds,
                                      sample_weight)
            out.update({k: v.tolist() for k, v in curves.items()
                        if k != "thresholds"})
        return out


class OpMultiClassificationEvaluator(OpEvaluatorBase):
    """Multiclass metrics + topN/threshold histograms
    (OpMultiClassificationEvaluator.scala: topNs default (1,3), thresholds
    default 0.00..1.00 step 0.01, calculateThresholdMetrics :153-240).

    ``num_classes``: authoritative class count (from the label indexer /
    selector metadata).  When absent it is inferred from the data AND the
    probability width — never from the label max alone, so an eval slice
    missing the top class cannot silently shrink the class space.
    """

    default_metric = "F1"

    def __init__(self, label_col=None, prediction_col=None,
                 top_ns=(1, 3), thresholds=None,
                 num_classes: Optional[int] = None):
        super().__init__(label_col, prediction_col)
        self.top_ns = tuple(top_ns)
        self.thresholds = thresholds
        self.num_classes = num_classes

    def evaluate(self, data, sample_weight=None):
        y, batch = _label_scores(data, self.label_col, self.prediction_col)
        pred = np.asarray(batch.prediction, np.float64)
        proba = getattr(batch, "probability", None)
        n_classes = self.num_classes or int(max(
            y.max(), pred.max(),
            (proba.shape[1] - 1) if proba is not None else 0)) + 1
        out = multiclass_metrics(y.astype(int), pred.astype(int), n_classes,
                                 sample_weight)
        conf = out.pop("confusion")
        out["confusionMatrix"] = np.asarray(conf).tolist()
        if proba is not None:
            p = np.clip(np.asarray(proba), 1e-15, 1.0)
            idx = np.clip(y.astype(int), 0, p.shape[1] - 1)
            out["LogLoss"] = float(
                -np.mean(np.log(p[np.arange(len(y)), idx])))
            out["ThresholdMetrics"] = multiclass_threshold_metrics(
                y.astype(int), np.asarray(proba), top_ns=self.top_ns,
                thresholds=self.thresholds)
        return out


class OpRegressionEvaluator(OpEvaluatorBase):
    default_metric = "RootMeanSquaredError"
    larger_better = False

    def evaluate(self, data, sample_weight=None):
        y, batch = _label_scores(data, self.label_col, self.prediction_col)
        return regression_metrics(y, np.asarray(batch.prediction, np.float64),
                                  sample_weight)


class OpForecastEvaluator(OpEvaluatorBase):
    default_metric = "SMAPE"
    larger_better = False

    def __init__(self, label_col=None, prediction_col=None,
                 seasonal_period: int = 1):
        super().__init__(label_col, prediction_col)
        self.seasonal_period = seasonal_period

    def evaluate(self, data, sample_weight=None):
        y, batch = _label_scores(data, self.label_col, self.prediction_col)
        return forecast_metrics(y, np.asarray(batch.prediction, np.float64),
                                self.seasonal_period)


class OpBinScoreEvaluator(OpEvaluatorBase):
    """Calibration-bin diagnostics (OpBinScoreEvaluator.scala:53)."""

    default_metric = "BrierScore"
    larger_better = False

    def __init__(self, label_col=None, prediction_col=None, num_bins: int = 100):
        super().__init__(label_col, prediction_col)
        self.num_bins = num_bins

    def evaluate(self, data, sample_weight=None):
        y, batch = _label_scores(data, self.label_col, self.prediction_col)
        score = (np.asarray(batch.probability)[:, 1]
                 if getattr(batch, "probability", None) is not None
                 else np.asarray(batch.prediction, np.float64))
        bins = np.clip((score * self.num_bins).astype(int), 0,
                       self.num_bins - 1)
        counts = np.bincount(bins, minlength=self.num_bins)
        sum_scores = np.bincount(bins, weights=score, minlength=self.num_bins)
        sum_labels = np.bincount(bins, weights=y, minlength=self.num_bins)
        nz = np.maximum(counts, 1)
        avg_score = sum_scores / nz
        avg_conv = sum_labels / nz
        brier = float(np.mean((score - y) ** 2))
        return {
            "BrierScore": brier,
            "binCenters": ((np.arange(self.num_bins) + 0.5) / self.num_bins).tolist(),
            "numberOfDataPoints": counts.tolist(),
            "averageScore": avg_score.tolist(),
            "averageConversionRate": avg_conv.tolist(),
        }


class Evaluators:
    """Factory catalogue (Evaluators.scala:40-240)."""

    class BinaryClassification:
        @staticmethod
        def auPR():
            ev = OpBinaryClassificationEvaluator()
            ev.default_metric = "AuPR"
            return ev

        @staticmethod
        def auROC():
            ev = OpBinaryClassificationEvaluator()
            ev.default_metric = "AuROC"
            return ev

        @staticmethod
        def precision():
            ev = OpBinaryClassificationEvaluator()
            ev.default_metric = "Precision"
            return ev

        @staticmethod
        def recall():
            ev = OpBinaryClassificationEvaluator()
            ev.default_metric = "Recall"
            return ev

        @staticmethod
        def f1():
            ev = OpBinaryClassificationEvaluator()
            ev.default_metric = "F1"
            return ev

        @staticmethod
        def error():
            ev = OpBinaryClassificationEvaluator()
            ev.default_metric = "Error"
            ev.larger_better = False
            return ev

        @staticmethod
        def brierScore():
            ev = OpBinaryClassificationEvaluator()
            ev.default_metric = "BrierScore"
            ev.larger_better = False
            return ev

        @staticmethod
        def custom(metric_name: str, larger_better: bool,
                   fn: Callable[[np.ndarray, np.ndarray], float]):
            ev = _CustomBinaryEvaluator(metric_name=metric_name, fn=fn)
            ev.larger_better = larger_better
            return ev

    class MultiClassification:
        @staticmethod
        def f1():
            ev = OpMultiClassificationEvaluator()
            ev.default_metric = "F1"
            return ev

        @staticmethod
        def precision():
            ev = OpMultiClassificationEvaluator()
            ev.default_metric = "Precision"
            return ev

        @staticmethod
        def recall():
            ev = OpMultiClassificationEvaluator()
            ev.default_metric = "Recall"
            return ev

        @staticmethod
        def error():
            ev = OpMultiClassificationEvaluator()
            ev.default_metric = "Error"
            ev.larger_better = False
            return ev

    class Regression:
        @staticmethod
        def rmse():
            ev = OpRegressionEvaluator()
            ev.default_metric = "RootMeanSquaredError"
            return ev

        @staticmethod
        def mse():
            ev = OpRegressionEvaluator()
            ev.default_metric = "MeanSquaredError"
            return ev

        @staticmethod
        def mae():
            ev = OpRegressionEvaluator()
            ev.default_metric = "MeanAbsoluteError"
            return ev

        @staticmethod
        def r2():
            ev = OpRegressionEvaluator()
            ev.default_metric = "R2"
            ev.larger_better = True
            return ev


class _CustomBinaryEvaluator(OpEvaluatorBase):
    def __init__(self, metric_name: str, fn, label_col=None,
                 prediction_col=None):
        super().__init__(label_col, prediction_col)
        self.default_metric = metric_name
        self.fn = fn

    def evaluate(self, data, sample_weight=None):
        y, batch = _label_scores(data, self.label_col, self.prediction_col)
        score = (np.asarray(batch.probability)[:, 1]
                 if getattr(batch, "probability", None) is not None
                 else np.asarray(batch.prediction, np.float64))
        return {self.default_metric: float(self.fn(y, score))}
