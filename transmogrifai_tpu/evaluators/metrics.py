"""Metric kernels — numpy for host-resident inputs, JAX for device-resident.

Reference: OpBinaryClassificationEvaluator (AuROC, AuPR, precision/recall/F1,
Brier, threshold metrics — core/.../evaluators/OpBinaryClassificationEvaluator.scala:56,192-223),
OpMultiClassificationEvaluator, OpRegressionEvaluator, OpForecastEvaluator
(SMAPE/MASE).

All binary metrics are computed from one descending sort of the scores —
the TPU-friendly replacement for Spark's `BinaryClassificationMetrics`
thresholded RDD sweeps.  Weighted variants support the CV fold-mask design.

Dispatch: metrics are O(N log N) scalar reductions, so HOST-RESIDENT inputs
always take the numpy path — an XLA metric program costs an upload + a
per-shape compile (1-10 s through a remote-compile tunnel) + a fetch for
milliseconds of math.  Device-resident inputs (the sweep's score vectors)
use the jitted sort-based kernels so nothing is fetched per candidate.
"""
from __future__ import annotations

import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

#: Metrics where SMALLER is better — the single source of truth for
#: selection direction (ModelSelector.larger_better, SelectedModelCombiner).
MINIMIZE_METRICS = (
    "RootMeanSquaredError", "MeanSquaredError", "MeanAbsoluteError",
    "Error", "LogLoss", "BrierScore", "SMAPE", "MASE", "SeasonalError",
)

__all__ = [
    "MINIMIZE_METRICS",
    "auroc", "aupr", "binary_metrics_at_threshold", "brier_score", "log_loss",
    "binary_classification_metrics", "multiclass_metrics",
    "multiclass_threshold_metrics",
    "regression_metrics", "forecast_metrics", "threshold_curves",
]


def _on_host(*arrays) -> bool:
    """Host numpy metrics for HOST-RESIDENT inputs of any size: a 1M-row
    numpy sort is ~0.2 s, while routing host data through the device costs
    an upload + a per-shape XLA compile + a fetch (measured ~30 s per
    metric call at 300k through the remote tunnel).  The jitted kernels are
    for inputs that ALREADY live on device (sweep score vectors), where the
    fetch is the expensive side."""
    return all(a is None or isinstance(a, np.ndarray) or np.isscalar(a)
               or isinstance(a, (list, tuple)) for a in arrays)


def _weights(y, w):
    y = jnp.asarray(y, jnp.float32)
    if w is None:
        w = jnp.ones_like(y)
    else:
        w = jnp.asarray(w, jnp.float32)
    return y, w


def _np_weights(y, w):
    y = np.asarray(y, np.float64)
    w = np.ones_like(y) if w is None else np.asarray(w, np.float64)
    return y, w


def auroc(y_true, y_score, sample_weight=None):
    """Weighted AUC = P(s+ > s-) + 0.5 P(s+ = s-) over score tie groups."""
    if _on_host(y_true, y_score, sample_weight):
        y, w = _np_weights(y_true, sample_weight)
        s = np.asarray(y_score, np.float64)
        order = np.argsort(s, kind="stable")
        s_sorted = s[order]
        wy = (w * y)[order]
        wn = (w * (1 - y))[order]
        is_new = np.concatenate([[True], s_sorted[1:] != s_sorted[:-1]])
        starts = np.flatnonzero(is_new)
        pos_g = np.add.reduceat(wy, starts)
        neg_g = np.add.reduceat(wn, starts)
        neg_below = np.cumsum(neg_g) - neg_g
        num = float(np.sum(pos_g * (neg_below + 0.5 * neg_g)))
        denom = max(float(wy.sum()) * float(wn.sum()), 1e-12)
        return float(np.clip(num / denom, 0.0, 1.0))
    return _auroc_dev(y_true, y_score, sample_weight)


@jax.jit
def _auroc_dev(y_true, y_score, sample_weight=None) -> jnp.ndarray:
    y, w = _weights(y_true, sample_weight)
    s = jnp.asarray(y_score, jnp.float32)
    n = s.shape[0]
    order = jnp.argsort(s)
    s_sorted = s[order]
    wy = (w * y)[order]
    wn = (w * (1 - y))[order]
    is_new = jnp.concatenate([jnp.ones(1, bool), s_sorted[1:] != s_sorted[:-1]])
    gid = jnp.cumsum(is_new) - 1  # tie-group id per element
    pos_g = jax.ops.segment_sum(wy, gid, num_segments=n)
    neg_g = jax.ops.segment_sum(wn, gid, num_segments=n)
    neg_below = jnp.cumsum(neg_g) - neg_g
    w_pos = jnp.sum(wy)
    w_neg = jnp.sum(wn)
    num = jnp.sum(pos_g * (neg_below + 0.5 * neg_g))
    return jnp.clip(num / jnp.maximum(w_pos * w_neg, 1e-12), 0.0, 1.0)


def aupr(y_true, y_score, sample_weight=None):
    """Area under precision-recall via descending-score sweep (average-
    precision style, matches sklearn/Spark)."""
    if _on_host(y_true, y_score, sample_weight):
        y, w = _np_weights(y_true, sample_weight)
        s = np.asarray(y_score, np.float64)
        order = np.argsort(-s, kind="stable")
        s_sorted = s[order]
        wy = (w * y)[order]
        ww = w[order]
        is_new = np.concatenate([[True], s_sorted[1:] != s_sorted[:-1]])
        starts = np.flatnonzero(is_new)
        pos_g = np.add.reduceat(wy, starts)
        tot_g = np.add.reduceat(ww, starts)
        tp = np.cumsum(pos_g)
        all_pred = np.cumsum(tot_g)
        pos = max(float(wy.sum()), 1e-12)
        precision = tp / np.maximum(all_pred, 1e-12)
        return float(np.clip(np.sum((pos_g / pos) * precision), 0.0, 1.0))
    return _aupr_dev(y_true, y_score, sample_weight)


@jax.jit
def _aupr_dev(y_true, y_score, sample_weight=None) -> jnp.ndarray:
    y, w = _weights(y_true, sample_weight)
    s = jnp.asarray(y_score, jnp.float32)
    n = s.shape[0]
    order = jnp.argsort(-s)
    s_sorted = s[order]
    wy = (w * y)[order]
    ww = w[order]
    # evaluate precision/recall only at distinct-threshold boundaries
    is_new = jnp.concatenate([jnp.ones(1, bool), s_sorted[1:] != s_sorted[:-1]])
    gid = jnp.cumsum(is_new) - 1
    pos_g = jax.ops.segment_sum(wy, gid, num_segments=n)
    tot_g = jax.ops.segment_sum(ww, gid, num_segments=n)
    tp = jnp.cumsum(pos_g)
    all_pred = jnp.cumsum(tot_g)
    pos = jnp.maximum(jnp.sum(wy), 1e-12)
    precision = tp / jnp.maximum(all_pred, 1e-12)
    dr = pos_g / pos
    return jnp.clip(jnp.sum(dr * precision), 0.0, 1.0)


def binary_metric_grid(y_true, scores, weights, metric: str):
    """Batched device metric for a validation sweep: ``scores`` (F, C, N)
    per-(fold, candidate) score rows and ``weights`` (F, N) per-fold eval
    weights (broadcast over candidates — never replicated) against one
    shared label vector -> (F, C) device metric values, or None when
    ``metric`` has no device kernel (callers fall back to per-candidate
    host metrics)."""
    fn = {"AuPR": _aupr_dev, "AuROC": _auroc_dev}.get(metric)
    if fn is None:
        return None
    y = jnp.asarray(y_true, jnp.float32)
    return jax.vmap(lambda s_f, w_f:
                    jax.vmap(lambda s: fn(y, s, w_f))(s_f))(scores, weights)


def _regression_metric_dev(y, p, w, metric: str):
    """THE weighted regression metric kernel — shared by the sequential
    sweep path (ModelSelector._metric_device) and the batched grid."""
    ws = jnp.maximum(w.sum(), 1e-12)
    err = p - y
    if metric == "MeanAbsoluteError":
        return (w * jnp.abs(err)).sum() / ws
    mse = (w * err ** 2).sum() / ws
    if metric == "MeanSquaredError":
        return mse
    if metric == "RootMeanSquaredError":
        return jnp.sqrt(mse)
    mean = (w * y).sum() / ws
    var = (w * (y - mean) ** 2).sum() / ws
    return 1.0 - mse / jnp.maximum(var, 1e-12)


def regression_metric_grid(y_true, preds, weights, metric: str):
    """Batched device regression metric: (F, C, N) predictions + (F, N)
    weights -> (F, C) device values; None when unsupported."""
    if metric not in ("RootMeanSquaredError", "MeanSquaredError",
                     "MeanAbsoluteError", "R2"):
        return None
    y = jnp.asarray(y_true, jnp.float32)
    return jax.vmap(lambda p_f, w_f: jax.vmap(
        lambda p: _regression_metric_dev(y, p, w_f, metric))(p_f))(
            preds, weights)


_MULTI_GRID_METRICS = ("F1", "Error", "Accuracy", "Precision", "Recall")


def _multiclass_metric_dev(y, p, w, n_classes: int, metric: str):
    """Weighted multiclass metric from int-valued label/prediction vectors —
    confusion matrix as one one-hot matmul (no scatter), shared by the
    batched grid below."""
    ok = ((y >= 0) & (y < n_classes) & (p >= 0) & (p < n_classes)
          ).astype(jnp.float32)
    wk = w * ok
    wsum = jnp.maximum(wk.sum(), 1e-12)
    if metric in ("Accuracy", "Error"):
        acc = jnp.sum(wk * (y == p)) / wsum
        return acc if metric == "Accuracy" else 1.0 - acc
    yo = jax.nn.one_hot(y, n_classes, dtype=jnp.float32)
    po = jax.nn.one_hot(p, n_classes, dtype=jnp.float32)
    conf = jax.lax.dot((yo * wk[:, None]).T, po,
                       precision=jax.lax.Precision.HIGHEST)  # (K, K)
    tp = jnp.diagonal(conf)
    support = conf.sum(axis=1)
    pred_count = conf.sum(axis=0)
    prec_k = tp / jnp.maximum(pred_count, 1e-12)
    rec_k = tp / jnp.maximum(support, 1e-12)
    wts = support / wsum
    if metric == "Precision":
        return jnp.sum(wts * prec_k)
    if metric == "Recall":
        return jnp.sum(wts * rec_k)
    f1_k = 2 * prec_k * rec_k / jnp.maximum(prec_k + rec_k, 1e-12)
    return jnp.sum(wts * f1_k)


def multiclass_metric_grid(y_true, preds, weights, n_classes: int,
                           metric: str):
    """Batched device multiclass metric: (F, C, N) predicted labels (float
    or int) + (F, N) eval weights against one shared label vector ->
    (F, C) device values; None when ``metric`` has no device kernel."""
    if metric not in _MULTI_GRID_METRICS:
        return None
    y = jnp.asarray(y_true, jnp.int32)
    return jax.vmap(lambda p_f, w_f: jax.vmap(
        lambda p: _multiclass_metric_dev(
            y, jnp.asarray(p, jnp.int32), w_f, n_classes, metric))(p_f))(
            preds, weights)


def binary_metrics_at_threshold(y_true, y_score, threshold=0.5,
                                sample_weight=None):
    if _on_host(y_true, y_score, sample_weight):
        y, w = _np_weights(y_true, sample_weight)
        s = np.asarray(y_score, np.float64)
        pred = (s >= threshold).astype(np.float64)
        tp = float(np.sum(w * pred * y))
        fp = float(np.sum(w * pred * (1 - y)))
        fn = float(np.sum(w * (1 - pred) * y))
        tn = float(np.sum(w * (1 - pred) * (1 - y)))
        precision = tp / max(tp + fp, 1e-12)
        recall = tp / max(tp + fn, 1e-12)
        f1 = 2 * precision * recall / max(precision + recall, 1e-12)
        error = (fp + fn) / max(tp + fp + fn + tn, 1e-12)
        return {"Precision": precision, "Recall": recall, "F1": f1,
                "Error": error, "TP": tp, "TN": tn, "FP": fp, "FN": fn}
    return _binary_at_threshold_dev(y_true, y_score, threshold, sample_weight)


@jax.jit
def _binary_at_threshold_dev(y_true, y_score, threshold=0.5,
                             sample_weight=None):
    y, w = _weights(y_true, sample_weight)
    s = jnp.asarray(y_score, jnp.float32)
    pred = (s >= threshold).astype(jnp.float32)
    tp = jnp.sum(w * pred * y)
    fp = jnp.sum(w * pred * (1 - y))
    fn = jnp.sum(w * (1 - pred) * y)
    tn = jnp.sum(w * (1 - pred) * (1 - y))
    precision = tp / jnp.maximum(tp + fp, 1e-12)
    recall = tp / jnp.maximum(tp + fn, 1e-12)
    f1 = 2 * precision * recall / jnp.maximum(precision + recall, 1e-12)
    error = (fp + fn) / jnp.maximum(tp + fp + fn + tn, 1e-12)
    return {"Precision": precision, "Recall": recall, "F1": f1,
            "Error": error, "TP": tp, "TN": tn, "FP": fp, "FN": fn}


def brier_score(y_true, y_prob, sample_weight=None):
    if _on_host(y_true, y_prob, sample_weight):
        y, w = _np_weights(y_true, sample_weight)
        p = np.asarray(y_prob, np.float64)
        return float(np.sum(w * (p - y) ** 2) / max(np.sum(w), 1e-12))
    return _brier_dev(y_true, y_prob, sample_weight)


@jax.jit
def _brier_dev(y_true, y_prob, sample_weight=None):
    y, w = _weights(y_true, sample_weight)
    p = jnp.asarray(y_prob, jnp.float32)
    return jnp.sum(w * (p - y) ** 2) / jnp.maximum(jnp.sum(w), 1e-12)


def log_loss(y_true, y_prob, sample_weight=None, eps: float = 1e-15):
    if _on_host(y_true, y_prob, sample_weight):
        y, w = _np_weights(y_true, sample_weight)
        p = np.clip(np.asarray(y_prob, np.float64), eps, 1 - eps)
        ll = -(y * np.log(p) + (1 - y) * np.log1p(-p))
        return float(np.sum(w * ll) / max(np.sum(w), 1e-12))
    return _log_loss_dev(y_true, y_prob, sample_weight, eps)


@functools.partial(jax.jit, static_argnames=("eps",))
def _log_loss_dev(y_true, y_prob, sample_weight=None, eps: float = 1e-15):
    y, w = _weights(y_true, sample_weight)
    p = jnp.clip(jnp.asarray(y_prob, jnp.float32), eps, 1 - eps)
    ll = -(y * jnp.log(p) + (1 - y) * jnp.log1p(-p))
    return jnp.sum(w * ll) / jnp.maximum(jnp.sum(w), 1e-12)


def binary_classification_metrics(y_true, y_prob, sample_weight=None,
                                  threshold: float = 0.5) -> Dict[str, float]:
    """Full binary metric set (OpBinaryClassificationEvaluator parity)."""
    at_t = binary_metrics_at_threshold(y_true, y_prob, threshold, sample_weight)
    out = {
        "AuROC": float(auroc(y_true, y_prob, sample_weight)),
        "AuPR": float(aupr(y_true, y_prob, sample_weight)),
        "BrierScore": float(brier_score(y_true, y_prob, sample_weight)),
        "LogLoss": float(log_loss(y_true, y_prob, sample_weight)),
    }
    out.update({k: float(v) for k, v in at_t.items()})
    return out


def threshold_curves(y_true, y_prob, n_thresholds: int = 100,
                     sample_weight=None) -> Dict[str, np.ndarray]:
    """Precision/recall/F1 across a threshold sweep (thresholdMetrics parity)."""
    ts = np.linspace(0.0, 1.0, n_thresholds)
    if _on_host(y_true, y_prob, sample_weight):
        rows = [binary_metrics_at_threshold(y_true, y_prob, t, sample_weight)
                for t in ts]
        return {"thresholds": ts,
                "precisionByThreshold": np.asarray([r["Precision"] for r in rows]),
                "recallByThreshold": np.asarray([r["Recall"] for r in rows]),
                "f1ByThreshold": np.asarray([r["F1"] for r in rows])}
    f = jax.jit(jax.vmap(
        lambda t: _binary_at_threshold_dev(y_true, y_prob, t, sample_weight)
    ))
    res = f(jnp.asarray(ts, jnp.float32))
    return {"thresholds": ts,
            "precisionByThreshold": np.asarray(res["Precision"]),
            "recallByThreshold": np.asarray(res["Recall"]),
            "f1ByThreshold": np.asarray(res["F1"])}


def multiclass_threshold_metrics(y_true, proba, top_ns=(1, 3),
                                 thresholds=None) -> Dict:
    """Top-N / confidence-threshold histograms for multiclass predictions.

    Parity with ``OpMultiClassificationEvaluator.calculateThresholdMetrics``
    (core/.../evaluators/OpMultiClassificationEvaluator.scala:153-240): for
    every topN value and every threshold, counts of rows whose TRUE class
    score is in the row's top-N and above threshold (``correct``), rows
    whose top score clears the threshold but the true class misses the top-N
    or falls below threshold (``incorrect``), and the remainder
    (``noPrediction``); the three sum to N at every threshold.

    TPU redesign of the reference's per-row sort + treeAggregate: the true
    class RANK is two masked reductions (no sort), and each count array is
    one (N,)x(N,T) masked-comparison matmul — the whole computation is a
    handful of fused reductions on device for at-scale inputs.
    """
    thr = (np.arange(0, 101) / 100.0 if thresholds is None
           else np.asarray(thresholds, np.float64))
    if thr.size == 0 or not np.all((thr >= 0) & (thr <= 1)):
        raise ValueError("thresholds must be a non-empty sequence in [0, 1]")
    tns = list(dict.fromkeys(int(t) for t in top_ns))  # order-keeping dedupe
    if not tns or any(t <= 0 for t in tns):
        raise ValueError("top_ns must be a non-empty sequence of positive "
                         "integers")
    on_host = _on_host(y_true, None) and not isinstance(proba, jax.Array)
    xp = np if on_host else jnp
    P = xp.asarray(proba, xp.float32 if xp is jnp else np.float64)
    y = xp.asarray(y_true, xp.int32 if xp is jnp else np.int64)
    n, k = P.shape
    lbl = xp.clip(y, 0, k - 1)
    seen = (y >= 0) & (y < k)  # unseen classes score 0 (reference :192)
    rows = xp.arange(n)
    true_score = xp.where(seen, P[rows, lbl], 0.0)
    top_score = P.max(axis=1)
    # stable-descending rank of the true class: scores strictly greater,
    # plus equal scores at earlier indices (matches the reference's stable
    # sortBy(-score) take(t) membership)
    gt = (P > true_score[:, None]).sum(axis=1)
    eq_before = ((P == true_score[:, None])
                 & (xp.arange(k)[None, :] < lbl[:, None])).sum(axis=1)
    rank = xp.where(seen, gt + eq_before, k)
    thr_x = xp.asarray(thr, P.dtype)
    # (N, T): does the true/top score clear each threshold
    true_ge = true_score[:, None] >= thr_x[None, :]
    top_ge = top_score[:, None] >= thr_x[None, :]
    out = {"topNs": tns, "thresholds": [float(t) for t in thr],
           "correctCounts": {}, "incorrectCounts": {},
           "noPredictionCounts": {}}
    for t in tns:
        in_top = (rank < t)
        correct = (in_top[:, None] & true_ge).sum(axis=0)
        incorrect = ((in_top[:, None] & top_ge & ~true_ge)
                     | (~in_top[:, None] & top_ge)).sum(axis=0)
        if xp is jnp:
            correct = np.asarray(correct)
            incorrect = np.asarray(incorrect)
        out["correctCounts"][t] = [int(c) for c in correct]
        out["incorrectCounts"][t] = [int(c) for c in incorrect]
        out["noPredictionCounts"][t] = [int(n - c - i) for c, i
                                        in zip(correct, incorrect)]
    return out


@functools.partial(jax.jit, static_argnames=("n_classes",))
def _multiclass_core(y_true, y_pred, n_classes, sample_weight=None):
    y = jnp.asarray(y_true, jnp.int32)
    p = jnp.asarray(y_pred, jnp.int32)
    w = (jnp.ones(y.shape[0], jnp.float32) if sample_weight is None
         else jnp.asarray(sample_weight, jnp.float32))
    wsum = jnp.maximum(w.sum(), 1e-12)
    correct = (y == p).astype(jnp.float32)
    acc = jnp.sum(w * correct) / wsum
    conf = jnp.zeros((n_classes, n_classes), jnp.float32).at[y, p].add(w)
    tp = jnp.diag(conf)
    support = conf.sum(axis=1)
    pred_count = conf.sum(axis=0)
    prec_k = tp / jnp.maximum(pred_count, 1e-12)
    rec_k = tp / jnp.maximum(support, 1e-12)
    f1_k = 2 * prec_k * rec_k / jnp.maximum(prec_k + rec_k, 1e-12)
    wts = support / wsum
    return {
        "Accuracy": acc,
        "Error": 1.0 - acc,
        "Precision": jnp.sum(wts * prec_k),
        "Recall": jnp.sum(wts * rec_k),
        "F1": jnp.sum(wts * f1_k),
        "confusion": conf,
    }


def multiclass_metrics(y_true, y_pred, n_classes: int,
                       sample_weight=None) -> Dict[str, float]:
    if _on_host(y_true, y_pred, sample_weight):
        y = np.asarray(y_true, np.int64)
        p = np.asarray(y_pred, np.int64)
        w = (np.ones(len(y)) if sample_weight is None
             else np.asarray(sample_weight, np.float64))
        # drop out-of-range labels (e.g. factorize's -1 for NaN) the same way
        # the device kernel's mode="drop" scatter does
        ok = (y >= 0) & (y < n_classes) & (p >= 0) & (p < n_classes)
        y, p, w = y[ok], p[ok], w[ok]
        wsum = max(w.sum(), 1e-12)
        acc = float(np.sum(w * (y == p)) / wsum)
        conf = np.zeros((n_classes, n_classes))
        np.add.at(conf, (y, p), w)
        tp = np.diag(conf)
        support = conf.sum(axis=1)
        pred_count = conf.sum(axis=0)
        prec_k = tp / np.maximum(pred_count, 1e-12)
        rec_k = tp / np.maximum(support, 1e-12)
        f1_k = 2 * prec_k * rec_k / np.maximum(prec_k + rec_k, 1e-12)
        wts = support / wsum
        return {"Accuracy": acc, "Error": 1.0 - acc,
                "Precision": float(np.sum(wts * prec_k)),
                "Recall": float(np.sum(wts * rec_k)),
                "F1": float(np.sum(wts * f1_k)), "confusion": conf}
    res = _multiclass_core(y_true, y_pred, n_classes, sample_weight)
    return {k: (float(v) if k != "confusion" else np.asarray(v))
            for k, v in res.items()}


@jax.jit
def _regression_core(y_true, y_pred, sample_weight=None):
    y, w = _weights(y_true, sample_weight)
    p = jnp.asarray(y_pred, jnp.float32)
    wsum = jnp.maximum(w.sum(), 1e-12)
    err = p - y
    mse = jnp.sum(w * err ** 2) / wsum
    mae = jnp.sum(w * jnp.abs(err)) / wsum
    ym = jnp.sum(w * y) / wsum
    ss_tot = jnp.sum(w * (y - ym) ** 2)
    ss_res = jnp.sum(w * err ** 2)
    r2 = 1.0 - ss_res / jnp.maximum(ss_tot, 1e-12)
    return {"RootMeanSquaredError": jnp.sqrt(mse), "MeanSquaredError": mse,
            "MeanAbsoluteError": mae, "R2": r2}


def regression_metrics(y_true, y_pred, sample_weight=None) -> Dict[str, float]:
    if _on_host(y_true, y_pred, sample_weight):
        y, w = _np_weights(y_true, sample_weight)
        p = np.asarray(y_pred, np.float64)
        wsum = max(w.sum(), 1e-12)
        err = p - y
        mse = float(np.sum(w * err ** 2) / wsum)
        mae = float(np.sum(w * np.abs(err)) / wsum)
        ym = np.sum(w * y) / wsum
        ss_tot = float(np.sum(w * (y - ym) ** 2))
        r2 = 1.0 - float(np.sum(w * err ** 2)) / max(ss_tot, 1e-12)
        return {"RootMeanSquaredError": float(np.sqrt(mse)),
                "MeanSquaredError": mse, "MeanAbsoluteError": mae, "R2": r2}
    return {k: float(v) for k, v in _regression_core(y_true, y_pred, sample_weight).items()}


def forecast_metrics(y_true, y_pred, seasonal_period: int = 1) -> Dict[str, float]:
    """SMAPE + MASE (OpForecastEvaluator parity)."""
    y = np.asarray(y_true, np.float64)
    p = np.asarray(y_pred, np.float64)
    smape = float(np.mean(
        2.0 * np.abs(p - y) / np.maximum(np.abs(p) + np.abs(y), 1e-12)))
    m = seasonal_period
    if len(y) > m:
        scale = np.mean(np.abs(y[m:] - y[:-m]))
        mase = float(np.mean(np.abs(p - y)) / max(scale, 1e-12))
    else:
        mase = float("nan")
    return {"SMAPE": smape, "MASE": mase}
