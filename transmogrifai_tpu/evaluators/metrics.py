"""Metric kernels (JAX, sort-based, static shapes).

Reference: OpBinaryClassificationEvaluator (AuROC, AuPR, precision/recall/F1,
Brier, threshold metrics — core/.../evaluators/OpBinaryClassificationEvaluator.scala:56,192-223),
OpMultiClassificationEvaluator, OpRegressionEvaluator, OpForecastEvaluator
(SMAPE/MASE).

All binary metrics are computed from one descending sort of the scores —
the TPU-friendly replacement for Spark's `BinaryClassificationMetrics`
thresholded RDD sweeps.  Weighted variants support the CV fold-mask design.
"""
from __future__ import annotations

import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "auroc", "aupr", "binary_metrics_at_threshold", "brier_score", "log_loss",
    "binary_classification_metrics", "multiclass_metrics",
    "regression_metrics", "forecast_metrics", "threshold_curves",
]


def _weights(y, w):
    y = jnp.asarray(y, jnp.float32)
    if w is None:
        w = jnp.ones_like(y)
    else:
        w = jnp.asarray(w, jnp.float32)
    return y, w


@jax.jit
def auroc(y_true, y_score, sample_weight=None) -> jnp.ndarray:
    """Weighted AUC = P(s+ > s-) + 0.5 P(s+ = s-), computed over score tie
    groups with segment sums (one device sort, static shapes)."""
    y, w = _weights(y_true, sample_weight)
    s = jnp.asarray(y_score, jnp.float32)
    n = s.shape[0]
    order = jnp.argsort(s)
    s_sorted = s[order]
    wy = (w * y)[order]
    wn = (w * (1 - y))[order]
    is_new = jnp.concatenate([jnp.ones(1, bool), s_sorted[1:] != s_sorted[:-1]])
    gid = jnp.cumsum(is_new) - 1  # tie-group id per element
    pos_g = jax.ops.segment_sum(wy, gid, num_segments=n)
    neg_g = jax.ops.segment_sum(wn, gid, num_segments=n)
    neg_below = jnp.cumsum(neg_g) - neg_g
    w_pos = jnp.sum(wy)
    w_neg = jnp.sum(wn)
    num = jnp.sum(pos_g * (neg_below + 0.5 * neg_g))
    return jnp.clip(num / jnp.maximum(w_pos * w_neg, 1e-12), 0.0, 1.0)


@jax.jit
def aupr(y_true, y_score, sample_weight=None) -> jnp.ndarray:
    """Area under precision-recall via descending-score sweep, linear
    interpolation in recall (matches sklearn/Spark average-precision style)."""
    y, w = _weights(y_true, sample_weight)
    s = jnp.asarray(y_score, jnp.float32)
    n = s.shape[0]
    order = jnp.argsort(-s)
    s_sorted = s[order]
    wy = (w * y)[order]
    ww = w[order]
    # evaluate precision/recall only at distinct-threshold boundaries
    is_new = jnp.concatenate([jnp.ones(1, bool), s_sorted[1:] != s_sorted[:-1]])
    gid = jnp.cumsum(is_new) - 1
    pos_g = jax.ops.segment_sum(wy, gid, num_segments=n)
    tot_g = jax.ops.segment_sum(ww, gid, num_segments=n)
    tp = jnp.cumsum(pos_g)
    all_pred = jnp.cumsum(tot_g)
    pos = jnp.maximum(jnp.sum(wy), 1e-12)
    precision = tp / jnp.maximum(all_pred, 1e-12)
    dr = pos_g / pos
    return jnp.clip(jnp.sum(dr * precision), 0.0, 1.0)


@jax.jit
def binary_metrics_at_threshold(y_true, y_score, threshold=0.5,
                                sample_weight=None):
    y, w = _weights(y_true, sample_weight)
    s = jnp.asarray(y_score, jnp.float32)
    pred = (s >= threshold).astype(jnp.float32)
    tp = jnp.sum(w * pred * y)
    fp = jnp.sum(w * pred * (1 - y))
    fn = jnp.sum(w * (1 - pred) * y)
    tn = jnp.sum(w * (1 - pred) * (1 - y))
    precision = tp / jnp.maximum(tp + fp, 1e-12)
    recall = tp / jnp.maximum(tp + fn, 1e-12)
    f1 = 2 * precision * recall / jnp.maximum(precision + recall, 1e-12)
    error = (fp + fn) / jnp.maximum(tp + fp + fn + tn, 1e-12)
    return {"Precision": precision, "Recall": recall, "F1": f1,
            "Error": error, "TP": tp, "TN": tn, "FP": fp, "FN": fn}


@jax.jit
def brier_score(y_true, y_prob, sample_weight=None):
    y, w = _weights(y_true, sample_weight)
    p = jnp.asarray(y_prob, jnp.float32)
    return jnp.sum(w * (p - y) ** 2) / jnp.maximum(jnp.sum(w), 1e-12)


@jax.jit
def log_loss(y_true, y_prob, sample_weight=None, eps: float = 1e-15):
    y, w = _weights(y_true, sample_weight)
    p = jnp.clip(jnp.asarray(y_prob, jnp.float32), eps, 1 - eps)
    ll = -(y * jnp.log(p) + (1 - y) * jnp.log1p(-p))
    return jnp.sum(w * ll) / jnp.maximum(jnp.sum(w), 1e-12)


def binary_classification_metrics(y_true, y_prob, sample_weight=None,
                                  threshold: float = 0.5) -> Dict[str, float]:
    """Full binary metric set (OpBinaryClassificationEvaluator parity)."""
    at_t = binary_metrics_at_threshold(y_true, y_prob, threshold, sample_weight)
    out = {
        "AuROC": float(auroc(y_true, y_prob, sample_weight)),
        "AuPR": float(aupr(y_true, y_prob, sample_weight)),
        "BrierScore": float(brier_score(y_true, y_prob, sample_weight)),
        "LogLoss": float(log_loss(y_true, y_prob, sample_weight)),
    }
    out.update({k: float(v) for k, v in at_t.items()})
    return out


def threshold_curves(y_true, y_prob, n_thresholds: int = 100,
                     sample_weight=None) -> Dict[str, np.ndarray]:
    """Precision/recall/F1 across a threshold sweep (thresholdMetrics parity)."""
    ts = np.linspace(0.0, 1.0, n_thresholds)
    f = jax.jit(jax.vmap(
        lambda t: binary_metrics_at_threshold(y_true, y_prob, t, sample_weight)
    ))
    res = f(jnp.asarray(ts, jnp.float32))
    return {"thresholds": ts,
            "precisionByThreshold": np.asarray(res["Precision"]),
            "recallByThreshold": np.asarray(res["Recall"]),
            "f1ByThreshold": np.asarray(res["F1"])}


@functools.partial(jax.jit, static_argnames=("n_classes",))
def _multiclass_core(y_true, y_pred, n_classes, sample_weight=None):
    y = jnp.asarray(y_true, jnp.int32)
    p = jnp.asarray(y_pred, jnp.int32)
    w = (jnp.ones(y.shape[0], jnp.float32) if sample_weight is None
         else jnp.asarray(sample_weight, jnp.float32))
    wsum = jnp.maximum(w.sum(), 1e-12)
    correct = (y == p).astype(jnp.float32)
    acc = jnp.sum(w * correct) / wsum
    conf = jnp.zeros((n_classes, n_classes), jnp.float32).at[y, p].add(w)
    tp = jnp.diag(conf)
    support = conf.sum(axis=1)
    pred_count = conf.sum(axis=0)
    prec_k = tp / jnp.maximum(pred_count, 1e-12)
    rec_k = tp / jnp.maximum(support, 1e-12)
    f1_k = 2 * prec_k * rec_k / jnp.maximum(prec_k + rec_k, 1e-12)
    wts = support / wsum
    return {
        "Accuracy": acc,
        "Error": 1.0 - acc,
        "Precision": jnp.sum(wts * prec_k),
        "Recall": jnp.sum(wts * rec_k),
        "F1": jnp.sum(wts * f1_k),
        "confusion": conf,
    }


def multiclass_metrics(y_true, y_pred, n_classes: int,
                       sample_weight=None) -> Dict[str, float]:
    res = _multiclass_core(y_true, y_pred, n_classes, sample_weight)
    return {k: (float(v) if k != "confusion" else np.asarray(v))
            for k, v in res.items()}


@jax.jit
def _regression_core(y_true, y_pred, sample_weight=None):
    y, w = _weights(y_true, sample_weight)
    p = jnp.asarray(y_pred, jnp.float32)
    wsum = jnp.maximum(w.sum(), 1e-12)
    err = p - y
    mse = jnp.sum(w * err ** 2) / wsum
    mae = jnp.sum(w * jnp.abs(err)) / wsum
    ym = jnp.sum(w * y) / wsum
    ss_tot = jnp.sum(w * (y - ym) ** 2)
    ss_res = jnp.sum(w * err ** 2)
    r2 = 1.0 - ss_res / jnp.maximum(ss_tot, 1e-12)
    return {"RootMeanSquaredError": jnp.sqrt(mse), "MeanSquaredError": mse,
            "MeanAbsoluteError": mae, "R2": r2}


def regression_metrics(y_true, y_pred, sample_weight=None) -> Dict[str, float]:
    return {k: float(v) for k, v in _regression_core(y_true, y_pred, sample_weight).items()}


def forecast_metrics(y_true, y_pred, seasonal_period: int = 1) -> Dict[str, float]:
    """SMAPE + MASE (OpForecastEvaluator parity)."""
    y = np.asarray(y_true, np.float64)
    p = np.asarray(y_pred, np.float64)
    smape = float(np.mean(
        2.0 * np.abs(p - y) / np.maximum(np.abs(p) + np.abs(y), 1e-12)))
    m = seasonal_period
    if len(y) > m:
        scale = np.mean(np.abs(y[m:] - y[:-m]))
        mase = float(np.mean(np.abs(p - y)) / max(scale, 1e-12))
    else:
        mase = float("nan")
    return {"SMAPE": smape, "MASE": mase}
