from .feature import Feature, FeatureCycleError, FeatureHistory  # noqa: F401
from .builder import FeatureBuilder, infer_schema_from_pandas  # noqa: F401
