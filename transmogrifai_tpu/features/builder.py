"""FeatureBuilder — typed construction of raw features.

Reference: features/FeatureBuilder.scala:51,193-330 —
``FeatureBuilder.Real[Passenger].extract(_.age).asPredictor`` plus
``FeatureBuilder.fromDataFrame`` which derives typed features from a DataFrame
schema, picking the response by name.

Python shape:

    age = FeatureBuilder.Real("age").extract(lambda r: r["age"]).as_predictor()
    features, label = FeatureBuilder.from_dataframe(df, response="Survived")
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Type

import numpy as np

from ..stages.generator import FeatureGeneratorStage
from ..types import feature_types as ft
from ..types.feature_types import FeatureType
from .feature import Feature

__all__ = ["FeatureBuilder", "infer_schema_from_pandas"]


class _TypedFeatureBuilder:
    def __init__(self, ftype: Type[FeatureType], name: str):
        self.ftype = ftype
        self.name = name
        self._extract_fn: Optional[Callable[[Any], Any]] = None
        self._aggregator: Optional[str] = None
        self._window_ms: Optional[int] = None
        self._event_field: Optional[str] = None

    def extract(self, fn: Callable[[Any], Any],
                event_field: Optional[str] = None) -> "_TypedFeatureBuilder":
        """Set the record->value extractor.  ``event_field`` optionally
        declares WHICH event-record field the lambda reads — opaque
        lambdas defeat static analysis, so the event-time leakage lint
        (TM060) uses this declaration to track response fields consumed
        as predictors."""
        self._extract_fn = fn
        self._event_field = event_field
        return self

    def aggregate(self, aggregator: str) -> "_TypedFeatureBuilder":
        """Set a registered monoid aggregator name (FeatureBuilder.aggregate)."""
        self._aggregator = aggregator
        return self

    def window(self, window_ms: int) -> "_TypedFeatureBuilder":
        self._window_ms = window_ms
        return self

    def _build(self, is_response: bool) -> Feature:
        stage = FeatureGeneratorStage(
            name=self.name,
            output_type=self.ftype,
            extract_fn=self._extract_fn,
            is_response=is_response,
            aggregator=self._aggregator,
            aggregate_window_ms=self._window_ms,
            event_field=self._event_field,
        )
        return stage.get_output()

    def as_predictor(self) -> Feature:
        return self._build(is_response=False)

    def as_response(self) -> Feature:
        if not issubclass(self.ftype, (ft.SingleResponse, ft.MultiResponse)):
            raise TypeError(
                f"{self.ftype.type_name()} cannot be a response feature"
            )
        return self._build(is_response=True)


class _FeatureBuilderMeta(type):
    """Provides ``FeatureBuilder.Real("x")`` etc. for every registered type."""

    def __getattr__(cls, type_name: str):
        try:
            ftype = ft.type_by_name(type_name)
        except KeyError as e:
            raise AttributeError(type_name) from e

        def make(name: str) -> _TypedFeatureBuilder:
            return _TypedFeatureBuilder(ftype, name)

        return make


class FeatureBuilder(metaclass=_FeatureBuilderMeta):
    """Entry point: ``FeatureBuilder.<TypeName>(name)`` or ``from_dataframe``."""

    @staticmethod
    def of(ftype: Type[FeatureType], name: str) -> _TypedFeatureBuilder:
        return _TypedFeatureBuilder(ftype, name)

    @staticmethod
    def from_schema(
        schema: Dict[str, Type[FeatureType]],
        response: str,
        response_type: Type[FeatureType] = ft.RealNN,
    ) -> Tuple[Feature, List[Feature]]:
        """Build (response, predictors) from {name: type}.

        Reference FeatureBuilder.fromSchema/fromDataFrame
        (features/FeatureBuilder.scala:193-246).
        """
        if response not in schema:
            raise ValueError(f"response column {response!r} not in schema")
        resp = _TypedFeatureBuilder(response_type, response).as_response()
        preds = [
            _TypedFeatureBuilder(t, n).as_predictor()
            for n, t in schema.items()
            if n != response
        ]
        return resp, preds

    @staticmethod
    def from_dataframe(
        df,
        response: str,
        response_type: Type[FeatureType] = ft.RealNN,
        overrides: Optional[Dict[str, Type[FeatureType]]] = None,
    ) -> Tuple[Feature, List[Feature]]:
        schema = infer_schema_from_pandas(df)
        if overrides:
            schema.update(overrides)
        return FeatureBuilder.from_schema(schema, response, response_type)


def infer_schema_from_pandas(df) -> Dict[str, Type[FeatureType]]:
    """Map pandas dtypes -> semantic types (conservative defaults).

    Heuristics mirror ``FeatureSparkTypes.featureTypeTagOf``: ints -> Integral,
    floats -> Real, bools -> Binary, datetimes -> DateTime, low-cardinality
    strings -> PickList, other strings -> Text.
    """
    schema: Dict[str, Type[FeatureType]] = {}
    n = max(len(df), 1)
    for name in df.columns:
        s = df[name]
        kind = s.dtype.kind
        if kind == "b":
            schema[name] = ft.Binary
        elif kind in ("i", "u"):
            nunique = s.nunique(dropna=True)
            schema[name] = ft.Binary if nunique <= 2 and set(
                s.dropna().unique()
            ) <= {0, 1} else ft.Integral
        elif kind == "f":
            schema[name] = ft.Real
        elif kind == "M":
            schema[name] = ft.DateTime
        else:
            nunique = s.nunique(dropna=True)
            schema[name] = ft.PickList if nunique <= max(50, 0.1 * n) else ft.Text
    return schema
