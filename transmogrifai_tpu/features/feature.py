"""Lazy feature DAG nodes.

Reference: ``FeatureLike``/``Feature`` (features/FeatureLike.scala:48,
features/Feature.scala:52).  A ``Feature`` is a *lazy* handle: it records which
stage produces it and from which parent features; no data is attached.  The
workflow reconstructs the full stage DAG from result features by walking
parents (OpWorkflow.setStagesDAG, OpWorkflow.scala:208).

Cycle detection parity: features/FeatureCycleException.scala.
"""
from __future__ import annotations

from typing import TYPE_CHECKING, Callable, Dict, List, Optional, Sequence, Set, Tuple, Type

from ..types.feature_types import FeatureType, OPVector
from ..utils.uid import uid_for

if TYPE_CHECKING:  # pragma: no cover
    from ..stages.base import PipelineStage

__all__ = ["Feature", "FeatureCycleError", "FeatureHistory"]


class FeatureCycleError(Exception):
    """Raised when the feature graph contains a cycle (reference FeatureCycleException)."""


class FeatureHistory:
    """Provenance of a feature: origin raw features + stage path.

    Reference: utils/.../op/FeatureHistory.scala.
    """

    def __init__(self, origin_features: Sequence[str], stages: Sequence[str]):
        self.origin_features = sorted(set(origin_features))
        self.stages = list(stages)

    def merge(self, other: "FeatureHistory") -> "FeatureHistory":
        return FeatureHistory(
            self.origin_features + other.origin_features,
            list(dict.fromkeys(self.stages + other.stages)),
        )

    def to_json(self) -> dict:
        return {"originFeatures": self.origin_features, "stages": self.stages}


class Feature:
    """A typed node in the feature DAG.

    ``origin_stage`` is None for raw features only after deserialization
    corner-cases; normally raw features point at their ``FeatureGeneratorStage``
    (reference Feature.scala:52 — raw features still have an origin stage).
    """

    def __init__(
        self,
        name: str,
        ftype: Type[FeatureType],
        is_response: bool = False,
        origin_stage: Optional["PipelineStage"] = None,
        parents: Sequence["Feature"] = (),
        uid: Optional[str] = None,
    ):
        self.name = name
        self.ftype = ftype
        self.is_response = bool(is_response)
        self.origin_stage = origin_stage
        self.parents: List[Feature] = list(parents)
        self.uid = uid or uid_for("Feature")

    # -- introspection ------------------------------------------------------

    @property
    def is_raw(self) -> bool:
        from ..stages.generator import FeatureGeneratorStage

        return self.origin_stage is None or isinstance(
            self.origin_stage, FeatureGeneratorStage
        )

    def traverse(self, visit: Callable[["Feature"], None]) -> None:
        """DFS over ancestors with cycle detection (FeatureLike.traverse :309)."""
        on_path: Set[int] = set()
        seen: Set[int] = set()

        def rec(f: "Feature"):
            if id(f) in on_path:
                raise FeatureCycleError(
                    f"cycle detected in feature graph at {f.name!r}"
                )
            if id(f) in seen:
                return
            on_path.add(id(f))
            visit(f)
            for p in f.parents:
                rec(p)
            on_path.discard(id(f))
            seen.add(id(f))

        rec(self)

    def raw_features(self) -> List["Feature"]:
        """All raw ancestor features (FeatureLike.rawFeatures :345)."""
        out: List[Feature] = []

        def visit(f: Feature):
            if f.is_raw:
                out.append(f)

        self.traverse(visit)
        # dedupe by uid, stable order
        seen: Set[str] = set()
        uniq = []
        for f in out:
            if f.uid not in seen:
                seen.add(f.uid)
                uniq.append(f)
        return uniq

    def parent_stages(self) -> List["PipelineStage"]:
        """All ancestor stages (FeatureLike.parentStages :360)."""
        out: List["PipelineStage"] = []
        seen: Set[str] = set()

        def visit(f: Feature):
            s = f.origin_stage
            if s is not None and s.uid not in seen:
                seen.add(s.uid)
                out.append(s)

        self.traverse(visit)
        return out

    def history(self) -> FeatureHistory:
        raws = [f.name for f in self.raw_features()]
        stages = [s.uid for s in self.parent_stages()]
        return FeatureHistory(raws, stages)

    # -- graph rewriting ----------------------------------------------------

    def copy_with_new_stages(
        self, stage_map: Dict[str, "PipelineStage"]
    ) -> "Feature":
        """Rebuild this feature's ancestry replacing stages by uid.

        Used when substituting fitted models for estimators
        (reference Feature.copyWithNewStages, Feature.scala:86).
        """
        cache: Dict[str, Feature] = {}

        def rec(f: Feature) -> Feature:
            if f.uid in cache:
                return cache[f.uid]
            new_parents = [rec(p) for p in f.parents]
            stage = stage_map.get(f.origin_stage.uid, f.origin_stage) if f.origin_stage else None
            nf = Feature(
                f.name, f.ftype, f.is_response, stage, new_parents, uid=f.uid
            )
            cache[f.uid] = nf
            return nf

        return rec(self)

    # -- typed combinators (DSL hooks attach more; see ops/dsl.py) ----------

    def transform_with(self, stage: "PipelineStage", *others: "Feature") -> "Feature":
        """Apply a stage to this (+ other) features, returning its output feature.

        Reference FeatureLike.transformWith (:210-283).
        """
        stage.set_input(self, *others)
        return stage.get_output()

    # -- equality: by semantic ancestry, like FeatureLike.equals (:143) -----

    def semantic_key(self) -> Tuple:
        stage_key = self.origin_stage.uid if self.origin_stage else None
        return (
            self.name,
            self.ftype.type_name(),
            self.is_response,
            stage_key,
            tuple(p.semantic_key() for p in self.parents),
        )

    def __repr__(self):
        return (
            f"Feature(name={self.name!r}, type={self.ftype.type_name()}, "
            f"response={self.is_response}, uid={self.uid!r})"
        )

    # -- serialization (FeatureJsonHelper parity) ---------------------------

    def to_json(self) -> dict:
        return {
            "name": self.name,
            "typeName": self.ftype.type_name(),
            "isResponse": self.is_response,
            "uid": self.uid,
            "originStage": self.origin_stage.uid if self.origin_stage else None,
            "parents": [p.uid for p in self.parents],
        }
