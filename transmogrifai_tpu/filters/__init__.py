"""Raw-feature QA filters (reference core/.../filters/, SURVEY §2.6)."""
from .feature_distribution import FeatureDistribution, profile_column
from .raw_feature_filter import (
    ExclusionReasons, RawFeatureFilter, RawFeatureFilterResults,
)

__all__ = ["FeatureDistribution", "profile_column", "RawFeatureFilter",
           "RawFeatureFilterResults", "ExclusionReasons"]
