"""Per-raw-feature distribution profiles for RawFeatureFilter.

Reference: ``FeatureDistribution`` (core/.../filters/FeatureDistribution.scala
:58,235) — count / nulls / histogram per raw feature (and per map key), built
as a monoid so Spark can map-reduce it over partitions (:187-192); numerics
profile through the streaming histogram, text through hashed token counts.

Here columns are profiled in one vectorized pass; the monoid ``+`` remains so
distributions reduce across data shards (the mesh/host-shard analogue of the
reference's partition reduce).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

import numpy as np

from ..types.columns import FeatureColumn
from ..utils.hashing import murmur3_32
from ..utils.streaming_histogram import StreamingHistogram

__all__ = ["FeatureDistribution", "profile_column"]

TEXT_BINS = 255          # hashed token buckets for text (reference default)
NUMERIC_BINS = 100
#: cells for train-vs-score density comparison — coarser than the histogram
#: so per-cell mass is well estimated (keeps JS of identical dists near 0)
JS_GRID = 20


@dataclasses.dataclass
class FeatureDistribution:
    name: str
    key: Optional[str] = None          # map key (None for scalar features)
    count: int = 0
    nulls: int = 0
    hist: Optional[StreamingHistogram] = None     # numeric profile
    text_counts: Optional[np.ndarray] = None      # hashed text profile
    moments_n: float = 0.0
    moments_sum: float = 0.0
    moments_sum2: float = 0.0
    #: null×label leakage co-counts (monoid fields): with the null
    #: indicator n_i ∈ {0,1} per row and label l_i, the Pearson
    #: corr(null, label) the filter's leakage check needs is a pure
    #: function of (count, nulls, Σl, Σl², Σ n_i·l_i) — so the check
    #: streams and merges exactly like fill rates do
    lab_sum: float = 0.0
    lab_sum2: float = 0.0
    null_lab_sum: float = 0.0
    has_label: bool = False

    @property
    def full_name(self) -> str:
        return f"{self.name}[{self.key}]" if self.key is not None else self.name

    def fill_rate(self) -> float:
        return (self.count - self.nulls) / self.count if self.count else 0.0

    def relative_fill_rate(self, other: "FeatureDistribution") -> float:
        return abs(self.fill_rate() - other.fill_rate())

    def relative_fill_ratio(self, other: "FeatureDistribution") -> float:
        a, b = self.fill_rate(), other.fill_rate()
        lo, hi = min(a, b), max(a, b)
        return float("inf") if lo == 0 else hi / lo

    def __add__(self, other: "FeatureDistribution") -> "FeatureDistribution":
        assert (self.name, self.key) == (other.name, other.key)
        if ((self.hist is not None and other.text_counts is not None)
                or (self.text_counts is not None and other.hist is not None)):
            # representation conflict (a map key that looked numeric in one
            # chunk and textual in another): degrade to a fill-rate-only
            # profile — the JS check then reads 0 (never drops), which is
            # the conservative failure mode for a heterogeneous key
            hist, tc = None, None
        else:
            hist = (self.hist.merge(other.hist)
                    if self.hist is not None and other.hist is not None
                    else self.hist or other.hist)
            tc = None
            if self.text_counts is not None or other.text_counts is not None:
                a = self.text_counts if self.text_counts is not None else 0
                b = other.text_counts if other.text_counts is not None else 0
                tc = a + b
        return FeatureDistribution(
            self.name, self.key, self.count + other.count,
            self.nulls + other.nulls, hist, tc,
            self.moments_n + other.moments_n,
            self.moments_sum + other.moments_sum,
            self.moments_sum2 + other.moments_sum2,
            self.lab_sum + other.lab_sum,
            self.lab_sum2 + other.lab_sum2,
            self.null_lab_sum + other.null_lab_sum,
            self.has_label or other.has_label)

    def null_label_corr(self) -> float:
        """Pearson correlation between the per-row null indicator and the
        label, from the accumulated co-counts (identical to
        ``np.corrcoef(null, label)`` up to float summation order)."""
        n = self.count
        if n == 0 or not self.has_label:
            return 0.0
        p = self.nulls / n
        var_null = p * (1.0 - p)
        mean_l = self.lab_sum / n
        var_l = self.lab_sum2 / n - mean_l * mean_l
        if var_null <= 0.0 or var_l <= 0.0:
            return 0.0
        cov = self.null_lab_sum / n - p * mean_l
        return float(cov / np.sqrt(var_null * var_l))

    def _note_label(self, null_mask: np.ndarray, label: np.ndarray) -> None:
        """Accumulate the leakage co-counts for this profile's rows."""
        lab = np.nan_to_num(np.asarray(label, np.float64))
        self.lab_sum += float(lab.sum())
        self.lab_sum2 += float((lab ** 2).sum())
        self.null_lab_sum += float(lab[np.asarray(null_mask, bool)].sum())
        self.has_label = True

    def js_divergence(self, other: "FeatureDistribution") -> float:
        """Jensen-Shannon divergence between two profiles of the same feature
        (FeatureDistribution.jsDivergence) — in [0, 1] with log base 2."""
        p, q = self._density_pair(other)
        if p is None:
            return 0.0
        m = 0.5 * (p + q)

        def kl(a, b):
            mask = a > 0
            return float(np.sum(a[mask] * np.log2(a[mask] / b[mask])))

        return 0.5 * kl(p, m) + 0.5 * kl(q, m)

    def _density_pair(self, other):
        if self.hist is not None and other.hist is not None:
            lo1, hi1 = self.hist.bounds
            lo2, hi2 = other.hist.bounds
            if np.isnan(lo1) or np.isnan(lo2):
                return None, None
            lo, hi = min(lo1, lo2), max(hi1, hi2)
            if lo == hi:
                return None, None
            grid = np.linspace(lo, hi, JS_GRID)
            return self.hist.density(grid), other.hist.density(grid)
        if self.text_counts is not None and other.text_counts is not None:
            ts, to = self.text_counts.sum(), other.text_counts.sum()
            if ts == 0 or to == 0:
                return None, None
            return self.text_counts / ts, other.text_counts / to
        return None, None

    def to_json(self) -> dict:
        return {
            "name": self.name, "key": self.key, "count": self.count,
            "nulls": self.nulls, "fillRate": self.fill_rate(),
            "moments": {"n": self.moments_n, "sum": self.moments_sum,
                        "sum2": self.moments_sum2},
            "histogram": self.hist.to_json() if self.hist else None,
            "textCounts": (self.text_counts.tolist()
                           if self.text_counts is not None else None),
        }


def _profile_numeric(name, key, vals: np.ndarray, mask: np.ndarray,
                     label: Optional[np.ndarray] = None):
    d = FeatureDistribution(name, key, count=len(vals),
                            nulls=int((~mask).sum()))
    finite = vals[mask & np.isfinite(vals)]
    d.hist = StreamingHistogram(NUMERIC_BINS).update(finite)
    d.moments_n = float(finite.size)
    d.moments_sum = float(finite.sum())
    d.moments_sum2 = float((finite ** 2).sum())
    if label is not None:
        d._note_label(~np.asarray(mask, bool), label)
    return d


def _profile_text(name, key, values,
                  label: Optional[np.ndarray] = None) -> FeatureDistribution:
    d = FeatureDistribution(name, key, count=len(values))
    counts = np.zeros(TEXT_BINS, np.float64)
    null = np.zeros(len(values), bool)
    for i, v in enumerate(values):
        if v is None:
            null[i] = True
        else:
            counts[murmur3_32(str(v)) % TEXT_BINS] += 1
    d.nulls = int(null.sum())
    d.text_counts = counts
    if label is not None:
        d._note_label(null, label)
    return d


def profile_column(name: str, col: FeatureColumn,
                   label: Optional[np.ndarray] = None
                   ) -> List[FeatureDistribution]:
    """Profile one raw column into distributions (one per map key for maps).

    ``label`` (the response values for the SAME rows, already
    ``nan_to_num``-able) additionally accumulates the null×label leakage
    co-counts — pass it on the training side so the filter's leakage
    decision is a pure function of the (mergeable) distributions.
    """
    st = col.ftype.storage
    if st in ("real", "integral", "binary", "date"):
        vals = np.asarray(col.values, np.float64)
        return [_profile_numeric(name, None, vals, np.asarray(col.mask),
                                 label)]
    if st == "text":
        return [_profile_text(name, None, list(col.values), label)]
    if st in ("text_list", "multi_pick_list", "date_list"):
        flat = [" ".join(map(str, sorted(v))) if v else None
                for v in col.values]
        return [_profile_text(name, None, flat, label)]
    if st == "geolocation":
        vals = np.asarray(col.values, np.float64)
        mask = np.asarray(col.mask)
        return [_profile_numeric(name, None, vals[:, 0], mask, label)]
    if st == "map":
        keys = sorted({k for row in col.values for k in row})
        out = []
        for k in keys:
            sample = next((row[k] for row in col.values if k in row), None)
            if isinstance(sample, (int, float, bool)) and not isinstance(
                    sample, bool):
                try:
                    vals, mask = [], []
                    for row in col.values:
                        v = row.get(k)
                        mask.append(v is not None)
                        vals.append(float(v) if v is not None else np.nan)
                    out.append(_profile_numeric(
                        name, k, np.asarray(vals), np.asarray(mask), label))
                    continue
                except (TypeError, ValueError):
                    pass  # heterogeneous values — profile as text below
            out.append(_profile_text(
                name, k, [None if row.get(k) is None else str(row.get(k))
                          for row in col.values], label))
        return out
    # vectors and unknowns: count-only profile
    return [FeatureDistribution(name, None, count=len(col))]


def merge_distributions(acc: Dict[tuple, FeatureDistribution],
                        dists: List[FeatureDistribution]) -> None:
    """Fold one chunk's profiles into the running (name, key)-keyed monoid
    accumulator — the streaming analogue of the reference's partition
    map-reduce (FeatureDistribution.scala:187-192)."""
    for d in dists:
        k = (d.name, d.key)
        prev = acc.get(k)
        acc[k] = d if prev is None else prev + d
