"""RawFeatureFilter — workflow-level raw-feature QA before the DAG runs.

Reference: ``RawFeatureFilter`` (core/.../filters/RawFeatureFilter.scala:90):
profiles every raw feature (and map key) on the training and (optionally)
scoring readers, then drops features whose training fill rate is too low,
whose train/score fill rates diverge (absolute difference or ratio), whose
train/score distributions diverge (Jensen-Shannon), or whose null-indicator
correlates with the label (leakage) — decision logic at :445-486; cleaned
data + dropped lists returned by ``generateFilteredRaw`` :486-575; results
recorded as ``RawFeatureFilterResults`` (filters/RawFeatureFilterResults.scala).
Defaults mirror ``OpWorkflow.withRawFeatureFilter`` (OpWorkflow.scala:541-545).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..types.columns import ColumnarDataset, FeatureColumn
from .feature_distribution import (FeatureDistribution, merge_distributions,
                                   profile_column)

__all__ = ["RawFeatureFilter", "RawFeatureFilterResults", "ExclusionReasons"]


@dataclasses.dataclass
class ExclusionReasons:
    """Why a feature/key was (or wasn't) dropped (ExclusionReasons parity)."""
    name: str
    key: Optional[str]
    train_fill_rate: float
    low_fill: bool = False
    fill_difference: bool = False
    fill_ratio: bool = False
    js_divergence: bool = False
    null_label_leakage: bool = False

    @property
    def excluded(self) -> bool:
        return (self.low_fill or self.fill_difference or self.fill_ratio
                or self.js_divergence or self.null_label_leakage)

    def to_json(self) -> dict:
        return dataclasses.asdict(self) | {"excluded": self.excluded}


@dataclasses.dataclass
class RawFeatureFilterResults:
    """Config + distributions + decisions (RawFeatureFilterResults parity)."""
    config: Dict[str, Any]
    train_distributions: List[FeatureDistribution]
    score_distributions: List[FeatureDistribution]
    exclusion_reasons: List[ExclusionReasons]
    dropped_features: List[str]
    dropped_map_keys: Dict[str, List[str]]

    def to_json(self) -> dict:
        return {
            "config": self.config,
            "trainDistributions": [d.to_json() for d in self.train_distributions],
            "scoreDistributions": [d.to_json() for d in self.score_distributions],
            "exclusionReasons": [r.to_json() for r in self.exclusion_reasons],
            "droppedFeatures": self.dropped_features,
            "droppedMapKeys": self.dropped_map_keys,
        }


class RawFeatureFilter:
    def __init__(self,
                 min_fill_rate: float = 0.001,
                 max_fill_difference: float = 0.90,
                 max_fill_ratio_diff: float = 20.0,
                 max_js_divergence: float = 0.90,
                 max_correlation: float = 0.95,
                 protected_features: Sequence[str] = (),
                 js_divergence_protected_features: Sequence[str] = (),
                 scoring_data=None):
        if not 0.0 <= min_fill_rate <= 1.0:
            raise ValueError(f"invalid min_fill_rate {min_fill_rate}")
        if not 0.0 <= max_fill_difference <= 1.0:
            raise ValueError(f"invalid max_fill_difference {max_fill_difference}")
        if max_fill_ratio_diff < 0:
            raise ValueError(f"invalid max_fill_ratio_diff {max_fill_ratio_diff}")
        if not 0.0 <= max_js_divergence <= 1.0:
            raise ValueError(f"invalid max_js_divergence {max_js_divergence}")
        self.min_fill_rate = min_fill_rate
        self.max_fill_difference = max_fill_difference
        self.max_fill_ratio_diff = max_fill_ratio_diff
        self.max_js_divergence = max_js_divergence
        self.max_correlation = max_correlation
        self.protected_features: Set[str] = set(protected_features)
        self.js_protected: Set[str] = set(js_divergence_protected_features)
        self.scoring_data = scoring_data
        #: optional jax.sharding.Mesh — numeric distribution passes then run
        #: as ONE row-sharded psum program (with_mesh); runtime-only
        self.mesh = None

    def with_mesh(self, mesh) -> "RawFeatureFilter":
        """Profile numeric columns mesh-sharded: the TPU analogue of the
        reference's executor-distributed per-partition profile + monoid
        reduce (RawFeatureFilter.scala:489-545).  Text/map columns keep the
        host profiling pass (hash-token loops are host work in both
        implementations)."""
        self.mesh = mesh
        return self

    # -- profiling ----------------------------------------------------------

    _MESH_NUMERIC = ("real", "integral", "binary", "date")

    def _profiles(self, data: ColumnarDataset, names: Sequence[str],
                  label: Optional[np.ndarray] = None):
        out: List[FeatureDistribution] = []
        mesh_cols: List[str] = []
        for n in names:
            if n not in data:
                continue
            if (self.mesh is not None
                    and data[n].ftype.storage in self._MESH_NUMERIC):
                mesh_cols.append(n)
            else:
                out.extend(profile_column(n, data[n], label))
        if mesh_cols:
            out.extend(self._profiles_numeric_sharded(data, mesh_cols,
                                                      label))
        return out

    def _profiles_numeric_sharded(self, data: ColumnarDataset,
                                  names: Sequence[str],
                                  label: Optional[np.ndarray] = None):
        """All scalar-numeric columns in one sharded device pass; the
        fixed-grid histogram loads into the same StreamingHistogram
        estimator the host pass builds (grid centers as centroids)."""
        from ..parallel.sharded import profile_numeric_sharded
        from ..utils.streaming_histogram import StreamingHistogram
        from .feature_distribution import NUMERIC_BINS

        X = np.stack([np.asarray(data[n].values, np.float64)
                      for n in names], axis=1)
        mask = np.stack([np.asarray(data[n].mask) for n in names], axis=1)
        nulls, valid, s, s2, mn, mx, hist, edges = profile_numeric_sharded(
            X.astype(np.float32), mask, self.mesh, n_bins=NUMERIC_BINS)
        out = []
        for j, name in enumerate(names):
            d = FeatureDistribution(name, None, count=X.shape[0],
                                    nulls=int(nulls[j]))
            h = StreamingHistogram(NUMERIC_BINS)
            centers = 0.5 * (edges[:-1, j] + edges[1:, j])
            nz = hist[:, j] > 0
            h.centroids = centers[nz].astype(np.float64)
            h.counts = hist[nz, j].astype(np.float64)
            d.hist = h
            d.moments_n = float(valid[j])
            d.moments_sum = float(s[j])
            d.moments_sum2 = float(s2[j])
            if label is not None:
                d._note_label(~mask[:, j], label)
            out.append(d)
        return out

    # -- decision + data cleaning ------------------------------------------

    def _decide(self, train_dists: List[FeatureDistribution],
                score_dists: List[FeatureDistribution]
                ) -> Tuple[List[ExclusionReasons], List[str],
                           Dict[str, List[str]]]:
        """Drop decisions as a pure function of the (mergeable)
        distributions — shared by the in-core and streaming profiles, so
        chunked profiling cannot drift from the reference decision logic
        (RawFeatureFilter.scala:445-486)."""
        score_by_key = {(d.name, d.key): d for d in score_dists}
        reasons: List[ExclusionReasons] = []
        for d in train_dists:
            r = ExclusionReasons(d.name, d.key, d.fill_rate())
            if d.name not in self.protected_features:
                r.low_fill = d.fill_rate() < self.min_fill_rate
                s = score_by_key.get((d.name, d.key))
                if s is not None and s.count > 0:
                    r.fill_difference = (d.relative_fill_rate(s)
                                         > self.max_fill_difference)
                    r.fill_ratio = (d.relative_fill_ratio(s)
                                    > self.max_fill_ratio_diff)
                    if d.name not in self.js_protected:
                        r.js_divergence = (d.js_divergence(s)
                                           > self.max_js_divergence)
                if d.has_label:
                    r.null_label_leakage = (abs(d.null_label_corr())
                                            > self.max_correlation)
            reasons.append(r)

        dropped_features: List[str] = []
        dropped_map_keys: Dict[str, List[str]] = {}
        by_feature: Dict[str, List[ExclusionReasons]] = {}
        for r in reasons:
            by_feature.setdefault(r.name, []).append(r)
        for name, rs in by_feature.items():
            keyed = [r for r in rs if r.key is not None]
            if keyed:
                bad = [r.key for r in keyed if r.excluded]
                if bad:
                    if len(bad) == len(keyed):
                        dropped_features.append(name)
                    else:
                        dropped_map_keys[name] = bad
            elif any(r.excluded for r in rs):
                dropped_features.append(name)
        return reasons, dropped_features, dropped_map_keys

    def _results(self, train_dists, score_dists, reasons, dropped_features,
                 dropped_map_keys) -> RawFeatureFilterResults:
        return RawFeatureFilterResults(
            config={
                "minFillRate": self.min_fill_rate,
                "maxFillDifference": self.max_fill_difference,
                "maxFillRatioDiff": self.max_fill_ratio_diff,
                "maxJSDivergence": self.max_js_divergence,
                "maxCorrelation": self.max_correlation,
            },
            train_distributions=train_dists,
            score_distributions=score_dists,
            exclusion_reasons=reasons,
            dropped_features=dropped_features,
            dropped_map_keys=dropped_map_keys,
        )

    def clean_chunk(self, data: ColumnarDataset,
                    dropped_features: Sequence[str],
                    dropped_map_keys: Dict[str, List[str]]
                    ) -> ColumnarDataset:
        """Apply already-made drop decisions to one dataset/chunk — the
        per-chunk cleaning step of the streaming path (decisions are made
        once on the profile pass; every later reader pass cleans chunks
        identically, so chunking never changes what the DAG sees)."""
        cleaned = data
        to_drop = [n for n in dropped_features if n in cleaned]
        if to_drop:
            cleaned = cleaned.drop(to_drop)
        for name, keys in dropped_map_keys.items():
            if name not in cleaned:
                continue
            col = cleaned[name]
            vals = np.empty(len(col.values), dtype=object)
            bad = set(keys)
            for i, row in enumerate(col.values):
                vals[i] = {k: v for k, v in row.items() if k not in bad}
            if cleaned is data:
                cleaned = data.copy()
            cleaned.set(name, FeatureColumn(col.ftype, vals))
        return cleaned

    def filter_raw_data(self, data: ColumnarDataset,
                        raw_features) -> Tuple[ColumnarDataset,
                                               RawFeatureFilterResults]:
        predictors = [f for f in raw_features if not f.is_response]
        responses = [f for f in raw_features if f.is_response]
        pred_names = [f.name for f in predictors]

        label = None
        if responses and responses[0].name in data:
            label = np.nan_to_num(
                np.asarray(data[responses[0].name].values, np.float64))

        train_dists = self._profiles(data, pred_names, label=label)
        score_dists: List[FeatureDistribution] = []
        if self.scoring_data is not None:
            from ..readers.base import reader_for

            score_data = reader_for(self.scoring_data).generate_dataset(
                predictors)
            score_dists = self._profiles(score_data, pred_names)

        reasons, dropped_features, dropped_map_keys = self._decide(
            train_dists, score_dists)
        cleaned = self.clean_chunk(data.copy(), dropped_features,
                                   dropped_map_keys)
        results = self._results(train_dists, score_dists, reasons,
                                dropped_features, dropped_map_keys)
        return cleaned, results

    # -- streaming profile (out-of-core trains) -----------------------------

    def filter_streaming(self, reader, raw_features, chunk_rows: int,
                         pod=None
                         ) -> Tuple[RawFeatureFilterResults,
                                    Dict[str, Any]]:
        """Profile the TRAIN reader (and the scoring reader, when given)
        one bounded chunk at a time and make the same drop decisions as
        the in-core pass — ``FeatureDistribution`` is a monoid, so the
        per-chunk profiles merge exactly like the reference's partition
        reduce, and the leakage check rides the null×label co-counts
        accumulated alongside.  Adds ONE reader pass before the streaming
        fit passes (the ``rff.pass`` fault point fires per pass: index 0
        tag="train", index 1 tag="score").

        Returns ``(results, stats)`` where stats carries the pass's row /
        retry / quarantine accounting for the ingest profiler.
        """
        from ..utils import faults

        predictors = [f for f in raw_features if not f.is_response]
        responses = [f for f in raw_features if f.is_response]
        pred_names = [f.name for f in predictors]
        label_name = responses[0].name if responses else None

        stats: Dict[str, Any] = {"passes": 1, "rows": 0, "score_rows": 0,
                                 "retries": 0, "retry_wait_s": 0.0}
        faults.fire("rff.pass", index=0, tag="train")
        train_dists, rows = self._profile_reader(
            reader, list(raw_features), pred_names, label_name, chunk_rows,
            stats, pod=pod)
        stats["rows"] = rows

        score_dists: List[FeatureDistribution] = []
        if self.scoring_data is not None:
            from ..readers.base import reader_for

            faults.fire("rff.pass", index=1, tag="score")
            stats["passes"] = 2
            score_dists, srows = self._profile_reader(
                reader_for(self.scoring_data), predictors, pred_names,
                None, chunk_rows, stats)
            stats["score_rows"] = srows

        reasons, dropped_features, dropped_map_keys = self._decide(
            train_dists, score_dists)
        results = self._results(train_dists, score_dists, reasons,
                                dropped_features, dropped_map_keys)
        return results, stats

    def _profile_reader(self, reader, read_features, pred_names: List[str],
                        label_name: Optional[str], chunk_rows: int,
                        stats: Dict[str, Any], pod=None
                        ) -> Tuple[List[FeatureDistribution], int]:
        """One chunked profile pass over ``reader``; honors the reader's
        resilience config (retry/backoff + bad-record quarantine), so a
        corrupt row hit here AND by the later fit passes still counts
        once in the sidecar (dedup on (source, location)).

        ``pod`` (an active ``distributed.PodContext``) means ``reader``
        covers only THIS process's host ranges: the per-host monoid
        accumulators (and the label totals the leakage co-counts need)
        allgather and re-merge before normalization, so every process
        makes IDENTICAL drop decisions from the full-data profile —
        ``FeatureDistribution`` merges exactly like the reference's
        partition reduce, just across processes now."""
        rcfg = getattr(reader, "resilience", None)
        if rcfg is not None and rcfg.retry is not None:
            from ..readers.resilience import RetryingChunkStream

            stream = RetryingChunkStream(
                lambda: reader.iter_chunks(read_features, chunk_rows),
                rcfg.retry)
        else:
            stream = reader.iter_chunks(read_features, chunk_rows)
        acc: Dict[tuple, FeatureDistribution] = {}
        rows = 0
        lab_n = lab_sum = lab_sum2 = 0.0
        for chunk in stream:
            label = None
            if label_name is not None and label_name in chunk:
                label = np.nan_to_num(np.asarray(
                    chunk[label_name].values, np.float64))
                lab_n += len(label)
                lab_sum += float(label.sum())
                lab_sum2 += float((label ** 2).sum())
            for name in pred_names:
                if name not in chunk:
                    continue
                merge_distributions(acc,
                                    profile_column(name, chunk[name], label))
            rows += len(chunk)
        stats["retries"] += int(getattr(stream, "retries", 0) or 0)
        stats["retry_wait_s"] += float(
            getattr(stream, "retry_wait_s", 0.0) or 0.0)
        if pod is not None and pod.active:
            parts = pod.allgather_obj(
                (list(acc.items()), rows, lab_n, lab_sum, lab_sum2))
            acc = {}
            rows = 0
            lab_n = lab_sum = lab_sum2 = 0.0
            for items, r, ln, ls, ls2 in parts:
                for _key, d in items:
                    merge_distributions(acc, [d])
                rows += r
                lab_n += ln
                lab_sum += ls
                lab_sum2 += ls2
        return self._ordered_dists(acc, pred_names, rows,
                                   (lab_sum, lab_sum2) if lab_n else None
                                   ), rows

    @staticmethod
    def _ordered_dists(acc: Dict[tuple, FeatureDistribution],
                       pred_names: List[str], total_rows: int,
                       label_totals: Optional[Tuple[float, float]]
                       ) -> List[FeatureDistribution]:
        """Deterministic in-core-parity ordering + map-key normalization:
        a map key absent from some chunks never produced a profile for
        those rows, so its count/nulls (and, with a label, the null×label
        co-counts — the missing rows are all-null) are topped up to the
        full row count, matching the single-pass profile exactly."""
        out: List[FeatureDistribution] = []
        for name in pred_names:
            keyed = sorted((k for (n, k) in acc if n == name
                            and k is not None))
            if (name, None) in acc:
                out.append(acc[(name, None)])
            for k in keyed:
                d = acc[(name, k)]
                if d.count < total_rows:
                    missing = total_rows - d.count
                    d.nulls += missing
                    d.count = total_rows
                    if label_totals is not None and d.has_label:
                        # the missing rows are all-null: their labels move
                        # into the null·label cross term, and the label
                        # moments become the full-data moments
                        tot_sum, tot_sum2 = label_totals
                        d.null_lab_sum += tot_sum - d.lab_sum
                        d.lab_sum = tot_sum
                        d.lab_sum2 = tot_sum2
                out.append(d)
        return out
