"""RawFeatureFilter — workflow-level raw-feature QA before the DAG runs.

Reference: ``RawFeatureFilter`` (core/.../filters/RawFeatureFilter.scala:90):
profiles every raw feature (and map key) on the training and (optionally)
scoring readers, then drops features whose training fill rate is too low,
whose train/score fill rates diverge (absolute difference or ratio), whose
train/score distributions diverge (Jensen-Shannon), or whose null-indicator
correlates with the label (leakage) — decision logic at :445-486; cleaned
data + dropped lists returned by ``generateFilteredRaw`` :486-575; results
recorded as ``RawFeatureFilterResults`` (filters/RawFeatureFilterResults.scala).
Defaults mirror ``OpWorkflow.withRawFeatureFilter`` (OpWorkflow.scala:541-545).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..types.columns import ColumnarDataset, FeatureColumn
from .feature_distribution import FeatureDistribution, profile_column

__all__ = ["RawFeatureFilter", "RawFeatureFilterResults", "ExclusionReasons"]


@dataclasses.dataclass
class ExclusionReasons:
    """Why a feature/key was (or wasn't) dropped (ExclusionReasons parity)."""
    name: str
    key: Optional[str]
    train_fill_rate: float
    low_fill: bool = False
    fill_difference: bool = False
    fill_ratio: bool = False
    js_divergence: bool = False
    null_label_leakage: bool = False

    @property
    def excluded(self) -> bool:
        return (self.low_fill or self.fill_difference or self.fill_ratio
                or self.js_divergence or self.null_label_leakage)

    def to_json(self) -> dict:
        return dataclasses.asdict(self) | {"excluded": self.excluded}


@dataclasses.dataclass
class RawFeatureFilterResults:
    """Config + distributions + decisions (RawFeatureFilterResults parity)."""
    config: Dict[str, Any]
    train_distributions: List[FeatureDistribution]
    score_distributions: List[FeatureDistribution]
    exclusion_reasons: List[ExclusionReasons]
    dropped_features: List[str]
    dropped_map_keys: Dict[str, List[str]]

    def to_json(self) -> dict:
        return {
            "config": self.config,
            "trainDistributions": [d.to_json() for d in self.train_distributions],
            "scoreDistributions": [d.to_json() for d in self.score_distributions],
            "exclusionReasons": [r.to_json() for r in self.exclusion_reasons],
            "droppedFeatures": self.dropped_features,
            "droppedMapKeys": self.dropped_map_keys,
        }


class RawFeatureFilter:
    def __init__(self,
                 min_fill_rate: float = 0.001,
                 max_fill_difference: float = 0.90,
                 max_fill_ratio_diff: float = 20.0,
                 max_js_divergence: float = 0.90,
                 max_correlation: float = 0.95,
                 protected_features: Sequence[str] = (),
                 js_divergence_protected_features: Sequence[str] = (),
                 scoring_data=None):
        if not 0.0 <= min_fill_rate <= 1.0:
            raise ValueError(f"invalid min_fill_rate {min_fill_rate}")
        if not 0.0 <= max_fill_difference <= 1.0:
            raise ValueError(f"invalid max_fill_difference {max_fill_difference}")
        if max_fill_ratio_diff < 0:
            raise ValueError(f"invalid max_fill_ratio_diff {max_fill_ratio_diff}")
        if not 0.0 <= max_js_divergence <= 1.0:
            raise ValueError(f"invalid max_js_divergence {max_js_divergence}")
        self.min_fill_rate = min_fill_rate
        self.max_fill_difference = max_fill_difference
        self.max_fill_ratio_diff = max_fill_ratio_diff
        self.max_js_divergence = max_js_divergence
        self.max_correlation = max_correlation
        self.protected_features: Set[str] = set(protected_features)
        self.js_protected: Set[str] = set(js_divergence_protected_features)
        self.scoring_data = scoring_data
        #: optional jax.sharding.Mesh — numeric distribution passes then run
        #: as ONE row-sharded psum program (with_mesh); runtime-only
        self.mesh = None

    def with_mesh(self, mesh) -> "RawFeatureFilter":
        """Profile numeric columns mesh-sharded: the TPU analogue of the
        reference's executor-distributed per-partition profile + monoid
        reduce (RawFeatureFilter.scala:489-545).  Text/map columns keep the
        host profiling pass (hash-token loops are host work in both
        implementations)."""
        self.mesh = mesh
        return self

    # -- profiling ----------------------------------------------------------

    _MESH_NUMERIC = ("real", "integral", "binary", "date")

    def _profiles(self, data: ColumnarDataset, names: Sequence[str]):
        out: List[FeatureDistribution] = []
        mesh_cols: List[str] = []
        for n in names:
            if n not in data:
                continue
            if (self.mesh is not None
                    and data[n].ftype.storage in self._MESH_NUMERIC):
                mesh_cols.append(n)
            else:
                out.extend(profile_column(n, data[n]))
        if mesh_cols:
            out.extend(self._profiles_numeric_sharded(data, mesh_cols))
        return out

    def _profiles_numeric_sharded(self, data: ColumnarDataset,
                                  names: Sequence[str]):
        """All scalar-numeric columns in one sharded device pass; the
        fixed-grid histogram loads into the same StreamingHistogram
        estimator the host pass builds (grid centers as centroids)."""
        from ..parallel.sharded import profile_numeric_sharded
        from ..utils.streaming_histogram import StreamingHistogram
        from .feature_distribution import NUMERIC_BINS

        X = np.stack([np.asarray(data[n].values, np.float64)
                      for n in names], axis=1)
        mask = np.stack([np.asarray(data[n].mask) for n in names], axis=1)
        nulls, valid, s, s2, mn, mx, hist, edges = profile_numeric_sharded(
            X.astype(np.float32), mask, self.mesh, n_bins=NUMERIC_BINS)
        out = []
        for j, name in enumerate(names):
            d = FeatureDistribution(name, None, count=X.shape[0],
                                    nulls=int(nulls[j]))
            h = StreamingHistogram(NUMERIC_BINS)
            centers = 0.5 * (edges[:-1, j] + edges[1:, j])
            nz = hist[:, j] > 0
            h.centroids = centers[nz].astype(np.float64)
            h.counts = hist[nz, j].astype(np.float64)
            d.hist = h
            d.moments_n = float(valid[j])
            d.moments_sum = float(s[j])
            d.moments_sum2 = float(s2[j])
            out.append(d)
        return out

    def _null_label_corr(self, data: ColumnarDataset, name: str,
                         key: Optional[str], label: np.ndarray) -> float:
        col = data[name]
        if key is not None:
            null = np.array([key not in row or row.get(key) is None
                             for row in col.values], np.float64)
        elif col.mask is not None:
            null = (~np.asarray(col.mask)).astype(np.float64)
        else:
            null = np.array([v is None for v in col.values], np.float64)
        if null.std() == 0 or np.std(label) == 0:
            return 0.0
        return float(np.corrcoef(null, label)[0, 1])

    # -- decision + data cleaning ------------------------------------------

    def filter_raw_data(self, data: ColumnarDataset,
                        raw_features) -> Tuple[ColumnarDataset,
                                               RawFeatureFilterResults]:
        predictors = [f for f in raw_features if not f.is_response]
        responses = [f for f in raw_features if f.is_response]
        pred_names = [f.name for f in predictors]

        train_dists = self._profiles(data, pred_names)
        score_data = None
        score_dists: List[FeatureDistribution] = []
        if self.scoring_data is not None:
            from ..readers.base import reader_for

            score_data = reader_for(self.scoring_data).generate_dataset(
                predictors)
            score_dists = self._profiles(score_data, pred_names)
        score_by_key = {(d.name, d.key): d for d in score_dists}

        label = None
        if responses and responses[0].name in data:
            label = np.nan_to_num(
                np.asarray(data[responses[0].name].values, np.float64))

        reasons: List[ExclusionReasons] = []
        for d in train_dists:
            r = ExclusionReasons(d.name, d.key, d.fill_rate())
            if d.name not in self.protected_features:
                r.low_fill = d.fill_rate() < self.min_fill_rate
                s = score_by_key.get((d.name, d.key))
                if s is not None and s.count > 0:
                    r.fill_difference = (d.relative_fill_rate(s)
                                         > self.max_fill_difference)
                    r.fill_ratio = (d.relative_fill_ratio(s)
                                    > self.max_fill_ratio_diff)
                    if d.name not in self.js_protected:
                        r.js_divergence = (d.js_divergence(s)
                                           > self.max_js_divergence)
                if label is not None:
                    corr = self._null_label_corr(data, d.name, d.key, label)
                    r.null_label_leakage = abs(corr) > self.max_correlation
            reasons.append(r)

        dropped_features: List[str] = []
        dropped_map_keys: Dict[str, List[str]] = {}
        by_feature: Dict[str, List[ExclusionReasons]] = {}
        for r in reasons:
            by_feature.setdefault(r.name, []).append(r)
        for name, rs in by_feature.items():
            keyed = [r for r in rs if r.key is not None]
            if keyed:
                bad = [r.key for r in keyed if r.excluded]
                if bad:
                    if len(bad) == len(keyed):
                        dropped_features.append(name)
                    else:
                        dropped_map_keys[name] = bad
            elif any(r.excluded for r in rs):
                dropped_features.append(name)

        cleaned = data.copy()
        for name in dropped_features:
            if name in cleaned:
                cleaned = cleaned.drop([name])
        for name, keys in dropped_map_keys.items():
            col = cleaned[name]
            vals = np.empty(len(col.values), dtype=object)
            bad = set(keys)
            for i, row in enumerate(col.values):
                vals[i] = {k: v for k, v in row.items() if k not in bad}
            cleaned.set(name, FeatureColumn(col.ftype, vals))

        results = RawFeatureFilterResults(
            config={
                "minFillRate": self.min_fill_rate,
                "maxFillDifference": self.max_fill_difference,
                "maxFillRatioDiff": self.max_fill_ratio_diff,
                "maxJSDivergence": self.max_js_divergence,
                "maxCorrelation": self.max_correlation,
            },
            train_distributions=train_dists,
            score_distributions=score_dists,
            exclusion_reasons=reasons,
            dropped_features=dropped_features,
            dropped_map_keys=dropped_map_keys,
        )
        return cleaned, results
