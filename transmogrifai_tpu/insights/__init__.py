"""Model + record insights (reference ModelInsights / RecordInsightsLOCO)."""
from .model_insights import (
    ModelInsights, extract_model_insights, feature_importances,
)
from .record_insights import (
    NormType, RecordInsightsCorr, RecordInsightsCorrModel, RecordInsightsLOCO,
    parse_insights,
)

__all__ = ["ModelInsights", "extract_model_insights", "feature_importances",
           "RecordInsightsLOCO", "RecordInsightsCorr",
           "RecordInsightsCorrModel", "NormType", "parse_insights"]
