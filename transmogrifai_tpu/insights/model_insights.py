"""ModelInsights — one merged JSON document describing a trained workflow.

Reference: ``ModelInsights`` (core/.../ModelInsights.scala:74): merges the
label summary, SanityChecker metadata, RawFeatureFilter results, selected-
model validation results and per-feature contributions into one artifact
(``extractFromStages`` :444, ``getFeatureInsights`` :569); ``prettyPrint``
renders the README summary tables (:101).
"""
from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Optional

import numpy as np

__all__ = ["ModelInsights", "extract_model_insights", "feature_importances"]


def feature_importances(stage, d: int) -> Optional[np.ndarray]:
    """Per-slot contribution of a fitted predictor.

    Linear models: |coefficient| per slot (mean over classes for
    multinomial).  Tree ensembles: valid-split counts per feature
    (importance by split frequency).  SelectedModel: recurse into winner.
    """
    from ..models.trees import TreeEnsembleModel
    from ..selector.model_selector import SelectedModel

    if isinstance(stage, SelectedModel):
        return feature_importances(stage.inner, d)
    if isinstance(stage, TreeEnsembleModel):
        feat = np.asarray(stage.feat)          # (T, nodes)
        thresh = np.asarray(stage.thresh)
        n_bins = int(thresh.max()) if thresh.size else 0
        out = np.zeros(d, np.float64)
        valid = thresh < (np.asarray(stage.edges).shape[1] + 1
                          if stage.edges is not None else n_bins)
        np.add.at(out, feat[valid], 1.0)
        s = out.sum()
        return out / s if s else out
    coef = getattr(stage, "coef", None)
    if coef is not None:
        c = np.abs(np.asarray(coef, np.float64))
        if c.ndim == 2:
            c = c.mean(axis=0)
        if c.shape[0] == d:
            return c
    return None


@dataclasses.dataclass
class FeatureInsight:
    feature_name: str
    feature_type: str
    derived_columns: List[Dict[str, Any]]

    def to_json(self):
        return {"featureName": self.feature_name,
                "featureType": self.feature_type,
                "derivedFeatures": self.derived_columns}


@dataclasses.dataclass
class ModelInsights:
    label: Dict[str, Any]
    features: List[FeatureInsight]
    selected_model_info: Optional[Dict[str, Any]]
    training_params: Dict[str, Any]
    stage_info: List[Dict[str, Any]]
    raw_feature_filter_results: Optional[Dict[str, Any]] = None

    def to_json(self) -> dict:
        return {
            "label": self.label,
            "features": [f.to_json() for f in self.features],
            "selectedModelInfo": self.selected_model_info,
            "trainingParams": self.training_params,
            "stageInfo": self.stage_info,
            "rawFeatureFilterResults": self.raw_feature_filter_results,
        }

    def pretty_print(self, top_k: int = 15) -> str:
        """README-style summary tables (ModelInsights.prettyPrint :101)."""
        lines: List[str] = []
        smi = self.selected_model_info
        if smi:
            lines.append("Evaluated %d models:" % len(smi.get(
                "validationResults", [])))
            for r in smi.get("validationResults", [])[:top_k]:
                lines.append(f"  {r['modelType']} {r['params']} -> "
                             f"{r['metricName']}={r['metricValue']:.4f}")
            lines.append(f"Selected model: {smi.get('bestModelType')} "
                         f"{smi.get('bestModelParams')}")
            if smi.get("holdoutMetrics"):
                lines.append("Holdout metrics: "
                             + json.dumps(smi["holdoutMetrics"]))
        contribs = []
        for f in self.features:
            for c in f.derived_columns:
                if c.get("contribution"):
                    contribs.append((c["columnName"], c["contribution"]))
        if contribs:
            contribs.sort(key=lambda t: -t[1])
            lines.append("Top model contributions:")
            for name, v in contribs[:top_k]:
                lines.append(f"  {name}: {v:.4f}")
        return "\n".join(lines) if lines else "(no insights)"


def _label_summary(model) -> Dict[str, Any]:
    resp = next((f for f in model.raw_features() if f.is_response), None)
    out: Dict[str, Any] = {"labelName": resp.name if resp else None}
    if resp and model.train_data is not None and resp.name in model.train_data:
        y = np.asarray(model.train_data[resp.name].values, np.float64)
        y = y[np.isfinite(y)]
        uniq = np.unique(y)
        out["sampleSize"] = int(y.size)
        if uniq.size <= 30:
            out["distribution"] = {str(v): int((y == v).sum()) for v in uniq}
        else:
            out["distribution"] = {
                "mean": float(y.mean()), "std": float(y.std()),
                "min": float(y.min()), "max": float(y.max())}
    return out


def extract_model_insights(model, feature=None) -> ModelInsights:
    """Build insights for a fitted OpWorkflowModel (modelInsights :167)."""
    # locate the prediction stage + sanity summary + vector metadata
    selected = None
    sel_summary = None
    sanity_summary = None
    for s in model.stages:
        if "model_selector_summary" in s.metadata:
            sel_summary = s.metadata["model_selector_summary"]
            selected = s
        elif hasattr(s, "predict_batch") and selected is None:
            selected = s
        if "columnStats" in s.metadata.get("summary", {}):
            sanity_summary = s.metadata["summary"]

    vmeta = None
    d = None
    if selected is not None and len(selected.input_features) >= 2:
        feats_feature = selected.input_features[-1]
        if model.train_data is not None and feats_feature.name in model.train_data:
            col = model.train_data[feats_feature.name]
            vmeta = col.vmeta
            d = int(np.asarray(col.values).shape[1])
        origin = feats_feature.origin_stage
        if vmeta is None and origin is not None:
            vmeta = getattr(origin, "_new_vmeta", None)
    if vmeta is not None and d is None:
        d = vmeta.size

    contributions = (feature_importances(selected, d)
                     if selected is not None and d else None)
    stats_by_col = {}
    if sanity_summary:
        stats_by_col = {s["name"]: s
                        for s in sanity_summary.get("columnStats", [])}

    insights: Dict[str, FeatureInsight] = {}
    for f in model.raw_features():
        if f.is_response:
            continue
        insights[f.name] = FeatureInsight(f.name, f.ftype.type_name(), [])
    if vmeta is not None:
        for j, c in enumerate(vmeta.columns):
            parent = c.parent_feature
            if parent not in insights:
                insights[parent] = FeatureInsight(parent, c.parent_type, [])
            col_name = vmeta.column_names()[j]
            entry: Dict[str, Any] = {
                "columnName": col_name,
                "indicatorValue": c.indicator_value,
                "descriptorValue": c.descriptor_value,
                "contribution": (float(contributions[j])
                                 if contributions is not None
                                 and j < len(contributions) else None),
            }
            st = stats_by_col.get(col_name)
            if st:
                entry.update({k: st.get(k) for k in
                              ("mean", "variance", "min", "max", "corr_label",
                               "cramers_v", "dropped", "reasons")})
            insights[parent].derived_columns.append(entry)

    stage_info = [{"uid": s.uid, "stage": type(s).__name__,
                   "operation": s.operation_name} for s in model.stages]
    rff = model.raw_feature_filter_results
    return ModelInsights(
        label=_label_summary(model),
        features=list(insights.values()),
        selected_model_info=sel_summary,
        training_params={},
        stage_info=stage_info,
        raw_feature_filter_results=(rff.to_json()
                                    if hasattr(rff, "to_json") else rff),
    )
