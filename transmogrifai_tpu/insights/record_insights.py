"""RecordInsightsLOCO — per-row leave-one-column-out explanations.

Reference: ``RecordInsightsLOCO`` (core/.../impl/insights/RecordInsightsLOCO
.scala:100): for each vector slot, zero it and measure the prediction change;
aggregate slots per raw feature via the vector column metadata
(OpVectorColumnHistory, :186-246); keep the top-K positive/negative
(:282).  Parser: ``RecordInsightsParser``.

TPU note: the reference computes LOCO per row inside a row-UDF; here the
whole batch is scored per zeroed slot (one vectorized predict per slot),
which batches naturally on device — SURVEY §7 step 7 ("LOCO is trivially
batched: vmap over zeroed slots").
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..stages.base import BinaryEstimator, BinaryModel, UnaryTransformer
from ..types.columns import ColumnarDataset, FeatureColumn
from ..types.feature_types import OPVector, TextMap

__all__ = ["RecordInsightsLOCO", "RecordInsightsCorr",
           "RecordInsightsCorrModel", "NormType", "parse_insights"]


class RecordInsightsLOCO(UnaryTransformer):
    """Input: the model's feature vector; output: TextMap of per-feature
    insight JSON for each row."""

    def __init__(self, model, top_k: int = 20,
                 aggregate_by_feature: bool = True,
                 uid: Optional[str] = None):
        super().__init__(operation_name="recordInsightsLOCO",
                         output_type=TextMap, uid=uid)
        self.model = model            # fitted PredictorModel
        self.top_k = top_k
        self.aggregate_by_feature = aggregate_by_feature

    def _score(self, X: np.ndarray) -> np.ndarray:
        batch = self.model.predict_batch(X)
        if batch.probability is not None:
            return np.asarray(batch.probability, np.float64)
        return np.asarray(batch.prediction, np.float64)[:, None]

    def transform_columns(self, features_col: FeatureColumn) -> FeatureColumn:
        X = np.asarray(features_col.values, np.float32)
        n, d = X.shape
        vmeta = features_col.vmeta
        base = self._score(X)                     # (N, K)

        # diffs per slot: score with slot j zeroed, minus base
        diffs = np.zeros((d, n, base.shape[1]), np.float64)
        for j in range(d):
            if not np.any(X[:, j]):
                continue
            Xz = X.copy()
            Xz[:, j] = 0.0
            diffs[j] = self._score(Xz) - base

        names = (vmeta.column_names() if vmeta is not None
                 and vmeta.size == d else [f"f_{j}" for j in range(d)])
        parents = ([c.parent_feature for c in vmeta.columns]
                   if vmeta is not None and vmeta.size == d else names)

        out = np.empty(n, dtype=object)
        for i in range(n):
            per: Dict[str, np.ndarray] = {}
            for j in range(d):
                key = parents[j] if self.aggregate_by_feature else names[j]
                per[key] = per.get(key, 0.0) + diffs[j, i]
            scored: List[Tuple[str, List[float]]] = [
                (k, list(np.atleast_1d(v))) for k, v in per.items()]
            scored.sort(key=lambda t: -max(abs(x) for x in t[1]))
            out[i] = {k: json.dumps(v) for k, v in scored[: self.top_k]}
        return FeatureColumn(TextMap, out)


def parse_insights(row_map: Dict[str, str]) -> Dict[str, List[float]]:
    """RecordInsightsParser.parseInsights parity."""
    return {k: json.loads(v) for k, v in row_map.items()}


# ---------------------------------------------------------------------------
# RecordInsightsCorr — correlation-based record insights
# ---------------------------------------------------------------------------

class NormType:
    """Feature scaling applied before computing importances.

    Reference ``NormType`` (core/.../impl/insights/RecordInsightsCorr
    .scala:166-204): minMax (x-min)/range, zNorm (x-mean)/std,
    minMaxCentered 2*(x-min)/range - 1.
    """

    MIN_MAX = "minMax"
    Z_NORM = "zNorm"
    MIN_MAX_CENTERED = "minMaxCentered"


def _pred_matrix(col: FeatureColumn) -> np.ndarray:
    """Prediction input -> (N, P) score matrix.

    Accepts an OPVector column, a PredictionBatch-valued column, or an
    object column of prediction row-maps (probability_* preferred,
    else prediction) — the reference requires callers to pre-convert
    regression outputs to a one-column vector (RecordInsightsCorr.scala:52).
    """
    v = col.values
    if hasattr(v, "probability"):          # PredictionBatch
        if v.probability is not None:
            return np.asarray(v.probability, np.float64)
        return np.asarray(v.prediction, np.float64)[:, None]
    arr = np.asarray(v)
    if arr.dtype == object:                # row maps
        rows = []
        for m in arr:
            pk = sorted((k for k in m if k.startswith("probability_")),
                        key=lambda k: int(k.rsplit("_", 1)[1]))
            rows.append([m[k] for k in pk] if pk else [m["prediction"]])
        return np.asarray(rows, np.float64)
    return arr.astype(np.float64).reshape(len(arr), -1)


class RecordInsightsCorr(BinaryEstimator):
    """Correlation-based per-record insights.

    Reference ``RecordInsightsCorr`` (core/.../impl/insights/
    RecordInsightsCorr.scala:55-121): inputs (predictions, feature vector);
    fit computes the correlation of every feature slot with every prediction
    column plus normalization stats; the model scores a row as
    ``corr[pred, slot] * normalized(x[slot])`` and keeps the top-K slots by
    absolute importance, keyed by vector-metadata column name.

    TPU note: the correlation is one standardized X^T @ P matmul over the
    batch (MXU-friendly) instead of Spark's ``Statistics.corr`` pass.
    """

    def __init__(self, norm_type: str = NormType.MIN_MAX,
                 correlation_type: str = "pearson", top_k: int = 20,
                 uid: Optional[str] = None):
        super().__init__(operation_name="recordInsightsCorr",
                         output_type=TextMap, uid=uid)
        self.norm_type = norm_type
        self.correlation_type = correlation_type
        self.top_k = top_k

    def fit_columns(self, data: ColumnarDataset, pred_col: FeatureColumn,
                    feat_col: FeatureColumn):
        import jax
        import jax.numpy as jnp

        from ..ops.stats import ranks

        P = _pred_matrix(pred_col)                       # (N, p)
        X = np.asarray(feat_col.values, np.float64)      # (N, d)
        if self.correlation_type == "spearman":
            col_ranks = jax.vmap(ranks, in_axes=1, out_axes=1)
            X_c = np.asarray(col_ranks(jnp.asarray(X)), np.float64)
            P_c = np.asarray(col_ranks(jnp.asarray(P)), np.float64)
        else:
            X_c, P_c = X, P
        n = max(len(X), 1)
        Xs = X_c - X_c.mean(axis=0)
        Ps = P_c - P_c.mean(axis=0)
        xsd = Xs.std(axis=0)
        psd = Ps.std(axis=0)
        denom = np.outer(psd, xsd) * n
        with np.errstate(invalid="ignore", divide="ignore"):
            corr = (Ps.T @ Xs) / np.where(denom == 0, np.nan, denom)

        if self.norm_type == NormType.Z_NORM:
            shift, scale, offset = X.mean(axis=0), X.std(axis=0), 0.0
        else:
            if len(X):
                mn, rng = X.min(axis=0), np.ptp(X, axis=0)
            else:
                mn = rng = np.zeros(X.shape[1])
            if self.norm_type == NormType.MIN_MAX_CENTERED:
                shift, scale, offset = mn, rng / 2.0, 1.0
            else:
                shift, scale, offset = mn, rng, 0.0
        return RecordInsightsCorrModel(
            score_corr=np.nan_to_num(corr), shift=shift, scale=scale,
            offset=float(offset), top_k=self.top_k)


class RecordInsightsCorrModel(BinaryModel):
    def __init__(self, score_corr: np.ndarray, shift: np.ndarray,
                 scale: np.ndarray, offset: float = 0.0, top_k: int = 20,
                 uid: Optional[str] = None):
        super().__init__(operation_name="recordInsightsCorr",
                         output_type=TextMap, uid=uid)
        self.score_corr = np.asarray(score_corr, np.float64)
        self.shift = np.asarray(shift, np.float64)
        self.scale = np.asarray(scale, np.float64)
        self.offset = float(offset)
        self.top_k = top_k

    def transform_columns(self, pred_col: FeatureColumn,
                          feat_col: FeatureColumn) -> FeatureColumn:
        X = np.asarray(feat_col.values, np.float64)
        n, d = X.shape
        vmeta = feat_col.vmeta
        names = (vmeta.column_names() if vmeta is not None
                 and vmeta.size == d else [f"f_{j}" for j in range(d)])
        with np.errstate(invalid="ignore", divide="ignore"):
            normed = np.where(self.scale == 0, 0.0,
                              (X - self.shift) / self.scale) - self.offset
        # (N, p, d): per-row importance of each slot for each prediction col
        imp = self.score_corr[None, :, :] * normed[:, None, :]
        out = np.empty(n, dtype=object)
        p = self.score_corr.shape[0]
        # Reference semantics (RecordInsightsCorr.scala:146-154): rank top-K
        # per PREDICTION COLUMN, then merge the per-column maps — a slot's
        # entry lists only the prediction indices where it made that
        # column's top-K, and the merged map holds up to K·P keys.
        kk = min(self.top_k, imp.shape[2])
        # (N, p, K) slot indices of the per-column top-K by |importance|
        # (argpartition: O(d) per column, no full sort of the slot axis)
        order = (np.argpartition(-np.abs(imp), kk - 1, axis=2)[:, :, :kk]
                 if kk < imp.shape[2] else
                 np.broadcast_to(np.arange(kk), imp.shape[:2] + (kk,)))
        for i in range(n):
            entries: dict = {}
            for c in range(p):
                for j in order[i, c]:
                    entries.setdefault(int(j), []).append(
                        [c, float(imp[i, c, j])])
            out[i] = {names[j]: json.dumps(v) for j, v in entries.items()}
        return FeatureColumn(TextMap, out)
