"""RecordInsightsLOCO — per-row leave-one-column-out explanations.

Reference: ``RecordInsightsLOCO`` (core/.../impl/insights/RecordInsightsLOCO
.scala:100): for each vector slot, zero it and measure the prediction change;
aggregate slots per raw feature via the vector column metadata
(OpVectorColumnHistory, :186-246); keep the top-K positive/negative
(:282).  Parser: ``RecordInsightsParser``.

TPU note: the reference computes LOCO per row inside a row-UDF; here the
whole batch is scored per zeroed slot (one vectorized predict per slot),
which batches naturally on device — SURVEY §7 step 7 ("LOCO is trivially
batched: vmap over zeroed slots").
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..stages.base import UnaryTransformer
from ..types.columns import ColumnarDataset, FeatureColumn
from ..types.feature_types import OPVector, TextMap

__all__ = ["RecordInsightsLOCO", "parse_insights"]


class RecordInsightsLOCO(UnaryTransformer):
    """Input: the model's feature vector; output: TextMap of per-feature
    insight JSON for each row."""

    def __init__(self, model, top_k: int = 20,
                 aggregate_by_feature: bool = True,
                 uid: Optional[str] = None):
        super().__init__(operation_name="recordInsightsLOCO",
                         output_type=TextMap, uid=uid)
        self.model = model            # fitted PredictorModel
        self.top_k = top_k
        self.aggregate_by_feature = aggregate_by_feature

    def _score(self, X: np.ndarray) -> np.ndarray:
        batch = self.model.predict_batch(X)
        if batch.probability is not None:
            return np.asarray(batch.probability, np.float64)
        return np.asarray(batch.prediction, np.float64)[:, None]

    def transform_columns(self, features_col: FeatureColumn) -> FeatureColumn:
        X = np.asarray(features_col.values, np.float32)
        n, d = X.shape
        vmeta = features_col.vmeta
        base = self._score(X)                     # (N, K)

        # diffs per slot: score with slot j zeroed, minus base
        diffs = np.zeros((d, n, base.shape[1]), np.float64)
        for j in range(d):
            if not np.any(X[:, j]):
                continue
            Xz = X.copy()
            Xz[:, j] = 0.0
            diffs[j] = self._score(Xz) - base

        names = (vmeta.column_names() if vmeta is not None
                 and vmeta.size == d else [f"f_{j}" for j in range(d)])
        parents = ([c.parent_feature for c in vmeta.columns]
                   if vmeta is not None and vmeta.size == d else names)

        out = np.empty(n, dtype=object)
        for i in range(n):
            per: Dict[str, np.ndarray] = {}
            for j in range(d):
                key = parents[j] if self.aggregate_by_feature else names[j]
                per[key] = per.get(key, 0.0) + diffs[j, i]
            scored: List[Tuple[str, List[float]]] = [
                (k, list(np.atleast_1d(v))) for k, v in per.items()]
            scored.sort(key=lambda t: -max(abs(x) for x in t[1]))
            out[i] = {k: json.dumps(v) for k, v in scored[: self.top_k]}
        return FeatureColumn(TextMap, out)


def parse_insights(row_map: Dict[str, str]) -> Dict[str, List[float]]:
    """RecordInsightsParser.parseInsights parity."""
    return {k: json.loads(v) for k, v in row_map.items()}
