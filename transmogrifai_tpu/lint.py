"""``python -m transmogrifai_tpu.lint`` — pipeline static analyzer entry.

Thin shim over :mod:`transmogrifai_tpu.analysis.cli`; also reachable as the
``lint`` subcommand of the package CLI (``tmog lint``).
"""
import sys

from .analysis.cli import main  # noqa: F401  re-exported for embedding

if __name__ == "__main__":
    sys.exit(main())
