"""Spark-free local scoring (reference local/ module, SURVEY §2.15)."""
from .scorer import load_model_local, score_function, score_function_batch

__all__ = ["score_function", "score_function_batch", "load_model_local"]
