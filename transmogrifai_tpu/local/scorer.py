"""Spark-free ("local") scoring of a trained workflow model.

Reference: ``OpWorkflowModelLocal.scoreFunction`` — load the persisted model
once, then score plain ``Map[String, Any]`` rows with no cluster runtime at
all (local/OpWorkflowModelLocal.scala:43-120, loaded via
``OpWorkflowModel.load(path, asSpark=false)`` OpWorkflowModel.scala:470).
The reference needs a second execution path (MLeap + row-level
``transformKeyValue``); here the columnar stages simply run on a batch of
one (or a micro-batch) — same code path as training, no drift risk, and no
device requirement (numpy on host; JAX CPU backend for the model kernels).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

from ..models.prediction import PredictionBatch
from ..stages.generator import FeatureGeneratorStage
from ..types.columns import ColumnarDataset, FeatureColumn
from ..workflow.dag import transform_dag

__all__ = ["score_function", "score_function_batch", "load_model_local"]


def score_function(model) -> Callable[[Dict[str, Any]], Dict[str, Any]]:
    """Build ``row_map -> score_map`` from a fitted/loaded workflow model.

    The returned function accepts one record (dict of raw feature values)
    and returns ``{result_feature_name: value}`` with ``Prediction`` values
    expanded to the reference's reserved-key map
    (prediction / probability_i / rawPrediction_i — Maps.scala:339-394).
    """
    batch = score_function_batch(model)

    def score_one(row: Dict[str, Any]) -> Dict[str, Any]:
        return batch([row])[0]

    return score_one


def score_function_batch(model) -> Callable[[Sequence[Dict[str, Any]]],
                                            List[Dict[str, Any]]]:
    """Micro-batch variant: list of records in, list of score maps out."""
    dag = model._scoring_dag()
    raw_feats = model.raw_features()
    result_names = [f.name for f in model.result_features]

    def score_batch(rows: Sequence[Dict[str, Any]]) -> List[Dict[str, Any]]:
        rows = list(rows)
        if not rows:
            # nothing to score: skip dataset construction entirely (stages
            # may assume non-empty batches) and honor the list-in/list-out
            # contract
            return []
        for i, r in enumerate(rows):
            if not isinstance(r, dict):
                raise TypeError(
                    f"score_function_batch expects dict rows "
                    f"(raw feature name -> value); row {i} is "
                    f"{type(r).__name__!r}")
        data = ColumnarDataset()
        for f in raw_feats:
            stage = f.origin_stage
            if isinstance(stage, FeatureGeneratorStage) and not f.is_response:
                data.set(f.name, stage.extract_column(rows))
            elif isinstance(stage, FeatureGeneratorStage):
                # response may be absent at scoring time
                vals = [r.get(f.name) if isinstance(r, dict) else None
                        for r in rows]
                data.set(f.name, FeatureColumn.from_values(f.ftype, vals))
        # keep only the result columns alive: the memoized plan prunes every
        # intermediate as soon as its last consumer stage has run (serving
        # micro-batches score thousands of times per model, so the pruned
        # plan is derived once and shared)
        scored = transform_dag(dag, data, keep=result_names)
        out: List[Dict[str, Any]] = [dict() for _ in rows]
        for name in result_names:
            if name not in scored:
                continue
            col = scored[name]
            if isinstance(col.values, PredictionBatch):
                for i in range(len(rows)):
                    out[i][name] = col.values.row(i)
            else:
                vals = col.to_list()
                for i in range(len(rows)):
                    out[i][name] = vals[i]
        return out

    return score_batch


def load_model_local(path: str):
    """Load a saved model for host-only scoring (load(path, asSpark=false))."""
    from ..workflow.persistence import load_workflow_model

    return load_workflow_model(path)
