from .classification import (  # noqa: F401
    OpLogisticRegression, OpLinearSVC, OpNaiveBayes,
)
from .mlp import (  # noqa: F401
    OpMultilayerPerceptronClassifier, MLPClassificationModel,
)
from .regression import (  # noqa: F401
    OpLinearRegression, OpGeneralizedLinearRegression,
    IsotonicRegressionCalibrator,
)
from .trees import (  # noqa: F401
    OpRandomForestClassifier, OpRandomForestRegressor,
    OpGBTClassifier, OpGBTRegressor,
    OpDecisionTreeClassifier, OpDecisionTreeRegressor,
    OpXGBoostClassifier, OpXGBoostRegressor,
)
from .prediction import PredictionBatch, PredictorEstimator, PredictorModel  # noqa: F401
