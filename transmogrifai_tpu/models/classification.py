"""Classification model stages (XLA-trained).

Reference wrappers (core/.../impl/classification/): OpLogisticRegression
(OpLogisticRegression.scala:46), OpLinearSVC (:47), OpNaiveBayes (:46),
OpMultilayerPerceptronClassifier (:48).  Tree/boosted models live in
``models.trees``.

Each estimator takes (label RealNN, features OPVector) and yields a fitted
``PredictorModel`` producing a ``Prediction`` column — same contract as the
reference's OpPredictorWrapper pipeline.
"""
from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..types.columns import ColumnarDataset, FeatureColumn
from .linear import (
    fit_linear_svc, fit_logistic_regression, fit_multinomial_logreg,
    fit_naive_bayes, logreg_predict_proba, naive_bayes_predict_log_proba,
    softmax_predict_proba, svc_decision,
)
from .prediction import PredictionBatch, PredictorEstimator, PredictorModel

__all__ = [
    "OpLogisticRegression", "LogisticRegressionModel",
    "OpLinearSVC", "LinearSVCModel",
    "OpNaiveBayes", "NaiveBayesModel",
]


def _extract_xy(label_col: FeatureColumn, features_col: FeatureColumn):
    X = np.asarray(features_col.values, dtype=np.float32)
    y = np.asarray(label_col.values, dtype=np.float32)
    return X, np.nan_to_num(y)


@jax.jit
def _device_sigmoid_score(X, coef, intercept):
    return jax.nn.sigmoid(X @ coef + intercept)


@jax.jit
def _device_standardize(X, mu, sigma):
    return (X - mu) / sigma


@jax.jit
def _device_standardize_stats(X, w=None):
    """Weighted column mean/std on device, matching ``_standardize_stats``
    (sigma floored to 1.0 below 1e-12)."""
    if w is None:
        mu = X.mean(axis=0)
        sigma = X.std(axis=0)
    else:
        ws = jnp.maximum(w.sum(), 1e-12)
        mu = (w[:, None] * X).sum(axis=0) / ws
        sigma = jnp.sqrt((w[:, None] * (X - mu) ** 2).sum(axis=0) / ws)
    return mu, jnp.where(sigma < 1e-12, 1.0, sigma)


@jax.jit
def _device_std_sigmoid_score(X, mu, sigma, coef, intercept):
    return jax.nn.sigmoid(((X - mu) / sigma) @ coef + intercept)


# -- AOT-exportable scoring programs (serving/aot.py) ------------------------
# Pure jax functions of (X, *params) with static shapes: the serving plane
# lowers one executable per (model digest, shape bucket) and persists it in
# the AOT store, so a fresh replica cold-starts without tracing or
# compiling.  Everything stays float32 regardless of the x64 flag so the
# same program (and the same persisted executable) serves tests and prod.

def _aot_logreg_binary(X, coef, intercept):
    z = X @ coef + intercept
    p1 = jax.nn.sigmoid(z)
    raw = jnp.stack([-z, z], axis=1)
    proba = jnp.stack([jnp.float32(1.0) - p1, p1], axis=1)
    pred = (p1 >= jnp.float32(0.5)).astype(jnp.float32)
    return pred, raw, proba


def _aot_softmax(X, coef, intercept):
    Z = X @ coef.T + intercept
    e = jnp.exp(Z - Z.max(axis=1, keepdims=True))
    proba = e / e.sum(axis=1, keepdims=True)
    pred = proba.argmax(axis=1).astype(jnp.float32)
    return pred, Z, proba


def _aot_svc(X, coef, intercept):
    z = X @ coef + intercept
    raw = jnp.stack([-z, z], axis=1)
    pred = (z >= jnp.float32(0.0)).astype(jnp.float32)
    return pred, raw


def _aot_naive_bayes(X, log_prior, log_lik):
    Xc = jnp.maximum(X, jnp.float32(0.0))
    joint = Xc @ log_lik.T + log_prior
    m = joint.max(axis=1, keepdims=True)
    logp = joint - (m + jnp.log(
        jnp.exp(joint - m).sum(axis=1, keepdims=True)))
    proba = jnp.exp(logp)
    pred = proba.argmax(axis=1).astype(jnp.float32)
    return pred, logp, proba


class OpLogisticRegression(PredictorEstimator):
    """L2/elastic-net logistic regression trained by jitted Newton-IRLS.

    Param names follow Spark's (regParam, elasticNetParam, maxIter, tol,
    fitIntercept) so default grids transfer verbatim
    (DefaultSelectorParams.scala:36-75).
    """

    def __init__(self, reg_param: float = 0.0, elastic_net_param: float = 0.0,
                 max_iter: int = 50, tol: float = 1e-6,
                 fit_intercept: bool = True, standardization: bool = True,
                 sample_weight_col: Optional[str] = None,
                 uid: Optional[str] = None):
        super().__init__(operation_name="logreg", uid=uid)
        self.reg_param = reg_param
        self.elastic_net_param = elastic_net_param
        self.max_iter = max_iter
        self.tol = tol
        self.fit_intercept = fit_intercept
        self.standardization = standardization
        self.sample_weight_col = sample_weight_col
        self.mesh = None

    def with_mesh(self, mesh) -> "OpLogisticRegression":
        """Multi-chip fit: rows shard over the mesh's data axis and GSPMD
        psums the per-iteration IRLS Gram products over ICI
        (parallel/sharded.fit_logreg_sharded).  Binary only — the
        multinomial path stays single-device."""
        self.mesh = mesh
        return self

    def fit_columns(self, data: ColumnarDataset, label_col, features_col):
        X, y = _extract_xy(label_col, features_col)
        w = None
        if self.sample_weight_col and self.sample_weight_col in data:
            w = np.asarray(data[self.sample_weight_col].values, np.float32)
        return self.fit_raw(X, y, w)

    def fit_device(self, X, y, w, problem_type: str):
        """Sweep path: Newton-IRLS fit and sigmoid scores stay on device
        (binary only) — no coefficient fetch per candidate, and the feature
        matrix uploads ONCE (content-memoized); per-fold standardization is
        a device elementwise op, not a fresh host matrix + upload."""
        if problem_type != "binary" or (len(y) and np.nanmax(y) > 1):
            return None
        from .trees import _dev_f32

        fit, mu, sigma = self._fit_binary_on_device(X, y, w)

        def score(Xe):
            Xe_dev = _dev_f32(Xe)
            if mu is None:
                return _device_sigmoid_score(Xe_dev, fit.coef, fit.intercept)
            return _device_std_sigmoid_score(
                Xe_dev, mu, sigma, fit.coef, fit.intercept)
        return score

    #: past this element count the refit standardizes + fits on device from
    #: the (memoized) uploaded matrix — host mean/std/copy passes over a
    #: multi-GB matrix cost tens of seconds on a 1-core host
    _DEVICE_FIT_ELEMS = 1 << 24

    def _fit_binary_on_device(self, X, y, w):
        """Memoized upload + device standardization stats + IRLS fit —
        the ONE binary device-fit path shared by the CV sweep
        (``fit_device``) and the big-matrix refit, so the two cannot
        diverge.  Stats on DEVICE: a host mean/std pass over a 2 GB matrix
        costs ~17 s per candidate on a 1-core host; on device it is two
        fused reductions over the already-resident matrix."""
        from .trees import _dev_f32

        X_dev = _dev_f32(X)
        if self.standardization:
            mu, sigma = _device_standardize_stats(
                X_dev, None if w is None else jnp.asarray(w, jnp.float32))
            Xs = _device_standardize(X_dev, mu, sigma)
        else:
            mu = sigma = None
            Xs = X_dev
        fit = fit_logistic_regression(
            Xs, y, sample_weight=w, reg_param=self.reg_param,
            elastic_net_param=self.elastic_net_param,
            max_iter=self.max_iter, tol=self.tol,
            fit_intercept=self.fit_intercept)
        return fit, mu, sigma

    def fit_raw(self, X: np.ndarray, y: np.ndarray,
                w: Optional[np.ndarray] = None):
        classes = np.unique(y[~np.isnan(y)]).astype(int)
        n_classes = max(int(classes.max()) + 1 if len(classes) else 2, 2)
        if (n_classes <= 2 and self.mesh is None
                and np.size(X) > self._DEVICE_FIT_ELEMS):
            fit, mu_d, sigma_d = self._fit_binary_on_device(X, y, w)
            mu = None if mu_d is None else np.asarray(mu_d)
            sigma = None if sigma_d is None else np.asarray(sigma_d)
            coef, intercept = _unstandardize(
                np.asarray(fit.coef), float(np.asarray(fit.intercept)),
                mu, sigma)
            return LogisticRegressionModel(
                coef=coef.tolist(), intercept=float(intercept))
        mu, sigma = _standardize_stats(X, w) if self.standardization else (None, None)
        Xs = _apply_standardize(X, mu, sigma)
        if n_classes <= 2:
            if self.mesh is not None:
                from ..parallel.sharded import fit_logreg_sharded

                fit = fit_logreg_sharded(
                    np.asarray(Xs, np.float32), y, self.mesh, w,
                    reg_param=self.reg_param,
                    elastic_net_param=self.elastic_net_param,
                    max_iter=self.max_iter, tol=self.tol,
                    fit_intercept=self.fit_intercept)
            else:
                fit = fit_logistic_regression(
                    Xs, y, sample_weight=w, reg_param=self.reg_param,
                    elastic_net_param=self.elastic_net_param,
                    max_iter=self.max_iter, tol=self.tol,
                    fit_intercept=self.fit_intercept)
            coef, intercept = _unstandardize(
                np.asarray(fit.coef), float(np.asarray(fit.intercept)), mu, sigma)
            return LogisticRegressionModel(
                coef=coef.tolist(), intercept=float(intercept))
        fit = fit_multinomial_logreg(
            Xs, y.astype(np.int32), n_classes=n_classes, sample_weight=w,
            reg_param=self.reg_param, elastic_net_param=self.elastic_net_param,
            max_iter=self.max_iter, tol=self.tol,
            fit_intercept=self.fit_intercept)
        coefs, intercepts = [], []
        for k in range(n_classes):
            c, i = _unstandardize(np.asarray(fit.coef)[k],
                                  float(np.asarray(fit.intercept)[k]), mu, sigma)
            coefs.append(c.tolist())
            intercepts.append(float(i))
        return LogisticRegressionModel(coef=coefs, intercept=intercepts)


def _standardize_stats(X, w):
    if w is None:
        mu = X.mean(axis=0)
        sigma = X.std(axis=0)
    else:
        ws = max(w.sum(), 1e-12)
        mu = (w[:, None] * X).sum(axis=0) / ws
        sigma = np.sqrt((w[:, None] * (X - mu) ** 2).sum(axis=0) / ws)
    sigma = np.where(sigma < 1e-12, 1.0, sigma)
    return mu.astype(np.float32), sigma.astype(np.float32)


def _apply_standardize(X, mu, sigma):
    if mu is None:
        return X
    return (X - mu) / sigma


def _unstandardize(coef, intercept, mu, sigma):
    """Map standardized-space coefficients back to raw feature space."""
    if mu is None:
        return coef, intercept
    raw = coef / sigma
    return raw, intercept - float(np.dot(raw, mu))


class LogisticRegressionModel(PredictorModel):
    """Binary: coef (D,); multinomial: coef (K, D) + intercept list."""

    def __init__(self, coef, intercept, uid: Optional[str] = None):
        super().__init__(operation_name="logreg", uid=uid)
        self.coef = coef
        self.intercept = intercept

    def predict_batch(self, X: np.ndarray) -> PredictionBatch:
        from .. import native
        coef = np.asarray(self.coef, np.float32)
        if isinstance(X, np.ndarray):
            # host path: a dot + sigmoid is host-BLAS territory — shipping a
            # 1M-row matrix to the device just to predict costs ~70 s of
            # tunnel upload (device scoring is for device-resident inputs)
            if coef.ndim == 1:
                if native.AVAILABLE and len(X) <= 4096:
                    beta = np.append(coef, np.float32(self.intercept))
                    z = native.linear_margin(np.asarray(X, np.float32), beta)
                else:
                    z = np.asarray(X, np.float32) @ coef + np.float32(
                        self.intercept)
                with np.errstate(over="ignore"):
                    p1 = 1.0 / (1.0 + np.exp(-z))
                proba = np.stack([1.0 - p1, p1], axis=1)
                return PredictionBatch(
                    prediction=(p1 >= 0.5).astype(np.float64),
                    raw_prediction=np.stack([-z, z], axis=1),
                    probability=proba)
            Z = (np.asarray(X, np.float32) @ coef.T
                 + np.asarray(self.intercept, np.float32))
            e = np.exp(Z - Z.max(axis=1, keepdims=True))
            proba = e / e.sum(axis=1, keepdims=True)
            return PredictionBatch(
                prediction=proba.argmax(axis=1).astype(np.float64),
                raw_prediction=Z, probability=proba)
        if coef.ndim == 1:
            proba, raw = logreg_predict_proba(
                jnp.asarray(coef), jnp.float32(self.intercept), X)
            proba = np.asarray(proba)
            return PredictionBatch(
                prediction=(proba[:, 1] >= 0.5).astype(np.float64),
                raw_prediction=np.asarray(raw),
                probability=proba)
        proba, raw = softmax_predict_proba(
            jnp.asarray(coef), jnp.asarray(self.intercept, jnp.float32), X)
        proba = np.asarray(proba)
        return PredictionBatch(
            prediction=proba.argmax(axis=1).astype(np.float64),
            raw_prediction=np.asarray(raw),
            probability=proba)

    def aot_scoring_spec(self):
        from .prediction import AOTScoringSpec
        coef = np.asarray(self.coef, np.float32)
        if coef.ndim == 1:
            return AOTScoringSpec(
                name="logreg.binary", fn=_aot_logreg_binary,
                params=(coef, np.float32(self.intercept)),
                outputs=("prediction", "rawPrediction", "probability"),
                n_features=int(coef.shape[-1]))
        return AOTScoringSpec(
            name="logreg.softmax", fn=_aot_softmax,
            params=(coef, np.asarray(self.intercept, np.float32)),
            outputs=("prediction", "rawPrediction", "probability"),
            n_features=int(coef.shape[-1]))


class OpLinearSVC(PredictorEstimator):
    """Squared-hinge linear SVM via jitted Newton (OpLinearSVC parity)."""

    def __init__(self, reg_param: float = 1e-4, max_iter: int = 100,
                 tol: float = 1e-6, fit_intercept: bool = True,
                 standardization: bool = True, uid: Optional[str] = None):
        super().__init__(operation_name="linsvc", uid=uid)
        self.reg_param = reg_param
        self.max_iter = max_iter
        self.tol = tol
        self.fit_intercept = fit_intercept
        self.standardization = standardization

    def fit_columns(self, data: ColumnarDataset, label_col, features_col):
        X, y = _extract_xy(label_col, features_col)
        return self.fit_raw(X, y)

    def fit_raw(self, X: np.ndarray, y: np.ndarray,
                w: Optional[np.ndarray] = None):
        mu, sigma = _standardize_stats(X, w) if self.standardization else (None, None)
        fit = fit_linear_svc(
            _apply_standardize(X, mu, sigma), y, sample_weight=w,
            reg_param=self.reg_param,
            max_iter=self.max_iter, tol=self.tol,
            fit_intercept=self.fit_intercept)
        coef, intercept = _unstandardize(
            np.asarray(fit.coef), float(np.asarray(fit.intercept)), mu, sigma)
        return LinearSVCModel(coef=coef.tolist(), intercept=float(intercept))


class LinearSVCModel(PredictorModel):
    def __init__(self, coef: List[float], intercept: float,
                 uid: Optional[str] = None):
        super().__init__(operation_name="linsvc", uid=uid)
        self.coef = coef
        self.intercept = intercept

    def predict_batch(self, X: np.ndarray) -> PredictionBatch:
        from .. import native
        if native.AVAILABLE and len(X) <= 4096:
            beta = np.append(np.asarray(self.coef, np.float32),
                             np.float32(self.intercept))
            z = native.linear_margin(np.asarray(X, np.float32), beta)
        else:
            z = np.asarray(svc_decision(jnp.asarray(self.coef, jnp.float32),
                                        jnp.float32(self.intercept), X))
        raw = np.stack([-z, z], axis=1)
        return PredictionBatch(prediction=(z >= 0).astype(np.float64),
                               raw_prediction=raw)

    def aot_scoring_spec(self):
        from .prediction import AOTScoringSpec
        coef = np.asarray(self.coef, np.float32)
        return AOTScoringSpec(
            name="linsvc", fn=_aot_svc,
            params=(coef, np.float32(self.intercept)),
            outputs=("prediction", "rawPrediction"),
            n_features=int(coef.shape[-1]))


class OpNaiveBayes(PredictorEstimator):
    """Multinomial naive Bayes (OpNaiveBayes parity, smoothing=1.0)."""

    def __init__(self, smoothing: float = 1.0, uid: Optional[str] = None):
        super().__init__(operation_name="naivebayes", uid=uid)
        self.smoothing = smoothing

    def fit_columns(self, data: ColumnarDataset, label_col, features_col):
        X, y = _extract_xy(label_col, features_col)
        return self.fit_raw(X, y)

    def fit_raw(self, X: np.ndarray, y: np.ndarray,
                w: Optional[np.ndarray] = None):
        classes = np.unique(y)
        n_classes = max(int(classes.max()) + 1 if len(classes) else 2, 2)
        log_prior, log_lik = fit_naive_bayes(
            X, y.astype(np.int32), n_classes=n_classes, sample_weight=w,
            smoothing=self.smoothing)
        return NaiveBayesModel(log_prior=np.asarray(log_prior).tolist(),
                               log_lik=np.asarray(log_lik).tolist())

    # -- streaming fit: per-class (count, feature-sum) is a plain monoid ----
    # Multinomial NB's sufficient statistics are exactly class counts and
    # per-class feature sums — the fit streams whole, so a chunked train
    # never materializes the feature matrix for this model (tolerance vs
    # in-core: chunked float64 sums vs the device's float32 one-hot matmul,
    # ~1e-5 on the log-likelihoods).

    supports_streaming_fit = True

    def begin_fit(self):
        return {}  # class value -> [count, feat_sum (D,) float64]

    def update_chunk(self, state, data, label_col, features_col):
        X, y = _extract_xy(label_col, features_col)
        Xc = np.maximum(X, 0.0)  # fit_naive_bayes clips negatives
        for uv in np.unique(y):
            mask = (y == uv)
            # one sgemv per class instead of a row gather: indicator sums
            # stay exact in float32 below 2^24 rows, real-valued slots land
            # within the documented 1e-4 log-likelihood tolerance
            sums = (mask.astype(np.float32) @ Xc).astype(np.float64)
            cnt = int(mask.sum())
            ent = state.get(float(uv))
            if ent is None:
                state[float(uv)] = [cnt, sums]
            else:
                ent[0] += cnt
                ent[1] = ent[1] + sums
        return state

    def merge_states(self, a, b):
        for k, (cnt, sums) in b.items():
            ent = a.get(k)
            if ent is None:
                a[k] = [cnt, sums]
            else:
                ent[0] += cnt
                ent[1] = ent[1] + sums
        return a

    def finish_fit(self, state):
        if not state:
            raise ValueError("NaiveBayes streaming fit saw no rows")
        n_classes = max(int(max(state)) + 1, 2)
        d = len(next(iter(state.values()))[1])
        class_count = np.zeros(n_classes, np.float64)
        feat_count = np.zeros((n_classes, d), np.float64)
        for k, (cnt, sums) in state.items():
            class_count[int(k)] = cnt
            feat_count[int(k)] = sums
        log_prior = (np.log(class_count + 1e-12)
                     - np.log(max(class_count.sum(), 1e-12)))
        log_lik = (np.log(feat_count + self.smoothing)
                   - np.log(feat_count.sum(axis=1, keepdims=True)
                            + self.smoothing * d))
        return NaiveBayesModel(
            log_prior=np.asarray(log_prior, np.float32).tolist(),
            log_lik=np.asarray(log_lik, np.float32).tolist())


class NaiveBayesModel(PredictorModel):
    def __init__(self, log_prior, log_lik, uid: Optional[str] = None):
        super().__init__(operation_name="naivebayes", uid=uid)
        self.log_prior = log_prior
        self.log_lik = log_lik

    def predict_batch(self, X: np.ndarray) -> PredictionBatch:
        # host numpy: the predict is one slim GEMV-like product and the
        # eager jnp op chain ratcheted the CPU client's buffer pool by
        # ~5 MB per call — block-wise scoring (serving, the out-of-core
        # assemble) paid that as a permanent RSS high-water.  Same
        # max-shifted logsumexp as jax.scipy's.
        lp = np.asarray(self.log_prior, np.float32)
        ll = np.asarray(self.log_lik, np.float32)
        Xc = np.maximum(np.asarray(X, np.float32), 0.0)
        joint = Xc @ ll.T + lp                       # (N, K)
        m = joint.max(axis=1, keepdims=True)
        logp = joint - (m + np.log(
            np.exp(joint - m).sum(axis=1, keepdims=True)))
        proba = np.exp(logp)
        return PredictionBatch(prediction=proba.argmax(axis=1).astype(np.float64),
                               raw_prediction=logp, probability=proba)

    def aot_scoring_spec(self):
        from .prediction import AOTScoringSpec
        log_lik = np.asarray(self.log_lik, np.float32)
        return AOTScoringSpec(
            name="naivebayes", fn=_aot_naive_bayes,
            params=(np.asarray(self.log_prior, np.float32), log_lik),
            outputs=("prediction", "rawPrediction", "probability"),
            n_features=int(log_lik.shape[-1]))
