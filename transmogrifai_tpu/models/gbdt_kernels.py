"""Histogram decision-tree kernels in pure JAX — the TPU replacement for
XGBoost's C++ histogram GBDT core.

Reference dependency being replaced: xgboost4j JNI (SURVEY §2.11 — the one
genuinely native component of the reference; wrappers
OpXGBoostClassifier.scala:47 / OpXGBoostRegressor.scala:48) and Spark MLlib's
RandomForest/GBT (OpRandomForestClassifier.scala:58, OpGBTClassifier.scala:46).

Design (gpu_hist-style, adapted to XLA):
 * features pre-quantized to ``max_bins`` integer bins (quantile sketch on a
   sample, host-side; binned matrix lives in HBM as int8/int32)
 * trees grow level-wise; every level is one jitted kernel:
     - histogram: scatter-add of [grad(K), hess(K), count] into
       (nodes, D, B, 2K+1) via one flattened ``.at[].add`` — XLA lowers this
       to an efficient sort/segment pattern on TPU
     - split search: cumulative sums over bins -> best (feature, bin) per
       node by the standard gain formula  GL²/(HL+λ) + GR²/(HR+λ) − G²/(H+λ)
     - partition: rows move to ``2*node + go_right`` (no data movement — just
       an int vector update)
 * the tree is a *full* binary tree of ``max_depth`` levels in heap layout;
   nodes that fail min-gain/min-weight constraints emit an "always left"
   split (threshold = B), which keeps every shape static — no ragged trees,
   no recompilation across rounds/trees (SURVEY §7 hard part a).
 * multi-output targets (K>1) support multiclass GBDT (softmax, K trees'
   worth of leaf values per round in one pass) and RF classification
   (leaf = class histogram; variance gain over one-hot targets ≡ Gini gain).
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

__all__ = ["TreeEnsemble", "quantile_bins", "apply_bins", "grow_tree",
           "predict_tree", "predict_ensemble"]


class TreeEnsemble(NamedTuple):
    """Stacked trees: feat (T, 2^d-1) int32, thresh (T, 2^d-1) int32,
    leaf (T, 2^d, K) float32.  Heap layout: node i children 2i+1, 2i+2."""
    feat: jnp.ndarray
    thresh: jnp.ndarray
    leaf: jnp.ndarray

    @property
    def max_depth(self) -> int:
        # feat has 2^d - 1 internal nodes
        return int(np.log2(self.feat.shape[1] + 1))


# ---------------------------------------------------------------------------
# Quantile binning
# ---------------------------------------------------------------------------

def quantile_bins(X: np.ndarray, max_bins: int = 32,
                  sample_rows: int = 200_000, seed: int = 7) -> np.ndarray:
    """Per-feature quantile bin edges, shape (D, max_bins-1).

    Host-side on a row sample (the analogue of XGBoost's sketch); edges are
    deduplicated so constant/low-cardinality features waste no bins.
    """
    X = np.asarray(X)
    n, d = X.shape
    if n > sample_rows:
        rng = np.random.default_rng(seed)
        X = X[rng.choice(n, sample_rows, replace=False)]
    qs = np.linspace(0, 1, max_bins + 1)[1:-1]
    edges = np.quantile(X, qs, axis=0).T.astype(np.float32)  # (D, B-1)
    # strictly increasing edges; collapse duplicates to +inf (unused bins)
    eps = 1e-7
    for j in range(d):
        e = edges[j]
        dup = np.concatenate([[False], np.diff(e) <= eps])
        edges[j] = np.where(dup, np.inf, e)
    return edges


@jax.jit
def apply_bins(X: jnp.ndarray, edges: jnp.ndarray) -> jnp.ndarray:
    """Quantized matrix (N, D) int32 in [0, B)."""
    X = jnp.asarray(X, jnp.float32)
    # count of edges <= x  (edges padded with +inf never trigger)
    return jnp.sum(X[:, :, None] > edges[None, :, :], axis=2).astype(jnp.int32)


# ---------------------------------------------------------------------------
# Level kernel
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("n_nodes", "n_bins"))
def _level_kernel(binned, node, G, H, C, feat_mask, n_nodes: int,
                  n_bins: int, lam, min_child_weight, min_info_gain,
                  min_instances):
    """One level of growth for all ``n_nodes`` nodes simultaneously.

    Returns (feat (M,), thresh (M,), new node assignment (N,)).
    G,H: (N, K) grad/hess channels; C: (N,) count weights.
    """
    n, d = binned.shape
    k = G.shape[1]
    nch = 2 * k + 1
    M = n_nodes
    B = n_bins

    # --- histogram: one scatter-add over (M*D*B) cells x channels ----------
    chans = jnp.concatenate([G, H, C[:, None]], axis=1)  # (N, 2K+1)
    flat_idx = (node[:, None] * (d * B)
                + jnp.arange(d)[None, :] * B
                + binned)                                  # (N, D)
    hist = jnp.zeros((M * d * B, nch), jnp.float32)
    # updates broadcast (N,1,nch) -> (N,D,nch); XLA fuses the broadcast into
    # the scatter so the (N*D) expansion is never materialized in HBM
    hist = hist.at[flat_idx].add(chans[:, None, :])
    hist = hist.reshape(M, d, B, nch)

    Gh = hist[..., :k]           # (M, D, B, K)
    Hh = hist[..., k:2 * k]
    Ch = hist[..., 2 * k]        # (M, D, B)

    GL = jnp.cumsum(Gh, axis=2)  # left sums for split at bin b (x <= b)
    HL = jnp.cumsum(Hh, axis=2)
    CL = jnp.cumsum(Ch, axis=2)
    Gtot = GL[:, :1, -1:, :]     # totals are same for every feature; take f0
    Htot = HL[:, :1, -1:, :]
    Ctot = CL[:, :1, -1:]
    GR = Gtot - GL
    HR = Htot - HL
    CR = Ctot - CL

    def score(Gs, Hs):
        return jnp.sum(Gs ** 2 / (Hs + lam), axis=-1)  # sum over K

    gain = score(GL, HL) + score(GR, HR) - score(Gtot, Htot)  # (M, D, B)
    hl_min = jnp.min(HL, axis=-1)
    hr_min = jnp.min(HR, axis=-1)
    valid = ((hl_min >= min_child_weight) & (hr_min >= min_child_weight)
             & (CL >= min_instances) & (CR >= min_instances))
    # last bin = degenerate split (everything left)
    valid = valid & (jnp.arange(B)[None, None, :] < B - 1)
    valid = valid & feat_mask[None, :, None]
    # normalized gain threshold (minInfoGain semantics: impurity decrease
    # per unit of node weight)
    node_w = jnp.maximum(Ctot[..., 0], 1e-12)  # (M, 1)
    gain = jnp.where(valid, gain, -jnp.inf)

    flat_gain = gain.reshape(M, d * B)
    best = jnp.argmax(flat_gain, axis=1)                  # (M,)
    best_gain = jnp.take_along_axis(flat_gain, best[:, None], 1)[:, 0]
    ok = (best_gain > 0) & (best_gain / node_w[:, 0] >= min_info_gain) & \
         jnp.isfinite(best_gain)
    feat = jnp.where(ok, best // B, 0).astype(jnp.int32)
    thresh = jnp.where(ok, best % B, B).astype(jnp.int32)  # B => always left

    # --- partition rows ----------------------------------------------------
    f_row = feat[node]                                     # (N,)
    t_row = thresh[node]
    x_row = jnp.take_along_axis(binned, f_row[:, None], 1)[:, 0]
    go_right = (x_row > t_row).astype(jnp.int32)
    new_node = 2 * node + go_right
    return feat, thresh, new_node


@functools.partial(jax.jit, static_argnames=("n_leaves",))
def _leaf_kernel(node, G, H, C, n_leaves: int, lam, newton, lr):
    """Leaf values for the final level: -lr*G/(H+λ) (newton) or G/C (mean)."""
    k = G.shape[1]
    Gs = jnp.zeros((n_leaves, k), jnp.float32).at[node].add(G)
    Hs = jnp.zeros((n_leaves, k), jnp.float32).at[node].add(H)
    Cs = jnp.zeros((n_leaves,), jnp.float32).at[node].add(C)
    newton_val = -lr * Gs / (Hs + lam)
    mean_val = Gs / jnp.maximum(Cs, 1e-12)[:, None]
    return jnp.where(newton, newton_val, mean_val)


def grow_tree(binned: jnp.ndarray, G: jnp.ndarray, H: jnp.ndarray,
              C: jnp.ndarray, max_depth: int, n_bins: int,
              lam: float = 1.0, min_child_weight: float = 0.0,
              min_info_gain: float = 0.0, min_instances: float = 1.0,
              feat_mask: Optional[jnp.ndarray] = None,
              newton_leaf: bool = True, learning_rate: float = 1.0,
              ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Grow one full tree; returns heap arrays (feat, thresh, leaf).

    Python loop over ``max_depth`` levels — each level is a cached jitted
    kernel (shapes depend only on (level, D, B, K), so compilation amortizes
    across all trees, rounds, folds and grid points).
    """
    n, d = binned.shape
    if feat_mask is None:
        feat_mask = jnp.ones(d, bool)
    node = jnp.zeros(n, jnp.int32)
    feats, threshs = [], []
    for level in range(max_depth):
        f, t, node = _level_kernel(
            binned, node, G, H, C, feat_mask, 2 ** level, n_bins,
            jnp.float32(lam), jnp.float32(min_child_weight),
            jnp.float32(min_info_gain), jnp.float32(min_instances))
        feats.append(f)
        threshs.append(t)
    leaf = _leaf_kernel(node, G, H, C, 2 ** max_depth, jnp.float32(lam),
                        jnp.bool_(newton_leaf), jnp.float32(learning_rate))
    return (jnp.concatenate(feats), jnp.concatenate(threshs), leaf)


# ---------------------------------------------------------------------------
# Prediction
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("max_depth",))
def predict_tree(binned: jnp.ndarray, feat: jnp.ndarray, thresh: jnp.ndarray,
                 leaf: jnp.ndarray, max_depth: int) -> jnp.ndarray:
    """Route rows through one tree; returns (N, K) leaf values."""
    n = binned.shape[0]
    node = jnp.zeros(n, jnp.int32)

    def level(l, node):
        base = 2 ** l - 1
        heap = base + node
        f = feat[heap]
        t = thresh[heap]
        x = jnp.take_along_axis(binned, f[:, None], 1)[:, 0]
        return 2 * node + (x > t).astype(jnp.int32)

    node = lax.fori_loop(0, max_depth, level, node)
    return leaf[node]


@functools.partial(jax.jit, static_argnames=("max_depth",))
def predict_ensemble(binned: jnp.ndarray, feat: jnp.ndarray,
                     thresh: jnp.ndarray, leaf: jnp.ndarray,
                     max_depth: int) -> jnp.ndarray:
    """Sum of all trees' outputs: feat/thresh (T, 2^d-1), leaf (T, 2^d, K).

    scan over trees (static T unrolled by XLA where profitable).
    """

    def body(acc, tree):
        f, t, lf = tree
        return acc + predict_tree(binned, f, t, lf, max_depth), None

    n = binned.shape[0]
    k = leaf.shape[2]
    acc0 = jnp.zeros((n, k), jnp.float32)
    out, _ = lax.scan(body, acc0, (feat, thresh, leaf))
    return out
