"""Histogram decision-tree kernels in pure JAX — the TPU replacement for
XGBoost's C++ histogram GBDT core.

Reference dependency being replaced: xgboost4j JNI (SURVEY §2.11 — the one
genuinely native component of the reference; wrappers
OpXGBoostClassifier.scala:47 / OpXGBoostRegressor.scala:48) and Spark MLlib's
RandomForest/GBT (OpRandomForestClassifier.scala:58, OpGBTClassifier.scala:46).

Design (gpu_hist-style, adapted to XLA):
 * features pre-quantized to ``max_bins`` integer bins (quantile sketch on a
   sample, host-side; binned matrix lives in HBM as int8/int32)
 * trees grow level-wise; every level is one jitted kernel:
     - histogram: scatter-add of [grad(K), hess(K), count] into
       (nodes, D, B, 2K+1) via one flattened ``.at[].add`` — XLA lowers this
       to an efficient sort/segment pattern on TPU
     - split search: cumulative sums over bins -> best (feature, bin) per
       node by the standard gain formula  GL²/(HL+λ) + GR²/(HR+λ) − G²/(H+λ)
     - partition: rows move to ``2*node + go_right`` (no data movement — just
       an int vector update)
 * the tree is a *full* binary tree of ``max_depth`` levels in heap layout;
   nodes that fail min-gain/min-weight constraints emit an "always left"
   split (threshold = B), which keeps every shape static — no ragged trees,
   no recompilation across rounds/trees (SURVEY §7 hard part a).
 * multi-output targets (K>1) support multiclass GBDT (softmax, K trees'
   worth of leaf values per round in one pass) and RF classification
   (leaf = class histogram; variance gain over one-hot targets ≡ Gini gain).
"""
from __future__ import annotations

import contextlib
import functools
from typing import List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

__all__ = ["TreeEnsemble", "quantile_bins", "apply_bins", "grow_tree",
           "grow_forest", "grow_forest_rf", "forest_chunk_size",
           "predict_tree", "predict_ensemble", "compile_depth_hint",
           "FeatureBundles", "bundle_features", "bundle_matrix",
           "unbundle_ensemble", "goss_plan", "hist_accum_bf16"]

# Shared compile-depth hint: a model-selection sweep compiles ONE tree-growth
# program at the grid's deepest max_depth and runs every candidate through it
# with a traced per-tree depth_limit, instead of one ~5-16 s XLA compile per
# distinct depth (the depth sets the static heap shapes).  Set via the
# ``compile_depth_hint`` context manager (ModelSelector does this around its
# candidate sweep).
_COMPILE_DEPTH_HINT: Optional[int] = None


@contextlib.contextmanager
def compile_depth_hint(depth: Optional[int]):
    """Grow trees with heap shapes sized for ``depth`` within the context."""
    global _COMPILE_DEPTH_HINT
    prev = _COMPILE_DEPTH_HINT
    _COMPILE_DEPTH_HINT = depth
    try:
        yield
    finally:
        _COMPILE_DEPTH_HINT = prev


def _resolve_compile_depth(max_depth: int) -> int:
    if _COMPILE_DEPTH_HINT is not None and _COMPILE_DEPTH_HINT >= max_depth:
        return _COMPILE_DEPTH_HINT
    return max_depth


def hist_accum_bf16() -> bool:
    """bf16 histogram ACCUMULATION (not just bf16 operands): the level's
    partial gradient/hessian sums accumulate in bf16 and upcast to f32
    only at the level cumsum.  Opt-in via ``TMOG_MATRIX_PRECISION=bf16``
    (the same knob that governs the bf16 matrix upload; ``f32`` is the
    escape hatch for both) and accelerator-gated like the operand flag —
    XLA-CPU emulates bf16 scalar-slow, and there is no bandwidth to save
    there.  The quality contract is the TM028 tolerance probe
    (``analysis.contracts.check_accum_tolerance``): accumulation drift
    must stay within 1e-3 of the f32-accumulated metric, proven under
    TMOG_CHECK=1 next to the TM024 pad-invariance gate."""
    import os

    return (os.environ.get("TMOG_MATRIX_PRECISION", "auto") == "bf16"
            and _accel_bf16())


@functools.lru_cache(maxsize=1)
def _accel_bf16() -> bool:
    """bf16 histogram operands only help on accelerators: XLA-CPU emulates
    bf16 dots scalar-slow (measured ~30x on the config-5 fit — 78.7 s f32
    vs 2556 s bf16 at 25k×1000 on one core), so CPU execution keeps f32
    regardless of the requested hist precision."""
    import jax

    return jax.default_backend() not in ("cpu",)


#: rows per histogram block in the streamed build; the per-block bins
#: one-hot is ROW_BLOCK × B·D f32 per tree under vmap — 2.1 GB at 500
#: features × 32 bins, 0.4 GB at 100 features (forest_chunk_size budgets it)
ROW_BLOCK = 32768

#: engage sibling subtraction (left-child histograms only; right = parent −
#: left) at levels with at least this many slots — below it the bins one-hot
#: stream dominates and halving the node term buys nothing
SIBLING_MIN_SLOTS = 1024


class TreeEnsemble(NamedTuple):
    """Stacked trees: feat (T, 2^d-1) int32, thresh (T, 2^d-1) int32,
    leaf (T, 2^d, K) float32.  Heap layout: node i children 2i+1, 2i+2."""
    feat: jnp.ndarray
    thresh: jnp.ndarray
    leaf: jnp.ndarray

    @property
    def max_depth(self) -> int:
        # feat has 2^d - 1 internal nodes
        return int(np.log2(self.feat.shape[1] + 1))


# ---------------------------------------------------------------------------
# Quantile binning
# ---------------------------------------------------------------------------

def quantile_bins(X: np.ndarray, max_bins: int = 32,
                  sample_rows: int = 200_000, seed: int = 7) -> np.ndarray:
    """Per-feature quantile bin edges, shape (D, max_bins-1).

    Host-side on a row sample (the analogue of XGBoost's sketch); edges are
    deduplicated so constant/low-cardinality features waste no bins.
    """
    X = np.asarray(X)
    n, d = X.shape
    if n > sample_rows:
        rng = np.random.default_rng(seed)
        X = X[rng.choice(n, sample_rows, replace=False)]
    qs = np.linspace(0, 1, max_bins + 1)[1:-1]
    edges = np.quantile(X, qs, axis=0).T.astype(np.float32)  # (D, B-1)
    # strictly increasing edges; collapse duplicates to +inf (unused bins)
    eps = 1e-7
    for j in range(d):
        e = edges[j]
        dup = np.concatenate([[False], np.diff(e) <= eps])
        edges[j] = np.where(dup, np.inf, e)
    return edges


def quantile_bins_streaming(hists, max_bins: int = 32) -> np.ndarray:
    """Per-feature quantile bin edges from streamed histogram sketches.

    The out-of-core analogue of ``quantile_bins``: each feature's values
    were absorbed chunk-by-chunk into a ``StreamingHistogram``
    (utils/streaming_histogram.py — Ben-Haim/Tom-Tov bounded sketch, the
    design of XGBoost's external-memory quantile sketch, arXiv:1806.11248),
    and edges come from the sketch's quantiles.  Same output contract as
    ``quantile_bins``: (D, max_bins-1) float32, duplicate edges collapsed
    to +inf.

    Accuracy (documented tolerance, asserted in tests): with the default
    sketch budget of ``8 * max_bins`` histogram bins, each edge's empirical
    quantile rank is within ~0.05 of the exact rank — bin-edge placement
    noise on the order of one bin, immaterial to quantile-bin trees (the
    same argument as the reference sketch's eps).
    """
    qs = np.linspace(0, 1, max_bins + 1)[1:-1]
    d = len(hists)
    edges = np.empty((d, max_bins - 1), np.float32)
    for j, h in enumerate(hists):
        edges[j] = np.array([h.quantile(q) for q in qs], np.float32)
    eps = 1e-7
    for j in range(d):
        e = edges[j]
        dup = np.concatenate([[False], np.diff(e) <= eps])
        edges[j] = np.where(dup | ~np.isfinite(e), np.inf, e)
    return edges


def streaming_histograms_for(chunks, hist_bins: int = 256):
    """Per-feature ``StreamingHistogram`` sketches over (n, D) chunk
    matrices — the sketch pass of a two-pass external-memory tree fit."""
    from ..utils.streaming_histogram import StreamingHistogram

    hists = None
    for chunk in chunks:
        M = np.asarray(chunk, np.float64)
        if hists is None:
            hists = [StreamingHistogram(hist_bins) for _ in range(M.shape[1])]
        for j in range(M.shape[1]):
            hists[j].update(M[:, j])
    return hists or []


@jax.jit
def apply_bins(X: jnp.ndarray, edges: jnp.ndarray) -> jnp.ndarray:
    """Quantized matrix (N, D) int32 in [0, B)."""
    X = jnp.asarray(X, jnp.float32)
    # count of edges <= x  (edges padded with +inf never trigger)
    return jnp.sum(X[:, :, None] > edges[None, :, :], axis=2).astype(jnp.int32)


#: features at least this fraction zero sketch their quantiles over the
#: NONZERO values (with an edge pinned at 0): an all-values sketch of a 95%-
#: zero feature collapses every sub-0.95 quantile to 0, leaving ~2 usable
#: bins — XGBoost's sparsity-aware sketch (the C++ core behind
#: OpXGBoostClassifier.scala:47) keeps full resolution on the nonzeros
SPARSE_SKETCH_ZERO_FRAC = 0.5


def quantile_bins_sparse_aware(X: np.ndarray, max_bins: int = 32,
                               sample_rows: int = 200_000,
                               seed: int = 7) -> np.ndarray:
    """Per-feature bin edges like ``quantile_bins``, but features that are
    mostly zero spend their quantiles on the nonzero values (plus a pinned
    0.0 edge separating the zeros)."""
    X = np.asarray(X)
    n, d = X.shape
    if n > sample_rows:
        rng = np.random.default_rng(seed)
        X = X[rng.choice(n, sample_rows, replace=False)]
        n = sample_rows
    edges = np.full((d, max_bins - 1), np.inf, np.float32)
    qs_dense = np.linspace(0, 1, max_bins + 1)[1:-1]
    qs_sparse = np.linspace(0, 1, max_bins)[1:-1]       # B-2 qs + the 0 edge
    eps = 1e-7
    for j in range(d):
        col = X[:, j]
        # NaN entries are excluded from the sketch (the binning convention
        # pins NaN to bin 0 — trees._host_bins); nanquantile keeps a
        # NaN-containing feature from poisoning every edge
        nz = col[(col != 0) & ~np.isnan(col)]
        if len(nz) and 1.0 - len(nz) / n >= SPARSE_SKETCH_ZERO_FRAC:
            e = np.unique(np.concatenate(
                [[0.0], np.quantile(nz, qs_sparse)]).astype(np.float32))
        else:
            e = np.nanquantile(col, qs_dense).astype(np.float32)
            e = e[np.isfinite(e)]
            dup = np.concatenate([[False], np.diff(e) <= eps]) \
                if len(e) else np.zeros(0, bool)
            e = e[~dup]
        edges[j, :len(e)] = e[:max_bins - 1]
        # keep strictly increasing (dedup collapsed to +inf tail already)
    return edges


def build_feature_csr(X: np.ndarray, edges: np.ndarray
                      ) -> Optional[Tuple[np.ndarray, np.ndarray,
                                          np.ndarray]]:
    """Per-feature padded CSR of the NONZERO entries, for the sparse
    histogram path: returns (rows (D, NZ) int32, bins (D, NZ) int8,
    zero_bin (D,) int8) or None when the matrix doesn't qualify.

    ``rows`` is padded with the sentinel N (gathers index a zero-padded
    channel row, so pad entries contribute nothing); ``zero_bin[j]`` is the
    bin value 0.0 falls in — the kernel reconstructs that bin's row
    analytically (zero-bin = node totals − nonzero sums), so the histogram
    build touches only the ~5% nonzero entries (VERDICT r3 Missing #4).

    Qualification: overall density ≤ 0.25 and no near-dense outlier column
    (max nnz ≤ 4× mean) — one dense column would pad every feature's CSR
    to its length.
    """
    X = np.asarray(X)
    n, d = X.shape
    if edges.shape[1] + 1 > 127:
        return None   # bins/zero_bin are int8; decline rather than wrap
    mask = X != 0
    nnz = mask.sum(axis=0)
    total = int(nnz.sum())
    if total == 0 or total / (n * d) > 0.25:
        return None
    nz_max = int(nnz.max())
    if nz_max > max(4.0 * total / d, 64.0):
        return None
    rows = np.full((d, nz_max), n, np.int32)
    bins = np.zeros((d, nz_max), np.int8)
    for j in range(d):
        idx = np.nonzero(mask[:, j])[0]
        rows[j, :len(idx)] = idx
        e = np.sort(edges[j])
        vals = X[idx, j].astype(np.float32)
        b = np.searchsorted(e, vals, side="left").astype(np.int8)
        # NaN entries (counted as "nonzero" by the mask) follow the dense
        # binning convention: pinned to bin 0 (trees._host_bins) so the
        # histogram credits them where routing actually sends them
        bins[j, :len(idx)] = np.where(np.isnan(vals), np.int8(0), b)
    zero_bin = np.asarray(
        [np.searchsorted(np.sort(edges[j]), 0.0, side="left")
         for j in range(d)], np.int8)
    return rows, bins, zero_bin


# ---------------------------------------------------------------------------
# Exclusive feature bundling (EFB) — histogram-width reduction
# ---------------------------------------------------------------------------
#
# transmogrify() emits wide one-hot / picklist indicator blocks: groups of
# mutually exclusive, mostly-zero columns.  The histogram kernels stream a
# (rows, B·D) bins one-hot per level — their bandwidth floor — and pay it
# for every indicator column even though at most one per group is nonzero
# in any row.  ``bundle_features`` packs mutually exclusive columns into
# shared histogram columns with per-member bin offsets (the LightGBM EFB
# algorithm applied to the already-binned matrix), shrinking D before any
# device work.
#
# Invertibility: the split search on a bundled column enumerates only
# PER-MEMBER splits.  Member m occupies bundle bins [base_m, e_m] (its
# original nonzero bins shifted by base_m - 1; bundle bin 0 = every
# member at its default/zero bin), and threshold t with end table
# ``E(t) = min{e_m : e_m > t}`` opens the interval split "bundle bin in
# (t, E(t)]" — exactly "member m's ORIGINAL bin > t - base_m + 1", a
# single original (feature, threshold) pair.  Grown trees therefore map
# back losslessly (``unbundle_ensemble``): the persisted TreeEnsemble
# routes on the ORIGINAL binned matrix and feature importances land on
# original column ids.  On conflict-free matrices the bundled fit is
# bit-for-tree identical to the unbundled fit (property-tested in
# tests/test_tree_grid.py); under bounded conflicts (two members nonzero
# in one row, admitted by ``max_conflict_rate``) the smaller encoded
# value loses that row — an approximation bounded by the conflict budget.

#: a column qualifies for bundling when at most this fraction of sampled
#: rows is nonzero (indicator blocks sit far below this)
EFB_MAX_ACTIVE_FRAC = 0.5
#: rows sampled for the exclusivity scan — the bundle DECISION is made on
#: the sample; the full matrix is re-encoded exactly
EFB_SAMPLE_ROWS = 65536
#: bundling must shrink the histogram width to at most this ratio to pay
#: for the re-encode pass (singleton-heavy matrices decline)
EFB_MIN_WIDTH_RATIO = 0.85


class FeatureBundles(NamedTuple):
    """The invertible bundling plan ``bundle_features`` produces.

    ``plan``: one entry per BUNDLED column — an ``int`` original column
    id (verbatim copy) or a tuple of ``(orig_id, base, end)`` member
    triples (member's original nonzero bins shifted to bundle bins
    [base, end]).  ``col_feat``/``col_thresh`` are the (D_b, B) split
    map back to original (feature, threshold); ``end_bin`` is the (B,
    D_b) per-threshold member-end table the growth kernel consumes.
    """

    plan: Tuple
    col_feat: np.ndarray      # (D_b, B) int32
    col_thresh: np.ndarray    # (D_b, B) int32
    end_bin: np.ndarray       # (B, D_b) int32
    n_orig: int
    n_bins: int

    @property
    def width(self) -> int:
        return int(self.col_feat.shape[0])

    @property
    def width_ratio(self) -> float:
        return self.width / max(self.n_orig, 1)

    def bundled_dd_mask(self, dd_mask: Optional[np.ndarray]) -> np.ndarray:
        """Default-direction eligibility in BUNDLED column space: bundle
        columns never learn a default direction (their bin 0 is 'every
        member default' — variant-b routing would not map back to a
        single original feature); singleton columns keep their flag."""
        out = np.zeros(self.width, bool)
        if dd_mask is None:
            return out
        dd = np.asarray(dd_mask, bool)
        for c, spec in enumerate(self.plan):
            if isinstance(spec, (int, np.integer)):
                out[c] = bool(dd[int(spec)])
        return out


def bundle_features(binned: np.ndarray, edges: np.ndarray, max_bins: int,
                    max_conflict_rate: float = 0.0,
                    sample_rows: int = EFB_SAMPLE_ROWS,
                    min_width_ratio: float = EFB_MIN_WIDTH_RATIO,
                    ) -> Optional[FeatureBundles]:
    """Greedy exclusive-feature-bundling plan over a binned matrix, or
    None when bundling would not shrink the histogram width enough.

    Host-side and sample-based like the quantile sketch: exclusivity is
    decided on a strided row sample (``max_conflict_rate`` bounds the
    admitted conflicts per bundle, as a fraction of sampled rows); the
    encode pass (:func:`bundle_matrix`) then runs exactly over all rows.
    Only columns whose zeros bin to bin 0 qualify — the bundle's shared
    bin 0 must mean "this member is at its default".
    """
    binned = np.asarray(binned)
    n, d = binned.shape
    if d < 3 or max_bins > 127:
        return None
    e = np.asarray(edges, np.float32)
    finite = np.isfinite(e)
    used_bins = finite.sum(axis=1) + 1                 # bins 0..u-1 occur
    # zeros must land in bin 0: the smallest finite edge is >= 0
    first_edge = np.where(finite, e, np.inf).min(axis=1)
    zero_ok = first_edge >= 0.0

    step = max(1, n // sample_rows)
    samp = binned[::step][:sample_rows]
    ns = samp.shape[0]
    active = samp != 0                                  # (ns, d)
    act_frac = active.mean(axis=0)
    cand = (used_bins >= 2) & zero_ok & (act_frac <= EFB_MAX_ACTIVE_FRAC)
    cand_ids = np.where(cand)[0]
    if len(cand_ids) < 2:
        return None

    budget = int(max_conflict_rate * ns)
    # greedy pack, densest candidate first (the LightGBM ordering)
    order = cand_ids[np.argsort(-act_frac[cand_ids], kind="stable")]
    bundles: List[dict] = []
    for j in order:
        uj = int(used_bins[j])
        aj = active[:, j]
        placed = False
        for b in bundles:
            if b["bins"] + (uj - 1) > max_bins:
                continue
            conflicts = int(np.count_nonzero(aj & b["active"]))
            if b["conflicts"] + conflicts > budget:
                continue
            b["members"].append(int(j))
            b["bins"] += uj - 1
            b["conflicts"] += conflicts
            b["active"] |= aj
            placed = True
            break
        if not placed:
            bundles.append({"members": [int(j)], "bins": 1 + (uj - 1),
                            "conflicts": 0, "active": aj.copy()})
    multi = {}
    for b in bundles:
        if len(b["members"]) >= 2:
            ms = sorted(b["members"])
            multi[ms[0]] = ms
    if not multi:
        return None
    in_multi = {j for ms in multi.values() for j in ms}
    width = d - len(in_multi) + len(multi)
    if width > min_width_ratio * d:
        return None

    B = int(max_bins)
    plan: List = []
    for j in range(d):
        if j in in_multi:
            if j in multi:                    # bundle sits at first member
                specs, base = [], 1
                for m in multi[j]:
                    um = int(used_bins[m])
                    specs.append((m, base, base + um - 2))
                    base += um - 1
                plan.append(tuple(specs))
        else:
            plan.append(j)
    d_b = len(plan)
    col_feat = np.zeros((d_b, B), np.int32)
    col_thresh = np.zeros((d_b, B), np.int32)
    end_bin = np.empty((B, d_b), np.int32)
    ts = np.arange(B, dtype=np.int32)
    for c, spec in enumerate(plan):
        if isinstance(spec, (int, np.integer)):
            col_feat[c] = int(spec)
            col_thresh[c] = ts
            end_bin[:, c] = B - 1
        else:
            ends = np.asarray([s[2] for s in spec], np.int32)
            # owner(t): the member whose end is the smallest end > t;
            # past the last member the interval (t, t] is empty (no split)
            owner = np.searchsorted(ends, ts, side="right")
            tail = owner >= len(spec)
            owner = np.minimum(owner, len(spec) - 1)
            end_bin[:, c] = np.where(tail, ts, ends[owner])
            feats = np.asarray([s[0] for s in spec], np.int32)
            bases = np.asarray([s[1] for s in spec], np.int32)
            col_feat[c] = feats[owner]
            col_thresh[c] = np.maximum(ts - bases[owner] + 1, 0)
    return FeatureBundles(plan=tuple(plan), col_feat=col_feat,
                          col_thresh=col_thresh, end_bin=end_bin,
                          n_orig=d, n_bins=B)


def bundle_matrix(bundles: FeatureBundles, binned: np.ndarray) -> np.ndarray:
    """Encode the (N, D) binned matrix into (N, D_b) bundled columns.

    Bundle bin = base_m + orig_bin - 1 for the active member; 0 when every
    member sits at its zero bin.  Conflicting rows (several members
    active — only possible under a nonzero conflict budget) keep the
    LARGEST encoded value, deterministically."""
    binned = np.asarray(binned)
    n = binned.shape[0]
    out = np.zeros((n, bundles.width), binned.dtype)
    for c, spec in enumerate(bundles.plan):
        if isinstance(spec, (int, np.integer)):
            out[:, c] = binned[:, int(spec)]
        else:
            enc = np.zeros(n, np.int32)
            for orig, base, _end in spec:
                v = binned[:, orig].astype(np.int32)
                np.maximum(enc, np.where(v > 0, base + v - 1, 0), out=enc)
            out[:, c] = enc.astype(binned.dtype)
    return out


def unbundle_ensemble(bundles: FeatureBundles, feat, thresh):
    """Map grown (T, nodes) split arrays from bundled column space back to
    ORIGINAL (feature, threshold) pairs — exact for every per-member
    interval split the bundled gain search emits.  No-split sentinels
    (thresh == B) and default-direction splits (negative thresholds, only
    ever emitted on singleton columns) pass through unchanged."""
    feat = np.asarray(feat)
    thresh = np.asarray(thresh)
    B = bundles.n_bins
    t_id = np.clip(thresh, 0, B - 1)
    f_orig = bundles.col_feat[feat, t_id]
    t_orig = bundles.col_thresh[feat, t_id]
    passthrough = (thresh >= B) | (thresh < 0)
    f_out = np.where(passthrough, bundles.col_feat[feat, 0], f_orig)
    t_out = np.where(passthrough, thresh, t_orig)
    return f_out.astype(np.int32), t_out.astype(np.int32)


# ---------------------------------------------------------------------------
# GOSS — gradient-based one-side sampling (deep boosted candidates)
# ---------------------------------------------------------------------------

#: GOSS only engages at/above this tree depth: shallow trees are cheap
#: and the sampling noise isn't worth it (the ISSUE 11 contract)
GOSS_MIN_DEPTH = 8
#: below this many rows the gather outweighs the histogram savings
GOSS_MIN_ROWS = 20000
#: keep fraction by |gradient| / uniform-sample fraction of the rest —
#: the LightGBM defaults' neighbourhood (a=0.2, b=0.2, amp=(1-a)/b)
GOSS_TOP_FRAC = 0.2
GOSS_REST_FRAC = 0.2


def goss_plan(n_rows: int, min_depth: int) -> Optional[Tuple[int, int]]:
    """Static (k_top, k_rest) GOSS row budget for a launch whose
    shallowest candidate has ``min_depth``, or None when GOSS stays off.
    ``TMOG_GOSS``: '1' forces on (row gate bypassed; the depth gate is
    part of the contract and always holds), '0' forces off, 'auto'
    (default) engages at depth >= 8 and n >= GOSS_MIN_ROWS.  Resolved by
    the non-jitted callers so the budget is a static jit-cache-key arg."""
    import os

    v = os.environ.get("TMOG_GOSS", "auto")
    if v == "0" or min_depth < GOSS_MIN_DEPTH:
        return None
    if v != "1" and n_rows < GOSS_MIN_ROWS:
        return None
    k_top = max(1, int(round(GOSS_TOP_FRAC * n_rows)))
    k_rest = max(1, int(round(GOSS_REST_FRAC * n_rows)))
    if k_top + k_rest >= n_rows:
        return None
    return k_top, k_rest


def _goss_select(ga, key, k_top: int, k_rest: int):
    """One chain/tree's GOSS row selection: the ``k_top`` rows of largest
    |gradient| kept at weight 1, ``k_rest`` uniform samples of the rest
    amplified by (N - k_top)/k_rest — the standard unbiasedness weights.
    Returns (row indices (k_top+k_rest,), per-row multipliers);
    deterministic in ``key``."""
    _, top_idx = lax.top_k(ga, k_top)
    r = jax.random.uniform(key, ga.shape)
    r = r.at[top_idx].set(-1.0)             # exclude kept rows
    _, rest_idx = lax.top_k(r, k_rest)
    idx = jnp.concatenate([top_idx, rest_idx])
    amp = (ga.shape[0] - k_top) / k_rest
    mult = jnp.concatenate([jnp.ones(k_top, jnp.float32),
                            jnp.full(k_rest, amp, jnp.float32)])
    return idx, mult


# ---------------------------------------------------------------------------
# Segmented (sort-by-node) histogram accumulation — the Pallas VMEM path
# ---------------------------------------------------------------------------
#
# The dense formulation pays 2·N·nchan·M·B·D dot FLOPs per level (every row
# multiplied against every node slot) and streams an (N, B·D) one-hot
# through HBM — measured ~50x above the HLO bytes floor (VERDICT r4 #2).
# Here rows are SORTED by node slot and each slot's run padded to a
# multiple of ``SEG_ROW_BLOCK``, so every row block belongs to exactly one
# slot: a Pallas grid step builds its block's bins one-hot in VMEM (never
# HBM) and reduces it straight into that single slot's histogram row — no
# M factor in the FLOPs, no one-hot materialization.
#
# Measured on the tunneled v5e (depth-10 rounds, skip_counts, warm):
#   isolated level (1M x 512, M=512): kernel 8.8 ms + sort/align ~41 ms
#     vs dense dot ~330 ms (~6.6x)
#   in-program, 1 chain:  1M x 500: 1233 vs 2185 ms/round (1.77x);
#     250k x 1000: 417 vs 582 ms/round (1.40x)
#   in-program, 6 vmapped chains (1M x 500): ~7.0 s/round EITHER WAY —
#     dense amortizes its (rows, B·D) one-hot across chains (per-chain
#     2185 -> 1150 ms from S=1 to S=6) while seg pays its per-chain
#     sort/align row gathers (~16 GB/s effective — the GATHER, not the
#     kernel, is seg's wall) with nothing to share across chains.
# Hence auto engages only for LOW-chain-count programs at large N
# (single XGB fits, config-5-class shapes, budget-chunked launches);
# wide lockstep sweeps keep the dense shared-one-hot formulation.

#: rows per Pallas grid step == slot-run padding alignment
SEG_ROW_BLOCK = 128
#: feature-axis tile (B * SEG_D_BLOCK columns of one-hot per step in VMEM)
SEG_D_BLOCK = 512
#: auto mode: segmented path from this many rows (measured crossover)
SEG_MIN_ROWS = 250_000
#: auto mode: dense's cross-chain one-hot sharing wins above this many
#: chains per launch (measured: seg 1.77x at S=1, parity at S=6)
SEG_MAX_CHAINS = 2
#: histogram slots above which the padding overhead (M * SEG_ROW_BLOCK
#: rows) stops paying — depth <= 10 chains stay under this
SEG_MAX_SLOTS = 512


def seg_hist_auto(n_rows: int, n_chains: int = 1) -> bool:
    """Resolve the segmented-histogram flag for a program growing
    ``n_chains`` trees per launch over ``n_rows`` rows (called by the
    non-jitted fitters so the choice is a static jit-cache-key arg).
    ``TMOG_SEG_HIST``: '1' force on, '0' force off, 'auto' (default)."""
    import os

    v = os.environ.get("TMOG_SEG_HIST", "auto")
    if v == "1":
        return True
    if v == "0":
        return False
    # TPU only: the kernel uses pltpu grid specs (interpret-mode runs
    # cover CPU tests; other accelerators would fail to lower)
    return (n_rows >= SEG_MIN_ROWS and n_chains <= SEG_MAX_CHAINS
            and jax.default_backend() == "tpu")


def _seg_kernel(bs_ref, binned_ref, ch_ref, out_ref, *, n_bins: int,
                d_blk: int, nchan: int):
    """One grid step: reduce an (A, B·d_blk) bins one-hot (built in VMEM)
    into this block's slot's histogram row.  Out block is selected by the
    scalar-prefetched block->slot map; consecutive blocks of one slot
    accumulate in VMEM and flush once on slot change."""
    import jax.experimental.pallas as pl

    i_r = pl.program_id(1)

    @pl.when((i_r == 0) | (bs_ref[i_r] != bs_ref[jnp.maximum(i_r - 1, 0)]))
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    rows = binned_ref[...].astype(jnp.int32)            # (A, d_blk)
    ch = ch_ref[...]                                    # (A, nchan)
    b_iota = jax.lax.broadcasted_iota(
        jnp.int32, (SEG_ROW_BLOCK, n_bins, d_blk), 1)
    oh = rows[:, None, :] == b_iota                     # (A, B, d_blk)
    parts = []
    for c in range(nchan):
        w = ch[:, c][:, None, None]
        parts.append(jnp.sum(jnp.where(oh, w, 0.0), axis=0))  # (B, d_blk)
    out_ref[0] = out_ref[0] + jnp.concatenate(parts, axis=0)


def _seg_align(slot, binned_pad_cols, chans, M: int):
    """Sort rows by slot and pad each slot's run to a SEG_ROW_BLOCK
    multiple.  Returns (block_slots (n_blocks,) int32, binned (N', d)
    reordered, ch (N', nchan) reordered; padded rows carry zero channel
    weight so they contribute nothing to their block's slot."""
    A = SEG_ROW_BLOCK
    n = slot.shape[0]
    ch = jnp.stack(chans, axis=1)
    perm = jnp.argsort(slot)
    ss = slot[perm]
    sl_ids = jnp.arange(M, dtype=ss.dtype)
    starts = jnp.searchsorted(ss, sl_ids, side="left",
                              method="compare_all").astype(jnp.int32)
    ends = jnp.searchsorted(ss, sl_ids, side="right",
                            method="compare_all").astype(jnp.int32)
    counts = ends - starts
    # every slot gets AT LEAST one (all-padding) block: an empty slot with
    # no block would never be visited by the kernel grid, leaving its
    # output row UNINITIALIZED HBM (empty nodes are routine — a no-split
    # node routes every row left, emptying the right child).  The padding
    # block's zeroed channels write exact zeros, matching the dense path.
    padded = jnp.maximum(-(-counts // A), 1) * A
    pad_off = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), jnp.cumsum(padded)[:-1].astype(jnp.int32)])
    n_pad = (-(-n // A) + M) * A
    # per-slot quantities resolve at BLOCK granularity then broadcast to
    # rows: a positionwise searchsorted lowers to a sequential scan over
    # MB-scale vectors (~110 ms/level at 1M — measured)
    blk_start = pad_off // A
    bi = jnp.arange(n_pad // A, dtype=jnp.int32)
    bs_blk = (jnp.searchsorted(blk_start, bi, side="right",
                               method="compare_all").astype(jnp.int32) - 1)
    bs_blk = jnp.clip(bs_blk, 0, M - 1)

    def widen(v_blk):
        return jnp.broadcast_to(v_blk[:, None], (n_pad // A, A)).reshape(-1)

    p = jnp.arange(n_pad, dtype=jnp.int32)
    off = p - widen(pad_off[bs_blk])
    valid = off < widen(counts[bs_blk])
    src_sorted = jnp.where(valid, widen(starts[bs_blk]) + off, 0)
    src = perm[src_sorted]
    # padding rows alias row perm[0]'s bins but carry ZERO channel weight —
    # they contribute nothing to their block's slot, so only the channel
    # matrix needs masking (a masked rewrite of the (N', d) binned copy
    # cost a full extra memory pass)
    binned_sorted = binned_pad_cols[src]
    ch_sorted = jnp.where(valid[:, None], ch[src], 0.0)
    return bs_blk, binned_sorted, ch_sorted


def _seg_level_hists(binned_seg, slot, chans, M: int, B: int, d: int):
    """One level's per-channel histograms [(M, B, d)] via the segmented
    Pallas kernel.  ``binned_seg`` is the full-width matrix with its
    feature axis pre-padded to a SEG_D_BLOCK multiple (hoisted out of the
    level loop by the caller); accumulation is f32 (the one-hot never
    materializes, so there is no bf16 stream to halve — hist_bf16 is a
    no-op on this path)."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    A = SEG_ROW_BLOCK
    nchan = len(chans)
    d_pad = binned_seg.shape[1]
    bs, bp, cp = _seg_align(slot, binned_seg, chans, M)
    n_rb = bp.shape[0] // A
    n_db = d_pad // SEG_D_BLOCK
    out = pl.pallas_call(
        functools.partial(_seg_kernel, n_bins=B, d_blk=SEG_D_BLOCK,
                          nchan=nchan),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(n_db, n_rb),
            in_specs=[
                pl.BlockSpec((A, SEG_D_BLOCK),
                             lambda i_d, i_r, bs: (i_r, i_d)),
                pl.BlockSpec((A, nchan), lambda i_d, i_r, bs: (i_r, 0)),
            ],
            out_specs=pl.BlockSpec(
                (1, nchan * B, SEG_D_BLOCK),
                lambda i_d, i_r, bs: (bs[i_r], 0, i_d)),
        ),
        out_shape=jax.ShapeDtypeStruct((M, nchan * B, d_pad), jnp.float32),
        interpret=jax.default_backend() != "tpu",
    )(bs, bp, cp)
    return [out[:, c * B:(c + 1) * B, :d] for c in range(nchan)]


#: sparse-path entry block: bounds the transient (D, Eb, M) slot one-hot
SPARSE_ENTRY_BLOCK_ELEMS = 1 << 28
#: above this many slots the (entries, M) one-hot exceeds the dense bins
#: stream (breakeven ~ density·(M + B·nchan) vs ~2.5·B) — fall back dense
SPARSE_MAX_SLOTS = 2048


def _sparse_level_hists(csr_rows, csr_bins, zero_b_oh, slot, chans,
                        Mh: int, B: int, hdt, dot_prec):
    """One level's histograms from the nonzero entries only.

    ``hist[c][m, b, j] = Σ_e ch_c[row(j,e)]·1[slot=m]·1[bin=b]`` as a
    feature-batched matmul ``(D, M, E)@(D, E, B·nchan)`` — the plain slot
    one-hot is the big operand (E·M), the channel values ride the SMALL
    bins one-hot (E·B·nchan) — with the zero-bin row reconstructed
    analytically: zero-bin = per-slot channel totals (one tiny scatter-add
    over rows) − the nonzero sums.  Touches ~density·N·D entries instead
    of the full N·B·D one-hot stream.
    """
    n = slot.shape[0]
    d, nz = csr_rows.shape
    nchan = len(chans)
    # sentinel row n -> zero-padded channel row (pad entries contribute 0)
    slot_pad = jnp.concatenate([slot, jnp.zeros(1, jnp.int32)])
    ch_pad = jnp.concatenate(
        [jnp.stack(chans, axis=1),
         jnp.zeros((1, nchan), chans[0].dtype)])          # (N+1, nchan)

    eb = max(1, min(nz, SPARSE_ENTRY_BLOCK_ELEMS // max(d * Mh, 1)))
    n_blocks = -(-nz // eb)
    pad = n_blocks * eb - nz
    rows_b = jnp.pad(csr_rows, ((0, 0), (0, pad)),
                     constant_values=n).reshape(d, n_blocks, eb)
    bins_b = jnp.pad(csr_bins, ((0, 0), (0, pad))).reshape(d, n_blocks, eb)
    rows_b = jnp.swapaxes(rows_b, 0, 1)                   # (blocks, D, Eb)
    bins_b = jnp.swapaxes(bins_b, 0, 1)

    def block(acc, xs):
        r_b, b_b = xs                                      # (D, Eb)
        sl = slot_pad[r_b]                                 # (D, Eb)
        oh_m = (sl[:, :, None] == jnp.arange(Mh)[None, None, :]).astype(hdt)
        vals = ch_pad[r_b].astype(hdt)                     # (D, Eb, nchan)
        oh_b = (b_b[:, :, None] == jnp.arange(B)[None, None, :]).astype(hdt)
        wb = (oh_b[:, :, :, None] * vals[:, :, None, :]).reshape(
            d, -1, B * nchan)                              # (D, Eb, B·nchan)
        part = jax.lax.dot_general(
            jnp.swapaxes(oh_m, 1, 2), wb,
            (((2,), (1,)), ((0,), (0,))),                  # (D, M, B·nchan)
            precision=dot_prec, preferred_element_type=jnp.float32)
        return acc + part, None

    acc0 = jnp.zeros((d, Mh, B * nchan), jnp.float32)
    hist_sp, _ = lax.scan(block, acc0, (rows_b, bins_b))
    hist_sp = hist_sp.reshape(d, Mh, B, nchan)
    # per-slot channel totals over ALL rows: one (N, nchan) scatter-add
    tot = jnp.zeros((Mh, nchan), jnp.float32).at[slot].add(
        jnp.stack(chans, axis=1), mode="drop")             # (M, nchan)
    zero_contrib = tot[None] - hist_sp.sum(axis=2)         # (D, M, nchan)
    hist_sp = hist_sp + (zero_contrib[:, :, None, :]
                         * zero_b_oh[:, None, :, None])
    return [jnp.transpose(hist_sp[..., c], (1, 2, 0))      # (M, B, D)
            for c in range(nchan)]


def default_dir_mask(edges) -> np.ndarray:
    """(D,) bool: features whose bin 0 is a GENUINE missing/absent bucket —
    their smallest finite bin edge is the sparse-aware sketch's pinned 0.0
    (zeros and NaNs land in bin 0, real values in bins >= 1).  Only these
    features may learn a default direction: on a dense feature bin 0 is
    merely the lowest quantile."""
    e = np.asarray(edges, np.float64)
    first = np.where(np.isfinite(e), e, np.inf).min(axis=1)
    return first == 0.0


def _route_right(x, t):
    """THE split routing rule, shared by growth and prediction.

    ``t`` in [0, B-1): go right iff bin > t.  ``t == B``: no-split
    sentinel (always left).  ``t < 0``: default-direction split (XGBoost
    missing-value semantics) — effective threshold -t-1, and the bin-0
    (missing/absent) bucket routes RIGHT instead of left."""
    dr = t < 0
    te = jnp.where(dr, -t - 1, t)
    return (x > te) | (dr & (x == 0))


def _grow_tree_traced(binned, G, H, C, feat_mask, depth_limit,
                      max_depth: int, n_bins: int, lam, min_child_weight,
                      min_info_gain, min_instances, newton_leaf,
                      learning_rate, hist_bf16: bool = False,
                      all_reduce=None, min_gain_raw=None,
                      bag_mode: str = "none", feat_idx=None,
                      leaf_levels: Tuple[int, ...] = (), csr=None,
                      seg_hist: bool = False, default_dir: bool = False,
                      dd_mask=None, bundle_end=None,
                      acc_bf16: bool = False):
    """One whole tree under trace: Python-unrolled loop over levels.

    ``bundle_end``: optional (B, D) int32 per-(threshold, feature) member
    END-bin table from :func:`bundle_features` — the matrix is then in
    BUNDLED column space and every split candidate becomes the per-member
    interval split "bin in (t, E(t)]" (right) vs everything else (left),
    which maps back to a single ORIGINAL (feature, threshold) pair.
    Unbundled columns carry E = B-1, making the interval form bit-
    identical to the standard "bin > t" split.  Incompatible with
    ``feat_idx`` (callers guard); ``default_dir`` composes only through a
    ``dd_mask`` that excludes bundle columns (FeatureBundles.
    bundled_dd_mask).

    ``acc_bf16``: accumulate the histogram partials in bf16 (operands
    already ride ``hist_bf16``) and upcast to f32 at the level cumsum —
    the TMOG_MATRIX_PRECISION=bf16 opt-in, quality-gated by the TM028
    tolerance probe.

    ``csr``: optional (rows (D, NZ) int32, bins (D, NZ) int8,
    zero_bin_onehot (D, B)) device triple from ``build_feature_csr`` — wide
    mostly-zero matrices then build each level's histograms from the
    nonzero entries only (``_sparse_level_hists``), with the zero bin
    recovered analytically.  Split search, routing, and leaves are
    unchanged (the dense int8 matrix still routes rows).  Incompatible
    with ``feat_idx`` and ``all_reduce`` (callers guard).

    ``leaf_levels``: static sorted levels at which to ALSO emit the leaf
    values of the depth-ℓ TRUNCATION of this tree (one (2^ℓ, K) array per
    level, 4th return element).  For level-wise greedy growth, splits at
    level ℓ are independent of deeper levels, so a shallower ``max_depth``
    grid candidate is exactly this tree truncated at its depth — the
    snapshot's per-node value sums come FREE from the level's own histogram
    totals (Σ over bins of any feature's column), so one grown tree serves
    every depth in a hyperparameter grid (the r3 default grid grew the
    (min_info_gain, min_instances) × 3-depth product 3x redundantly).

    This is the dispatch-collapsing design: the per-level kernel approach
    costs depth×trees device round-trips (ruinous through a remote TPU
    tunnel — measured ~12-17 s per 50-tree fit from launch overhead alone);
    here a full tree (and, via vmap, a whole chunk of trees) is ONE XLA
    program.  Two scaling decisions keep deep trees cheap:

    * **Node compaction**: a level has at most ``min(2^level, N)`` populated
      nodes, so when ``2^level`` exceeds the row count the level's node ids
      are compacted (sort + first-occurrence ranks) into ``next_pow2(N)``
      slots.  Histogram/split work therefore scales with the DATA, not with
      ``2^depth`` — a depth-12 tree on 891 rows does 1024-slot levels, not
      2048-slot ones, and depth 16+ stays flat.
    * **Tile-friendly layout**: per-channel histograms are shaped
      ``(slots, bins, features)`` so the minor axis is the wide feature
      dimension (pads to the 128-lane tile at ~1.2×), not the 32-bin axis
      (which pads 4×, and OOMed a 6-tree chunk at depth 12).
    * **MXU histograms**: the histogram is two one-hot matmuls —
      ``(slots, N) @ (N, bins·features)`` — instead of a scatter-add.  XLA
      lowers TPU scatters to sorts (measured ~5 ms per (N, D) scatter; ~1800
      of them per 50-tree depth-12 fit ≈ 8 s), while the matmul form rides
      the systolic array and the bin one-hot is built once per chunk.
    """
    # Feature-subset fast path (RF's featureSubsetStrategy): when the tree
    # uses only ``msub`` of D features, build histograms at width msub
    # instead of D.  The per-level (rows, B·msub) bins one-hot is the
    # kernel's bandwidth bottleneck (measured: per-level cost is flat in
    # slot count and linear in D at 100k×500), so sqrt-D subsetting cuts
    # the histogram traffic ~D/msub (≈23x at D=500).  The one-hot is
    # gathered DIRECTLY into its flat (rows, B·msub) layout from the
    # full-width matrix (``col_idx`` repeats the subset ids per bin):
    # materializing a (rows, msub)-gathered copy and a (rows, B, msub)
    # one-hot put msub=22 on the minor axis, padding every row to the
    # 128-lane tile (5.8x wasted stream — VERDICT r4 #3); the flat minor
    # axis B·msub (704 at 32 bins) pads only ~1.09x.
    # (hist_bf16 is resolved by the non-jitted callers — grow_tree,
    # grow_forest_rf, grow_rf_grid, the GBT fitters — as
    # ``requested and _accel_bf16()`` so the backend gate participates in
    # the jit cache key; resolving it here at trace time let a CPU-traced
    # f32 executable be silently reused under a bf16 key and vice versa.)
    binned_full = binned
    n = binned.shape[0]
    if feat_idx is not None:
        feat_idx = feat_idx.astype(jnp.int32)
        d = feat_idx.shape[0]
        # flat one-hot column c = b*msub + j  <->  (bin b, subset slot j):
        # the SAME b-major/j-minor order as the reshape form, so histogram
        # numerics are bit-identical to the gathered formulation
        col_idx = jnp.tile(feat_idx, n_bins)               # (B·msub,)
        bin_vec = jnp.repeat(jnp.arange(n_bins, dtype=binned.dtype), d)
        feat_mask = jnp.ones(d, bool)
    else:
        d = binned.shape[1]
        col_idx = None
        bin_vec = None
    k = G.shape[1]
    B = n_bins
    n_cap = 1 << int(np.ceil(np.log2(max(n, 2))))   # static pow2 ≥ N
    if all_reduce is not None:
        # sharded growth: shards see different rows, so shard-local node
        # compaction would produce inconsistent slot<->node mappings; grow
        # with the full 2^level slot layout and psum the histograms
        n_cap = 1 << 62
    # Bagged forests have structurally redundant channels: H_i == C (hessian
    # IS the bag weight), and for one-hot classification targets the class
    # gradients sum to the counts (Σ_i G_i == C).  Building only the
    # irreducible channels cuts the histogram matmul count from 2K+1 to K
    # ("onehot": K-1 grads + counts) or K+1 ("bagged" regression: K grads +
    # counts) — a 2.5x FLOP cut for binary RF, the sweep's hot op.  The
    # dropped histograms are reconstructed exactly below (same partial sums,
    # one extra subtraction of rounding-level error).
    if bag_mode == "onehot":
        chans = [G[:, i] for i in range(k - 1)] + [C]
    elif bag_mode == "bagged":
        chans = [G[:, i] for i in range(k)] + [C]
    elif bag_mode == "newton":
        # count channel dropped (XGBoost semantics): callers guarantee
        # min_instances <= 1 and min_info_gain == 0 — XGB's own gating is
        # min_child_weight + gamma, both hessian/raw-gain based — so count
        # gating and per-node-weight gain normalization are inert, and 2K
        # channels instead of 2K+1 cut the per-chain histogram dot and
        # one-hot stream by a third (binary GBT: 3 -> 2)
        chans = [G[:, i] for i in range(k)] + [H[:, i] for i in range(k)]
        min_instances = jnp.float32(0.0)   # CL proxy is hessian mass
    else:
        chans = [G[:, i] for i in range(k)] \
            + [H[:, i] for i in range(k)] + [C]
    nchan = len(chans)
    # RF grad/hess are bag-weight × one-hot class values — exact in bf16
    # for integer weights, ≲1e-3 relative under fractional balancer weights,
    # either way immaterial to split selection; DEFAULT precision (bf16 in,
    # f32 accumulate) runs the histogram dots at ~2x MXU throughput.  GBT
    # gradients are continuous and compound across rounds: keep HIGHEST.
    dot_prec = (jax.lax.Precision.DEFAULT if hist_bf16
                else jax.lax.Precision.HIGHEST)

    # One-hot operands materialize in bf16 under ``hist_bf16`` — the 0/1
    # one-hots are exact in bf16 and the stream (the kernel's bandwidth
    # floor) halves; channel values ride the already-accepted hist_bf16
    # precision contract.
    hdt = jnp.bfloat16 if hist_bf16 else jnp.float32
    # histogram ACCUMULATION dtype (preferred_element_type of the dots and
    # the row-block scan carry); f32 unless the TM028-gated opt-in is on
    adt = jnp.bfloat16 if acc_bf16 else jnp.float32

    # Row-blocked histogram build: the bins one-hot is (rows, B·D) — at
    # 1M×500×32 bins that is 64 GB f32 if materialized whole, so rows stream
    # through in blocks with the (M, B·D) accumulators carried by lax.scan.
    # Small inputs keep the single hoisted one-hot (no scan overhead).
    def bins_onehot(rows_b):
        """Flat (rows, B·d) bins one-hot for a block of full-width rows.

        Subset path: one gather from the (well-tiled) full-width matrix
        straight into the flat layout — no msub-minor intermediate.  Full-
        width path: the reshape form (minor axis d is already >= a lane
        tile for the wide matrices this kernel targets)."""
        if col_idx is not None:
            return (rows_b[:, col_idx] == bin_vec[None, :]).astype(hdt)
        return (rows_b[:, None, :] == jnp.arange(B)[None, :, None]
                ).astype(hdt).reshape(rows_b.shape[0], B * d)

    # segmented (sort-by-node) histogram path: resolved statically by the
    # callers (seg_hist_auto); engages per level at Mh <= SEG_MAX_SLOTS
    seg = (seg_hist and csr is None and feat_idx is None
           and all_reduce is None)
    if seg:
        d_pad = -(-d // SEG_D_BLOCK) * SEG_D_BLOCK
        binned_seg = (binned_full if d_pad == d
                      else jnp.pad(binned_full, ((0, 0), (0, d_pad - d))))

    blocked = n > ROW_BLOCK
    if blocked:
        n_blocks = -(-n // ROW_BLOCK)
        n_pad = n_blocks * ROW_BLOCK
        pad = n_pad - n
        binned_blk = jnp.pad(binned_full, ((0, pad), (0, 0))).reshape(
            n_blocks, ROW_BLOCK, binned_full.shape[1])
        # padded rows carry zero channel weight: they land in slot 0 bin 0
        # and contribute nothing
        chans_blk = jnp.pad(jnp.stack(chans, 1), ((0, pad), (0, 0))).reshape(
            n_blocks, ROW_BLOCK, nchan)
    else:
        # (N, B·d) one-hot, minor axis flat (128-lane tile friendly)
        onehot_bins = bins_onehot(binned_full)

    node = jnp.zeros(n, jnp.int32)
    heap_feat_levels, heap_thresh_levels = [], []
    leaf_snaps = []    # (2^l, K) truncation leaf values per leaf_levels entry
    prev_cums = None   # previous level's per-channel bin cumsums (M, B, d)

    for level in range(max_depth):
        level_nodes = 2 ** level
        compact = level_nodes > n_cap
        M = n_cap if compact else level_nodes        # static slot count

        # Sibling subtraction: at wide non-compact levels build histograms
        # for LEFT children only (slot 2j -> column j; right-child rows
        # contribute zero) and derive the right child's cumsums from the
        # retained parent cumsums (right = parent − left) — halves the
        # (rows, M) node one-hot stream and the histogram dots exactly
        # where M makes them dominant.  Non-compact level l implies
        # non-compact l−1, so the parent cumsums are always full-layout.
        # Integer-channel bag modes only (RF one-hot/bagged): the bagged
        # channels are integer-valued so parent − left is exact, while
        # continuous GBT gradient/hessian channels suffer cancellation —
        # tiny negative hessian residuals could flip min_child_weight /
        # min_instances gating vs the direct build (ADVICE r3).
        sib = (level >= 1 and not compact and M >= SIBLING_MIN_SLOTS
               and prev_cums is not None
               and bag_mode in ("onehot", "bagged"))
        Mh = M // 2 if sib else M

        if compact:
            # rows occupy ≤ N distinct nodes: rank their sorted ids
            sorted_ids = jnp.sort(node)
            first = jnp.concatenate(
                [jnp.ones(1, bool), sorted_ids[1:] != sorted_ids[:-1]])
            uniq = jnp.sort(jnp.where(first, sorted_ids, jnp.int32(2**31 - 1)))
            # (M,) padded with INT32_MAX (n ≤ M = next_pow2(n) by construction)
            uniq = jnp.full(M, jnp.int32(2**31 - 1)).at[:n].set(uniq)
            # compare_all: the default 'scan' method lowers to a sequential
            # log(M) loop — poor fit for the TPU's wide vector units
            slot = jnp.searchsorted(uniq, node,
                                    method="compare_all").astype(jnp.int32)
        else:
            uniq = jnp.arange(M, dtype=jnp.int32)
            slot = node

        def node_onehot(slot_v, rows: int):
            """(rows, Mh) one-hot — full slots, or left children only."""
            if sib:
                oh = (((slot_v // 2)[:, None] == jnp.arange(Mh)[None, :])
                      & (slot_v % 2 == 0)[:, None])
            else:
                oh = slot_v[:, None] == jnp.arange(Mh)[None, :]
            return oh.astype(hdt)

        if seg and not sib and Mh <= SEG_MAX_SLOTS:
            hists = _seg_level_hists(binned_seg, slot, chans, Mh, B, d)
        elif csr is not None and not sib and Mh <= SPARSE_MAX_SLOTS:
            hists = _sparse_level_hists(csr[0], csr[1], csr[2], slot,
                                        chans, Mh, B, hdt, dot_prec)
        elif blocked:
            slot_blk = jnp.pad(slot, (0, n_pad - n)).reshape(
                n_blocks, ROW_BLOCK)

            def hist_block(acc, xs):
                slot_b, binned_b, ch_b = xs
                oh_bins = bins_onehot(binned_b)            # (RB, B·d)
                oh_node = node_onehot(slot_b, ROW_BLOCK)   # (RB, Mh)
                ch_h = ch_b.astype(hdt)
                # all channels in ONE dot: separate per-channel dots re-read
                # the (RB, B·D) bins one-hot — the stream that IS the
                # kernel's bandwidth floor — nchan times from HBM
                wnode = jnp.concatenate(
                    [oh_node * ch_h[:, c][:, None] for c in range(nchan)],
                    axis=1)                            # (RB, nchan·Mh)
                part = jax.lax.dot(wnode.T, oh_bins,
                                   precision=dot_prec,
                                   preferred_element_type=adt)
                return acc + part.reshape(nchan, Mh, B * d), None

            acc0 = jnp.zeros((nchan, Mh, B * d), adt)
            hist_stack, _ = lax.scan(
                hist_block, acc0, (slot_blk, binned_blk, chans_blk))
            hists = [hist_stack[c].reshape(Mh, B, d) for c in range(nchan)]
        else:
            onehot_node = node_onehot(slot, n)            # (N, Mh)
            wnode = jnp.concatenate(
                [onehot_node * ch.astype(hdt)[:, None] for ch in chans],
                axis=1)                               # (N, nchan·Mh)
            hist_all = jax.lax.dot(
                wnode.T, onehot_bins, precision=dot_prec,
                preferred_element_type=adt)           # (nchan·Mh, B·D)
            hists = [hist_all[c * Mh:(c + 1) * Mh].reshape(Mh, B, d)
                     for c in range(nchan)]           # 2K+1 × (Mh, B, D)
        if acc_bf16:
            # upcast once per level: gain search / gating stay f32
            hists = [h.astype(jnp.float32) for h in hists]
        if all_reduce is not None:
            # ICI collective replaces Spark's treeAggregate / Rabit allreduce
            # (channel reduction also means fewer collectives per level)
            hists = [all_reduce(h) for h in hists]
        cums_h = [jnp.cumsum(h, axis=1) for h in hists]
        if sib:
            # interleave left cumsums with (parent − left) right cumsums
            cums = [jnp.stack([lc, pc - lc], axis=1).reshape(M, B, d)
                    for lc, pc in zip(cums_h, prev_cums)]
        else:
            cums = cums_h
        # retain for the next level only when it will engage the sibling path
        prev_cums = cums if (level + 1 < max_depth
                             and 2 * level_nodes <= n_cap
                             and 2 * M >= SIBLING_MIN_SLOTS) else None
        if bag_mode == "onehot":
            CL = cums[-1]
            GLs = list(cums[: k - 1])
            GLs.append(CL - sum(GLs) if GLs else CL)
            HLs = [CL] * k
        elif bag_mode == "bagged":
            CL = cums[-1]
            GLs = list(cums[:k])
            HLs = [CL] * k
        elif bag_mode == "newton":
            GLs = list(cums[:k])
            HLs = list(cums[k:2 * k])
            CL = HLs[0]   # hessian mass stands in; gating inert (min_inst 0)
        else:
            CL = cums[-1]
            GLs = list(cums[:k])
            HLs = list(cums[k:2 * k])

        if level in leaf_levels:
            # depth-``level`` truncation leaves: per-node value sums are the
            # histograms' full-bin totals (feature 0's column — every row of
            # a node lands in exactly one bin of any feature), so the
            # snapshot costs no extra data pass
            Gs_n = jnp.stack([GL[:, -1, 0] for GL in GLs], axis=1)  # (M, K)
            Hs_n = jnp.stack([HL[:, -1, 0] for HL in HLs], axis=1)
            Cs_n = cums[-1][:, -1, 0]                               # (M,)
            snap = jnp.where(newton_leaf,
                             -learning_rate * Gs_n / (Hs_n + lam),
                             Gs_n / jnp.maximum(Cs_n, 1e-12)[:, None])
            if compact:
                snap = jnp.zeros((level_nodes, k), jnp.float32).at[uniq].set(
                    snap, mode="drop")
            leaf_snaps.append(snap)

        gain = 0.0
        HLmin = jnp.inf
        HRmin = jnp.inf
        if bundle_end is not None:
            # EFB interval splits: right = bins in (t, E(t)] — the owner
            # member's remaining bins; left = everything else (other
            # members + the shared default bin).  Unbundled columns carry
            # E = B-1, collapsing to the standard form bit-for-bit.
            # Entries with E = B-1 (unbundled columns, and a bundle's
            # LAST member) compute the STANDARD arithmetic (Gtot - GL)
            # rather than GL[E] - GL: the two agree exactly in real
            # arithmetic but differ by f32 cumsum rounding, and that
            # last-ulp noise would break gain-PLATEAU ties (thresholds
            # spanning empty bins) differently from the unbundled
            # program — the bit-for-tree contract hinges on it.
            Eb = jnp.broadcast_to(bundle_end[None], (M, B, d))
            is_std = Eb == (B - 1)

            def right_interval(A):
                return jnp.take_along_axis(A, Eb, axis=1) - A

            for GL, HL in zip(GLs, HLs):
                Gtot = GL[:, -1:, :1]
                Htot = HL[:, -1:, :1]
                GR = jnp.where(is_std, Gtot - GL, right_interval(GL))
                HR = jnp.where(is_std, Htot - HL, right_interval(HL))
                GLft = jnp.where(is_std, GL, Gtot - GR)
                HLft = jnp.where(is_std, HL, Htot - HR)
                gain = gain + (GLft ** 2 / (HLft + lam)
                               + GR ** 2 / (HR + lam)
                               - Gtot ** 2 / (Htot + lam))
                HLmin = jnp.minimum(HLmin, HLft)
                HRmin = jnp.minimum(HRmin, HR)
            Ctot = CL[:, -1:, :1]
            CR = jnp.where(is_std, Ctot - CL, right_interval(CL))
            CLft = jnp.where(is_std, CL, Ctot - CR)
        else:
            for GL, HL in zip(GLs, HLs):
                Gtot = GL[:, -1:, :1]
                Htot = HL[:, -1:, :1]
                GR, HR = Gtot - GL, Htot - HL
                gain = gain + (GL ** 2 / (HL + lam) + GR ** 2 / (HR + lam)
                               - Gtot ** 2 / (Htot + lam))
                HLmin = jnp.minimum(HLmin, HL)
                HRmin = jnp.minimum(HRmin, HR)
            Ctot = CL[:, -1:, :1]
            CR = Ctot - CL
            CLft = CL

        valid = ((HLmin >= min_child_weight) & (HRmin >= min_child_weight)
                 & (CLft >= min_instances) & (CR >= min_instances)
                 & (jnp.arange(B)[None, :, None] < B - 1)
                 & feat_mask[None, None, :])
        node_w = jnp.maximum(Ctot[:, 0, 0], 1e-12)
        gain = jnp.where(valid, gain, -jnp.inf)      # (M, B, D)
        flat_gain = gain.reshape(M, B * d)

        if default_dir:
            # XGBoost default-direction (missing/sparse) splits: variant b
            # routes the bin-0 (missing/absent) mass RIGHT — its cumsums
            # are the plain ones minus the bin-0 row — a per-(node, t,
            # feature) 2-way gain compare, exactly the C++ core's
            # enumerate-both-directions loop (OpXGBoostClassifier.scala:47
            # wraps those semantics).  Encoded as a NEGATIVE threshold
            # -(t+1) so heap shapes/persistence are unchanged.  ``dd_mask``
            # (from the caller's bin edges) limits variant b to features
            # whose bin 0 IS a genuine missing/zero bucket (first edge
            # pinned at 0.0 by the sparse-aware sketch): on a dense
            # feature, bin 0 is just the lowest quantile, and routing it
            # with the high side would fabricate non-contiguous splits real
            # XGBoost cannot produce (code-review r5).
            gain_b = 0.0
            HLbmin = jnp.inf
            HRbmin = jnp.inf
            for GL, HL in zip(GLs, HLs):
                Gtot = GL[:, -1:, :1]
                Htot = HL[:, -1:, :1]
                GLb, HLb = GL - GL[:, 0:1, :], HL - HL[:, 0:1, :]
                GRb, HRb = Gtot - GLb, Htot - HLb
                gain_b = gain_b + (GLb ** 2 / (HLb + lam)
                                   + GRb ** 2 / (HRb + lam)
                                   - Gtot ** 2 / (Htot + lam))
                HLbmin = jnp.minimum(HLbmin, HLb)
                HRbmin = jnp.minimum(HRbmin, HRb)
            c0 = CL[:, 0:1, :]
            CLb = CL - c0
            CRb = Ctot - CLb
            valid_b = ((HLbmin >= min_child_weight)
                       & (HRbmin >= min_child_weight)
                       & (CLb >= min_instances) & (CRb >= min_instances)
                       & (jnp.arange(B)[None, :, None] < B - 1)
                       & feat_mask[None, None, :]
                       & (c0 > 0))        # no bin-0 mass -> b duplicates a
            if dd_mask is not None:
                valid_b = valid_b & dd_mask[None, None, :]
            gain_b = jnp.where(valid_b, gain_b, -jnp.inf)
            flat_gain = jnp.concatenate(
                [flat_gain, gain_b.reshape(M, B * d)], axis=1)  # (M, 2Bd)

        best = jnp.argmax(flat_gain, axis=1)
        best_gain = jnp.take_along_axis(flat_gain, best[:, None], 1)[:, 0]
        # depth_limit is a TRACED scalar: trees of different requested depths
        # share one compiled program (one XLA compile per sweep, not one per
        # distinct max_depth); levels at/past the limit emit no splits
        ok = ((best_gain > 0) & (best_gain / node_w >= min_info_gain)
              & jnp.isfinite(best_gain) & (level < depth_limit))
        if min_gain_raw is not None:
            # XGBoost's gamma thresholds the RAW loss-reduction, unlike
            # Spark's per-node-weight minInfoGain
            ok = ok & (best_gain >= min_gain_raw)
        if default_dir:
            is_b = best >= B * d
            bloc = best - jnp.where(is_b, B * d, 0)
            t_raw = (bloc // d).astype(jnp.int32)
            feat_l = jnp.where(ok, bloc % d, 0).astype(jnp.int32)
            thresh_l = jnp.where(
                ok, jnp.where(is_b, -(t_raw + 1), t_raw), B
            ).astype(jnp.int32)
        else:
            feat_l = jnp.where(ok, best % d, 0).astype(jnp.int32)
            thresh_l = jnp.where(ok, best // d, B).astype(jnp.int32)

        if compact:
            # write per-slot results back to the level's heap segment at the
            # slots' true node ids; INT32_MAX padding slots drop out of range
            seg_feat = jnp.zeros(level_nodes, jnp.int32)
            seg_thresh = jnp.full(level_nodes, B, jnp.int32)
            seg_feat = seg_feat.at[uniq].set(feat_l, mode="drop")
            seg_thresh = seg_thresh.at[uniq].set(thresh_l, mode="drop")
        else:
            seg_feat, seg_thresh = feat_l, thresh_l
        heap_feat_levels.append(seg_feat)
        heap_thresh_levels.append(seg_thresh)

        # routing reads the FULL-width matrix: subset-local split ids map
        # through feat_idx (no msub-wide gathered copy exists anymore)
        fid = feat_idx[feat_l] if feat_idx is not None else feat_l
        x_row = jnp.take_along_axis(binned_full, fid[slot][:, None], 1)[:, 0]
        tv = thresh_l[slot]
        go_right = _route_right(x_row, tv)
        if bundle_end is not None:
            # interval cap: rows past the owner member's end bin belong
            # to OTHER members of the bundle and route left (flat gather:
            # 2-D advanced indexing miscompiles at some shapes, see
            # predict_ensemble)
            ev = bundle_end.reshape(-1)[
                jnp.clip(tv, 0, B - 1) * d + fid[slot]]
            go_right = go_right & (x_row <= ev)
        node = 2 * node + go_right.astype(jnp.int32)

    # heap layout: level l occupies slots [2^l - 1, 2^{l+1} - 1)
    heap_feat = jnp.concatenate(heap_feat_levels)
    heap_thresh = jnp.concatenate(heap_thresh_levels)
    if feat_idx is not None:
        # map subset-local feature ids back to the full feature space
        # (no-split nodes keep thresh == B, which routes every row left
        # regardless of the mapped feature id)
        heap_feat = feat_idx[heap_feat]

    n_leaves = 2 ** max_depth
    if n * n_leaves <= (64 << 20):
        # leaf sums as one-hot matmuls (same scatter-avoidance as histograms)
        onehot_leaf = (node[:, None] == jnp.arange(n_leaves)[None, :]
                       ).astype(jnp.float32)          # (N, 2^d)
        stacked = jnp.concatenate([G, H, C[:, None]], axis=1)  # (N, 2K+1)
        sums = jax.lax.dot(onehot_leaf.T, stacked,
                           precision=jax.lax.Precision.HIGHEST)
        Gs, Hs, Cs = sums[:, :k], sums[:, k:2 * k], sums[:, 2 * k]
    else:  # one-hot too large for very deep trees; scatter scales with N
        Gs = jnp.zeros((n_leaves, k), jnp.float32).at[node].add(G)
        Hs = jnp.zeros((n_leaves, k), jnp.float32).at[node].add(H)
        Cs = jnp.zeros((n_leaves,), jnp.float32).at[node].add(C)
    if all_reduce is not None:
        Gs, Hs, Cs = all_reduce(Gs), all_reduce(Hs), all_reduce(Cs)
    newton_val = -learning_rate * Gs / (Hs + lam)
    mean_val = Gs / jnp.maximum(Cs, 1e-12)[:, None]
    leaf = jnp.where(newton_leaf, newton_val, mean_val)
    return heap_feat, heap_thresh, leaf, tuple(leaf_snaps)


@functools.partial(jax.jit,
                   static_argnames=("max_depth", "n_bins", "hist_bf16",
                                    "seg_hist", "default_dir", "goss",
                                    "acc_bf16"))
def _grow_chunk(binned, G, H, C, feat_mask, depth_limit, max_depth: int,
                n_bins: int, lam, min_child_weight, min_info_gain,
                min_instances, newton_leaf, learning_rate,
                hist_bf16: bool = False, min_gain_raw=0.0, csr=None,
                seg_hist: bool = False, default_dir: bool = False,
                dd_mask=None, bundle_end=None, acc_bf16: bool = False,
                goss=None, goss_key=None):
    """Grow a chunk of trees in one XLA program.

    binned (N, D) shared; G/H (T, N, K), C (T, N), feat_mask (T, D),
    depth_limit (T,) traced per-tree effective depth.
    Returns (feat (T, 2^d-1), thresh (T, 2^d-1), leaf (T, 2^d, K)).
    ``goss``: static (k_top, k_rest) GOSS budget — each tree then grows
    on its own gradient-selected row gather (``goss_key`` folded per
    tree), with csr/seg paths declined by the callers.
    """
    kw = dict(max_depth=max_depth, n_bins=n_bins,
              lam=lam, min_child_weight=min_child_weight,
              min_info_gain=min_info_gain, min_instances=min_instances,
              newton_leaf=newton_leaf, learning_rate=learning_rate,
              hist_bf16=hist_bf16, min_gain_raw=min_gain_raw, csr=csr,
              seg_hist=seg_hist, default_dir=default_dir, dd_mask=dd_mask,
              bundle_end=bundle_end, acc_bf16=acc_bf16)
    if goss is not None:
        k_top, k_rest = goss

        def one(g, h, c, m, lim, tid):
            ga = jnp.sum(jnp.abs(g), axis=1)
            idx, mult = _goss_select(ga, jax.random.fold_in(goss_key, tid),
                                     k_top, k_rest)
            f, t, lf, _ = _grow_tree_traced(
                binned[idx], g[idx] * mult[:, None],
                h[idx] * mult[:, None], c[idx] * mult, m, lim, **kw)
            return f, t, lf

        return jax.vmap(one)(G, H, C, feat_mask, depth_limit,
                             jnp.arange(G.shape[0]))
    fn = functools.partial(_grow_tree_traced, binned, **kw)
    f, t, lf, _ = jax.vmap(fn)(G, H, C, feat_mask, depth_limit)
    return f, t, lf


@functools.partial(jax.jit,
                   static_argnames=("max_depth", "n_bins", "hist_bf16",
                                    "onehot_targets"))
def _grow_chunk_bagged(binned, Y, BW, feat_mask, depth_limit, max_depth: int,
                       n_bins: int, lam, min_child_weight, min_info_gain,
                       min_instances, newton_leaf, learning_rate,
                       hist_bf16: bool = False,
                       onehot_targets: bool = False, feat_idx=None):
    """Bagged-forest chunk: G/H derived from the (C, N) bag weights and the
    shared (N, K) targets *inside* the jit, so the (C, N, K) gradient
    tensors exist only transiently per launch (fused by XLA), never as
    host-built arrays — peak memory stays bounded by the chunk budget.
    ``onehot_targets`` (classification) activates the reduced-channel
    histogram path (see _grow_tree_traced bag_mode)."""
    G = BW[:, :, None] * Y[None, :, :]
    H = jnp.broadcast_to(BW[:, :, None], G.shape)
    kw = dict(max_depth=max_depth, n_bins=n_bins,
              lam=lam, min_child_weight=min_child_weight,
              min_info_gain=min_info_gain, min_instances=min_instances,
              newton_leaf=newton_leaf, learning_rate=learning_rate,
              hist_bf16=hist_bf16,
              bag_mode="onehot" if onehot_targets else "bagged")
    if feat_idx is not None:
        f, t, lf, _ = jax.vmap(lambda g, h, c, m, lim, fi: _grow_tree_traced(
            binned, g, h, c, m, lim, feat_idx=fi, **kw))(
            G, H, BW, feat_mask, depth_limit, feat_idx)
        return f, t, lf
    fn = functools.partial(_grow_tree_traced, binned, **kw)
    f, t, lf, _ = jax.vmap(fn)(G, H, BW, feat_mask, depth_limit)
    return f, t, lf


#: HBM budget for a chunk's histogram buffers — bounds vmap width.  Sized for
#: a 16 GB v5e chip: deep trees must still batch several per launch, because
#: each launch pays the host↔device dispatch round trip (expensive through a
#: remote tunnel) — launches, not FLOPs, dominate small-data deep forests.
HIST_BYTES_BUDGET = 4 << 30


def forest_chunk_size(n_trees: int, max_depth: int, d: int, n_bins: int,
                      k: int, budget: int = HIST_BYTES_BUDGET,
                      n_rows: Optional[int] = None,
                      compact: bool = True,
                      n_channels: Optional[int] = None,
                      d_full: Optional[int] = None,
                      onehot_bytes: int = 4) -> int:
    # node compaction caps a level's histogram slots at next_pow2(n_rows);
    # 1.3x covers the 128-lane padding of the minor (feature) axis.
    # compact=False is the all-reduce (mesh-sharded) path, which keeps the
    # full 2^level slot layout so every shard agrees on histogram indices.
    # ``d`` is the HISTOGRAM width (= msub on the feature-subset path);
    # ``n_channels`` overrides the default 2K+1 when the reduced-channel
    # bagged path is active; ``d_full`` adds the per-tree gathered binned
    # copy the subset path materializes; ``onehot_bytes`` is 2 when the
    # one-hot operands materialize bf16 (hist_bf16).
    nchan = n_channels if n_channels is not None else 2 * k + 1
    slots = 2 ** (max_depth - 1)
    if n_rows is not None and compact:
        slots = min(slots, 1 << int(np.ceil(np.log2(max(n_rows, 2)))))
    # sibling subtraction retains the parent level's cumsums alongside the
    # current level's: ~1.5x the histogram-buffer peak at engaged depths
    sib_factor = 1.5 if slots >= SIBLING_MIN_SLOTS else 1.0
    per_tree = int(slots * d * n_bins * nchan * 4 * 1.3 * sib_factor)
    if n_rows is not None:
        # matmul-histogram operands live per tree under vmap: the per-block
        # (rows, slots) node one-hot and (rows, B·D) bins one-hot (rows
        # streamed in ROW_BLOCK chunks past that size), plus the (rows, K)
        # G/H gradient channels and bag-weight row derived per tree
        rows = min(n_rows, ROW_BLOCK)
        per_tree += int(rows * slots * onehot_bytes * 1.3)
        if n_rows > ROW_BLOCK:
            per_tree += int(rows * n_bins * d * onehot_bytes * 1.3)
        per_tree += int(n_rows * (2 * k + 1) * 4)
        if d_full is not None and d_full != d:
            # the per-tree (rows, msub) int32 gather of the binned matrix
            per_tree += int(n_rows * d * 4)
    return int(np.clip(budget // max(per_tree, 1), 1, n_trees))


def grow_forest(binned: jnp.ndarray, Y: np.ndarray, BW: np.ndarray,
                feat_mask: np.ndarray, max_depth: int,
                n_bins: int, lam: float = 1.0,
                min_child_weight: float = 0.0, min_info_gain: float = 0.0,
                min_instances: float = 1.0, newton_leaf: bool = False,
                learning_rate: float = 1.0, as_numpy: bool = True,
                onehot_targets: bool = False,
                ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Grow ``T`` independent bagged trees in ceil(T/chunk) XLA launches.

    ``Y`` (N, K) shared targets; ``BW`` (T, N) per-tree bag weights;
    gradients are derived per chunk inside the jit (``_grow_chunk_bagged``)
    so peak HBM is bounded by ``HIST_BYTES_BUDGET`` regardless of T.  The
    trailing partial chunk is zero-weight padded to the same shape so every
    launch reuses one compiled program; padded trees are sliced off.
    """
    T, n = BW.shape
    d = binned.shape[1]
    Yj = jnp.asarray(Y, jnp.float32)
    k = Yj.shape[1]
    heap_depth = _resolve_compile_depth(max_depth)
    chunk = forest_chunk_size(T, heap_depth, d, n_bins, k, n_rows=n)
    args = (jnp.float32(lam), jnp.float32(min_child_weight),
            jnp.float32(min_info_gain), jnp.float32(min_instances),
            jnp.bool_(newton_leaf), jnp.float32(learning_rate))
    BW = np.asarray(BW, np.float32)
    feat_mask = np.asarray(feat_mask, bool)
    limit = jnp.full((chunk,), max_depth, jnp.int32)
    feats, threshs, leaves = [], [], []
    from ..utils.profiling import count_launch

    for s in range(0, T, chunk):
        count_launch("forest_chunk")
        e = min(s + chunk, T)
        pad = chunk - (e - s)
        BWc = jnp.asarray(np.pad(BW[s:e], ((0, pad), (0, 0))))
        Mc = jnp.asarray(np.pad(feat_mask[s:e], ((0, pad), (0, 0))))
        f, t, lf = _grow_chunk_bagged(binned, Yj, BWc, Mc, limit, heap_depth,
                                      n_bins, *args,
                                      onehot_targets=onehot_targets)
        if as_numpy:
            f, t, lf = np.asarray(f), np.asarray(t), np.asarray(lf)
        feats.append(f[:e - s])
        threshs.append(t[:e - s])
        leaves.append(lf[:e - s])
    if as_numpy:
        # host-side concat: a device concatenate costs a ~5 s remote compile
        return (np.concatenate(feats), np.concatenate(threshs),
                np.concatenate(leaves))
    if len(feats) == 1:
        return feats[0], threshs[0], leaves[0]
    return (jnp.concatenate(feats), jnp.concatenate(threshs),
            jnp.concatenate(leaves))


def _rf_bag_and_features(tid, seed, n: int, d: int, msub: int,
                         subsample_rate):
    """Per-tree Poisson bag weights + feature-subset indices from
    ``fold_in(seed, tree_id)`` — THE single definition of RF randomness,
    shared by the single-device on-device generator and the mesh path so
    both grow identical forests."""
    key = jax.random.fold_in(jax.random.PRNGKey(seed), tid)
    kb, km = jax.random.split(key)
    bw = jax.random.poisson(kb, subsample_rate, (n,)).astype(jnp.float32)
    r = jax.random.uniform(km, (d,))
    # the msub smallest ranks — the same SET as the mask form (r <= kth),
    # as indices so the histogram runs at width msub
    idx = jnp.argsort(r)[:msub].astype(jnp.int32)
    return bw, idx


def rf_bags_and_features(seed: int, n_trees: int, n: int, d: int, msub: int,
                         subsample_rate: float):
    """Host copies of every tree's bag weights and feature subset (the mesh
    path shards precomputed bags; same generator as the on-device path).

    Generated on the CPU backend: running this on a remote accelerator
    would round-trip the (T, N) Poisson matrix through the tunnel (~200 MB
    at 50 trees × 1M rows) just to re-upload it sharded."""
    try:
        dev = jax.devices("cpu")[0]
    except RuntimeError:  # pragma: no cover - cpu backend always exists
        dev = None
    gen = jax.jit(jax.vmap(
        lambda tid: _rf_bag_and_features(tid, jnp.int32(seed), n, d, msub,
                                         jnp.float32(subsample_rate))))
    if dev is not None:
        with jax.default_device(dev):
            BW, idx = gen(jnp.arange(n_trees))
    else:
        BW, idx = gen(jnp.arange(n_trees))
    return np.asarray(BW), np.asarray(idx)


@functools.partial(jax.jit, static_argnames=("chunk", "msub", "max_depth",
                                             "n_bins", "onehot_targets",
                                             "hist_bf16"))
def _grow_chunk_rf(binned, Y, base_w, seed, start, n_trees, depth_limit_val,
                   subsample_rate, chunk: int, msub: int, max_depth: int,
                   n_bins: int, lam, min_child_weight, min_info_gain,
                   min_instances, learning_rate,
                   onehot_targets: bool = False, hist_bf16: bool = False):
    """RF chunk with ON-DEVICE bag-weight + feature-mask generation.

    Through a remote-TPU tunnel, uploading per-tree (T, N) Poisson weights
    and (T, D) masks per fit dominates the sweep; here the caller ships only
    ``seed``/``start`` scalars and the memoized fold data, and each tree
    derives its bag from ``fold_in(seed, tree_id)`` inside the program.
    """
    n, d = binned.shape
    tree_ids = start + jnp.arange(chunk)
    BWr, feat_idx = jax.vmap(
        lambda tid: _rf_bag_and_features(tid, seed, n, d, msub,
                                         subsample_rate))(tree_ids)
    BW = base_w[None, :] * BWr * (tree_ids < n_trees)[:, None]
    masks = jnp.ones((chunk, d), bool)  # unused on the feat_idx path
    limit = jnp.full((chunk,), depth_limit_val, jnp.int32)
    return _grow_chunk_bagged(
        binned, Y, BW, masks, limit, max_depth, n_bins, lam,
        min_child_weight, min_info_gain, min_instances,
        jnp.bool_(False), learning_rate, hist_bf16=hist_bf16,
        onehot_targets=onehot_targets, feat_idx=feat_idx)


@functools.partial(jax.jit, static_argnames=("chunk", "msub", "max_depth",
                                             "n_bins", "onehot_targets",
                                             "t_per", "leaf_levels",
                                             "hist_bf16"))
def _grow_chunk_rf_grid(binned, Y, W_tr, seed, flat_start, total,
                        pair_fold, pair_min_ig, pair_min_inst, pair_depth,
                        subsample_rate, chunk: int, msub: int,
                        max_depth: int, n_bins: int, lam,
                        min_child_weight, t_per: int,
                        onehot_targets: bool = False,
                        leaf_levels: Tuple[int, ...] = (),
                        hist_bf16: bool = False):
    """RF chunk spanning the WHOLE (candidate x fold) grid.

    Flat tree index i = pair * t_per + t: tree t of grid pair ``i // t_per``
    draws the SAME on-device bag/feature-subset stream as a sequential
    per-candidate fit (``fold_in(seed, t)``), trains against that pair's
    fold weights (``W_tr[pair_fold]``) and its traced (min_info_gain,
    min_instances, depth_limit) — so one launch stream grows every
    candidate's forest for every fold with results identical to the
    per-candidate path (same randomness, same split masking).

    ``leaf_levels`` additionally emits depth-truncation leaf snapshots per
    tree (see ``_grow_tree_traced``), which lets the caller run only the
    unique (min_info_gain, min_instances) × fold pairs at their max grid
    depth and derive every shallower max_depth candidate for free.
    """
    n, d = binned.shape
    flat = flat_start + jnp.arange(chunk)
    t_loc = (flat % t_per).astype(jnp.int32)
    p_idx = jnp.minimum(flat // t_per, pair_fold.shape[0] - 1)
    BWr, feat_idx = jax.vmap(
        lambda tid: _rf_bag_and_features(tid, seed, n, d, msub,
                                         subsample_rate))(t_loc)
    base_w = W_tr[pair_fold[p_idx]]                       # (chunk, N)
    BW = base_w * BWr * (flat < total)[:, None]
    kw = dict(max_depth=max_depth, n_bins=n_bins, lam=lam,
              min_child_weight=min_child_weight, newton_leaf=jnp.bool_(False),
              learning_rate=jnp.float32(1.0), hist_bf16=hist_bf16,
              bag_mode="onehot" if onehot_targets else "bagged",
              leaf_levels=leaf_levels)

    def one(bw_row, mig, mins, lim, fi):
        g = bw_row[:, None] * Y
        h = jnp.broadcast_to(bw_row[:, None], g.shape)
        return _grow_tree_traced(
            binned, g, h, bw_row, jnp.ones(d, bool), lim,
            min_info_gain=mig, min_instances=mins, feat_idx=fi, **kw)

    return jax.vmap(one)(BW, pair_min_ig[p_idx], pair_min_inst[p_idx],
                         pair_depth[p_idx], feat_idx)


def grow_rf_grid(binned, Y, W_tr, seed: int, n_trees: int,
                 pair_fold: np.ndarray, pair_min_ig: np.ndarray,
                 pair_min_inst: np.ndarray, pair_depth: np.ndarray,
                 msub: int, subsample_rate: float, n_bins: int,
                 lam: float = 1e-3, min_child_weight: float = 0.0,
                 onehot_targets: bool = False,
                 leaf_levels: Tuple[int, ...] = ()):
    """Grow every (candidate x fold) pair's forest as one chunked launch
    stream; returns device (P, T, nodes...) stacked ensembles.

    With ``leaf_levels``, additionally returns ``{level: (P, T, 2^level, K)}``
    depth-truncation leaf snapshots — the caller then needs only the unique
    (min_info_gain, min_instances) × fold pairs grown at their deepest grid
    depth, deriving each shallower max_depth candidate by truncation (exact
    for level-wise growth; splits at a level never depend on deeper ones).
    """
    n, d = binned.shape
    k = Y.shape[1]
    P = int(pair_fold.shape[0])
    # >= 1: an all-stump grid (every max_depth <= 0) still needs one heap
    # level to emit leaf arrays (depth_limit 0 keeps the trees split-free)
    heap_depth = _resolve_compile_depth(max(int(pair_depth.max()), 1))
    leaf_levels = tuple(sorted(set(int(v) for v in leaf_levels
                                   if 0 < int(v) < heap_depth)))
    hist_bf16 = _accel_bf16()
    chunk = forest_chunk_size(
        n_trees * P, heap_depth, msub, n_bins, k, n_rows=n,
        n_channels=(k if onehot_targets else k + 1), d_full=d,
        onehot_bytes=2 if hist_bf16 else 4)
    total = n_trees * P
    pf = jnp.asarray(pair_fold, jnp.int32)
    pg = jnp.asarray(pair_min_ig, jnp.float32)
    pi = jnp.asarray(pair_min_inst, jnp.float32)
    pd_ = jnp.asarray(pair_depth, jnp.int32)
    from ..utils.profiling import count_launch

    feats, threshs, leaves = [], [], []
    snaps: List[list] = [[] for _ in leaf_levels]
    for s in range(0, total, chunk):
        count_launch("rf_grid_chunk")
        f, t, lf, sn = _grow_chunk_rf_grid(
            binned, Y, W_tr, jnp.int32(seed), jnp.int32(s), jnp.int32(total),
            pf, pg, pi, pd_, jnp.float32(subsample_rate), chunk, msub,
            heap_depth, n_bins, jnp.float32(lam),
            jnp.float32(min_child_weight), n_trees,
            onehot_targets=onehot_targets, leaf_levels=leaf_levels,
            hist_bf16=hist_bf16)
        e = min(s + chunk, total)
        feats.append(f[:e - s])
        threshs.append(t[:e - s])
        leaves.append(lf[:e - s])
        for li, sv in enumerate(sn):
            snaps[li].append(sv[:e - s])
    if len(feats) > 1:
        feats = jnp.concatenate(feats)
        threshs = jnp.concatenate(threshs)
        leaves = jnp.concatenate(leaves)
        snaps = [jnp.concatenate(sv) for sv in snaps]
    else:
        feats, threshs, leaves = feats[0], threshs[0], leaves[0]
        snaps = [sv[0] for sv in snaps]
    nodes = feats.shape[1]
    out = (feats.reshape(P, n_trees, nodes),
           threshs.reshape(P, n_trees, nodes),
           leaves.reshape(P, n_trees, *leaves.shape[1:]))
    if not leaf_levels:
        return out
    snap_map = {lv: sv.reshape(P, n_trees, *sv.shape[1:])
                for lv, sv in zip(leaf_levels, snaps)}
    return (*out, snap_map)


def grow_forest_rf(binned, Y, base_w, seed: int, n_trees: int, msub: int,
                   subsample_rate: float, max_depth: int, n_bins: int,
                   lam: float = 1e-3, min_child_weight: float = 0.0,
                   min_info_gain: float = 0.0, min_instances: float = 1.0,
                   onehot_targets: bool = False):
    """Bagged random forest, bags generated on device (see _grow_chunk_rf).

    Returns device (T, 2^hd-1) feat/thresh and (T, 2^hd, K) leaves, where hd
    is the shared compile depth (``compile_depth_hint``)."""
    n, d = binned.shape
    k = Y.shape[1]
    heap_depth = _resolve_compile_depth(max_depth)
    hist_bf16 = _accel_bf16()
    # feat_idx path: histograms at width msub with the reduced channel
    # count (K for one-hot classification, K+1 for bagged regression)
    chunk = forest_chunk_size(
        n_trees, heap_depth, msub, n_bins, k, n_rows=n,
        n_channels=(k if onehot_targets else k + 1), d_full=d,
        onehot_bytes=2 if hist_bf16 else 4)
    args = (jnp.float32(lam), jnp.float32(min_child_weight),
            jnp.float32(min_info_gain), jnp.float32(min_instances),
            jnp.float32(1.0))
    from ..utils.profiling import count_launch

    feats, threshs, leaves = [], [], []
    for s in range(0, n_trees, chunk):
        count_launch("rf_chunk")
        f, t, lf = _grow_chunk_rf(
            binned, Y, base_w, jnp.int32(seed), jnp.int32(s),
            jnp.int32(n_trees), jnp.int32(max_depth),
            jnp.float32(subsample_rate), chunk, msub, heap_depth, n_bins,
            *args, onehot_targets=onehot_targets, hist_bf16=hist_bf16)
        e = min(s + chunk, n_trees)
        if e - s < chunk:
            f, t, lf = f[:e - s], t[:e - s], lf[:e - s]
        feats.append(f)
        threshs.append(t)
        leaves.append(lf)
    if len(feats) == 1:
        return feats[0], threshs[0], leaves[0]
    return (jnp.concatenate(feats), jnp.concatenate(threshs),
            jnp.concatenate(leaves))


@functools.partial(jax.jit, static_argnames=("max_depth", "n_bins", "obj",
                                             "hist_bf16"))
def _gbt_chain_round_jit(binned, y, W, Fm, depth_lim, lams, mcws, migs,
                         mins_, lrs, mgrs, max_depth: int, n_bins: int,
                         obj: str, hist_bf16: bool = False):
    """One boosting round for a chunk of chains: gradients from each
    chain's margins + ONE vmapped growth (the bins one-hot is chain-
    invariant, so XLA builds it once per row block for every chain's
    histogram dots)."""
    n, d = binned.shape
    if obj == "binary":
        P = jax.nn.sigmoid(Fm)                       # (S, N)
        G = W * (P - y[None, :])
        H = W * jnp.maximum(P * (1 - P), 1e-6)
    else:
        G = W * (Fm - y[None, :])
        H = W
    mask = jnp.ones(d, bool)

    def one(g, h, c, lim, lam, mcw, mig, mi, lr, mgr):
        return _grow_tree_traced(
            binned, g[:, None], h[:, None], c, mask, lim,
            max_depth=max_depth, n_bins=n_bins, lam=lam,
            min_child_weight=mcw, min_info_gain=mig, min_instances=mi,
            newton_leaf=jnp.bool_(True), learning_rate=lr,
            hist_bf16=hist_bf16, min_gain_raw=mgr)[:3]

    return jax.vmap(one)(G, H, W, depth_lim, lams, mcws, migs, mins_,
                         lrs, mgrs)


@functools.partial(jax.jit, static_argnames=("n_rounds", "max_depth",
                                             "n_bins", "obj", "hist_bf16",
                                             "use_es", "skip_counts",
                                             "seg_hist", "default_dir",
                                             "goss", "acc_bf16"))
def _gbt_chain_rounds_jit(binned, y, W, Fm0, vi, depth_lim, lams, mcws,
                          migs, mins_, lrs, mgrs, n_rounds: int,
                          max_depth: int, n_bins: int, obj: str,
                          hist_bf16: bool = False, use_es: bool = False,
                          csr=None, skip_counts: bool = False,
                          seg_hist: bool = False, default_dir: bool = False,
                          dd_mask=None, bundle_end=None,
                          acc_bf16: bool = False, goss=None,
                          goss_seed=None, chain_ids=None,
                          round_offset=None):
    """``n_rounds`` boosting rounds for a chunk of chains in ONE launch.

    ``lax.scan`` over rounds (body compiled once) carries the (S, N)
    margins and stacks each round's trees + per-chain ES metric — through a
    remote-device tunnel the per-round dispatch was the dominant cost
    (measured ~390 ms/round vs ~120 ms device compute at 100k x 500), and
    the scan leaves ONE dispatch (and one lagged metric fetch) per
    ``es_chunk`` of rounds.  Returns (Fm_end, feats (R, S, nodes), threshs,
    leaves (R, S, L, K), metrics (R, S)).

    ``bundle_end``: EFB member-end table — ``binned`` is then the BUNDLED
    matrix; growth, routing and margin updates all run in bundled space
    (the caller unbundles the returned trees before persisting/scoring
    outside this launch).  ``goss`` (static (k_top, k_rest)): each chain
    grows its round tree on a gradient-selected row gather, seeded
    ``fold_in(fold_in(PRNGKey(goss_seed), round_id), chain_id)`` with
    GLOBAL chain ids (``chain_ids``) and the global round offset
    (``round_offset``), so results are invariant to chunking."""
    n, d = binned.shape
    mask = jnp.ones(d, bool)
    grow_kw = dict(max_depth=max_depth, n_bins=n_bins,
                   newton_leaf=jnp.bool_(True), hist_bf16=hist_bf16,
                   bag_mode="newton" if skip_counts else "none",
                   default_dir=default_dir, dd_mask=dd_mask,
                   bundle_end=bundle_end, acc_bf16=acc_bf16)

    def round_step(Fm, rid):
        if obj == "binary":
            P = jax.nn.sigmoid(Fm)                   # (S, N)
            G = W * (P - y[None, :])
            H = W * jnp.maximum(P * (1 - P), 1e-6)
        else:
            G = W * (Fm - y[None, :])
            H = W

        if goss is not None:
            k_top, k_rest = goss

            def one(g, h, c, lim, lam, mcw, mig, mi, lr, mgr, cid):
                key = jax.random.fold_in(jax.random.fold_in(
                    jax.random.PRNGKey(goss_seed), rid), cid)
                idx, mult = _goss_select(jnp.abs(g), key, k_top, k_rest)
                return _grow_tree_traced(
                    binned[idx], (g[idx] * mult)[:, None],
                    (h[idx] * mult)[:, None], c[idx] * mult, mask, lim,
                    lam=lam, min_child_weight=mcw, min_info_gain=mig,
                    min_instances=mi, learning_rate=lr, min_gain_raw=mgr,
                    **grow_kw)[:3]

            f, t, lf = jax.vmap(one)(G, H, W, depth_lim, lams, mcws, migs,
                                     mins_, lrs, mgrs, chain_ids)
        else:
            def one(g, h, c, lim, lam, mcw, mig, mi, lr, mgr):
                return _grow_tree_traced(
                    binned, g[:, None], h[:, None], c, mask, lim,
                    lam=lam, min_child_weight=mcw, min_info_gain=mig,
                    min_instances=mi, learning_rate=lr, min_gain_raw=mgr,
                    csr=csr, seg_hist=seg_hist, **grow_kw)[:3]

            f, t, lf = jax.vmap(one)(G, H, W, depth_lim, lams, mcws, migs,
                                     mins_, lrs, mgrs)
        if bundle_end is not None:
            inc = jax.vmap(lambda ff, tt, ll: _predict_tree_bundled(
                binned, ff, tt, ll, max_depth, bundle_end))(f, t, lf)[:, :, 0]
        else:
            inc = jax.vmap(lambda ff, tt, ll: predict_tree(
                binned, ff, tt, ll, max_depth))(f, t, lf)[:, :, 0]
        Fm = Fm + inc
        if use_es:
            m = _chain_es_metric(Fm, y, vi, obj)
        else:
            m = jnp.zeros(Fm.shape[0], jnp.float32)
        return Fm, (f, t, lf, m)

    rounds = jnp.arange(n_rounds, dtype=jnp.int32)
    if round_offset is not None:
        rounds = rounds + round_offset
    Fm_end, (fs, ts, lfs, ms) = lax.scan(round_step, Fm0, rounds)
    return Fm_end, fs, ts, lfs, ms


def _chain_es_metric(Fm, y, vi, obj: str):
    """Per-chain early-stopping metric on the validation rows (trace-safe:
    shared by the standalone jit below and the in-scan round body)."""
    return _chain_es_metric_val(Fm[:, vi], y[vi], obj)


def _chain_es_metric_val(Z, yv, obj: str):
    """The metric half of ``_chain_es_metric``, over already-gathered
    (S, V) validation margins — the sharded chain kernel psum-gathers
    each shard's owned validation rows first and feeds them here, so
    both paths score with identical code."""
    if obj == "binary":
        from ..evaluators.metrics import _aupr_dev

        return jax.vmap(lambda z: _aupr_dev(yv, jax.nn.sigmoid(z)))(Z)
    return -jnp.mean((Z - yv[None, :]) ** 2, axis=1)


@functools.partial(jax.jit, static_argnames=("max_depth",))
def _predict_round_jit(binned, feat, thresh, leaf, max_depth: int):
    """(S, N) margin increments for one round's chain trees."""
    out = jax.vmap(lambda f, t, lf: predict_tree(binned, f, t, lf,
                                                 max_depth))(
        feat, thresh, leaf)
    return out[:, :, 0]


_chain_es_metric_jit = jax.jit(_chain_es_metric,
                               static_argnames=("obj",))


def gbt_chain_chunk(n_chains: int, max_depth: int, d: int, n_bins: int,
                    n_rows: int, budget: int = 2 * HIST_BYTES_BUDGET,
                    seg_hist: bool = False,
                    full_slots: bool = False) -> int:
    """Chains per round launch: the (ROW_BLOCK, B*D) bins one-hot is shared
    (counted once), per-chain terms are the slot one-hot + the 3-channel
    histogram accumulator.  The budget is deliberately larger than the
    forest chunker's — splitting a round across launches re-materializes
    the shared one-hot stream, the round's dominant cost.

    ``seg_hist``: the segmented path has no shared one-hot, but each chain
    transiently holds its slot-sorted padded copy of the binned matrix
    ((N', d_pad) int8) plus the sort/align index vectors.

    ``full_slots``: the mesh-sharded chain path disables node compaction
    (shards must agree on the full 2^level slot layout), so its budget
    uses the uncompacted slot count."""
    slots = 2 ** (max_depth - 1)
    if n_rows is not None and not full_slots:
        slots = min(slots, 1 << int(np.ceil(np.log2(max(n_rows, 2)))))
    if seg_hist and slots <= SEG_MAX_SLOTS:
        d_pad = -(-d // SEG_D_BLOCK) * SEG_D_BLOCK
        n_pad = (-(-n_rows // SEG_ROW_BLOCK) + slots) * SEG_ROW_BLOCK
        per_chain = int(n_pad * d_pad * 1.3          # sorted binned copy
                        + n_pad * 8 * 4              # align index vectors
                        + slots * n_bins * d * 3 * 4 * 1.3
                        + n_rows * 4 * 4)
        return int(np.clip(budget // max(per_chain, 1), 1, n_chains))
    shared = int(min(n_rows, ROW_BLOCK) * n_bins * d * 4 * 1.3)
    per_chain = int(slots * n_bins * d * 3 * 4 * 1.3
                    + min(n_rows, ROW_BLOCK) * slots * 4 * 1.3
                    + n_rows * 4 * 4)
    return int(np.clip((budget - shared) // max(per_chain, 1), 1, n_chains))


def grow_tree(binned: jnp.ndarray, G: jnp.ndarray, H: jnp.ndarray,
              C: jnp.ndarray, max_depth: int, n_bins: int,
              lam: float = 1.0, min_child_weight: float = 0.0,
              min_info_gain: float = 0.0, min_instances: float = 1.0,
              feat_mask: Optional[jnp.ndarray] = None,
              newton_leaf: bool = True, learning_rate: float = 1.0,
              min_gain_raw: float = 0.0, hist_bf16: bool = False,
              csr=None, seg_hist: Optional[bool] = None,
              default_dir: bool = False, dd_mask=None, bundle_end=None,
              acc_bf16: Optional[bool] = None,
              goss: Optional[Tuple[int, int]] = None, goss_key=None,
              ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Grow one tree (single-tree view of ``grow_forest``): one XLA launch.

    ``bundle_end``: EFB member-end table — the matrix is then in bundled
    column space and the returned splits need ``unbundle_ensemble``.
    ``goss``/``goss_key``: static GOSS row budget + PRNG key (see
    ``goss_plan``); incompatible with csr/seg (forced off here).
    """
    n, d = binned.shape
    if feat_mask is None:
        feat_mask = jnp.ones(d, bool)
    heap_depth = _resolve_compile_depth(max_depth)
    hist_bf16 = hist_bf16 and _accel_bf16()
    if acc_bf16 is None:
        acc_bf16 = hist_accum_bf16()
    if goss is not None:
        csr, seg_hist = None, False
        if goss_key is None:
            goss_key = jax.random.PRNGKey(0)
    if seg_hist is None:
        seg_hist = seg_hist_auto(n)
    limit = jnp.full((1,), max_depth, jnp.int32)
    f, t, lf = _grow_chunk(
        binned, G[None], H[None], C[None], feat_mask[None], limit,
        heap_depth, n_bins, jnp.float32(lam), jnp.float32(min_child_weight),
        jnp.float32(min_info_gain), jnp.float32(min_instances),
        jnp.bool_(newton_leaf), jnp.float32(learning_rate),
        hist_bf16=hist_bf16, min_gain_raw=jnp.float32(min_gain_raw),
        csr=csr, seg_hist=seg_hist, default_dir=default_dir,
        dd_mask=dd_mask, bundle_end=bundle_end, acc_bf16=acc_bf16,
        goss=goss, goss_key=goss_key)
    return f[0], t[0], lf[0]


# ---------------------------------------------------------------------------
# Prediction
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("max_depth",))
def predict_tree(binned: jnp.ndarray, feat: jnp.ndarray, thresh: jnp.ndarray,
                 leaf: jnp.ndarray, max_depth: int) -> jnp.ndarray:
    """Route rows through one tree; returns (N, K) leaf values."""
    n = binned.shape[0]
    node = jnp.zeros(n, jnp.int32)

    def level(l, node):
        base = 2 ** l - 1
        heap = base + node
        f = feat[heap]
        t = thresh[heap]
        x = jnp.take_along_axis(binned, f[:, None], 1)[:, 0]
        return 2 * node + _route_right(x, t).astype(jnp.int32)

    node = lax.fori_loop(0, max_depth, level, node)
    return leaf[node]


def _predict_tree_bundled(binned, feat, thresh, leaf, max_depth: int,
                          bundle_end):
    """``predict_tree`` in BUNDLED column space: splits are per-member
    intervals, so routing right additionally requires the bin to sit at
    or below the owner member's end bin (``bundle_end``).  Used only for
    the in-launch margin updates of EFB growth — persisted trees are
    unbundled and route through the ordinary predictors."""
    n, d = binned.shape
    B = bundle_end.shape[0]
    be_f = bundle_end.reshape(-1)
    node = jnp.zeros(n, jnp.int32)

    def level(l, node):
        base = 2 ** l - 1
        heap = base + node
        f = feat[heap]
        t = thresh[heap]
        x = jnp.take_along_axis(binned, f[:, None], 1)[:, 0]
        ev = be_f[jnp.clip(t, 0, B - 1) * d + f]
        go = _route_right(x, t) & (x <= ev)
        return 2 * node + go.astype(jnp.int32)

    node = lax.fori_loop(0, max_depth, level, node)
    return leaf[node]


@functools.partial(jax.jit, static_argnames=("max_depth",))
def predict_ensemble(binned: jnp.ndarray, feat: jnp.ndarray,
                     thresh: jnp.ndarray, leaf: jnp.ndarray,
                     max_depth: int) -> jnp.ndarray:
    """Sum of all trees' outputs: feat/thresh (T, 2^d-1), leaf (T, 2^d, K).

    All trees route in parallel — ``max_depth`` sequential steps of one
    (T, N) gather each, instead of a scan over trees (T × depth serial
    steps, which left the TPU idle between tiny kernels).

    Every gather is expressed over FLATTENED operands with explicit row/
    tree offsets: the 2-D advanced-indexing forms (``feat[tree, heap]``,
    ``binned[row, f]``) MISCOMPILE on the tunneled TPU backend at some
    (T, N) shapes — deterministically wrong routing at T=166/200 × 100k
    rows while T ≤ 128 and T = 180 are fine — and the flat formulation is
    correct at every probed shape (same per-tree results as the scalar
    ``predict_tree`` and a host reference implementation).
    """
    n = binned.shape[0]
    d = binned.shape[1]
    T, nodes = feat.shape
    if n * d >= 2 ** 31:
        # flat int32 gather offsets would overflow: route rows through in
        # static chunks (shapes are trace-time constants, so this Python
        # loop unrolls into a few sub-programs — no host round trips) and
        # concatenate.  Keeps GB-scale predicts (e.g. 1M rows x 2200+
        # features) working instead of hard-failing at serving time.
        n_chunks = int(np.ceil(n * d / (2 ** 31 - 1)))
        rows = -(-n // n_chunks)
        return jnp.concatenate(
            [predict_ensemble(binned[s:s + rows], feat, thresh, leaf,
                              max_depth)
             for s in range(0, n, rows)], axis=0)
    node = jnp.zeros((T, n), jnp.int32)
    feat_f = feat.reshape(-1)
    thresh_f = thresh.reshape(-1)
    binned_f = binned.reshape(-1)
    tree_off = (jnp.arange(T, dtype=jnp.int32) * nodes)[:, None]
    row_off = (jnp.arange(n, dtype=jnp.int32) * jnp.int32(d))[None, :]

    def level(l, node):
        heap = (2 ** l - 1) + node + tree_off            # (T, N) flat ids
        f = feat_f[heap]
        t = thresh_f[heap]
        x = binned_f[row_off + f]                        # (T, N)
        return 2 * node + _route_right(x, t).astype(jnp.int32)

    node = lax.fori_loop(0, max_depth, level, node)
    # leaf-sum in tree chunks: one (T, N, K) gather would cost T·N·K·4 bytes
    # of HBM (4 GB for 512 trees × 1M rows); chunks bound it at ~32 MB
    k = leaf.shape[2]
    n_leaves = leaf.shape[1]
    leaf_f = leaf.reshape(T * n_leaves, k)
    leaf_off = (jnp.arange(T, dtype=jnp.int32) * n_leaves)[:, None]
    chunk = max(1, min(T, (32 << 20) // max(n * k * 4, 1)))
    out = jnp.zeros((n, k), jnp.float32)
    for s in range(0, T, chunk):
        e = min(s + chunk, T)
        out = out + leaf_f[node[s:e] + leaf_off[s:e]].sum(axis=0)
    return out
