"""Jitted linear-model trainers — the XLA replacement for Spark MLlib's
iterative LBFGS/OWLQN solvers.

Reference model wrappers these back:
 * OpLogisticRegression (core/.../impl/classification/OpLogisticRegression.scala:46)
 * OpLinearRegression / OpGeneralizedLinearRegression (impl/regression/:47-48)
 * OpLinearSVC (impl/classification/OpLinearSVC.scala:47)
 * OpNaiveBayes (impl/classification/OpNaiveBayes.scala:46)

TPU-first design decisions:
 * Full-batch second-order solvers: tabular designs are (N large, D moderate),
   so one Newton/IRLS step = one (D,N)@(N,D) matmul on the MXU + a (D,D)
   Cholesky solve — far fewer passes over HBM than SGD.  Elastic net runs
   exact proximal-gradient (scalar-majorizer FISTA) to the true composite
   optimum.
 * Everything is ``jax.jit``-compiled with static shapes and
   ``lax.while_loop``/``fori_loop`` control flow; the grid trainers
   (``fit_logreg_grid``, ``fit_linreg_grid``) run the WHOLE folds ×
   hyperparameter product as one program with traced reg/alpha vectors
   (no re-tracing per grid point — SURVEY §7 hard part c).
 * Sample weights everywhere: cross-validation folds are expressed as 0/1
   weight masks over one resident feature matrix, so fold training never
   reshapes or copies data (static shapes on device).
 * float32 accumulation; inputs may arrive bf16 — matmuls hit the MXU either
   way via XLA.
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

__all__ = [
    "LinearFit", "fit_logistic_regression", "fit_linear_regression",
    "fit_linear_svc", "fit_naive_bayes", "fit_multinomial_logreg",
    "logreg_predict_proba", "softmax_predict_proba",
    "linear_predict", "svc_decision", "naive_bayes_predict_log_proba",
]


class LinearFit(NamedTuple):
    """coef: (D,) or (K, D); intercept: scalar or (K,)."""
    coef: jnp.ndarray
    intercept: jnp.ndarray
    n_iter: jnp.ndarray
    converged: jnp.ndarray


def _damped_solve(H, g, rel: float = 1e-5):
    """Cholesky solve with damping scaled to the Hessian's magnitude.

    Pivoted one-hot blocks make H exactly singular when reg_param=0 (the
    indicator columns sum to the intercept column); a fixed 1e-8 jitter is
    below float32 resolution at typical diag scales, so damping is relative:
    eps = rel * max|diag(H)|.  This is a Levenberg-style modified Newton
    step — direction stays ascent-aligned, convergence unaffected.
    """
    d = H.shape[0]
    eps = rel * jnp.max(jnp.abs(jnp.diagonal(H))) + 1e-12
    return jax.scipy.linalg.solve(H + eps * jnp.eye(d, dtype=H.dtype), g,
                                  assume_a="pos")


def _finite_or(new, old):
    """Reject a non-finite update (keeps the last good iterate)."""
    ok = jnp.all(jnp.isfinite(new))
    return jnp.where(ok, new, old)


def _prep(X, y, sample_weight):
    X = jnp.asarray(X, jnp.float32)
    y = jnp.asarray(y, jnp.float32)
    if sample_weight is None:
        w = jnp.ones(X.shape[0], jnp.float32)
    else:
        w = jnp.asarray(sample_weight, jnp.float32)
    return X, y, w


# ---------------------------------------------------------------------------
# Binary logistic regression — weighted IRLS (Newton) with L2 + optional L1
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("max_iter", "fit_intercept"))
def fit_logistic_regression(
    X: jnp.ndarray,
    y: jnp.ndarray,
    sample_weight: Optional[jnp.ndarray] = None,
    reg_param: float = 0.0,
    elastic_net_param: float = 0.0,
    max_iter: int = 50,
    tol: float = 1e-6,
    fit_intercept: bool = True,
) -> LinearFit:
    """Newton-IRLS with ridge-damped Hessian for the smooth (L2) case; L1
    candidates run exact proximal-gradient (scalar-majorizer FISTA), whose
    fixed point is the TRUE elastic-net optimum — matching Spark's OWLQN
    semantics and the batched grid solver (``fit_logreg_grid``), instead of
    the biased soft-threshold-after-Newton heuristic.

    ``reg_param``/``elastic_net_param`` follow Spark's parameterisation
    (regParam, elasticNetParam in DefaultSelectorParams.scala:36-75):
    l2 = reg*(1-alpha), l1 = reg*alpha, scaled by n.
    """
    X, y, w = _prep(X, y, sample_weight)
    n, d = X.shape
    wsum = jnp.maximum(w.sum(), 1.0)
    l2 = reg_param * (1.0 - elastic_net_param)
    l1 = reg_param * elastic_net_param
    da = d + (1 if fit_intercept else 0)

    if fit_intercept:
        Xa = jnp.concatenate([X, jnp.ones((n, 1), X.dtype)], axis=1)
    else:
        Xa = X

    def smooth_grad(beta):
        z = Xa @ beta
        p = jax.nn.sigmoid(z)
        g = Xa.T @ (w * (p - y) / wsum)
        return g.at[:d].add(l2 * beta[:d]), p

    def newton_loop(_):
        def step(state):
            beta, _, it = state
            grad, p = smooth_grad(beta)
            s = jnp.maximum(w * p * (1 - p) / wsum, 1e-10)
            H = (Xa * s[:, None]).T @ Xa
            H = H.at[jnp.arange(d), jnp.arange(d)].add(l2)
            new_beta = _finite_or(beta - _damped_solve(H, grad), beta)
            return new_beta, jnp.max(jnp.abs(new_beta - beta)), it + 1

        def cond(state):
            _, dn, it = state
            return (dn > tol) & (it < max_iter)

        beta0 = jnp.zeros(da, jnp.float32)
        return lax.while_loop(
            cond, step, (beta0, jnp.float32(jnp.inf), jnp.int32(0)))

    def fista_loop(_):
        # Lipschitz bound via matvec power iteration on X'WX/(4 wsum)
        def pow_it(i, v):
            v = Xa.T @ (w * (Xa @ v)) / (4.0 * wsum)
            return v / (jnp.linalg.norm(v) + 1e-12)
        v = lax.fori_loop(0, 16, pow_it, jnp.ones(da, X.dtype)
                          / jnp.sqrt(da))
        L = jnp.vdot(v, Xa.T @ (w * (Xa @ v)) / (4.0 * wsum)) * 1.01 \
            + l2 + 1e-6
        thr = l1 / L
        coef_dims = jnp.arange(da) < d

        def step(state):
            beta, zb, t_m, _, it = state
            grad, _ = smooth_grad(zb)
            nb = zb - grad / L
            nb = jnp.where(coef_dims,
                           jnp.sign(nb) * jnp.maximum(jnp.abs(nb) - thr,
                                                      0.0),
                           nb)
            nb = _finite_or(nb, beta)
            nt = 0.5 * (1.0 + jnp.sqrt(1.0 + 4.0 * t_m * t_m))
            nz = nb + (t_m - 1.0) / nt * (nb - beta)
            return nb, nz, nt, jnp.max(jnp.abs(nb - beta)), it + 1

        def cond(state):
            _, _, _, dn, it = state
            # proximal steps are ~D/N cheaper than Newton steps: scale the
            # iteration budget so max_iter keeps its "solver effort" meaning
            return (dn > tol) & (it < 8 * max_iter)

        beta0 = jnp.zeros(da, jnp.float32)
        beta, _, _, dn, it = lax.while_loop(
            cond, step, (beta0, beta0, jnp.float32(1.0),
                         jnp.float32(jnp.inf), jnp.int32(0)))
        return beta, dn, it

    beta, delta_norm, it = lax.cond(l1 > 0, fista_loop, newton_loop,
                                    operand=None)
    coef = beta[:d]
    intercept = beta[d] if fit_intercept else jnp.float32(0.0)
    return LinearFit(coef, intercept, it, delta_norm <= tol)


def logreg_predict_proba(coef, intercept, X):
    z = jnp.asarray(X, jnp.float32) @ coef + intercept
    p1 = jax.nn.sigmoid(z)
    return jnp.stack([1.0 - p1, p1], axis=1), jnp.stack([-z, z], axis=1)


# ---------------------------------------------------------------------------
# Grid-batched binary logistic regression — the WHOLE folds x candidates
# sweep as one XLA program (SURVEY §2.12 row 2's concurrency axis)
# ---------------------------------------------------------------------------

def _grid_fold_stats(X, W_tr, wsum, fit_intercept: bool,
                     standardization: bool):
    """Per-fold weighted centering/scale vectors, shared by every grid
    solver (standardization folds in algebraically — the standardized
    matrix is never materialized per fold)."""
    mu = (W_tr @ X) / wsum[:, None]                        # (F, D)
    if standardization:
        ex2 = (W_tr @ (X * X)) / wsum[:, None]
        sig = jnp.sqrt(jnp.maximum(ex2 - mu ** 2, 0.0))
        sig = jnp.where(sig < 1e-12, 1.0, sig)
    else:
        sig = jnp.ones_like(mu)
    cen = mu if fit_intercept else jnp.zeros_like(mu)
    return cen, sig


def _grid_fold_grams(X, W_tr, wsum, cen, sig):
    """Standardized per-fold weighted covariance Grams — the one O(N D²)
    cost of a grid solve.  lax.map, not vmap: a batched Gram would
    materialize the (F, N, D) weighted matrices at once.  HIGH precision
    (bf16_3x, ~f32 quality): DEFAULT on this stack runs batched f32 gemms
    in single-pass bf16, whose ~3e-3 noise would corrupt a majorizing
    metric."""
    def fold_gram(w_f):
        return jax.lax.dot((X * w_f[:, None]).T, X,
                           precision=jax.lax.Precision.HIGH,
                           preferred_element_type=jnp.float32)
    Q = lax.map(fold_gram, W_tr) / wsum[:, None, None]     # (F, D, D)
    Qs = Q - (cen[:, :, None] * cen[:, None, :])
    return Qs / (sig[:, :, None] * sig[:, None, :])


def _grid_lmax(Qs):
    """Per-fold top Gram eigenvalue (power iteration) — the scalar-majorizer
    Lipschitz bound for the grid solvers' L1/FISTA paths."""
    d = Qs.shape[-1]

    def lmax_fold(Qs_f):
        def pow_it(i, v):
            v = Qs_f @ v
            return v / (jnp.linalg.norm(v) + 1e-12)
        v = lax.fori_loop(0, 16, pow_it,
                          jnp.ones(d, Qs.dtype) / jnp.sqrt(d))
        return jnp.vdot(v, Qs_f @ v) * 1.01
    return jax.vmap(lmax_fold)(Qs)

@functools.partial(jax.jit, static_argnames=("max_iter", "fit_intercept",
                                             "standardization"))
def fit_logreg_grid(
    X: jnp.ndarray,          # (N, D) shared matrix
    y: jnp.ndarray,          # (N,)
    W_tr: jnp.ndarray,       # (F, N) per-fold training weights
    regs: jnp.ndarray,       # (C,) regParam per candidate
    alphas: jnp.ndarray,     # (C,) elasticNetParam per candidate
    max_iter: int = 50,
    tol: float = 1e-5,
    fit_intercept: bool = True,
    standardization: bool = True,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Every (fold, candidate) binary-LR fit in ONE launch.

    Returns ``(scores, iters, coef, intercept)``: ``scores`` is the
    (F, C, N) sigmoid score matrix over ALL rows (validators mask
    train/eval via weights); ``coef`` (F, C, D) / ``intercept`` (F, C)
    are RAW-feature-space solutions — callers that append a full-train
    weight row take the winning candidate's refit model straight from
    that row (``GridGroup.refit_model``) instead of a sequential
    ``fit_logistic_regression`` refit.

    Solver: proximal majorization with Nesterov momentum.  The logistic
    Hessian obeys X'diag(w p(1-p))X <= X'diag(w)X / 4 (Böhning-Lindsay), so
    one weighted Gram per FOLD — computed once, shared by every candidate —
    yields a fixed majorizing metric; each iteration is then two (N, D)
    matvecs batched over the whole grid instead of a fresh (D, N)@(N, D)
    Hessian per candidate per iteration (the Newton-IRLS cost that made
    per-candidate fits the sweep's dominant term).  Pure-L2 candidates
    converge to the same optimum as Newton-IRLS; L1 candidates run exact
    proximal-gradient (scalar-majorizer FISTA), whose fixed point is the
    TRUE elastic-net optimum — the sequential IRLS's after-step threshold
    is itself an approximate prox, so the two paths agree to metric level
    (<~2e-3 AuPR) rather than per-coefficient.  Standardization is folded in
    algebraically (mean/scale corrections on the Gram and gradient), so the
    standardized matrix is never materialized per fold.
    """
    X = jnp.asarray(X, jnp.float32)
    y = jnp.asarray(y, jnp.float32)
    n, d = X.shape
    F = W_tr.shape[0]
    C = regs.shape[0]
    wsum = jnp.maximum(W_tr.sum(axis=1), 1.0)              # (F,)
    l2 = regs[None, :] * (1.0 - alphas[None, :])           # (F->, C)
    l1 = regs[None, :] * alphas[None, :]

    cen, sig = _grid_fold_stats(X, W_tr, wsum, fit_intercept,
                                standardization)
    Qs = _grid_fold_grams(X, W_tr, wsum, cen, sig)

    # fixed majorizer per (f, c): Qs/4 + l2 I — inverted ONCE: the per-
    # iteration solve is then a TPU-friendly matvec (a triangular solve in
    # the loop is latency-bound: ~200 sequential substitution steps each)
    eye = jnp.eye(d, dtype=X.dtype)
    # damping relative to the standardized diag (0.25): reg_param=0 over
    # pivoted one-hot blocks makes Qs exactly singular, and an absolute
    # 1e-7 jitter is below f32 resolution there (_damped_solve rationale)
    H = (Qs[:, None] / 4.0
         + (l2[:, :, None, None] + 2.5e-6) * eye[None, None])  # (F, C, D, D)
    H_inv = jax.vmap(jax.vmap(jnp.linalg.inv))(H)

    def z_of(b, b0, precision=jax.lax.Precision.DEFAULT):
        """(F, C, N) standardized-space logits against the RAW matrix:
        Xs@b + b0 = X@(b/sig) - cen@(b/sig) + b0.  In-loop calls run at
        DEFAULT (bf16) — the gradient tolerates it and it is the per-
        iteration cost — while the final scoring pass runs at HIGH."""
        u = b / sig[:, None, :]
        z = jnp.einsum("nd,fcd->fcn", X, u, precision=precision)
        return z - jnp.einsum("fd,fcd->fc", cen, u)[..., None] + b0[..., None]

    def grad(b, b0):
        p = jax.nn.sigmoid(z_of(b, b0))
        r = (W_tr[:, None, :] * (p - y[None, None, :])
             / wsum[:, None, None])                         # (F, C, N)
        g_raw = jnp.einsum("fcn,nd->fcd", r, X,
                           precision=jax.lax.Precision.DEFAULT)
        rsum = r.sum(axis=2)
        g = (g_raw - cen[:, None, :] * rsum[..., None]) / sig[:, None, :]
        return g + l2[..., None] * b, rsum                  # grad_b, grad_b0

    def mm_solve(g):
        """delta = H^-1 g via the precomputed per-(f, c) inverse."""
        return jnp.einsum("fcde,fce->fcd", H_inv, g)

    # scalar majorizer for the L1 candidates: FISTA with step 1/L and
    # threshold l1/L is the EXACT proximal-gradient method, whose fixed
    # point is the true elastic-net optimum (a plain soft-threshold after a
    # dense H^-1 step is NOT the prox under that metric — its fixed point
    # is biased on correlated features, measured up to 0.022 in p)
    Lf = _grid_lmax(Qs)                                    # (F,)
    L_fc = Lf[:, None] / 4.0 + l2 + 1e-6                   # (F, C)
    has_l1 = l1[..., None] > 0

    def step(state):
        b, b0, pb, pb0, tm, _, it = state
        # Nesterov: gradient at the extrapolated point
        gb, g0 = grad(b, b0)
        nb_mm = b - mm_solve(gb)
        nb_prox = b - gb / L_fc[..., None]
        thr = l1[..., None] / L_fc[..., None]
        nb_prox = jnp.sign(nb_prox) * jnp.maximum(jnp.abs(nb_prox) - thr,
                                                  0.0)
        nb = jnp.where(has_l1, nb_prox, nb_mm)
        n0 = b0 - 4.0 * g0 if fit_intercept else b0
        ntm = 0.5 * (1.0 + jnp.sqrt(1.0 + 4.0 * tm * tm))
        mom = (tm - 1.0) / ntm
        yb_ = nb + mom * (nb - pb)
        y0_ = n0 + mom * (n0 - pb0)
        dn = jnp.maximum(jnp.max(jnp.abs(nb - pb)),
                         jnp.max(jnp.abs(n0 - pb0)))
        return yb_, y0_, nb, n0, ntm, dn, it + 1

    def cond(state):
        *_, dn, it = state
        return (dn > tol) & (it < max_iter)

    b0_init = jnp.zeros((F, C), X.dtype)
    binit = jnp.zeros((F, C, d), X.dtype)
    state = (binit, b0_init, binit, b0_init, jnp.float32(1.0),
             jnp.float32(jnp.inf), jnp.int32(0))
    final = lax.while_loop(cond, step, state)
    b, b0, iters = final[2], final[3], final[6]
    # raw-space coefficients alongside the scores: callers that append a
    # full-train weight row get the winning candidate's REFIT model from
    # the same program (ModelSelector.scala:145-209's refit without a
    # fresh sequential fit — VERDICT r3 Missing #6)
    u = b / sig[:, None, :]
    icpt = b0 - jnp.einsum("fd,fcd->fc", cen, u)
    return (jax.nn.sigmoid(z_of(b, b0, jax.lax.Precision.HIGH)), iters,
            u, icpt)


@functools.partial(jax.jit, static_argnames=("n_classes", "max_iter",
                                             "fit_intercept",
                                             "standardization"))
def fit_softmax_grid(
    X: jnp.ndarray,          # (N, D) shared matrix
    y: jnp.ndarray,          # (N,) int labels
    n_classes: int,
    W_tr: jnp.ndarray,       # (F, N) per-fold training weights
    regs: jnp.ndarray,       # (C,) regParam per candidate
    alphas: jnp.ndarray,     # (C,) elasticNetParam per candidate
    max_iter: int = 200,
    tol: float = 1e-5,
    fit_intercept: bool = True,
    standardization: bool = True,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Every (fold, candidate) softmax-LR fit in ONE launch — the multiclass
    sibling of ``fit_logreg_grid`` (MultiClassificationModelSelector default
    grid, DefaultSelectorParams.scala:36-75).

    Returns ``(logits, iters)``: ``logits`` is the (F, C, K, N) class-score
    matrix over ALL rows — callers argmax over axis 2 for predicted labels
    (softmax is argmax-invariant, so it is never materialized here).
    Solver: proximal majorization with Nesterov momentum
    under Böhning's multinomial bound  H ⪯ ½·(I_K − 11ᵀ/K) ⊗ XᵀWX ⪯
    ½·I_K ⊗ XᵀWX — one weighted Gram per fold shared by every candidate and
    class, inverted once; each iteration is matvecs batched over the whole
    (fold, candidate, class) grid.  L1 candidates run scalar-majorizer FISTA
    (exact prox), as in the binary solver.
    """
    X = jnp.asarray(X, jnp.float32)
    yi = jnp.asarray(y, jnp.int32)
    K = n_classes
    n, d = X.shape
    F = W_tr.shape[0]
    C = regs.shape[0]
    Y = jax.nn.one_hot(yi, K, dtype=jnp.float32)            # (N, K)
    wsum = jnp.maximum(W_tr.sum(axis=1), 1.0)               # (F,)
    l2 = regs[None, :] * (1.0 - alphas[None, :])            # (1, C)
    l1 = regs[None, :] * alphas[None, :]

    cen, sig = _grid_fold_stats(X, W_tr, wsum, fit_intercept,
                                standardization)
    Qs = _grid_fold_grams(X, W_tr, wsum, cen, sig)

    eye = jnp.eye(d, dtype=X.dtype)
    # Böhning majorizer ½·Qs + l2 I, inverted once per (f, c)
    H = (Qs[:, None] / 2.0
         + (l2[:, :, None, None] + 2.5e-6) * eye[None, None])  # (F, C, D, D)
    H_inv = jax.vmap(jax.vmap(jnp.linalg.inv))(H)

    def z_of(B, B0, precision=jax.lax.Precision.DEFAULT):
        """(F, C, K, N) standardized-space logits against the RAW matrix."""
        u = B / sig[:, None, None, :]                        # (F, C, K, D)
        z = jnp.einsum("nd,fckd->fckn", X, u, precision=precision)
        return (z - jnp.einsum("fd,fckd->fck", cen, u)[..., None]
                + B0[..., None])

    def grad(B, B0):
        P = jax.nn.softmax(z_of(B, B0), axis=2)              # (F, C, K, N)
        r = (W_tr[:, None, None, :]
             * (P - Y.T[None, None, :, :]) / wsum[:, None, None, None])
        g_raw = jnp.einsum("fckn,nd->fckd", r, X,
                           precision=jax.lax.Precision.DEFAULT)
        rsum = r.sum(axis=3)                                 # (F, C, K)
        g = (g_raw - cen[:, None, None, :] * rsum[..., None]) \
            / sig[:, None, None, :]
        return g + l2[..., None, None] * B, rsum

    def mm_solve(g):
        return jnp.einsum("fcde,fcke->fckd", H_inv, g)

    Lf = _grid_lmax(Qs)                                     # (F,)
    L_fc = Lf[:, None] / 2.0 + l2 + 1e-6                    # (F, C)
    has_l1 = l1[..., None, None] > 0

    def step(state):
        B, B0, pB, pB0, tm, _, it = state
        gB, g0 = grad(B, B0)
        nB_mm = B - mm_solve(gB)
        nB_prox = B - gB / L_fc[..., None, None]
        thr = l1[..., None, None] / L_fc[..., None, None]
        nB_prox = jnp.sign(nB_prox) * jnp.maximum(jnp.abs(nB_prox) - thr,
                                                  0.0)
        nB = jnp.where(has_l1, nB_prox, nB_mm)
        n0 = B0 - 2.0 * g0 if fit_intercept else B0
        ntm = 0.5 * (1.0 + jnp.sqrt(1.0 + 4.0 * tm * tm))
        mom = (tm - 1.0) / ntm
        yB_ = nB + mom * (nB - pB)
        y0_ = n0 + mom * (n0 - pB0)
        dn = jnp.maximum(jnp.max(jnp.abs(nB - pB)),
                         jnp.max(jnp.abs(n0 - pB0)))
        return yB_, y0_, nB, n0, ntm, dn, it + 1

    def cond(state):
        *_, dn, it = state
        return (dn > tol) & (it < max_iter)

    B0_init = jnp.zeros((F, C, K), X.dtype)
    Binit = jnp.zeros((F, C, K, d), X.dtype)
    state = (Binit, B0_init, Binit, B0_init, jnp.float32(1.0),
             jnp.float32(jnp.inf), jnp.int32(0))
    final = lax.while_loop(cond, step, state)
    B, B0, iters = final[2], final[3], final[6]
    return z_of(B, B0, jax.lax.Precision.HIGH), iters        # (F, C, K, N)


# ---------------------------------------------------------------------------
# Multinomial (softmax) logistic regression — damped Newton on block-diagonal
# Hessian approximation (per-class), good convergence for tabular K<=~50
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("n_classes", "max_iter", "fit_intercept"))
def fit_multinomial_logreg(
    X: jnp.ndarray,
    y: jnp.ndarray,  # int labels (N,)
    n_classes: int,
    sample_weight: Optional[jnp.ndarray] = None,
    reg_param: float = 0.0,
    elastic_net_param: float = 0.0,
    max_iter: int = 100,
    tol: float = 1e-6,
    fit_intercept: bool = True,
) -> LinearFit:
    X = jnp.asarray(X, jnp.float32)
    yi = jnp.asarray(y, jnp.int32)
    n, d = X.shape
    w = (jnp.ones(n, jnp.float32) if sample_weight is None
         else jnp.asarray(sample_weight, jnp.float32))
    wsum = jnp.maximum(w.sum(), 1.0)
    Y = jax.nn.one_hot(yi, n_classes, dtype=jnp.float32)
    l2 = reg_param * (1.0 - elastic_net_param)
    l1 = reg_param * elastic_net_param
    if fit_intercept:
        Xa = jnp.concatenate([X, jnp.ones((n, 1), X.dtype)], axis=1)
    else:
        Xa = X
    da = Xa.shape[1]

    def smooth_grad(B):
        P = jax.nn.softmax(Xa @ B, axis=1)
        G = Xa.T @ (w[:, None] * (P - Y)) / wsum  # (da, K)
        return G.at[:d].add(l2 * B[:d]), P

    def newton_loop(_):
        def step(state):
            B, _, it = state  # (da, K)
            G, P = smooth_grad(B)

            # per-class block-diagonal Hessian:
            # H_k = X^T diag(w p_k(1-p_k)) X
            def solve_class(g_k, p_k):
                s = jnp.maximum(w * p_k * (1 - p_k) / wsum, 1e-10)
                H = (Xa * s[:, None]).T @ Xa
                H = H.at[jnp.arange(d), jnp.arange(d)].add(l2)
                return _damped_solve(H, g_k)

            delta = jax.vmap(solve_class, in_axes=(1, 1), out_axes=1)(G, P)
            # damping for stability of blockwise Newton
            newB = _finite_or(B - 0.9 * delta, B)
            dn = jnp.max(jnp.abs(newB - B))
            return newB, dn, it + 1

        def cond(state):
            _, dn, it = state
            return (dn > tol) & (it < max_iter)

        B0 = jnp.zeros((da, n_classes), jnp.float32)
        return lax.while_loop(cond, step,
                              (B0, jnp.float32(jnp.inf), jnp.int32(0)))

    def fista_loop(_):
        # exact proximal-gradient under Böhning's multinomial bound
        # H ⪯ ½ XᵀWX — same elastic-net fixed point as the batched grid
        # solver (fit_softmax_grid), replacing the biased
        # soft-threshold-after-Newton heuristic
        def pow_it(i, v):
            v = Xa.T @ (w * (Xa @ v)) / (2.0 * wsum)
            return v / (jnp.linalg.norm(v) + 1e-12)
        v = lax.fori_loop(0, 16, pow_it,
                          jnp.ones(da, X.dtype) / jnp.sqrt(da))
        L = jnp.vdot(v, Xa.T @ (w * (Xa @ v)) / (2.0 * wsum)) * 1.01 \
            + l2 + 1e-6
        thr = l1 / L
        coef_dims = (jnp.arange(da) < d)[:, None]

        def step(state):
            B, zB, t_m, _, it = state
            G, _ = smooth_grad(zB)
            nB = zB - G / L
            nB = jnp.where(coef_dims,
                           jnp.sign(nB) * jnp.maximum(jnp.abs(nB) - thr,
                                                      0.0),
                           nB)
            nB = _finite_or(nB, B)
            nt = 0.5 * (1.0 + jnp.sqrt(1.0 + 4.0 * t_m * t_m))
            nz = nB + (t_m - 1.0) / nt * (nB - B)
            return nB, nz, nt, jnp.max(jnp.abs(nB - B)), it + 1

        def cond(state):
            _, _, _, dn, it = state
            # proximal steps are ~D/N cheaper than Newton steps
            return (dn > tol) & (it < 8 * max_iter)

        B0 = jnp.zeros((da, n_classes), jnp.float32)
        B, _, _, dn, it = lax.while_loop(
            cond, step, (B0, B0, jnp.float32(1.0), jnp.float32(jnp.inf),
                         jnp.int32(0)))
        return B, dn, it

    B, dn, it = lax.cond(l1 > 0, fista_loop, newton_loop, operand=None)
    coef = B[:d].T  # (K, D)
    intercept = B[d] if fit_intercept else jnp.zeros(n_classes, jnp.float32)
    return LinearFit(coef, intercept, it, dn <= tol)


def softmax_predict_proba(coef, intercept, X):
    Z = jnp.asarray(X, jnp.float32) @ coef.T + intercept
    return jax.nn.softmax(Z, axis=1), Z


# ---------------------------------------------------------------------------
# Linear regression — closed-form ridge / proximal coordinate-free elastic net
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("max_iter", "fit_intercept"))
def fit_linear_regression(
    X: jnp.ndarray,
    y: jnp.ndarray,
    sample_weight: Optional[jnp.ndarray] = None,
    reg_param: float = 0.0,
    elastic_net_param: float = 0.0,
    max_iter: int = 200,
    tol: float = 1e-7,
    fit_intercept: bool = True,
) -> LinearFit:
    """Ridge by normal equations (one MXU matmul + Cholesky); elastic net by
    FISTA on the quadratic loss (still one gram matrix, no data passes)."""
    X, y, w = _prep(X, y, sample_weight)
    n, d = X.shape
    wsum = jnp.maximum(w.sum(), 1.0)
    l2 = reg_param * (1.0 - elastic_net_param)
    l1 = reg_param * elastic_net_param

    if fit_intercept:
        xm = (w @ X) / wsum
        ym = (w @ y) / wsum
    else:
        xm = jnp.zeros(d, X.dtype)
        ym = jnp.float32(0.0)
    Xc = X - xm
    yc = y - ym
    A = (Xc * w[:, None]).T @ Xc / wsum          # gram (D,D)
    b = (Xc * w[:, None]).T @ yc / wsum          # (D,)

    def ridge(_):
        M = A + l2 * jnp.eye(d, dtype=X.dtype)
        coef = _damped_solve(M, b)
        return coef, jnp.int32(1), jnp.bool_(True)

    def fista(_):
        # Lipschitz constant upper bound via power iteration
        def pow_it(i, v):
            v = A @ v
            return v / (jnp.linalg.norm(v) + 1e-12)
        v = pow_it(0, jnp.ones(d, X.dtype) / jnp.sqrt(d))
        v = lax.fori_loop(0, 16, pow_it, v)
        L = jnp.vdot(v, A @ v) + l2 + 1e-6

        def step(state):
            beta, z, t, _, it = state
            grad = A @ z - b + l2 * z
            nb = z - grad / L
            nb = jnp.sign(nb) * jnp.maximum(jnp.abs(nb) - l1 / L, 0.0)
            nt = 0.5 * (1 + jnp.sqrt(1 + 4 * t * t))
            nz = nb + (t - 1) / nt * (nb - beta)
            dn = jnp.max(jnp.abs(nb - beta))
            return nb, nz, nt, dn, it + 1

        def cond(state):
            _, _, _, dn, it = state
            return (dn > tol) & (it < max_iter)

        beta0 = jnp.zeros(d, X.dtype)
        beta, _, _, dn, it = lax.while_loop(
            cond, step, (beta0, beta0, jnp.float32(1.0), jnp.float32(jnp.inf),
                         jnp.int32(0)))
        return beta, it, dn <= tol

    coef, it, conv = lax.cond(l1 > 0, fista, ridge, operand=None)
    intercept = ym - jnp.dot(xm, coef) if fit_intercept else jnp.float32(0.0)
    return LinearFit(coef, intercept, it, conv)


def linear_predict(coef, intercept, X):
    return jnp.asarray(X, jnp.float32) @ coef + intercept


@functools.partial(jax.jit, static_argnames=("max_iter", "fit_intercept",
                                             "standardization"))
def fit_linreg_grid(
    X: jnp.ndarray,          # (N, D)
    y: jnp.ndarray,          # (N,)
    W_tr: jnp.ndarray,       # (F, N)
    regs: jnp.ndarray,       # (C,)
    alphas: jnp.ndarray,     # (C,)
    max_iter: int = 200,
    tol: float = 1e-7,
    fit_intercept: bool = True,
    standardization: bool = True,
) -> jnp.ndarray:
    """Every (fold, candidate) linear-regression fit in one launch.

    One weighted Gram per fold (shared across candidates), then per-(f, c)
    ridge solves — or FISTA iterations entirely on the (D, D) Gram when any
    candidate carries L1 — with zero further passes over the data.  The
    penalty applies in standardized space (Spark parity), folded into the
    Gram algebraically.  Returns the (F, C, N) prediction matrix.
    """
    X = jnp.asarray(X, jnp.float32)
    y = jnp.asarray(y, jnp.float32)
    n, d = X.shape
    F, C = W_tr.shape[0], regs.shape[0]
    wsum = jnp.maximum(W_tr.sum(axis=1), 1.0)
    l2 = regs[None, :] * (1.0 - alphas[None, :])           # (1, C)
    l1 = regs[None, :] * alphas[None, :]

    xm = (W_tr @ X) / wsum[:, None] if fit_intercept else \
        jnp.zeros((F, d), X.dtype)
    ym = (W_tr @ y) / wsum if fit_intercept else jnp.zeros(F, X.dtype)

    def fold_parts(w_f):
        A = jax.lax.dot((X * w_f[:, None]).T, X,
                        precision=jax.lax.Precision.HIGH,
                        preferred_element_type=jnp.float32)
        bv = jax.lax.dot((X * w_f[:, None]).T, y[:, None],
                         precision=jax.lax.Precision.HIGH)[:, 0]
        return A, bv
    A_raw, b_raw = lax.map(fold_parts, W_tr)               # (F,D,D), (F,D)
    A = A_raw / wsum[:, None, None] - xm[:, :, None] * xm[:, None, :]
    bv = b_raw / wsum[:, None] - xm * ym[:, None]          # centered
    if standardization:
        sig = jnp.sqrt(jnp.maximum(
            jnp.diagonal(A, axis1=1, axis2=2), 0.0))
        sig = jnp.where(sig < 1e-12, 1.0, sig)
        A = A / (sig[:, :, None] * sig[:, None, :])
        bv = bv / sig
    else:
        sig = jnp.ones((F, d), X.dtype)

    eye = jnp.eye(d, dtype=X.dtype)

    def solve_fc(A_f, b_f, l2_c, l1_c):
        # relative damping on the unit-diagonal standardized Gram:
        # reg_param=0 candidates over collinear blocks are exactly singular
        M = A_f + (l2_c + 1e-5) * eye

        def ridge(_):
            return jax.scipy.linalg.solve(M, b_f, assume_a="pos")

        def fista(_):
            # Lipschitz bound via power iteration (trace is ~d/λmax too loose
            # on a standardized Gram and would stall the FISTA steps)
            def pow_it(i, v):
                v = A_f @ v
                return v / (jnp.linalg.norm(v) + 1e-12)
            v = lax.fori_loop(0, 16, pow_it,
                              jnp.ones(d, X.dtype) / jnp.sqrt(d))
            Lc = jnp.vdot(v, A_f @ v) * 1.01 + l2_c + 1e-6

            def stp(st):
                beta, z, t, _, it = st
                g = A_f @ z - b_f + l2_c * z
                nb = z - g / Lc
                nb = jnp.sign(nb) * jnp.maximum(jnp.abs(nb) - l1_c / Lc, 0.0)
                nt = 0.5 * (1 + jnp.sqrt(1 + 4 * t * t))
                nz = nb + (t - 1) / nt * (nb - beta)
                return nb, nz, nt, jnp.max(jnp.abs(nb - beta)), it + 1

            def cnd(st):
                _, _, _, dn, it = st
                return (dn > tol) & (it < max_iter)

            b0 = jnp.zeros(d, X.dtype)
            out = lax.while_loop(cnd, stp,
                                 (b0, b0, jnp.float32(1.0),
                                  jnp.float32(jnp.inf), jnp.int32(0)))
            return out[0]

        return lax.cond(l1_c > 0, fista, ridge, operand=None)

    coef_s = jax.vmap(lambda A_f, b_f: jax.vmap(
        lambda l2_c, l1_c: solve_fc(A_f, b_f, l2_c, l1_c))(
            l2[0], l1[0]))(A, bv)                          # (F, C, D) std space
    coef = coef_s / sig[:, None, :]
    icpt = ym[:, None] - jnp.einsum("fd,fcd->fc", xm, coef)
    preds = jnp.einsum("nd,fcd->fcn", X, coef,
                       precision=jax.lax.Precision.HIGH)
    # raw-space (coef, intercept) ride along for winner-refit reuse (the
    # caller may append a full-train weight row — see fit_logreg_grid)
    return preds + icpt[..., None], coef, icpt


# ---------------------------------------------------------------------------
# Linear SVC — squared-hinge + L2 via Newton (smooth enough a.e.)
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("max_iter", "fit_intercept"))
def fit_linear_svc(
    X: jnp.ndarray,
    y: jnp.ndarray,  # {0,1}
    sample_weight: Optional[jnp.ndarray] = None,
    reg_param: float = 1e-4,
    max_iter: int = 50,
    tol: float = 1e-6,
    fit_intercept: bool = True,
) -> LinearFit:
    X, y, w = _prep(X, y, sample_weight)
    n, d = X.shape
    ypm = 2.0 * y - 1.0  # {-1, +1}
    wsum = jnp.maximum(w.sum(), 1.0)
    if fit_intercept:
        Xa = jnp.concatenate([X, jnp.ones((n, 1), X.dtype)], axis=1)
    else:
        Xa = X
    da = Xa.shape[1]

    def step(state):
        beta, _, it = state
        z = Xa @ beta
        margin = 1.0 - ypm * z
        active = (margin > 0).astype(X.dtype) * w / wsum
        grad = Xa.T @ (-2.0 * active * ypm * margin)
        grad = grad.at[:d].add(reg_param * beta[:d])
        H = (Xa * (2.0 * active)[:, None]).T @ Xa
        H = H.at[jnp.arange(d), jnp.arange(d)].add(reg_param)
        delta = _damped_solve(H, grad)
        nb = _finite_or(beta - delta, beta)
        dn = jnp.max(jnp.abs(nb - beta))
        return nb, dn, it + 1

    def cond(state):
        _, dn, it = state
        return (dn > tol) & (it < max_iter)

    beta0 = jnp.zeros(da, jnp.float32)
    beta, dn, it = lax.while_loop(cond, step,
                                  (beta0, jnp.float32(jnp.inf), jnp.int32(0)))
    coef = beta[:d]
    intercept = beta[d] if fit_intercept else jnp.float32(0.0)
    return LinearFit(coef, intercept, it, dn <= tol)


def svc_decision(coef, intercept, X):
    return jnp.asarray(X, jnp.float32) @ coef + intercept


# ---------------------------------------------------------------------------
# (Multinomial/Bernoulli-ish) Naive Bayes on non-negative features
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("n_classes",))
def fit_naive_bayes(
    X: jnp.ndarray,
    y: jnp.ndarray,
    n_classes: int,
    sample_weight: Optional[jnp.ndarray] = None,
    smoothing: float = 1.0,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Multinomial NB: returns (log_prior (K,), log_likelihood (K, D)).

    Matches Spark's NaiveBayes multinomial default (smoothing=1.0).
    Features must be non-negative (counts/indicators) — the transmogrified
    matrix's one-hot/hash slots qualify; numeric slots are clipped at 0.
    """
    X = jnp.maximum(jnp.asarray(X, jnp.float32), 0.0)
    yi = jnp.asarray(y, jnp.int32)
    n, d = X.shape
    w = (jnp.ones(n, jnp.float32) if sample_weight is None
         else jnp.asarray(sample_weight, jnp.float32))
    Y = jax.nn.one_hot(yi, n_classes, dtype=jnp.float32) * w[:, None]
    class_count = Y.sum(axis=0)                      # (K,)
    feat_count = Y.T @ X                             # (K, D)
    log_prior = jnp.log(class_count + 1e-12) - jnp.log(
        jnp.maximum(class_count.sum(), 1e-12))
    log_lik = jnp.log(feat_count + smoothing) - jnp.log(
        (feat_count.sum(axis=1, keepdims=True) + smoothing * d))
    return log_prior, log_lik


def naive_bayes_predict_log_proba(log_prior, log_lik, X):
    X = jnp.maximum(jnp.asarray(X, jnp.float32), 0.0)
    joint = X @ log_lik.T + log_prior                # (N, K)
    return joint - jax.scipy.special.logsumexp(joint, axis=1, keepdims=True)
