"""Multilayer perceptron classifier — jitted dense network on the MXU.

Reference: ``OpMultilayerPerceptronClassifier``
(core/.../impl/classification/OpMultilayerPerceptronClassifier.scala:48),
wrapping Spark's feed-forward MLP (sigmoid hidden units, softmax output,
full-batch solver, ``layers = [in, hidden..., out]``, maxIter=100,
tol=1e-6, stepSize=0.03, seed).

TPU redesign, not a translation: the whole training loop is ONE compiled
XLA program — a ``lax.while_loop`` of full-batch Adam steps over bf16-
friendly dense matmuls (each layer is an (N, D)·(D, H) MXU matmul), with
the tol-based early exit as traced control flow.  Spark's L-BFGS is a
JVM-driver loop with per-iteration cluster aggregation; here one launch
owns the fit and only the final weights leave the device.  Hidden
activation stays sigmoid for score parity with the reference.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..types.columns import ColumnarDataset
from .prediction import PredictionBatch, PredictorEstimator, PredictorModel

__all__ = ["OpMultilayerPerceptronClassifier", "MLPClassificationModel"]


def _init_params(key, sizes: Sequence[int]):
    """Glorot-uniform weights + zero biases per layer."""
    params = []
    for i in range(len(sizes) - 1):
        key, sub = jax.random.split(key)
        fan_in, fan_out = sizes[i], sizes[i + 1]
        lim = float(np.sqrt(6.0 / (fan_in + fan_out)))
        W = jax.random.uniform(sub, (fan_in, fan_out), jnp.float32,
                               -lim, lim)
        params.append((W, jnp.zeros((fan_out,), jnp.float32)))
    return params


def _forward(params, X):
    """Sigmoid hidden layers, linear logits at the top (Spark MLP layout)."""
    h = X
    for W, b in params[:-1]:
        h = jax.nn.sigmoid(h @ W + b)
    W, b = params[-1]
    return h @ W + b


def _loss(params, X, Y, w):
    logits = _forward(params, X)
    logp = jax.nn.log_softmax(logits, axis=1)
    return -(w * (Y * logp).sum(axis=1)).sum() / jnp.maximum(w.sum(), 1e-12)


def fit_mlp(X, Y, w, sizes: Tuple[int, ...], *, max_iter: int = 100,
            tol: float = 1e-6, step_size: float = 0.03, seed: int = 42):
    """One-launch full-batch Adam fit; returns the fitted parameter pytree.

    The while_loop carries (params, adam m/v, iteration, previous loss):
    it stops at ``max_iter`` or when the loss improves by less than ``tol``
    — the traced analogue of Spark's convergence tolerance.
    """
    Xj = jnp.asarray(X, jnp.float32)
    Yj = jnp.asarray(Y, jnp.float32)
    wj = jnp.asarray(w, jnp.float32)
    params0 = _init_params(jax.random.PRNGKey(seed), sizes)
    return _fit_jit(Xj, Yj, wj, params0, jnp.int32(max_iter),
                    jnp.float32(tol), jnp.float32(step_size))


@jax.jit
def _fit_jit(X, Y, w, params0, max_iter, tol, lr):
    grad_fn = jax.value_and_grad(_loss)
    tmap = jax.tree_util.tree_map

    def body(carry):
        params, m, v, it, prev, _ = carry
        loss, g = grad_fn(params, X, Y, w)
        t = (it + 1).astype(jnp.float32)
        m = tmap(lambda mi, gi: 0.9 * mi + 0.1 * gi, m, g)
        v = tmap(lambda vi, gi: 0.999 * vi + 0.001 * gi * gi, v, g)
        params = tmap(
            lambda p, mi, vi: p - lr * (mi / (1 - 0.9 ** t))
            / (jnp.sqrt(vi / (1 - 0.999 ** t)) + 1e-8),
            params, m, v)
        done = jnp.abs(prev - loss) < tol
        return params, m, v, it + 1, loss, done

    def cond(carry):
        _, _, _, it, _, done = carry
        return jnp.logical_and(it < max_iter, jnp.logical_not(done))

    zeros = tmap(jnp.zeros_like, params0)
    init = (params0, zeros, zeros, jnp.int32(0), jnp.float32(jnp.inf),
            jnp.bool_(False))
    params, _, _, n_iter, final_loss, _ = lax.while_loop(cond, body, init)
    return params, n_iter, final_loss


class OpMultilayerPerceptronClassifier(PredictorEstimator):
    """Feed-forward MLP classifier (binary or multiclass).

    ``layers`` follows Spark's full spec ``[input, hidden..., output]``
    (validated against the data); ``hidden_layers`` is the grid-friendly
    alternative — just the hidden sizes, input/output inferred from the
    data (OpMultilayerPerceptronClassifier.scala:48 setLayers).
    """

    _op_name = "mlpCls"

    def __init__(self, layers: Optional[Sequence[int]] = None,
                 hidden_layers: Sequence[int] = (10,),
                 max_iter: int = 100, tol: float = 1e-6,
                 step_size: float = 0.03, block_size: int = 128,
                 solver: str = "adam", standardization: bool = True,
                 seed: int = 42, uid: Optional[str] = None):
        super().__init__(operation_name=self._op_name, uid=uid)
        self.layers = list(layers) if layers is not None else None
        self.hidden_layers = list(hidden_layers)
        self.max_iter = max_iter
        self.tol = tol
        self.step_size = step_size
        # accepted for Spark API parity; full-batch XLA has no block tiling
        self.block_size = block_size
        self.solver = solver
        self.standardization = standardization
        self.seed = seed

    def fit_columns(self, data: ColumnarDataset, label_col, features_col):
        X = np.asarray(features_col.values, dtype=np.float32)
        y = np.nan_to_num(np.asarray(label_col.values, dtype=np.float32))
        return self.fit_raw(X, y)

    def _sizes(self, d: int, k: int) -> Tuple[int, ...]:
        """Layer sizes; an explicit Spark-style spec is the authority on the
        class count (a CV train fold missing the top class must not shrink
        the softmax head), so only the input dim is validated against data
        and ``k`` may only GROW past the spec when the labels demand it."""
        if self.layers is not None:
            sizes = tuple(int(s) for s in self.layers)
            if sizes[0] != d:
                raise ValueError(
                    f"layers {sizes} do not match data: input dim {d} "
                    f"(Spark MLP layers are [in, hidden..., out])")
            if sizes[-1] < k:
                raise ValueError(
                    f"layers {sizes} declare {sizes[-1]} classes but labels "
                    f"contain class {k - 1}")
            return sizes
        return (d, *map(int, self.hidden_layers), k)

    def fit_raw(self, X: np.ndarray, y: np.ndarray,
                w: Optional[np.ndarray] = None):
        from .classification import _apply_standardize, _standardize_stats

        n, d = X.shape
        k = max(int(np.nanmax(y)) + 1 if len(y) else 2, 2)
        sizes = self._sizes(d, k)
        k = sizes[-1]  # explicit spec wins: one-hot width matches the head
        Y = np.eye(k, dtype=np.float32)[y.astype(int)]
        wv = np.ones(n, np.float32) if w is None else np.asarray(w,
                                                                 np.float32)
        if self.standardization:
            mu, sigma = _standardize_stats(X, wv)
            Xs = _apply_standardize(X, mu, sigma)
        else:
            mu = np.zeros(d, np.float32)
            sigma = np.ones(d, np.float32)
            Xs = X
        params, n_iter, _ = fit_mlp(
            np.asarray(Xs, np.float32), Y, wv, sizes,
            max_iter=self.max_iter, tol=self.tol,
            step_size=self.step_size, seed=self.seed)
        weights = [[np.asarray(W).tolist(), np.asarray(b).tolist()]
                   for W, b in params]
        return MLPClassificationModel(weights=weights, mu=mu.tolist(),
                                      sigma=sigma.tolist())


class MLPClassificationModel(PredictorModel):
    """Fitted MLP: JSON-serializable layer weights + input standardization."""

    def __init__(self, weights: List, mu: List, sigma: List,
                 uid: Optional[str] = None):
        super().__init__(operation_name="mlpCls", uid=uid)
        self.weights = weights
        self.mu = mu
        self.sigma = sigma

    def predict_batch(self, X: np.ndarray) -> PredictionBatch:
        h = ((np.asarray(X, np.float32) - np.asarray(self.mu, np.float32))
             / np.asarray(self.sigma, np.float32))
        n_layers = len(self.weights)
        for i, (W, b) in enumerate(self.weights):
            z = h @ np.asarray(W, np.float32) + np.asarray(b, np.float32)
            if i < n_layers - 1:
                with np.errstate(over="ignore"):
                    h = 1.0 / (1.0 + np.exp(-z))
            else:
                h = z
        e = np.exp(h - h.max(axis=1, keepdims=True))
        proba = e / e.sum(axis=1, keepdims=True)
        return PredictionBatch(
            prediction=proba.argmax(axis=1).astype(np.float64),
            raw_prediction=h, probability=proba)
