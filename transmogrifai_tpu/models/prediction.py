"""Prediction column batch + shared predictor stage bases.

Reference: the ``Prediction`` feature type (features/types/Maps.scala:339-394)
and ``OpPredictorWrapper``/``OpProbabilisticClassifierModel``
(core/.../sparkwrappers/specific/OpPredictorWrapper.scala:71,121).

A ``PredictionBatch`` stores the whole batch's predictions as arrays
(columnar, device-friendly) while presenting the reference's per-row
``Map[String, Double]`` view for local scoring and tests.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Iterator, List, Optional

import numpy as np

from ..features.feature import Feature
from ..stages.base import BinaryEstimator, BinaryModel
from ..types.columns import FeatureColumn
from ..types.feature_types import OPNumeric, OPVector, Prediction

__all__ = ["PredictionBatch", "prediction_column", "PredictorEstimator",
           "PredictorModel", "AOTScoringSpec"]


@dataclasses.dataclass(frozen=True)
class AOTScoringSpec:
    """A model's pure device scoring program, in AOT-exportable form.

    ``fn(X, *params)`` must be a pure jax function of a fixed-shape
    ``(N, D) float32`` matrix plus the model's parameter arrays, returning
    a tuple of arrays named by ``outputs`` (a subset/order of
    ``("prediction", "rawPrediction", "probability")``).  Parameters are
    RUNTIME arguments (not baked constants) so the serialized executable's
    shape is exactly ``(bucket, D)`` + the param shapes — the serving AOT
    cache (serving/aot.py) content-addresses entries on a digest of the
    params anyway, so a changed model can never reuse a stale program.
    """

    name: str                 # program family, e.g. "logreg.binary"
    fn: Any                   # callable (X, *params) -> tuple of arrays
    params: tuple             # numpy arrays / np scalars, fixed order
    outputs: tuple            # names for fn's returned tuple, in order
    #: width D of the (N, D) input matrix.  Explicit because it is NOT
    #: inferrable from the params in general (NaiveBayes' params[0] is the
    #: (K,) class prior, not the (K, D) likelihood matrix).
    n_features: Optional[int] = None


@dataclasses.dataclass
class PredictionBatch:
    """Columnar predictions: prediction (N,), optional raw/proba (N, K)."""

    prediction: np.ndarray
    raw_prediction: Optional[np.ndarray] = None
    probability: Optional[np.ndarray] = None

    def __len__(self) -> int:
        return len(self.prediction)

    def __getitem__(self, idx):
        if isinstance(idx, (int, np.integer)):
            return self.row(int(idx))
        return PredictionBatch(
            self.prediction[idx],
            None if self.raw_prediction is None else self.raw_prediction[idx],
            None if self.probability is None else self.probability[idx],
        )

    def row(self, i: int) -> Dict[str, float]:
        out = {"prediction": float(self.prediction[i])}
        if self.raw_prediction is not None:
            for k, v in enumerate(np.atleast_1d(self.raw_prediction[i])):
                out[f"rawPrediction_{k}"] = float(v)
        if self.probability is not None:
            for k, v in enumerate(np.atleast_1d(self.probability[i])):
                out[f"probability_{k}"] = float(v)
        return out

    def __iter__(self) -> Iterator[Dict[str, float]]:
        for i in range(len(self)):
            yield self.row(i)


def prediction_column(prediction, raw_prediction=None, probability=None) -> FeatureColumn:
    batch = PredictionBatch(
        np.asarray(prediction),
        None if raw_prediction is None else np.asarray(raw_prediction),
        None if probability is None else np.asarray(probability),
    )
    return FeatureColumn(Prediction, batch)


class PredictorEstimator(BinaryEstimator):
    """Base for model estimators: inputs (response RealNN, features OPVector)."""

    # model fits dispatch XLA programs: the execution plan (workflow/plan.py)
    # serializes these in stable layer order instead of pooling them
    device_heavy = True

    # input schema (SchemaError at wiring, TM004 statically); position 0 is
    # the label slot for the leakage lint (TM006)
    input_types = (OPNumeric, OPVector)
    label_input_positions = (0,)

    def __init__(self, operation_name: str, uid: Optional[str] = None):
        super().__init__(operation_name=operation_name, output_type=Prediction,
                         uid=uid)

    def output_is_response(self) -> bool:
        return False  # Prediction output is never the workflow response

    @property
    def label_feature(self) -> Feature:
        return self.input_features[0]

    @property
    def features_feature(self) -> Feature:
        return self.input_features[1]

    def fit_device(self, X: np.ndarray, y: np.ndarray, w,
                   problem_type: str):
        """Device-resident fit for validation sweeps.

        Returns ``score(X_eval) -> jax.Array`` (the validation score vector,
        see ``PredictorModel.score_device``) or None to fall back to
        ``fit_raw`` + host scoring.  Implementations must not materialize
        device values on host (each sync costs a ~0.6 s tunnel round trip).
        """
        return None


class PredictorModel(BinaryModel):
    """Base for fitted predictors; subclasses implement predict(X)."""

    device_heavy = True  # batch predicts are jitted device programs

    input_types = (OPNumeric, OPVector)
    label_input_positions = (0,)

    def __init__(self, operation_name: str, uid: Optional[str] = None):
        super().__init__(operation_name=operation_name, output_type=Prediction,
                         uid=uid)

    def output_is_response(self) -> bool:
        return False

    def predict_batch(self, X: np.ndarray) -> PredictionBatch:
        raise NotImplementedError

    def aot_scoring_spec(self) -> Optional[AOTScoringSpec]:
        """The model's scoring program as an :class:`AOTScoringSpec`, or
        None when the family has no single-program device form (trees,
        isotonic) — serving then keeps the host ``predict_batch`` path.
        """
        return None

    def score_device(self, X: np.ndarray, problem_type: str):
        """Validation score vector as a DEVICE array, or None if unsupported.

        binary -> P(class 1); regression/multiclass -> prediction.  Sweeps
        use this to keep fit→score→metric on device: through a remote-TPU
        tunnel every host materialization costs a ~0.6 s round trip, so the
        selector fetches one stacked metric array per sweep instead of one
        score vector per candidate×fold (see OpValidator's thread-pool
        analogue, OpCrossValidation.scala:113-138).
        """
        return None

    def transform_columns(self, label_col, features_col) -> FeatureColumn:
        X = np.asarray(features_col.values, dtype=np.float32)
        # serving device path: when a BucketedExecutor has installed AOT/
        # JIT-compiled per-bucket scoring programs on this model AND the
        # calling thread is inside the device scoring context (set by the
        # executor, never by the breaker's host-fallback path), route
        # through the compiled program for this batch shape.  Unknown
        # shapes return None and fall through to the host predict.
        programs = getattr(self, "_serving_programs", None)
        if programs is not None:
            from ..serving.aot import device_scoring_active

            if device_scoring_active():
                batch = programs.predict(X)
                if batch is not None:
                    return FeatureColumn(Prediction, batch)
        batch = self.predict_batch(X)
        return FeatureColumn(Prediction, batch)
