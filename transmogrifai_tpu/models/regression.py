"""Regression model stages (XLA-trained).

Reference wrappers (core/.../impl/regression/): OpLinearRegression (:47),
OpGeneralizedLinearRegression (:48), IsotonicRegressionCalibrator
(IsotonicRegressionCalibrator.scala).  Tree regressors live in ``models.trees``.
"""
from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..types.columns import ColumnarDataset, FeatureColumn
from .classification import _apply_standardize, _extract_xy, _standardize_stats, _unstandardize
from .linear import (
    _damped_solve, _finite_or, fit_linear_regression, linear_predict,
)
from .prediction import PredictionBatch, PredictorEstimator, PredictorModel

__all__ = [
    "OpLinearRegression", "LinearRegressionModel",
    "OpGeneralizedLinearRegression",
    "IsotonicRegressionCalibrator", "IsotonicRegressionModel",
]


class OpLinearRegression(PredictorEstimator):
    """Ridge/elastic-net linear regression — closed-form / FISTA on device."""

    def __init__(self, reg_param: float = 0.0, elastic_net_param: float = 0.0,
                 max_iter: int = 200, tol: float = 1e-7,
                 fit_intercept: bool = True, standardization: bool = True,
                 uid: Optional[str] = None):
        super().__init__(operation_name="linreg", uid=uid)
        self.reg_param = reg_param
        self.elastic_net_param = elastic_net_param
        self.max_iter = max_iter
        self.tol = tol
        self.fit_intercept = fit_intercept
        self.standardization = standardization

    def fit_columns(self, data: ColumnarDataset, label_col, features_col):
        X, y = _extract_xy(label_col, features_col)
        return self.fit_raw(X, y)

    def fit_raw(self, X: np.ndarray, y: np.ndarray, w=None):
        mu, sigma = _standardize_stats(X, w) if self.standardization else (None, None)
        fit = fit_linear_regression(
            _apply_standardize(X, mu, sigma), y, sample_weight=w,
            reg_param=self.reg_param,
            elastic_net_param=self.elastic_net_param, max_iter=self.max_iter,
            tol=self.tol, fit_intercept=self.fit_intercept)
        coef, intercept = _unstandardize(
            np.asarray(fit.coef), float(np.asarray(fit.intercept)), mu, sigma)
        return LinearRegressionModel(coef=coef.tolist(), intercept=float(intercept))

    def fit_device(self, X, y, w, problem_type: str):
        """Sweep path: fit + linear predict stay on device (no coef fetch;
        matrix uploads once, standardization is a device op)."""
        if problem_type != "regression":
            return None
        from .classification import _device_standardize
        from .trees import _dev_f32

        mu, sigma = (_standardize_stats(X, w) if self.standardization
                     else (None, None))
        X_dev = _dev_f32(X)
        Xs = (_device_standardize(X_dev, jnp.asarray(mu), jnp.asarray(sigma))
              if mu is not None else X_dev)
        fit = fit_linear_regression(
            Xs, y, sample_weight=w, reg_param=self.reg_param,
            elastic_net_param=self.elastic_net_param, max_iter=self.max_iter,
            tol=self.tol, fit_intercept=self.fit_intercept)

        def score(Xe):
            Xe_dev = _dev_f32(Xe)
            Xes = (_device_standardize(Xe_dev, jnp.asarray(mu),
                                       jnp.asarray(sigma))
                   if mu is not None else Xe_dev)
            return _device_linear_score(Xes, fit.coef, fit.intercept)
        return score


@jax.jit
def _device_linear_score(X, coef, intercept):
    return X @ coef + intercept


def _aot_linear(X, coef, intercept):
    # AOT-exportable scoring program (serving/aot.py): prediction only
    return (X @ coef + intercept,)


class LinearRegressionModel(PredictorModel):
    def __init__(self, coef: List[float], intercept: float,
                 uid: Optional[str] = None):
        super().__init__(operation_name="linreg", uid=uid)
        self.coef = coef
        self.intercept = intercept

    def predict_batch(self, X: np.ndarray) -> PredictionBatch:
        from .. import native
        if native.AVAILABLE and len(X) <= 4096:
            beta = np.append(np.asarray(self.coef, np.float32),
                             np.float32(self.intercept))
            pred = native.linear_margin(np.asarray(X, np.float32), beta)
        elif isinstance(X, np.ndarray):
            # host BLAS: don't ship a large host matrix to the device for
            # one dot (see LogisticRegressionModel.predict_batch)
            pred = (np.asarray(X, np.float32) @ np.asarray(
                self.coef, np.float32) + np.float32(self.intercept))
        else:
            pred = np.asarray(linear_predict(
                jnp.asarray(self.coef, jnp.float32),
                jnp.float32(self.intercept), X))
        return PredictionBatch(prediction=pred.astype(np.float64))

    def aot_scoring_spec(self):
        from .prediction import AOTScoringSpec
        coef = np.asarray(self.coef, np.float32)
        return AOTScoringSpec(
            name="linreg", fn=_aot_linear,
            params=(coef, np.float32(self.intercept)),
            outputs=("prediction",),
            n_features=int(coef.shape[-1]))


class OpGeneralizedLinearRegression(PredictorEstimator):
    """GLM via IRLS for gaussian/poisson/gamma families (log/identity links).

    Reference OpGeneralizedLinearRegression (impl/regression/:48) wraps
    Spark's GLR; here the IRLS loop is one jitted while_loop.
    """

    def __init__(self, family: str = "gaussian", link: Optional[str] = None,
                 reg_param: float = 0.0, max_iter: int = 50, tol: float = 1e-6,
                 fit_intercept: bool = True, uid: Optional[str] = None):
        super().__init__(operation_name="glm", uid=uid)
        self.family = family
        self.link = link or {"gaussian": "identity", "poisson": "log",
                             "gamma": "log", "binomial": "logit"}[family]
        self.reg_param = reg_param
        self.max_iter = max_iter
        self.tol = tol
        self.fit_intercept = fit_intercept

    def fit_columns(self, data: ColumnarDataset, label_col, features_col):
        X, y = _extract_xy(label_col, features_col)
        return self.fit_raw(X, y)

    def fit_raw(self, X: np.ndarray, y: np.ndarray, w=None):
        if self.family == "gaussian" and self.link == "identity":
            fit = fit_linear_regression(
                X, y, reg_param=self.reg_param, max_iter=self.max_iter,
                tol=self.tol, fit_intercept=self.fit_intercept)
            return GLMModel(coef=np.asarray(fit.coef).tolist(),
                            intercept=float(np.asarray(fit.intercept)),
                            link=self.link)
        coef, intercept = _fit_glm_irls(
            X, y, family=self.family, link=self.link, reg=self.reg_param,
            max_iter=self.max_iter, tol=self.tol,
            fit_intercept=self.fit_intercept)
        return GLMModel(coef=coef.tolist(), intercept=float(intercept),
                        link=self.link)


def _fit_glm_irls(X, y, family, link, reg, max_iter, tol, fit_intercept):
    X = jnp.asarray(X, jnp.float32)
    y = jnp.asarray(y, jnp.float32)
    n, d = X.shape
    Xa = jnp.concatenate([X, jnp.ones((n, 1), X.dtype)], 1) if fit_intercept else X
    da = Xa.shape[1]

    def inv_link(eta):
        if link == "log":
            return jnp.exp(jnp.clip(eta, -30, 30))
        if link == "logit":
            return jax.nn.sigmoid(eta)
        return eta

    def variance(mu):
        if family == "poisson":
            return jnp.maximum(mu, 1e-8)
        if family == "gamma":
            return jnp.maximum(mu ** 2, 1e-8)
        if family == "binomial":
            return jnp.maximum(mu * (1 - mu), 1e-8)
        return jnp.ones_like(mu)

    def dmu_deta(eta, mu):
        if link == "log":
            return jnp.maximum(mu, 1e-8)
        if link == "logit":
            return jnp.maximum(mu * (1 - mu), 1e-8)
        return jnp.ones_like(eta)

    import functools
    from jax import lax

    def step(state):
        beta, _, it = state
        eta = Xa @ beta
        mu = inv_link(eta)
        gp = dmu_deta(eta, mu)
        wirls = gp ** 2 / variance(mu)
        z = eta + (y - mu) / gp
        A = (Xa * wirls[:, None]).T @ Xa / n
        A = A.at[jnp.arange(d), jnp.arange(d)].add(reg)
        b = (Xa * wirls[:, None]).T @ z / n
        nb = _finite_or(_damped_solve(A, b), beta)
        dn = jnp.max(jnp.abs(nb - beta))
        return nb, dn, it + 1

    def cond(state):
        _, dn, it = state
        return (dn > tol) & (it < max_iter)

    beta0 = jnp.zeros(da, jnp.float32)
    beta, _, _ = lax.while_loop(cond, step,
                                (beta0, jnp.float32(jnp.inf), jnp.int32(0)))
    coef = np.asarray(beta[:d])
    intercept = float(beta[d]) if fit_intercept else 0.0
    return coef, intercept


class GLMModel(PredictorModel):
    def __init__(self, coef, intercept, link: str = "identity",
                 uid: Optional[str] = None):
        super().__init__(operation_name="glm", uid=uid)
        self.coef = coef
        self.intercept = intercept
        self.link = link

    def predict_batch(self, X: np.ndarray) -> PredictionBatch:
        eta = X @ np.asarray(self.coef, np.float32) + self.intercept
        if self.link == "log":
            pred = np.exp(eta)
        elif self.link == "logit":
            pred = 1 / (1 + np.exp(-eta))
        else:
            pred = eta
        return PredictionBatch(prediction=pred.astype(np.float64))


class IsotonicRegressionCalibrator(PredictorEstimator):
    """Isotonic calibration via pool-adjacent-violators (host-side).

    Reference IsotonicRegressionCalibrator (impl/regression/).
    """

    def __init__(self, isotonic: bool = True, uid: Optional[str] = None):
        super().__init__(operation_name="isoreg", uid=uid)
        self.isotonic = isotonic

    def fit_columns(self, data: ColumnarDataset, label_col, score_col):
        y = np.nan_to_num(np.asarray(label_col.values, np.float64))
        x = np.asarray(
            score_col.values.probability[:, 1]
            if hasattr(score_col.values, "probability")
            and score_col.values.probability is not None
            else score_col.masked_values(), np.float64)
        sign = 1.0 if self.isotonic else -1.0
        order = np.argsort(x)
        xs, ys = x[order], sign * y[order]
        # pool adjacent violators
        vals: List[float] = []
        wts: List[float] = []
        xs_blocks: List[List[float]] = []
        for xi, yi in zip(xs, ys):
            vals.append(yi)
            wts.append(1.0)
            xs_blocks.append([xi])
            while len(vals) > 1 and vals[-2] > vals[-1]:
                v = (vals[-2] * wts[-2] + vals[-1] * wts[-1]) / (wts[-2] + wts[-1])
                w = wts[-2] + wts[-1]
                xb = xs_blocks[-2] + xs_blocks[-1]
                vals = vals[:-2] + [v]
                wts = wts[:-2] + [w]
                xs_blocks = xs_blocks[:-2] + [xb]
        bx = [float(np.mean(b)) for b in xs_blocks]
        by = [sign * v for v in vals]
        return IsotonicRegressionModel(boundaries=bx, predictions=by)


class IsotonicRegressionModel(PredictorModel):
    def __init__(self, boundaries: List[float], predictions: List[float],
                 uid: Optional[str] = None):
        super().__init__(operation_name="isoreg", uid=uid)
        self.boundaries = boundaries
        self.predictions = predictions

    def predict_batch(self, X: np.ndarray) -> PredictionBatch:
        x = np.asarray(X).reshape(len(X), -1)[:, 0]
        pred = np.interp(x, self.boundaries, self.predictions)
        return PredictionBatch(prediction=pred.astype(np.float64))

    def transform_columns(self, label_col, score_col) -> FeatureColumn:
        vals = score_col.values
        if hasattr(vals, "probability") and vals.probability is not None:
            x = np.asarray(vals.probability[:, 1])
        else:
            x = np.asarray(score_col.masked_values())
        return FeatureColumn(self.output_type, self.predict_batch(x[:, None]))
