"""Tree-ensemble model stages: Random Forest, GBT, Decision Tree, XGBoost-parity.

Reference wrappers being re-implemented natively (no JNI, no Spark):
 * OpRandomForestClassifier (impl/classification/OpRandomForestClassifier.scala:58)
 * OpGBTClassifier (:46), OpDecisionTreeClassifier (:46)
 * OpRandomForestRegressor / OpGBTRegressor / OpDecisionTreeRegressor
   (impl/regression/:47)
 * OpXGBoostClassifier / OpXGBoostRegressor (OpXGBoostClassifier.scala:47,
   OpXGBoostRegressor.scala:48) — the reference's only C++ component
   (xgboost4j, SURVEY §2.11); here the histogram GBDT runs as jitted XLA
   kernels (models.gbdt_kernels) with XGBoost's parameterisation (eta,
   num_round, gamma as RAW loss-reduction threshold, min_child_weight,
   early stopping on a
   validation slice, aucpr eval — DefaultSelectorParams.scala XGB block).

All training happens on the quantized (N, D) int matrix resident on device;
bootstrap resampling is expressed as Poisson sample-weights (no copies).
"""
from __future__ import annotations

import functools
import hashlib
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..types.columns import ColumnarDataset
from .gbdt_kernels import (
    TreeEnsemble, apply_bins, grow_forest_rf, grow_tree, predict_ensemble,
    quantile_bins,
)
from .prediction import PredictionBatch, PredictorEstimator, PredictorModel

__all__ = [
    "OpRandomForestClassifier", "OpRandomForestRegressor",
    "OpGBTClassifier", "OpGBTRegressor",
    "OpDecisionTreeClassifier", "OpDecisionTreeRegressor",
    "OpXGBoostClassifier", "OpXGBoostRegressor",
    "TreeEnsembleModel",
]


class TreeEnsembleModel(PredictorModel):
    """Fitted forest/boosted ensemble.

    mode: 'rf_cls' (leaf = class probs, average), 'rf_reg' (average),
    'gbdt_binary' (sum -> sigmoid), 'gbdt_multi' (sum -> softmax),
    'gbdt_reg' (sum + base).
    """

    def __init__(self, mode: str, edges, feat, thresh, leaf,
                 base_score: float = 0.0, n_classes: int = 2,
                 uid: Optional[str] = None):
        super().__init__(operation_name="treeEnsemble", uid=uid)
        self.mode = mode
        self.edges = edges
        self.feat = feat
        self.thresh = thresh
        self.leaf = leaf
        self.base_score = base_score
        self.n_classes = n_classes

    def _raw(self, X: np.ndarray) -> np.ndarray:
        depth = int(np.log2(self.feat.shape[1] + 1))
        from .. import native
        # small-batch serving (the local scorer's case): the C++ kernels skip
        # JAX dispatch + device transfer — measured ~240x lower 1-row latency.
        # Only when the ensemble is already host-resident, though: a freshly
        # fitted model keeps its trees on device so CV never downloads the
        # ~3 MB ensemble per candidate just to score it; XLA predicts and only
        # the (N, K) scores come back.  Large batches stay on XLA either way.
        host_trees = isinstance(self.feat, np.ndarray)
        if native.AVAILABLE and host_trees and len(X) <= 4096:
            binned = native.apply_bins(np.asarray(X, np.float32),
                                       np.asarray(self.edges, np.float32))
            return native.predict_ensemble(
                binned, np.asarray(self.feat), np.asarray(self.thresh),
                np.asarray(self.leaf), depth)
        # memoized binning: big matrices quantize on host and upload int8
        binned = _binned_for_edges(X, self.edges)
        feat = jnp.asarray(self.feat, jnp.int32)
        thresh = jnp.asarray(self.thresh, jnp.int32)
        leaf = jnp.asarray(self.leaf, jnp.float32)
        out = predict_ensemble(binned, feat, thresh, leaf, depth)
        return np.asarray(out)

    def score_device(self, X: np.ndarray, problem_type: str):
        """Device validation scores: ONE fused program (predict + mode
        transform) — un-jitted ops each cost a ~30 ms tunnel dispatch."""
        depth = int(np.log2(self.feat.shape[1] + 1))
        binned = _binned_for_edges(X, self.edges)
        return _score_ensemble_jit(
            binned, jnp.asarray(self.feat, jnp.int32),
            jnp.asarray(self.thresh, jnp.int32),
            jnp.asarray(self.leaf, jnp.float32),
            jnp.float32(self.base_score), depth, self.mode, problem_type)

    def predict_batch(self, X: np.ndarray) -> PredictionBatch:
        raw = self._raw(X)
        t = self.feat.shape[0]
        if self.mode == "rf_cls":
            proba = raw / t
            proba = np.clip(proba, 1e-9, 1.0)
            proba = proba / proba.sum(axis=1, keepdims=True)
            return PredictionBatch(
                prediction=proba.argmax(axis=1).astype(np.float64),
                raw_prediction=raw, probability=proba)
        if self.mode == "rf_reg":
            return PredictionBatch(prediction=(raw[:, 0] / t
                                               + self.base_score).astype(np.float64))
        if self.mode == "gbdt_binary":
            z = raw[:, 0] + self.base_score
            p1 = 1.0 / (1.0 + np.exp(-z))
            proba = np.stack([1 - p1, p1], axis=1)
            return PredictionBatch(
                prediction=(p1 >= 0.5).astype(np.float64),
                raw_prediction=np.stack([-z, z], axis=1), probability=proba)
        if self.mode == "gbdt_multi":
            z = raw + self.base_score
            e = np.exp(z - z.max(axis=1, keepdims=True))
            proba = e / e.sum(axis=1, keepdims=True)
            return PredictionBatch(
                prediction=proba.argmax(axis=1).astype(np.float64),
                raw_prediction=z, probability=proba)
        # gbdt_reg
        return PredictionBatch(
            prediction=(raw[:, 0] + self.base_score).astype(np.float64))


@functools.partial(jax.jit,
                   static_argnames=("depth", "mode", "problem_type"))
def _score_ensemble_jit(binned, feat, thresh, leaf, base_score, depth: int,
                        mode: str, problem_type: str):
    raw = predict_ensemble(binned, feat, thresh, leaf, depth)
    t = feat.shape[0]
    if mode == "rf_cls":
        proba = jnp.clip(raw / t, 1e-9, 1.0)
        proba = proba / proba.sum(axis=1, keepdims=True)
        return (proba[:, 1] if problem_type == "binary"
                else jnp.argmax(proba, axis=1).astype(jnp.float32))
    if mode == "rf_reg":
        return raw[:, 0] / t + base_score
    if mode == "gbdt_binary":
        p1 = jax.nn.sigmoid(raw[:, 0] + base_score)
        return (p1 if problem_type == "binary"
                else (p1 >= 0.5).astype(jnp.float32))
    if mode == "gbdt_multi":
        return jnp.argmax(raw, axis=1).astype(jnp.float32)
    return raw[:, 0] + base_score  # gbdt_reg


import threading
from collections import OrderedDict

_BIN_CACHE: "OrderedDict" = OrderedDict()
_BIN_CACHE_CAPACITY = 32
_HASH_BY_ID: dict = {}
_MEMO_LOCK = threading.Lock()
#: key -> Event for builds in flight (the sketch-prefetch thread and the
#: sweep's tree group may race to the same prep; second caller waits)
_MEMO_INFLIGHT: dict = {}
#: bumped by clear_sweep_caches: an in-flight build that started before a
#: clear must not repopulate the cache after it (device buffers would
#: outlive the end-of-train housekeeping)
_MEMO_GEN = 0


def clear_sweep_caches() -> None:
    """Release the sweep memos' device buffers (end-of-train housekeeping).

    Takes the memo lock (a prefetch thread may be mutating the cache) and
    bumps the generation so in-flight builds that started before the clear
    do not repopulate it afterwards."""
    global _MEMO_GEN
    with _MEMO_LOCK:
        _MEMO_GEN += 1
        _BIN_CACHE.clear()
        _HASH_BY_ID.clear()
        _CONTIG_BY_ID.clear()


def _memo_peek(key):
    """Memo probe without building (None on miss)."""
    with _MEMO_LOCK:
        hit = _BIN_CACHE.get(key)
        if hit is not None:
            _BIN_CACHE.move_to_end(key)
        return hit


def _memo(key, build):
    """Content-keyed sweep memo with LRU eviction.

    A CV×grid sweep re-touches the same fold matrices for every candidate;
    through a remote-TPU tunnel each redundant upload/binning launch costs
    tens of milliseconds (seconds at 1M rows), so device uploads deduplicate
    by content hash.  Eviction is oldest-first — a wholesale clear would
    re-upload the sweep's hot fold matrices mid-run.

    Thread-aware: concurrent builders of the SAME key deduplicate (the
    selector's sketch-prefetch thread overlaps host prep with the sweep's
    queued device work; when the tree group arrives it waits for the
    in-flight build instead of re-sketching a GB-scale matrix).
    """
    with _MEMO_LOCK:
        hit = _BIN_CACHE.get(key)
        if hit is not None:
            _BIN_CACHE.move_to_end(key)
            return hit
        ev = _MEMO_INFLIGHT.get(key)
        owner = ev is None
        if owner:
            ev = threading.Event()
            _MEMO_INFLIGHT[key] = ev
        gen = _MEMO_GEN
    if not owner:
        ev.wait()
        with _MEMO_LOCK:
            hit = _BIN_CACHE.get(key)
        if hit is not None:
            return hit
        # the owning build failed (or a clear raced it): build here too —
        # concurrent rebuilds on this rare path are benign (same content)
    try:
        val = build()
        with _MEMO_LOCK:
            # insert BEFORE waking waiters (they re-probe the cache on
            # wake); skip if clear_sweep_caches ran since the build began
            if _MEMO_GEN == gen:
                while len(_BIN_CACHE) >= _BIN_CACHE_CAPACITY:
                    _BIN_CACHE.popitem(last=False)
                _BIN_CACHE[key] = val
    finally:
        if owner:
            with _MEMO_LOCK:
                _MEMO_INFLIGHT.pop(key, None)
            ev.set()
    return val


_BIG_ARRAY_BYTES = 64 << 20


def _sample_digest(a: np.ndarray) -> str:
    """Cheap per-call digest over a strided sample + both array ends.

    Guards the per-object hash cache of NON-frozen (view) arrays against
    IN-PLACE mutation: any realistic batch overwrite perturbs the sampled
    bytes, changing the memo key even though the cached base hash is stale.
    ~40 KB of work regardless of array size.
    """
    flat = a.reshape(-1)
    step = max(1, flat.size // 8192)
    parts = (np.ascontiguousarray(flat[::step]).tobytes()
             + flat[:1024].tobytes() + flat[-1024:].tobytes())
    return hashlib.md5(parts).hexdigest()[:16]


def _full_hash(a: np.ndarray) -> str:
    """Full-bytes content hash: md5 up to 64 MB, crc32+adler32 (each ~GB/s
    in C) beyond, where md5's ~1 s/GB would show up in sweep latency.  The
    weaker big-array checksum pair is never used alone — ``_content_hash``
    always appends the per-call md5 sample digest to the memo key."""
    if a.nbytes > _BIG_ARRAY_BYTES:
        import zlib
        mv = memoryview(np.ascontiguousarray(a)).cast("B")
        return f"crc{zlib.crc32(mv):08x}a{zlib.adler32(mv):08x}n{len(mv)}"
    return hashlib.md5(a.tobytes()).hexdigest()


_SMALL_REHASH_BYTES = 1 << 20


def _content_hash(a: np.ndarray) -> str:
    """Memo key component for an array: full-bytes content hash + per-call
    mutation guard.

    The sweep usually probes the memo with the SAME matrix object for every
    candidate; a per-object cache makes those probes free.  Arrays up to
    1 MB are fully re-hashed on every probe (sub-ms — exact, no staleness).
    Bigger arrays hash their full bytes ONCE per object (ADVICE r1: big
    arrays previously keyed by identity only) and append a per-call sampled
    digest (strided sample + both ends) so realistic in-place overwrites
    change the key even though the cached base hash is stale.  In-place
    batch reuse of a fitted matrix therefore stays supported.
    """
    if a.nbytes <= _SMALL_REHASH_BYTES:
        return hashlib.md5(a.tobytes()).hexdigest()
    import weakref
    k = id(a)
    h = _HASH_BY_ID.get(k)
    if h is None:
        h = _full_hash(a)
        _HASH_BY_ID[k] = h
        try:
            weakref.finalize(a, _HASH_BY_ID.pop, k, None)
        except TypeError:  # pragma: no cover - non-weakrefable view
            _HASH_BY_ID.pop(k, None)
    return f"{h}-{_sample_digest(a)}"


_CONTIG_BY_ID: dict = {}


def _view_digest(Xf: np.ndarray) -> str:
    """Cheap mutation guard for a possibly-strided array: strided row sample
    + both ends, no full reshape (reshape(-1) of a non-contiguous matrix
    would copy the whole thing)."""
    if Xf.ndim == 0 or Xf.size == 0:
        return hashlib.md5(Xf.tobytes()).hexdigest()[:16]
    step = max(1, Xf.shape[0] // 256)
    parts = (np.ascontiguousarray(Xf[::step]).tobytes()
             + np.ascontiguousarray(Xf[:1]).tobytes()
             + np.ascontiguousarray(Xf[-1:]).tobytes())
    return hashlib.md5(parts).hexdigest()[:16]


def _as_f32(X) -> np.ndarray:
    """float32 C-contiguous view; returns X itself when already so (keeps
    object identity stable for the per-object hash cache).

    A non-contiguous input (e.g. the SanityChecker's column-filtered matrix)
    is copied ONCE per object and memoized — the selector sweep probes with
    the same matrix for every candidate, and re-copying a GB-scale matrix
    per probe measured ~17 s of a 200k-row sweep.  A sampled digest guards
    the cache against in-place mutation of the source."""
    Xf = np.asarray(X, np.float32)
    if Xf.flags.c_contiguous:
        return Xf
    k = id(X)
    digest = _view_digest(Xf)
    hit = _CONTIG_BY_ID.get(k)
    if hit is not None and hit[0] == digest:
        return hit[1]
    Xc = np.ascontiguousarray(Xf)
    _CONTIG_BY_ID[k] = (digest, Xc)
    try:
        import weakref
        weakref.finalize(X, _CONTIG_BY_ID.pop, k, None)
    except TypeError:  # pragma: no cover - non-weakrefable input
        _CONTIG_BY_ID.pop(k, None)
    return Xc


def _upload_timed(a):
    """jnp.asarray with transfer accounting (bytes + enqueue-blocking time)."""
    import time as _time

    from ..utils.profiling import count_upload
    t0 = _time.perf_counter()
    out = jnp.asarray(a)
    count_upload(a.nbytes, _time.perf_counter() - t0)
    return out


def _dev_memo(arr, tag: str = "up"):
    """Upload a host array once per distinct content."""
    a = np.asarray(arr)
    if not a.flags.c_contiguous:
        a = _as_f32(arr) if a.dtype == np.float32 else np.ascontiguousarray(a)
    key = (tag, _content_hash(a), a.shape, str(a.dtype))
    return _memo(key, lambda: _upload_timed(a))


#: past this element count the shared matrix uploads as bf16 (half the
#: tunnel bytes; measured upload bandwidth is ~10-20 MB/s and byte-
#: proportional, so a 1M x 500 f32 matrix costs ~2 minutes vs ~1 as bf16).
#: bf16 keeps f32's exponent range (no overflow on large-magnitude
#: features); matmul consumers accumulate in f32 either way.
_BF16_UPLOAD_ELEMS = 1 << 25


def _dev_f32(X, tag: str = "X_f32"):
    """THE shared device upload of a host matrix.

    Every consumer of the full matrix (linear-model fits, device
    standardization stats, on-device quantile binning, SanityChecker-scale
    stats) goes through this one memo, so a selector sweep uploads the
    GB-scale matrix across the tunnel exactly once per train.  Large
    matrices (``_BF16_UPLOAD_ELEMS``) upload as bf16 — the tunnel is the
    sweep's dominant cost at headline shapes — and consumers upcast on
    device; small ones stay exact f32.

    This applies to the sweep AND to big-matrix refits/scoring of the
    winning linear model — a deliberate trade (bf16 keeps f32's exponent
    range; coefficient noise is ~1e-3 relative and measured AuPR-neutral)
    because a second full-precision upload would cost another ~2 minutes at
    1M x 500.  Set ``TMOG_MATRIX_PRECISION=f32`` to force exact uploads.
    """
    import os

    from .gbdt_kernels import _accel_bf16

    Xf = _as_f32(X)
    force_f32 = (os.environ.get("TMOG_MATRIX_PRECISION", "auto") == "f32"
                 or not _accel_bf16())   # no tunnel to save on CPU, and
    #                                      XLA-CPU bf16 matmuls are emulated
    if tag == "X_f32" and Xf.size > _BF16_UPLOAD_ELEMS and not force_f32:
        hx = _content_hash(Xf)
        key = ("X_bf16", hx, Xf.shape)

        def build():
            import ml_dtypes
            return _upload_timed(Xf.astype(ml_dtypes.bfloat16))
        return _memo(key, build)
    return _dev_memo(Xf, tag)


def _dev_memo_sharded(arr, sharding, tag: str = "up"):
    """Upload a host array ONCE per (content, sharding) — the mesh sweep
    probes with the same fold matrices for every grid candidate, and each
    redundant sharded upload costs seconds of tunnel transfer."""
    import jax

    a = np.ascontiguousarray(np.asarray(arr))
    key = (tag, _content_hash(a), a.shape, str(a.dtype), str(sharding))
    return _memo(key, lambda: jax.device_put(a, sharding))


@jax.jit
def _apply_bins_i8(X: jnp.ndarray, edges: jnp.ndarray) -> jnp.ndarray:
    """On-device quantization to int8 (B <= 127), for when the matrix is
    already device-resident: skips the host binning pass AND the int8 upload."""
    X = X.astype(jnp.float32)
    return jnp.sum(X[:, :, None] > edges[None, :, :], axis=2).astype(jnp.int8)


def _binned_for_edges(X, edges):
    """Device-binned matrix for given edges (scoring path).

    Shares one memo entry with the fit path (``_prep_tree_inputs``), keyed by
    (matrix, edges) content — scoring the training matrix re-binned it from
    scratch before (measured 2x the whole binning cost per sweep)."""
    Xf = _as_f32(X)
    return _binned_cached(Xf, _content_hash(Xf), edges)


def _binned_cached(Xf: np.ndarray, hx: str, edges):
    ef = np.ascontiguousarray(np.asarray(edges, np.float32))
    key = ("bins", hx, _content_hash(ef), Xf.shape)

    def build():
        big = Xf.size > _HOST_BIN_ELEMS and ef.shape[1] < 127
        if big:
            # reuse the sweep's shared upload when present: device binning
            # is one launch vs a ~10 s/1M-row host pass + a second upload.
            # (Binning the bf16 copy can flip values that sit within bf16
            # rounding of an edge — immaterial to quantile-bin trees.)
            # explicit None test: `or` would ask the device array for truth
            xdev = _memo_peek(("X_bf16", hx, Xf.shape))
            if xdev is None:
                xdev = _memo_peek(("X_f32", hx, Xf.shape, "float32"))
            if xdev is not None:
                from ..utils.profiling import count_launch
                count_launch("device_bin")
                return _apply_bins_i8(xdev, jnp.asarray(ef))
            return _upload_timed(_host_bins(Xf, ef))
        return apply_bins(jnp.asarray(Xf), jnp.asarray(ef))
    return _memo(key, build)


_HOST_BIN_ELEMS = 1 << 22


def _host_bins(Xf: np.ndarray, edges: np.ndarray) -> np.ndarray:
    """Host-side quantization, uploaded as int8 (B <= 127).

    At 1M×500 the device path uploads ~800 MB of f32 (X for apply_bins plus
    the int32 result paid again on fetch-free reuse); binning on host and
    shipping int8 cuts the tunnel transfer 8x (measured 35 s -> ~4 s prep).
    """
    n, d = Xf.shape
    out = np.empty((n, d), np.int8)
    for j in range(d):
        # apply_bins counts edges < x; searchsorted(left) on sorted edges
        # (dedup +inf sentinels sort to the end) gives the same count.
        # NaN sorts past +inf in searchsorted but compares False against
        # every edge on device — pin it to bin 0 to match.
        col = Xf[:, j]
        b = np.searchsorted(np.sort(edges[j]), col,
                            side="left").astype(np.int8)
        out[:, j] = np.where(np.isnan(col), np.int8(0), b)
    return out


def _prep_tree_inputs(X, max_bins):
    """Quantile-sketch + binning (fit path); shares the binned-matrix memo
    with the scoring path (same (matrix, edges) key)."""
    Xf = _as_f32(X)
    hx = _content_hash(Xf)
    edges = _memo(("edges", hx, Xf.shape, max_bins),
                  lambda: quantile_bins(Xf, max_bins))
    return edges, _binned_cached(Xf, hx, edges)


def _prep_tree_inputs_mesh(X, max_bins, mesh):
    """Quantile sketch + binning with the sketch MESH-SHARDED: each shard
    samples its rows, the samples all_gather over ICI, quantiles compute
    replicated (parallel.sharded.quantile_bins_sharded — the analogue of
    the reference's executor-distributed sketch, RawFeatureFilter.scala:
    489-545 / XGBoost's Rabit sketch).  Same memo keys per (matrix, mesh
    topology) so a sweep sketches once.

    Mostly-zero matrices keep the HOST sparse-aware sketch (pinned 0.0
    edge, full resolution on the nonzeros): the sharded sketch has no
    nonzero-aware variant yet, and an all-values sketch of a 95%-zero
    feature collapses to ~2 usable bins (code-review r5)."""
    from ..parallel.sharded import quantile_bins_sharded

    Xf = _as_f32(X)
    n = Xf.shape[0]
    step = max(1, n // 4096)
    if (Xf.size >= _SPARSE_MIN_ELEMS
            and float((Xf[::step] == 0).mean()) >= _SPARSE_ZERO_FRAC):
        e, b, _ = _prep_tree_inputs_sparse(Xf, max_bins)
        return e, b
    hx = _content_hash(Xf)
    mesh_key = tuple(sorted(mesh.shape.items()))
    edges = _memo(("edges_mesh", hx, Xf.shape, max_bins, mesh_key),
                  lambda: quantile_bins_sharded(Xf, mesh, max_bins))
    return edges, _binned_cached(Xf, hx, edges)


#: sampled zero fraction at/above which the tree fit takes the sparse path
#: (nonzero-aware sketch + CSR histogram build)
_SPARSE_ZERO_FRAC = 0.75
#: below this element count the dense kernel is fast enough that CSR
#: build cost isn't worth it
_SPARSE_MIN_ELEMS = 1 << 24


def _prep_tree_inputs_sparse(X, max_bins):
    """Like ``_prep_tree_inputs`` but detects wide mostly-zero matrices:
    their bin edges sketch over the NONZERO values
    (quantile_bins_sparse_aware) — an all-values sketch of a 95%-zero
    feature collapses to ~2 usable bins, while XGBoost's sketch is
    sparsity-aware (SURVEY §2.11); matching it measured +0.016 train AuPR
    on the config-5 shape at the same round budget.

    The third return element is the CSR device triple for the sparse
    HISTOGRAM path (gbdt_kernels._sparse_level_hists) — opt-in via
    ``TMOG_SPARSE_HIST=1``, default OFF: measured at 250k×1000×5% the
    per-feature-batched CSR matmuls ((D, M, E)@(D, E, B·nchan), ~tens of
    rows/cols per batch element) run ~2.2× SLOWER per round than the
    dense bf16 one-hot stream at every slot width (1185-1353 ms vs 557 ms
    per depth-10 round) — the MXU wants the dense formulation's big
    tiles; the sparse win needs a Pallas accumulation kernel, not a
    matmul reshuffle.  The build stays for that work (parity-tested in
    tests/test_sparse_path.py).
    """
    import os

    from .gbdt_kernels import (
        build_feature_csr, quantile_bins_sparse_aware,
    )

    Xf = _as_f32(X)
    n, d = Xf.shape
    if Xf.size < _SPARSE_MIN_ELEMS:
        e, b = _prep_tree_inputs(Xf, max_bins)
        return e, b, None
    step = max(1, n // 4096)
    if float((Xf[::step] == 0).mean()) < _SPARSE_ZERO_FRAC:
        e, b = _prep_tree_inputs(Xf, max_bins)
        return e, b, None
    hx = _content_hash(Xf)
    edges = _memo(("edges_sp", hx, Xf.shape, max_bins),
                  lambda: quantile_bins_sparse_aware(Xf, max_bins))
    binned = _binned_cached(Xf, hx, edges)
    if os.environ.get("TMOG_SPARSE_HIST", "0") != "1":
        return edges, binned, None

    def build():
        host = build_feature_csr(Xf, edges)
        if host is None:
            return ()          # non-qualifying: memoized as empty, not None
        rows, bins, zero_bin = host
        zb_oh = np.eye(max_bins, dtype=np.float32)[zero_bin]   # (D, B)
        return (_upload_timed(rows), _upload_timed(bins),
                _upload_timed(zb_oh))
    csr = _memo(("csr", hx, Xf.shape, max_bins), build)
    return edges, binned, (csr if csr else None)


def _efb_enabled() -> bool:
    """``TMOG_EFB``: '0' disables exclusive feature bundling, '1' forces
    it past the width-ratio gate, 'auto' (default) engages when the
    greedy packer shrinks the histogram width enough to pay for the
    re-encode pass (gbdt_kernels.EFB_MIN_WIDTH_RATIO)."""
    import os

    return os.environ.get("TMOG_EFB", "auto") != "0"


def _maybe_bundle(hx: str, edges, binned, max_bins: int):
    """Memoized EFB plan + bundled device matrices for a fit matrix.

    Returns ``(FeatureBundles, bundled binned device array, end-bin device
    array)`` or None when bundling declines.  Keyed on the SAME content
    hash as the edges/binned memos, so one host pack serves every
    candidate of a sweep; the host binned matrix downloads once (the
    device copy is the memoized upload — on-host backends this is free).
    """
    import os

    from .gbdt_kernels import (EFB_MIN_WIDTH_RATIO, bundle_features,
                               bundle_matrix)

    force = os.environ.get("TMOG_EFB", "auto") == "1"
    # edges participate in the key: the weight-aware sketch can produce
    # different edges for the same matrix content (TM024 pad rows)
    ec = np.ascontiguousarray(np.asarray(edges, np.float32))
    key = ("efb", hx, _content_hash(ec), tuple(binned.shape), max_bins,
           force)

    def build():
        host = np.asarray(binned)
        b = bundle_features(host, np.asarray(edges), max_bins,
                            min_width_ratio=(1.0 if force
                                             else EFB_MIN_WIDTH_RATIO))
        if b is None:
            return ()
        return (b, _upload_timed(bundle_matrix(b, host)),
                _upload_timed(b.end_bin))

    val = _memo(key, build)
    return val if val else None


def _prep_tree_inputs_weighted(X, max_bins: int, row_weight=None):
    """``_prep_tree_inputs_sparse`` with a PADDING-aware sketch: a
    TRAILING block of zero-total-weight rows (mesh row padding — the
    TM024 contract's shape) is excluded from the quantile sketch, since
    pad rows participate in no fit and must not move the bin edges;
    binning still covers every row.  INTERIOR zero-weight rows (holdout
    reservations, balancer drops) stay in the sketch — the sequential
    per-candidate fits sketch over all rows, and the batched groups must
    bin with the same edges those fits would win selection with.
    """
    Xf = _as_f32(X)
    if row_weight is None:
        return _prep_tree_inputs_sparse(Xf, max_bins)
    w = np.asarray(row_weight)
    nz = np.nonzero(w > 0)[0]
    if len(nz) == 0 or nz[-1] == len(w) - 1:
        return _prep_tree_inputs_sparse(Xf, max_bins)
    Xm = np.ascontiguousarray(Xf[: nz[-1] + 1])
    hxm = _content_hash(Xm)
    step = max(1, Xm.shape[0] // 4096)
    if (Xm.size >= _SPARSE_MIN_ELEMS
            and float((Xm[::step] == 0).mean()) >= _SPARSE_ZERO_FRAC):
        from .gbdt_kernels import quantile_bins_sparse_aware

        edges = _memo(("edges_sp", hxm, Xm.shape, max_bins),
                      lambda: quantile_bins_sparse_aware(Xm, max_bins))
    else:
        edges = _memo(("edges", hxm, Xm.shape, max_bins),
                      lambda: quantile_bins(Xm, max_bins))
    return edges, _binned_cached(Xf, _content_hash(Xf), edges), None


def _feature_subset_size(strategy: str, d: int, is_classification: bool) -> int:
    if strategy == "all":
        return d
    if strategy == "sqrt" or (strategy == "auto" and is_classification):
        return max(1, int(np.sqrt(d)))
    if strategy == "onethird" or (strategy == "auto" and not is_classification):
        return max(1, d // 3)
    return d


class _RandomForestBase(PredictorEstimator):
    def __init__(self, num_trees: int = 20, max_depth: int = 5,
                 max_bins: int = 32, min_instances_per_node: int = 1,
                 min_info_gain: float = 0.0, subsample_rate: float = 1.0,
                 feature_subset_strategy: str = "auto", seed: int = 42,
                 uid: Optional[str] = None):
        super().__init__(operation_name=self._op_name, uid=uid)
        self.num_trees = num_trees
        self.max_depth = max_depth
        self.max_bins = max_bins
        self.min_instances_per_node = min_instances_per_node
        self.min_info_gain = min_info_gain
        self.subsample_rate = subsample_rate
        self.feature_subset_strategy = feature_subset_strategy
        self.seed = seed
        #: optional jax.sharding.Mesh: rows shard over the mesh's data axis
        #: and per-level histograms psum over ICI (grow_forest_sharded);
        #: runtime-only (not a persisted ctor param)
        self.mesh = None

    def with_mesh(self, mesh) -> "_RandomForestBase":
        self.mesh = mesh
        return self

    _op_name = "randomForest"
    _classification = True

    def fit_columns(self, data: ColumnarDataset, label_col, features_col):
        X = np.asarray(features_col.values, dtype=np.float32)
        y = np.nan_to_num(np.asarray(label_col.values, dtype=np.float32))
        return self.fit_raw(X, y)

    def fit_raw(self, X: np.ndarray, y: np.ndarray, w=None):
        n, d = X.shape
        if self.mesh is not None:
            # mesh-sharded sketch (all_gather'd per-shard samples) — the
            # executor-distributed sketch of the reference (VERDICT r4 #5)
            edges, binned = _prep_tree_inputs_mesh(X, self.max_bins,
                                                   self.mesh)
        else:
            # sparse-aware sketch (CSR unused — RF histograms run at
            # feature-subset width): the SAME edges/memo keys as
            # RFGridGroup's sweep, so a winner refit on a qualifying sparse
            # matrix trains with the bin edges the candidate won selection
            # on (ADVICE r4 medium) and reuses the sweep's host sketch +
            # binned-matrix upload
            edges, binned, _ = _prep_tree_inputs_sparse(X, self.max_bins)
        base_w = (np.ones(n, np.float32) if w is None
                  else np.asarray(w, np.float32))
        if self._classification:
            k = max(int(y.max()) + 1, 2)
            Y = np.eye(k, dtype=np.float32)[y.astype(int)]
        else:
            k = 1
            Y = y[:, None].astype(np.float32)
        msub = _feature_subset_size(self.feature_subset_strategy, d,
                                    self._classification)
        if self.mesh is not None:
            f, th, lf = self._fit_sharded(binned, Y, base_w, msub)
        else:
            # bootstrap bags (Poisson weights) + feature subsets generate ON
            # DEVICE from the seed (grow_forest_rf); the fold data uploads
            # once (memoized), so each candidate fit is a couple of
            # scalar-arg launches — no per-tree weights cross the tunnel
            f, th, lf = grow_forest_rf(
                binned, _dev_memo(Y, "rf_Y"), _dev_memo(base_w, "rf_w"),
                seed=self.seed, n_trees=self.num_trees, msub=msub,
                subsample_rate=self.subsample_rate,
                max_depth=self.max_depth, n_bins=self.max_bins, lam=1e-3,
                min_info_gain=self.min_info_gain,
                min_instances=float(self.min_instances_per_node),
                onehot_targets=self._classification)
        # ensemble stays device-resident: during model selection only the
        # scores come back to host; the winning ensemble downloads lazily at
        # persistence/native-serving time (TreeEnsembleModel._raw)
        mode = "rf_cls" if self._classification else "rf_reg"
        return TreeEnsembleModel(
            mode=mode, edges=edges, feat=f, thresh=th, leaf=lf,
            n_classes=k if self._classification else 2)


    def _fit_sharded(self, binned, Y, base_w, msub: int):
        """Multi-chip fit: pad rows to tile the mesh's data axis (padded
        rows carry zero bag weight) and grow with psum'd histograms.
        Bags/feature subsets come from the SAME generator as the
        single-device path (gbdt_kernels._rf_bag_and_features) so both grow
        from identical randomness; split decisions can still differ at
        rounding margins (bf16 subset histograms vs f32 full-width)."""
        from ..parallel.mesh import pad_to_multiple
        from ..parallel.sharded import grow_forest_sharded
        from .gbdt_kernels import rf_bags_and_features

        n, d = binned.shape
        T = self.num_trees
        BWr, feat_idx = rf_bags_and_features(
            self.seed, T, n, d, msub, self.subsample_rate)
        BW = np.asarray(base_w, np.float32)[None, :] * BWr
        masks = np.zeros((T, d), bool)
        np.put_along_axis(masks, feat_idx, True, axis=1)
        ndata = self.mesh.shape[self.mesh.axis_names[0]]
        binned_h, _ = pad_to_multiple(np.asarray(binned), ndata, axis=0)
        BW, _ = pad_to_multiple(BW, ndata, axis=1)   # zero weight on pad
        Y_h, _ = pad_to_multiple(np.asarray(Y, np.float32), ndata, axis=0)
        return grow_forest_sharded(
            binned_h, Y_h, BW, masks, self.mesh,
            max_depth=self.max_depth, n_bins=self.max_bins, lam=1e-3,
            min_info_gain=self.min_info_gain,
            min_instances=float(self.min_instances_per_node),
            onehot_targets=self._classification)


class OpRandomForestClassifier(_RandomForestBase):
    _op_name = "randomForestCls"
    _classification = True


class OpRandomForestRegressor(_RandomForestBase):
    _op_name = "randomForestReg"
    _classification = False


class OpDecisionTreeClassifier(OpRandomForestClassifier):
    """Single unbagged tree (OpDecisionTreeClassifier parity)."""

    _op_name = "decisionTreeCls"

    def __init__(self, max_depth: int = 5, max_bins: int = 32,
                 min_instances_per_node: int = 1, min_info_gain: float = 0.0,
                 seed: int = 42, uid: Optional[str] = None):
        super().__init__(num_trees=1, max_depth=max_depth, max_bins=max_bins,
                         min_instances_per_node=min_instances_per_node,
                         min_info_gain=min_info_gain, subsample_rate=1.0,
                         feature_subset_strategy="all", seed=seed, uid=uid)
        # single tree: no bootstrap
        self.subsample_rate = 0.0

    def fit_raw(self, X, y, w=None):
        # bypass Poisson bagging: weight 1 everywhere
        self_copy = self
        n, d = X.shape
        edges, binned = _prep_tree_inputs(X, self.max_bins)
        base_w = (np.ones(n, np.float32) if w is None
                  else np.asarray(w, np.float32))
        if self._classification:
            k = max(int(y.max()) + 1, 2)
            Y = np.eye(k, dtype=np.float32)[y.astype(int)]
        else:
            k = 1
            Y = y[:, None].astype(np.float32)
        G = jnp.asarray(Y * base_w[:, None])
        H = jnp.asarray(np.repeat(base_w[:, None], k, axis=1))
        f, th, lf = grow_tree(
            binned, G, H, jnp.asarray(base_w), max_depth=self.max_depth,
            n_bins=self.max_bins, lam=1e-3, min_info_gain=self.min_info_gain,
            min_instances=float(self.min_instances_per_node),
            newton_leaf=False)
        mode = "rf_cls" if self._classification else "rf_reg"
        return TreeEnsembleModel(
            mode=mode, edges=edges, feat=np.asarray(f)[None],
            thresh=np.asarray(th)[None], leaf=np.asarray(lf)[None],
            n_classes=k if self._classification else 2)


class OpDecisionTreeRegressor(OpDecisionTreeClassifier):
    _op_name = "decisionTreeReg"
    _classification = False


class _GBTBase(PredictorEstimator):
    """Gradient-boosted trees (binary logistic / multiclass softmax / squared).

    Spark-GBT parameterisation (maxIter, stepSize, maxDepth) with XGBoost
    extras (reg_lambda, min_child_weight, gamma->min_split_gain, subsample,
    colsample, early stopping).
    """

    _op_name = "gbt"
    _objective = "binary"  # or "regression", "multiclass"

    def __init__(self, max_iter: int = 20, max_depth: int = 5,
                 step_size: float = 0.1, max_bins: int = 32,
                 reg_lambda: float = 1.0, min_child_weight: float = 1.0,
                 min_info_gain: float = 0.0, subsample_rate: float = 1.0,
                 colsample: float = 1.0,
                 early_stopping_rounds: int = 0,
                 validation_fraction: float = 0.2,
                 min_instances_per_node: int = 1,
                 min_split_gain_raw: float = 0.0,
                 seed: int = 42, hist_precision: str = "bf16",
                 sparse_default_direction: bool = False,
                 uid: Optional[str] = None):
        super().__init__(operation_name=self._op_name, uid=uid)
        self.max_iter = max_iter
        self.max_depth = max_depth
        self.step_size = step_size
        self.max_bins = max_bins
        self.reg_lambda = reg_lambda
        self.min_child_weight = min_child_weight
        self.min_info_gain = min_info_gain
        self.subsample_rate = subsample_rate
        self.colsample = colsample
        self.early_stopping_rounds = early_stopping_rounds
        self.validation_fraction = validation_fraction
        self.min_instances_per_node = min_instances_per_node
        #: XGBoost's gamma: RAW loss-reduction threshold (not Spark's
        #: per-node-weight minInfoGain)
        self.min_split_gain_raw = min_split_gain_raw
        self.seed = seed
        #: XGBoost missing-value semantics: each split also learns a
        #: default direction for the bin-0 (missing/absent) bucket by
        #: trying both routings in the gain search — the actual sparsity
        #: feature of the C++ core (OpXGBoostClassifier.scala:47 wraps it).
        #: Default ON for the XGB-parameterised estimators, OFF for the
        #: Spark-GBT-parity ones (MLlib has no default direction).
        self.sparse_default_direction = sparse_default_direction
        #: 'bf16' (default) or 'f32': histogram one-hot/dot precision.
        #: bf16 halves the (rows, bins·features) one-hot stream — the
        #: kernel's bandwidth floor — and runs the dots at ~2x MXU
        #: throughput.  RF always ran it (integer channels, exact); for
        #: GBT's continuous compounding gradients the default is backed by
        #: the measured quality gate in tests/test_bf16_gate.py (holdout
        #: AuPR/RMSE deltas inside seed noise).  Set 'f32' to opt out.
        self.hist_precision = hist_precision
        self.mesh = None

    def _hist_bf16(self) -> bool:
        """The STATIC hist-precision flag handed to the jitted growth
        programs: requested precision AND the backend gate, resolved here
        so it participates in the jit cache key (resolving inside the
        traced body let a CPU-traced f32 executable be reused under a bf16
        key — ADVICE r4)."""
        from .gbdt_kernels import _accel_bf16

        return self.hist_precision == "bf16" and _accel_bf16()

    def streaming_bin_edges(self, chunks, hist_bins: int = 0) -> np.ndarray:
        """Quantile bin edges from CHUNKED feature matrices — the sketch
        half of an external-memory tree fit (arXiv:1806.11248): per-feature
        ``StreamingHistogram`` sketches absorb (n, D) chunks, then edges
        come from the sketch quantiles (``gbdt_kernels.
        quantile_bins_streaming``; documented rank tolerance ~0.05 at the
        default ``8 * max_bins`` sketch budget).  The tree growth itself
        consumes the materialized packed matrix (the two-pass driver's
        output), exactly like the paper's split."""
        from .gbdt_kernels import (quantile_bins_streaming,
                                   streaming_histograms_for)

        hists = streaming_histograms_for(
            chunks, hist_bins=hist_bins or 8 * self.max_bins)
        return quantile_bins_streaming(hists, self.max_bins)

    def with_mesh(self, mesh) -> "_GBTBase":
        """Multi-chip boosting: the binned matrix, labels and per-row state
        (margins, gradients) live row-sharded on the mesh's data axis and
        every boosting iteration's histogram/gradient programs run under
        GSPMD, which inserts the ICI reductions (the XLA analogue of
        XGBoost's Rabit allreduce, SURVEY §2.11-2.12).  Padded rows carry
        zero training weight, so results match the single-device fit."""
        self.mesh = mesh
        return self

    def fit_columns(self, data: ColumnarDataset, label_col, features_col):
        X = np.asarray(features_col.values, dtype=np.float32)
        y = np.nan_to_num(np.asarray(label_col.values, dtype=np.float32))
        return self.fit_raw(X, y)

    def fit_raw(self, X: np.ndarray, y: np.ndarray, w=None):
        n, d = X.shape
        if self.mesh is None:
            # wide mostly-zero matrices take the sparse histogram path
            # (nonzero-aware sketch + CSR build over the ~density·N·D
            # nonzero entries; XGBoost-core parity, SURVEY §2.11)
            edges, binned, csr = _prep_tree_inputs_sparse(X, self.max_bins)
        else:
            # mesh-sharded sketch over ICI (VERDICT r4 #5)
            edges, binned = _prep_tree_inputs_mesh(X, self.max_bins,
                                                   self.mesh)
            csr = None
        rng = np.random.default_rng(self.seed)
        base_w = (np.ones(n, np.float32) if w is None
                  else np.asarray(w, np.float32))

        use_es = self.early_stopping_rounds > 0
        if use_es:
            val = rng.random(n) < self.validation_fraction
            train_w = base_w * (~val)
        else:
            val = np.zeros(n, bool)
            train_w = base_w

        obj = self._objective
        Y = None
        if obj == "multiclass":
            k = max(int(y.max()) + 1, 2)
            Y = np.eye(k, dtype=np.float32)[y.astype(int)]
            base = np.zeros(k, np.float32)
        elif obj == "binary":
            k = 1
            pos = float((base_w * y).sum())
            tot = float(base_w.sum())
            p0 = min(max(pos / max(tot, 1e-9), 1e-6), 1 - 1e-6)
            base = np.float32(np.log(p0 / (1 - p0)))
        else:
            k = 1
            base = np.float32((base_w @ y) / max(base_w.sum(), 1e-9))

        if self.mesh is not None:
            # row-shard the boosting state over the mesh's data axis; zero
            # weight on padded rows keeps histograms identical
            from ..parallel.mesh import data_sharding, pad_to_multiple

            ndata = self.mesh.shape[self.mesh.axis_names[0]]
            binned_h, _ = pad_to_multiple(np.asarray(binned), ndata, axis=0)
            y_h, _ = pad_to_multiple(np.asarray(y, np.float32), ndata)
            tw_h, _ = pad_to_multiple(np.asarray(train_w, np.float32), ndata)
            n_pad = binned_h.shape[0]
            ds = data_sharding(self.mesh)
            # content-memoized sharded uploads: a sweep probes with the same
            # fold matrices for every grid candidate
            binned = _dev_memo_sharded(binned_h, ds, "gbt_binned")
            yj = _dev_memo_sharded(y_h, ds, "gbt_y")
            twj = _dev_memo_sharded(tw_h, ds, "gbt_w")
            if obj == "multiclass":
                Y_h, _ = pad_to_multiple(Y, ndata, axis=0)
                Yj = _dev_memo_sharded(Y_h, ds, "gbt_Y")
            else:
                Yj = None
            # no explicit mesh context needed: the committed shardings on
            # these inputs propagate through every jitted program below and
            # GSPMD inserts the cross-device reductions
            F = jax.device_put(np.full((n_pad, k), base, np.float32), ds)
        else:
            yj = jnp.asarray(y, jnp.float32)
            Yj = jnp.asarray(Y) if obj == "multiclass" else None
            twj = jnp.asarray(train_w)
            F = jnp.full((n, k), base, jnp.float32)

        if (self.mesh is None and self.subsample_rate >= 1.0
                and self.colsample >= 1.0
                and obj in ("binary", "regression")):
            # no per-round host RNG: the whole fit runs as scan-chunked
            # launches (the 1-chain case of the grid group's kernel) —
            # per-round dispatch through a remote tunnel costs ~3x the
            # round's device compute
            return self._fit_scan_chunks(binned, edges, yj, twj, obj,
                                         float(base), use_es,
                                         np.where(val)[0], csr=csr,
                                         integer_weights=bool(
                                             (train_w == np.floor(train_w))
                                             .all()),
                                         hx=_content_hash(_as_f32(X)))

        feats, threshs, leaves = [], [], []
        best_metric, best_len, stall = -np.inf, 0, 0
        val_idx = np.where(val)[0]
        from .gbdt_kernels import default_dir_mask, seg_hist_auto
        # default-direction eligibility from the bin edges (pinned-zero
        # features only); segmented histograms never on the mesh path (the
        # Pallas kernel has no GSPMD partitioning rule — code-review r5)
        dd = (jnp.asarray(default_dir_mask(edges))
              if self.sparse_default_direction else None)
        seg_seq = seg_hist_auto(n, 1) if self.mesh is None else False
        # early-stopping metrics fetch in CHUNKS: a per-round host sync
        # costs a ~0.3-0.65 s tunnel round trip (200 rounds = minutes);
        # the stall decision replays per-round on host from the fetched
        # chunk, so best_len (and the truncated model) is unchanged — at
        # most chunk-1 extra rounds of compute are grown then discarded
        es_chunk = max(1, min(8, self.early_stopping_rounds))
        # hoisted: re-uploading the index vector every round is a per-round
        # transfer the chunked sync is meant to remove
        vi_dev = (jnp.asarray(val_idx, jnp.int32)
                  if use_es and len(val_idx) else None)
        pending: list = []
        lagged: list = []
        stop = False
        for it in range(self.max_iter):
            G, H = _grad_hess(obj, F, yj, Yj, twj)
            bw = twj
            if self.subsample_rate < 1.0:
                # draw over the REAL rows (same rng stream as the
                # single-device fit), then pad for the sharded state
                sub = (rng.random(n) < self.subsample_rate).astype(np.float32)
                if len(sub) < int(twj.shape[0]):
                    sub = np.pad(sub, (0, int(twj.shape[0]) - len(sub)))
                bw = twj * jnp.asarray(sub)
                G, H = _grad_hess(obj, F, yj, Yj, bw)
            mask = np.ones(d, bool)
            if self.colsample < 1.0:
                mask = np.zeros(d, bool)
                msub = max(1, int(d * self.colsample))
                mask[rng.choice(d, msub, replace=False)] = True
            f, th, lf = grow_tree(
                binned, G, H, bw, max_depth=self.max_depth,
                n_bins=self.max_bins, lam=self.reg_lambda,
                min_child_weight=self.min_child_weight,
                min_info_gain=self.min_info_gain,
                min_instances=float(self.min_instances_per_node),
                feat_mask=jnp.asarray(mask), newton_leaf=True,
                learning_rate=self.step_size,
                min_gain_raw=self.min_split_gain_raw,
                hist_bf16=self._hist_bf16(), csr=csr,
                seg_hist=seg_seq,
                default_dir=self.sparse_default_direction, dd_mask=dd)
            from .gbdt_kernels import predict_tree

            heap_depth = int(np.log2(f.shape[0] + 1))
            F = F + predict_tree(binned, f, th, lf, heap_depth)
            # trees stay device-resident: a per-iteration np.asarray costs a
            # ~0.6 s tunnel round trip — 3 fetches × max_iter per fit
            feats.append(f)
            threshs.append(th)
            leaves.append(lf)
            if use_es and len(val_idx):
                pending.append((len(feats),
                                self._eval_metric_dev(F, yj, vi_dev)))
                if len(pending) >= es_chunk:
                    # LAGGED fetch: materialize the chunk enqueued one chunk
                    # ago (finished ~es_chunk rounds back — near-free sync)
                    # instead of blocking on the fresh one, which would
                    # serialize the boosting pipeline on the fetch round trip
                    best_metric, best_len, stall, stop = _es_patience(
                        _materialize_es(lagged, overlapped=True),
                        best_metric, best_len,
                        stall, self.early_stopping_rounds)
                    lagged, pending = pending, []
                    if stop:
                        break
        if use_es and len(val_idx) and not stop:
            # drain the in-flight chunks so best_len is exact
            best_metric, best_len, stall, _ = _es_patience(
                _materialize_es(lagged + pending), best_metric, best_len,
                stall, self.early_stopping_rounds)
        if use_es and best_len:
            feats, threshs, leaves = (feats[:best_len], threshs[:best_len],
                                      leaves[:best_len])
        mode = {"binary": "gbdt_binary", "multiclass": "gbdt_multi",
                "regression": "gbdt_reg"}[obj]
        return TreeEnsembleModel(
            mode=mode, edges=edges, feat=jnp.stack(feats),
            thresh=jnp.stack(threshs), leaf=jnp.stack(leaves),
            base_score=float(base) if k == 1 else 0.0,
            n_classes=(k if obj == "multiclass" else 2))

    def _fit_scan_chunks(self, binned, edges, yj, twj, obj: str,
                         base: float, use_es: bool, val_idx, csr=None,
                         integer_weights: bool = True,
                         hx: Optional[str] = None):
        """Whole-fit scan-chunked boosting: es_chunk rounds per launch via
        ``_gbt_chain_rounds_jit`` with S=1 — the same kernel, patience rule
        and masked trimming as the batched GBT grid group, so the two paths
        cannot diverge.  Requires subsample/colsample == 1 (no per-round
        host RNG) and a single device.

        The tree fast path composes here: EFB (``_maybe_bundle``) shrinks
        the histogram width before any launch and the grown splits
        unbundle back to original columns at the end; GOSS
        (``goss_plan``) engages for deep fits (max_depth >= 8), growing
        each round's tree on a gradient-selected row gather; bf16
        histogram accumulation rides ``TMOG_MATRIX_PRECISION=bf16``."""
        from ..utils.profiling import count_launch
        from .gbdt_kernels import (_gbt_chain_rounds_jit,
                                   _resolve_compile_depth, default_dir_mask,
                                   goss_plan, hist_accum_bf16,
                                   seg_hist_auto, unbundle_ensemble)

        n = int(binned.shape[0])
        seg = seg_hist_auto(n, n_chains=1)
        dd_host = (default_dir_mask(edges)
                   if self.sparse_default_direction else None)
        bundles = None
        bend = None
        if _efb_enabled() and csr is None and hx is not None:
            eb = _maybe_bundle(hx, edges, binned, self.max_bins)
            if eb is not None:
                bundles, binned, bend = eb
                if dd_host is not None:
                    dd_host = bundles.bundled_dd_mask(dd_host)
        dd = jnp.asarray(dd_host) if dd_host is not None else None
        goss = goss_plan(n, self.max_depth)
        if goss is not None:
            csr, seg = None, False
        acc = hist_accum_bf16()
        # family compile-depth hint: sequential-fallback candidates of
        # differing max_depth share ONE compiled scan program (their own
        # depth rides the traced depth limit) instead of recompiling the
        # whole n-rounds scan per distinct depth (ADVICE r3)
        heap_depth = _resolve_compile_depth(self.max_depth)
        # XGB-style gating (min_child_weight + gamma) with no count-based
        # gates: the count histogram channel is inert — drop it (1/3 off
        # the per-chain histogram cost; gbdt_kernels bag_mode='newton').
        # Integer weights only: the count channel is WEIGHTED, so with
        # fractional sample weights 'CL >= 1' can gate a split that
        # dropping the channel would allow (code-review r4)
        skip_counts = (float(self.min_instances_per_node) <= 1
                       and float(self.min_info_gain) == 0.0
                       and integer_weights)
        es_chunk = max(1, min(8, self.early_stopping_rounds or 8))
        run_es = use_es and len(val_idx) > 0
        vi_arr = (jnp.asarray(val_idx, jnp.int32) if run_es
                  else jnp.zeros(1, jnp.int32))
        Fm = jnp.full((1, n), base, jnp.float32)
        W1 = twj[None, :]

        def one(v):
            return jnp.full((1,), v, jnp.float32)

        depth1 = jnp.full((1,), self.max_depth, jnp.int32)
        lagged: list = []
        best_metric = np.full(1, -np.inf)
        best_len_a = np.zeros(1, np.int32)
        stall_a = np.zeros(1, np.int32)
        stopped = np.zeros(1, bool)
        fb, tb, lb = [], [], []
        n_rounds = 0
        for ci in range(-(-self.max_iter // es_chunk)):
            count_launch("gbt_rounds")
            Fm, fs, ts, lfs, ms = _gbt_chain_rounds_jit(
                binned, yj, W1, Fm, vi_arr, depth1,
                one(self.reg_lambda), one(self.min_child_weight),
                one(self.min_info_gain),
                one(self.min_instances_per_node),
                one(self.step_size), one(self.min_split_gain_raw),
                es_chunk, heap_depth, self.max_bins, obj,
                self._hist_bf16(), run_es, csr=csr,
                skip_counts=skip_counts, seg_hist=seg,
                default_dir=self.sparse_default_direction, dd_mask=dd,
                bundle_end=bend, acc_bf16=acc, goss=goss,
                goss_seed=jnp.int32(self.seed),
                chain_ids=jnp.zeros(1, jnp.int32),
                round_offset=jnp.int32(n_rounds))
            fb.append(fs)
            tb.append(ts)
            lb.append(lfs)
            start = n_rounds
            n_rounds += es_chunk
            if run_es:
                pending = [(start + j + 1, ms[j]) for j in range(es_chunk)
                           if start + j + 1 <= self.max_iter]
                if es_patience_vec(_materialize_es(lagged, overlapped=True),
                                   stopped,
                                   best_metric, best_len_a, stall_a,
                                   self.early_stopping_rounds):
                    break
                lagged = pending
        if run_es and not stopped.all():
            es_patience_vec(_materialize_es(lagged), stopped, best_metric,
                            best_len_a, stall_a, self.early_stopping_rounds)
        if run_es and best_len_a[0]:
            best_len = int(best_len_a[0])
        else:
            best_len = n_rounds
        best_len = min(best_len, self.max_iter)
        feat = jnp.concatenate(fb)[:best_len, 0]
        thresh = jnp.concatenate(tb)[:best_len, 0]
        leaf = jnp.concatenate(lb)[:best_len, 0]
        if bundles is not None:
            # splits grown in bundled column space map back to original
            # (feature, threshold) pairs — the persisted model routes on
            # the ORIGINAL edges/binned matrix
            feat, thresh = unbundle_ensemble(
                bundles, np.asarray(feat), np.asarray(thresh))
            leaf = np.asarray(leaf)
        mode = "gbdt_binary" if obj == "binary" else "gbdt_reg"
        return TreeEnsembleModel(
            mode=mode, edges=edges, feat=feat, thresh=thresh, leaf=leaf,
            base_score=base, n_classes=2)

    def _eval_metric_dev(self, F, yj, val_idx):
        """Early-stopping metric as a device scalar (sync is the caller's)."""
        from ..evaluators.metrics import _aupr_dev

        vi = (val_idx if isinstance(val_idx, jax.Array)
              else jnp.asarray(val_idx, jnp.int32))
        if self._objective == "binary":
            return _aupr_dev(yj[vi], jax.nn.sigmoid(F[vi, 0]))
        if self._objective == "multiclass":
            return jnp.mean((jnp.argmax(F[vi], axis=1)
                             == yj[vi].astype(jnp.int32)).astype(jnp.float32))
        return -jnp.mean((F[vi, 0] - yj[vi]) ** 2)


def _materialize_es(chunk_rows, overlapped: bool = False):
    """Fetch a chunk of (round, device-metric) pairs in ONE sync — THE
    chunk-fetch idiom for both ES paths: metrics may be scalars (single
    chain) or (S,) chain vectors (the batched GBT grid group).  The sync
    books queue-drain separately from the byte transfer (fetch_timed);
    ``overlapped=True`` is the LAGGED call sites' booking (the next
    chunk's rounds are already enqueued behind these values, so the wait
    runs under live compute — ``overlapSecs``, not ``drainSecs``), while
    the end-of-fit drain of the in-flight chunk stays a genuine drain."""
    if not chunk_rows:
        return []
    from ..utils.profiling import fetch_timed
    vals = fetch_timed(jnp.stack([m for _, m in chunk_rows]),
                       tag="gbt.es", overlapped=overlapped)
    return [(n_at, m) for (n_at, _), m in zip(chunk_rows, vals)]


def es_patience_vec(rows, stopped, best_metric, best_len, stall,
                    patience: int) -> bool:
    """THE early-stopping patience rule (improve/stall/stop), vectorized
    over chains: single-estimator fits are the 1-chain case
    (``_es_patience``) and the batched GBT grid group replays whole chain
    chunks through it, so the two paths cannot desynchronize.  ``rows`` is
    a list of (round, metric-vector) pairs; the state arrays mutate in
    place.  Returns True when every chain has stopped."""
    for n_at, mrow in rows:
        live = ~stopped
        better = live & (mrow > best_metric + 1e-9)
        best_metric[better] = mrow[better]
        best_len[better] = n_at
        stall[better] = 0
        stall[live & ~better] += 1
        stopped |= stall >= patience
    return bool(stopped.all())


def _es_patience(rows, best_metric, best_len, stall, patience):
    """Single-chain view of ``es_patience_vec`` (same rule, scalar state)."""
    bm = np.asarray([best_metric], np.float64)
    bl = np.asarray([best_len], np.int64)
    st = np.asarray([stall], np.int64)
    stopped = np.zeros(1, bool)
    es_patience_vec([(n, np.asarray([m])) for n, m in rows],
                    stopped, bm, bl, st, patience)
    return float(bm[0]), int(bl[0]), int(st[0]), bool(stopped[0])


def _grad_hess(obj, F, y, Y, w):
    if obj == "binary":
        p = jax.nn.sigmoid(F[:, 0])
        g = (w * (p - y))[:, None]
        h = (w * jnp.maximum(p * (1 - p), 1e-6))[:, None]
        return g, h
    if obj == "multiclass":
        P = jax.nn.softmax(F, axis=1)
        g = w[:, None] * (P - Y)
        h = w[:, None] * jnp.maximum(P * (1 - P), 1e-6)
        return g, h
    g = (w * (F[:, 0] - y))[:, None]
    h = w[:, None]
    return g, h


class OpGBTClassifier(_GBTBase):
    """Binary GBT (OpGBTClassifier parity; Spark GBT supports binary only)."""
    _op_name = "gbtCls"
    _objective = "binary"


class OpGBTRegressor(_GBTBase):
    _op_name = "gbtReg"
    _objective = "regression"


class OpXGBoostClassifier(_GBTBase):
    """XGBoost-parameterised boosted classifier (binary or multiclass).

    Defaults follow the reference's XGB defaults for binary selection
    (DefaultSelectorParams: NumRound=200, Eta=0.02, MaxDepth=10,
    MinChildWeight in {1,10}, Gamma=0.8, aucpr early stopping after 20).
    """

    _op_name = "xgbCls"
    _objective = "binary"

    def __init__(self, num_round: int = 200, eta: float = 0.02,
                 max_depth: int = 10, min_child_weight: float = 1.0,
                 gamma: float = 0.8, reg_lambda: float = 1.0,
                 subsample: float = 1.0, colsample_bytree: float = 1.0,
                 max_bins: int = 32, early_stopping_rounds: int = 20,
                 num_class: int = 0, seed: int = 42,
                 hist_precision: str = "bf16",
                 sparse_default_direction: bool = True,
                 uid: Optional[str] = None):
        super().__init__(
            max_iter=num_round, max_depth=max_depth, step_size=eta,
            max_bins=max_bins, reg_lambda=reg_lambda,
            min_child_weight=min_child_weight,
            min_split_gain_raw=gamma, subsample_rate=subsample,
            colsample=colsample_bytree,
            early_stopping_rounds=early_stopping_rounds, seed=seed,
            hist_precision=hist_precision,
            sparse_default_direction=sparse_default_direction, uid=uid)
        self.num_round = num_round
        self.eta = eta
        self.gamma = gamma
        self.subsample = subsample
        self.colsample_bytree = colsample_bytree
        self.num_class = num_class

    def fit_raw(self, X, y, w=None):
        if self.num_class > 2 or (self.num_class == 0 and y.max() > 1):
            self._objective = "multiclass"
        return super().fit_raw(X, y, w)


class OpXGBoostRegressor(OpXGBoostClassifier):
    _op_name = "xgbReg"
    _objective = "regression"

    def fit_raw(self, X, y, w=None):
        self._objective = "regression"
        return _GBTBase.fit_raw(self, X, y, w)
