"""Native (C++) runtime kernels, loaded via ctypes.

SURVEY §2.11: the reference's native inventory is the XGBoost C++ core
(serving + training behind JNI wrappers) and the in-tree Java
``StreamingHistogram``.  Here the native library covers the host-side hot
paths — batched tree-ensemble/linear scoring for the Spark-free ``local``
scorer, quantile-bin application, and the streaming histogram — while tree
*training* stays on device (JAX/XLA).

The shared library is built on demand with ``g++ -O3`` (no pybind11 in this
environment; plain C ABI + ctypes) and cached next to the source.  Every
entry point has a numpy fallback, so the package works identically when no
compiler is present: check ``native.AVAILABLE``.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

__all__ = [
    "AVAILABLE", "load", "build",
    "predict_ensemble", "apply_bins", "linear_margin", "sigmoid", "softmax",
    "NativeStreamingHistogram",
]

_SRC = os.path.join(os.path.dirname(__file__), "src", "tmog_native.cpp")
_LIB_PATH = os.path.join(os.path.dirname(__file__), "libtmognative.so")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def build(force: bool = False) -> bool:
    """Compile the shared library with g++; returns success.

    Compiles to a temp file then ``os.rename``s it into place so concurrent
    processes can never dlopen a partially written .so.  Portable codegen
    (no -march=native): the cached artifact may be shared across machines.
    """
    if os.path.exists(_LIB_PATH) and not force \
            and os.path.getmtime(_LIB_PATH) >= os.path.getmtime(_SRC):
        return True
    tmp = f"{_LIB_PATH}.tmp.{os.getpid()}"
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
           "-pthread", _SRC, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=300)
        os.rename(tmp, _LIB_PATH)
        return True
    except (OSError, subprocess.SubprocessError):
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass
        return False


def _declare(lib: ctypes.CDLL) -> ctypes.CDLL:
    i32, i64, f32p = ctypes.c_int32, ctypes.c_int64, \
        ctypes.POINTER(ctypes.c_float)
    i32p = ctypes.POINTER(ctypes.c_int32)
    f64 = ctypes.c_double
    f64p = ctypes.POINTER(ctypes.c_double)
    vp = ctypes.c_void_p
    lib.tmog_predict_ensemble.argtypes = [
        i32p, i64, i64, i32p, i32p, f32p, i64, i32, i64, f32p, i32]
    lib.tmog_apply_bins.argtypes = [f32p, i64, i64, f32p, i32, i32p]
    lib.tmog_linear_margin.argtypes = [f32p, i64, i64, f32p, f32p]
    lib.tmog_sigmoid.argtypes = [f32p, i64, f32p]
    lib.tmog_softmax.argtypes = [f32p, i64, i64, f32p]
    lib.tmog_hist_new.argtypes = [i32]
    lib.tmog_hist_new.restype = vp
    lib.tmog_hist_free.argtypes = [vp]
    lib.tmog_hist_load.argtypes = [vp, f64p, f64p, i64]
    lib.tmog_hist_update.argtypes = [vp, f64p, i64]
    lib.tmog_hist_merge.argtypes = [vp, vp]
    lib.tmog_hist_size.argtypes = [vp]
    lib.tmog_hist_size.restype = i32
    lib.tmog_hist_get.argtypes = [vp, f64p, f64p]
    lib.tmog_hist_sum.argtypes = [vp, f64]
    lib.tmog_hist_sum.restype = f64
    return lib


def load() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        if os.environ.get("TMOG_DISABLE_NATIVE"):
            _load_failed = True
            return None
        if not build():
            _load_failed = True
            return None
        try:
            _lib = _declare(ctypes.CDLL(_LIB_PATH))
        except OSError:
            _load_failed = True
    return _lib


class _Available:
    """Lazy truthiness: first check triggers the build."""

    def __bool__(self) -> bool:
        return load() is not None


AVAILABLE = _Available()


def _f32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _i32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def _f64p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_double))


# ---------------------------------------------------------------------------
# Kernels (numpy fallback in every branch)
# ---------------------------------------------------------------------------

def predict_ensemble(binned: np.ndarray, feat: np.ndarray, thresh: np.ndarray,
                     leaf: np.ndarray, depth: int,
                     n_threads: int = 0) -> np.ndarray:
    """Sum of all trees' leaf values; layouts match gbdt_kernels.predict_ensemble
    (binned (N,D) int32; feat/thresh (T, 2^depth-1); leaf (T, 2^depth, K))."""
    binned = np.ascontiguousarray(binned, np.int32)
    feat = np.ascontiguousarray(feat, np.int32)
    thresh = np.ascontiguousarray(thresh, np.int32)
    leaf = np.ascontiguousarray(leaf, np.float32)
    n, d = binned.shape
    n_trees, k = leaf.shape[0], leaf.shape[2]
    lib = load()
    if lib is not None:
        out = np.zeros((n, k), np.float32)
        if n_threads <= 0:
            n_threads = min(os.cpu_count() or 1, 16)
        lib.tmog_predict_ensemble(
            _i32p(binned), n, d, _i32p(feat), _i32p(thresh), _f32p(leaf),
            n_trees, depth, k, _f32p(out), n_threads)
        return out
    # numpy fallback: vectorized heap walk per tree
    out = np.zeros((n, k), np.float32)
    rows = np.arange(n)
    for t in range(n_trees):
        node = np.zeros(n, np.int64)
        for l in range(depth):
            heap = (1 << l) - 1 + node
            f = feat[t][heap]
            th = thresh[t][heap]
            node = 2 * node + (binned[rows, f] > th)
        out += leaf[t][node]
    return out


def apply_bins(X: np.ndarray, edges: np.ndarray) -> np.ndarray:
    """Quantized (N, D) int32; parity with gbdt_kernels.apply_bins."""
    X = np.ascontiguousarray(X, np.float32)
    edges = np.ascontiguousarray(edges, np.float32)
    n, d = X.shape
    lib = load()
    if lib is not None:
        out = np.empty((n, d), np.int32)
        lib.tmog_apply_bins(_f32p(X), n, d, _f32p(edges), edges.shape[1],
                            _i32p(out))
        return out
    return np.sum(X[:, :, None] > edges[None, :, :], axis=2).astype(np.int32)


def linear_margin(X: np.ndarray, beta: np.ndarray) -> np.ndarray:
    """X @ beta[:-1] + beta[-1] in float32."""
    X = np.ascontiguousarray(X, np.float32)
    beta = np.ascontiguousarray(beta, np.float32)
    lib = load()
    if lib is not None:
        out = np.empty(X.shape[0], np.float32)
        lib.tmog_linear_margin(_f32p(X), X.shape[0], X.shape[1], _f32p(beta),
                               _f32p(out))
        return out
    return (X @ beta[:-1] + beta[-1]).astype(np.float32)


def sigmoid(x: np.ndarray) -> np.ndarray:
    x = np.ascontiguousarray(x, np.float32)
    lib = load()
    if lib is not None:
        out = np.empty(x.shape, np.float32)
        lib.tmog_sigmoid(_f32p(x), x.size, _f32p(out))
        return out
    return (1.0 / (1.0 + np.exp(-x))).astype(np.float32)


def softmax(x: np.ndarray) -> np.ndarray:
    x = np.ascontiguousarray(x, np.float32)
    n, k = x.shape
    lib = load()
    if lib is not None:
        out = np.empty((n, k), np.float32)
        lib.tmog_softmax(_f32p(x), n, k, _f32p(out))
        return out
    m = x - x.max(axis=1, keepdims=True)
    e = np.exp(m)
    return (e / e.sum(axis=1, keepdims=True)).astype(np.float32)


class NativeStreamingHistogram:
    """ctypes wrapper over the C++ Ben-Haim/Tom-Tov histogram.

    Same surface as utils.streaming_histogram.StreamingHistogram (update /
    merge / bins / sum); raises RuntimeError when the library is absent —
    callers pick the implementation via ``native.AVAILABLE``.
    """

    def __init__(self, max_bins: int = 100):
        lib = load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self.max_bins = max_bins
        self._h = lib.tmog_hist_new(max_bins)

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.tmog_hist_free(h)
            self._h = None

    def update(self, values) -> "NativeStreamingHistogram":
        v = np.ascontiguousarray(np.asarray(values, np.float64).ravel())
        self._lib.tmog_hist_update(self._h, _f64p(v), v.size)
        return self

    def load(self, centers: np.ndarray, counts: np.ndarray
             ) -> "NativeStreamingHistogram":
        """Seed with weighted bins (resuming from a serialized state)."""
        c = np.ascontiguousarray(centers, np.float64)
        m = np.ascontiguousarray(counts, np.float64)
        self._lib.tmog_hist_load(self._h, _f64p(c), _f64p(m), c.size)
        return self

    def merge(self, other: "NativeStreamingHistogram"
              ) -> "NativeStreamingHistogram":
        self._lib.tmog_hist_merge(self._h, other._h)
        return self

    @property
    def bins(self):
        nb = self._lib.tmog_hist_size(self._h)
        centers = np.empty(nb, np.float64)
        counts = np.empty(nb, np.float64)
        if nb:
            self._lib.tmog_hist_get(self._h, _f64p(centers), _f64p(counts))
        return centers, counts

    def sum(self, x: float) -> float:
        return float(self._lib.tmog_hist_sum(self._h, float(x)))
