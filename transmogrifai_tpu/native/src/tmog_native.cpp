// tmog_native — native runtime kernels for the host-side paths of
// transmogrifai_tpu.
//
// Parity rationale (SURVEY §2.11): the reference's only native components
// are the XGBoost C++ core (tree-ensemble training/eval behind
// OpXGBoostClassifier/Regressor via JNI) and the in-tree Java
// StreamingHistogram (utils/.../stats/StreamingHistogram.java:36) used for
// raw-feature profiling.  The TPU build keeps tree *training* on device
// (JAX/XLA, models/gbdt_kernels.py) and makes the serving/profiling paths
// native:
//   * batched tree-ensemble + linear scoring (the local/ Spark-free scorer's
//     hot loop — reference uses MLeap on the JVM, local/MLeapModelConverter
//     .scala:40)
//   * feature binning (quantile-sketch application)
//   * Ben-Haim/Tom-Tov streaming histogram (RawFeatureFilter profiling)
//
// Data layouts match models/gbdt_kernels.py exactly so fitted arrays are
// shared with the device path with no conversion:
//   binned  (N, D)   int32   bin ids in [0, B)
//   feat    (T, 2^depth - 1) int32   heap-indexed internal nodes
//   thresh  (T, 2^depth - 1) int32
//   leaf    (T, 2^depth, K)  float32
// Routing rule per level (gbdt_kernels._route_right):
//   t >= 0: node <- 2*node + (x > t); t == B is the no-split sentinel
//   t < 0:  default-direction split (XGBoost missing-value semantics) —
//           effective threshold -t-1, and bin 0 (the missing/absent
//           bucket) routes RIGHT instead of left
//
// Plain C ABI (ctypes-consumed; no pybind11 in this environment).

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// Tree-ensemble scoring
// ---------------------------------------------------------------------------

static void predict_rows(const int32_t* binned, int64_t row0, int64_t row1,
                         int64_t d, const int32_t* feat, const int32_t* thresh,
                         const float* leaf, int64_t n_trees, int depth,
                         int64_t k, float* out) {
  const int64_t n_internal = (int64_t(1) << depth) - 1;
  const int64_t n_leaves = int64_t(1) << depth;
  for (int64_t r = row0; r < row1; ++r) {
    const int32_t* xrow = binned + r * d;
    float* orow = out + r * k;
    for (int64_t t = 0; t < n_trees; ++t) {
      const int32_t* tf = feat + t * n_internal;
      const int32_t* tt = thresh + t * n_internal;
      int64_t node = 0;
      for (int l = 0; l < depth; ++l) {
        const int64_t heap = (int64_t(1) << l) - 1 + node;
        const int32_t tv = tt[heap];
        const int32_t x = xrow[tf[heap]];
        int right;
        if (tv < 0) {
          right = (x > -tv - 1 || x == 0) ? 1 : 0;
        } else {
          right = (x > tv) ? 1 : 0;
        }
        node = 2 * node + right;
      }
      const float* lf = leaf + (t * n_leaves + node) * k;
      for (int64_t c = 0; c < k; ++c) orow[c] += lf[c];
    }
  }
}

// out (N, K) must be zero-initialised by the caller.
void tmog_predict_ensemble(const int32_t* binned, int64_t n, int64_t d,
                           const int32_t* feat, const int32_t* thresh,
                           const float* leaf, int64_t n_trees, int32_t depth,
                           int64_t k, float* out, int32_t n_threads) {
  if (n_threads <= 1 || n < 4096) {
    predict_rows(binned, 0, n, d, feat, thresh, leaf, n_trees, depth, k, out);
    return;
  }
  std::vector<std::thread> pool;
  const int64_t block = (n + n_threads - 1) / n_threads;
  for (int32_t i = 0; i < n_threads; ++i) {
    const int64_t lo = i * block, hi = std::min(n, lo + block);
    if (lo >= hi) break;
    pool.emplace_back(predict_rows, binned, lo, hi, d, feat, thresh, leaf,
                      n_trees, depth, k, out);
  }
  for (auto& th : pool) th.join();
}

// ---------------------------------------------------------------------------
// Binning (apply_bins parity: bin = #edges with x > edge; +inf edges unused)
// ---------------------------------------------------------------------------

void tmog_apply_bins(const float* X, int64_t n, int64_t d, const float* edges,
                     int32_t n_edges, int32_t* out) {
  for (int64_t r = 0; r < n; ++r) {
    const float* xrow = X + r * d;
    int32_t* orow = out + r * d;
    for (int64_t j = 0; j < d; ++j) {
      const float* e = edges + j * n_edges;
      const float x = xrow[j];
      int32_t b = 0;
      for (int32_t q = 0; q < n_edges; ++q) b += (x > e[q]) ? 1 : 0;
      orow[j] = b;
    }
  }
}

// ---------------------------------------------------------------------------
// Linear / logistic scoring
// ---------------------------------------------------------------------------

// margin[i] = X[i] . beta[0:d] + beta[d]
void tmog_linear_margin(const float* X, int64_t n, int64_t d,
                        const float* beta, float* out) {
  for (int64_t r = 0; r < n; ++r) {
    const float* xrow = X + r * d;
    double acc = beta[d];
    for (int64_t j = 0; j < d; ++j) acc += double(xrow[j]) * beta[j];
    out[r] = float(acc);
  }
}

void tmog_sigmoid(const float* x, int64_t n, float* out) {
  for (int64_t i = 0; i < n; ++i) out[i] = 1.0f / (1.0f + std::exp(-x[i]));
}

// row-wise softmax over (N, K)
void tmog_softmax(const float* x, int64_t n, int64_t k, float* out) {
  for (int64_t r = 0; r < n; ++r) {
    const float* xr = x + r * k;
    float* orow = out + r * k;
    float m = xr[0];
    for (int64_t c = 1; c < k; ++c) m = std::max(m, xr[c]);
    double s = 0;
    for (int64_t c = 0; c < k; ++c) {
      orow[c] = std::exp(xr[c] - m);
      s += orow[c];
    }
    for (int64_t c = 0; c < k; ++c) orow[c] = float(orow[c] / s);
  }
}

// ---------------------------------------------------------------------------
// Ben-Haim / Tom-Tov streaming histogram
// (StreamingHistogram.java:36,120-280 behavioral parity: bounded bins,
//  count-weighted centroid merge of the closest adjacent pair, trapezoidal
//  cumulative sum)
// ---------------------------------------------------------------------------

struct TmogHist {
  int32_t max_bins;
  std::vector<double> centers;
  std::vector<double> counts;
};

void* tmog_hist_new(int32_t max_bins) {
  auto* h = new TmogHist();
  h->max_bins = max_bins < 2 ? 2 : max_bins;
  return h;
}

void tmog_hist_free(void* hp) { delete static_cast<TmogHist*>(hp); }

static void hist_insert_sorted(TmogHist* h, double c, double cnt) {
  auto it = std::lower_bound(h->centers.begin(), h->centers.end(), c);
  const size_t idx = size_t(it - h->centers.begin());
  if (it != h->centers.end() && *it == c) {
    h->counts[idx] += cnt;
    return;
  }
  h->centers.insert(it, c);
  h->counts.insert(h->counts.begin() + idx, cnt);
}

static void hist_shrink(TmogHist* h) {
  while (int32_t(h->centers.size()) > h->max_bins) {
    // merge the closest adjacent pair (count-weighted mean)
    size_t best = 0;
    double best_gap = h->centers[1] - h->centers[0];
    for (size_t i = 1; i + 1 < h->centers.size(); ++i) {
      const double gap = h->centers[i + 1] - h->centers[i];
      if (gap < best_gap) {
        best_gap = gap;
        best = i;
      }
    }
    const double c1 = h->counts[best], c2 = h->counts[best + 1];
    h->centers[best] = (h->centers[best] * c1 + h->centers[best + 1] * c2) /
                       (c1 + c2);
    h->counts[best] = c1 + c2;
    h->centers.erase(h->centers.begin() + best + 1);
    h->counts.erase(h->counts.begin() + best + 1);
  }
}

// bulk-load weighted bins (seeding from an existing histogram state);
// caller must hold counts conservation — no shrink until the next update
void tmog_hist_load(void* hp, const double* centers, const double* counts,
                    int64_t n) {
  auto* h = static_cast<TmogHist*>(hp);
  for (int64_t i = 0; i < n; ++i)
    hist_insert_sorted(h, centers[i], counts[i]);
  hist_shrink(h);
}

void tmog_hist_update(void* hp, const double* xs, int64_t n) {
  auto* h = static_cast<TmogHist*>(hp);
  for (int64_t i = 0; i < n; ++i) {
    if (!std::isfinite(xs[i])) continue;
    hist_insert_sorted(h, xs[i], 1.0);
    hist_shrink(h);
  }
}

void tmog_hist_merge(void* ap, const void* bp) {
  auto* a = static_cast<TmogHist*>(ap);
  const auto* b = static_cast<const TmogHist*>(bp);
  for (size_t i = 0; i < b->centers.size(); ++i)
    hist_insert_sorted(a, b->centers[i], b->counts[i]);
  hist_shrink(a);
}

int32_t tmog_hist_size(const void* hp) {
  return int32_t(static_cast<const TmogHist*>(hp)->centers.size());
}

void tmog_hist_get(const void* hp, double* centers, double* counts) {
  const auto* h = static_cast<const TmogHist*>(hp);
  std::memcpy(centers, h->centers.data(), h->centers.size() * sizeof(double));
  std::memcpy(counts, h->counts.data(), h->counts.size() * sizeof(double));
}

// estimated number of points <= x (trapezoidal interpolation, the Java
// sum() at StreamingHistogram.java:200-240)
double tmog_hist_sum(const void* hp, double x) {
  const auto* h = static_cast<const TmogHist*>(hp);
  const auto& p = h->centers;
  const auto& m = h->counts;
  const size_t nb = p.size();
  if (nb == 0) return 0.0;
  if (x < p.front()) return 0.0;
  if (x >= p.back()) {
    double s = 0;
    for (double c : m) s += c;
    return s;
  }
  size_t i = size_t(std::upper_bound(p.begin(), p.end(), x) - p.begin()) - 1;
  double s = 0;
  for (size_t j = 0; j < i; ++j) s += m[j];
  s += m[i] / 2.0;
  const double pi = p[i], pj = p[i + 1], mi = m[i], mj = m[i + 1];
  const double frac = (x - pi) / (pj - pi);
  const double mx = mi + (mj - mi) * frac;
  s += (mi + mx) * frac / 2.0;
  return s;
}

}  // extern "C"
