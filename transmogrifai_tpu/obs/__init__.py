"""Unified observability — span tracing, flight recorder, exposition.

One subsystem, three sinks over a shared span tree (docs/observability.md):

* **Span tracing** (``obs/trace.py``): ``start_trace()`` arms a
  process-wide :class:`Tracer`; hooks threaded through
  ``OpWorkflow.train/refresh``, plan execution, the streaming driver,
  the sweep work queue, and the serving batch path record a single
  hierarchical timeline with a per-run ``trace_id``.
* **Flight recorder** (``obs/flight.py``): a bounded ring of structured
  state-transition events (device loss, mesh shrink, quarantine,
  checkpoint save/resume, drift trigger, swap/rollback, breaker
  transitions, fault firings) with span-id causality links; JSONL on
  demand or on crash.
* **Exposition** (``obs/export.py``, ``obs/prometheus.py``): Chrome-trace
  JSON that loads in ``chrome://tracing``/Perfetto (summarized by
  ``tmog trace``), and Prometheus text of ServingMetrics + RunCounters
  served at ``/metrics?format=prometheus``.

Plus the compiled-program feature capture (``obs/hlo.py``) that lands
per-stage HLO op mix / FLOPs / bytes-accessed on ``StageProfile`` /
``StageObservation`` for the tuning cost model, and the shared
``bench_meta()`` block every ``benchmarks/*_latest.json`` carries.

Everything is off-path-free when disabled: each hook is one module-global
``None`` check (gated <1% of train wall by the OBS_SMOKE contract).
"""
from .bench_meta import bench_meta, estimate_disabled_overhead_s
from .export import (summarize_file, to_chrome_trace, trace_summary,
                     validate_chrome_trace)
from .flight import (FlightRecorder, arm_crash_dump, current_recorder,
                     disarm_crash_dump, install_recorder, record_event)
from .prometheus import parse_exposition, prometheus_text
from .trace import (Span, Tracer, begin_span, current_span, current_tracer,
                    end_span, install_tracer, new_trace_id, span,
                    start_trace, stop_trace, tracing)

__all__ = [
    "Span", "Tracer", "span", "begin_span", "end_span", "current_span",
    "current_tracer", "install_tracer", "start_trace", "stop_trace",
    "tracing", "new_trace_id",
    "FlightRecorder", "record_event", "install_recorder",
    "current_recorder", "arm_crash_dump", "disarm_crash_dump",
    "to_chrome_trace", "validate_chrome_trace", "trace_summary",
    "summarize_file",
    "prometheus_text", "parse_exposition",
    "bench_meta", "estimate_disabled_overhead_s",
]
