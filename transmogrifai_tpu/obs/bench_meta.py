"""Shared bench-JSON metadata — one helper instead of N hand-rolled copies.

Every ``examples/bench_*.py`` script used to assemble its own backend /
rss / timestamp fields for ``benchmarks/*_latest.json``; the shapes had
drifted (some recorded rss, some not; none carried a run id).  This
helper gives every bench JSON an identical ``meta`` block — including the
active trace id when the run was traced, so a bench artifact links back
to its span tree and flight recording.
"""
from __future__ import annotations

import os
import time
from typing import Any, Dict, Optional

__all__ = ["bench_meta", "estimate_disabled_overhead_s"]


def _rss_mb() -> Optional[float]:
    try:
        import resource

        return round(
            resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0, 1)
    except Exception:  # pragma: no cover - non-POSIX
        return None


def bench_meta(wall_s: Optional[float] = None) -> Dict[str, Any]:
    """The standard metadata block every bench JSON carries:
    backend, jax version, peak RSS, pid, unix time, a fresh run id, and
    the active trace id (None when the run was untraced)."""
    from ..utils.profiling import backend_name
    from ..utils.uid import uid_for
    from .trace import current_tracer

    tracer = current_tracer()
    meta: Dict[str, Any] = {
        "backend": backend_name(),
        "rssMb": _rss_mb(),
        "at": int(time.time()),
        "pid": os.getpid(),
        "runId": uid_for("Bench"),
        "traceId": tracer.trace_id if tracer is not None else None,
    }
    try:
        import jax

        meta["jax"] = jax.__version__
    except Exception:  # pragma: no cover - jax must be importable
        pass
    if wall_s is not None:
        meta["wallSecs"] = round(float(wall_s), 3)
    return meta


def estimate_disabled_overhead_s(n_hooks: int,
                                 samples: int = 50_000) -> float:
    """Measured cost of ``n_hooks`` disabled tracing hooks.

    Times ``samples`` begin/end span pairs plus flight-event checks with
    tracing OFF (the steady production state) and scales to ``n_hooks`` —
    the ``lint_wall_s``-style fraction bench_pipeline emits to prove the
    instrumentation stays off-path when disabled.  Callers must invoke
    this with no tracer installed; it raises otherwise rather than
    reporting an enabled-path number as the disabled overhead."""
    from .flight import current_recorder, record_event
    from .trace import begin_span, current_tracer, end_span

    if current_tracer() is not None or current_recorder() is not None:
        raise RuntimeError(
            "estimate_disabled_overhead_s must run with tracing disabled")
    t0 = time.perf_counter()
    for _ in range(samples):
        sp = begin_span("x", cat="bench")
        record_event("x")
        end_span(sp)
    per_hook = (time.perf_counter() - t0) / samples
    return per_hook * max(int(n_hooks), 0)
