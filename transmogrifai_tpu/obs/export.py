"""Chrome-trace / Perfetto export of a span tree.

The exported document is the Trace Event Format's JSON-object form
(``{"traceEvents": [...]}``): complete events (``ph: "X"``) for spans,
instant events (``ph: "i"``) for flight-recorder entries, and metadata
events naming the threads — a file that loads directly in
``chrome://tracing`` / https://ui.perfetto.dev.  ``tmog trace FILE``
renders :func:`trace_summary` over the same document.

:func:`validate_chrome_trace` is the schema gate the OBS_SMOKE CI step
(and tests) run over every export — shape drift in the exporter fails
fast instead of producing files the viewer silently rejects.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

__all__ = ["to_chrome_trace", "validate_chrome_trace", "trace_summary"]


def to_chrome_trace(tracer, flight=None) -> Dict[str, Any]:
    """Render ``tracer``'s spans (and optionally a flight recorder's
    events) as a Chrome-trace JSON document."""
    spans = tracer.snapshot()
    flight = flight if flight is not None else tracer.flight
    # stable thread ids: order of first appearance
    tids: Dict[str, int] = {}
    events: List[Dict[str, Any]] = []
    for sp in spans:
        tid = tids.setdefault(sp.thread, len(tids))
        args = {k: _jsonable(v) for k, v in sp.attrs.items()}
        args["spanId"] = sp.span_id
        if sp.parent_id is not None:
            args["parentId"] = sp.parent_id
        events.append({
            "ph": "X", "name": sp.name, "cat": sp.cat,
            "ts": round(sp.t0_unix * 1e6, 1),
            "dur": round((sp.dur_s or 0.0) * 1e6, 1),
            "pid": 0, "tid": tid, "args": args,
        })
    for name, tid in tids.items():
        events.append({"ph": "M", "name": "thread_name", "pid": 0,
                       "tid": tid, "args": {"name": name}})
    if flight is not None:
        for e in flight.events():
            args = {k: _jsonable(v) for k, v in e["attrs"].items()}
            args["seq"] = e["seq"]
            if e.get("spanId") is not None:
                args["spanId"] = e["spanId"]
            events.append({
                "ph": "i", "name": e["kind"], "cat": "event",
                "ts": round(e["t"] * 1e6, 1), "pid": 0, "tid": 0,
                "s": "g", "args": args,
            })
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "traceId": tracer.trace_id,
            "label": tracer.label,
            "spans": len(spans),
            "droppedSpans": tracer.dropped,
        },
    }


def _jsonable(v: Any) -> Any:
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    return str(v)


#: phases the exporter emits; a doc containing others is not OURS
_KNOWN_PHASES = {"X", "i", "M", "B", "E", "b", "e", "C"}


def validate_chrome_trace(doc: Any) -> List[str]:
    """Structural check of a Chrome-trace JSON document; returns the list
    of problems (empty = valid)."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return [f"document must be a JSON object, got {type(doc).__name__}"]
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        return ["traceEvents must be a list"]
    for i, e in enumerate(events):
        where = f"traceEvents[{i}]"
        if not isinstance(e, dict):
            problems.append(f"{where}: not an object")
            continue
        ph = e.get("ph")
        if ph not in _KNOWN_PHASES:
            problems.append(f"{where}: unknown phase {ph!r}")
            continue
        if not isinstance(e.get("name"), str) or not e["name"]:
            problems.append(f"{where}: missing name")
        if ph in ("X", "i", "B", "E"):
            ts = e.get("ts")
            if not isinstance(ts, (int, float)) or ts < 0:
                problems.append(f"{where}: bad ts {ts!r}")
        if ph == "X":
            dur = e.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                problems.append(f"{where}: bad dur {dur!r}")
        if ph != "M" and not isinstance(e.get("pid"), int):
            problems.append(f"{where}: missing pid")
        if len(problems) >= 20:
            problems.append("... (truncated)")
            break
    return problems


def trace_summary(doc: Dict[str, Any], top_k: int = 15) -> str:
    """Human summary of an exported trace document (``tmog trace``):
    span/event counts, per-category wall, the top spans by duration."""
    events = doc.get("traceEvents", [])
    spans = [e for e in events if e.get("ph") == "X"]
    instants = [e for e in events if e.get("ph") == "i"]
    other = doc.get("otherData", {})
    lines = [
        f"trace {other.get('traceId', '?')}"
        + (f" ({other['label']})" if other.get("label") else "")
        + f": {len(spans)} spans, {len(instants)} events"
        + (f", {other['droppedSpans']} dropped"
           if other.get("droppedSpans") else "")]
    by_cat: Dict[str, List[float]] = {}
    for e in spans:
        by_cat.setdefault(e.get("cat", "?"), []).append(
            float(e.get("dur", 0.0)))
    for cat in sorted(by_cat):
        durs = by_cat[cat]
        lines.append(f"  {cat:<10} {len(durs):5d} spans  "
                     f"{sum(durs) / 1e6:9.3f}s total")
    top = sorted(spans, key=lambda e: -float(e.get("dur", 0.0)))[:top_k]
    if top:
        lines.append("top spans:")
        for e in top:
            lines.append(
                f"  {float(e.get('dur', 0.0)) / 1e3:9.1f} ms  "
                f"[{e.get('cat', '?')}] {e['name']}")
    counts: Dict[str, int] = {}
    for e in instants:
        counts[e["name"]] = counts.get(e["name"], 0) + 1
    if counts:
        lines.append("events:")
        for k in sorted(counts):
            lines.append(f"  {counts[k]:5d}  {k}")
    return "\n".join(lines)


def summarize_file(path: str, top_k: int = 15) -> Optional[str]:
    """Load + validate + summarize a trace file; returns the summary, or
    None after printing problems (the ``tmog trace`` body)."""
    import json
    import sys

    with open(path) as f:
        doc = json.load(f)
    problems = validate_chrome_trace(doc)
    if problems:
        for p in problems:
            print(f"invalid trace: {p}", file=sys.stderr)
        return None
    return trace_summary(doc, top_k=top_k)
