"""Flight recorder — a bounded ring of structured run events.

The span tree (``obs/trace.py``) answers "where did the time go"; the
flight recorder answers "what HAPPENED, in what order" — the discrete
state transitions an operator replays after an incident: device losses,
mesh shrinks, quarantines, checkpoint saves/resumes, drift triggers,
guarded swaps and rollbacks, breaker transitions, fault-point firings.

Each event carries a monotonically increasing ``seq`` (the causal order,
immune to wall-clock granularity), the wall time, the event ``kind``, the
emitting site's attributes, and — when a tracer is active — the enclosing
span's id, so an event chain links back into the span tree ("this device
loss fired inside sweep unit 4 of trace 9f2…").

Like the fault harness and the tracer, recording is a single module-global
``None`` check when no recorder is installed — the disabled path costs one
branch.  The ring is bounded (``capacity``), so a pathological event storm
ages out old events instead of growing without bound.

Persistence: :meth:`FlightRecorder.dump_jsonl` writes the ring as JSONL on
demand; :func:`arm_crash_dump` additionally hooks ``sys.excepthook`` so an
unhandled crash flushes the ring to disk before the process dies (SIGKILL
cannot be hooked — the crash-resume story for kills is the checkpoint
layer's, not the recorder's).
"""
from __future__ import annotations

import collections
import json
import sys
import threading
import time
from typing import Any, Dict, List, Optional

__all__ = ["FlightRecorder", "install_recorder", "current_recorder",
           "record_event", "record_events", "arm_crash_dump",
           "disarm_crash_dump", "merge_flight_dumps"]


class FlightRecorder:
    """Thread-safe bounded event ring for one run/process."""

    def __init__(self, capacity: int = 4096,
                 trace_id: Optional[str] = None):
        self.capacity = int(capacity)
        self.trace_id = trace_id
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._seq = 0
        self.recorded = 0  # lifetime count (ring may have aged events out)

    def record(self, kind: str, attrs: Dict[str, Any]) -> None:
        from .trace import current_span

        sp = current_span()
        with self._lock:
            self._seq += 1
            self.recorded += 1
            self._ring.append({
                "seq": self._seq,
                "t": round(time.time(), 6),
                "kind": kind,
                "traceId": self.trace_id,
                "spanId": sp.span_id if sp is not None else None,
                "attrs": attrs,
            })

    # -- reading -------------------------------------------------------------

    def events(self, kind_prefix: Optional[str] = None
               ) -> List[Dict[str, Any]]:
        """Events in causal (seq) order, optionally filtered by a kind
        prefix (``"elastic."`` matches every elastic event)."""
        with self._lock:
            out = list(self._ring)
        if kind_prefix is not None:
            out = [e for e in out if e["kind"].startswith(kind_prefix)]
        return out

    def kinds(self) -> List[str]:
        """The kind sequence in causal order (assertion convenience)."""
        return [e["kind"] for e in self.events()]

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    # -- persistence ---------------------------------------------------------

    def dump_jsonl(self, path: str) -> int:
        """Write the ring to ``path`` as JSON Lines; returns the event
        count.  Plain write (not tmp+rename): on the crash path the
        half-written file is still more evidence than no file."""
        events = self.events()
        with open(path, "w") as f:
            for e in events:
                f.write(json.dumps(e, default=str) + "\n")
        return len(events)


def merge_flight_dumps(paths, out_path: Optional[str] = None):
    """Merge per-process flight-recorder JSONL dumps into ONE causally
    ordered stream — the pod coordinator's view of the whole train.

    Events sort by wall time then (process, seq) — each process's
    internal ``seq`` order is preserved, and every event is tagged with
    the ``process`` index derived from its dump's position (unless the
    event already carries one).  Returns the merged event list; with
    ``out_path`` also writes it as JSONL (the coordinator is the only
    writer — TM047's convention).
    """
    merged = []
    for proc, path in enumerate(paths):
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    e = json.loads(line)
                    e.setdefault("process", proc)
                    merged.append(e)
        except OSError:
            continue
    merged.sort(key=lambda e: (e.get("t", 0.0), e.get("process", 0),
                               e.get("seq", 0)))
    if out_path is not None:
        with open(out_path, "w") as f:
            for e in merged:
                f.write(json.dumps(e, default=str) + "\n")
    return merged


#: installed recorder; None = event recording disabled (the fast path)
_RECORDER: Optional[FlightRecorder] = None


def install_recorder(rec: Optional[FlightRecorder]
                     ) -> Optional[FlightRecorder]:
    """Install ``rec`` process-wide (None disables recording)."""
    global _RECORDER
    _RECORDER = rec
    return rec


def current_recorder() -> Optional[FlightRecorder]:
    return _RECORDER


def record_event(kind: str, **attrs) -> None:
    """Event-site hook — one global ``None`` check when disabled."""
    rec = _RECORDER
    if rec is not None:
        rec.record(kind, attrs)


def record_events(kind: str, batch) -> None:
    """Record a batch of events of one ``kind`` (each item an attrs
    dict) — the collective watchdog dumps its ledger tail through this
    so one hang costs one enable check, not one per entry."""
    rec = _RECORDER
    if rec is not None:
        for attrs in batch:
            rec.record(kind, dict(attrs))


# ---------------------------------------------------------------------------
# crash persistence
# ---------------------------------------------------------------------------

_crash_lock = threading.Lock()
_crash_path: Optional[str] = None
_prev_excepthook = None


def _crash_hook(exc_type, exc, tb):
    rec = _RECORDER
    path = _crash_path
    if rec is not None and path is not None:
        try:
            rec.record("crash", {"error": f"{exc_type.__name__}: {exc}"})
            rec.dump_jsonl(path)
        except Exception:  # the recorder must never mask the real crash
            pass
    hook = _prev_excepthook or sys.__excepthook__
    hook(exc_type, exc, tb)


def arm_crash_dump(path: str) -> None:
    """Flush the installed recorder's ring to ``path`` (JSONL) from
    ``sys.excepthook`` if the process dies on an unhandled exception."""
    global _crash_path, _prev_excepthook
    with _crash_lock:
        if _prev_excepthook is None:
            _prev_excepthook = sys.excepthook
            sys.excepthook = _crash_hook
        _crash_path = path


def disarm_crash_dump() -> None:
    global _crash_path, _prev_excepthook
    with _crash_lock:
        _crash_path = None
        if _prev_excepthook is not None:
            sys.excepthook = _prev_excepthook
            _prev_excepthook = None
