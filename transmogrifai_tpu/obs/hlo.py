"""Compiled-program feature capture — HLO op mix, FLOPs, bytes accessed.

ROADMAP item 4's cost model wants features "from the compiled program
(HLO op mix, bytes-accessed, launch counts)" per stage, following "A
Learned Performance Model for TPUs" / "TpuGraphs" (PAPERS.md).  The
launch counts already flow through ``RunCounters``; this module captures
the compile-time half: while armed, every XLA compilation in the process
is intercepted at jax's single compile chokepoint
(``jax._src.compiler.compile_or_get_cached`` — the path both explicit
``lower().compile()`` and implicit first-call jit compiles take), and the
resulting executable's ``cost_analysis()`` plus an op histogram of the
submitted StableHLO module land in a process-wide ledger.

The execution plan (workflow/plan.py) attributes ledger deltas to the
device-heavy stage that triggered them (same serial-stage discipline as
the launch counters), so a traced run's ``StageProfile``/
``StageObservation`` records carry per-stage compiled-program features
for the tuning cost model to consume.

Armed only while a trace is active (``obs.start_trace``); disarmed, the
patch is removed entirely — zero import-time or steady-state cost.  The
hook is defensive throughout: any failure inside capture degrades to "no
features recorded", never to a broken compile (telemetry must not take
down the run it observes).
"""
from __future__ import annotations

import re
import threading
from typing import Any, Dict, List, Optional

__all__ = ["arm", "disarm", "is_armed", "mark", "since", "aggregate",
           "op_histogram", "cost_features"]

_lock = threading.Lock()
_orig = None          # the unpatched compile_or_get_cached while armed
_orig_keep = None     # same, but never cleared (see _hooked)
_ledger: List[Dict[str, Any]] = []

#: cap on the MLIR text scanned for the op histogram — a pathological
#: megamodule costs bounded capture time, not an unbounded regex pass
_MODULE_TEXT_CAP = 1_000_000

_OP_RE = re.compile(r"=\s*(?:stablehlo|mhlo|chlo|func|tt)\.([a-zA-Z0-9_]+)")


def op_histogram(module_text: str,
                 cap: int = _MODULE_TEXT_CAP) -> Dict[str, int]:
    """Opcode histogram of a StableHLO/MHLO module's text form."""
    out: Dict[str, int] = {}
    for m in _OP_RE.finditer(module_text[:cap]):
        op = m.group(1)
        out[op] = out.get(op, 0) + 1
    return out


def _normalize_cost(ca: Any) -> Dict[str, float]:
    """``LoadedExecutable.cost_analysis()`` returns a dict (or a
    one-per-partition list of dicts); keep the scalar headline keys."""
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        return {}
    out: Dict[str, float] = {}
    for key, dest in (("flops", "flops"),
                      ("bytes accessed", "bytes_accessed"),
                      ("transcendentals", "transcendentals"),
                      ("optimal_seconds", "optimal_seconds")):
        v = ca.get(key)
        if isinstance(v, (int, float)):
            out[dest] = float(v)
    return out


def cost_features(compiled, module_text: str = "",
                  name: str = "") -> Dict[str, Any]:
    """Feature record for one compiled executable (also usable directly
    on a ``lowered.compile()`` result, bench_kernels-style)."""
    entry: Dict[str, Any] = {"name": name}
    try:
        entry.update(_normalize_cost(compiled.cost_analysis()))
    except Exception:  # cost analysis is best-effort per backend
        pass
    if module_text:
        try:
            entry["ops"] = op_histogram(module_text)
        except Exception:
            pass
    return entry


def _hooked(backend, computation, devices, compile_options,
            host_callbacks, *args, **kwargs):
    # _orig_keep (never cleared) covers the disarm-while-compiling race:
    # a thread already inside the hook when disarm() restores the patch
    # must still reach the real compiler
    executable = (_orig or _orig_keep)(
        backend, computation, devices, compile_options,
        host_callbacks, *args, **kwargs)
    try:
        try:
            name = str(computation.operation.attributes["sym_name"]
                       ).strip('"')
        except Exception:
            name = ""
        entry = cost_features(executable, module_text=str(computation),
                              name=name)
        with _lock:
            _ledger.append(entry)
    except Exception:  # capture must never break a compile
        pass
    return executable


def arm() -> bool:
    """Install the compile hook; True when (now) armed.  Safe to call
    repeatedly; a jax whose internals moved leaves capture disabled."""
    global _orig, _orig_keep
    with _lock:
        if _orig is not None:
            return True
        try:
            from jax._src import compiler as _compiler

            fn = _compiler.compile_or_get_cached
        except Exception:
            return False
        if fn is _hooked:  # double-armed by another path: keep as-is
            return True
        _orig = _orig_keep = fn
        _compiler.compile_or_get_cached = _hooked
        return True


def disarm() -> None:
    global _orig
    with _lock:
        if _orig is None:
            return
        try:
            from jax._src import compiler as _compiler

            if _compiler.compile_or_get_cached is _hooked:
                _compiler.compile_or_get_cached = _orig
        except Exception:
            pass
        _orig = None


def is_armed() -> bool:
    with _lock:
        return _orig is not None


def mark() -> int:
    """Current ledger position; pass to :func:`since` for delta
    attribution around a stage execution."""
    with _lock:
        return len(_ledger)


def since(position: int) -> List[Dict[str, Any]]:
    with _lock:
        return list(_ledger[position:])


def aggregate(entries: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Merge per-program feature records into one per-stage summary:
    summed FLOPs/bytes, merged op histogram, program count."""
    out: Dict[str, Any] = {"programs": len(entries)}
    ops: Dict[str, int] = {}
    for e in entries:
        for key in ("flops", "bytes_accessed", "transcendentals"):
            v = e.get(key)
            if isinstance(v, (int, float)):
                out[key] = out.get(key, 0.0) + float(v)
        for op, n in (e.get("ops") or {}).items():
            ops[op] = ops.get(op, 0) + int(n)
    if ops:
        out["ops"] = ops
    return out
