"""Prometheus text exposition of the serving + run ledgers.

Renders a ``ServingMetrics`` snapshot (serving/metrics.py) and the global
``RunCounters`` (utils/profiling.py) in the Prometheus text format
(version 0.0.4) — the payload ``GET /metrics?format=prometheus`` serves so
a stock Prometheus scraper can watch a replica without a JSON exporter in
between.

Empty-state discipline (the satellite fix this module ships with): a
fresh server has an empty latency reservoir (quantiles are ``None``) and
zero batches — those render as the ``# TYPE`` header with the quantile
samples simply absent, never as ``None``/``NaN`` literals, so the
exposition always parses.  Counters render ``0`` explicitly (a scraper
distinguishes "zero" from "gone").
"""
from __future__ import annotations

import re
from typing import Any, Dict, List, Optional

__all__ = ["prometheus_text", "parse_exposition"]

#: ServingMetrics snapshot keys exposed as monotonic counters
_SERVING_COUNTERS = (
    ("requests", "requests admitted"),
    ("rows", "rows admitted"),
    ("batches", "micro-batches executed"),
    ("paddedRows", "pad rows added by the shape bucketer"),
    ("shed", "requests shed by backpressure"),
    ("deadlineExpired", "requests expired while queued"),
    ("deviceErrors", "device scoring errors"),
    ("hostFallbacks", "batches served by the host fallback"),
    ("breakerOpens", "circuit breaker open transitions"),
    ("hotSwaps", "registry hot swaps"),
    ("swapsAccepted", "guarded swaps accepted"),
    ("swapsRejected", "guarded swap proposals rejected"),
    ("rollbacks", "guarded-swap rollbacks"),
)

#: snapshot keys exposed as gauges
_SERVING_GAUGES = (
    ("uptimeSecs", "seconds since server start"),
    ("queueDepth", "rows currently queued"),
    ("queueDepthPeak", "peak queued rows"),
    ("latencyObservations", "latency reservoir lifetime observations"),
)


def _snake(name: str) -> str:
    s = re.sub(r"(?<=[a-z0-9])(?=[A-Z])", "_", name).lower()
    return re.sub(r"[^a-z0-9_]", "_", s)


def _num(v: Any) -> Optional[float]:
    return float(v) if isinstance(v, (int, float)) else None


def _esc(v: Any) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"')


class _Doc:
    def __init__(self):
        self.lines: List[str] = []

    def metric(self, name: str, mtype: str, help_text: str,
               samples: List) -> None:
        """One metric family; ``samples`` = [(labels_dict_or_None, value)].
        Emitted even with no samples (TYPE line only) so consumers see the
        family exists — the empty-reservoir case."""
        self.lines.append(f"# HELP {name} {help_text}")
        self.lines.append(f"# TYPE {name} {mtype}")
        for labels, value in samples:
            if value is None:
                continue
            label_s = ""
            if labels:
                inner = ",".join(f'{k}="{_esc(v)}"'
                                 for k, v in sorted(labels.items()))
                label_s = "{" + inner + "}"
            self.lines.append(f"{name}{label_s} {_fmt(value)}")

    def text(self) -> str:
        return "\n".join(self.lines) + "\n"


def _fmt(v: float) -> str:
    f = float(v)
    return str(int(f)) if f.is_integer() and abs(f) < 1e15 else repr(f)


def prometheus_text(snapshot: Optional[Dict[str, Any]] = None,
                    counters=None,
                    tenants: Optional[Dict[str, Dict[str, Any]]] = None,
                    fabric: Optional[Dict[str, Any]] = None) -> str:
    """The full exposition.  ``snapshot`` is a ``ServingMetrics.snapshot()``
    dict (None = no serving section); ``counters`` a ``RunCounters``
    (None = the process-global ``COUNTERS``); ``tenants`` maps tenant name
    -> serving snapshot — every serving sample then carries a
    ``tenant="<name>"`` label, one family emitted once with one sample per
    tenant (the multi-tenant registry's per-tenant exposition); ``fabric``
    is a ``ServingFabric.snapshot()`` — the router's fleet view, with
    every per-host sample carrying a ``host="<id>"`` label."""
    doc = _Doc()
    sections = []
    if snapshot is not None:
        sections.append((None, snapshot))
    for name, snap in sorted((tenants or {}).items()):
        sections.append(({"tenant": name}, snap))
    if sections:
        _serving_section(doc, sections)
    if fabric is not None:
        _fabric_section(doc, fabric)
    if counters is None:
        from ..utils import profiling

        counters = profiling.COUNTERS
    _run_section(doc, counters)
    return doc.text()


def _with_labels(base: Optional[Dict[str, str]],
                 extra: Dict[str, str]) -> Dict[str, str]:
    out = dict(base or {})
    out.update(extra)
    return out


def _serving_section(doc: _Doc, sections) -> None:
    """``sections`` = [(labels_or_None, snapshot)]: each metric family is
    emitted ONCE with one sample per section (per tenant)."""
    for key, help_text in _SERVING_COUNTERS:
        doc.metric(f"tmog_serving_{_snake(key)}_total", "counter",
                   help_text,
                   [(labels, _num(snap.get(key)) or 0.0)
                    for labels, snap in sections])
    for key, help_text in _SERVING_GAUGES:
        doc.metric(f"tmog_serving_{_snake(key)}", "gauge", help_text,
                   [(labels, _num(snap.get(key)) or 0.0)
                    for labels, snap in sections])
    # latency quantiles: absent samples when the reservoir is empty —
    # a summary with no observations yet is a TYPE line, not a NaN
    q_samples = []
    for labels, snap in sections:
        lat = snap.get("latencyMs") or {}
        for q_key, q in (("p50", "0.5"), ("p95", "0.95"), ("p99", "0.99")):
            v = _num(lat.get(q_key))
            if v is not None:
                q_samples.append((_with_labels(labels, {"quantile": q}),
                                  v / 1000.0))
    doc.metric("tmog_serving_request_latency_seconds", "summary",
               "end-to-end request latency (reservoir quantiles)",
               q_samples)
    h_samples = []
    for labels, snap in sections:
        hist = snap.get("batchSizeHistogram") or {}
        h_samples.extend(
            (_with_labels(labels, {"bucket": str(k)}), _num(v))
            for k, v in sorted(hist.items(), key=lambda kv: int(kv[0])))
    doc.metric("tmog_serving_batches_by_bucket_total", "counter",
               "executed micro-batches per shape bucket", h_samples)
    # compile/AOT ledger is process-global: emit once, never per tenant
    cache = (sections[0][1].get("compileCache") or {}).get("totals") or {}
    doc.metric("tmog_compile_cache_events_total", "counter",
               "warm-program compiles vs hits vs AOT store loads/misses",
               [({"event": "compile"}, _num(cache.get("compiles")) or 0.0),
                ({"event": "hit"}, _num(cache.get("hits")) or 0.0),
                ({"event": "aot_load"}, _num(cache.get("aotLoads")) or 0.0),
                ({"event": "aot_miss"},
                 _num(cache.get("aotMisses")) or 0.0)])
    age_samples = []
    for labels, snap in sections:
        age = _num(snap.get("lastFallbackAgeSecs"))
        if age is not None:
            age_samples.append((labels, age))
    doc.metric("tmog_serving_last_fallback_age_seconds", "gauge",
               "seconds since the last host fallback (absent = never)",
               age_samples)


#: per-host fabric counters (FabricMetrics host ledger keys)
_FABRIC_HOST_COUNTERS = (
    ("forwards", "requests forwarded to this host"),
    ("rows", "rows forwarded to this host"),
    ("failovers", "transport failures failed over away from this host"),
    ("spills", "requests spilled past this host under pressure"),
    ("probeFailures", "failed health probes of this host"),
    ("evictions", "router evictions of this host"),
    ("readmissions", "router readmissions of this host"),
)


def _fabric_section(doc: _Doc, snap: Dict[str, Any]) -> None:
    """The router's fleet view: one sample per host (``host="<id>"``
    labels) plus fleet-level request/retry/shed totals and the routed-
    request latency summary."""
    hosts = snap.get("hosts") or {}
    for key, help_text in _FABRIC_HOST_COUNTERS:
        doc.metric(f"tmog_fabric_{_snake(key)}_total", "counter",
                   help_text,
                   [({"host": h}, _num(c.get(key)) or 0.0)
                    for h, c in sorted(hosts.items())])
    doc.metric("tmog_fabric_host_up", "gauge",
               "1 = host in rotation, 0 = evicted or draining",
               [({"host": h},
                 0.0 if (c.get("evicted") or c.get("draining")) else 1.0)
                for h, c in sorted(hosts.items())])
    doc.metric("tmog_fabric_requests_total", "counter",
               "requests routed by the fabric",
               [(None, _num(snap.get("requests")) or 0.0)])
    doc.metric("tmog_fabric_rows_total", "counter",
               "rows routed by the fabric",
               [(None, _num(snap.get("rows")) or 0.0)])
    doc.metric("tmog_fabric_retried_requests_total", "counter",
               "requests that needed at least one failover retry",
               [(None, _num(snap.get("retriedRequests")) or 0.0)])
    doc.metric("tmog_fabric_shed_total", "counter",
               "rows the router shed, by reason",
               [({"reason": r}, _num(v) or 0.0) for r, v in
                sorted((snap.get("shedByReason") or {}).items())])
    q_samples = []
    lat = snap.get("latencyMs") or {}
    for q_key, q in (("p50", "0.5"), ("p95", "0.95"), ("p99", "0.99")):
        v = _num(lat.get(q_key))
        if v is not None:
            q_samples.append(({"quantile": q}, v / 1000.0))
    doc.metric("tmog_fabric_request_latency_seconds", "summary",
               "end-to-end routed-request latency (reservoir quantiles)",
               q_samples)


def _run_section(doc: _Doc, counters) -> None:
    doc.metric("tmog_run_transfers_total", "counter",
               "host<->device transfer operations",
               [({"op": "upload"}, counters.uploads),
                ({"op": "fetch"}, counters.fetches),
                ({"op": "drain"}, counters.drains)])
    doc.metric("tmog_run_transfer_bytes_total", "counter",
               "host<->device bytes moved",
               [({"op": "upload"}, counters.upload_bytes),
                ({"op": "fetch"}, counters.fetch_bytes)])
    doc.metric("tmog_run_transfer_seconds_total", "counter",
               "seconds spent in transfers (enqueue-side lower bound)",
               [({"op": "upload"}, round(counters.upload_s, 6)),
                ({"op": "fetch"}, round(counters.fetch_s, 6)),
                ({"op": "drain"}, round(counters.drain_s, 6))])
    doc.metric("tmog_run_launches_total", "counter",
               "explicit kernel dispatches at framework call sites",
               [(None, counters.launches)])
    doc.metric("tmog_run_elastic_events_total", "counter",
               "elastic sweep events (device loss / shrink / retry / ...)",
               [({"kind": k}, v) for k, v in
                sorted(counters.elastic.items())])
    doc.metric("tmog_run_refresh_events_total", "counter",
               "warm-start refresh estimator outcomes",
               [({"kind": k}, v) for k, v in
                sorted(counters.refresh.items())])


# ---------------------------------------------------------------------------
# parsing (the round-trip check the smoke + tests run over every render)
# ---------------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"          # metric name
    r"(\{[^}]*\})?"                          # optional labels
    r" ([+-]?(?:\d+\.?\d*(?:[eE][+-]?\d+)?|Inf|NaN))$")  # value


def parse_exposition(text: str) -> Dict[str, float]:
    """Minimal Prometheus text-format parser: returns
    ``{metric{labels}: value}`` and raises ``ValueError`` on any line that
    is neither a comment nor a well-formed sample — the validation the
    OBS_SMOKE gate runs over the live exposition."""
    out: Dict[str, float] = {}
    for i, line in enumerate(text.splitlines()):
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"line {i + 1} is not a valid sample: {line!r}")
        name, labels, value = m.groups()
        out[f"{name}{labels or ''}"] = float(value)
    if not text.endswith("\n"):
        raise ValueError("exposition must end with a newline")
    return out
