"""Hierarchical span tracing — one timeline across train/sweep/serve.

Every plane of the system kept its own siloed profiler (``PlanProfiler``,
``IngestProfiler``, ``ServingMetrics``, elastic counters) — good ledgers,
but none of them answers "what happened, in what order, and why" when a
sweep shrinks its mesh mid-rung or a guarded swap rolls back.  This module
is the shared timeline: a process-wide :class:`Tracer` collects
:class:`Span` records (name, category, parent, wall interval, attributes)
from lightweight hooks threaded through ``OpWorkflow.train/refresh``, the
execution plan, the streaming driver, the sweep work queue, and the
serving batch path.

Design constraints, in priority order:

* **Off-path-free when disabled.**  Tracing is opt-in
  (:func:`start_trace`); every hook starts with a single module-global
  ``None`` check, so the disabled cost per hook is one attribute load +
  branch (gated <1% of train wall by the OBS_SMOKE bench contract).
* **Thread-correct.**  The span stack is thread-local; code that fans out
  to worker threads (the plan's host-stage pool, the serving dispatch
  thread) passes the parent span explicitly — the same discipline the
  ``MetricsCollector`` install already follows.
* **Bounded.**  A tracer retains at most ``max_spans`` finished spans
  (drops count in ``dropped``) so a runaway loop cannot OOM the process
  it was meant to observe.

Sinks live in sibling modules: Chrome-trace export (``obs/export.py``),
the flight-recorder event ring (``obs/flight.py``), Prometheus text
exposition (``obs/prometheus.py``).
"""
from __future__ import annotations

import contextlib
import itertools
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

__all__ = ["Span", "Tracer", "start_trace", "stop_trace", "install_tracer",
           "current_tracer", "tracing", "span", "current_span",
           "begin_span", "end_span", "new_trace_id", "set_global_attrs",
           "global_attrs"]

#: attrs stamped onto EVERY span this process opens — the pod runtime
#: sets {"process": process_index} here so the coordinator can merge the
#: per-process span trees and still attribute each span to its host
_GLOBAL_ATTRS: Dict[str, Any] = {}


def set_global_attrs(**attrs: Any) -> None:
    """Merge process-wide span attributes (e.g. the pod process index).
    Only consulted while a tracer is armed — the disabled hook path stays
    a single None check."""
    _GLOBAL_ATTRS.update(attrs)


def global_attrs() -> Dict[str, Any]:
    return dict(_GLOBAL_ATTRS)


def new_trace_id() -> str:
    return uuid.uuid4().hex[:16]


class Span:
    """One timed node of the span tree (finished spans are immutable by
    convention; ``attrs`` may be enriched until :func:`end_span`)."""

    __slots__ = ("name", "cat", "trace_id", "span_id", "parent_id",
                 "t0_unix", "t0", "dur_s", "attrs", "thread")

    def __init__(self, name: str, cat: str, trace_id: str, span_id: int,
                 parent_id: Optional[int], attrs: Dict[str, Any]):
        self.name = name
        self.cat = cat
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.t0_unix = time.time()
        self.t0 = time.perf_counter()
        self.dur_s: Optional[float] = None
        self.attrs = attrs
        self.thread = threading.current_thread().name

    def to_json(self) -> Dict[str, Any]:
        return {"name": self.name, "cat": self.cat,
                "traceId": self.trace_id, "spanId": self.span_id,
                "parentId": self.parent_id,
                "t0": round(self.t0_unix, 6),
                "durSecs": (None if self.dur_s is None
                            else round(self.dur_s, 6)),
                "thread": self.thread, "attrs": dict(self.attrs)}


class Tracer:
    """Collects one run's span tree; thread-safe."""

    def __init__(self, label: str = "", trace_id: Optional[str] = None,
                 max_spans: int = 100_000):
        self.label = label
        self.trace_id = trace_id or new_trace_id()
        self.max_spans = int(max_spans)
        self.started_at = time.time()
        self.spans: List[Span] = []
        self.dropped = 0
        self._ids = itertools.count(1)
        self._lock = threading.Lock()
        #: FlightRecorder installed alongside this tracer (start_trace
        #: wires one by default so span ids link events to the tree)
        self.flight = None

    def begin(self, name: str, cat: str, parent_id: Optional[int],
              attrs: Dict[str, Any]) -> Span:
        return Span(name, cat, self.trace_id, next(self._ids),
                    parent_id, attrs)

    def end(self, sp: Span) -> None:
        sp.dur_s = time.perf_counter() - sp.t0
        with self._lock:
            if len(self.spans) < self.max_spans:
                self.spans.append(sp)
            else:
                self.dropped += 1

    def snapshot(self) -> List[Span]:
        with self._lock:
            return list(self.spans)

    def __len__(self) -> int:
        with self._lock:
            return len(self.spans)


#: the installed tracer; None = tracing disabled (every hook's fast path)
_TRACER: Optional[Tracer] = None

_local = threading.local()


def current_tracer() -> Optional[Tracer]:
    return _TRACER


def current_span() -> Optional[Span]:
    stack = getattr(_local, "stack", None)
    return stack[-1] if stack else None


def install_tracer(tracer: Optional[Tracer]) -> Optional[Tracer]:
    """Install ``tracer`` process-wide (None disables tracing)."""
    global _TRACER
    _TRACER = tracer
    return tracer


def start_trace(label: str = "", max_spans: int = 100_000,
                flight_capacity: int = 4096,
                capture_hlo: bool = True) -> Tracer:
    """Arm tracing process-wide: installs a fresh :class:`Tracer`, a
    linked :class:`~transmogrifai_tpu.obs.flight.FlightRecorder` (span-id
    causality links come for free), and — unless ``capture_hlo=False`` —
    the compiled-program feature hook (``obs/hlo.py``) so device stages
    record their HLO op mix / FLOPs / bytes-accessed."""
    from . import flight as _flight
    from . import hlo as _hlo

    tracer = Tracer(label=label, max_spans=max_spans)
    tracer.flight = _flight.FlightRecorder(capacity=flight_capacity,
                                           trace_id=tracer.trace_id)
    _flight.install_recorder(tracer.flight)
    if capture_hlo:
        _hlo.arm()
    install_tracer(tracer)
    return tracer


def stop_trace() -> Optional[Tracer]:
    """Disarm tracing; returns the tracer that was active (its spans and
    flight recorder stay readable/exportable after stop)."""
    from . import flight as _flight
    from . import hlo as _hlo

    tracer = _TRACER
    install_tracer(None)
    _flight.install_recorder(None)
    _hlo.disarm()
    return tracer


@contextlib.contextmanager
def tracing(label: str = "", **kwargs):
    """``with tracing() as tracer:`` — start/stop_trace as a scope."""
    tracer = start_trace(label, **kwargs)
    try:
        yield tracer
    finally:
        stop_trace()


def begin_span(name: str, cat: str = "run",
               parent: Optional[Span] = None, **attrs) -> Optional[Span]:
    """Open a span (explicit form for hot paths / cross-thread parents).

    Returns None when tracing is disabled — callers hand the result
    straight back to :func:`end_span`, which no-ops on None, so the
    disabled path stays two cheap calls with no allocation."""
    t = _TRACER
    if t is None:
        return None
    if parent is None:
        parent = current_span()
    if _GLOBAL_ATTRS:
        attrs = {**_GLOBAL_ATTRS, **attrs}
    sp = t.begin(name, cat, parent.span_id if parent is not None else None,
                 attrs)
    stack = getattr(_local, "stack", None)
    if stack is None:
        stack = _local.stack = []
    stack.append(sp)
    return sp


def end_span(sp: Optional[Span], **attrs) -> None:
    """Close a span opened by :func:`begin_span` (None = no-op).  Extra
    ``attrs`` merge in at close (e.g. retry counts known only at exit)."""
    if sp is None:
        return
    if attrs:
        sp.attrs.update(attrs)
    stack = getattr(_local, "stack", None)
    if stack:
        try:
            stack.remove(sp)
        except ValueError:  # closed from a different thread: fine
            pass
    t = _TRACER
    if t is not None and t.trace_id == sp.trace_id:
        t.end(sp)


@contextlib.contextmanager
def span(name: str, cat: str = "run", parent: Optional[Span] = None,
         **attrs):
    """Context-manager span; yields the Span (or None when disabled)."""
    sp = begin_span(name, cat, parent=parent, **attrs)
    try:
        yield sp
    finally:
        end_span(sp)
