from .transmogrify import transmogrify, TransmogrifierDefaults  # noqa: F401
from .vector_metadata import VectorMetadata, VectorColumnMetadata  # noqa: F401
from .vectorizers import (  # noqa: F401
    RealVectorizer, IntegralVectorizer, BinaryVectorizer, OneHotVectorizer,
    TextHashingVectorizer, SmartTextVectorizer, MultiPickListVectorizer,
    VectorsCombiner,
)
from .date_geo import DateToUnitCircleVectorizer, GeolocationVectorizer  # noqa: F401
