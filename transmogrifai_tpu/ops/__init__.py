from .transmogrify import transmogrify, TransmogrifierDefaults  # noqa: F401
from .vector_metadata import VectorMetadata, VectorColumnMetadata  # noqa: F401
from .vectorizers import (  # noqa: F401
    RealVectorizer, IntegralVectorizer, BinaryVectorizer, OneHotVectorizer,
    TextHashingVectorizer, SmartTextVectorizer, MultiPickListVectorizer,
    VectorsCombiner,
)
from .date_geo import (  # noqa: F401
    DateToUnitCircleVectorizer, GeolocationVectorizer, DateListVectorizer,
    TimePeriodTransformer, TimePeriodMapTransformer, extract_time_period,
)
from .embeddings import OpWord2Vec, OpWord2VecModel, OpLDA, OpLDAModel  # noqa: F401
from .map_vectorizers import (  # noqa: F401
    NumericMapVectorizer, TextMapPivotVectorizer, MultiPickListMapVectorizer,
    SmartTextMapVectorizer, GeoMapVectorizer,
)
from .detectors import (  # noqa: F401
    MimeTypeDetector, MimeTypeMapDetector, LangDetector,
    ParsePhoneNumber, ParsePhoneDefaultCountry, IsValidPhoneNumber,
    IsValidPhoneDefaultCountry, IsValidPhoneMapDefaultCountry,
    ValidEmailTransformer, HumanNameDetector, NameEntityRecognizer,
    EmailToPickListMapTransformer, UrlMapToPickListMapTransformer, FilterMap,
)
