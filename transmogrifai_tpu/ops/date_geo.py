"""Date and geolocation vectorizers.

Reference: ``DateToUnitCircleTransformer`` (impl/feature/DateToUnitCircleTransformer.scala)
— projects a timestamp onto sin/cos of the chosen period(s) so cyclic time is
linearly separable; ``DateListVectorizer`` modes; ``GeolocationVectorizer``
(impl/feature/GeolocationVectorizer.scala) — fill with mean coordinates +
null indicator.
"""
from __future__ import annotations

import math
from typing import List, Optional, Sequence

import numpy as np

from ..stages.base import SequenceEstimator, SequenceModel, SequenceTransformer
from ..types.columns import ColumnarDataset, FeatureColumn
from ..types.feature_types import OPVector
from .vector_metadata import NULL_INDICATOR, VectorColumnMetadata, VectorMetadata
from .vectorizers import _vec_column

__all__ = ["DateToUnitCircleVectorizer", "GeolocationVectorizer",
           "GeolocationVectorizerModel", "TIME_PERIODS"]

_MS_PER_DAY = 86400000.0
# period name -> ms wavelength
TIME_PERIODS = {
    "HourOfDay": 3600000.0 * 24,       # position within day
    "DayOfWeek": _MS_PER_DAY * 7,
    "DayOfMonth": _MS_PER_DAY * 30.4375,
    "DayOfYear": _MS_PER_DAY * 365.25,
}


class DateToUnitCircleVectorizer(SequenceTransformer):
    """Timestamp (ms) -> (sin, cos) per configured time period (stateless).

    Default period HourOfDay matches the reference's
    ``DateToUnitCircleTransformer`` default.
    """

    def __init__(self, time_periods: Sequence[str] = ("HourOfDay",),
                 track_nulls: bool = True, uid: Optional[str] = None):
        super().__init__(operation_name="dateToUnitCircle", output_type=OPVector, uid=uid)
        self.time_periods = list(time_periods)
        self.track_nulls = track_nulls

    def transform_columns(self, *cols: FeatureColumn) -> FeatureColumn:
        parts, meta = [], []
        for f, c in zip(self.input_features, cols):
            ms = np.nan_to_num(np.asarray(c.values, dtype=np.float64))
            m = np.asarray(c.mask)
            tname = f.ftype.type_name()
            for period in self.time_periods:
                wl = TIME_PERIODS[period]
                theta = 2.0 * math.pi * ((ms % wl) / wl)
                parts.append(np.where(m, np.sin(theta), 0.0))
                parts.append(np.where(m, np.cos(theta), 0.0))
                meta.append(VectorColumnMetadata(f.name, tname,
                                                 descriptor_value=f"{period}_x"))
                meta.append(VectorColumnMetadata(f.name, tname,
                                                 descriptor_value=f"{period}_y"))
            if self.track_nulls:
                parts.append(~m)
                meta.append(VectorColumnMetadata(f.name, tname, grouping=f.name,
                                                 indicator_value=NULL_INDICATOR))
        return _vec_column(np.stack(parts, axis=1), VectorMetadata("date_vec", meta))


class GeolocationVectorizer(SequenceEstimator):
    """(lat, lon, accuracy) -> filled triple + null indicator."""

    def __init__(self, fill_with_mean: bool = True, track_nulls: bool = True,
                 uid: Optional[str] = None):
        super().__init__(operation_name="vecGeo", output_type=OPVector, uid=uid)
        self.fill_with_mean = fill_with_mean
        self.track_nulls = track_nulls

    def fit_columns(self, data: ColumnarDataset, *cols: FeatureColumn):
        fills = []
        for c in cols:
            m = np.asarray(c.mask)
            if self.fill_with_mean and m.any():
                fills.append(np.nan_to_num(
                    np.asarray(c.values, dtype=np.float64)[m].mean(axis=0)
                ).tolist())
            else:
                fills.append([0.0, 0.0, 0.0])
        return GeolocationVectorizerModel(fills=fills, track_nulls=self.track_nulls)


class GeolocationVectorizerModel(SequenceModel):
    def __init__(self, fills: List[List[float]], track_nulls: bool = True,
                 uid: Optional[str] = None):
        super().__init__(operation_name="vecGeo", output_type=OPVector, uid=uid)
        self.fills = fills
        self.track_nulls = track_nulls

    def transform_columns(self, *cols: FeatureColumn) -> FeatureColumn:
        parts, meta = [], []
        for f, fill, c in zip(self.input_features, self.fills, cols):
            vals = np.nan_to_num(np.asarray(c.values, dtype=np.float64))
            m = np.asarray(c.mask)
            filled = np.where(m[:, None], vals, np.asarray(fill)[None, :])
            parts.append(filled)
            tname = f.ftype.type_name()
            for d in ("lat", "lon", "accuracy"):
                meta.append(VectorColumnMetadata(f.name, tname,
                                                 descriptor_value=d))
            if self.track_nulls:
                parts.append((~m)[:, None].astype(np.float64))
                meta.append(VectorColumnMetadata(f.name, tname, grouping=f.name,
                                                 indicator_value=NULL_INDICATOR))
        return _vec_column(np.concatenate(parts, axis=1),
                           VectorMetadata("geo_vec", meta))
