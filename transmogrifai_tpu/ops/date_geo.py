"""Date and geolocation vectorizers.

Reference: ``DateToUnitCircleTransformer`` (impl/feature/DateToUnitCircleTransformer.scala)
— projects a timestamp onto sin/cos of the chosen period(s) so cyclic time is
linearly separable; ``TimePeriodTransformer`` (impl/feature/TimePeriodTransformer.scala)
and ``TimePeriodMapTransformer`` — extract a calendar period as an integer;
``DateListVectorizer`` (impl/feature/DateListVectorizer.scala) — SinceFirst/
SinceLast/ModeDay/ModeMonth/ModeHour pivots; ``GeolocationVectorizer``
(impl/feature/GeolocationVectorizer.scala) — fill with mean coordinates +
null indicator.
"""
from __future__ import annotations

import math
from typing import List, Optional, Sequence

import numpy as np

from ..stages.base import (
    SequenceEstimator, SequenceModel, SequenceTransformer, UnaryTransformer,
)
from ..types.columns import ColumnarDataset, FeatureColumn
from ..types.feature_types import (Date, DateList, DateMap, Geolocation,
                                   Integral, IntegralMap, OPVector)
from .vector_metadata import NULL_INDICATOR, VectorColumnMetadata, VectorMetadata
from .vectorizers import _vec_column

__all__ = ["DateToUnitCircleVectorizer", "GeolocationVectorizer",
           "GeolocationVectorizerModel", "TIME_PERIODS", "TIME_PERIOD_NAMES",
           "extract_time_period", "TimePeriodTransformer",
           "TimePeriodMapTransformer", "DateListVectorizer"]

_MS_PER_DAY = 86400000.0
# period name -> ms wavelength
TIME_PERIODS = {
    "HourOfDay": 3600000.0 * 24,       # position within day
    "DayOfWeek": _MS_PER_DAY * 7,
    "DayOfMonth": _MS_PER_DAY * 30.4375,
    "DayOfYear": _MS_PER_DAY * 365.25,
}


TIME_PERIOD_NAMES = ("DayOfMonth", "DayOfWeek", "DayOfYear", "HourOfDay",
                     "MonthOfYear", "WeekOfMonth", "WeekOfYear")


def extract_time_period(ms: np.ndarray, period: str) -> np.ndarray:
    """Vectorized calendar-period extraction from epoch-millisecond arrays.

    Mirrors the reference's ``TimePeriod`` enum
    (features/.../impl/feature/TimePeriod.scala:54-60): DayOfMonth 1-31,
    DayOfWeek ISO 1-7 (Mon=1), DayOfYear 1-366, HourOfDay 0-23, MonthOfYear
    1-12, WeekOfMonth 1-6, WeekOfYear 1-53.  Weeks are aligned to the first
    day of the month/year (the reference delegates to locale-dependent Java
    ``WeekFields``; this framework pins the locale-free alignment so results
    are reproducible across hosts).
    """
    ms = np.asarray(ms, dtype=np.int64)
    dt = ms.astype("datetime64[ms]")
    days = dt.astype("datetime64[D]")
    if period == "DayOfWeek":
        return (days.astype(np.int64) + 3) % 7 + 1  # 1970-01-01 = Thursday
    if period == "HourOfDay":
        return (ms // 3_600_000) % 24
    if period == "MonthOfYear":
        return dt.astype("datetime64[M]").astype(np.int64) % 12 + 1
    dom = (days - dt.astype("datetime64[M]").astype("datetime64[D]")
           ).astype(np.int64) + 1
    if period == "DayOfMonth":
        return dom
    if period == "WeekOfMonth":
        return (dom - 1) // 7 + 1
    doy = (days - dt.astype("datetime64[Y]").astype("datetime64[D]")
           ).astype(np.int64) + 1
    if period == "DayOfYear":
        return doy
    if period == "WeekOfYear":
        return (doy - 1) // 7 + 1
    raise ValueError(f"unknown time period {period!r}; "
                     f"one of {TIME_PERIOD_NAMES}")


class TimePeriodTransformer(UnaryTransformer):
    """Date -> Integral calendar period (TimePeriodTransformer.scala:46-56)."""

    input_types = (Date,)

    def __init__(self, period: str = "HourOfDay", uid: Optional[str] = None):
        super().__init__(operation_name="dateToTimePeriod",
                         output_type=Integral, uid=uid)
        if period not in TIME_PERIOD_NAMES:
            raise ValueError(f"unknown time period {period!r}")
        self.period = period

    def transform_columns(self, col: FeatureColumn) -> FeatureColumn:
        mask = np.asarray(col.mask)
        ms = np.nan_to_num(np.asarray(col.values, dtype=np.float64))
        out = extract_time_period(ms.astype(np.int64), self.period)
        return FeatureColumn(Integral, out.astype(np.float64), mask.copy())


class TimePeriodMapTransformer(UnaryTransformer):
    """DateMap -> IntegralMap of the period per key
    (TimePeriodMapTransformer.scala:53-56)."""

    input_types = (DateMap,)

    def __init__(self, period: str = "HourOfDay", uid: Optional[str] = None):
        super().__init__(operation_name="dateMapToTimePeriod",
                         output_type=IntegralMap, uid=uid)
        if period not in TIME_PERIOD_NAMES:
            raise ValueError(f"unknown time period {period!r}")
        self.period = period

    def transform_columns(self, col: FeatureColumn) -> FeatureColumn:
        out = np.empty(len(col), dtype=object)
        for i, d in enumerate(col.values):
            keys = [k for k, ms in (d or {}).items() if ms is not None]
            vals = extract_time_period(
                np.asarray([d[k] for k in keys], dtype=np.int64), self.period
            ) if keys else np.empty(0, np.int64)
            out[i] = {k: int(v) for k, v in zip(keys, vals)}
        return FeatureColumn(IntegralMap, out)


def _clean_events(v) -> List[int]:
    """Event list with None entries dropped (None survives from_values)."""
    return [t for t in (v or ()) if t is not None]


_DATE_LIST_PIVOTS = {
    "SinceFirst": None, "SinceLast": None,
    "ModeDay": ("DayOfWeek", 7, 1,
                ("Mon", "Tue", "Wed", "Thu", "Fri", "Sat", "Sun")),
    "ModeMonth": ("MonthOfYear", 12, 1,
                  ("Jan", "Feb", "Mar", "Apr", "May", "Jun", "Jul", "Aug",
                   "Sep", "Oct", "Nov", "Dec")),
    "ModeHour": ("HourOfDay", 24, 0,
                 tuple(f"{h:02d}" for h in range(24))),
}


class DateListVectorizer(SequenceEstimator):
    """DateList(s) -> OPVector by pivot (DateListVectorizer.scala:60-95).

    Pivots: ``SinceFirst``/``SinceLast`` — days between the first/last event
    and a reference date (one slot + optional null indicator per feature);
    ``ModeDay``/``ModeMonth``/``ModeHour`` — one-hot of the modal day-of-week
    / month / hour over the list's events.

    The reference pins ``referenceDate`` at pipeline-construction wall-clock
    time (Transmogrifier.scala:58).  Here, when ``reference_ms`` is not given,
    fit captures the latest training event instead — deterministic, and the
    same reference is reused at scoring so the feature is train/score stable.
    """

    input_types = (DateList,)

    def __init__(self, pivot: str = "SinceFirst",
                 reference_ms: Optional[int] = None, fill_value: float = 0.0,
                 track_nulls: bool = True, uid: Optional[str] = None):
        super().__init__(operation_name="vecDateList", output_type=OPVector,
                         uid=uid)
        if pivot not in _DATE_LIST_PIVOTS:
            raise ValueError(f"unknown pivot {pivot!r}; "
                             f"one of {sorted(_DATE_LIST_PIVOTS)}")
        self.pivot = pivot
        self.reference_ms = reference_ms
        self.fill_value = fill_value
        self.track_nulls = track_nulls

    def fit_columns(self, data: ColumnarDataset, *cols: FeatureColumn):
        ref = self.reference_ms
        if ref is None:
            ref = max((max(ev) for c in cols for v in c.values
                       for ev in [_clean_events(v)] if ev), default=0)
        return DateListVectorizerModel(
            pivot=self.pivot, reference_ms=int(ref),
            fill_value=self.fill_value, track_nulls=self.track_nulls)


class DateListVectorizerModel(SequenceModel):

    input_types = (DateList,)
    def __init__(self, pivot: str = "SinceFirst", reference_ms: int = 0,
                 fill_value: float = 0.0, track_nulls: bool = True,
                 uid: Optional[str] = None):
        super().__init__(operation_name="vecDateList", output_type=OPVector,
                         uid=uid)
        self.pivot = pivot
        self.reference_ms = reference_ms
        self.fill_value = fill_value
        self.track_nulls = track_nulls

    def transform_columns(self, *cols: FeatureColumn) -> FeatureColumn:
        parts, meta = [], []
        for f, c in zip(self.input_features, cols):
            tname = f.ftype.type_name()
            events = [_clean_events(v) for v in c.values]
            empty = np.array([not v for v in events], bool)
            if self.pivot in ("SinceFirst", "SinceLast"):
                pick = min if self.pivot == "SinceFirst" else max
                days = np.array(
                    [(self.reference_ms - pick(v)) / _MS_PER_DAY if v
                     else self.fill_value for v in events], np.float64)
                parts.append(days[:, None])
                meta.append(VectorColumnMetadata(
                    f.name, tname, descriptor_value=self.pivot))
            else:
                period, width, lo, names = _DATE_LIST_PIVOTS[self.pivot]
                block = np.zeros((len(c), width), np.float64)
                for i, v in enumerate(events):
                    if not v:
                        continue
                    vals = extract_time_period(
                        np.asarray(v, dtype=np.int64), period) - lo
                    block[i, np.bincount(vals, minlength=width).argmax()] = 1.0
                parts.append(block)
                meta.extend(VectorColumnMetadata(f.name, tname,
                                                 indicator_value=nm)
                            for nm in names)
            if self.track_nulls:
                parts.append(empty[:, None].astype(np.float64))
                meta.append(VectorColumnMetadata(f.name, tname,
                                                 grouping=f.name,
                                                 indicator_value=NULL_INDICATOR))
        return _vec_column(np.concatenate(parts, axis=1),
                           VectorMetadata("date_list_vec", meta))


class DateToUnitCircleVectorizer(SequenceTransformer):
    """Timestamp (ms) -> (sin, cos) per configured time period (stateless).

    Default period HourOfDay matches the reference's
    ``DateToUnitCircleTransformer`` default.
    """

    input_types = (Date,)

    def __init__(self, time_periods: Sequence[str] = ("HourOfDay",),
                 track_nulls: bool = True, uid: Optional[str] = None):
        super().__init__(operation_name="dateToUnitCircle", output_type=OPVector, uid=uid)
        self.time_periods = list(time_periods)
        self.track_nulls = track_nulls

    def transform_columns(self, *cols: FeatureColumn) -> FeatureColumn:
        parts, meta = [], []
        for f, c in zip(self.input_features, cols):
            ms = np.nan_to_num(np.asarray(c.values, dtype=np.float64))
            m = np.asarray(c.mask)
            tname = f.ftype.type_name()
            for period in self.time_periods:
                wl = TIME_PERIODS[period]
                theta = 2.0 * math.pi * ((ms % wl) / wl)
                parts.append(np.where(m, np.sin(theta), 0.0))
                parts.append(np.where(m, np.cos(theta), 0.0))
                meta.append(VectorColumnMetadata(f.name, tname,
                                                 descriptor_value=f"{period}_x"))
                meta.append(VectorColumnMetadata(f.name, tname,
                                                 descriptor_value=f"{period}_y"))
            if self.track_nulls:
                parts.append(~m)
                meta.append(VectorColumnMetadata(f.name, tname, grouping=f.name,
                                                 indicator_value=NULL_INDICATOR))
        return _vec_column(np.stack(parts, axis=1), VectorMetadata("date_vec", meta))


class GeolocationVectorizer(SequenceEstimator):
    """(lat, lon, accuracy) -> filled triple + null indicator."""

    input_types = (Geolocation,)

    def __init__(self, fill_with_mean: bool = True, track_nulls: bool = True,
                 uid: Optional[str] = None):
        super().__init__(operation_name="vecGeo", output_type=OPVector, uid=uid)
        self.fill_with_mean = fill_with_mean
        self.track_nulls = track_nulls

    def fit_columns(self, data: ColumnarDataset, *cols: FeatureColumn):
        fills = []
        for c in cols:
            m = np.asarray(c.mask)
            if self.fill_with_mean and m.any():
                fills.append(np.nan_to_num(
                    np.asarray(c.values, dtype=np.float64)[m].mean(axis=0)
                ).tolist())
            else:
                fills.append([0.0, 0.0, 0.0])
        return GeolocationVectorizerModel(fills=fills, track_nulls=self.track_nulls)


class GeolocationVectorizerModel(SequenceModel):

    input_types = (Geolocation,)
    def __init__(self, fills: List[List[float]], track_nulls: bool = True,
                 uid: Optional[str] = None):
        super().__init__(operation_name="vecGeo", output_type=OPVector, uid=uid)
        self.fills = fills
        self.track_nulls = track_nulls

    def transform_columns(self, *cols: FeatureColumn) -> FeatureColumn:
        parts, meta = [], []
        for f, fill, c in zip(self.input_features, self.fills, cols):
            vals = np.nan_to_num(np.asarray(c.values, dtype=np.float64))
            m = np.asarray(c.mask)
            filled = np.where(m[:, None], vals, np.asarray(fill)[None, :])
            parts.append(filled)
            tname = f.ftype.type_name()
            for d in ("lat", "lon", "accuracy"):
                meta.append(VectorColumnMetadata(f.name, tname,
                                                 descriptor_value=d))
            if self.track_nulls:
                parts.append((~m)[:, None].astype(np.float64))
                meta.append(VectorColumnMetadata(f.name, tname, grouping=f.name,
                                                 indicator_value=NULL_INDICATOR))
        return _vec_column(np.concatenate(parts, axis=1),
                           VectorMetadata("geo_vec", meta))
