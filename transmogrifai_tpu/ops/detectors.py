"""Derived-type detector stages (host-side, pure Python).

Reference (core/.../impl/feature/, SURVEY §2.5 "Derived-type detectors"):
 * ``MimeTypeDetector``/``MimeTypeMapDetector`` (MimeTypeDetector.scala:49,61)
   — Tika content sniffing becomes a magic-byte table over the decoded
   base64 prefix (``maxBytesToParse`` default 1024, MimeTypeDetector.scala:92).
 * ``LangDetector`` (LangDetector.scala:46) — the Optimaize detector becomes
   a script + stop-word profile scorer emitting ``RealMap`` of
   {language code -> confidence}.
 * Phone stages (PhoneNumberParser.scala:143-258) — libphonenumber becomes
   digit-count validation per region with the reference's
   ``DefaultCountryCodes`` country->dialing-code table
   (PhoneNumberParser.scala:325).
 * ``ValidEmailTransformer`` (ValidEmailTransformer.scala:41).
 * ``HumanNameDetector`` estimator + model (HumanNameDetector.scala:56,87)
   and ``NameEntityRecognizer`` (NameEntityRecognizer.scala:56) — OpenNLP
   models become a built-in first-name dictionary + capitalisation
   heuristics; output is ``NameStats`` (Maps.scala:288-306 keys) /
   ``MultiPickListMap`` of entities per token.
 * ``EmailToPickListMapTransformer`` / ``UrlMapToPickListMapTransformer``
   (EmailToPickListMapTransformer.scala, UrlMapToPickListMapTransformer.scala)
   and ``FilterMap`` key/value filtering (RichMapFeature.scala filter ops).

These are deliberately host-side: they run once per raw column during
ingestion/feature-materialisation and produce small categorical outputs that
the TPU path then vectorizes; there is no FLOP-heavy inner loop to put on
device.
"""
from __future__ import annotations

import base64
import binascii
import re
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..stages.base import (
    BinaryTransformer, UnaryEstimator, UnaryModel, UnaryTransformer,
)
from ..types.columns import ColumnarDataset, FeatureColumn
from ..types.feature_types import (
    Binary, BinaryMap, MultiPickListMap, NameStats, OPMap, Phone,
    PickListMap, RealMap, Text,
)

__all__ = [
    "MimeTypeDetector", "MimeTypeMapDetector",
    "LangDetector",
    "ParsePhoneNumber", "ParsePhoneDefaultCountry",
    "IsValidPhoneNumber", "IsValidPhoneDefaultCountry",
    "IsValidPhoneMapDefaultCountry",
    "ValidEmailTransformer",
    "HumanNameDetector", "HumanNameDetectorModel", "NameEntityRecognizer",
    "EmailToPickListMapTransformer", "UrlMapToPickListMapTransformer",
    "FilterMap",
    "DEFAULT_COUNTRY_CODES",
]


# ---------------------------------------------------------------------------
# MIME type detection (magic bytes)
# ---------------------------------------------------------------------------

#: (prefix bytes, mime) — ordered, first match wins (longest prefixes first)
_MAGIC: List[Tuple[bytes, str]] = [
    (b"%PDF-", "application/pdf"),
    (b"\x89PNG\r\n\x1a\n", "image/png"),
    (b"\xff\xd8\xff", "image/jpeg"),
    (b"GIF87a", "image/gif"),
    (b"GIF89a", "image/gif"),
    (b"BM", "image/bmp"),
    (b"II*\x00", "image/tiff"),
    (b"MM\x00*", "image/tiff"),
    (b"PK\x03\x04", "application/zip"),
    (b"\x1f\x8b", "application/gzip"),
    (b"BZh", "application/x-bzip2"),
    (b"\x7fELF", "application/x-executable"),
    (b"MZ", "application/x-msdownload"),
    (b"OggS", "audio/ogg"),
    (b"ID3", "audio/mpeg"),
    (b"fLaC", "audio/flac"),
    (b"RIFF", "audio/x-wav"),
    (b"\x00\x00\x00\x18ftyp", "video/mp4"),
    (b"\x00\x00\x00\x20ftyp", "video/mp4"),
    (b"{\\rtf", "application/rtf"),
]

_XML_RE = re.compile(rb"^\s*<\?xml")
_HTML_RE = re.compile(rb"^\s*<(!doctype\s+html|html)", re.IGNORECASE)
_JSON_RE = re.compile(rb"^\s*[\[{]")


def _sniff_mime(raw: bytes) -> str:
    for prefix, mime in _MAGIC:
        if raw.startswith(prefix):
            return mime
    if _XML_RE.match(raw):
        return "application/xml"
    if _HTML_RE.match(raw):
        return "text/html"
    if _JSON_RE.match(raw):
        return "application/json"
    try:
        raw.decode("utf-8")
        return "text/plain"
    except UnicodeDecodeError:
        return "application/octet-stream"


_B64_WS_RE = re.compile(r"\s+")
_B64_ALPHABET_RE = re.compile(r"[A-Za-z0-9+/]+={0,2}")


def _detect_mime(v: Optional[str], type_hint: str,
                 max_bytes_to_parse: int) -> Optional[str]:
    if v is None or v == "":
        return None
    if type_hint:
        return type_hint
    # decode just enough base64 chars to cover max_bytes_to_parse bytes;
    # MIME line wrapping must be stripped first or padding misaligns
    n_chars = ((max_bytes_to_parse + 2) // 3) * 4
    chunk = _B64_WS_RE.sub("", v[: n_chars * 2])[:n_chars]
    chunk = chunk[: len(chunk) - len(chunk) % 4]
    if not chunk or not _B64_ALPHABET_RE.fullmatch(chunk):
        return None
    try:
        raw = base64.b64decode(chunk, validate=True)
    except (binascii.Error, ValueError):
        return None
    if not raw:
        return None
    return _sniff_mime(raw[:max_bytes_to_parse])


class MimeTypeDetector(UnaryTransformer):
    """Base64 -> Text mime type (MimeTypeDetector.scala:49-58).

    ``type_hint`` short-circuits detection (typeHint param, :92);
    ``max_bytes_to_parse`` bounds the decoded prefix inspected (default 1024).
    """

    def __init__(self, type_hint: str = "", max_bytes_to_parse: int = 1024,
                 uid: Optional[str] = None):
        super().__init__(operation_name="mimeDetect", output_type=Text, uid=uid)
        self.type_hint = type_hint
        self.max_bytes_to_parse = max_bytes_to_parse

    def detect(self, v: Optional[str]) -> Optional[str]:
        return _detect_mime(v, self.type_hint, self.max_bytes_to_parse)

    def transform_columns(self, col: FeatureColumn) -> FeatureColumn:
        out = np.empty(len(col), dtype=object)
        for i, v in enumerate(col.values):
            out[i] = self.detect(v)
        return FeatureColumn(Text, out)


class MimeTypeMapDetector(UnaryTransformer):
    """Base64Map -> PickListMap of mime types (MimeTypeDetector.scala:61-77)."""

    def __init__(self, type_hint: str = "", max_bytes_to_parse: int = 1024,
                 uid: Optional[str] = None):
        super().__init__(operation_name="mimeMapDetect",
                         output_type=PickListMap, uid=uid)
        self.type_hint = type_hint
        self.max_bytes_to_parse = max_bytes_to_parse

    def transform_columns(self, col: FeatureColumn) -> FeatureColumn:
        out = np.empty(len(col), dtype=object)
        for i, m in enumerate(col.values):
            res = {}
            for k, v in (m or {}).items():
                mime = _detect_mime(v, self.type_hint, self.max_bytes_to_parse)
                if mime is not None:
                    res[k] = mime
            out[i] = res
        return FeatureColumn(PickListMap, out)


# ---------------------------------------------------------------------------
# Language detection (script + stop-word profiles)
# ---------------------------------------------------------------------------

_LANG_PROFILES: Dict[str, frozenset] = {
    "en": frozenset("the and of to in is you that it he was for on are with"
                    " as his they be at one have this from had not but what"
                    .split()),
    "fr": frozenset("le la les de des et un une du en est que qui dans pour"
                    " pas sur ne se ce il elle nous vous au aux son ses mais"
                    .split()),
    "de": frozenset("der die das und ist von zu den dem ein eine nicht mit"
                    " sich auf für als auch es an werden aus er hat dass sie"
                    .split()),
    "es": frozenset("el la los las de y un una del en es que no se por con"
                    " para su al lo como más pero sus le ya o este sí porque"
                    .split()),
    "it": frozenset("il la le di e un una del in è che non si per con su"
                    " come più ma anche dei delle nel alla questo sono della"
                    .split()),
    "pt": frozenset("o a os as de e um uma do da em é que não se por com"
                    " para seu ao como mais mas os foi são dos uma pelo nos"
                    .split()),
    "nl": frozenset("de het een en van in is dat op te zijn met die voor"
                    " niet aan er om ook als maar dan zij bij uit nog naar"
                    .split()),
}

_SCRIPT_RANGES: List[Tuple[int, int, str]] = [
    (0x0400, 0x04FF, "ru"),   # Cyrillic
    (0x0590, 0x05FF, "he"),   # Hebrew
    (0x0600, 0x06FF, "ar"),   # Arabic
    (0x0900, 0x097F, "hi"),   # Devanagari
    (0x3040, 0x30FF, "ja"),   # Hiragana/Katakana
    (0xAC00, 0xD7AF, "ko"),   # Hangul
    (0x4E00, 0x9FFF, "zh"),   # CJK ideographs
    (0x0E00, 0x0E7F, "th"),   # Thai
    (0x0370, 0x03FF, "el"),   # Greek
]

_WORD_RE = re.compile(r"[\w']+", re.UNICODE)


class LangDetector(UnaryTransformer):
    """Text -> RealMap {ISO language -> confidence} (LangDetector.scala:46-60).

    Non-Latin scripts are detected by unicode block; Latin-script languages
    by stop-word profile hit rate, normalised to sum to 1 over languages with
    any hits.
    """

    def __init__(self, uid: Optional[str] = None):
        super().__init__(operation_name="langDetect", output_type=RealMap,
                         uid=uid)

    def detect(self, v: Optional[str]) -> Dict[str, float]:
        if not v:
            return {}
        script_hits: Dict[str, int] = {}
        n_alpha = 0
        for ch in v:
            o = ord(ch)
            if o < 0x250:
                if ch.isalpha():
                    n_alpha += 1
                continue
            for lo, hi, lang in _SCRIPT_RANGES:
                if lo <= o <= hi:
                    script_hits[lang] = script_hits.get(lang, 0) + 1
                    break
        if script_hits:
            # Japanese text mixes kana + CJK ideographs: kana presence wins
            if "ja" in script_hits and "zh" in script_hits:
                script_hits["ja"] += script_hits.pop("zh")
        n_script = sum(script_hits.values())
        # Latin-script languages scored by stop-word profile hit rate
        latin_scores: Dict[str, float] = {}
        if n_alpha:
            words = [w.lower() for w in _WORD_RE.findall(v)]
            for lang, profile in _LANG_PROFILES.items():
                hits = sum(1 for w in words if w in profile)
                if hits:
                    latin_scores[lang] = hits / len(words)
        # blend the two families by their share of alphabetic characters so a
        # stray non-Latin char cannot override a mostly-Latin text
        total_chars = n_script + n_alpha
        lt = sum(latin_scores.values())
        out: Dict[str, float] = {}
        if total_chars == 0:
            return {}
        if n_script:
            w_script = n_script / total_chars if lt else 1.0
            for k, c in script_hits.items():
                out[k] = w_script * c / n_script
        if lt:
            w_latin = 1.0 - sum(out.values()) if out else 1.0
            for k, sc in latin_scores.items():
                out[k] = out.get(k, 0.0) + w_latin * sc / lt
        return out

    def transform_columns(self, col: FeatureColumn) -> FeatureColumn:
        out = np.empty(len(col), dtype=object)
        for i, v in enumerate(col.values):
            out[i] = self.detect(v)
        return FeatureColumn(RealMap, out)


# ---------------------------------------------------------------------------
# Phone parsing / validation
# ---------------------------------------------------------------------------

#: country name -> dialing code (PhoneNumberParser.scala:325 DefaultCountryCodes)
DEFAULT_COUNTRY_CODES: Dict[str, str] = {
    "UNITED STATES": "1", "CANADA": "1", "UNITED KINGDOM": "44",
    "FRANCE": "33", "GERMANY": "49", "SPAIN": "34", "ITALY": "39",
    "AUSTRALIA": "61", "JAPAN": "81", "CHINA": "86", "INDIA": "91",
    "BRAZIL": "55", "MEXICO": "52", "NETHERLANDS": "31", "SWEDEN": "46",
    "SWITZERLAND": "41", "IRELAND": "353", "SINGAPORE": "65",
    "NEW ZEALAND": "64", "SOUTH AFRICA": "27", "ISRAEL": "972",
    "KOREA": "82", "RUSSIA": "7", "POLAND": "48", "PORTUGAL": "351",
}

#: region -> required national-number digit counts (libphonenumber-lite)
_REGION_DIGITS: Dict[str, Tuple[int, int]] = {
    "1": (10, 10), "44": (9, 10), "33": (9, 9), "49": (7, 11),
    "34": (9, 9), "39": (8, 11), "61": (9, 9), "81": (9, 10),
    "86": (10, 11), "91": (10, 10), "55": (10, 11), "52": (10, 10),
}

_CLEAN_PHONE_RE = re.compile(r"[^+\d]")


def _clean_number(pn: str) -> str:
    """PhoneNumberParser.cleanNumber (:267): strip all but digits and '+'."""
    return _CLEAN_PHONE_RE.sub("", pn.strip())


def _region_code(region: str) -> str:
    """Accept a dialing code, a country name, or an ISO-ish name."""
    r = region.strip().upper()
    if r.isdigit():
        return r
    return DEFAULT_COUNTRY_CODES.get(r, "1")


def _parse_phone(pn: Optional[str], region: str,
                 strict: bool) -> Optional[str]:
    """Return E.164 string or None (PhoneNumberParser.parse :314)."""
    if not pn:
        return None
    cleaned = _clean_number(pn)
    if not cleaned:
        return None
    if cleaned.startswith("+"):
        digits = cleaned[1:]
        if not (7 <= len(digits) <= 15) or not digits.isdigit():
            return None
        return "+" + digits
    code = _region_code(region)
    digits = cleaned.lstrip("0") if not strict else cleaned
    if not digits.isdigit():
        return None
    lo, hi = _REGION_DIGITS.get(code, (7, 12))
    # tolerate a leading trunk/country prefix when not strict
    if digits.startswith(code) and len(digits) - len(code) >= lo and not strict:
        digits = digits[len(code):]
    if not (lo <= len(digits) <= hi):
        return None
    if code == "1":
        # NANP: area code and exchange cannot start with 0/1
        if digits[0] in "01" or digits[3] in "01":
            return None
    return f"+{code}{digits}"


class ParsePhoneNumber(BinaryTransformer):
    """(Phone, Text region) -> E.164 Phone (PhoneNumberParser.scala:143-167)."""

    def __init__(self, strict_validation: bool = False,
                 uid: Optional[str] = None):
        super().__init__(operation_name="parsePhone", output_type=Phone,
                         uid=uid)
        self.strict_validation = strict_validation

    def transform_columns(self, phone: FeatureColumn,
                          region: FeatureColumn) -> FeatureColumn:
        out = np.empty(len(phone), dtype=object)
        for i, (p, r) in enumerate(zip(phone.values, region.values)):
            out[i] = _parse_phone(p, r or "1", self.strict_validation)
        return FeatureColumn(Phone, out)


class ParsePhoneDefaultCountry(UnaryTransformer):
    """Phone -> E.164 Phone with one default region
    (PhoneNumberParser.scala:170-196)."""

    def __init__(self, default_region: str = "1", strict_validation: bool = False,
                 uid: Optional[str] = None):
        super().__init__(operation_name="parsePhoneDefault", output_type=Phone,
                         uid=uid)
        self.default_region = default_region
        self.strict_validation = strict_validation

    def transform_columns(self, col: FeatureColumn) -> FeatureColumn:
        out = np.empty(len(col), dtype=object)
        for i, p in enumerate(col.values):
            out[i] = _parse_phone(p, self.default_region,
                                  self.strict_validation)
        return FeatureColumn(Phone, out)


class IsValidPhoneNumber(BinaryTransformer):
    """(Phone, Text region) -> Binary (PhoneNumberParser.scala:198-222)."""

    def __init__(self, strict_validation: bool = False,
                 uid: Optional[str] = None):
        super().__init__(operation_name="validPhone", output_type=Binary,
                         uid=uid)
        self.strict_validation = strict_validation

    def transform_columns(self, phone: FeatureColumn,
                          region: FeatureColumn) -> FeatureColumn:
        out = [
            None if p is None
            else _parse_phone(p, r or "1", self.strict_validation) is not None
            for p, r in zip(phone.values, region.values)
        ]
        return FeatureColumn.from_values(Binary, out)


class IsValidPhoneDefaultCountry(UnaryTransformer):
    """Phone -> Binary with one default region
    (PhoneNumberParser.scala:225-239)."""

    def __init__(self, default_region: str = "1", strict_validation: bool = False,
                 uid: Optional[str] = None):
        super().__init__(operation_name="validPhoneDefault", output_type=Binary,
                         uid=uid)
        self.default_region = default_region
        self.strict_validation = strict_validation

    def transform_columns(self, col: FeatureColumn) -> FeatureColumn:
        out = [
            None if p is None
            else _parse_phone(p, self.default_region,
                              self.strict_validation) is not None
            for p in col.values
        ]
        return FeatureColumn.from_values(Binary, out)


class IsValidPhoneMapDefaultCountry(UnaryTransformer):
    """PhoneMap -> BinaryMap (PhoneNumberParser.scala:241-257)."""

    def __init__(self, default_region: str = "1", strict_validation: bool = False,
                 uid: Optional[str] = None):
        super().__init__(operation_name="validPhoneMapDefault",
                         output_type=BinaryMap, uid=uid)
        self.default_region = default_region
        self.strict_validation = strict_validation

    def transform_columns(self, col: FeatureColumn) -> FeatureColumn:
        out = np.empty(len(col), dtype=object)
        for i, m in enumerate(col.values):
            out[i] = {
                k: _parse_phone(v, self.default_region,
                                self.strict_validation) is not None
                for k, v in (m or {}).items() if v is not None
            }
        return FeatureColumn(BinaryMap, out)


# ---------------------------------------------------------------------------
# Email validation / domain extraction
# ---------------------------------------------------------------------------

_EMAIL_RE = re.compile(
    r"^[A-Za-z0-9.!#$%&'*+/=?^_`{|}~-]+@"
    r"[A-Za-z0-9](?:[A-Za-z0-9-]{0,61}[A-Za-z0-9])?"
    r"(?:\.[A-Za-z0-9](?:[A-Za-z0-9-]{0,61}[A-Za-z0-9])?)+$")


class ValidEmailTransformer(UnaryTransformer):
    """Email -> Binary validity (ValidEmailTransformer.scala:41-47)."""

    def __init__(self, uid: Optional[str] = None):
        super().__init__(operation_name="validEmail", output_type=Binary,
                         uid=uid)

    def transform_columns(self, col: FeatureColumn) -> FeatureColumn:
        out = [None if v is None else bool(_EMAIL_RE.match(v))
               for v in col.values]
        return FeatureColumn.from_values(Binary, out)


def _email_domain(v: Optional[str]) -> Optional[str]:
    if v is None or "@" not in v:
        return None
    return v.rsplit("@", 1)[1].lower() or None


_URL_HOST_RE = re.compile(
    r"^(?:[a-z][a-z0-9+.-]*:)?//(?:[^/?#@]*@)?([^/?#:@]+)", re.IGNORECASE)


def _url_host(v: Optional[str]) -> Optional[str]:
    if not v:
        return None
    has_scheme = "://" in v or v.startswith("//")
    m = _URL_HOST_RE.match(v if has_scheme else "//" + v)
    return m.group(1).lower() if m else None


class EmailToPickListMapTransformer(UnaryTransformer):
    """EmailMap -> PickListMap of email domains
    (EmailToPickListMapTransformer.scala)."""

    def __init__(self, uid: Optional[str] = None):
        super().__init__(operation_name="emailToPickListMap",
                         output_type=PickListMap, uid=uid)

    def transform_columns(self, col: FeatureColumn) -> FeatureColumn:
        out = np.empty(len(col), dtype=object)
        for i, m in enumerate(col.values):
            res = {}
            for k, v in (m or {}).items():
                d = _email_domain(v)
                if d is not None:
                    res[k] = d
            out[i] = res
        return FeatureColumn(PickListMap, out)


class UrlMapToPickListMapTransformer(UnaryTransformer):
    """URLMap -> PickListMap of hostnames
    (UrlMapToPickListMapTransformer.scala)."""

    def __init__(self, uid: Optional[str] = None):
        super().__init__(operation_name="urlToPickListMap",
                         output_type=PickListMap, uid=uid)

    def transform_columns(self, col: FeatureColumn) -> FeatureColumn:
        out = np.empty(len(col), dtype=object)
        for i, m in enumerate(col.values):
            res = {}
            for k, v in (m or {}).items():
                h = _url_host(v)
                if h is not None:
                    res[k] = h
            out[i] = res
        return FeatureColumn(PickListMap, out)


class FilterMap(UnaryTransformer):
    """OPMap -> OPMap filtered by key allow/block lists and value block list
    (RichMapFeature filter ops / FilterMap in the reference DSL)."""

    input_arity = (1, 1)

    def __init__(self, allow_keys: Optional[Sequence[str]] = None,
                 block_keys: Sequence[str] = (),
                 block_values: Sequence[str] = (),
                 uid: Optional[str] = None):
        super().__init__(operation_name="filterMap", output_type=OPMap, uid=uid)
        self.allow_keys = list(allow_keys) if allow_keys else None
        self.block_keys = list(block_keys)
        self.block_values = list(block_values)

    def on_set_input(self) -> None:
        # output keeps the concrete input map type; this hook runs before the
        # base class constructs the output feature from self.output_type
        self.output_type = self.input_features[0].ftype

    def transform_columns(self, col: FeatureColumn) -> FeatureColumn:
        allow = set(self.allow_keys) if self.allow_keys else None
        block = set(self.block_keys)
        bvals = set(self.block_values)
        out = np.empty(len(col), dtype=object)
        for i, m in enumerate(col.values):
            out[i] = {
                k: v for k, v in (m or {}).items()
                if (allow is None or k in allow) and k not in block
                and (not isinstance(v, str) or v not in bvals)
            }
        return FeatureColumn(self.output_type, out)


# ---------------------------------------------------------------------------
# Human name detection
# ---------------------------------------------------------------------------

#: small built-in first-name dictionary with gender tags (OpenNLP replacement)
_FIRST_NAMES: Dict[str, str] = {}
for _male in ("james john robert michael william david richard joseph thomas"
              " charles christopher daniel matthew anthony mark donald steven"
              " paul andrew joshua kenneth kevin brian george edward ronald"
              " timothy jason jeffrey ryan jacob gary nicholas eric jonathan"
              " stephen larry justin scott brandon benjamin samuel frank"
              " gregory raymond alexander patrick jack dennis jerry tyler"
              " aaron jose adam henry nathan douglas zachary peter kyle"
              " walter ethan jeremy harold keith christian roger noah alan"
              " juan carlos luis miguel pedro diego pierre jean luca marco"
              " hans klaus yuki hiroshi wei chen raj arjun").split():
    _FIRST_NAMES[_male] = "Male"
for _female in ("mary patricia jennifer linda elizabeth barbara susan jessica"
                " sarah karen nancy lisa margaret betty sandra ashley dorothy"
                " kimberly emily donna michelle carol amanda melissa deborah"
                " stephanie rebecca laura sharon cynthia kathleen amy shirley"
                " angela helen anna brenda pamela nicole ruth katherine"
                " samantha christine emma catherine debra virginia rachel"
                " carolyn janet maria heather diane julie joyce victoria"
                " olivia sophia isabella mia charlotte amelia evelyn abigail"
                " ava grace chloe camila penelope riley aria lily nora zoe"
                " marie sofia ana lucia elena ingrid yuna mei priya").split():
    _FIRST_NAMES[_female] = "Female"

_NAME_TOKEN_RE = re.compile(r"[A-Za-z][A-Za-z'\-]*")


def _name_stats(v: Optional[str]) -> Dict[str, str]:
    """Per-value NameStats map (HumanNameDetectorModel.transformFn :98-114)."""
    if not v:
        return {}
    tokens = _NAME_TOKEN_RE.findall(v)
    if not 1 <= len(tokens) <= 4:
        return {"OriginalValue": v, "IsName": "false"}
    first = tokens[0].lower()
    gender = _FIRST_NAMES.get(first)
    dict_hit = gender is not None
    # capitalised tokens that aren't sentence-like
    looks = all(t[0].isupper() for t in tokens if len(t) > 1)
    is_name = dict_hit or (looks and len(tokens) in (2, 3))
    stats = {"OriginalValue": v, "IsName": str(is_name).lower()}
    if is_name:
        stats["FirstName"] = tokens[0]
        if len(tokens) > 1:
            stats["LastName"] = tokens[-1]
        stats["Gender"] = gender if gender else "GenderNotInferred"
    return stats


class HumanNameDetector(UnaryEstimator):
    """Text -> NameStats estimator (HumanNameDetector.scala:56-84).

    Fit decides whether the column as a whole is a name column: the fraction
    of non-null values recognised as names must reach ``threshold``
    (defaultThreshold in the reference).  The model then emits per-row
    ``NameStats`` maps (empty when the column is not a name column).
    """

    def __init__(self, threshold: float = 0.5, uid: Optional[str] = None):
        super().__init__(operation_name="humanNameDetect",
                         output_type=NameStats, uid=uid)
        self.threshold = threshold

    def fit_columns(self, data: ColumnarDataset, col: FeatureColumn):
        n, hits = 0, 0
        for v in col.values:
            if v is None or v == "":
                continue
            n += 1
            if _name_stats(v).get("IsName") == "true":
                hits += 1
        treat_as_name = n > 0 and hits / n >= self.threshold
        self.metadata["name_fraction"] = hits / n if n else 0.0
        return HumanNameDetectorModel(treat_as_name=treat_as_name)


class HumanNameDetectorModel(UnaryModel):
    def __init__(self, treat_as_name: bool = False, uid: Optional[str] = None):
        super().__init__(operation_name="humanNameDetect",
                         output_type=NameStats, uid=uid)
        self.treat_as_name = treat_as_name

    def transform_columns(self, col: FeatureColumn) -> FeatureColumn:
        out = np.empty(len(col), dtype=object)
        for i, v in enumerate(col.values):
            out[i] = _name_stats(v) if self.treat_as_name else {}
        return FeatureColumn(NameStats, out)


class NameEntityRecognizer(UnaryTransformer):
    """Text -> MultiPickListMap token->entity-tags
    (NameEntityRecognizer.scala:56-90).

    The OpenNLP NER chain becomes a dictionary + capitalisation tagger; each
    recognised token maps to the set of entity tags found for it (the
    reference emits {token -> Set(entity)} the same way).
    """

    def __init__(self, uid: Optional[str] = None):
        super().__init__(operation_name="nameEntityRec",
                         output_type=MultiPickListMap, uid=uid)

    def transform_columns(self, col: FeatureColumn) -> FeatureColumn:
        out = np.empty(len(col), dtype=object)
        for i, v in enumerate(col.values):
            tags: Dict[str, set] = {}
            if v:
                tokens = _NAME_TOKEN_RE.findall(v)
                for j, t in enumerate(tokens):
                    low = t.lower()
                    if low in _FIRST_NAMES:
                        tags.setdefault(t, set()).add("Person")
                        # a capitalised follower of a known first name is
                        # treated as the surname of the same Person entity
                        if (j + 1 < len(tokens)
                                and tokens[j + 1][0].isupper()):
                            tags.setdefault(tokens[j + 1], set()).add("Person")
            out[i] = {k: frozenset(s) for k, s in tags.items()}
        return FeatureColumn(MultiPickListMap, out)
