"""Algebra / utility transformers behind the feature DSL.

Reference (core/.../impl/feature/, SURVEY §2.5 "Algebra/DSL ops"):
``MathTransformers`` (+,-,*,/ on features), ``AliasTransformer``,
``FilterTransformer``, ``SubstringTransformer``, ``JaccardSimilarity``,
``NGramSimilarity``, ``ToOccurTransformer``, ``ExistsTransformer``,
``ReplaceTransformer``, ``DropIndicesByTransformer``
(DropIndicesByTransformer.scala).
"""
from __future__ import annotations

from typing import Any, Callable, List, Optional

import numpy as np

from ..stages.base import BinaryTransformer, UnaryTransformer
from ..types.columns import FeatureColumn
from ..types.feature_types import (
    Binary, OPVector, Real, RealNN, Text,
)
from .vector_metadata import VectorColumnMetadata

__all__ = [
    "MathBinaryTransformer", "MathScalarTransformer", "AliasTransformer",
    "FilterTransformer", "SubstringTransformer", "JaccardSimilarity",
    "NGramSimilarity", "ToOccurTransformer", "ExistsTransformer",
    "ReplaceTransformer", "DropIndicesByTransformer",
]

_BIN_OPS = {
    "plus": lambda a, b: a + b,
    "minus": lambda a, b: a - b,
    "multiply": lambda a, b: a * b,
    "divide": lambda a, b: np.divide(a, np.where(b == 0, np.nan, b)),
}


class MathBinaryTransformer(BinaryTransformer):
    """Elementwise arithmetic of two numeric features (MathTransformers.scala);
    missing in either side -> missing out."""

    def __init__(self, op: str, uid: Optional[str] = None):
        super().__init__(operation_name=op, output_type=Real, uid=uid)
        if op not in _BIN_OPS:
            raise ValueError(f"unknown op {op!r}")
        self.op = op

    def transform_columns(self, a: FeatureColumn, b: FeatureColumn) -> FeatureColumn:
        va = np.nan_to_num(np.asarray(a.values, np.float64))
        vb = np.nan_to_num(np.asarray(b.values, np.float64))
        out = _BIN_OPS[self.op](va, vb)
        mask = np.asarray(a.mask) & np.asarray(b.mask) & np.isfinite(out)
        return FeatureColumn(Real, np.where(mask, out, np.nan), mask)


class MathScalarTransformer(UnaryTransformer):
    """feature <op> scalar (MathTransformers.scala scalar variants)."""

    def __init__(self, op: str, scalar: float, uid: Optional[str] = None):
        super().__init__(operation_name=f"{op}Scalar", output_type=Real,
                         uid=uid)
        if op not in _BIN_OPS:
            raise ValueError(f"unknown op {op!r}")
        self.op = op
        self.scalar = scalar

    def transform_columns(self, col: FeatureColumn) -> FeatureColumn:
        v = np.nan_to_num(np.asarray(col.values, np.float64))
        out = _BIN_OPS[self.op](v, np.float64(self.scalar))
        mask = np.asarray(col.mask) & np.isfinite(out)
        return FeatureColumn(Real, np.where(mask, out, np.nan), mask)


class AliasTransformer(UnaryTransformer):
    """Rename-only pass-through (AliasTransformer.scala)."""

    def __init__(self, name: str, uid: Optional[str] = None):
        super().__init__(operation_name="alias", output_type=Real, uid=uid)
        self.name = name

    def on_set_input(self) -> None:
        self.output_type = self.input_features[0].ftype

    def make_output_name(self) -> str:
        return self.name

    def transform_columns(self, col: FeatureColumn) -> FeatureColumn:
        return col


class FilterTransformer(UnaryTransformer):
    """Keep values passing a predicate, else missing (FilterTransformer)."""

    def __init__(self, predicate: Callable[[Any], bool],
                 uid: Optional[str] = None):
        super().__init__(operation_name="filter", output_type=Real, uid=uid)
        self.predicate = predicate

    def on_set_input(self) -> None:
        self.output_type = self.input_features[0].ftype

    def transform_columns(self, col: FeatureColumn) -> FeatureColumn:
        vals = col.to_list()
        kept = [v if v is not None and self.predicate(v) else None
                for v in vals]
        return FeatureColumn.from_values(self.output_type, kept)


class SubstringTransformer(BinaryTransformer):
    """Binary(text2 is substring of text1) (SubstringTransformer.scala)."""

    def __init__(self, uid: Optional[str] = None):
        super().__init__(operation_name="substring", output_type=Binary,
                         uid=uid)

    def transform_columns(self, a: FeatureColumn, b: FeatureColumn) -> FeatureColumn:
        out, mask = [], []
        for x, y in zip(a.values, b.values):
            if x is None or y is None:
                out.append(False)
                mask.append(False)
            else:
                out.append(str(y).lower() in str(x).lower())
                mask.append(True)
        return FeatureColumn(Binary, np.asarray(out, np.float64),
                             np.asarray(mask))


def _jaccard(s1, s2) -> float:
    a, b = set(s1), set(s2)
    if not a and not b:
        return 1.0
    return len(a & b) / len(a | b)


class JaccardSimilarity(BinaryTransformer):
    """Jaccard similarity of two sets/lists (JaccardSimilarity.scala,
    utils/stats/JaccardSim.scala)."""

    def __init__(self, uid: Optional[str] = None):
        super().__init__(operation_name="jaccardSim", output_type=RealNN,
                         uid=uid)

    def transform_columns(self, a: FeatureColumn, b: FeatureColumn) -> FeatureColumn:
        out = np.array([_jaccard(x or (), y or ())
                        for x, y in zip(a.values, b.values)], np.float64)
        return FeatureColumn(RealNN, out, np.ones(len(out), bool))


class NGramSimilarity(BinaryTransformer):
    """Character n-gram Jaccard similarity of two texts
    (NGramSimilarity.scala)."""

    def __init__(self, n: int = 3, uid: Optional[str] = None):
        super().__init__(operation_name="ngramSim", output_type=RealNN,
                         uid=uid)
        self.n = n

    def _grams(self, v) -> set:
        if v is None:
            return set()
        if isinstance(v, (tuple, list, set, frozenset)):
            v = " ".join(map(str, v))
        s = str(v).lower()
        return {s[i:i + self.n] for i in range(max(len(s) - self.n + 1, 0))}

    def transform_columns(self, a: FeatureColumn, b: FeatureColumn) -> FeatureColumn:
        out = np.array([_jaccard(self._grams(x), self._grams(y))
                        for x, y in zip(a.values, b.values)], np.float64)
        return FeatureColumn(RealNN, out, np.ones(len(out), bool))


class ToOccurTransformer(UnaryTransformer):
    """Any feature -> RealNN(0/1) presence/truthiness (ToOccurTransformer)."""

    def __init__(self, matches: Optional[Callable[[Any], bool]] = None,
                 uid: Optional[str] = None):
        super().__init__(operation_name="toOccur", output_type=RealNN,
                         uid=uid)
        self.matches = matches

    def transform_columns(self, col: FeatureColumn) -> FeatureColumn:
        fn = self.matches or (lambda v: bool(v) or v == 0.0)
        out = np.array([1.0 if (v is not None and fn(v)) else 0.0
                        for v in col.to_list()], np.float64)
        return FeatureColumn(RealNN, out, np.ones(len(out), bool))


class ExistsTransformer(UnaryTransformer):
    """Binary(value is present) (ExistsTransformer.scala)."""

    def __init__(self, uid: Optional[str] = None):
        super().__init__(operation_name="exists", output_type=Binary, uid=uid)

    def transform_columns(self, col: FeatureColumn) -> FeatureColumn:
        # missing collection values are stored as empty containers, so
        # presence = isEmpty semantics there; an empty *string* is still a
        # present Text value (reference Text(Some("")).nonEmpty)
        def present(v):
            if v is None:
                return False
            if isinstance(v, (list, tuple, set, frozenset, dict)):
                return len(v) > 0
            return True

        out = np.array([present(v) for v in col.to_list()], np.float64)
        return FeatureColumn(Binary, out, np.ones(len(out), bool))


class ReplaceTransformer(UnaryTransformer):
    """Replace matching values (ReplaceTransformer.scala)."""

    def __init__(self, replace: Any, with_value: Any,
                 uid: Optional[str] = None):
        super().__init__(operation_name="replace", output_type=Text, uid=uid)
        self.replace = replace
        self.with_value = with_value

    def on_set_input(self) -> None:
        self.output_type = self.input_features[0].ftype

    def transform_columns(self, col: FeatureColumn) -> FeatureColumn:
        vals = [self.with_value if v == self.replace else v
                for v in col.to_list()]
        return FeatureColumn.from_values(self.output_type, vals)


class DropIndicesByTransformer(UnaryTransformer):
    """Drop vector slots whose metadata matches a predicate
    (DropIndicesByTransformer.scala)."""

    def __init__(self, predicate: Callable[[VectorColumnMetadata], bool],
                 uid: Optional[str] = None):
        super().__init__(operation_name="dropIndicesBy",
                         output_type=OPVector, uid=uid)
        self.predicate = predicate

    def transform_columns(self, col: FeatureColumn) -> FeatureColumn:
        if col.vmeta is None:
            raise ValueError("input vector has no metadata to filter by")
        keep = [j for j, c in enumerate(col.vmeta.columns)
                if not self.predicate(c)]
        X = np.asarray(col.values)[:, keep]
        return FeatureColumn(OPVector, X.astype(np.float32),
                             vmeta=col.vmeta.select(keep))
