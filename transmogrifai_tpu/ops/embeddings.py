"""Learned text embeddings: word2vec and LDA, trained as XLA programs.

Reference parity:
 * ``OpWord2Vec`` (core/.../impl/feature/OpWord2Vec.scala:42) wraps Spark ML
   Word2Vec (skip-gram + negative sampling trained by distributed SGD);
   the model embeds a TextList as the average of its tokens' vectors.
 * ``OpLDA`` (core/.../impl/feature/OpLDA.scala:42) wraps Spark ML LDA
   (online variational Bayes, Hoffman et al.); the model emits the
   per-document topic distribution.

TPU-first design: both trainers are formulated as dense-matmul loops —
skip-gram negative sampling as batched gather + outer-product SGD steps under
``lax.fori_loop``, LDA as the classic variational E/M recurrence whose inner
loop is two (docs×topics)·(topics×vocab) matmuls — so the hot path lands on
the MXU instead of the reference's executor-distributed scalar updates.
Defaults follow Spark ML: vector_size=100, window=5, min_count=5, step=0.025,
max_iter=1 (word2vec); k=10, max_iter=20 (LDA).
"""
from __future__ import annotations

from collections import Counter
from typing import Dict, List, Optional

import numpy as np

from ..stages.base import UnaryEstimator, UnaryModel
from ..types.columns import ColumnarDataset, FeatureColumn
from ..types.feature_types import OPVector
from .vector_metadata import VectorColumnMetadata, VectorMetadata
from .vectorizers import _vec_column

__all__ = ["OpWord2Vec", "OpWord2VecModel", "OpLDA", "OpLDAModel"]


# ---------------------------------------------------------------------------
# word2vec
# ---------------------------------------------------------------------------

class OpWord2Vec(UnaryEstimator):
    """TextList -> document embedding via skip-gram negative sampling.

    The (center, context) pair list is built host-side, then the SGD loop runs
    as one jitted ``lax.fori_loop`` over fixed-size minibatches: each step is
    a gather of center/context/negative rows, a sigmoid-weighted outer
    product, and a scatter-add — all static shapes.
    """

    def __init__(self, vector_size: int = 100, window_size: int = 5,
                 min_count: int = 5, max_iter: int = 1, step_size: float = 0.025,
                 num_negatives: int = 5, batch_size: int = 1024,
                 seed: int = 42, uid: Optional[str] = None):
        super().__init__(operation_name="w2v", output_type=OPVector, uid=uid)
        self.vector_size = vector_size
        self.window_size = window_size
        self.min_count = min_count
        self.max_iter = max_iter
        self.step_size = step_size
        self.num_negatives = num_negatives
        self.batch_size = batch_size
        self.seed = seed

    def _pairs(self, docs, index: Dict[str, int]) -> np.ndarray:
        centers, contexts = [], []
        for toks in docs:
            ids = [index[t] for t in (toks or ()) if t in index]
            for i, c in enumerate(ids):
                lo = max(0, i - self.window_size)
                for j in range(lo, min(len(ids), i + self.window_size + 1)):
                    if j != i:
                        centers.append(c)
                        contexts.append(ids[j])
        if not centers:
            return np.empty((0, 2), np.int32)
        return np.stack([np.asarray(centers, np.int32),
                         np.asarray(contexts, np.int32)], axis=1)

    def fit_columns(self, data: ColumnarDataset, col: FeatureColumn):
        counts: Counter = Counter()
        for toks in col.values:
            counts.update(toks or ())
        vocab = sorted(str(w) for w, n in counts.items()
                       if n >= self.min_count)
        index = {w: i for i, w in enumerate(vocab)}
        v, d = len(vocab), self.vector_size
        rng = np.random.default_rng(self.seed)
        if v == 0:
            return OpWord2VecModel(vocab=[], vectors=np.zeros((0, d), np.float32))

        pairs = self._pairs(col.values, index)
        if len(pairs) == 0:
            return OpWord2VecModel(
                vocab=vocab,
                vectors=rng.normal(0, 0.1, (v, d)).astype(np.float32))

        import jax
        import jax.numpy as jnp

        b = min(self.batch_size, len(pairs))
        # pad pair list to a multiple of the batch so every step is static
        n_steps = -(-len(pairs) // b) * self.max_iter
        perm = rng.permutation(len(pairs))
        pad = (-len(pairs)) % b
        pairs = np.concatenate([pairs[perm], pairs[perm[:pad]]]) if pad else pairs[perm]
        negs = rng.integers(0, v, size=(n_steps, b, self.num_negatives),
                            dtype=np.int32)
        order = np.stack([rng.permutation(len(pairs) // b)
                          for _ in range(self.max_iter)]).reshape(-1)

        w_in = jnp.asarray(rng.normal(0, 1.0 / d, (v, d)), jnp.float32)
        w_out = jnp.zeros((v, d), jnp.float32)
        pairs_j, negs_j = jnp.asarray(pairs), jnp.asarray(negs)
        order_j = jnp.asarray(order, jnp.int32)
        lr = self.step_size

        def step(i, state):
            win, wout = state
            batch = jax.lax.dynamic_slice_in_dim(pairs_j, order_j[i] * b, b)
            ctr, ctx = batch[:, 0], batch[:, 1]
            neg = negs_j[i]                                   # (b, k)
            vc = win[ctr]                                     # (b, d)
            # positive + negative outputs in one (b, 1+k, d) gather
            out_idx = jnp.concatenate([ctx[:, None], neg], axis=1)
            uo = wout[out_idx]                                # (b, 1+k, d)
            score = jnp.einsum("bd,bkd->bk", vc, uo)
            label = jnp.concatenate(
                [jnp.ones((b, 1)), jnp.zeros((b, self.num_negatives))], axis=1)
            g = (jax.nn.sigmoid(score) - label)               # (b, 1+k)
            grad_vc = jnp.einsum("bk,bkd->bd", g, uo)
            grad_uo = g[:, :, None] * vc[:, None, :]
            # average (not sum) gradients per embedding row: with a small
            # vocabulary a batch hits the same row ~b/v times, and summed
            # scatter updates scale the step by that factor and diverge
            flat_out = out_idx.reshape(-1)
            ctr_cnt = jnp.zeros(v).at[ctr].add(1.0)
            out_cnt = jnp.zeros(v).at[flat_out].add(1.0)
            win = win.at[ctr].add(-lr * grad_vc / ctr_cnt[ctr][:, None])
            wout = wout.at[flat_out].add(
                -lr * grad_uo.reshape(-1, d) / out_cnt[flat_out][:, None])
            return win, wout

        w_in, _ = jax.lax.fori_loop(0, n_steps, step, (w_in, w_out))
        return OpWord2VecModel(vocab=vocab,
                               vectors=np.asarray(jax.device_get(w_in)))


class OpWord2VecModel(UnaryModel):
    def __init__(self, vocab: List[str], vectors: np.ndarray,
                 uid: Optional[str] = None):
        super().__init__(operation_name="w2v", output_type=OPVector, uid=uid)
        self.vocab = list(vocab)
        self.vectors = np.asarray(vectors, np.float32)

    def transform_columns(self, col: FeatureColumn) -> FeatureColumn:
        f = self.input_features[0]
        index = {w: i for i, w in enumerate(self.vocab)}
        d = self.vectors.shape[1] if self.vectors.size else 0
        out = np.zeros((len(col), max(d, 1)), np.float32)
        if d:
            for i, toks in enumerate(col.values):
                ids = [index[t] for t in (toks or ()) if t in index]
                if ids:
                    out[i] = self.vectors[ids].mean(axis=0)
        meta = [VectorColumnMetadata(f.name, f.ftype.type_name(),
                                     descriptor_value=f"w2v_{j}")
                for j in range(out.shape[1])]
        return _vec_column(out, VectorMetadata("w2v", meta))


# ---------------------------------------------------------------------------
# LDA
# ---------------------------------------------------------------------------

def _lda_e_step(counts, exp_elog_beta, alpha, n_iter):
    """Batch variational E-step (Hoffman online-LDA recurrence).

    counts: (n, v); exp_elog_beta: (k, v).  Returns (gamma, sstats) where the
    inner loop is two dense matmuls per iteration — MXU-shaped.
    """
    import jax
    import jax.numpy as jnp
    from jax.scipy.special import digamma

    n, _ = counts.shape
    k = exp_elog_beta.shape[0]

    def exp_elog(g):
        return jnp.exp(digamma(g) - digamma(g.sum(1, keepdims=True)))

    def body(_, gamma):
        theta = exp_elog(gamma)                                # (n, k)
        phinorm = theta @ exp_elog_beta + 1e-100               # (n, v)
        return alpha + theta * ((counts / phinorm) @ exp_elog_beta.T)

    gamma0 = jnp.ones((n, k)) + counts.sum(1, keepdims=True) / k
    gamma = jax.lax.fori_loop(0, n_iter, body, gamma0)
    theta = exp_elog(gamma)
    sstats = theta.T @ (counts / (theta @ exp_elog_beta + 1e-100))
    return gamma, sstats * exp_elog_beta


class OpLDA(UnaryEstimator):
    """OPVector of term counts -> topic distribution (OpLDA.scala:42).

    Batch variational Bayes: E-step/M-step alternation jitted end-to-end;
    every inner update is a (docs×topics)x(topics×vocab) matmul pair.
    """

    def __init__(self, k: int = 10, max_iter: int = 20, e_step_iter: int = 10,
                 doc_concentration: Optional[float] = None,
                 topic_concentration: Optional[float] = None,
                 seed: int = 42, uid: Optional[str] = None):
        super().__init__(operation_name="lda", output_type=OPVector, uid=uid)
        if k < 2:
            raise ValueError("k must be > 1")
        self.k = k
        self.max_iter = max_iter
        self.e_step_iter = e_step_iter
        self.doc_concentration = doc_concentration
        self.topic_concentration = topic_concentration
        self.seed = seed

    def fit_columns(self, data: ColumnarDataset, col: FeatureColumn):
        import jax
        import jax.numpy as jnp
        from jax.scipy.special import digamma

        counts = jnp.asarray(np.maximum(
            np.asarray(col.values, np.float64), 0.0))
        v = counts.shape[1]
        alpha = self.doc_concentration or 1.0 / self.k   # Spark online default
        eta = self.topic_concentration or 1.0 / self.k
        rng = np.random.default_rng(self.seed)
        lam = jnp.asarray(rng.gamma(100.0, 1.0 / 100.0, (self.k, v)))

        e_iter = self.e_step_iter

        def m_step(_, lam):
            exp_elog_beta = jnp.exp(
                digamma(lam) - digamma(lam.sum(1, keepdims=True)))
            _, sstats = _lda_e_step(counts, exp_elog_beta, alpha, e_iter)
            return eta + sstats

        lam = jax.lax.fori_loop(0, self.max_iter, m_step, lam)
        return OpLDAModel(topic_word=np.asarray(jax.device_get(lam)),
                          doc_concentration=float(alpha),
                          e_step_iter=self.e_step_iter)


class OpLDAModel(UnaryModel):
    def __init__(self, topic_word: np.ndarray, doc_concentration: float = 0.1,
                 e_step_iter: int = 10, uid: Optional[str] = None):
        super().__init__(operation_name="lda", output_type=OPVector, uid=uid)
        self.topic_word = np.asarray(topic_word, np.float64)
        self.doc_concentration = doc_concentration
        self.e_step_iter = e_step_iter

    def transform_columns(self, col: FeatureColumn) -> FeatureColumn:
        import jax
        import jax.numpy as jnp
        from jax.scipy.special import digamma

        f = self.input_features[0]
        counts = jnp.asarray(np.maximum(
            np.asarray(col.values, np.float64), 0.0))
        lam = jnp.asarray(self.topic_word)
        exp_elog_beta = jnp.exp(
            digamma(lam) - digamma(lam.sum(1, keepdims=True)))
        gamma, _ = _lda_e_step(counts, exp_elog_beta,
                               self.doc_concentration, self.e_step_iter)
        theta = np.asarray(jax.device_get(
            gamma / gamma.sum(1, keepdims=True)), np.float32)
        meta = [VectorColumnMetadata(f.name, f.ftype.type_name(),
                                     descriptor_value=f"topic_{j}")
                for j in range(theta.shape[1])]
        return _vec_column(theta, VectorMetadata("lda", meta))
